"""ZeRO-2/3 (PR 9): gradient and parameter sharding composed with the
overlap buckets and the quantized wire.

Contracts under test (ISSUE 9 acceptance):

* ZeRO-2 trajectories BIT-EXACT vs ZeRO-1 (fp32 wire, op=Sum); ZeRO-3
  update math (gradient shards, moments, updates) bit-exact with params
  within 1 ulp — XLA contracts the caller-side ``params + update`` add
  into an FMA at stage 3 (the stage-1 add consumes an all-gather output
  and cannot contract; see sharded_optimizer.update).
* Zero retraces across steady-state steps; ONE cached bucket schedule
  shared by the scatter and gather legs.
* Lowered ZeRO-2 module: exactly N per-bucket reduce-scatters, ZERO
  full-size all-reduces; the grad_guard adds exactly one scalar psum.
* Lowered ZeRO-3 module: N per-bucket parameter all-gathers at forward
  frontiers, mutually independent (no monolithic unshard), and the
  backward adds NO all-gathers beyond the schedule.
* Sharded int8 wire: pad elements excluded from block scales and EF
  residuals BY CONSTRUCTION (zero-pad contract of parallel.fsdp.pad_to).
* Elastic 8→6 reshard: Adam moments + guard counters + ag residuals
  carried bit-exactly; rs residuals preserve the un-transmitted total.
* Stage-3 shard rows checkpoint through CheckpointManager (digest
  sidecar included) WITHOUT unsharding, and training resumes bit-exact.
"""

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import Mesh, PartitionSpec as P

import horovod_tpu as hvd_pkg
from horovod_tpu import analysis
from horovod_tpu.ops import overlap, traced

WORLD = 8


def _problem(rng, d_in=12, d_out=7):
    # awkward sizes: 12*7=84 and 7 don't divide 8 -> padding everywhere
    w = rng.normal(size=(d_in, d_out)).astype(np.float32)
    params = {
        "w": jnp.asarray(rng.normal(size=(d_in, d_out)), jnp.float32),
        "b": jnp.zeros((d_out,), jnp.float32),
    }
    x = rng.normal(size=(WORLD, 16, d_in)).astype(np.float32)
    y = np.einsum("wbi,io->wbo", x, w).astype(np.float32)
    return params, jnp.asarray(x), jnp.asarray(y)


def _loss(params, xb, yb):
    pred = xb @ params["w"] + params["b"]
    return jnp.mean((pred - yb) ** 2)


def _make_z1_step(opt, mesh):
    """Canonical ZeRO-1 step: full grads into update."""

    @partial(
        jax.shard_map, mesh=mesh,
        in_specs=(P(), opt.state_spec(), P(hvd_pkg.WORLD_AXIS),
                  P(hvd_pkg.WORLD_AXIS)),
        out_specs=(P(), opt.state_spec(), P()),
        check_vma=False,
    )
    def step(p, st, xb, yb):
        loss, g = jax.value_and_grad(_loss)(p, xb[0], yb[0])
        u, st = opt.update(g, st, p)
        return optax.apply_updates(p, u), st, jax.lax.pmean(
            loss, hvd_pkg.WORLD_AXIS
        )

    return jax.jit(step)


def _make_z2_step(opt, mesh):
    """Canonical ZeRO-2 step: shard grads from the in-backprop scatter
    boundary straight into update."""

    @partial(
        jax.shard_map, mesh=mesh,
        in_specs=(P(), opt.state_spec(), P(hvd_pkg.WORLD_AXIS),
                  P(hvd_pkg.WORLD_AXIS)),
        out_specs=(P(), opt.state_spec(), P()),
        check_vma=False,
    )
    def step(p, st, xb, yb):
        loss, g_sh = opt.value_and_grad(_loss)(p, xb[0], yb[0])
        u, st = opt.update(g_sh, st, p)
        return optax.apply_updates(p, u), st, jax.lax.pmean(
            loss, hvd_pkg.WORLD_AXIS
        )

    return jax.jit(step)


def _make_z3_step(opt, mesh):
    """Canonical ZeRO-3 step: sharded params in, sharded params out."""

    @partial(
        jax.shard_map, mesh=mesh,
        in_specs=(opt.state_spec(), opt.state_spec(),
                  P(hvd_pkg.WORLD_AXIS), P(hvd_pkg.WORLD_AXIS)),
        out_specs=(opt.state_spec(), opt.state_spec(), P()),
        check_vma=False,
    )
    def step(psh, st, xb, yb):
        local = opt.local_shards(psh)
        loss, g_sh = opt.value_and_grad(_loss)(local, xb[0], yb[0])
        u, st = opt.update(g_sh, st, local)
        return (
            opt.as_rows(optax.apply_updates(local, u)),
            st,
            jax.lax.pmean(loss, hvd_pkg.WORLD_AXIS),
        )

    return jax.jit(step)


# --------------------------------------------------- trajectory parity


@pytest.mark.parametrize("inner", ["adam", "sgd_momentum"], ids=str)
def test_zero2_bitexact_vs_zero1(hvd, inner):
    """ZeRO-2 (fp32 wire, op=Sum): the in-backprop bucketed scatter +
    shard update + bucketed gather produces the EXACT ZeRO-1 param
    trajectory, step over step."""
    mesh = hvd_pkg.mesh()
    rng = np.random.default_rng(0)
    params, x, y = _problem(rng)
    make = {
        "adam": lambda: optax.adam(1e-2),
        "sgd_momentum": lambda: optax.sgd(1e-2, momentum=0.9),
    }[inner]
    o1 = hvd_pkg.ShardedDistributedOptimizer(make(), op=hvd_pkg.Sum)
    o2 = hvd_pkg.ShardedDistributedOptimizer(
        make(), op=hvd_pkg.Sum, zero_stage=2,
        overlap_buckets=2, overlap_min_bytes=0,
    )
    s1, s2 = o1.init(params), o2.init(params)
    st1, st2 = _make_z1_step(o1, mesh), _make_z2_step(o2, mesh)
    p1 = p2 = params
    for step in range(10):
        p1, s1, l1 = st1(p1, s1, x, y)
        p2, s2, l2 = st2(p2, s2, x, y)
        assert float(l1) == float(l2), step
        for k in params:
            assert (np.asarray(p1[k]) == np.asarray(p2[k])).all(), (
                step, k,
            )
    for a, b in zip(
        jax.tree_util.tree_leaves(jax.device_get(s1)),
        jax.tree_util.tree_leaves(jax.device_get(s2)),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert float(l1) < float(
        _loss(params, np.asarray(x[0]), np.asarray(y[0]))
    )


def test_zero3_matches_zero1_update_math_bitexact(hvd):
    """ZeRO-3 (fp32 wire, op=Sum): optimizer moments stay BIT-EXACT vs
    ZeRO-1 step over step, losses identical, and the parameters sit
    within 1 ulp (XLA fuses the final `p + u` into an FMA at stage 3 —
    one rounding instead of two; the update values themselves are
    bit-exact, pinned by the moment equality)."""
    mesh = hvd_pkg.mesh()
    rng = np.random.default_rng(1)
    params, x, y = _problem(rng)
    o1 = hvd_pkg.ShardedDistributedOptimizer(
        optax.adam(1e-2), op=hvd_pkg.Sum
    )
    o3 = hvd_pkg.ShardedDistributedOptimizer(
        optax.adam(1e-2), op=hvd_pkg.Sum, zero_stage=3,
        overlap_buckets=2, overlap_min_bytes=0,
    )
    s1, s3 = o1.init(params), o3.init(params)
    ps3 = o3.init_params(params)
    st1, st3 = _make_z1_step(o1, mesh), _make_z3_step(o3, mesh)
    p1 = params
    # step 1 from BIT-IDENTICAL inputs: the whole update pipeline —
    # gradient shards, moments, updates — is bit-exact; only the final
    # param apply differs (FMA, <=1 ulp)
    p1, s1, l1 = st1(p1, s1, x, y)
    ps3, s3, l3 = st3(ps3, s3, x, y)
    assert float(l1) == float(l3)
    for a, b in zip(
        jax.tree_util.tree_leaves(jax.device_get(s1)),
        jax.tree_util.tree_leaves(jax.device_get(s3)),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    p3 = o3.unshard_params(jax.device_get(ps3))
    for k in params:
        np.testing.assert_array_max_ulp(
            np.asarray(p1[k]), np.asarray(p3[k]), maxulp=1
        )
    # across the trajectory the per-step 1-ulp apply difference feeds
    # the next step's grads, so drift stays at ulp scale but is no
    # longer bitwise; pin it tight
    for step in range(9):
        p1, s1, l1 = st1(p1, s1, x, y)
        ps3, s3, l3 = st3(ps3, s3, x, y)
        assert np.isclose(float(l1), float(l3), rtol=1e-6), step
    p3 = o3.unshard_params(jax.device_get(ps3))
    for k in params:
        np.testing.assert_allclose(
            np.asarray(p1[k]), np.asarray(p3[k]),
            rtol=1e-6, atol=1e-7,
        )
    for a, b in zip(
        jax.tree_util.tree_leaves(jax.device_get(s1)),
        jax.tree_util.tree_leaves(jax.device_get(s3)),
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-7
        )


def test_zero3_param_residency_is_world_fold_smaller(hvd):
    """The stage-3 acceptance number, measured from the actual arrays:
    between-step resident params bytes drop world-fold (>= 1.8x at any
    world >= 2) — the live-buffer claim bench_zero.py re-measures with
    step timing and memory_analysis."""
    rng = np.random.default_rng(2)
    params, _, _ = _problem(rng, d_in=32, d_out=16)
    o3 = hvd_pkg.ShardedDistributedOptimizer(
        optax.adam(1e-2), zero_stage=3, overlap_buckets=2,
        overlap_min_bytes=0,
    )
    ps = o3.init_params(params)
    full = sum(
        l.size * l.dtype.itemsize
        for l in jax.tree_util.tree_leaves(params)
    )
    per_rank = sum(
        int(np.prod(l.shape[1:], dtype=np.int64)) * l.dtype.itemsize
        for l in jax.tree_util.tree_leaves(ps)
    )
    assert full / per_rank >= 1.8
    # padding overhead stays sub-2x of the ideal 1/world split
    assert per_rank <= 2 * full / WORLD


def test_zero_steps_do_not_retrace(hvd):
    """Steady-state compile stability: 5 steps of the canonical ZeRO-2
    and ZeRO-3 steps trace ONCE each and build ONE shared schedule per
    tree geometry (the scatter and gather legs hit the same cache
    entry)."""
    overlap.reset_schedule_cache()
    mesh = hvd_pkg.mesh()
    rng = np.random.default_rng(3)
    params, x, y = _problem(rng)
    traces = {"z2": 0, "z3": 0}

    o2 = hvd_pkg.ShardedDistributedOptimizer(
        optax.adam(1e-2), zero_stage=2, overlap_buckets=2,
        overlap_min_bytes=0,
    )
    s2 = o2.init(params)

    @partial(
        jax.shard_map, mesh=mesh,
        in_specs=(P(), o2.state_spec(), P(hvd_pkg.WORLD_AXIS),
                  P(hvd_pkg.WORLD_AXIS)),
        out_specs=(P(), o2.state_spec()),
        check_vma=False,
    )
    def z2(p, st, xb, yb):
        traces["z2"] += 1
        _, g_sh = o2.value_and_grad(_loss)(p, xb[0], yb[0])
        u, st = o2.update(g_sh, st, p)
        return optax.apply_updates(p, u), st

    z2 = jax.jit(z2)
    p = params
    for _ in range(5):
        p, s2 = z2(p, s2, x, y)
    assert traces["z2"] == 1, "ZeRO-2 step retraced"

    o3 = hvd_pkg.ShardedDistributedOptimizer(
        optax.adam(1e-2), zero_stage=3, overlap_buckets=2,
        overlap_min_bytes=0,
    )
    ps3, s3 = o3.init_params(params), o3.init(params)

    @partial(
        jax.shard_map, mesh=mesh,
        in_specs=(o3.state_spec(), o3.state_spec(),
                  P(hvd_pkg.WORLD_AXIS), P(hvd_pkg.WORLD_AXIS)),
        out_specs=(o3.state_spec(), o3.state_spec()),
        check_vma=False,
    )
    def z3(psh, st, xb, yb):
        traces["z3"] += 1
        local = o3.local_shards(psh)
        _, g_sh = o3.value_and_grad(_loss)(local, xb[0], yb[0])
        u, st = o3.update(g_sh, st, local)
        return o3.as_rows(optax.apply_updates(local, u)), st

    z3 = jax.jit(z3, donate_argnums=(0, 1))
    for _ in range(5):
        ps3, s3 = z3(ps3, s3, x, y)
    assert traces["z3"] == 1, "ZeRO-3 step retraced"
    stats = overlap.schedule_cache_stats()
    assert stats["misses"] <= 2, stats  # one per distinct geometry
    assert stats["hits"] >= 1, stats  # scatter/gather legs share


# --------------------------------------------- compiled-program shape


class TestLoweredModules:
    # structure gates ride the shared horovod_tpu.analysis parser —
    # no per-file regex over as_text()
    N = 3

    def _lower_z2(self, guard):
        mesh = hvd_pkg.mesh()
        rng = np.random.default_rng(4)
        params = {
            f"w{i}": jnp.asarray(
                rng.normal(size=(16, 16)), jnp.float32
            )
            for i in range(6)
        }
        x = jnp.asarray(rng.normal(size=(WORLD, 4, 16)), jnp.float32)
        opt = hvd_pkg.ShardedDistributedOptimizer(
            optax.adam(1e-2), op=hvd_pkg.Sum, zero_stage=2,
            overlap_buckets=self.N, overlap_min_bytes=0,
            grad_guard=guard,
        )
        st = opt.init(params)

        def loss(p, xb):
            h = xb
            for k in sorted(p):
                h = jnp.tanh(h @ p[k])
            return jnp.sum(h * h)

        @partial(
            jax.shard_map, mesh=mesh,
            in_specs=(P(), opt.state_spec(), P(hvd_pkg.WORLD_AXIS)),
            out_specs=(P(), opt.state_spec()),
            check_vma=False,
        )
        def step(p, s, xb):
            _, g_sh = opt.value_and_grad(loss)(p, xb[0])
            u, s = opt.update(g_sh, s, p)
            return optax.apply_updates(p, u), s

        return analysis.parse_module(jax.jit(step).lower(params, st, x))

    def test_zero2_n_reduce_scatters_zero_full_allreduce(self, hvd):
        """Satellite 3 assertion: the ZeRO-2 step lowers to exactly N
        per-bucket reduce-scatters and N all-gathers, ZERO all-reduces
        of any size (no hidden full-gradient exchange), and the
        reduce-scatters are mutually independent."""
        g = self._lower_z2(guard=False)
        analysis.expect(
            g,
            analysis.CollectiveCount("reduce_scatter", self.N),
            analysis.CollectiveCount("all_gather", self.N),
            analysis.CollectiveCount("all_reduce", 0),
            analysis.NoInterCollectiveDefUse("reduce_scatter"),
        )

    def test_zero2_guard_adds_exactly_one_scalar_psum(self, hvd):
        """The PR 7 grad_guard contract under ZeRO-2: +1 scalar psum
        and nothing else — the GuardOverhead rule proves the one extra
        all_reduce carries a SCALAR operand (a full-gradient psum
        would carry a shaped tensor there)."""
        base = self._lower_z2(guard=False)
        g = self._lower_z2(guard=True)
        analysis.expect(
            g,
            analysis.CollectiveCount("reduce_scatter", self.N),
            analysis.CollectiveCount("all_reduce", 1),
            analysis.GuardOverhead(base, extra_scalar_allreduces=1),
        )

    def test_zero3_forward_interleaved_gathers(self, hvd):
        """Acceptance: the ZeRO-3 module carries N per-bucket parameter
        all-gathers — mutually independent, no monolithic unshard —
        and the backward adds NO all-gathers beyond the schedule
        (total == N) while the gradient leg adds exactly N
        reduce-scatters."""
        mesh = hvd_pkg.mesh()
        rng = np.random.default_rng(5)
        params = {
            f"w{i}": jnp.asarray(
                rng.normal(size=(16, 16)), jnp.float32
            )
            for i in range(6)
        }
        x = jnp.asarray(rng.normal(size=(WORLD, 4, 16)), jnp.float32)
        opt = hvd_pkg.ShardedDistributedOptimizer(
            optax.adam(1e-2), op=hvd_pkg.Sum, zero_stage=3,
            overlap_buckets=self.N, overlap_min_bytes=0,
        )
        ps, st = opt.init_params(params), opt.init(params)

        def loss(p, xb):
            h = xb
            for k in sorted(p):
                h = jnp.tanh(h @ p[k])
            return jnp.sum(h * h)

        @partial(
            jax.shard_map, mesh=mesh,
            in_specs=(opt.state_spec(), opt.state_spec(),
                      P(hvd_pkg.WORLD_AXIS)),
            out_specs=(opt.state_spec(), opt.state_spec()),
            check_vma=False,
        )
        def step(psh, s, xb):
            local = opt.local_shards(psh)
            _, g_sh = opt.value_and_grad(loss)(local, xb[0])
            u, s = opt.update(g_sh, s, local)
            return opt.as_rows(optax.apply_updates(local, u)), s

        g = analysis.parse_module(jax.jit(step).lower(ps, st, x))
        analysis.expect(
            g,
            analysis.CollectiveCount("all_gather", self.N),
            analysis.CollectiveCount("reduce_scatter", self.N),
            analysis.CollectiveCount("all_reduce", 0),
            analysis.NoInterCollectiveDefUse("all_gather"),
        )


# --------------------------------------------- sharded wire + padding


class TestShardedWirePadExclusion:
    """Satellite 2: pad elements never enter int8 block scales or EF
    residuals on the sharded wire — the by-construction contract of
    parallel.fsdp.pad_to (zeros quantize to zeros and never raise a
    block's absmax)."""

    def _shmap(self, fn, n_out=1):
        mesh = hvd_pkg.mesh()
        outs = P() if n_out == 1 else tuple(P() for _ in range(n_out))
        return partial(
            jax.shard_map, mesh=mesh, in_specs=(P(),),
            out_specs=outs, check_vma=False,
        )(fn)

    def test_reducescatter_pad_scales_and_residual(self, hvd):
        rng = np.random.default_rng(6)
        cols = 70  # with block 32 -> tail block is half padding
        base = rng.normal(size=(WORLD, cols)).astype(np.float32) * 5
        padded = np.concatenate(
            [base, np.zeros((WORLD, 26), np.float32)], axis=1
        )

        def run(x2d):
            return self._shmap(
                lambda t: traced.quantized_reducescatter(
                    t, op=hvd_pkg.Sum, seed=3, block_size=32,
                    return_residual=True,
                ),
                n_out=2,
            )(jnp.asarray(x2d))

        shard_p, res_p = run(padded)
        # residual at EVERY pad position is exactly zero
        assert (np.asarray(res_p)[:, cols:] == 0).all()
        # the pad tail of the reduced shard is exactly zero too
        # (zeros quantize to zeros regardless of the block scale)
        np.testing.assert_array_equal(
            np.asarray(shard_p)[cols:],
            np.zeros(96 - cols, np.float32),
        )
        # block scales are pad-independent BY CONSTRUCTION: quantizing
        # the padded vs unpadded buffer yields identical scales in
        # every block, INCLUDING the tail block the padding lands in
        # (zeros never raise an absmax)
        from horovod_tpu.ops.traced import _stochastic_round_blocks

        key = jax.random.PRNGKey(0)
        _, s_pad = _stochastic_round_blocks(
            jnp.asarray(padded), 32, key
        )
        _, s_un = _stochastic_round_blocks(jnp.asarray(base), 32, key)
        np.testing.assert_array_equal(
            np.asarray(s_pad), np.asarray(s_un)
        )

    def test_allgather_pad_residual(self, hvd):
        rng = np.random.default_rng(7)
        shard = np.zeros(24, np.float32)
        shard[:17] = rng.normal(size=17).astype(np.float32) * 3

        full, res = self._shmap(
            lambda t: traced.quantized_allgather(
                t, seed=5, block_size=16, return_residual=True
            ),
            n_out=2,
        )(jnp.asarray(shard))
        assert (np.asarray(res)[17:] == 0).all()
        assert (np.asarray(full)[:, 17:] == 0).all()

    def test_end_to_end_ag_residual_pad_slots_zero(self, hvd):
        """Through the optimizer: after int8+EF steps, the ag residual
        entries at global pad positions (beyond each leaf's size) are
        exactly zero."""
        mesh = hvd_pkg.mesh()
        rng = np.random.default_rng(8)
        params, x, y = _problem(rng)  # b: 7 elems over 8 ranks -> pads
        opt = hvd_pkg.ShardedDistributedOptimizer(
            optax.adam(1e-2), zero_stage=2, overlap_buckets=2,
            overlap_min_bytes=0, wire="int8", wire_block=32,
            error_feedback=True,
        )
        st = opt.init(params)
        step = _make_z1_step(opt, mesh)  # full-grad path (EF contract)
        p = params
        for _ in range(4):
            p, st, _ = step(p, st, x, y)
        agb = np.asarray(st["wire"]["ag"]["b"]).reshape(-1)
        assert (agb[7:] == 0).all()  # pads carry zero residual
        assert np.abs(agb[:7]).max() > 0  # real slots carry EF signal
        rsw = np.asarray(st["wire"]["rs"]["w"])
        assert np.abs(rsw).max() > 0


class TestShardedWireTraining:
    def test_int8_ef_trains_and_beats_no_ef_drift(self, hvd):
        """int8 wire on both sharded legs with EF: still learns, and
        the wire-seed counter advances per step."""
        mesh = hvd_pkg.mesh()
        rng = np.random.default_rng(9)
        params, x, y = _problem(rng, d_in=24, d_out=9)
        opt = hvd_pkg.ShardedDistributedOptimizer(
            optax.adam(1e-2), zero_stage=2, overlap_buckets=2,
            overlap_min_bytes=0, wire="int8", wire_block=64,
            error_feedback=True,
        )
        st = opt.init(params)
        step = _make_z1_step(opt, mesh)
        p, losses = params, []
        for _ in range(12):
            p, st, l = step(p, st, x, y)
            losses.append(float(l))
        assert losses[-1] < losses[0] * 0.9, losses
        assert losses[-1] == min(losses), losses
        assert int(np.asarray(st["wire"]["step"])[0]) == 12

    def test_bf16_wire_close_to_fp32(self, hvd):
        mesh = hvd_pkg.mesh()
        rng = np.random.default_rng(10)
        params, x, y = _problem(rng)
        o_ref = hvd_pkg.ShardedDistributedOptimizer(
            optax.sgd(1e-2), zero_stage=2, overlap_buckets=2,
            overlap_min_bytes=0,
        )
        o_b = hvd_pkg.ShardedDistributedOptimizer(
            optax.sgd(1e-2), zero_stage=2, overlap_buckets=2,
            overlap_min_bytes=0, wire="bf16",
        )
        sr, sb = o_ref.init(params), o_b.init(params)
        str_, stb = _make_z2_step(o_ref, mesh), _make_z2_step(o_b, mesh)
        pr = pb = params
        for _ in range(3):
            pr, sr, _ = str_(pr, sr, x, y)
            pb, sb, _ = stb(pb, sb, x, y)
        for k in params:
            np.testing.assert_allclose(
                np.asarray(pr[k]), np.asarray(pb[k]),
                rtol=2e-2, atol=2e-2,
            )

    def test_guard_skip_under_zero2_keeps_everything(self, hvd):
        mesh = hvd_pkg.mesh()
        rng = np.random.default_rng(11)
        params, x, y = _problem(rng)
        opt = hvd_pkg.ShardedDistributedOptimizer(
            optax.adam(1e-2), zero_stage=2, overlap_buckets=2,
            overlap_min_bytes=0, wire="int8", wire_block=32,
            error_feedback=True, grad_guard=True,
        )
        st = opt.init(params)
        step = _make_z1_step(opt, mesh)
        p = params
        for _ in range(3):
            p, st, _ = step(p, st, x, y)
        xbad = x.at[0, 0, 0].set(jnp.nan)
        p2, st2, _ = step(p, st, xbad, y)
        for a, b in zip(
            jax.tree_util.tree_leaves(p), jax.tree_util.tree_leaves(p2)
        ):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        # residuals of the LAST APPLIED step survive the skip
        for a, b in zip(
            jax.tree_util.tree_leaves(jax.device_get(st["wire"]["rs"])),
            jax.tree_util.tree_leaves(jax.device_get(st2["wire"]["rs"])),
        ):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        assert int(np.asarray(st2["guard"]["skips"])[0]) == 1


# ----------------------------------------------------- elastic + ckpt


def _full_moments(state_inner):
    out = []
    for leaf in jax.tree_util.tree_leaves(state_inner):
        a = np.asarray(leaf)
        out.append(a[:1] if a.ndim == 1 else a.reshape(-1))
    return out


class TestElasticReshard:
    def test_zero2_8_to_6_gang_restart_full_carry(self, hvd):
        """Satellite 3, the chaos shape: train at world 8 with
        guard+int8+EF, reshard to 6, assert bit-exact Adam-moment and
        ag-residual carry (rs residuals preserve the un-transmitted
        TOTAL), guard counters survive, and training continues on the
        6-chip mesh."""
        mesh = hvd_pkg.mesh()
        rng = np.random.default_rng(12)
        params, x, y = _problem(rng, d_in=24, d_out=9)
        opt = hvd_pkg.ShardedDistributedOptimizer(
            optax.adam(1e-2), zero_stage=2, overlap_buckets=2,
            overlap_min_bytes=0, wire="int8", wire_block=32,
            error_feedback=True, grad_guard=True,
        )
        st = opt.init(params)
        step8 = _make_z1_step(opt, mesh)
        p, losses = params, []
        for _ in range(4):
            p, st, l = step8(p, st, x, y)
            losses.append(float(l))
        st = jax.device_get(st)

        st6 = opt.reshard_state(st, params, 6)
        # Adam moments: full-vector bit-exact (prefix — tails are pad)
        for a, b in zip(
            _full_moments(st["state"]), _full_moments(st6["state"])
        ):
            n = min(a.size, np.asarray(b).size)
            np.testing.assert_array_equal(a[:n], np.asarray(b)[:n])
        # guard counters carried
        for key in ("skips", "streak", "step"):
            assert (
                np.asarray(st6["guard"][key])
                == np.asarray(st["guard"][key]).reshape(-1)[0]
            ).all()
        # ag residuals: shard-major, bit-exact like the moments
        for a, b in zip(
            jax.tree_util.tree_leaves(st["wire"]["ag"]),
            jax.tree_util.tree_leaves(st6["wire"]["ag"]),
        ):
            fa, fb = np.asarray(a).reshape(-1), np.asarray(b).reshape(-1)
            n = min(fa.size, fb.size)
            np.testing.assert_array_equal(fa[:n], fb[:n])
        # rs residuals: the cross-rank TOTAL (all the wire ever
        # consumes) is preserved exactly
        for a, b in zip(
            jax.tree_util.tree_leaves(st["wire"]["rs"]),
            jax.tree_util.tree_leaves(st6["wire"]["rs"]),
        ):
            np.testing.assert_array_equal(
                np.asarray(a).sum(axis=0), np.asarray(b).sum(axis=0)
            )
        # wire-seed counter carried
        assert (
            np.asarray(st6["wire"]["step"])
            == np.asarray(st["wire"]["step"]).reshape(-1)[0]
        ).all()

        # continue on a fresh 6-device mesh — the gang-restart shape
        mesh6 = Mesh(
            np.asarray(jax.devices()[:6]), (hvd_pkg.WORLD_AXIS,)
        )
        p = jax.tree_util.tree_map(np.asarray, jax.device_get(p))
        st6 = jax.tree_util.tree_map(np.asarray, st6)
        step6 = _make_z1_step(opt, mesh6)
        for _ in range(4):
            p, st6, l6 = step6(p, st6, x[:6], y[:6])
        assert float(l6) < losses[1], (float(l6), losses)

    def test_zero3_param_reshard_8_to_6_and_back(self, hvd):
        rng = np.random.default_rng(13)
        params, x, y = _problem(rng)
        opt = hvd_pkg.ShardedDistributedOptimizer(
            optax.adam(1e-2), zero_stage=3, overlap_buckets=2,
            overlap_min_bytes=0,
        )
        ps, st = opt.init_params(params), opt.init(params)
        step8 = _make_z3_step(opt, hvd_pkg.mesh())
        for _ in range(3):
            ps, st, _ = step8(ps, st, x, y)
        full8 = opt.unshard_params(jax.device_get(ps))

        ps6 = opt.reshard_params(jax.device_get(ps), params, 6)
        st6 = opt.reshard_state(jax.device_get(st), params, 6)
        full6 = opt.unshard_params(ps6)
        for k in params:
            np.testing.assert_array_equal(
                np.asarray(full8[k]), np.asarray(full6[k])
            )
        # round-trip back up is exact too
        ps8 = opt.reshard_params(ps6, params, 8)
        for k, leaf in opt.unshard_params(ps8).items():
            np.testing.assert_array_equal(
                np.asarray(full8[k]), np.asarray(leaf)
            )
        opt.reshard_state(jax.device_get(st6), params, 8)

        # resume training at world 6
        opt6 = hvd_pkg.ShardedDistributedOptimizer(
            optax.adam(1e-2), zero_stage=3, overlap_buckets=2,
            overlap_min_bytes=0, world=6,
        )
        opt6.bind_params_like(params)
        mesh6 = Mesh(
            np.asarray(jax.devices()[:6]), (hvd_pkg.WORLD_AXIS,)
        )
        ps6 = jax.tree_util.tree_map(np.asarray, ps6)
        st6 = jax.tree_util.tree_map(np.asarray, st6)
        step6 = _make_z3_step(opt6, mesh6)
        losses6 = []
        for _ in range(4):
            ps6, st6, l6 = step6(ps6, st6, x[:6], y[:6])
            losses6.append(float(l6))
        assert losses6[-1] < losses6[0]

    def test_reshard_accepts_eval_shape_template(self, hvd):
        """The documented elastic-resume path passes a SHAPE template
        (jax.eval_shape output) — reshard_state and reshard_params must
        accept it and produce the same result as concrete params."""
        rng = np.random.default_rng(15)
        params, x, y = _problem(rng)
        tmpl = jax.eval_shape(lambda: params)
        opt = hvd_pkg.ShardedDistributedOptimizer(
            optax.adam(1e-2), zero_stage=2, overlap_buckets=2,
            overlap_min_bytes=0, wire="int8", wire_block=32,
            error_feedback=True, grad_guard=True,
        )
        st = opt.init(params)
        step = _make_z1_step(opt, hvd_pkg.mesh())
        p = params
        for _ in range(2):
            p, st, _ = step(p, st, x, y)
        st = jax.device_get(st)
        via_tmpl = opt.reshard_state(st, tmpl, 6)
        via_real = opt.reshard_state(st, params, 6)
        for a, b in zip(
            jax.tree_util.tree_leaves(via_tmpl),
            jax.tree_util.tree_leaves(via_real),
        ):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        # EF-synthesis migration from a flat state works on a template
        flat_opt = hvd_pkg.ShardedDistributedOptimizer(
            optax.adam(1e-2), zero_stage=2
        )
        flat = jax.device_get(flat_opt.init(params))
        up = opt.reshard_state(flat, tmpl, 8)
        assert {"state", "guard", "wire"} == set(up)
        # stage-3 param rows reshard off a template too
        o3 = hvd_pkg.ShardedDistributedOptimizer(
            optax.adam(1e-2), zero_stage=3
        )
        ps = o3.init_params(params)
        ps6 = o3.reshard_params(jax.device_get(ps), tmpl, 6)
        full = o3.unshard_params(ps6)
        for k in params:
            np.testing.assert_array_equal(
                np.asarray(full[k]), np.asarray(params[k])
            )

    def test_zero3_checkpoint_roundtrip_sharded_no_gather(
        self, hvd, tmp_path
    ):
        """DurableJaxState/CheckpointManager contract: the stage-3
        shard rows and the optimizer state save and digest-verify AS
        SHARD ROWS (never unsharded), and the restored job continues
        bit-exact."""
        from horovod_tpu.checkpoint import CheckpointManager

        rng = np.random.default_rng(14)
        params, x, y = _problem(rng)
        opt = hvd_pkg.ShardedDistributedOptimizer(
            optax.adam(1e-2), zero_stage=3, overlap_buckets=2,
            overlap_min_bytes=0,
        )
        ps, st = opt.init_params(params), opt.init(params)
        step = _make_z3_step(opt, hvd_pkg.mesh())
        for _ in range(3):
            ps, st, _ = step(ps, st, x, y)

        tree = {"pstate": ps, "opt_state": st}
        with CheckpointManager(
            str(tmp_path / "ckpt"), async_save=False
        ) as m:
            m.save(3, tree)
            m.wait_until_finished()
            # digest sidecar exists over the SHARDED layout
            step_id, restored = m.restore_latest_good(like=tree)
        assert step_id == 3
        for a, b in zip(
            jax.tree_util.tree_leaves(tree),
            jax.tree_util.tree_leaves(restored),
        ):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        # a fresh optimizer resumes from the restored rows bit-exactly
        opt2 = hvd_pkg.ShardedDistributedOptimizer(
            optax.adam(1e-2), zero_stage=3, overlap_buckets=2,
            overlap_min_bytes=0,
        )
        opt2.bind_params_like(params)
        step2 = _make_z3_step(opt2, hvd_pkg.mesh())
        a1, s1, _ = step(ps, st, x, y)
        a2, s2, _ = step2(
            restored["pstate"], restored["opt_state"], x, y
        )
        for u, v in zip(
            jax.tree_util.tree_leaves(a1), jax.tree_util.tree_leaves(a2)
        ):
            np.testing.assert_array_equal(np.asarray(u), np.asarray(v))


# ------------------------------------------------------- guard rails


class TestValidation:
    def test_zero_stage_env_default(self, hvd, monkeypatch):
        monkeypatch.setenv("HOROVOD_ZERO_STAGE", "2")
        hvd.shutdown()
        hvd.init()
        opt = hvd_pkg.ShardedDistributedOptimizer(optax.sgd(1e-2))
        assert opt._stage == 2

    def test_bad_zero_stage_rejected(self, hvd):
        with pytest.raises(ValueError, match="zero_stage"):
            hvd_pkg.ShardedDistributedOptimizer(
                optax.sgd(1e-2), zero_stage=4
            )

    def test_bad_wire_rejected(self, hvd):
        with pytest.raises(ValueError, match="wire"):
            hvd_pkg.ShardedDistributedOptimizer(
                optax.sgd(1e-2), wire="fp8"
            )

    def test_ef_needs_quantized_wire(self, hvd):
        with pytest.raises(ValueError, match="error_feedback"):
            hvd_pkg.ShardedDistributedOptimizer(
                optax.sgd(1e-2), wire="bf16", error_feedback=True
            )

    def test_ef_rejected_at_stage3(self, hvd):
        with pytest.raises(ValueError, match="stage"):
            hvd_pkg.ShardedDistributedOptimizer(
                optax.sgd(1e-2), zero_stage=3, wire="int8",
                error_feedback=True,
            )

    def test_wire_layout_migration(self, hvd):
        """EF-on against a residual-less state errors at update and
        migrates through reshard_state (synthesize); EF-off against a
        residual-carrying state errors and strips."""
        params = {"w": jnp.linspace(0, 1, 32)}
        plain = hvd_pkg.ShardedDistributedOptimizer(
            optax.adam(1e-2), zero_stage=2
        )
        ef = hvd_pkg.ShardedDistributedOptimizer(
            optax.adam(1e-2), zero_stage=2, wire="int8",
            error_feedback=True,
        )
        flat = plain.init(params)
        with_res = ef.init(params)
        with pytest.raises(ValueError, match="wire residual"):
            ef.update({"w": jnp.ones(32)}, flat, params)
        with pytest.raises(ValueError, match="wire residual"):
            plain.update({"w": jnp.ones(32)}, with_res, params)
        up = ef.reshard_state(flat, params, 8)
        assert set(up) == {"state", "wire"}
        assert np.asarray(up["wire"]["rs"]["w"]).shape == (8, 32)
        down = plain.reshard_state(with_res, params, 8)
        assert not isinstance(down, dict) or "wire" not in down

    def test_mixed_grad_tree_rejected(self, hvd):
        mesh = hvd_pkg.mesh()
        params = {
            "a": jnp.ones((16,), jnp.float32),
            "b": jnp.ones((24,), jnp.float32),
        }
        opt = hvd_pkg.ShardedDistributedOptimizer(
            optax.sgd(1e-2), zero_stage=2
        )
        st = opt.init(params)
        grads = {
            "a": jnp.ones((16,), jnp.float32),  # full
            "b": jnp.ones((3,), jnp.float32),  # shard (24/8)
        }

        @partial(
            jax.shard_map, mesh=mesh,
            in_specs=(P(), opt.state_spec(), P()),
            out_specs=(P(), opt.state_spec()),
            check_vma=False,
        )
        def step(p, s, g):
            return opt.update(g, s, p)

        with pytest.raises(ValueError, match="mixes full and shard"):
            jax.jit(step)(params, st, grads)

    def test_stage3_update_rejects_full_params(self, hvd):
        mesh = hvd_pkg.mesh()
        params = {"w": jnp.ones((8, 4), jnp.float32)}
        opt = hvd_pkg.ShardedDistributedOptimizer(
            optax.sgd(1e-2), zero_stage=3
        )
        st = opt.init(params)
        opt.init_params(params)

        @partial(
            jax.shard_map, mesh=mesh,
            in_specs=(P(), opt.state_spec()),
            out_specs=(P(), opt.state_spec()),
            check_vma=False,
        )
        def step(p, s):
            g = jax.tree_util.tree_map(jnp.ones_like, p)
            return opt.update(g, s, p)

        with pytest.raises(ValueError, match="parameter shards"):
            jax.jit(step)(params, st)

    def test_gather_requires_bound_meta(self, hvd):
        opt = hvd_pkg.ShardedDistributedOptimizer(
            optax.sgd(1e-2), zero_stage=3
        )
        with pytest.raises(ValueError, match="geometry is unbound"):
            opt.unshard_params({"w": jnp.zeros((8, 4))})
