"""Worker-side elastic machinery: notifications + the ``run`` wrapper.

Rebuild of the reference's worker half (ref: horovod/common/elastic.py
`run` + horovod/runner/elastic/worker.py WorkerNotificationService/
Manager [V] — SURVEY.md §2.5, §3.4).

Flow (§3.4): the wrapped train function loops — ``state.sync()``, run
the body; on ``HorovodInternalError`` restore to the last commit, on
``HostsUpdatedInterrupt`` keep current state; either way shut down and
re-init the runtime against the new world, then retry the body.
"""

from __future__ import annotations

import functools
import socket
import threading
from typing import Optional

from ..common.basics import (
    HorovodInternalError,
    HostsUpdatedInterrupt,
)
from ..runner.service import BasicService


class WorkerNotificationService(BasicService):
    """Tiny RPC endpoint inside each worker the driver pings on
    membership changes (ref: WorkerNotificationService [V])."""

    def __init__(self, secret_key: bytes, manager: "WorkerNotificationManager"):
        super().__init__("worker-notification", secret_key)
        self.register("hosts_updated", manager._on_hosts_updated)


class WorkerNotificationManager:
    """Registers with the driver's rendezvous, listens for updates,
    surfaces them as HostsUpdatedInterrupt at commit boundaries
    (ref: WorkerNotificationManager [V])."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._service: Optional[WorkerNotificationService] = None
        self._updated = threading.Event()

    def init(self) -> None:
        """Start the notification endpoint and advertise it in the
        driver's KV store under workers.<epoch>/<process_id>. No-op
        when not under an elastic driver (env absent) or already up."""
        with self._lock:
            if self._service is not None:
                return
            from ..common import config as config_mod
            from ..runner import rendezvous as _rdv

            # a previous teardown may have latched the KV-poll abort;
            # this process is (re)joining a gang, so re-arm the pollers
            _rdv.reset_poll_shutdown()
            # the audit publisher caches its KV client; a rejoining
            # worker must re-dial the (possibly new) rendezvous.
            # NB: ``from .. import audit`` would pick up the
            # ``hvd.audit`` FUNCTION (the package re-export shadows
            # the module attribute); import from the module directly.
            from ..audit import _reset_client as _audit_reset

            _audit_reset()
            # same re-dial contract for the rebalance-weight reader
            _reset_rebalance_cache()
            # a (re)joining gang starts a fresh collective schedule:
            # carrying the old epoch's fingerprint would mis-flag the
            # whole new gang as divergent from itself
            from ..analysis import sched_audit as _sched_audit

            _sched_audit.reset()
            # local-SGD phase/driver state is per-gang too: the new
            # world resolves its own split, and the sync retry ladder
            # (incl. any open circuit) starts fresh — the rejoin
            # round re-syncs params from the Adasum consensus
            # (local_sgd.rejoin_sync), not from a root broadcast
            from .. import local_sgd as _local_sgd

            _local_sgd.reset()
            cfg = config_mod.Config.from_env()
            if not (
                cfg.rendezvous_addr
                and cfg.rendezvous_port
                and cfg.secret_key_hex
            ):
                return
            import os

            secret = bytes.fromhex(cfg.secret_key_hex)
            self._service = WorkerNotificationService(secret, self)
            port = self._service.start()
            epoch = os.environ.get("HOROVOD_ELASTIC_EPOCH", "0")
            process_id = os.environ.get("HOROVOD_PROCESS_ID", "0")
            # our address as the driver should dial it
            hostname = os.environ.get("HOROVOD_HOSTNAME", "")
            if hostname in ("localhost", "127.0.0.1", "", socket.gethostname()):
                hostname = "127.0.0.1"
            from ..runner.rendezvous import (
                RendezvousClient,
                put_heartbeat,
            )

            client = RendezvousClient(
                cfg.rendezvous_addr, cfg.rendezvous_port, secret_key=secret
            )
            client.put(
                f"workers.{epoch}", process_id,
                f"{hostname}:{port}".encode(),
            )
            self._publish_restart_ms(client, epoch)

            # Liveness for the driver's stall inspector: stamp
            # heartbeat/<rank> every 10s until shutdown (the rebuilt
            # cross-process stall signal — stall_inspector.cc [V]).
            rank = int(os.environ.get("HOROVOD_RANK", process_id))
            stop = threading.Event()
            self._hb_stop = stop

            def _beat():
                from ..common import telemetry as _telemetry
                from ..testing import chaos as _chaos

                while not stop.is_set():
                    try:
                        # ``heartbeat`` injection site: a delayed or
                        # dropped stamp must read as ONE late beat (the
                        # KV client's RetryPolicy underneath absorbs
                        # transport flakes), never kill the thread
                        _chaos.inject("heartbeat")
                        # piggyback the straggler ledger: this worker's
                        # last step id + ring p50 ride the liveness
                        # stamp, so the driver can tell slow from
                        # silent ({} before the first recorded step)
                        put_heartbeat(
                            client, rank,
                            stats=_telemetry.heartbeat_stats(),
                        )
                    except Exception:
                        pass  # rendezvous going away = job ending
                    stop.wait(10.0)

            t = threading.Thread(
                target=_beat, name="hvd-heartbeat", daemon=True
            )
            t.start()

    def _publish_restart_ms(self, client, epoch: str) -> None:
        """Close the restart clock: the driver stamped wall time at
        gang teardown (``_reset``); a worker of the stamped epoch
        publishes ``now − ts`` as ``elastic.restart_ms`` (and
        ``serve.scaleup_ms`` for a scale-up restart) — the per-worker
        measurement of how fast the gang healed, warm vs cold. Best-
        effort: a missing/foreign stamp is a first launch, not an
        error."""
        import time as _time

        from ..common.metrics import registry as _metrics
        from ..runner.rendezvous import read_restart_stamp

        try:
            stamp = read_restart_stamp(client)
        except Exception:
            return
        if stamp is None or str(stamp.get("epoch")) != str(epoch):
            return  # stale stamp from an older epoch, or first launch
        ms = max((_time.time() - float(stamp["ts"])) * 1e3, 0.0)
        _metrics.gauge("elastic.restart_ms", ms)
        _metrics.gauge(
            "elastic.restart_warm", 1.0 if stamp.get("warm") else 0.0
        )
        if stamp.get("kind") == "scaleup":
            _metrics.gauge("serve.scaleup_ms", ms)

    def _on_hosts_updated(self, request: dict) -> dict:
        self._updated.set()
        return {}

    def raise_if_updated(self) -> None:
        if self._updated.is_set():
            self._updated.clear()
            raise HostsUpdatedInterrupt()

    def reset(self) -> None:
        self._updated.clear()

    def shutdown(self) -> None:
        with self._lock:
            if getattr(self, "_hb_stop", None) is not None:
                self._hb_stop.set()
                self._hb_stop = None
            if self._service is not None:
                self._service.stop()
                self._service = None
        # abort any KV poll loop still in flight (broadcast/allgather
        # waits): a worker tearing down must not spin against the
        # driver's KV until its timeout expires
        from ..runner import rendezvous as _rdv

        _rdv.request_poll_shutdown()


notification_manager = WorkerNotificationManager()


# rebalance_weights is documented for per-micro-batch polling, so the
# KV client is built once per endpoint and reads are rate-limited —
# the hot loop must never pay an env parse + TCP roundtrip per batch
# (the driver publishes on CHANGE only; a few-seconds-stale map is by
# construction still valid).
_REBALANCE_POLL_S = 5.0
_rebalance_cache = {"endpoint": None, "client": None, "ts": 0.0, "map": {}}


def _reset_rebalance_cache() -> None:
    """Drop the cached client/map (gang restart re-dials rendezvous)."""
    _rebalance_cache.update(
        endpoint=None, client=None, ts=0.0, map={}
    )


def rebalance_weights(max_age_s: float = _REBALANCE_POLL_S) -> dict:
    """The driver's newest micro-batch weight map
    (``{rank: weight in (0, 1]}``) from the rendezvous KV, or ``{}``
    when no driver published one (HOROVOD_REBALANCE off, not under an
    elastic driver, or no straggler ever stayed flagged). Worker side
    of the straggler-aware scheduling loop — see
    :func:`rebalance_weight` for the single-rank view. Reads are
    cached for ``max_age_s`` (pass 0 to force a fresh KV read)."""
    import time

    now = time.monotonic()
    if (
        _rebalance_cache["client"] is not None
        and now - _rebalance_cache["ts"] < max_age_s
    ):
        return dict(_rebalance_cache["map"])
    from ..runner.rendezvous import read_rebalance_weights

    client = _kv_client()
    if client is None:
        return {}
    try:
        weights = read_rebalance_weights(client)
    except OSError:
        return dict(_rebalance_cache["map"])  # rendezvous going away
    _rebalance_cache["ts"] = now
    _rebalance_cache["map"] = weights
    return dict(weights)


def _kv_client():
    """The worker's cached rendezvous-KV client (shared with the
    rebalance reader — same endpoint, same re-dial-on-restart
    contract), or None outside an elastic/runner job."""
    from ..common import config as config_mod
    from ..runner.rendezvous import _client_from_cfg

    cfg = config_mod.Config.from_env()
    if not (cfg.rendezvous_addr and cfg.rendezvous_port):
        return None
    endpoint = (cfg.rendezvous_addr, cfg.rendezvous_port)
    if (
        _rebalance_cache["client"] is None
        or _rebalance_cache["endpoint"] != endpoint
    ):
        _rebalance_cache["client"] = _client_from_cfg(cfg)
        _rebalance_cache["endpoint"] = endpoint
    return _rebalance_cache["client"]


def publish_expert_load(
    expert_tokens,
    dropped: float,
    total: float,
    capacity_factor: Optional[float] = None,
    rank: Optional[int] = None,
) -> bool:
    """Publish this rank's per-expert load summary (a fetched
    ``MoEStats`` — host floats) into the rendezvous KV so the driver
    and the capacity autotuner see expert heat fleet-wide (PR 12; the
    PR 10 rebalance plumbing generalized). Call it at the MoE step
    harness's cadence, not per micro-batch. Returns False (and stays
    silent) outside an elastic job or when rendezvous is going away —
    a scheduling hint must never take training down."""
    import os

    client = _kv_client()
    if client is None:
        return False
    if rank is None:
        rank = int(os.environ.get("HOROVOD_RANK", "0") or 0)
    from ..runner.rendezvous import put_expert_load

    try:
        put_expert_load(
            client, rank, expert_tokens, dropped, total, capacity_factor
        )
    except OSError:
        return False
    return True


def expert_loads() -> dict:
    """Every rank's newest published expert-load summary
    (``{rank: payload}``), or ``{}`` outside an elastic job. The
    driver-side aggregation lives in elastic/driver.py; this is the
    worker-side peek (a capacity harness can fold sibling ranks' heat
    into its own decision)."""
    client = _kv_client()
    if client is None:
        return {}
    from ..runner.rendezvous import read_expert_loads

    try:
        return read_expert_loads(client)
    except OSError:
        return {}


def rebalance_weight(rank: Optional[int] = None, default: float = 1.0) -> float:
    """This rank's micro-batch weight under the driver's straggler
    rebalance (1.0 when none is published). Poll it at micro-batch
    boundaries and scale the LOCAL batch share by it::

        w = hvd.elastic.rebalance_weight()
        local_batch = max(1, int(round(base_batch * w)))

    The weight is a scheduling hint, not a collective contract: ranks
    keep participating in every collective (use ``allreduce(mask=)``
    or loss re-weighting to keep gradient expectations unbiased when
    shares diverge — docs/design.md shows the pattern)."""
    import os

    if rank is None:
        rank = int(os.environ.get("HOROVOD_RANK", "0") or 0)
    return float(rebalance_weights().get(int(rank), default))


def _reset_runtime() -> None:
    """Tear down and re-init against the (possibly new) world —
    the reference's hvd.shutdown()/hvd.init() reinit boundary (§3.4)."""
    from ..common import basics

    basics.shutdown()
    basics.init()


def run(func):
    """``@hvd.elastic.run`` — retry loop with commit/restore semantics
    (ref: horovod/common/elastic.py run_fn [V]).

    The wrapped function's first argument must be a ``State``.
    """

    @functools.wraps(func)
    def wrapper(state, *args, **kwargs):
        notification_manager.init()
        skip_sync = False
        while True:
            if not skip_sync:
                state.sync()
            try:
                return func(state, *args, **kwargs)
            except HorovodInternalError:
                # a peer died mid-collective (or the grad guard
                # escalated past K consecutive non-finite steps): roll
                # back to last commit. The guard ledger's streak view
                # is cleared — the restored state predates the poison,
                # so the retry must not re-escalate on stale counters.
                from ..common import guard as _guard

                _guard.guard().reset()
                state.restore()
                skip_sync = False
            except HostsUpdatedInterrupt:
                # membership changed but our state is good: keep it
                skip_sync = True
            _reset_runtime()
            notification_manager.reset()
            state.on_reset()

    return wrapper
