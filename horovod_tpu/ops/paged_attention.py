"""Paged flash attention: the serving attention read, fused over the
page pool.

The paged memory plane (`serving/paged_kv.py`) stores KV in a physical
block pool ``[num_pages, page_tokens, kv_heads, head_dim]`` per layer,
with each slot mapping its sequence through an int32 page table. Until
this kernel, the attention READ re-assembled every slot's pages into a
transient contiguous ``[slots, max_len, kv_heads, head_dim]`` view
inside the prefill/decode executables (``jnp.take`` over the pool) —
a full-cache-size HBM copy per decode step before a single attention
FLOP ran. This kernel deletes that copy: the Pallas grid walks each
slot's page-table row via scalar prefetch and streams K/V blocks
straight from the pool into VMEM, one page per grid step, with the
FlashAttention-2 online softmax accumulating across pages. The gather
buffer does not exist in the lowered program (asserted by the
``serve_paged_attn`` hlo_audit program), and HBM reads scale with each
slot's LIVE tokens (the loop bound clamps at the slot's page frontier)
instead of ``slots × max_len``.

Layout/contract (the `ops/flash_attention.py` mold):

* grid ``(batch, kv_heads, n_logical_pages)`` — the page axis is the
  innermost (sequential) dimension, so the online-softmax state lives
  in VMEM scratch across page steps. All ``r = heads / kv_heads``
  query heads of a KV head ride one grid step (the GQA analog of the
  flash kernel's ``b // r`` index map: K/V pages are fetched once per
  KV head, never repeated per query head).
* the page table and per-slot lengths are SCALAR-PREFETCH operands
  (``pltpu.PrefetchScalarGridSpec``): the K/V BlockSpec index maps read
  the table to pick each step's physical page, which is exactly how the
  gather disappears — page indirection happens in the DMA descriptor,
  not as a materialized HBM copy.
* steps past a slot's live frontier clamp their index map to the last
  live page (Mosaic elides the re-fetch of an unchanged block) and are
  ``pl.when``-masked out of the accumulation, so ragged multi-slot
  batches pay HBM bytes for live tokens only.
* numerics mirror the dense gather path op-for-op where it is free
  (fp32 scores, the same ``/ sqrt(head_dim)``, the same −1e30 mask);
  the one structural difference is the online softmax's reassociated
  denominator sum, which bounds the divergence at ≤1 ulp of the dense
  ``jax.nn.softmax`` result (greedy tokens are identical — the parity
  tests in tests/test_paged_attention.py pin both).
* RoPE needs nothing here: q and the written k are rotated BEFORE the
  cache write (`models/transformer.py`), so pool contents are already
  position-encoded.

Interpret mode runs the same kernel on CPU (tests + the dryrun bench
leg exercise the real code path). Callers gate through
:func:`unsupported_reason` — the backward-compatible fallback ladder
(non-dividing head dims, oversized pages vs the VMEM budget, missing
Pallas lowering) falls back LOUDLY to the gather path and is counted
(``serve.paged_attn_fallbacks``).
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp

try:  # the "missing Pallas support" rung of the fallback ladder
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    _PALLAS = True
except ImportError:  # pragma: no cover - baked-in jax ships pallas
    pl = None
    pltpu = None
    _PALLAS = False

from .flash_attention import _NEG_INF, _STATS_LANES, _interpret, _vmem_budget

# Mosaic tile floors on real TPU: lanes (minor dim) and sublanes. The
# interpret path has no layout rules, so CPU tests run any geometry.
_LANES = 128
_SUBLANES = 8


def fwd_vmem_bytes(
    queries: int, head_dim: int, page_tokens: int
) -> int:
    """Worst-case VMEM bytes one grid step stages: the q block and fp32
    accumulator (``queries`` = q rows × grouped query heads), the
    double-buffered K/V page pair, the m/l statistics lanes, and the
    output block. The same budget discipline as the flash backward's
    ``bwd_vmem_bytes`` — shapes whose estimate exceeds
    ``HOROVOD_FLASH_VMEM_BUDGET`` ride the gather path instead."""
    q_rows = max(int(queries), 1)
    d = max(int(head_dim), 1)
    pt = max(int(page_tokens), 1)
    fp32 = 4
    q_block = q_rows * d * fp32
    acc = q_rows * d * fp32
    out = q_rows * d * fp32
    kv = 2 * 2 * pt * d * fp32  # k + v, double-buffered pipeline
    stats = 2 * q_rows * _STATS_LANES * fp32
    return q_block + acc + out + kv + stats


def unsupported_reason(
    head_dim: int,
    page_tokens: int,
    *,
    queries: int = 1,
    backend: Optional[str] = None,
) -> Optional[str]:
    """The fallback ladder, one rung per return: None means the kernel
    path is usable for this geometry; a string names the rung (callers
    log it loudly and count ``serve.paged_attn_fallbacks``)."""
    if not _PALLAS:
        return "Pallas is unavailable in this jax build"
    backend = backend or jax.default_backend()
    if backend == "tpu":
        # Mosaic layout floors apply only on real hardware — interpret
        # mode (CPU tests, dryrun benches) runs any geometry.
        if head_dim % _LANES:
            return (
                f"head_dim {head_dim} does not divide the {_LANES}-lane "
                "MXU tile"
            )
        if page_tokens % _SUBLANES:
            return (
                f"page_tokens {page_tokens} is not {_SUBLANES}-sublane "
                "aligned"
            )
    est = fwd_vmem_bytes(queries, head_dim, page_tokens)
    budget = _vmem_budget()
    if est > budget:
        return (
            f"VMEM estimate {est} B exceeds the budget {budget} B "
            "(oversized page_tokens or prefill chunk; "
            "HOROVOD_FLASH_VMEM_BUDGET)"
        )
    return None


def _kernel(
    tbl_ref,
    lens_ref,
    q_ref,
    k_ref,
    v_ref,
    o_ref,
    m_ref,
    l_ref,
    acc_ref,
    *,
    t: int,
    r: int,
    page_tokens: int,
    causal: bool,
    sqrt_d: float,
):
    b = pl.program_id(0)
    j = pl.program_id(2)
    rows = t * r
    start = lens_ref[b]
    kv_len = start + t
    n_live = (kv_len + page_tokens - 1) // page_tokens

    @pl.when(j == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    @pl.when(j < n_live)
    def _accumulate():
        q = q_ref[0].astype(jnp.float32)  # [t, r, d]
        q = q.reshape(rows, q.shape[-1])
        k = k_ref[0, :, 0, :].astype(jnp.float32)  # [page_tokens, d]
        v = v_ref[0, :, 0, :].astype(jnp.float32)
        # same op order as the dense oracle: fp32 score matmul, THEN
        # the / sqrt(head_dim) — scaling q first would round differently
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) / sqrt_d  # [rows, page_tokens]
        # row i of the packed [t*r] rows is query position start + i//r
        q_pos = start + jax.lax.broadcasted_iota(
            jnp.int32, (rows, page_tokens), 0
        ) // r
        key_pos = j * page_tokens + jax.lax.broadcasted_iota(
            jnp.int32, (rows, page_tokens), 1
        )
        if causal:
            s = jnp.where(key_pos <= q_pos, s, _NEG_INF)
        s = jnp.where(key_pos < kv_len, s, _NEG_INF)
        m = m_ref[:, :1]  # [rows, 1] — lanes are broadcast copies
        l = l_ref[:, :1]
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l, l_ref.shape)

    @pl.when(j == pl.num_programs(2) - 1)
    def _finish():
        l_safe = jnp.maximum(l_ref[:, :1], 1e-30)
        out = (acc_ref[...] / l_safe).astype(o_ref.dtype)
        o_ref[0] = out.reshape(t, r, out.shape[-1])


def paged_attention(
    q,
    k_pool,
    v_pool,
    page_table,
    lengths,
    *,
    causal: bool = True,
):
    """Attention of ``q`` against paged KV, read straight from the pool.

    Args:
      q: ``[batch, t, num_heads, head_dim]`` queries (RoPE already
        applied by the caller). ``t`` is 1 for decode, the chunk width
        for prefill.
      k_pool / v_pool: the physical block pools,
        ``[num_pages, page_tokens, kv_heads, head_dim]`` — this call's
        k/v already scattered in (the write stays pure XLA; only the
        read is fused here).
      page_table: ``[batch, n_logical]`` int32 — each row maps the
        slot's logical pages to physical pool pages. Sentinel /
        out-of-range entries are clamped in the index map; the length
        bound keeps them unattendable, exactly like the gather path's
        ``mode="clip"``.
      lengths: ``[batch]`` int32 — tokens already cached BEFORE this
        call (the engine's ``cache_index``); live KV length is
        ``lengths + t``.
      causal: apply the global causal mask ``key_pos <= query_pos``
        (serving decode is always causal; the flag exists for the
        mold's sake and symmetry with :func:`flash_attention`).

    Returns ``[batch, t, num_heads, head_dim]`` in q's dtype.
    """
    if not _PALLAS:
        raise RuntimeError(
            "paged_attention requires Pallas; gate calls through "
            "unsupported_reason()"
        )
    b, t, h, d = q.shape
    num_pages, page_tokens, kvh, dk = k_pool.shape
    if v_pool.shape != k_pool.shape:
        raise ValueError(
            f"k_pool {k_pool.shape} vs v_pool {v_pool.shape} mismatch"
        )
    if dk != d:
        raise ValueError(f"head_dim mismatch: q has {d}, pool has {dk}")
    if h % kvh:
        raise ValueError(
            f"num_heads ({h}) must be a multiple of kv_heads ({kvh})"
        )
    r = h // kvh
    page_table = jnp.asarray(page_table, jnp.int32)
    lengths = jnp.asarray(lengths, jnp.int32).reshape(b)
    n_logical = page_table.shape[1]
    if page_table.shape[0] != b:
        raise ValueError(
            f"page_table rows ({page_table.shape[0]}) != batch ({b})"
        )
    rows = t * r
    last_page = num_pages - 1

    def _page(bi, kv, j, tbl, lens):
        # steps past the slot's live frontier re-address the last live
        # page: Mosaic skips the DMA for an unchanged block, so dead
        # grid steps cost no HBM bytes (pl.when masks their compute)
        n_live = (lens[bi] + t + page_tokens - 1) // page_tokens
        jj = jnp.minimum(j, n_live - 1)
        return (jnp.minimum(tbl[bi, jj], last_page), 0, kv, 0)

    kernel = functools.partial(
        _kernel,
        t=t,
        r=r,
        page_tokens=page_tokens,
        causal=causal,
        sqrt_d=float(math.sqrt(d)),
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, kvh, n_logical),
        in_specs=[
            pl.BlockSpec(
                (1, t, r, d), lambda bi, kv, j, tbl, lens: (bi, 0, kv, 0)
            ),
            pl.BlockSpec((1, page_tokens, 1, d), _page),
            pl.BlockSpec((1, page_tokens, 1, d), _page),
        ],
        out_specs=pl.BlockSpec(
            (1, t, r, d), lambda bi, kv, j, tbl, lens: (bi, 0, kv, 0)
        ),
        scratch_shapes=[
            pltpu.VMEM((rows, _STATS_LANES), jnp.float32),
            pltpu.VMEM((rows, _STATS_LANES), jnp.float32),
            pltpu.VMEM((rows, d), jnp.float32),
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        interpret=_interpret(),
    )(page_table, lengths, q, k_pool, v_pool)
