"""Training a Llama/Mistral-shaped model on padded batches.

Demonstrates the modern-LM kernel surface in one script: RoPE + grouped
-query attention + causal sliding window + native right-padding, all
through the Pallas flash kernels, under hvd data parallelism. The
reference has no model zoo at all — this is the capability a user
migrating a modern LM stack needs (SURVEY.md §2.6 beyond-parity).

Run (8-way CPU simulation; interpret kernels unless flash is forced):
    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    JAX_PLATFORMS=cpu python examples/llama_shape_train.py --steps 8
Run (TPU): same script; flash kernels engage automatically.
"""

import argparse
import dataclasses
import os

import jax

# The sandbox's sitecustomize can force-select a TPU platform; honor an
# explicit JAX_PLATFORMS request at the config level (see tests/conftest.py).
if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu.models import Transformer, TransformerConfig


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--steps", type=int, default=10)
    parser.add_argument("--seq-len", type=int, default=64)
    parser.add_argument("--batch-per-rank", type=int, default=2)
    args = parser.parse_args()

    hvd.init()
    mesh = hvd.mesh()
    world = hvd.size()

    cfg = dataclasses.replace(
        TransformerConfig.tiny(causal=True),
        rope=True,            # rotary positions, no learned table
        num_kv_heads=2,       # grouped-query attention
        sliding_window=16,    # causal band
        max_len=args.seq_len,
    )
    model = Transformer(cfg)
    b, t = args.batch_per_rank, args.seq_len
    rng = np.random.default_rng(0)
    tokens = jnp.asarray(
        rng.integers(0, cfg.vocab_size, (world, b, t)), jnp.int32
    )
    # right-padded batch: lengths in [3t/4, t]
    lengths = jnp.asarray(
        rng.integers(3 * t // 4, t + 1, (world, b)), jnp.int32
    )
    params = model.init(
        jax.random.PRNGKey(0), tokens[0], train=False
    )
    params = hvd.broadcast_parameters(params)
    opt = hvd.DistributedOptimizer(optax.adam(1e-3))
    opt_state = opt.init(params)

    from functools import partial

    @partial(
        jax.shard_map, mesh=mesh,
        in_specs=(P(), P(), P(hvd.WORLD_AXIS), P(hvd.WORLD_AXIS)),
        out_specs=(P(), P(), P()),
        check_vma=False,
    )
    def train_step(params, opt_state, tokens, lengths):
        tokens, lengths = tokens[0], lengths[0]
        labels = jnp.roll(tokens, -1, axis=1)

        def loss_fn(p):
            logits = model.apply(
                p, tokens, train=True, lengths=lengths,
                rngs={"dropout": jax.random.PRNGKey(1)},
            )
            per_tok = optax.softmax_cross_entropy_with_integer_labels(
                logits.astype(jnp.float32), labels
            )
            # next-token targets: position lengths-1 would read its
            # label FROM the padding (and t-1 wraps), so the loss mask
            # stops one short of the valid length
            valid = jnp.arange(t)[None, :] < (lengths[:, None] - 1)
            return jnp.sum(jnp.where(valid, per_tok, 0.0)) / jnp.sum(valid)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = opt.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, jax.lax.pmean(loss, hvd.WORLD_AXIS)

    step = jax.jit(train_step)
    losses = []
    for _ in range(args.steps):
        params, opt_state, loss = step(params, opt_state, tokens, lengths)
        losses.append(float(loss))
    print(f"llama-shape loss {losses[0]:.4f} -> {losses[-1]:.4f}")
    assert losses[-1] < losses[0], "training must reduce the loss"


if __name__ == "__main__":
    main()
