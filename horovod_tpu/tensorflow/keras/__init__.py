"""``import horovod_tpu.tensorflow.keras as hvd`` — the tf.keras
binding surface (ref: horovod/tensorflow/keras/__init__.py [V]).

The reference mounts a Keras-flavored module beside the TF one: same
runtime (init/rank/size/ops), plus the Keras ``DistributedOptimizer``,
the four callbacks under ``hvd.callbacks``, and ``hvd.load_model``.
Here the TF shim already carries all of that (its optimizer IS the
Keras flavor — TF1 Session training is out of scope, docs/design.md),
so this module re-exports the core names explicitly and forwards
everything else (elastic, process sets, predicates, grouped ops…) to
:mod:`horovod_tpu.tensorflow` via module ``__getattr__`` — scripts
port by changing one import, whichever subset of the surface they use.
"""

from __future__ import annotations

# the callbacks submodule reference scripts address as hvd.callbacks
from .. import callbacks  # noqa: F401
from .. import (  # noqa: F401
    Adasum,
    Average,
    DistributedOptimizer,
    Max,
    Min,
    Product,
    Sum,
    allgather,
    allreduce,
    broadcast,
    broadcast_variables,
    cross_rank,
    cross_size,
    init,
    is_initialized,
    load_model,
    local_rank,
    local_size,
    rank,
    shutdown,
    size,
)


def __getattr__(name):
    """Everything else (elastic, alltoall/reducescatter, grouped ops,
    process sets, build predicates…) lives on the TF shim — forward so
    the keras module is never a narrower surface than its parent [V]."""
    import horovod_tpu.tensorflow as _tf

    return getattr(_tf, name)
