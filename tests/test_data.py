"""Data-sharding utilities: DistributedSampler-contract tests
(ref: the reference examples' DistributedSampler idiom [V])."""

import numpy as np
import pytest

from horovod_tpu.data import (
    ShardedIndexSampler,
    prefetch_to_device,
    shard_array,
)


def test_sampler_partitions_all_indices(hvd):
    n, world = 103, 8
    seen = []
    for r in range(world):
        s = ShardedIndexSampler(n, num_replicas=world, rank=r,
                                shuffle=False)
        idx = list(s)
        assert len(idx) == len(s) == 13  # ceil(103/8)
        seen.extend(idx)
    # every index appears; padding wraps around the head
    assert set(seen) == set(range(n))
    assert len(seen) == 13 * world


def test_sampler_epoch_shuffling_deterministic(hvd):
    a = ShardedIndexSampler(64, num_replicas=8, rank=3, seed=7)
    a.set_epoch(1)
    first = list(a)
    a.set_epoch(2)
    second = list(a)
    assert first != second
    a.set_epoch(1)
    assert list(a) == first


def test_sampler_drop_last(hvd):
    s = ShardedIndexSampler(103, num_replicas=8, rank=0, shuffle=False,
                            drop_last=True)
    assert len(s) == 12  # floor


def test_sampler_defaults_from_runtime(hvd):
    s = ShardedIndexSampler(32)
    assert s.num_replicas == hvd.size()
    assert s.rank == hvd.rank()


def test_shard_array(hvd):
    x = np.arange(17)
    shard = shard_array(x, num_replicas=8, rank=2)
    np.testing.assert_array_equal(shard, [4, 5])
    with pytest.raises(ValueError, match="cannot shard"):
        shard_array(np.arange(3), num_replicas=8, rank=0)


def test_prefetch_to_device_preserves_order_and_moves(hvd):
    import jax

    batches = [{"x": np.full((2,), i)} for i in range(5)]
    out = list(prefetch_to_device(iter(batches), size=3))
    assert len(out) == 5
    for i, b in enumerate(out):
        assert isinstance(b["x"], jax.Array)
        np.testing.assert_array_equal(np.asarray(b["x"]), [i, i])


def test_sampler_fewer_items_than_replicas(hvd):
    """n < num_replicas must still give every rank an equal, non-empty
    shard (an empty shard would deadlock the first SPMD collective)."""
    lens = set()
    for r in range(8):
        s = ShardedIndexSampler(3, num_replicas=8, rank=r, shuffle=False)
        idx = list(s)
        assert len(idx) == len(s) == 1
        assert 0 <= idx[0] < 3
        lens.add(len(idx))
    assert lens == {1}


class TestShardedFileDataset:
    """Petastorm-reader slot (VERDICT r4 #9): directory of .npz shards
    -> per-rank lazy batch iterable with sampler semantics."""

    def _write(self, tmp_path, n=100, d=3, rows_per_shard=16, labels=True):
        from horovod_tpu.data import write_shards

        rng = np.random.default_rng(0)
        x = rng.normal(size=(n, d)).astype(np.float32)
        y = np.arange(n, dtype=np.int32)
        k = write_shards(
            str(tmp_path), x, y if labels else None,
            rows_per_shard=rows_per_shard,
        )
        assert k == (n + rows_per_shard - 1) // rows_per_shard
        return x, y

    def test_roundtrip_single_rank_covers_all_rows(self, hvd, tmp_path):
        from horovod_tpu.data import ShardedFileDataset

        x, y = self._write(tmp_path)
        ds = ShardedFileDataset(
            str(tmp_path), batch_size=10, num_replicas=1, rank=0,
            shuffle=False,
        )
        assert len(ds) == 10
        seen_x, seen_y = [], []
        for xb, yb in ds:
            assert xb.shape == (10, 3) and yb.shape == (10,)
            seen_x.append(xb)
            seen_y.append(yb)
        got = np.concatenate(seen_x)[np.argsort(np.concatenate(seen_y))]
        np.testing.assert_allclose(got, x)

    def test_ranks_are_disjoint_and_cover(self, hvd, tmp_path):
        from horovod_tpu.data import ShardedFileDataset

        _, _ = self._write(tmp_path, n=96, rows_per_shard=10)
        rows = []
        for r in range(4):
            ds = ShardedFileDataset(
                str(tmp_path), batch_size=8, num_replicas=4, rank=r,
                shuffle=True, seed=3,
            )
            mine = [int(v) for _, yb in ds for v in yb]
            assert len(mine) == 24  # equal step counts (SPMD)
            rows.append(set(mine))
        assert set().union(*rows) == set(range(96))
        # disjoint modulo wrap-around padding (96 % 4 == 0 -> exact)
        for a in range(4):
            for b in range(a + 1, 4):
                assert not (rows[a] & rows[b])

    def test_epoch_shuffling_changes_order(self, hvd, tmp_path):
        from horovod_tpu.data import ShardedFileDataset

        self._write(tmp_path)
        ds = ShardedFileDataset(
            str(tmp_path), batch_size=10, num_replicas=1, rank=0,
            shuffle=True, seed=0,
        )
        ds.set_epoch(0)
        e0 = [int(v) for _, yb in ds for v in yb]
        ds.set_epoch(1)
        e1 = [int(v) for _, yb in ds for v in yb]
        assert e0 != e1 and sorted(e0) == sorted(e1)

    def test_labelless_directory_yields_bare_x(self, hvd, tmp_path):
        from horovod_tpu.data import ShardedFileDataset

        x, _ = self._write(tmp_path, labels=False)
        ds = ShardedFileDataset(
            str(tmp_path), batch_size=25, num_replicas=1, rank=0,
            shuffle=False,
        )
        assert ds.has_labels is False
        batches = list(ds)
        assert all(isinstance(b, np.ndarray) for b in batches)
        np.testing.assert_allclose(np.concatenate(batches), x)

    def test_empty_dir_raises(self, hvd, tmp_path):
        from horovod_tpu.data import ShardedFileDataset

        with pytest.raises(ValueError, match="no .npz"):
            ShardedFileDataset(str(tmp_path), batch_size=4)

    @pytest.mark.parametrize("native", ["1", "0"], ids=["native", "python"])
    def test_uncompressed_npy_format_roundtrip(
        self, hvd, tmp_path, monkeypatch, native
    ):
        """compressed=False writes .x.npy/.y.npy pairs served by the
        native mmap row-gather (csrc/npyio.cc) or the memmap fallback —
        both must agree with the npz path bit-for-bit."""
        from horovod_tpu.data import ShardedFileDataset, write_shards

        monkeypatch.setenv("HOROVOD_NATIVE", native)
        rng = np.random.default_rng(1)
        x = rng.normal(size=(90, 5)).astype(np.float32)
        y = np.arange(90, dtype=np.int64)
        write_shards(
            str(tmp_path), x, y, rows_per_shard=17, compressed=False
        )
        ds = ShardedFileDataset(
            str(tmp_path), batch_size=9, num_replicas=1, rank=0,
            shuffle=True, seed=5,
        )
        assert ds._fmt == "npy"
        seen_x, seen_y = [], []
        for xb, yb in ds:
            seen_x.append(xb)
            seen_y.append(yb)
        order = np.argsort(np.concatenate(seen_y))
        np.testing.assert_allclose(np.concatenate(seen_x)[order], x)

    def test_native_gather_matches_numpy(self, tmp_path):
        """Differential: the C row-gather equals numpy fancy indexing
        (same discipline as the other csrc twins, test_native.py)."""
        from horovod_tpu._native import loader

        x = np.random.default_rng(2).normal(size=(64, 3, 2)).astype(
            np.float32
        )
        p = str(tmp_path / "a.npy")
        np.save(p, x)
        r = loader.npy_reader(p)
        if r is None:
            pytest.skip("native library unavailable")
        idx = np.array([63, 0, 17, 17, 5], dtype=np.int64)
        np.testing.assert_array_equal(r.take(idx), x[idx])
        with pytest.raises(IndexError):
            r.take(np.array([64]))
        r.close()

    def test_native_reader_rejects_fortran_order(self, tmp_path):
        from horovod_tpu._native import loader

        if loader.get_lib() is None:
            pytest.skip("native library unavailable")
        p = str(tmp_path / "f.npy")
        np.save(p, np.asfortranarray(np.ones((8, 4), np.float32)))
        assert loader.npy_reader(p) is None  # falls back to memmap path
