"""Ring attention: exact attention over sequences sharded across chips.

Long-context sequence/context parallelism is absent from the reference
(SURVEY.md §5.7 — "no ring attention, no context parallel ... of any
kind"); the survey's build plan adds it as the TPU-native long-context
path: shard the sequence over the 'sp' mesh axis and rotate K/V blocks
around the ring with `ppermute` while accumulating attention online
(flash-attention-style running max/denominator), so each chip only ever
holds seq_len/sp keys — memory O(T/sp) with exact results, and each
ppermute hop overlaps with the block's compute on ICI.

Per-device code for use inside shard_map. Causal masking uses global
positions derived from each block's rank of origin.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def ring_attention(q, k, v, axis_name: str = "sp", causal: bool = False):
    """q, k, v: [B, T_local, H, Dh] (this chip's sequence shard).

    Returns [B, T_local, H, Dh] — exact softmax(QKᵀ)V over the full
    (sp·T_local)-token sequence.
    """
    sp = lax.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    b, t, h, d = q.shape
    scale = 1.0 / jnp.sqrt(d).astype(jnp.float32)
    qf = q.astype(jnp.float32)

    q_pos = my * t + jnp.arange(t)  # global positions of our queries

    # Ring schedule: at step i we hold the block that originated on rank
    # (my - i) mod sp; after computing we pass it to (my + 1) mod sp.
    perm = [(j, (j + 1) % sp) for j in range(sp)]

    def step(carry, i):
        k_cur, v_cur, out, m, denom = carry
        src = (my - i) % sp
        k_pos = src * t + jnp.arange(t)
        scores = (
            jnp.einsum(
                "bqhd,bkhd->bhqk",
                qf,
                k_cur.astype(jnp.float32),
                preferred_element_type=jnp.float32,
            )
            * scale
        )
        if causal:
            mask = q_pos[:, None] >= k_pos[None, :]  # [Tq, Tk]
            scores = jnp.where(mask[None, None], scores, -jnp.inf)
        block_max = jnp.max(scores, axis=-1)  # [B,H,Tq]
        new_m = jnp.maximum(m, block_max)
        # With causal masking a whole block can be -inf; guard the exp.
        safe_m = jnp.where(jnp.isfinite(new_m), new_m, 0.0)
        correction = jnp.exp(jnp.where(jnp.isfinite(m), m - safe_m, -jnp.inf))
        p = jnp.exp(scores - safe_m[..., None])  # masked entries → 0
        denom = denom * correction + jnp.sum(p, axis=-1)
        out = out * correction[..., None] + jnp.einsum(
            "bhqk,bkhd->bhqd", p, v_cur.astype(jnp.float32)
        )
        k_next = lax.ppermute(k_cur, axis_name, perm)
        v_next = lax.ppermute(v_cur, axis_name, perm)
        return (k_next, v_next, out, new_m, denom), None

    out0 = jnp.zeros((b, h, t, d), jnp.float32)
    m0 = jnp.full((b, h, t), -jnp.inf, jnp.float32)
    denom0 = jnp.zeros((b, h, t), jnp.float32)
    (_, _, out, _, denom), _ = lax.scan(
        step, (k, v, out0, m0, denom0), jnp.arange(sp)
    )
    out = out / jnp.maximum(denom[..., None], 1e-30)
    return jnp.einsum("bhqd->bqhd", out).astype(q.dtype)
