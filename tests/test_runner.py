"""Runner tests — the reference's `test/single/test_run.py` model
(SURVEY.md §4.2): hostfile parsing, slot math, env construction, command
assembly asserted in-process, no cluster. Plus live KV-rendezvous and
signed-RPC round-trips on localhost."""

import os
import subprocess
import sys
import textwrap

import pytest

from horovod_tpu.runner import (
    BasicClient,
    BasicService,
    HostInfo,
    RendezvousServer,
    assign_slots,
    make_secret_key,
    parse_hostfile,
    parse_hosts,
)
from horovod_tpu.runner.launch import (
    _runtime_env,
    _ssh_wrap,
    parse_args,
    worker_envs,
)
from horovod_tpu.runner.rendezvous import RendezvousClient
from horovod_tpu.runner.tpu_discovery import chips_per_host, discover_hosts


class TestHosts:
    def test_parse_hosts(self):
        hosts = parse_hosts("a:4, b:2,c")
        assert hosts == [HostInfo("a", 4), HostInfo("b", 2), HostInfo("c", 1)]

    def test_parse_ipv6(self):
        assert HostInfo.from_string("[::1]:4") == HostInfo("::1", 4)
        assert HostInfo.from_string("[fe80::2]") == HostInfo("fe80::2", 1)
        assert HostInfo.from_string("fe80::2") == HostInfo("fe80::2", 1)
        with pytest.raises(ValueError):
            HostInfo.from_string("[::1")
        with pytest.raises(ValueError):
            HostInfo.from_string("[::1]x")

    def test_parse_hosts_rejects_dupes_and_garbage(self):
        with pytest.raises(ValueError):
            parse_hosts("a:4,a:2")
        with pytest.raises(ValueError):
            parse_hosts("a:zero")
        with pytest.raises(ValueError):
            parse_hosts("  ")

    def test_parse_hostfile(self, tmp_path):
        f = tmp_path / "hostfile"
        f.write_text(
            textwrap.dedent(
                """\
                # cluster
                worker-0 slots=4
                worker-1:4
                worker-2   # bare host = 1 slot
                """
            )
        )
        hosts = parse_hostfile(str(f))
        assert hosts == [
            HostInfo("worker-0", 4),
            HostInfo("worker-1", 4),
            HostInfo("worker-2", 1),
        ]

    def test_assign_slots_numbering(self):
        # Reference numbering: rank-major by host, local_rank within host,
        # cross_rank = host index.
        slots = assign_slots([HostInfo("a", 2), HostInfo("b", 2)], np=4)
        assert [(s.rank, s.hostname, s.local_rank, s.cross_rank) for s in slots] == [
            (0, "a", 0, 0),
            (1, "a", 1, 0),
            (2, "b", 0, 1),
            (3, "b", 1, 1),
        ]
        assert all(s.size == 4 and s.cross_size == 2 for s in slots)

    def test_assign_slots_partial_and_overflow(self):
        slots = assign_slots([HostInfo("a", 4), HostInfo("b", 4)], np=3)
        assert [s.hostname for s in slots] == ["a", "a", "a"]
        assert slots[0].cross_size == 1
        with pytest.raises(ValueError):
            assign_slots([HostInfo("a", 2)], np=3)

    def test_slot_env_contract(self):
        (s,) = assign_slots([HostInfo("h", 1)], np=1)
        env = s.to_env()
        for key in (
            "HOROVOD_RANK",
            "HOROVOD_SIZE",
            "HOROVOD_LOCAL_RANK",
            "HOROVOD_LOCAL_SIZE",
            "HOROVOD_CROSS_RANK",
            "HOROVOD_CROSS_SIZE",
        ):
            assert key in env


class TestCLI:
    def test_flag_to_env_translation(self):
        args = parse_args(
            [
                "-np", "4",
                "--fusion-threshold-mb", "32",
                "--cycle-time-ms", "3.5",
                "--timeline-filename", "/tmp/t.json",
                "--autotune",
                "--", "python", "train.py",
            ]
        )
        env = _runtime_env(args)
        assert env["HOROVOD_FUSION_THRESHOLD"] == str(32 * 1024 * 1024)
        assert env["HOROVOD_CYCLE_TIME"] == "3.5"
        assert env["HOROVOD_TIMELINE"] == "/tmp/t.json"
        assert env["HOROVOD_AUTOTUNE"] == "1"
        assert args.command == ["python", "train.py"]

    def test_worker_envs_per_slot(self):
        slots = assign_slots([HostInfo("localhost", 4)], np=4)
        blocks = worker_envs(
            slots, "per-slot", "127.0.0.1", 1234, 5678, "ab" * 32
        )
        assert len(blocks) == 4
        for i, b in enumerate(blocks):
            assert b["HOROVOD_RANK"] == str(i)
            assert b["HOROVOD_LOCAL_SIZE"] == "1"
            assert b["HOROVOD_PROCESS_ID"] == str(i)
            assert b["HOROVOD_NUM_PROCESSES"] == "4"
            assert b["HOROVOD_COORDINATOR_PORT"] == "5678"
            assert b["HOROVOD_GLOO_RENDEZVOUS_ADDR"] == "127.0.0.1"
            assert b["JAX_PLATFORMS"] == "cpu"

    def test_worker_envs_per_host(self):
        slots = assign_slots([HostInfo("w0", 4), HostInfo("w1", 4)], np=8)
        blocks = worker_envs(slots, "per-host", "w0", 1234, 5678, "ab" * 32)
        assert len(blocks) == 2  # one process per host
        assert blocks[0]["HOROVOD_RANK"] == "0"
        assert blocks[1]["HOROVOD_RANK"] == "4"
        assert blocks[1]["HOROVOD_LOCAL_SIZE"] == "4"
        assert blocks[1]["HOROVOD_PROCESS_ID"] == "1"

    def test_single_process_gets_no_coordinator(self):
        slots = assign_slots([HostInfo("localhost", 1)], np=1)
        (b,) = worker_envs(slots, "per-slot", "127.0.0.1", 1, 2, "00")
        assert "HOROVOD_COORDINATOR_ADDR" not in b

    def test_ssh_command_assembly(self):
        # Reference test_run.py asserts on generated command strings [V].
        cmd = _ssh_wrap(
            "worker-1", 2222,
            {"HOROVOD_RANK": "3", "HOROVOD_SECRET_KEY": "deadbeef"},
            ["python", "t.py"],
        )
        assert cmd[0] == "ssh"
        assert "-p" in cmd and "2222" in cmd
        assert cmd[-2] == "worker-1"
        assert "HOROVOD_RANK=3" in cmd[-1]
        assert "python t.py" in cmd[-1]
        # secret travels over stdin, never the command line
        assert "deadbeef" not in " ".join(cmd)
        assert "read -r HOROVOD_SECRET_KEY" in cmd[-1]

    def test_coordinator_is_first_worker_host(self):
        slots = assign_slots([HostInfo("w0", 4), HostInfo("w1", 4)], np=8)
        blocks = worker_envs(slots, "per-host", "head", 1234, 9874, "00")
        assert all(b["HOROVOD_COORDINATOR_ADDR"] == "w0" for b in blocks)
        assert all(b["HOROVOD_GLOO_RENDEZVOUS_ADDR"] == "head" for b in blocks)


class TestRendezvous:
    def test_kv_round_trip(self):
        server = RendezvousServer()
        port = server.start()
        try:
            client = RendezvousClient("127.0.0.1", port)
            assert client.get("s", "k") is None
            client.put("s", "k", b"value")
            assert client.get("s", "k") == b"value"
            assert client.wait("s", "k", timeout=1) == b"value"
            assert client.keys("s") == ["k"]
            client._request("DELETE", "/kv/s")
            assert client.get("s", "k") is None
        finally:
            server.stop()

    def test_hmac_rejects_unauthenticated(self):
        key = make_secret_key()
        server = RendezvousServer(secret_key=key)
        port = server.start()
        try:
            good = RendezvousClient("127.0.0.1", port, secret_key=key)
            good.put("s", "k", b"v")
            assert good.get("s", "k") == b"v"
            bad = RendezvousClient("127.0.0.1", port)  # no key
            with pytest.raises(RuntimeError):
                bad.put("s", "k2", b"evil")
            assert bad.get("s", "k") is None  # 403 → None
            wrong = RendezvousClient(
                "127.0.0.1", port, secret_key=make_secret_key()
            )
            assert wrong.get("s", "k") is None
        finally:
            server.stop()

    def test_wait_times_out(self):
        server = RendezvousServer()
        port = server.start()
        try:
            client = RendezvousClient("127.0.0.1", port)
            with pytest.raises(TimeoutError):
                client.wait("s", "missing", timeout=0.2)
        finally:
            server.stop()


class TestService:
    def test_rpc_round_trip_and_auth(self):
        key = make_secret_key()
        svc = BasicService("driver", key)
        svc.register("ping", lambda req: {"echo": req.get("payload")})
        port = svc.start()
        try:
            client = BasicClient("127.0.0.1", port, key)
            out = client.request({"type": "ping", "payload": [1, 2, 3]})
            assert out == {"ok": True, "echo": [1, 2, 3]}
            out = client.request({"type": "nope"})
            assert out["ok"] is False and "unknown" in out["error"]
            # wrong key: server drops the frame, client sees closed conn
            evil = BasicClient("127.0.0.1", port, make_secret_key(), timeout=2)
            with pytest.raises((ConnectionError, OSError)):
                evil.request({"type": "ping"})
        finally:
            svc.stop()

    def test_handler_exception_is_reported(self):
        key = make_secret_key()
        svc = BasicService("driver", key)

        def boom(req):
            raise ValueError("bad slot")

        svc.register("boom", boom)
        port = svc.start()
        try:
            client = BasicClient("127.0.0.1", port, key)
            out = client.request({"type": "boom"})
            assert out["ok"] is False and "bad slot" in out["error"]
        finally:
            svc.stop()


class TestBroadcastObject:
    def test_broadcast_via_kv_root_publishes(self, hvd, monkeypatch):
        """Single-process half of the multi-controller broadcast: the
        root-owning process must publish the pickled payload to the
        rendezvous KV (the remote side is covered by the e2e launch)."""
        from horovod_tpu.runner.rendezvous import (
            RendezvousClient,
            broadcast_via_kv,
        )

        key = make_secret_key()
        server = RendezvousServer(secret_key=key)
        port = server.start()
        try:
            monkeypatch.setenv("HOROVOD_GLOO_RENDEZVOUS_ADDR", "127.0.0.1")
            monkeypatch.setenv("HOROVOD_GLOO_RENDEZVOUS_PORT", str(port))
            monkeypatch.setenv("HOROVOD_SECRET_KEY", key.hex())
            hvd.shutdown()
            hvd.init()
            obj = {"step": 7, "lr": 0.1}
            out = broadcast_via_kv(obj, root_rank=0, name="state")
            assert out == obj
            reader = RendezvousClient("127.0.0.1", port, secret_key=key)
            import pickle

            # round counter is folded into the key so a reused name
            # never returns a stale previous-round payload
            assert pickle.loads(reader.wait("broadcast", "state.0", 2)) == obj
            obj2 = {"step": 8}
            assert broadcast_via_kv(obj2, root_rank=0, name="state") == obj2
            assert pickle.loads(reader.wait("broadcast", "state.1", 2)) == obj2
        finally:
            server.stop()


class TestDiscovery:
    def test_explicit_override_wins(self):
        hosts = discover_hosts({"HOROVOD_TPU_HOSTS": "a:4,b:4"})
        assert hosts == [HostInfo("a", 4), HostInfo("b", 4)]

    def test_tpu_metadata(self):
        hosts = discover_hosts(
            {
                "TPU_WORKER_HOSTNAMES": "t0,t1",
                "TPU_CHIPS_PER_HOST_BOUNDS": "2,2,1",
            }
        )
        assert hosts == [HostInfo("t0", 4), HostInfo("t1", 4)]

    def test_chips_per_host_bounds(self, monkeypatch):
        monkeypatch.setenv("TPU_CHIPS_PER_HOST_BOUNDS", "2,2,1")
        assert chips_per_host() == 4


_LAUNCH_SCRIPT = """
import os
import jax
import horovod_tpu as hvd

hvd.init()
assert hvd.size() == 2, hvd.size()
assert hvd.cross_size() == 2
assert jax.process_count() == 2
rank = hvd.rank()
x = hvd.replicate(float(rank + 1))
out = hvd.allreduce(x, op=hvd.Sum)
assert float(hvd.first(out)) == 3.0, out
print("WORKER_OK", rank)
"""


@pytest.mark.slow
def test_end_to_end_two_process_launch(tmp_path):
    """Live parity with the reference's `horovodrun -np 2 python ...`
    localhost test mode (SURVEY.md §4.1): two real processes, real
    jax.distributed coordination, real collective, exit codes collected."""
    script = tmp_path / "worker.py"
    script.write_text(_LAUNCH_SCRIPT)
    env = dict(os.environ)
    # The workers must not inherit the 8-device test flag: each process
    # is its own 1-chip host. Clearing PALLAS_AXON_POOL_IPS keeps the
    # sandbox's sitecustomize from force-registering the TPU backend in
    # what is a CPU-simulation launch.
    env.pop("XLA_FLAGS", None)
    env.pop("JAX_PLATFORMS", None)
    env["PALLAS_AXON_POOL_IPS"] = ""
    out_dir = tmp_path / "logs"
    proc = subprocess.run(
        [
            sys.executable, "-m", "horovod_tpu.runner",
            "-np", "2", "--placement", "per-slot",
            "--output-filename", str(out_dir),
            "--", sys.executable, str(script),
        ],
        env=env,
        timeout=300,
        capture_output=True,
    )
    logs = "\n".join(
        p.read_text() for p in sorted(out_dir.glob("rank.*"))
    )
    assert proc.returncode == 0, f"launcher failed:\n{proc.stderr.decode()}\n{logs}"
    assert "WORKER_OK 0" in logs and "WORKER_OK 1" in logs


def test_failure_path_kills_all_and_reports(tmp_path):
    """§3.3: on any nonzero exit → terminate all, return the code."""
    script = tmp_path / "worker.py"
    script.write_text(
        "import os, sys, time\n"
        "if os.environ['HOROVOD_RANK'] == '0':\n"
        "    sys.exit(3)\n"
        "time.sleep(300)\n"
    )
    env = dict(os.environ)
    # Generous timeout: under full-suite load the driver's jax import
    # alone can take tens of seconds on a loaded single-core box; the
    # sleeping worker is SIGTERMed by the driver, so the real duration
    # is driver startup + ~15 s, not the sleep.
    proc = subprocess.run(
        [
            sys.executable, "-m", "horovod_tpu.runner",
            "-np", "2", "--placement", "per-slot",
            "--", sys.executable, str(script),
        ],
        env=env,
        timeout=240,
        capture_output=True,
    )
    assert proc.returncode == 3


class TestConfigFile:
    """hvdrun --config-file params YAML (ref: horovodrun --config-file,
    upstream runner/launch.py [V]). Precedence: CLI > file > defaults."""

    def _write(self, tmp_path, text):
        f = tmp_path / "params.yaml"
        f.write_text(text)
        return str(f)

    def test_yaml_values_with_nesting(self, tmp_path):
        path = self._write(
            tmp_path,
            "num-proc: 8\n"
            "placement: per-slot\n"
            "fusion:\n"
            "  threshold-mb: 32\n"
            "cycle-time-ms: 3.5\n"
            "autotune: true\n",
        )
        args = parse_args(
            ["--config-file", path, "--", "python", "train.py"]
        )
        assert args.num_proc == 8
        assert args.placement == "per-slot"
        assert args.fusion_threshold_mb == 32.0
        assert args.cycle_time_ms == 3.5
        assert args.autotune is True
        assert args.command == ["python", "train.py"]
        env = _runtime_env(args)
        assert env["HOROVOD_FUSION_THRESHOLD"] == str(32 * 1024 * 1024)

    def test_cli_overrides_config_file(self, tmp_path):
        path = self._write(
            tmp_path, "num-proc: 8\ncycle-time-ms: 3.5\n"
        )
        args = parse_args(
            ["--config-file", path, "-np", "2", "--", "x"]
        )
        assert args.num_proc == 2      # CLI wins
        assert args.cycle_time_ms == 3.5  # file still applies

    def test_underscore_keys_and_string_coercion(self, tmp_path):
        path = self._write(
            tmp_path, "num_proc: '4'\nstart_timeout: '30'\n"
        )
        args = parse_args(["--config-file", path, "--", "x"])
        assert args.num_proc == 4
        assert args.start_timeout == 30.0

    def test_unknown_key_fails_fast(self, tmp_path):
        path = self._write(tmp_path, "num-proc: 4\nnot-a-flag: 1\n")
        with pytest.raises(SystemExit):
            parse_args(["--config-file", path, "--", "x"])

    def test_np_still_required_without_config(self):
        with pytest.raises(SystemExit):
            parse_args(["--cycle-time-ms", "3.5", "--", "x"])

    def test_command_not_scanned_for_config_flag(self, tmp_path):
        """--config-file appearing only inside the launched command must
        not be treated as hvdrun's own flag."""
        args = parse_args(
            ["-np", "2", "--", "python", "t.py", "--config-file", "u.yaml"]
        )
        assert args.config_file is None
        assert args.command == [
            "python", "t.py", "--config-file", "u.yaml"
        ]

    def test_command_config_flag_without_separator(self, tmp_path):
        """Same, without the `--` separator: the pre-scan must stop at
        the first positional (start of the command)."""
        args = parse_args(
            ["-np", "2", "python", "t.py", "--config-file", "u.yaml"]
        )
        assert args.config_file is None
        assert args.command == [
            "python", "t.py", "--config-file", "u.yaml"
        ]


class TestVersionConsistency:
    """Same-version gang guard at init (ref: the launch driver's probe
    across hosts, horovod/runner/driver/driver_service.py [V])."""

    class _Cfg:
        def __init__(self, port):
            self.rendezvous_addr = "127.0.0.1"
            self.rendezvous_port = port
            self.secret_key_hex = None
            self.gloo_timeout_seconds = 1.0

    class _Topo:
        def __init__(self, rank):
            self.rank = rank

    def test_same_version_passes_and_rank0_publishes(self):
        from horovod_tpu.runner.rendezvous import check_version_consistency

        server = RendezvousServer()
        port = server.start()
        try:
            cfg = self._Cfg(port)
            check_version_consistency(cfg, self._Topo(0))
            # rank 0 published its version for the others, in the
            # elastic-epoch-keyed scope
            client = RendezvousClient("127.0.0.1", port)
            import horovod_tpu

            assert client.get("version.0", "0").decode() == \
                horovod_tpu.__version__
            check_version_consistency(cfg, self._Topo(1))  # matches
        finally:
            server.stop()

    def test_mismatch_raises_with_both_versions(self):
        from horovod_tpu.runner.rendezvous import check_version_consistency

        server = RendezvousServer()
        port = server.start()
        try:
            client = RendezvousClient("127.0.0.1", port)
            client.put("version.0", "0", b"9.9.9-other")
            with pytest.raises(RuntimeError, match="9.9.9-other"):
                check_version_consistency(
                    self._Cfg(port), self._Topo(2)
                )
        finally:
            server.stop()

    def test_stale_epoch_key_ignored(self, monkeypatch):
        """A previous elastic incarnation's version key must not fake a
        skew: the scope is keyed by HOROVOD_ELASTIC_EPOCH."""
        from horovod_tpu.runner.rendezvous import check_version_consistency

        server = RendezvousServer()
        port = server.start()
        try:
            client = RendezvousClient("127.0.0.1", port)
            client.put("version.0", "0", b"0.0.1-previous-gang")
            monkeypatch.setenv("HOROVOD_ELASTIC_EPOCH", "3")
            # epoch-3 rank 0 publishes the current version; rank 1 then
            # compares within epoch 3 and must NOT see the epoch-0 key
            check_version_consistency(self._Cfg(port), self._Topo(0))
            check_version_consistency(self._Cfg(port), self._Topo(1))
        finally:
            server.stop()

    def test_auth_skew_warns_not_fails(self):
        """A non-200 from the KV (e.g. secret out of sync mid-re-key)
        must warn, never fail init — only a real mismatch raises."""
        from horovod_tpu.runner.rendezvous import check_version_consistency

        key = make_secret_key()
        server = RendezvousServer(secret_key=key)
        port = server.start()
        try:
            cfg = self._Cfg(port)  # client has NO secret → 403 on put
            check_version_consistency(cfg, self._Topo(1))
        finally:
            server.stop()

    def test_timeout_warns_but_passes(self):
        from horovod_tpu.runner.rendezvous import check_version_consistency

        server = RendezvousServer()
        port = server.start()
        try:
            # rank 0 never publishes; non-root must not hard-fail
            check_version_consistency(self._Cfg(port), self._Topo(1))
        finally:
            server.stop()

    def test_no_rendezvous_is_noop(self):
        from horovod_tpu.runner.rendezvous import check_version_consistency

        cfg = self._Cfg(0)
        cfg.rendezvous_addr = None
        check_version_consistency(cfg, self._Topo(1))


class TestCheckBuild:
    """hvdrun --check-build prints the build summary and exits 0 without
    needing -np or a command (ref: horovodrun --check-build [V])."""

    def test_check_build_runs_without_np(self, capsys):
        from horovod_tpu.runner.launch import run_commandline

        assert run_commandline(["--check-build"]) == 0
        out = capsys.readouterr().out
        assert "Available Frameworks" in out
        assert "XLA collectives" in out
        assert "[X] JAX / Flax" in out
        # GPU-era transports must honestly report absent
        assert "[ ] NCCL" in out

    def test_short_flag(self, capsys):
        from horovod_tpu.runner.launch import run_commandline

        assert run_commandline(["-cb"]) == 0
        assert "Available Controllers" in capsys.readouterr().out

    def test_check_build_in_command_not_ours(self):
        """-cb inside the launched command must not trigger the mode."""
        args = parse_args(["-np", "2", "--", "python", "t.py", "-cb"])
        assert args.check_build is False
        assert args.command == ["python", "t.py", "-cb"]
