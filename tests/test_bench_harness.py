"""The bench harnesses are round artifacts — their sweep/efficiency
logic must hold without running a full benchmark (VERDICT r1 #3: a
world-size sweep with scaling_efficiency output, pod-ready)."""

import json
import os
import subprocess
import sys

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from bench_allreduce import (  # noqa: E402
    ring_factor,
    scaling_efficiency,
    sweep_worlds,
)


def test_sweep_worlds_small_box():
    assert sweep_worlds(1) == [1]
    assert sweep_worlds(8) == [1, 2, 4, 8]
    assert sweep_worlds(6) == [1, 2, 4, 6]


def test_sweep_worlds_pod_starts_at_8():
    """On a pod slice the sweep is the north star's 8→256 window."""
    assert sweep_worlds(256) == [8, 16, 32, 64, 128, 256]
    assert sweep_worlds(64) == [8, 16, 32, 64]


def test_ring_factor():
    assert ring_factor(1) == 1.0
    assert ring_factor(2) == 1.0
    assert abs(ring_factor(8) - 1.75) < 1e-12
    assert abs(ring_factor(256) - 2 * 255 / 256) < 1e-12


def test_scaling_efficiency_vs_base():
    base, eff = scaling_efficiency({1: 10.0, 2: 9.0, 4: 8.0})
    assert base == 1
    assert eff[1] == 1.0
    assert abs(eff[2] - 0.9) < 1e-12
    assert abs(eff[4] - 0.8) < 1e-12


def test_scaling_efficiency_empty():
    assert scaling_efficiency({}) == (None, {})


@pytest.mark.slow
def test_bench_allreduce_cpu_sim_end_to_end():
    """The sweep runs on the simulated mesh and emits both per-point
    busbw lines and the scaling summary, parseable."""
    from _hermetic import hermetic_cpu_env

    env = hermetic_cpu_env(n_devices=8)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["BENCH_SIZES"] = "4096,65536"
    env["BENCH_ITERS"] = "3"
    proc = subprocess.run(
        [sys.executable, os.path.join(_REPO, "bench_allreduce.py")],
        env=env, capture_output=True, text=True, timeout=600,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [json.loads(ln) for ln in proc.stdout.splitlines()
             if ln.strip().startswith("{")]
    busbw = [ln for ln in lines if ln["metric"] == "allreduce_busbw"]
    scaling = [ln for ln in lines if ln["metric"] == "allreduce_scaling"]
    assert {ln["world"] for ln in busbw} == {1, 2, 4, 8}
    assert {ln["world"] for ln in scaling} == {1, 2, 4, 8}
    assert all(ln["base_world"] == 1 for ln in scaling)
    base_line = next(ln for ln in scaling if ln["world"] == 1)
    assert base_line["value"] == 1.0
