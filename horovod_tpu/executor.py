"""In-cluster function executor — the Ray/Spark integration surface.

Parity with the reference's cluster integrations (ref:
horovod/ray/runner.py ``RayExecutor`` (start/run/execute/shutdown) and
``horovod.spark.run(fn)`` where each task runs one rank [V] —
SURVEY.md §2.5): hand the framework a Python function and get back one
result per rank, with the whole runner stack (rendezvous, HMAC'd env
contract, jax.distributed wiring) managed for you.

Neither Ray nor Spark schedulers exist on a TPU pod; the scheduler here
is the runner itself (per-host processes over ssh, per-slot locally).
``RayExecutor`` is kept as a thin alias so reference scripts port by
changing only the import; if the real ray is installed it can be swapped
in transparently later.
"""

from __future__ import annotations

import os
import pickle
import sys
import tempfile
from typing import Any, Callable, List, Optional, Sequence

from .runner import launch as _launch
from .runner.rendezvous import RendezvousServer
from .runner.secret import make_secret_key


def _dump_payload(obj, f) -> None:
    """Serialize the (fn, args, kwargs) payload. cloudpickle when
    available (ref: horovod.spark serializes the train fn with
    cloudpickle so closures and script-/notebook-defined functions
    work [V]); plain pickle otherwise (importable-by-reference
    functions only). The worker loads with stdlib ``pickle.load`` —
    cloudpickle emits standard pickle bytecode — but a payload pickled
    BY VALUE references cloudpickle internals, so multi-host jobs
    shipping closures need cloudpickle importable on every worker
    host too (same requirement as the reference's Spark workers)."""
    try:
        import cloudpickle as _cp
    except ImportError:
        pickle.dump(obj, f)
    else:
        _cp.dump(obj, f)


def _default_coordinator_port() -> int:
    """Per-job pseudo-random coordinator port: the port binds on worker
    0's host, unprobeable from the driver, so freeness can't be
    verified — but a random default keeps two concurrent multi-host
    jobs from colliding on one fixed number (the reference's runner
    derives per-job ports the same way [V])."""
    import random

    return 9874 + random.SystemRandom().randrange(8000)


def _collect_results(
    out_dir: str, expected_ranks: Sequence[int], code: int
) -> List[Any]:
    """Read per-rank result pickles, surfacing a worker's actual
    exception before the bare exit code (shared by Executor.run and
    ElasticRayExecutor.run — the collection rules must not diverge).

    On a failed job, scan EVERY expected rank for an error pickle
    before complaining about a missing one: in a multi-rank gang the
    raising rank writes its error while its peers get SIGTERM'd mid-fn
    (no pickle at all), and "rank 1 raised: ValueError…" must beat
    "rank 0 produced no result"."""
    if code != 0:
        for rank in expected_ranks:
            path = os.path.join(out_dir, f"result.{rank}.pkl")
            if not os.path.exists(path):
                continue
            with open(path, "rb") as f:
                status, value = pickle.load(f)
            if status == "error":
                raise RuntimeError(f"rank {rank} raised: {value}")
    results: List[Any] = []
    for rank in expected_ranks:
        path = os.path.join(out_dir, f"result.{rank}.pkl")
        if not os.path.exists(path):
            raise RuntimeError(
                f"executor job failed with exit code {code}: "
                f"rank {rank} produced no result"
            )
        with open(path, "rb") as f:
            status, value = pickle.load(f)
        if status == "error":
            raise RuntimeError(f"rank {rank} raised: {value}")
        results.append(value)
    if code != 0:
        raise RuntimeError(f"executor job failed with exit code {code}")
    return results


class Executor:
    """Run functions across a horovod_tpu worker set
    (ref: RayExecutor's start/run/shutdown lifecycle [V])."""

    def __init__(
        self,
        num_workers: int,
        hosts: Optional[str] = None,
        placement: str = "auto",
        env: Optional[dict] = None,
        start_timeout: float = 600.0,
        coordinator_port: Optional[int] = None,
        work_dir: Optional[str] = None,
    ) -> None:
        """Multi-host jobs (``hosts=``) require ``work_dir`` on a shared
        filesystem: the pickled function and per-rank results travel
        through it (the reference's Ray/Spark integrations lean on their
        schedulers' object stores for the same job [V])."""
        self.num_workers = int(num_workers)
        self.hosts = hosts
        self.placement = placement
        self.env = dict(env or {})
        self.start_timeout = start_timeout
        self.coordinator_port = coordinator_port
        self.work_dir = work_dir
        self._started = False

    # -- lifecycle ----------------------------------------------------

    def start(self) -> None:
        """Validate host resolution; actual processes are per-run (TPU
        workers own the chip exclusively, so a standing worker pool
        would pin the slice between runs — the reference's Ray actors
        hold GPUs the same way, which is what shutdown() is for)."""
        argv = ["-np", str(self.num_workers)]
        if self.hosts:
            argv += ["-H", self.hosts]
        argv += ["--", "true"]
        args = _launch.parse_args(argv)
        self._hosts = _launch._resolve_hosts(args)
        self._started = True

    def shutdown(self) -> None:
        self._started = False

    def __enter__(self) -> "Executor":
        self.start()
        return self

    def __exit__(self, *exc) -> None:
        self.shutdown()

    # -- execution ----------------------------------------------------

    def run(
        self,
        fn: Callable,
        args: Sequence = (),
        kwargs: Optional[dict] = None,
    ) -> List[Any]:
        """Execute ``fn(*args, **kwargs)`` on every launched process;
        returns the results ordered by rank (ref: RayExecutor.run [V]).

        per-slot placement launches one process per rank → one result
        per rank; per-host placement launches one process per host
        (driving local_size chips) → one result per host, keyed by its
        lead rank — same per-process semantics as the reference's
        fn-per-task model."""
        if not self._started:
            raise RuntimeError("Executor.run before start()")
        kwargs = kwargs or {}
        with tempfile.TemporaryDirectory(
            prefix="hvd_exec_", dir=self.work_dir
        ) as tmp:
            payload = os.path.join(tmp, "payload.pkl")
            with open(payload, "wb") as f:
                _dump_payload((fn, tuple(args), kwargs), f)
            out_dir = os.path.join(tmp, "out")
            os.makedirs(out_dir)
            code, expected_ranks = self._launch(payload, out_dir)
            return _collect_results(out_dir, expected_ranks, code)

    # `execute` is RayExecutor's name for the same thing [V]
    execute = run

    def _launch(self, payload: str, out_dir: str) -> int:
        import socket

        slots = _launch.assign_slots(self._hosts, self.num_workers)
        all_local = all(
            _launch._is_local(h.hostname) for h in self._hosts
        )
        placement = self.placement
        if placement == "auto":
            placement = "per-slot" if all_local else "per-host"
        secret = make_secret_key()
        server = RendezvousServer(secret_key=secret)
        port = server.start()
        try:
            # Same address discipline as run_commandline (launch.py):
            # loopback only when every worker is local; remote workers
            # must dial a routable driver name and a fixed, known
            # coordinator port (it binds on worker 0, unprobeable here).
            addr = "127.0.0.1" if all_local else socket.getfqdn()
            if all_local:
                coordinator_port = _launch._free_port()
            elif self.coordinator_port is not None:
                coordinator_port = self.coordinator_port
            else:
                coordinator_port = _default_coordinator_port()
            blocks = _launch.worker_envs(
                slots,
                placement,
                addr,
                port,
                coordinator_port,
                secret.hex(),
                extra={
                    **self.env,
                    "HOROVOD_EXECUTOR_OUT": out_dir,
                    # nested-in-elastic: results go to OUR flat out_dir,
                    # not an inherited epoch subdirectory
                    "HOROVOD_ELASTIC_EPOCH": "",
                },
            )
            command = [
                sys.executable,
                "-m",
                "horovod_tpu._executor_worker",
                payload,
            ]
            hostnames = [b["HOROVOD_HOSTNAME"] for b in blocks]
            expected_ranks = [int(b["HOROVOD_RANK"]) for b in blocks]
            code = _launch.launch_processes(
                blocks,
                command,
                hostnames,
                start_timeout=self.start_timeout,
            )
            return code, expected_ranks
        finally:
            server.stop()


def run(
    fn: Callable,
    args: Sequence = (),
    kwargs: Optional[dict] = None,
    num_proc: Optional[int] = None,
    **executor_kwargs,
) -> List[Any]:
    """One-shot form — parity with ``horovod.spark.run(fn, args,
    num_proc)`` [V]: each "task" is one rank; returns all ranks'
    results."""
    with Executor(num_workers=num_proc or 1, **executor_kwargs) as ex:
        return ex.run(fn, args=args, kwargs=kwargs)


def run_elastic(
    fn: Callable,
    args: Sequence = (),
    kwargs: Optional[dict] = None,
    num_proc: Optional[int] = None,
    min_np: Optional[int] = None,
    max_np: Optional[int] = None,
    discovery=None,
    **executor_kwargs,
) -> List[Any]:
    """One-shot ELASTIC form — parity with
    ``horovod.spark.run_elastic(fn, args, num_proc, min_np, max_np)``
    [V]: run ``fn`` under ``hvd.elastic`` semantics (commit/restore
    State, gang restart on failure or membership change) and return
    the final successful gang's results ordered by rank.

    Without a ``discovery`` source the gang is a fixed local one of
    ``num_proc`` slots — the elastic machinery over static membership,
    which is exactly the reference's shape on a static Spark cluster
    (workers can still fail and be relaunched; capacity just never
    grows). Pass any ``elastic.discovery.HostDiscovery`` for dynamic
    membership."""
    # A fixed local gang must be able to reach min_np — num_proc=None
    # with min_np=2 would otherwise build a 1-slot gang that can never
    # form and die as an opaque start_timeout 600s later.
    n = max(int(num_proc or 1), int(min_np or 1))
    if discovery is None:
        from .elastic.discovery import FixedHosts
        from .runner.hosts import HostInfo

        if num_proc is not None and int(num_proc) < int(min_np or 1):
            raise ValueError(
                f"run_elastic: num_proc={num_proc} is below "
                f"min_np={min_np} and no discovery source was given — "
                "the fixed local gang could never satisfy min_np"
            )
        discovery = FixedHosts([HostInfo(hostname="127.0.0.1", slots=n)])
        if max_np is None:
            max_np = n
    # With a caller-supplied discovery, absent max_np stays UNBOUNDED
    # (the reference's semantics); coercing it to num_proc's default
    # could silently cap the gang below min_np.
    with ElasticRayExecutor(
        min_np=int(min_np or n),
        max_np=None if max_np is None else int(max_np),
        discovery=discovery,
        **executor_kwargs,
    ) as ex:
        return ex.run(fn, args=args, kwargs=kwargs)


def _ray_or_none():
    try:
        import ray

        return ray
    except ImportError:
        return None


class RayExecutor(Executor):
    """Executor with a REAL ray backend when ray is importable (ref:
    horovod/ray/runner.py ``RayExecutor``: a placement group with one
    CPU bundle per worker, remote tasks carrying the env contract [V]).

    Ray mode lifecycle: ``start()`` connects/initializes ray and
    reserves a placement group (``placement_group_strategy``, default
    PACK — the reference's colocation default); ``run(fn)`` dispatches
    one remote task per rank pinned to its bundle. The rank-0 task's
    node hosts the ``jax.distributed`` coordinator; its address travels
    through a tiny ray actor, and every task receives the same
    ``HOROVOD_*`` env contract the local runner would export, so
    ``hvd.init()`` inside ``fn`` works identically in both modes.

    Without ray installed (``use_ray=None`` auto-detects) every call
    transparently falls back to the local runner — the documented
    degraded mode the non-ray tests exercise.
    """

    def __init__(
        self,
        *args,
        use_ray: Optional[bool] = None,
        placement_group_strategy: str = "PACK",
        **kwargs,
    ):
        super().__init__(*args, **kwargs)
        if use_ray is None:
            use_ray = _ray_or_none() is not None
        if use_ray and _ray_or_none() is None:
            raise RuntimeError(
                "use_ray=True but the 'ray' package is not importable"
            )
        self.use_ray = use_ray
        self.placement_group_strategy = placement_group_strategy
        self._pg = None

    # -- lifecycle -----------------------------------------------------

    def start(self) -> None:
        if not self.use_ray:
            return super().start()
        ray = _ray_or_none()
        from ray.util.placement_group import placement_group

        if not ray.is_initialized():
            ray.init(ignore_reinit_error=True)
        self._pg = placement_group(
            [{"CPU": 1}] * self.num_workers,
            strategy=self.placement_group_strategy,
        )
        ray.get(self._pg.ready(), timeout=self.start_timeout)
        self._started = True

    def shutdown(self) -> None:
        if not self.use_ray:
            return super().shutdown()
        if self._pg is not None:
            from ray.util.placement_group import remove_placement_group

            remove_placement_group(self._pg)
            self._pg = None
        self._started = False

    # -- dispatch ------------------------------------------------------

    def run(
        self,
        fn: Callable,
        args: Sequence = (),
        kwargs: Optional[dict] = None,
    ) -> List[Any]:
        if not self.use_ray:
            return super().run(fn, args=args, kwargs=kwargs)
        if not self._started:
            raise RuntimeError("RayExecutor.run before start()")
        ray = _ray_or_none()
        from ray.util.scheduling_strategies import (
            PlacementGroupSchedulingStrategy,
        )

        n = self.num_workers
        coord_port = self.coordinator_port or _default_coordinator_port()

        @ray.remote
        class _CoordInfo:
            """Rank→node-IP registry: once all ranks have registered,
            every worker derives the REAL host topology (local rank =
            order among same-node ranks) — colocated PACK bundles must
            not masquerade as separate single-rank hosts."""

            def __init__(self, world):
                self._world = world
                self._ips = {}

            def register(self, rank, ip):
                self._ips[rank] = ip

            def topology(self):
                if len(self._ips) < self._world:
                    return None
                return dict(self._ips)

        # fn/args ride the task submission itself: ray cloudpickles
        # them, so closures and locally-defined functions work (plain
        # pickle.dumps would reject any fn defined inside a function).
        @ray.remote
        def _worker(rank, world, fn, args, kwargs, extra_env, port,
                    coord):
            import os
            import time

            import ray as _ray

            ip = _ray.util.get_node_ip_address()
            _ray.get(coord.register.remote(rank, ip))
            topo = None
            deadline = time.monotonic() + 300.0
            while topo is None and time.monotonic() < deadline:
                topo = _ray.get(coord.topology.remote())
                if topo is None:
                    time.sleep(0.2)
            if topo is None:
                raise RuntimeError(
                    "worker topology never completed (some rank failed "
                    "to register)"
                )
            local_peers = sorted(
                r for r, host in topo.items() if host == ip
            )
            hosts = sorted(set(topo.values()), key=lambda h: min(
                r for r, hh in topo.items() if hh == h
            ))
            env = dict(extra_env)
            env.update(
                {
                    "HOROVOD_HOSTNAME": ip,
                    "HOROVOD_RANK": str(rank),
                    "HOROVOD_SIZE": str(world),
                    "HOROVOD_LOCAL_RANK": str(local_peers.index(rank)),
                    "HOROVOD_LOCAL_SIZE": str(len(local_peers)),
                    "HOROVOD_CROSS_RANK": str(hosts.index(ip)),
                    "HOROVOD_CROSS_SIZE": str(len(hosts)),
                    "HOROVOD_NUM_PROCESSES": str(world),
                    "HOROVOD_PROCESS_ID": str(rank),
                    "HOROVOD_CONTROLLER": "tpu",
                }
            )
            if world > 1:
                env["HOROVOD_COORDINATOR_ADDR"] = topo[0]
                env["HOROVOD_COORDINATOR_PORT"] = str(port)
            os.environ.update(env)
            return fn(*args, **kwargs)

        coord = _CoordInfo.options(num_cpus=0).remote(n)
        try:
            futures = [
                _worker.options(
                    scheduling_strategy=PlacementGroupSchedulingStrategy(
                        placement_group=self._pg,
                        placement_group_bundle_index=rank,
                    )
                ).remote(rank, n, fn, tuple(args), dict(kwargs or {}),
                         self.env, coord_port, coord)
                for rank in range(n)
            ]
            # No timeout here: start_timeout bounds STARTUP (the
            # placement-group wait in start()); the job itself may
            # legitimately run for hours — same contract as the base
            # Executor, whose start_timeout only gates process launch.
            return ray.get(futures)
        finally:
            ray.kill(coord)  # one actor per run() would otherwise leak

    execute = run


class RayHostDiscovery:
    """Elastic host discovery over the ray cluster's live node set
    (ref: horovod/ray/elastic.py RayHostDiscovery: maps ray.nodes() to
    host:slots [V]). Satisfies elastic.discovery.HostDiscovery.

    Slots per node default to the node's CPU resource divided by
    ``cpus_per_slot``; ``slots_per_host`` overrides with a fixed count
    (the TPU-pod deployment: one worker process per host driving the
    host's chips, so slots == 1 regardless of CPU count).
    """

    def __init__(
        self,
        cpus_per_slot: int = 1,
        slots_per_host: Optional[int] = None,
    ) -> None:
        self._cpus_per_slot = max(int(cpus_per_slot), 1)
        self._slots_per_host = slots_per_host

    def find_available_hosts_and_slots(self):
        from .runner.hosts import HostInfo

        ray = _ray_or_none()
        if ray is None or not ray.is_initialized():
            return []
        hosts = []
        for node in ray.nodes():
            if not node.get("Alive"):
                continue
            address = node.get("NodeManagerAddress") or node.get(
                "NodeManagerHostname"
            )
            if not address:
                continue
            if self._slots_per_host is not None:
                slots = int(self._slots_per_host)
            else:
                cpus = int(node.get("Resources", {}).get("CPU", 0))
                slots = cpus // self._cpus_per_slot
            if slots > 0:
                hosts.append(HostInfo(hostname=address, slots=slots))
        return hosts


class ElasticRayExecutor:
    """Elastic gang over a ray cluster (ref: horovod/ray/elastic.py
    ElasticRayExecutor [V]): the ray cluster's live node set drives
    membership, the elastic driver supervises gang restarts, and the
    user function runs under ``hvd.elastic`` semantics — on membership
    change workers receive HostsUpdatedInterrupt, commit their State,
    and the gang relaunches on the new node set.

    Execution engine: the same worker-payload machinery as
    ``Executor.run`` supervised by ``elastic.ElasticDriver`` (process
    launch over ssh/local — a TPU worker owns its hosts's chips, so
    one process per host is the deployment model; ray provides
    membership, not task placement). Returns the results of the final
    successful gang, ordered by rank. Without ray installed, pass
    ``discovery=`` explicitly (any HostDiscovery) — the documented
    degraded mode, which the tests exercise with a scripted discovery.
    """

    def __init__(
        self,
        min_np: int = 1,
        max_np: Optional[int] = None,
        slots_per_host: Optional[int] = None,
        env: Optional[dict] = None,
        start_timeout: float = 600.0,
        reset_limit: Optional[int] = None,
        discovery=None,
        discovery_interval: float = 1.0,
        work_dir: Optional[str] = None,
    ) -> None:
        self.min_np = int(min_np)
        self.max_np = max_np
        self.slots_per_host = slots_per_host
        self.env = dict(env or {})
        self.start_timeout = start_timeout
        self.reset_limit = reset_limit
        self.discovery = discovery
        self.discovery_interval = discovery_interval
        self.work_dir = work_dir
        self._started = False

    def start(self) -> None:
        """Connect to ray (when available) and resolve the discovery
        source; like the reference, start() owns cluster attachment and
        run() owns the job."""
        if self.discovery is None:
            ray = _ray_or_none()
            if ray is None:
                raise RuntimeError(
                    "ElasticRayExecutor needs ray installed, or an "
                    "explicit discovery= (any elastic HostDiscovery)"
                )
            if not ray.is_initialized():
                ray.init(ignore_reinit_error=True)
            self.discovery = RayHostDiscovery(
                slots_per_host=self.slots_per_host
            )
        self._started = True

    def shutdown(self) -> None:
        self._started = False

    def __enter__(self) -> "ElasticRayExecutor":
        self.start()
        return self

    def __exit__(self, *exc) -> None:
        self.shutdown()

    def run(
        self,
        fn: Callable,
        args: Sequence = (),
        kwargs: Optional[dict] = None,
    ) -> List[Any]:
        if not self._started:
            raise RuntimeError("ElasticRayExecutor.run before start()")
        from .elastic.driver import ElasticDriver

        kwargs = kwargs or {}
        with tempfile.TemporaryDirectory(
            prefix="hvd_elastic_", dir=self.work_dir
        ) as tmp:
            payload = os.path.join(tmp, "payload.pkl")
            with open(payload, "wb") as f:
                _dump_payload((fn, tuple(args), kwargs), f)
            out_dir = os.path.join(tmp, "out")
            os.makedirs(out_dir)
            command = [
                sys.executable,
                "-m",
                "horovod_tpu._executor_worker",
                payload,
            ]
            driver = ElasticDriver(
                discovery=self.discovery,
                command=command,
                min_np=self.min_np,
                max_np=self.max_np,
                slots_per_host=self.slots_per_host,
                discovery_interval=self.discovery_interval,
                start_timeout=self.start_timeout,
                reset_limit=self.reset_limit,
                extra_env={
                    **self.env,
                    "HOROVOD_EXECUTOR_OUT": out_dir,
                },
            )
            try:
                code = driver.run()
                epoch, lead_ranks = driver.gang_info()
            finally:
                driver.shutdown()
            if epoch is None or not lead_ranks:
                raise RuntimeError(
                    f"elastic executor job failed with exit code {code}:"
                    f" no gang was ever launched (capacity below min_np"
                    f" within start_timeout)"
                )
            # Final-gang results live in the per-epoch subdirectory the
            # workers wrote (stale larger epochs must not be read), at
            # the LEAD ranks of that gang (per-host placement = one
            # process, one result, per host).
            return _collect_results(
                os.path.join(out_dir, f"epoch.{epoch}"), lead_ranks, code
            )
