"""Training-state integrity plane (ISSUE 7): non-finite guards,
cross-rank parameter audit, exactly-once elastic resume.

Acceptance surface:

* GradGuard skip-step semantics in both optimizers — a NaN/Inf in the
  reduced gradients skips the update (zero updates, optimizer state
  and EF residuals untouched), counts ``guard.nonfinite_steps``, and
  after K consecutive skips latches an escalation that
  ``State.commit()`` raises as ``HorovodInternalError``.
* Guard overhead: the lowered guarded step carries the SAME collective
  count as the unguarded one (the flag is a scalar reduction over
  already-replicated values) and the no-skip path never reaches the
  host (zero callback fires across a finite run).
* ``hvd.audit`` digests + ``find_divergent`` majority logic + the
  driver's divergence quarantine/restart.
* Checkpoint content digests: corrupt-but-parseable checkpoints fall
  back; ``restore(like=)`` structure mismatches raise a clear
  ``CheckpointStructureError`` with the tree-path diff.
* Sampler/dataset cursors: reshard-deterministic global order,
  mid-epoch exactly-once resume across a save/SIGKILL/restore cycle
  including an 8→6 world change, bit-identical post-resume
  trajectories across repeated runs.
"""

import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402
import optax  # noqa: E402
from jax.sharding import PartitionSpec as P  # noqa: E402

import horovod_tpu as hvd_mod  # noqa: E402
from horovod_tpu import analysis  # noqa: E402
from horovod_tpu.common import guard as guard_mod  # noqa: E402
from horovod_tpu.common.compat import shard_map  # noqa: E402
from horovod_tpu.common.metrics import registry  # noqa: E402


def _delta(name, before):
    return registry.snapshot().get(name, 0.0) - before.get(name, 0.0)


@pytest.fixture(autouse=True)
def _fresh_guard():
    guard_mod._reset_guard()
    yield
    guard_mod._reset_guard()


def _jit_step(hvd, opt, mesh, lr_step=True):
    """One jitted data-parallel step: rank-major grads in, updated
    params + state out (the repo's standard shard_map harness)."""

    @jax.jit
    def step(grads, state, params):
        def body(g, s, p):
            g = jax.tree_util.tree_map(lambda x: x[0], g)
            u, s2 = opt.update(g, s, p)
            if lr_step:
                p = jax.tree_util.tree_map(lambda a, b: a + b, p, u)
                return p, s2, u
            return p, s2, u

        return shard_map(
            body, mesh=mesh,
            in_specs=(P(hvd.WORLD_AXIS), P(), P()),
            out_specs=(P(), P(), P()),
            check_vma=False,
        )(grads, state, params)

    return step


def _grads(world, n=16, bad=False, val=1.0):
    g = {"w": jnp.full((world, n), val, jnp.float32),
         "b": jnp.full((world, 4), val, jnp.float32)}
    if bad:
        g = {"w": g["w"].at[0, 0].set(jnp.nan), "b": g["b"]}
    return g


# ------------------------------------------------------------ grad guard


class TestGradGuard:
    @pytest.mark.parametrize("buckets", [0, 2])
    def test_skip_step_semantics(self, hvd, buckets):
        """A NaN step: zero updates, inner state untouched, step
        counter advanced, one skip counted (per-shard callbacks
        deduped), streak reset by the next good step."""
        world = hvd.size()
        opt = hvd_mod.DistributedOptimizer(
            optax.sgd(0.1), op=hvd_mod.Sum, grad_guard=True,
            overlap_buckets=buckets,
        )
        params = {"w": jnp.ones((16,)), "b": jnp.zeros((4,))}
        state = opt.init(params)
        step = _jit_step(hvd, opt, hvd.mesh())
        before = registry.snapshot()

        params, state, u = step(_grads(world), state, params)
        assert int(state.guard_skips) == 0
        p_good = jax.device_get(params)

        params, state, u = step(_grads(world, bad=True), state, params)
        jax.block_until_ready(u)
        assert int(state.guard_skips) == 1
        assert int(state.guard_streak) == 1
        assert int(state.step) == 2  # the step counter still advances
        assert float(jnp.abs(u["w"]).max()) == 0.0
        assert float(jnp.abs(u["b"]).max()) == 0.0
        # params unchanged by the skipped step
        np.testing.assert_array_equal(
            np.asarray(params["w"]), np.asarray(p_good["w"])
        )
        assert _delta("guard.nonfinite_steps", before) == 1  # deduped

        params, state, u = step(_grads(world), state, params)
        assert int(state.guard_streak) == 0  # good step resets
        assert int(state.guard_skips) == 1

    def test_trajectory_matches_unguarded_without_nan(self, hvd):
        """Finite gradients: the guarded optimizer matches the
        unguarded one to float tolerance. (Not bit-exact BY PROGRAM:
        the guard's lax.cond changes XLA's fusion choices, which can
        move a last-ulp rounding — the guard itself only ever reads.)"""
        world = hvd.size()
        mesh = hvd.mesh()
        params = {"w": jnp.linspace(0, 1, 16), "b": jnp.zeros((4,))}
        outs = []
        for g_on in (False, True):
            opt = hvd_mod.DistributedOptimizer(
                optax.adam(1e-2), op=hvd_mod.Sum, grad_guard=g_on,
                overlap_buckets=2,
            )
            p, state = dict(params), opt.init(params)
            step = _jit_step(hvd, opt, mesh)
            for i in range(3):
                p, state, _ = step(_grads(world, val=0.5 + i), state, p)
            outs.append(jax.device_get(p))
        np.testing.assert_allclose(
            outs[0]["w"], outs[1]["w"], rtol=1e-6, atol=1e-7
        )
        np.testing.assert_allclose(
            outs[0]["b"], outs[1]["b"], rtol=1e-6, atol=1e-7
        )

    def test_escalation_latches_and_commit_raises(self, hvd):
        from horovod_tpu.elastic.state import JaxState

        world = hvd.size()
        opt = hvd_mod.DistributedOptimizer(
            optax.sgd(0.1), op=hvd_mod.Sum, grad_guard=True,
            guard_max_skips=2,
        )
        params = {"w": jnp.ones((16,)), "b": jnp.zeros((4,))}
        state = opt.init(params)
        step = _jit_step(hvd, opt, hvd.mesh())
        for _ in range(2):
            params, state, u = step(
                _grads(world, bad=True), state, params
            )
            jax.block_until_ready(u)
        assert guard_mod.status()["escalated"]
        est = JaxState(params=params, batch=0)
        with pytest.raises(hvd_mod.HorovodInternalError):
            est.commit()
        # the raise cleared the latch; the next commit is clean
        est.commit()
        assert not guard_mod.status()["escalated"]

    def test_error_feedback_residual_kept_on_skip(self, hvd):
        """EF carry stays at the LAST APPLIED step's residual across a
        skipped step — the carry must describe what was actually
        transmitted."""
        world = hvd.size()
        opt = hvd_mod.DistributedOptimizer(
            optax.sgd(0.1), op=hvd_mod.Average,
            compression=hvd_mod.Compression.int8,
            error_feedback=True, grad_guard=True,
        )
        params = {"w": jnp.linspace(-1, 1, 64), "b": jnp.zeros((4,))}
        state = opt.init(params)
        step = _jit_step(hvd, opt, hvd.mesh())
        params, state, _ = step(_grads(world, n=64, val=0.37), state, params)
        res_good = jax.device_get(state.residual)
        assert float(np.abs(res_good["w"]).max()) > 0  # int8 did quantize
        params, state, u = step(
            _grads(world, n=64, bad=True), state, params
        )
        jax.block_until_ready(u)
        assert int(state.guard_skips) == 1
        res_after = jax.device_get(state.residual)
        np.testing.assert_array_equal(res_good["w"], res_after["w"])
        np.testing.assert_array_equal(res_good["b"], res_after["b"])

    def test_accumulation_boundary_skip_discards_window(self, hvd):
        """backward_passes_per_step=2: a NaN micro-batch poisons the
        boundary step — skipped, and the accumulator is cleared (the
        window is discarded, not replayed)."""
        world = hvd.size()
        opt = hvd_mod.DistributedOptimizer(
            optax.sgd(0.1), op=hvd_mod.Sum, grad_guard=True,
            backward_passes_per_step=2,
        )
        params = {"w": jnp.ones((16,)), "b": jnp.zeros((4,))}
        state = opt.init(params)
        step = _jit_step(hvd, opt, hvd.mesh())
        params, state, u = step(_grads(world, bad=True), state, params)
        assert int(state.guard_skips) == 0  # off-boundary: no event
        params, state, u = step(_grads(world), state, params)
        jax.block_until_ready(u)
        assert int(state.guard_skips) == 1  # boundary judged the window
        assert float(jnp.abs(u["w"]).max()) == 0.0
        acc = jax.device_get(state.accum)
        assert float(np.abs(acc["w"]).max()) == 0.0  # window discarded

    def test_guard_off_keeps_state_structure(self, hvd):
        opt = hvd_mod.DistributedOptimizer(
            optax.sgd(0.1), op=hvd_mod.Sum, grad_guard=False
        )
        state = opt.init({"w": jnp.ones((4,))})
        assert state.guard_skips is None
        assert state.guard_streak is None
        # None leaves are empty subtrees: unguarded checkpoints keep
        # their exact leaf list
        leaves = jax.tree_util.tree_leaves(state)
        opt0 = hvd_mod.DistributedOptimizer(optax.sgd(0.1), op=hvd_mod.Sum)
        assert len(leaves) == len(
            jax.tree_util.tree_leaves(opt0.init({"w": jnp.ones((4,))}))
        )


class TestGuardOverhead:
    """Acceptance: one fused scalar reduction per bucket — no extra
    collectives, no host sync on the no-skip path."""

    def _lowered_text(self, hvd, grad_guard):
        opt = hvd_mod.DistributedOptimizer(
            optax.sgd(0.1), op=hvd_mod.Sum, grad_guard=grad_guard,
            overlap_buckets=3, overlap_min_bytes=0,
        )
        # three SAME-SIZE leaves so the balanced partition closes one
        # bucket per leaf (a lopsided tree would merge the small ones)
        params = {
            "a": jnp.ones((32, 8)), "b": jnp.ones((32, 8)),
            "c": jnp.ones((32, 8)),
        }
        state = opt.init(params)
        world = hvd.size()
        grads = {
            k: jnp.ones((world,) + tuple(np.shape(v)))
            for k, v in params.items()
        }

        def step(g, s, p):
            def body(g, s, p):
                g = jax.tree_util.tree_map(lambda x: x[0], g)
                return opt.update(g, s, p)

            return shard_map(
                body, mesh=hvd.mesh(),
                in_specs=(P(hvd_mod.WORLD_AXIS), P(), P()),
                out_specs=(P(), P()),
                check_vma=False,
            )(g, s, p)

        return (
            analysis.parse_module(jax.jit(step).lower(grads, state, params)),
            opt, state, grads, params,
        )

    def test_no_additional_collectives(self, hvd):
        g_off, *_ = self._lowered_text(hvd, grad_guard=False)
        g_on, *_ = self._lowered_text(hvd, grad_guard=True)
        analysis.expect(
            g_off, analysis.CollectiveCount("all_reduce", 3)
        )  # one per bucket
        # the guard flag adds NO collective of ANY kind
        analysis.expect(
            g_on, analysis.GuardOverhead(g_off, extra_scalar_allreduces=0)
        )

    def test_no_host_sync_on_no_skip_path(self, hvd):
        """Run many finite steps under jit: the guard callback must
        never fire (it lives inside the skip branch only)."""
        _, opt, state, grads, params = self._lowered_text(
            hvd, grad_guard=True
        )

        @jax.jit
        def step(g, s, p):
            def body(g, s, p):
                g = jax.tree_util.tree_map(lambda x: x[0], g)
                return opt.update(g, s, p)

            return shard_map(
                body, mesh=hvd_mod.mesh(),
                in_specs=(P(hvd_mod.WORLD_AXIS), P(), P()),
                out_specs=(P(), P()),
                check_vma=False,
            )(g, s, p)

        before = registry.snapshot()
        for _ in range(10):
            u, state = step(grads, state, params)
        jax.block_until_ready(u)
        assert guard_mod.status()["nonfinite_steps"] == 0
        assert _delta("guard.nonfinite_steps", before) == 0


class TestShardedGuard:
    def test_skip_and_counters(self, hvd):
        world = hvd.size()
        opt = hvd_mod.ShardedDistributedOptimizer(
            optax.adam(1e-2), op=hvd_mod.Average, grad_guard=True,
            guard_max_skips=0,
        )
        params = {"w": jnp.linspace(0, 1, 32), "b": jnp.zeros((4,))}
        state = opt.init(params)
        assert set(state) == {"state", "guard"}

        @jax.jit
        def step(g, s, p):
            def body(g, s, p):
                g = jax.tree_util.tree_map(lambda x: x[0], g)
                return opt.update(g, s, p)

            return shard_map(
                body, mesh=hvd.mesh(),
                in_specs=(P(hvd.WORLD_AXIS), opt.state_spec(), P()),
                out_specs=(P(), opt.state_spec()),
                check_vma=False,
            )(g, s, p)

        u, state = step(_grads(world, n=32), state, params)
        mu_good = np.asarray(
            jax.tree_util.tree_leaves(state["state"])[1]
        ).copy()
        u, state = step(_grads(world, n=32, bad=True), state, params)
        jax.block_until_ready(u)
        assert np.asarray(state["guard"]["skips"]).max() == 1
        assert float(jnp.abs(u["w"]).max()) == 0.0
        # optimizer moments untouched by the skipped step
        mu_after = np.asarray(
            jax.tree_util.tree_leaves(state["state"])[1]
        )
        np.testing.assert_array_equal(mu_good, mu_after)
        assert guard_mod.status()["nonfinite_steps"] == 1

    def test_one_extra_scalar_collective_only(self, hvd):
        """The sharded flag costs exactly ONE extra all_reduce (the
        4-byte agreement psum) — shards diverge, so it cannot be
        free — and nothing else."""
        world = hvd.size()
        params = {"w": jnp.ones((32,)), "b": jnp.zeros((4,))}
        texts = {}
        for g_on in (False, True):
            opt = hvd_mod.ShardedDistributedOptimizer(
                optax.sgd(0.1), op=hvd_mod.Average, grad_guard=g_on
            )
            state = opt.init(params)
            grads = {
                k: jnp.ones((world,) + tuple(np.shape(v)))
                for k, v in params.items()
            }

            def step(g, s, p):
                def body(g, s, p):
                    g = jax.tree_util.tree_map(lambda x: x[0], g)
                    return opt.update(g, s, p)

                return shard_map(
                    body, mesh=hvd.mesh(),
                    in_specs=(P(hvd_mod.WORLD_AXIS), opt.state_spec(), P()),
                    out_specs=(P(), opt.state_spec()),
                    check_vma=False,
                )(g, s, p)

            texts[g_on] = analysis.parse_module(
                jax.jit(step).lower(grads, state, params)
            )
        # exactly one extra all_reduce, and it is SCALAR (the 4-byte
        # agreement flag) — GuardOverhead checks both
        analysis.expect(
            texts[True],
            analysis.GuardOverhead(texts[False], extra_scalar_allreduces=1),
        )

    def test_layout_migration_both_directions(self, hvd):
        """Flat state under a newly-enabled guard and guarded state
        under a disabled guard both get a clear error at update() and
        a working migration through reshard_state()."""
        params = {"w": jnp.linspace(0, 1, 32)}
        opt_off = hvd_mod.ShardedDistributedOptimizer(
            optax.adam(1e-2), op=hvd_mod.Average, grad_guard=False
        )
        opt_on = hvd_mod.ShardedDistributedOptimizer(
            optax.adam(1e-2), op=hvd_mod.Average, grad_guard=True
        )
        flat = opt_off.init(params)
        guarded = opt_on.init(params)
        with pytest.raises(ValueError, match="flat"):
            opt_on.update({"w": jnp.ones(32)}, flat, params)
        with pytest.raises(ValueError, match="guard counters"):
            opt_off.update({"w": jnp.ones(32)}, guarded, params)
        up = opt_on.reshard_state(flat, params, 8)
        assert set(up) == {"state", "guard"}
        assert np.asarray(up["guard"]["skips"]).shape == (8,)
        down = opt_off.reshard_state(guarded, params, 8)
        assert not isinstance(down, dict) or "guard" not in down

    def test_reshard_carries_guard_counters(self, hvd):
        world = hvd.size()
        opt = hvd_mod.ShardedDistributedOptimizer(
            optax.adam(1e-2), op=hvd_mod.Average, grad_guard=True
        )
        params = {"w": jnp.linspace(0, 1, 32)}
        state = opt.init(params)

        @jax.jit
        def step(g, s, p):
            def body(g, s, p):
                g = jax.tree_util.tree_map(lambda x: x[0], g)
                return opt.update(g, s, p)

            return shard_map(
                body, mesh=hvd.mesh(),
                in_specs=(P(hvd.WORLD_AXIS), opt.state_spec(), P()),
                out_specs=(P(), opt.state_spec()),
                check_vma=False,
            )(g, s, p)

        g = {"w": jnp.ones((world, 32))}
        _, state = step(g, state, params)
        _, state = step({"w": g["w"].at[0, 0].set(jnp.inf)}, state, params)
        state6 = opt.reshard_state(state, params, 6)
        assert np.asarray(state6["guard"]["skips"]).shape == (6,)
        assert np.asarray(state6["guard"]["skips"]).max() == 1
        assert np.asarray(state6["guard"]["step"]).max() == 2


# ----------------------------------------------------------------- audit


class TestAudit:
    def test_digest_canonical_and_sensitive(self, hvd):
        t = {"w": jnp.linspace(0, 1, 32), "n": 3}
        a = hvd_mod.tree_digest(t)
        b = hvd_mod.tree_digest(
            {"w": jnp.linspace(0, 1, 32), "n": 3}
        )
        assert a == b
        assert a != hvd_mod.tree_digest(
            {"w": jnp.linspace(0, 1, 32).at[7].add(1e-7), "n": 3}
        )
        assert a != hvd_mod.tree_digest({"w": jnp.linspace(0, 1, 32)})

    def test_audit_metrics_and_cadence(self, hvd):
        before = registry.snapshot()
        t = {"w": jnp.ones((4,))}
        assert hvd_mod.maybe_audit(t, step=3, every=5) is None
        assert hvd_mod.maybe_audit(t, step=5, every=5) is not None
        assert hvd_mod.maybe_audit(t, step=10, every=5) is not None
        assert hvd_mod.maybe_audit(t, step=10, every=0) is None
        assert _delta("audit.digests", before) == 2
        assert registry.snapshot()["audit.last_digest_step"] == 10

    @pytest.mark.parametrize(
        "digests,expect",
        [
            # majority wins
            (
                {0: ("aaa", 5), 1: ("aaa", 5), 2: ("bbb", 5)},
                (5, (2,)),
            ),
            # tie breaks toward rank 0's digest
            ({0: ("aaa", 5), 1: ("bbb", 5)}, (5, (1,))),
            # agreement -> healthy
            ({0: ("aaa", 5), 1: ("aaa", 5)}, None),
            # newest quorum step rules; stale odd rank ignored
            (
                {0: ("aaa", 6), 1: ("bbb", 5), 2: ("aaa", 6)},
                None,
            ),
            # single reporter: no quorum
            ({0: ("aaa", 5)}, None),
        ],
    )
    def test_find_divergent(self, digests, expect):
        from horovod_tpu.audit import find_divergent

        shaped = {
            r: {"digest": d, "step": s} for r, (d, s) in digests.items()
        }
        assert find_divergent(shaped) == expect

    def test_kv_roundtrip(self):
        from horovod_tpu.runner.rendezvous import (
            KVStore,
            put_audit,
            read_audit_digests,
        )

        class _C:
            def __init__(self, store):
                self._s = store

            def put(self, scope, key, value):
                self._s.put(scope, key, value)

        store = KVStore()
        put_audit(_C(store), 3, 17, "deadbeef")
        store.put("audit", "bogus", b"not json")
        out = read_audit_digests(store)
        assert out == {3: out[3]}
        assert out[3]["step"] == 17 and out[3]["digest"] == "deadbeef"

    def test_driver_divergence_quarantine(self, monkeypatch):
        from horovod_tpu.elastic.driver import ElasticDriver
        from horovod_tpu.runner.hosts import HostInfo
        from horovod_tpu.runner.rendezvous import KVStore, put_audit

        from tests.test_chaos import _StoreServer
        from tests.test_elastic import FakeDiscovery

        d = ElasticDriver(
            FakeDiscovery([HostInfo("a", 2), HostInfo("b", 6)]),
            ["true"], min_np=1,
        )
        d.host_manager.refresh()
        d._server = _StoreServer(KVStore())
        d._blocks = [
            {"HOROVOD_RANK": str(r), "HOROVOD_HOSTNAME": h}
            for r, h in enumerate(["a"] * 2 + ["b"] * 6)
        ]

        class _C:
            def __init__(self, store):
                self._s = store

            def put(self, scope, key, value):
                self._s.put(scope, key, value)

        c = _C(d._server.store)
        before = registry.snapshot()
        for r in range(8):
            put_audit(c, r, 40, "good" if r != 1 else "evil")
        d._last_audit_poll = -1e9
        reason = d._poll_audit(time.monotonic())
        assert reason is not None and "divergence" in reason
        assert "1" in reason
        assert d.host_manager.is_blacklisted("a")
        assert not d.host_manager.is_blacklisted("b")
        assert d.compute_assignment().world_size == 6
        assert _delta("driver.divergence_restarts", before) == 1
        # the same audit round is never judged twice
        d._last_audit_poll = -1e9
        assert d._poll_audit(time.monotonic()) is None

    def test_driver_divergence_capacity_guard_still_restarts(
        self, monkeypatch
    ):
        """A diverged replica is WRONG, not slow: when the capacity
        guard forbids blacklisting, the gang still restarts (the
        restore re-syncs the replicas — that is the repair)."""
        from horovod_tpu.elastic.driver import ElasticDriver
        from horovod_tpu.runner.hosts import HostInfo
        from horovod_tpu.runner.rendezvous import KVStore, put_audit

        from tests.test_chaos import _StoreServer
        from tests.test_elastic import FakeDiscovery

        d = ElasticDriver(
            FakeDiscovery([HostInfo("a", 4), HostInfo("b", 4)]),
            ["true"], min_np=8,
        )
        d.host_manager.refresh()
        d._server = _StoreServer(KVStore())
        d._blocks = [
            {"HOROVOD_RANK": str(r), "HOROVOD_HOSTNAME": h}
            for r, h in enumerate(["a"] * 4 + ["b"] * 4)
        ]

        class _C:
            def __init__(self, store):
                self._s = store

            def put(self, scope, key, value):
                self._s.put(scope, key, value)

        c = _C(d._server.store)
        for r in range(8):
            put_audit(c, r, 7, "good" if r != 6 else "evil")
        d._last_audit_poll = -1e9
        reason = d._poll_audit(time.monotonic())
        assert reason is not None and "divergence" in reason
        assert not d.host_manager.is_blacklisted("b")  # capacity guard


# ---------------------------------------------------- checkpoint digests


class TestCheckpointIntegrity:
    def _mgr(self, tmp_path, **kw):
        from horovod_tpu.checkpoint import CheckpointManager

        kw.setdefault("async_save", False)
        return CheckpointManager(str(tmp_path / "ckpt"), **kw)

    def test_digest_sidecar_written_and_pruned(self, hvd, tmp_path):
        mgr = self._mgr(tmp_path, max_to_keep=2)
        tree = {"w": jnp.linspace(0, 1, 256)}
        for s in (1, 2, 3):
            mgr.save(s, tree)
        mgr.wait_until_finished()
        root = str(tmp_path / "ckpt")
        names = sorted(
            n for n in os.listdir(root) if n.startswith("digest-")
        )
        assert names == ["digest-2.json", "digest-3.json"]

    def test_corrupt_but_parseable_falls_back(self, hvd, tmp_path):
        mgr = self._mgr(tmp_path)
        # non-constant payload: constant arrays compress away and the
        # flip would land in container slack
        tree = {"w": jnp.linspace(0, 1, 4096, dtype=jnp.float32)}
        mgr.save(1, tree)
        mgr.save(2, tree)
        mgr.wait_until_finished()
        before = registry.snapshot()
        mgr._bitflip_step(2)
        step, restored = mgr.restore_latest_good(like=tree)
        assert step == 1
        assert _delta("checkpoint.digest_mismatch", before) >= 1
        assert _delta("checkpoint.fallback", before) >= 1
        np.testing.assert_array_equal(
            np.asarray(restored["w"]), np.asarray(tree["w"])
        )

    def test_chaos_bitflip_kind_at_save(self, hvd, tmp_path):
        from horovod_tpu.testing import chaos

        chaos.configure("checkpoint.save@2:bitflip")
        try:
            mgr = self._mgr(tmp_path)
            tree = {"w": jnp.linspace(0, 2, 4096, dtype=jnp.float32)}
            mgr.save(1, tree)
            mgr.save(2, tree)  # hit 2: flipped post-commit
            mgr.wait_until_finished()
            step, _ = mgr.restore_latest_good(like=tree)
            assert step == 1
        finally:
            chaos.reset()

    def test_structure_mismatch_raises_clear_error(self, hvd, tmp_path):
        from horovod_tpu.checkpoint import CheckpointStructureError

        mgr = self._mgr(tmp_path)
        tree = {"params": {"w": jnp.ones((8,))}, "step": 3}
        mgr.save(1, tree)
        mgr.wait_until_finished()
        bad_like = {"params": {"weights": jnp.ones((8,))}, "step": 0}
        with pytest.raises(CheckpointStructureError) as ei:
            mgr.restore(1, like=bad_like)
        msg = str(ei.value)
        assert "weights" in msg and "w" in msg
        assert "structure" in msg
        # restore_latest_good re-raises immediately — older steps
        # cannot repair a caller bug
        with pytest.raises(CheckpointStructureError):
            mgr.restore_latest_good(like=bad_like)

    def test_dtype_casting_restore_is_not_corruption(self, hvd, tmp_path):
        """restore_latest_good(like=<re-typed tree>) casts on restore;
        the META digest gate must skip byte verification instead of
        misreading every retained checkpoint as corrupt."""
        mgr = self._mgr(tmp_path)
        tree = {"w": jnp.linspace(0, 1, 256, dtype=jnp.float32)}
        mgr.save(1, tree)
        mgr.wait_until_finished()
        like_bf16 = {"w": jnp.zeros((256,), jnp.bfloat16)}
        step, restored = mgr.restore_latest_good(like=like_bf16)
        assert step == 1
        assert restored["w"].dtype == jnp.bfloat16

    def test_matching_like_still_restores(self, hvd, tmp_path):
        mgr = self._mgr(tmp_path)
        tree = {"params": {"w": jnp.ones((8,))}, "step": 3}
        mgr.save(1, tree)
        mgr.wait_until_finished()
        out = mgr.restore(1, like=tree)
        assert int(out["step"]) == 3


# --------------------------------------------------- chaos data kinds


class TestChaosDataKinds:
    def test_parse_and_return(self):
        from horovod_tpu.testing import chaos

        plan = chaos.FaultPlan.parse("x@1:nan;y@1:bitflip;z@1:reset")
        assert plan.fire("x") == "nan"
        assert plan.fire("x") is None  # one-shot
        assert plan.fire("y") == "bitflip"
        with pytest.raises(ConnectionResetError):
            plan.fire("z")
        assert [f["kind"] for f in plan.fired()] == [
            "nan", "bitflip", "reset",
        ]

    def test_fusion_dispatch_nan_detected_by_eager_guard(self, hvd):
        from horovod_tpu.testing import chaos

        fusion = hvd_mod.common.basics.state().fusion
        fusion.guard = True
        chaos.configure("fusion.dispatch@1:nan")
        try:
            out = hvd.allreduce(
                hvd.replicate(np.ones((64,), np.float32)), op=hvd_mod.Sum
            )
            assert not bool(np.isfinite(np.asarray(out)).all())
            before = registry.snapshot()
            assert fusion.guard_poll() == 1
            assert _delta("guard.nonfinite_batches", before) == 1
            # a clean dispatch polls clean
            out = hvd.allreduce(
                hvd.replicate(np.ones((64,), np.float32)), op=hvd_mod.Sum
            )
            assert fusion.guard_poll() == 0
        finally:
            chaos.reset()


# ------------------------------------------------- exactly-once resume


class TestSamplerResume:
    def test_reshard_determinism_same_global_order(self):
        """The epoch order is a function of (seed, epoch) only: every
        world size walks the same permutation."""
        from horovod_tpu.data import ShardedIndexSampler

        orders = []
        for world in (2, 6, 8):
            s = ShardedIndexSampler(
                48, num_replicas=world, rank=0, seed=9
            )
            orders.append(s._epoch_order().tolist())
        assert orders[0] == orders[1] == orders[2]
        # and the union of rank stripes IS that order, in global terms
        world = 6
        stripes = [
            list(ShardedIndexSampler(48, num_replicas=world, rank=r, seed=9))
            for r in range(world)
        ]
        flat = [
            stripes[i % world][i // world] for i in range(48)
        ]
        assert flat == orders[0]

    def test_mid_epoch_resume_exactly_once_8_to_6(self):
        """Consume 24 of 96 on 8 ranks, reshard to 6 (72 remaining
        divides 6): the epoch is partitioned exactly — every sample
        once, none dropped, none replayed."""
        from horovod_tpu.data import ShardedIndexSampler

        samps = [
            ShardedIndexSampler(96, num_replicas=8, rank=r, seed=3)
            for r in range(8)
        ]
        seen = []
        for s in samps:
            it = iter(s)
            for _ in range(3):
                seen.append(next(it))
        states = [s.state_dict() for s in samps]
        assert all(st == states[0] for st in states)  # SPMD agreement
        assert states[0]["cursor"] == 24
        s6 = [
            ShardedIndexSampler(96, num_replicas=6, rank=r, seed=3)
            for r in range(6)
        ]
        for s in s6:
            s.load_state_dict(states[0])
        assert all(len(s) == 12 for s in s6)
        rest = [i for s in s6 for i in s]
        assert sorted(seen + rest) == list(range(96))

    def test_seed_mismatch_rejected(self):
        from horovod_tpu.data import ShardedIndexSampler

        s = ShardedIndexSampler(10, num_replicas=2, rank=0, seed=1)
        with pytest.raises(ValueError):
            s.load_state_dict({"epoch": 0, "cursor": 4, "seed": 2})

    def test_epoch_end_cursor_yields_nothing(self):
        from horovod_tpu.data import ShardedIndexSampler

        s = ShardedIndexSampler(10, num_replicas=2, rank=0, seed=1)
        s.load_state_dict({"epoch": 0, "cursor": 10, "seed": 1})
        assert list(s) == []
        s.set_epoch(1)
        assert len(list(s)) == 5  # new epoch resets the cursor


class TestDatasetResume:
    def _write(self, tmp_path, n=96):
        from horovod_tpu.data import write_shards

        x = np.arange(n, dtype=np.int64).reshape(n, 1)
        write_shards(str(tmp_path / "shards"), x, rows_per_shard=20)
        return str(tmp_path / "shards")

    def test_state_roundtrip_same_world(self, hvd, tmp_path):
        from horovod_tpu.data import ShardedFileDataset

        path = self._write(tmp_path)
        consumed = []
        dss = [
            ShardedFileDataset(
                path, batch_size=2, num_replicas=8, rank=r, seed=4
            )
            for r in range(8)
        ]
        for ds in dss:
            it = iter(ds)
            for _ in range(2):  # 2 batches x 2 rows
                consumed.append(next(it))
        st = dss[0].state_dict()
        assert st["cursor"] == 2 * 2 * 8
        fresh = [
            ShardedFileDataset(
                path, batch_size=2, num_replicas=8, rank=r, seed=4
            )
            for r in range(8)
        ]
        rest = []
        for ds in fresh:
            ds.load_state_dict(st)
            rest.extend(list(ds))
        ids_first = sorted(
            int(v) for b in consumed for v in np.asarray(b).reshape(-1)
        )
        ids_rest = sorted(
            int(v) for b in rest for v in np.asarray(b).reshape(-1)
        )
        assert sorted(ids_first + ids_rest) == list(range(96))

    @pytest.mark.slow
    def test_sigkill_resume_world_change_no_replay_no_drop(
        self, hvd, tmp_path
    ):
        """The acceptance drill's data half: iterate 2 batches/rank on
        8 ranks, commit durable state, SIGKILL the process; a fresh
        process at world 6 resumes from disk and lands on the exact
        next global index — the epoch partitions exactly across the
        kill + world change, three runs bit-identical."""
        path = self._write(tmp_path, n=96)
        ckdir = str(tmp_path / "state")
        script = tmp_path / "phase1.py"
        script.write_text(
            f"""
import os, signal
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import numpy as np
import horovod_tpu as hvd
from horovod_tpu.checkpoint import DurableJaxState
from horovod_tpu.data import ShardedFileDataset
import jax.numpy as jnp

dss = [
    ShardedFileDataset({path!r}, batch_size=2, num_replicas=8, rank=r,
                       seed=4)
    for r in range(8)
]
st = DurableJaxState({ckdir!r}, params={{"w": jnp.ones(4)}}, batch=0)
# ONE logical stream name (the world-size-independent contract): the
# cursor is global, so rank 0's sampler speaks for the gang
st.register_data("train", dss[0])
seen = []
its = [iter(ds) for ds in dss]
for _ in range(2):
    for it in its:
        seen.append(np.asarray(next(it)).reshape(-1).tolist())
st.batch = 2
st.commit()
st.wait_until_finished()
with open({str(tmp_path / 'seen.json')!r}, "w") as f:
    import json; json.dump(seen, f)
os.kill(os.getpid(), signal.SIGKILL)
"""
        )
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        repo = os.path.dirname(
            os.path.dirname(os.path.abspath(hvd_mod.__file__))
        )
        env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
        proc = subprocess.run(
            [sys.executable, str(script)], env=env, timeout=180
        )
        assert proc.returncode == -signal.SIGKILL
        with open(tmp_path / "seen.json") as f:
            seen = json.load(f)
        seen_ids = sorted(int(v) for b in seen for v in b)
        assert len(seen_ids) == 32  # 2 batches x 2 rows x 8 ranks

        def resume_rest():
            from horovod_tpu.checkpoint import DurableJaxState
            from horovod_tpu.data import ShardedFileDataset

            dss = [
                ShardedFileDataset(
                    path, batch_size=2, num_replicas=6, rank=r, seed=4
                )
                for r in range(6)
            ]
            st2 = DurableJaxState(
                ckdir, params={"w": jnp.zeros(4)}, batch=0
            )
            # each (simulated) process registers ITS dataset under the
            # same stream name and loads the shared global cursor
            st2.register_data("train", dss[0])
            assert st2.resume_latest()
            assert st2.batch == 2
            cursor = dss[0].state_dict()
            for ds in dss[1:]:
                ds.load_state_dict(cursor)
            out = []
            for ds in dss:
                out.append(
                    [np.asarray(b).reshape(-1).tolist() for b in ds]
                )
            return out

        runs = [resume_rest() for _ in range(3)]
        assert runs[0] == runs[1] == runs[2]  # deterministic resume
        rest_ids = sorted(
            int(v) for rank in runs[0] for b in rank for v in b
        )
        # 64 remaining over 6 ranks: ceil(64/6)=11 -> 5 batches x 2
        # rows x 6 ranks = 60 delivered inside exact batches; nothing
        # REPLAYED, and the undelivered tail is only the SPMD ragged
        # tail, never an arbitrary sample
        assert not (set(rest_ids) & set(seen_ids)), "sample replayed"
        assert len(rest_ids) == len(set(rest_ids)), "sample duplicated"
        missing = set(range(96)) - set(seen_ids) - set(rest_ids)
        assert len(missing) <= 64 - 60


class TestElasticCursorRollback:
    def test_restore_rewinds_data_cursor(self, hvd):
        from horovod_tpu.data import ShardedIndexSampler
        from horovod_tpu.elastic.state import JaxState

        s = ShardedIndexSampler(64, num_replicas=8, rank=0, seed=5)
        st = JaxState(params={"w": jnp.ones(4)}, batch=0)
        st.register_data("train", s)
        it = iter(s)
        [next(it) for _ in range(3)]
        st.batch = 3
        st.commit()
        it = iter(s)
        [next(it) for _ in range(2)]
        assert s.state_dict()["cursor"] == 16
        st.restore()
        assert s.state_dict()["cursor"] == 24  # last commit's cursor
        assert st.batch == 3

    def test_register_data_rejects_cursorless(self, hvd):
        from horovod_tpu.elastic.state import JaxState

        st = JaxState(params={"w": jnp.ones(4)})
        with pytest.raises(TypeError):
            st.register_data("x", object())


# ------------------------------------------------- end-to-end drill


@pytest.mark.slow
class TestEndToEndDrill:
    """The acceptance drill, composed: a seeded guarded run eats one
    injected NaN step (skipped + counted), one injected checkpoint
    bitflip (newest commit corrupted POST-commit), and a SIGKILL;
    resume at world 6 falls back past the damaged checkpoint via
    digest verification, lands on the exact next global sample, and
    produces a BIT-IDENTICAL post-resume loss trajectory across 3
    repeated resumes."""

    N, BATCH = 96, 2

    def test_full_drill(self, hvd, tmp_path):
        from horovod_tpu.data import ShardedFileDataset, write_shards

        path = str(tmp_path / "shards")
        x = np.arange(self.N, dtype=np.int64).reshape(self.N, 1)
        write_shards(path, x, rows_per_shard=20)
        ckdir = str(tmp_path / "state")
        repo = os.path.dirname(
            os.path.dirname(os.path.abspath(hvd_mod.__file__))
        )
        script = tmp_path / "phase1.py"
        script.write_text(
            f"""
import os, signal, sys, json
sys.path.insert(0, {repo!r})
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count=8"
).strip()
import numpy as np, jax, jax.numpy as jnp, optax
from jax.sharding import PartitionSpec as P
import horovod_tpu as hvd
from horovod_tpu.common.compat import shard_map
from horovod_tpu.checkpoint import DurableJaxState
from horovod_tpu.data import ShardedFileDataset
from horovod_tpu.testing import chaos

# the seeded plan: NaN at training step 3, bitflip on the 4th (last)
# checkpoint save — the NEWEST commit is the corrupted one
chaos.configure("seed=11;train.nan@3:nan;checkpoint.save@4:bitflip")
hvd.init()
world = 8
dss = [
    ShardedFileDataset({path!r}, batch_size={self.BATCH},
                       num_replicas=8, rank=r, seed=4)
    for r in range(8)
]
opt = hvd.DistributedOptimizer(
    optax.sgd(0.05), op=hvd.Average, grad_guard=True, guard_max_skips=0
)
params = {{"w": jnp.linspace(1.0, 2.0, 4096, dtype=jnp.float32)}}
ostate = opt.init(params)
st = DurableJaxState({ckdir!r}, params=params, opt_state=ostate, batch=0)
st.register_data("train", dss[0])
mesh = hvd.mesh()

@jax.jit
def step(g, s, p):
    def body(g, s, p):
        g = jax.tree_util.tree_map(lambda t: t[0], g)
        u, s2 = opt.update(g, s, p)
        return jax.tree_util.tree_map(lambda a, b: a + b, p, u), s2
    return shard_map(
        body, mesh=mesh, in_specs=(P(hvd.WORLD_AXIS), P(), P()),
        out_specs=(P(), P()), check_vma=False,
    )(g, s, p)

its = [iter(ds) for ds in dss]
losses = []
for i in range(1, 5):
    rows = [np.asarray(next(it)).reshape(-1) for it in its]
    g = {{"w": jnp.stack([
        jnp.full((4096,), float(r.sum()) / 100.0, jnp.float32)
        for r in rows
    ])}}
    if chaos.inject("train.nan") == "nan":
        g = {{"w": g["w"].at[0, 0].set(jnp.nan)}}
    newp, ostate = step(g, st.opt_state, st.params)
    jax.block_until_ready(newp["w"])
    st.params = newp
    st.opt_state = ostate
    st.batch = i
    losses.append(float(jnp.sum(newp["w"])))
    st.commit()
st.wait_until_finished()
assert int(st.opt_state.guard_skips) == 1, int(st.opt_state.guard_skips)
with open({str(tmp_path / "phase1.json")!r}, "w") as f:
    json.dump({{"losses": losses}}, f)
os.kill(os.getpid(), signal.SIGKILL)
"""
        )
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        proc = subprocess.run(
            [sys.executable, str(script)], env=env, timeout=300
        )
        assert proc.returncode == -signal.SIGKILL
        assert (tmp_path / "phase1.json").exists()

        # ---- resume at world 6, three times, bit-identical ----
        from jax.sharding import Mesh

        from horovod_tpu.checkpoint import DurableJaxState

        mesh6 = Mesh(
            np.array(jax.devices()[:6]), (hvd_mod.WORLD_AXIS,)
        )
        opt6 = hvd_mod.DistributedOptimizer(
            optax.sgd(0.05), op=hvd_mod.Average, grad_guard=True,
            guard_max_skips=0,
        )

        def resume_and_train():
            dss = [
                ShardedFileDataset(
                    path, batch_size=self.BATCH, num_replicas=6,
                    rank=r, seed=4,
                )
                for r in range(6)
            ]
            params = {"w": jnp.zeros((4096,), jnp.float32)}
            st2 = DurableJaxState(
                ckdir, params=params, opt_state=opt6.init(params),
                batch=0,
            )
            st2.register_data("train", dss[0])
            before = registry.snapshot()
            assert st2.resume_latest()
            # the bitflipped NEWEST commit (4) was bypassed: digest
            # mismatch counted, batch rolled to commit 3
            assert _delta("checkpoint.digest_mismatch", before) >= 1
            assert _delta("checkpoint.fallback", before) >= 1
            assert st2.batch == 3
            # the skipped NaN step survived the durable boundary
            assert int(st2.opt_state.guard_skips) == 1
            # exact next sample: 3 batches x 2 rows x 8 ranks consumed
            cursor = dss[0].state_dict()
            assert cursor["cursor"] == 3 * self.BATCH * 8
            for ds in dss[1:]:
                ds.load_state_dict(cursor)

            @jax.jit
            def step6(g, s, p):
                def body(g, s, p):
                    g = jax.tree_util.tree_map(lambda t: t[0], g)
                    u, s2 = opt6.update(g, s, p)
                    return (
                        jax.tree_util.tree_map(
                            lambda a, b: a + b, p, u
                        ),
                        s2,
                    )

                return shard_map(
                    body, mesh=mesh6,
                    in_specs=(P(hvd_mod.WORLD_AXIS), P(), P()),
                    out_specs=(P(), P()),
                    check_vma=False,
                )(g, s, p)

            its = [iter(ds) for ds in dss]
            # the elastic reinit re-replicates state onto the NEW
            # gang's mesh; this drill does it explicitly for the
            # 6-device sub-mesh
            from jax.sharding import NamedSharding

            sh6 = NamedSharding(mesh6, P())
            ostate = jax.device_put(jax.device_get(st2.opt_state), sh6)
            params = jax.device_put(jax.device_get(st2.params), sh6)
            losses, batch_ids = [], []
            for _ in range(3):
                rows = [np.asarray(next(it)).reshape(-1) for it in its]
                batch_ids.extend(int(v) for r in rows for v in r)
                g = {"w": jnp.stack([
                    jnp.full(
                        (4096,), float(r.sum()) / 100.0, jnp.float32
                    )
                    for r in rows
                ])}
                params, ostate = step6(g, ostate, params)
                jax.block_until_ready(params["w"])
                losses.append(float(jnp.sum(params["w"])))
            return losses, batch_ids

        runs = [resume_and_train() for _ in range(3)]
        assert runs[0] == runs[1] == runs[2]  # BIT-identical trajectory
        # no sample of the committed prefix is replayed: the first 48
        # global samples were consumed before the commit the resume
        # landed on
        order = np.random.default_rng((4, 0)).permutation(self.N)
        consumed = set(order[: 3 * self.BATCH * 8].tolist())
        assert not (set(runs[0][1]) & consumed), "sample replayed"


# --------------------------------------------------- StepStats deltas


class TestStepStatsIntegrity:
    def test_guard_and_audit_deltas_in_records(self, hvd):
        from horovod_tpu.common import telemetry

        telemetry._reset_hub()
        try:
            hub = telemetry.TelemetryHub(capacity=8)
            hub.step_begin(0)
            registry.counter("guard.nonfinite_steps")
            hvd_mod.audit({"w": jnp.ones(4)}, step=7)
            rec = hub.step_end()
            assert rec["guard.nonfinite_steps"] == 1
            assert rec["audit_ran"] == 1.0
            assert rec["audit.last_digest_step"] == 7.0  # the gauge
            hub.step_begin(1)
            rec = hub.step_end()
            assert rec["guard.nonfinite_steps"] == 0
            assert rec["audit_ran"] == 0.0
            assert rec["audit.last_digest_step"] == 7.0
        finally:
            telemetry._reset_hub()
