"""``horovod_tpu.spark`` — the Estimator-layer parity surface.

The reference's largest subsystem is ``horovod/spark/`` (~8k LoC [V],
SURVEY.md §2.5): ``horovod.spark.run(fn)`` for function dispatch, and a
DataFrame Estimator (``TorchEstimator``/``KerasEstimator`` +
``Store``) that trains a model over Spark data and hands back a
servable model. This package is the TPU-native analog, scoped as
follows (see also docs/design.md "Spark / Ray depth"):

* ``run(fn)`` — full parity in shape: dispatch a function across the
  worker set (delegates to :mod:`horovod_tpu.executor`, which owns the
  runner stack).
* ``TpuEstimator.fit(...) -> TpuModel`` — the Estimator contract
  (declare model+optimizer+loss, call fit, get a predictor with
  checkpointed weights) rebuilt on the TPU-native stack: jit-compiled
  data-parallel training over the world mesh with batch sharding (XLA
  inserts the gradient collectives), Orbax checkpoints through the
  ``Store`` abstraction.
* ``spark.keras.KerasEstimator`` / ``spark.torch.TorchEstimator`` —
  the framework-shim halves of the Estimator family (TF and torch),
  each broadcasting initial state and wrapping the shim's
  ``DistributedOptimizer``.
* ``Store`` / ``LocalStore`` — the reference's storage abstraction
  (``horovod/spark/common/store.py`` [V]): one object owning the
  checkpoint/log/run directories, local-FS or any fsspec-style mount.

Deliberately out of scope (documented, not silent): Spark DataFrames /
Petastorm ingestion — there is no Spark cluster adjacent to a TPU pod;
the Estimator consumes arrays or batch iterables instead. MLlib
pipeline integration (``HorovodEstimator`` as a Spark ML stage) falls
with it.
"""

from __future__ import annotations

import os
from typing import Any, Callable, Iterable, Optional, Sequence, Tuple

import numpy as np

from ..executor import run  # noqa: F401  — horovod.spark.run parity
from ..executor import run_elastic  # noqa: F401  — run_elastic parity


class Store:
    """Filesystem layout for an Estimator run (ref:
    horovod/spark/common/store.py Store [V]): checkpoints, logs, and
    a scratch run dir under one prefix."""

    def __init__(self, prefix_path: str):
        self.prefix_path = os.path.abspath(prefix_path)

    def checkpoint_dir(self, run_id: str) -> str:
        return os.path.join(self.prefix_path, run_id, "checkpoints")

    def logs_dir(self, run_id: str) -> str:
        return os.path.join(self.prefix_path, run_id, "logs")

    def run_dir(self, run_id: str) -> str:
        return os.path.join(self.prefix_path, run_id)

    def exists(self, path: str) -> bool:
        return os.path.exists(path)

    @classmethod
    def create(cls, prefix_path: str) -> "Store":
        return LocalStore(prefix_path)


class LocalStore(Store):
    """Local/NFS filesystem store (ref: LocalStore [V])."""


def _accepts_train(module) -> bool:
    import inspect

    try:
        return "train" in inspect.signature(type(module).__call__).parameters
    except (TypeError, ValueError):
        return False


class TpuModel:
    """The servable result of ``TpuEstimator.fit`` (ref: the Estimator's
    returned ``TorchModel``/``KerasModel`` transformers [V]): holds the
    trained params plus any auxiliary variable collections (e.g.
    batch_stats) and a jitted predict."""

    def __init__(self, module, params, collections=None):
        import jax

        self.module = module
        self.params = params
        self.collections = dict(collections or {})
        eval_kwargs = {"train": False} if _accepts_train(module) else {}

        def _apply(params, collections, x):
            return module.apply(
                {"params": params, **collections}, x, **eval_kwargs
            )

        self._predict = jax.jit(_apply)

    # kept for round-2 callers
    @property
    def batch_stats(self):
        return self.collections.get("batch_stats")

    def predict(self, x):
        import numpy as _np

        return _np.asarray(
            self._predict(self.params, self.collections, _np.asarray(x))
        )

    def save(self, path: str) -> None:
        from ..checkpoint import CheckpointManager

        tree = {"params": self.params}
        if self.collections:
            tree["collections"] = self.collections
        with CheckpointManager(path, async_save=False) as mgr:
            mgr.save(0, tree)

    @classmethod
    def load(cls, module, path: str):
        from ..checkpoint import CheckpointManager

        with CheckpointManager(path, async_save=False) as mgr:
            tree = mgr.restore()
        collections = tree.get("collections")
        if not collections and tree.get("batch_stats"):
            # round-2 checkpoints stored batch_stats at the top level
            collections = {"batch_stats": tree["batch_stats"]}
        return cls(module, tree["params"], collections or {})


class TpuEstimator:
    """Declarative trainer (ref: horovod/spark/torch/estimator.py
    TorchEstimator [V]): declare the model, optimizer and loss; call
    ``fit``; receive a :class:`TpuModel`.

    TPU-first training loop: ONE jitted train step, params replicated,
    batch sharded over the world mesh's data axis via NamedSharding —
    XLA inserts the gradient reduction (the scaling-book recipe), so
    there is no per-tensor hook machinery to schedule.
    """

    def __init__(
        self,
        model,
        loss: Callable,
        optimizer=None,
        store: Optional[Store] = None,
        run_id: str = "run",
        epochs: int = 1,
        batch_size: int = 32,
        checkpoint_every_n_epochs: int = 1,
        seed: int = 0,
    ):
        self.model = model
        self.loss = loss
        self.optimizer = optimizer
        self.store = store
        self.run_id = run_id
        self.epochs = int(epochs)
        self.batch_size = int(batch_size)
        self.checkpoint_every = int(checkpoint_every_n_epochs)
        self.seed = seed
        self.history: list = []

    def _batches(self, x, y, batch_size=None):
        bs = batch_size or self.batch_size
        n = x.shape[0]
        # drop the ragged tail so every jitted step sees one static shape
        # (XLA semantics: shapes are compile-time)
        steps = n // bs
        for i in range(steps):
            sl = slice(i * bs, (i + 1) * bs)
            yield x[sl], y[sl]

    def fit(self, x, y=None) -> TpuModel:
        """Train. ``x`` may be a feature array (with ``y`` labels) or an
        iterable of ``(x_batch, y_batch)`` pairs per epoch (the
        DataFrame/Petastorm slot in the reference [V])."""
        import jax
        import jax.numpy as jnp
        import optax

        from ..common import basics

        self.history = []  # fresh per fit(): re-fit must not append
        basics.init()
        mesh = basics.topology().world_mesh()
        from jax.sharding import NamedSharding, PartitionSpec as P

        world = basics.topology().size
        replicated = NamedSharding(mesh, P())
        opt = self.optimizer or optax.adam(1e-3)

        # Resolve the input FIRST: the sharding decision below must see
        # the batch size the batches will actually have. For dataset
        # input that is the DATASET's batch size (a stale estimator
        # value would pass the divisibility check and then fail
        # device_put mid-epoch, or silently lose data parallelism).
        dataset = None
        batch_size = self.batch_size  # effective; self stays unmutated
        if y is not None:
            x = np.asarray(x)
            y = np.asarray(y)
            sample = x[:batch_size]
        elif hasattr(x, "set_epoch") and hasattr(x, "__len__"):
            # Re-iterable sharded dataset (data.ShardedFileDataset — the
            # Petastorm-reader slot [V]): stream it lazily, do NOT
            # materialize; fit advances its epoch for per-epoch shuffles.
            dataset = x
            ds_batch = getattr(dataset, "batch_size", None)
            if ds_batch is not None and int(ds_batch) != batch_size:
                from ..common.logging import get_logger

                get_logger("spark").info(
                    "using the dataset's batch_size=%d (estimator "
                    "batch_size=%d does not apply to dataset input)",
                    int(ds_batch), batch_size,
                )
                batch_size = int(ds_batch)
            first = next(iter(dataset), None)
            if first is None:
                raise ValueError("empty dataset")
            if not (isinstance(first, tuple) and len(first) == 2):
                raise ValueError(
                    "fit() needs labeled batches: the dataset yields "
                    "bare feature arrays (written without y?); "
                    "write_shards(path, x, y) produces the (x, y) form"
                )
            sample = np.asarray(first[0])
        else:
            # Materialize the batch source: a one-shot generator must
            # survive the shape peek below AND re-iterate every epoch.
            x = list(x)
            if not x:
                raise ValueError("empty batch iterable")
            sample = np.asarray(x[0][0])
            batch_size = int(sample.shape[0])

        # Batch rides the data axis when it divides evenly; otherwise it
        # replicates (correct, just not parallel) — a loud log beats a
        # shape error mid-epoch.
        if batch_size % world == 0:
            data_sharding = NamedSharding(mesh, P(basics_world_axis()))
        else:
            from ..common.logging import get_logger

            get_logger("spark").warning(
                "batch_size %d not divisible by world %d; replicating "
                "batches (no data parallelism)",
                batch_size,
                world,
            )
            data_sharding = NamedSharding(mesh, P())

        rng = jax.random.PRNGKey(self.seed)
        model = self.model
        train_kwargs = {"train": True} if _accepts_train(model) else {}
        init_kwargs = {"train": False} if _accepts_train(model) else {}
        variables = model.init(
            {"params": rng, "dropout": jax.random.fold_in(rng, 1)},
            jnp.asarray(sample),
            **init_kwargs,
        )
        params = variables["params"]
        # Auxiliary collections (batch_stats etc.) thread through the
        # step as mutable state — BN/dropout models train out of the box.
        collections = {k: v for k, v in variables.items() if k != "params"}
        mutable = sorted(collections)
        params = jax.device_put(params, replicated)
        collections = jax.device_put(collections, replicated)
        opt_state = jax.device_put(opt.init(params), replicated)
        loss_fn = self.loss
        dropout_rng = jax.random.fold_in(rng, 2)

        @jax.jit
        def train_step(params, collections, opt_state, xb, yb, step):
            # fresh dropout mask every step — a fixed key would prune
            # the same units for the whole run
            step_rng = jax.random.fold_in(dropout_rng, step)

            def objective(p):
                if mutable:
                    preds, mutated = model.apply(
                        {"params": p, **collections},
                        xb,
                        mutable=mutable,
                        rngs={"dropout": step_rng},
                        **train_kwargs,
                    )
                else:
                    preds = model.apply(
                        {"params": p},
                        xb,
                        rngs={"dropout": step_rng},
                        **train_kwargs,
                    )
                    mutated = {}
                return loss_fn(preds, yb), mutated

            (loss, mutated), grads = jax.value_and_grad(
                objective, has_aux=True
            )(params)
            updates, opt_state2 = opt.update(grads, opt_state, params)
            new_cols = {**collections, **mutated}
            return (
                optax.apply_updates(params, updates),
                new_cols,
                opt_state2,
                loss,
            )

        mgr = None
        if self.store is not None:
            from ..checkpoint import CheckpointManager

            os.makedirs(self.store.logs_dir(self.run_id), exist_ok=True)
            mgr = CheckpointManager(
                self.store.checkpoint_dir(self.run_id), async_save=False
            )

        global_step = 0
        try:
            for epoch in range(self.epochs):
                epoch_losses = []
                if dataset is not None:
                    dataset.set_epoch(epoch)
                batches = (
                    self._batches(x, y, batch_size)
                    if y is not None
                    else iter(x)
                )
                for xb, yb in batches:
                    xb = jax.device_put(np.asarray(xb), data_sharding)
                    yb = jax.device_put(np.asarray(yb), data_sharding)
                    params, collections, opt_state, loss = train_step(
                        params, collections, opt_state, xb, yb,
                        jnp.asarray(global_step, jnp.int32),
                    )
                    global_step += 1
                    epoch_losses.append(float(loss))
                mean_loss = float(np.mean(epoch_losses or [np.nan]))
                self.history.append({"epoch": epoch, "loss": mean_loss})
                if mgr is not None and (epoch + 1) % self.checkpoint_every == 0:
                    tree = {"params": params}
                    if collections:
                        tree["collections"] = collections
                    mgr.save(epoch, tree)
        finally:
            if mgr is not None:
                mgr.close()

        return TpuModel(self.model, params, collections)


def basics_world_axis() -> str:
    from ..common.topology import WORLD_AXIS

    return WORLD_AXIS
