"""Reduction-op constants, API parity with the reference's op enum
(ref: horovod/common/message.h ReduceOp + horovod/torch/mpi_ops.py
Average/Sum/Adasum/Min/Max/Product [V], SURVEY.md §2.4)."""

from __future__ import annotations

import enum


class ReduceOp(enum.IntEnum):
    AVERAGE = 0
    SUM = 1
    ADASUM = 2
    MIN = 3
    MAX = 4
    PRODUCT = 5


# Module-level aliases matching `hvd.Average` etc.
Average = ReduceOp.AVERAGE
Sum = ReduceOp.SUM
Adasum = ReduceOp.ADASUM
Min = ReduceOp.MIN
Max = ReduceOp.MAX
Product = ReduceOp.PRODUCT


def resolve_op(op, average):
    """Reconcile the legacy ``average=`` kwarg with ``op=`` the way the
    reference does (horovod/torch/mpi_ops.py::_allreduce_function_factory
    handling [V]): passing both is an error; ``average`` maps to
    AVERAGE/SUM."""
    if average is not None:
        if op is not None:
            raise ValueError("'op' and deprecated 'average' cannot both be set")
        return Average if average else Sum
    return Average if op is None else ReduceOp(op)
