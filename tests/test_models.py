"""Model zoo: shapes, dtypes, and trainability (one-step loss decrease),
mirroring the reference's example-model smoke coverage
(examples/pytorch/pytorch_mnist.py path [V])."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from horovod_tpu.models import (
    MNISTConvNet,
    ResNet50,
    Transformer,
    TransformerConfig,
    ViT,
    ViTConfig,
)


def test_mnist_convnet_forward_and_train():
    model = MNISTConvNet()
    x = jnp.zeros((8, 28, 28, 1))
    params = model.init(jax.random.PRNGKey(0), x, train=False)
    logits = model.apply(params, x, train=False)
    assert logits.shape == (8, 10)

    y = jnp.zeros((8,), jnp.int32)
    opt = optax.sgd(0.1)
    state = opt.init(params)

    def loss_fn(p):
        lg = model.apply(p, x, train=False)
        return optax.softmax_cross_entropy_with_integer_labels(lg, y).mean()

    l0, g = jax.value_and_grad(loss_fn)(params)
    updates, state = opt.update(g, state, params)
    params2 = optax.apply_updates(params, updates)
    l1 = loss_fn(params2)
    assert float(l1) < float(l0)


def test_resnet50_forward_shapes():
    model = ResNet50(num_classes=10, dtype=jnp.float32)
    x = jnp.zeros((2, 64, 64, 3))
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    out = model.apply(variables, x, train=False)
    assert out.shape == (2, 10)
    assert out.dtype == jnp.float32
    # batch_stats collection exists (SyncBatchNorm state)
    assert "batch_stats" in variables


def test_resnet_sync_batchnorm_updates_stats():
    model = ResNet50(num_classes=10, dtype=jnp.float32)
    x = jnp.ones((2, 32, 32, 3))
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    _, mutated = model.apply(
        variables, x, train=True, mutable=["batch_stats"]
    )
    before = jax.tree_util.tree_leaves(variables["batch_stats"])
    after = jax.tree_util.tree_leaves(mutated["batch_stats"])
    assert any(
        not np.allclose(np.asarray(a), np.asarray(b))
        for a, b in zip(before, after)
    )


@pytest.mark.parametrize("causal", [True, False])
def test_transformer_forward(causal):
    cfg = TransformerConfig.tiny(causal=causal)
    model = Transformer(cfg)
    tokens = jnp.zeros((2, 16), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), tokens, train=False)
    logits = model.apply(params, tokens, train=False)
    assert logits.shape == (2, 16, cfg.vocab_size)
    assert logits.dtype == jnp.float32


def test_transformer_causality():
    """Changing a future token must not affect earlier logits."""
    cfg = TransformerConfig.tiny(causal=True)
    model = Transformer(cfg)
    t1 = jnp.asarray([[1, 2, 3, 4, 5, 6, 7, 8]], jnp.int32)
    t2 = t1.at[0, -1].set(99)
    params = model.init(jax.random.PRNGKey(0), t1, train=False)
    l1 = model.apply(params, t1, train=False)
    l2 = model.apply(params, t2, train=False)
    np.testing.assert_allclose(
        np.asarray(l1[0, :-1]), np.asarray(l2[0, :-1]), rtol=1e-5
    )
    assert not np.allclose(np.asarray(l1[0, -1]), np.asarray(l2[0, -1]))


@pytest.mark.parametrize("causal", [True, False])
def test_transformer_padded_lengths_flash_matches_dense(causal):
    """lengths= keeps the flash path (interpret kernels here) and must
    match the dense path's masked computation logit-for-logit; padded
    positions must not influence valid ones."""
    import dataclasses

    cfg = TransformerConfig.tiny(causal=causal)
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, cfg.vocab_size, (2, 16)),
        jnp.int32,
    )
    lengths = jnp.asarray([16, 7], jnp.int32)
    flash_cfg = dataclasses.replace(cfg, flash_attention=True)
    dense_cfg = dataclasses.replace(cfg, flash_attention=False)
    params = Transformer(flash_cfg).init(
        jax.random.PRNGKey(0), tokens, train=False
    )
    lf = Transformer(flash_cfg).apply(
        params, tokens, train=False, lengths=lengths
    )
    ld = Transformer(dense_cfg).apply(
        params, tokens, train=False, lengths=lengths
    )
    np.testing.assert_allclose(
        np.asarray(lf), np.asarray(ld), rtol=5e-4, atol=5e-4
    )
    # a token edit INSIDE the padding must not change valid logits
    tokens2 = tokens.at[1, 12].set(3)
    lf2 = Transformer(flash_cfg).apply(
        params, tokens2, train=False, lengths=lengths
    )
    np.testing.assert_allclose(
        np.asarray(lf[1, :7]), np.asarray(lf2[1, :7]), rtol=1e-5
    )


def test_lm_head_mixed_matches_fp32_within_bf16_rounding():
    """The mixed-precision head (bf16 operands, fp32 accumulation) must
    agree with the all-fp32 head to bf16 input-rounding tolerance, on
    an IDENTICAL param tree (checkpoints are layout-compatible)."""
    import dataclasses

    # bf16 trunk for BOTH configs: identical activations reach the
    # head, so the only difference measured is the head matmul's
    # precision (tiny()'s fp32 dtype would make the comparison vacuous)
    cfg32 = dataclasses.replace(
        TransformerConfig.tiny(causal=True),
        dtype=jnp.bfloat16,
        head_mixed_precision=False,
    )
    cfgmx = dataclasses.replace(cfg32, head_mixed_precision=True)
    tokens = jnp.asarray([[1, 2, 3, 4]], jnp.int32)
    p32 = Transformer(cfg32).init(jax.random.PRNGKey(0), tokens,
                                  train=False)
    pmx = Transformer(cfgmx).init(jax.random.PRNGKey(0), tokens,
                                  train=False)
    s32 = jax.tree_util.tree_map(lambda a: (a.shape, a.dtype.name), p32)
    smx = jax.tree_util.tree_map(lambda a: (a.shape, a.dtype.name), pmx)
    assert s32 == smx
    l32 = Transformer(cfg32).apply(p32, tokens, train=False)
    lmx = Transformer(cfgmx).apply(p32, tokens, train=False)
    assert lmx.dtype == jnp.float32
    scale = float(jnp.max(jnp.abs(l32)))
    assert float(jnp.max(jnp.abs(lmx - l32))) <= 0.02 * max(scale, 1.0)


def test_transformer_named_configs():
    gpt2 = TransformerConfig.gpt2_medium()
    assert (gpt2.num_layers, gpt2.d_model) == (24, 1024) and gpt2.causal
    bert = TransformerConfig.bert_large()
    assert (bert.num_layers, bert.d_model) == (24, 1024) and not bert.causal


def test_vit_forward():
    cfg = ViTConfig.tiny()
    model = ViT(cfg)
    x = jnp.zeros((2, 32, 32, 3))
    params = model.init(jax.random.PRNGKey(0), x, train=False)
    out = model.apply(params, x, train=False)
    assert out.shape == (2, 10)


def test_resnet_space_to_depth_stem_matches_grid():
    """The s2d stem (MLPerf TPU trick) must produce the exact conv7
    output grid and train end-to-end."""
    import jax
    import jax.numpy as jnp

    from horovod_tpu.models.resnet import ResNet

    x = jnp.asarray(
        np.random.default_rng(1).normal(size=(2, 32, 32, 3)), jnp.float32
    )
    shapes = {}
    for stem in ("conv7", "space_to_depth"):
        m = ResNet(
            stage_sizes=(1, 1), num_classes=7, width=8,
            dtype=jnp.float32, stem=stem,
        )
        v = m.init(jax.random.PRNGKey(0), x, train=False)
        y, _ = m.apply(v, x, train=True, mutable=["batch_stats"])
        shapes[stem] = y.shape
        assert bool(jnp.isfinite(y).all())
    assert shapes["conv7"] == shapes["space_to_depth"] == (2, 7)


def test_resnet_space_to_depth_equivalent_function_class():
    """A 7x7/s2 stem conv embeds exactly into the 4x4/s1 s2d conv: with
    the re-laid-out weights both compute the same function."""
    import jax
    import jax.numpy as jnp

    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.normal(size=(1, 16, 16, 3)), jnp.float32)
    w7 = jnp.asarray(rng.normal(size=(7, 7, 3, 4)), jnp.float32)
    y_ref = jax.lax.conv_general_dilated(
        x, w7, window_strides=(2, 2), padding=[(3, 3), (3, 3)],
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )
    # space-to-depth input
    n, h, w, c = x.shape
    x2 = (
        x.reshape(n, h // 2, 2, w // 2, 2, c)
        .transpose(0, 1, 3, 2, 4, 5)
        .reshape(n, h // 2, w // 2, 4 * c)
    )
    # embed w7 into the (4,4,12,4) kernel: tap (dy,dx) lands at
    # s2d position (ey+2, ex+2) channel (py*2+px)*c+cc with
    # dy-3 = 2*ey+py
    w4 = np.zeros((4, 4, 4 * c, 4), np.float32)
    for dy in range(7):
        for dx in range(7):
            ey, py = divmod(dy - 3, 2)
            ex, px = divmod(dx - 3, 2)
            w4[ey + 2, ex + 2, (py * 2 + px) * c : (py * 2 + px + 1) * c] = (
                np.asarray(w7[dy, dx])
            )
    y_s2d = jax.lax.conv_general_dilated(
        x2, jnp.asarray(w4), window_strides=(1, 1),
        padding=[(2, 1), (2, 1)],
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )
    np.testing.assert_allclose(
        np.asarray(y_ref), np.asarray(y_s2d), rtol=1e-5, atol=1e-5
    )


def test_vgg16_forward_and_train_step():
    """VGG-16 — the reference's 68%-scaling benchmark model
    (docs/benchmarks.rst [V]): forward shape + one grad step."""
    import jax
    import jax.numpy as jnp
    import optax

    from horovod_tpu.models import VGG16

    m = VGG16(num_classes=13, classifier_width=64, dtype=jnp.float32)
    x = jnp.asarray(
        np.random.default_rng(0).normal(size=(2, 32, 32, 3)), jnp.float32
    )
    v = m.init(jax.random.PRNGKey(0), x, train=False)
    y = m.apply(v, x, train=False)
    assert y.shape == (2, 13)
    # 16 weight layers: 13 convs + 3 dense
    n_layers = len(jax.tree_util.tree_leaves(v["params"])) // 2
    assert n_layers == 16

    def loss(p):
        out = m.apply(
            {"params": p}, x, train=True, rngs={"dropout": jax.random.PRNGKey(1)}
        )
        return optax.softmax_cross_entropy_with_integer_labels(
            out, jnp.zeros(2, jnp.int32)
        ).mean()

    g = jax.grad(loss)(v["params"])
    assert all(
        bool(jnp.isfinite(leaf).all()) for leaf in jax.tree_util.tree_leaves(g)
    )


def test_inception_v3_forward_shapes():
    """Inception V3 — the reference's headline ~90%-scaling model
    (docs/benchmarks.rst [V]): 299x299 input → 1000 logits, batch-stats
    collection works, param count ≈ 23.8M (torchvision parity ±5%)."""
    import jax
    import jax.numpy as jnp

    from horovod_tpu.models import InceptionV3

    m = InceptionV3(dtype=jnp.float32)
    x = jnp.zeros((1, 299, 299, 3), jnp.float32)
    v = jax.eval_shape(lambda: m.init(jax.random.PRNGKey(0), x, train=False))
    n_params = sum(
        int(np.prod(l.shape)) for l in jax.tree_util.tree_leaves(v["params"])
    )
    assert 22.5e6 < n_params < 25.5e6, n_params
    logits_shape = jax.eval_shape(
        lambda vv: m.apply(vv, x, train=False), v
    )
    assert tuple(logits_shape.shape) == (1, 1000)


def test_transformer_flash_matches_dense_path():
    """flash_attention='auto' must be numerically consistent with the
    dense path (same params, same tokens)."""
    import dataclasses

    import jax
    import jax.numpy as jnp

    from horovod_tpu.models import Transformer, TransformerConfig

    cfg_dense = dataclasses.replace(
        TransformerConfig.tiny(causal=True), flash_attention=False
    )
    cfg_flash = dataclasses.replace(cfg_dense, flash_attention=True)
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, 256, size=(2, 32)), jnp.int32
    )
    params = Transformer(cfg_dense).init(
        jax.random.PRNGKey(0), tokens, train=False
    )
    out_d = Transformer(cfg_dense).apply(params, tokens, train=False)
    out_f = Transformer(cfg_flash).apply(params, tokens, train=False)
    np.testing.assert_allclose(
        np.asarray(out_f), np.asarray(out_d), rtol=2e-4, atol=2e-4
    )


def test_transformer_gqa_flash_matches_dense():
    """num_kv_heads < num_heads: split q/kv projections, flash path
    reads shared kv rows; must match the dense path's repeated-head
    computation logit-for-logit."""
    import dataclasses

    cfg = dataclasses.replace(
        TransformerConfig.tiny(causal=True), num_kv_heads=2
    )
    assert cfg.num_heads % 2 == 0 and cfg.num_heads != 2
    tokens = jnp.asarray(
        np.random.default_rng(1).integers(0, cfg.vocab_size, (2, 16)),
        jnp.int32,
    )
    flash_cfg = dataclasses.replace(cfg, flash_attention=True)
    dense_cfg = dataclasses.replace(cfg, flash_attention=False)
    params = Transformer(flash_cfg).init(
        jax.random.PRNGKey(0), tokens, train=False
    )
    # the GQA param tree splits the projection
    blk = params["params"]["block_0"]["MultiHeadAttention_0"]
    assert "q" in blk and "kv" in blk and "qkv" not in blk
    lf = Transformer(flash_cfg).apply(params, tokens, train=False)
    ld = Transformer(dense_cfg).apply(params, tokens, train=False)
    np.testing.assert_allclose(
        np.asarray(lf), np.asarray(ld), rtol=5e-4, atol=5e-4
    )


def test_vit_flash_pad_matches_dense():
    """ViT's untileable token count (tiny: 16+1=17) padded to the next
    8-multiple with lengths= must reproduce the unpadded dense model's
    logits exactly — on both the dense-with-lengths path and the
    flash-forced path (interpret kernels)."""
    import dataclasses

    x = jnp.asarray(
        np.random.default_rng(2).normal(size=(2, 32, 32, 3)), jnp.float32
    )
    base = dataclasses.replace(ViTConfig.tiny(), flash_pad=False)
    params = ViT(base).init(jax.random.PRNGKey(0), x, train=False)
    want = ViT(base).apply(params, x, train=False)
    for cfg in (
        dataclasses.replace(ViTConfig.tiny(), flash_pad=True),
        dataclasses.replace(
            ViTConfig.tiny(), flash_pad=True, flash_attention=True
        ),
    ):
        got = ViT(cfg).apply(params, x, train=False)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=5e-5, atol=5e-5
        )


def test_transformer_mistral_trifecta_flash_matches_dense():
    """sliding_window + num_kv_heads + lengths composed in the model:
    flash path vs dense path logit-for-logit."""
    import dataclasses

    cfg = dataclasses.replace(
        TransformerConfig.tiny(causal=True),
        num_kv_heads=2, sliding_window=6,
    )
    tokens = jnp.asarray(
        np.random.default_rng(4).integers(0, cfg.vocab_size, (2, 16)),
        jnp.int32,
    )
    lengths = jnp.asarray([16, 9], jnp.int32)
    flash_cfg = dataclasses.replace(cfg, flash_attention=True)
    dense_cfg = dataclasses.replace(cfg, flash_attention=False)
    params = Transformer(flash_cfg).init(
        jax.random.PRNGKey(0), tokens, train=False
    )
    lf = Transformer(flash_cfg).apply(
        params, tokens, train=False, lengths=lengths
    )
    ld = Transformer(dense_cfg).apply(
        params, tokens, train=False, lengths=lengths
    )
    np.testing.assert_allclose(
        np.asarray(lf), np.asarray(ld), rtol=5e-4, atol=5e-4
    )
    # the window actually bites: full-causal config differs
    full = dataclasses.replace(flash_cfg, sliding_window=None)
    lfull = Transformer(full).apply(
        params, tokens, train=False, lengths=lengths
    )
    assert not np.allclose(np.asarray(lf), np.asarray(lfull), atol=1e-3)


def test_rope_properties_and_llama_shape_trains():
    """RoPE: relative-position property (scores depend only on row-col
    offset) and a full Llama/Mistral-shaped config (RoPE + GQA +
    sliding window, no learned pos table) trains through flash."""
    import dataclasses

    from horovod_tpu.models.transformer import apply_rope

    # property: <rope(q)_i, rope(k)_j> is a function of (i - j) only
    rng = np.random.default_rng(5)
    q = jnp.asarray(rng.normal(size=(1, 8, 1, 16)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(1, 8, 1, 16)), jnp.float32)
    # same q/k content placed at positions (2, 5) vs (0, 3): equal dots
    qc = jnp.broadcast_to(q[:, :1], q.shape)  # constant content
    kc = jnp.broadcast_to(k[:, :1], k.shape)
    rq, rk = apply_rope(qc), apply_rope(kc)
    dots = jnp.einsum("bthd,bshd->bts", rq, rk)[0]
    np.testing.assert_allclose(
        np.asarray(dots[2, 5]), np.asarray(dots[0, 3]), rtol=1e-5
    )
    np.testing.assert_allclose(
        np.asarray(jnp.diag(dots)),
        np.full(8, float(dots[0, 0])), rtol=1e-5,
    )
    # offset shifts positions: rope(x, offset=3)[:, 0] == rope(x)[:, 3]
    x = jnp.asarray(rng.normal(size=(1, 8, 2, 16)), jnp.float32)
    np.testing.assert_allclose(
        np.asarray(apply_rope(x, offset=3)[:, 0]),
        np.asarray(apply_rope(jnp.roll(x, 3, 1))[:, 3]),
        rtol=1e-5, atol=1e-6,
    )

    cfg = dataclasses.replace(
        TransformerConfig.tiny(causal=True),
        rope=True, num_kv_heads=2, sliding_window=6,
        flash_attention=True,
    )
    tokens = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, 16)),
                         jnp.int32)
    model = Transformer(cfg)
    params = model.init(jax.random.PRNGKey(0), tokens, train=False)
    # no learned position table in the tree
    assert not any("Embed_1" in k for k in params["params"])
    import optax

    def loss_fn(p):
        lg = model.apply(p, tokens, train=False)
        return optax.softmax_cross_entropy_with_integer_labels(
            lg.astype(jnp.float32), jnp.roll(tokens, -1, 1)
        ).mean()

    l0, g = jax.value_and_grad(loss_fn)(params)
    p2 = optax.apply_updates(
        params, jax.tree_util.tree_map(lambda x: -0.05 * x, g)
    )
    assert float(loss_fn(p2)) < float(l0)


@pytest.mark.parametrize(
    "rope,num_kv_heads", [(False, None), (True, 2)],
    ids=["learned-pos-mha", "rope-gqa"],
)
def test_transformer_incremental_decode_matches_full(rope, num_kv_heads):
    """The serving engine's model contract (docs/serving.md): the
    cache-threaded forward must reproduce the full-sequence forward —
    prefill logits bit-comparable, and token-by-token decode matching
    the full forward's greedy argmax at every position."""
    from horovod_tpu.models.transformer import init_cache

    cfg = TransformerConfig(
        vocab_size=97, num_layers=2, d_model=32, num_heads=4, d_ff=64,
        max_len=32, causal=True, dtype=jnp.float32, rope=rope,
        num_kv_heads=num_kv_heads,
    )
    model = Transformer(cfg)
    rng = np.random.default_rng(7)
    T = 9
    tokens = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, T)), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), tokens, train=False)
    full = np.asarray(model.apply(params, tokens, train=False))

    # whole-prompt prefill through the cache path: every position's
    # logits equal the full forward (extra cache keys are masked to
    # exact zeros, so the reductions see identical terms)
    cache = init_cache(cfg, 2, 16)
    logits, cache = model.apply(
        params, tokens, train=False,
        cache=cache, cache_index=jnp.zeros((2,), jnp.int32),
    )
    np.testing.assert_array_equal(full, np.asarray(logits))

    # token-by-token decode: greedy argmax bit-identical per position
    cache = init_cache(cfg, 2, 16)
    step_logits = []
    for i in range(T):
        lg, cache = model.apply(
            params, tokens[:, i:i + 1], train=False,
            cache=cache, cache_index=jnp.full((2,), i, jnp.int32),
        )
        step_logits.append(np.asarray(lg)[:, 0])
    stepwise = np.stack(step_logits, axis=1)
    np.testing.assert_array_equal(
        full.argmax(-1), stepwise.argmax(-1)
    )
    np.testing.assert_allclose(full, stepwise, rtol=2e-5, atol=2e-5)
    # staggered slots: the two rows decode at DIFFERENT cache indices
    # (row 0 at position 3, row 1 at position 7) in one call
    idx = jnp.asarray([3, 7], jnp.int32)
    stag_tokens = jnp.stack([tokens[0, 3], tokens[1, 7]])[:, None]
    cache4 = init_cache(cfg, 2, 16)
    _, cache4 = model.apply(
        params, tokens, train=False,
        cache=cache4, cache_index=jnp.zeros((2,), jnp.int32),
    )
    lg, _ = model.apply(
        params, stag_tokens, train=False,
        cache=cache4, cache_index=idx,
    )
    lg = np.asarray(lg)[:, 0]
    np.testing.assert_array_equal(
        full[0, 3].argmax(-1), lg[0].argmax(-1)
    )
    np.testing.assert_array_equal(
        full[1, 7].argmax(-1), lg[1].argmax(-1)
    )


def test_transformer_cache_rejects_bad_compositions():
    from horovod_tpu.models.transformer import init_cache

    cfg = TransformerConfig.tiny()
    model = Transformer(cfg)
    tokens = jnp.ones((1, 4), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), tokens, train=False)
    cache = init_cache(cfg, 1, 8)
    idx = jnp.zeros((1,), jnp.int32)
    with pytest.raises(ValueError, match="mask"):
        model.apply(
            params, tokens, train=False, cache=cache, cache_index=idx,
            mask=jnp.ones((1, 4), bool),
        )
    import dataclasses

    enc = dataclasses.replace(cfg, causal=False)
    enc_model = Transformer(enc)
    enc_params = enc_model.init(
        jax.random.PRNGKey(0), tokens, train=False
    )
    with pytest.raises(ValueError, match="causal"):
        enc_model.apply(
            enc_params, tokens, train=False,
            cache=init_cache(enc, 1, 8), cache_index=idx,
        )
