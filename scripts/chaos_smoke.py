"""Chaos smoke gate (ci.sh): the control plane survives its own medicine.

Runs a short multi-process elastic job under a seeded ``FaultPlan``:

* every worker's FIRST rendezvous-KV request eats an injected
  connection reset (``kv.request@1:reset``) and must absorb it through
  the shared ``RetryPolicy``;
* ONE worker (local rank 0 of the ``127.0.0.1`` "host") SIGKILLs
  itself at training step 3 of epoch 0 (``train.step@3:kill``), so the
  driver must blacklist that host and gang-restart the 8-worker job
  down to 6;
* the restarted gang completes, and rank 0 of the final epoch serves
  ``/metrics`` so this gate asserts — over the live scrape endpoint —
  nonzero ``hvd_retry_*`` counters and ``hvd_faults_injected`` >= 1.

Asserts: driver exit code 0, EXACTLY one gang restart (8 -> 6), the
expected per-epoch result files, and the scraped counters. Exit 0 on
success; any assertion failure is a CI failure.

An **integrity drill** (PR 7) runs first, in its own subprocess: a
guarded training loop on the 8-device CPU mesh eats one injected NaN
step (``train.nan@3:nan`` — the update must be SKIPPED and
``hvd_guard_nonfinite_steps`` counted) and one injected checkpoint
bitflip (``checkpoint.save@2:bitflip`` — ``restore_latest_good`` must
fall back past the digest mismatch), with every counter asserted over
the worker's live ``/metrics`` scrape.

A **serve-failover drill** (PR 19) runs last: a two-worker serving
fleet takes a burst of identical temperature-0 requests through the
Router; one worker is SIGKILLed mid-burst (in-flight requests REPLAYED
on the survivor — zero client-visible errors, every response
bit-identical) and a third worker is then SIGTERMed with a short
``HOROVOD_SERVE_DRAIN_DEADLINE_S`` so its in-flight sequences
live-migrate to the survivor (``hvd_serve_migrations_in`` on the
survivor's live scrape) and still answer the original clients. The
drill runs with the fleet TRACE plane on and asserts its contracts
under chaos: hedge and replay legs surface as tagged SIBLING
``route.attempt`` spans under one route root, and a live-migrated
request assembles (this client's ring + the survivor's live
``/traces`` scrape + the SIGTERMed worker's crash-drained ``.spans``
file) into a single connected trace spanning >= 3 processes.

A **standby-swap drill** (PR 18): the same SIGKILL-a-worker
story, twice — once cold (no cache, no standby) and once with
``HOROVOD_WARM_STANDBY=1`` + a shared ``HOROVOD_EXE_CACHE``. In the
warm pass the kill lands only after the driver's warmer announces
``armed`` over rendezvous KV; the restart swaps the standby host into
the gang (exactly ONE gang restart — the swap-in costs zero additional
resets), every survivor resolves its compile-heavy executable from the
persistent cache (``exe_cache.misses == 0`` — zero new compiles), and
the live-scraped ``hvd_elastic_restart_ms`` beats the cold pass, whose
restarted workers each paid the multi-second XLA recompile.
"""

import itertools
import json
import os
import sys
import tempfile
import threading
import time
import urllib.request

# runnable as `python scripts/chaos_smoke.py` from the repo root
sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
# the failover drill drives scripts/trace_assemble.py as a library
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

WORKER = """\
import json, os, sys, time
sys.path.insert(0, os.getcwd())
os.environ.setdefault("JAX_PLATFORMS", "cpu")
rank = int(os.environ["HOROVOD_RANK"])
epoch = int(os.environ.get("HOROVOD_ELASTIC_EPOCH", "0"))
host = os.environ.get("HOROVOD_HOSTNAME", "")
workdir = os.environ["CHAOS_SMOKE_DIR"]

from horovod_tpu.common import telemetry
from horovod_tpu.common.config import Config
from horovod_tpu.common.metrics import registry
from horovod_tpu.runner.rendezvous import _client_from_cfg
from horovod_tpu.testing import chaos

# exactly ONE victim: per-slot placement makes every process its own
# "host" (local_rank 0), so the 127.0.0.1 workers elect the victim
# through an exclusive lock file instead
victim = False
if epoch == 0 and host == "127.0.0.1":
    try:
        fd = os.open(
            os.path.join(workdir, "victim.lock"),
            os.O_CREAT | os.O_EXCL | os.O_WRONLY,
        )
        os.close(fd)
        victim = True
    except FileExistsError:
        pass
if victim:
    # the victim: same seeded plan PLUS a mid-run SIGKILL at step 3.
    # It holds its fire until every sibling has written its epoch-0
    # result, so the driver's gang-reap after the kill can never race
    # the survivors' dumps (8 concurrent interpreter starts skew by
    # seconds on a loaded CI box).
    chaos.configure("seed=11;kv.request@1:reset;train.step@3:kill")
    world = int(os.environ["HOROVOD_SIZE"])
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        done = [
            n for n in os.listdir(workdir) if n.startswith("result.e0.")
        ]
        if len(done) >= world - 1:
            break
        time.sleep(0.05)
else:
    assert chaos.active() is not None, "fault plan env did not load"

cfg = Config.from_env()
client = _client_from_cfg(cfg)
# rendezvous traffic: hit 1 eats the injected reset; RetryPolicy absorbs
client.put("smoke", str(rank), b"hello")
assert client.get("smoke", str(rank)) == b"hello"

hub = telemetry.hub()
for step in range(5):
    hub.step_begin(step)
    chaos.inject("train.step")  # the victim dies here at step 3
    time.sleep(0.02)            # "training"
    hub.step_end()

out = os.path.join(workdir, f"result.e{epoch}.r{rank}.json")
with open(out + ".tmp", "w") as f:
    json.dump(
        {"epoch": epoch, "rank": rank, "metrics": registry.snapshot()}, f
    )
os.replace(out + ".tmp", out)

if epoch >= 1 and rank == 0:
    # serve the live scrape endpoint until the gate has read it
    server = telemetry.MetricsServer(port=0)
    port = server.start()
    port_file = os.path.join(workdir, "scrape_port")
    with open(port_file + ".tmp", "w") as f:
        f.write(str(port))
    os.replace(port_file + ".tmp", port_file)
    ack = os.path.join(workdir, "scraped.ok")
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline and not os.path.exists(ack):
        time.sleep(0.1)
if epoch == 0:
    time.sleep(120)  # park; the gang restart reaps us
sys.exit(0)
"""


def _prom_value(text: str, name: str) -> float:
    for line in text.splitlines():
        if line.startswith(name + " "):
            return float(line.split()[1])
    raise AssertionError(f"metric {name} not in scrape:\n{text[:600]}")


def _prom_value_or(text: str, name: str, default: float) -> float:
    """A counter that never incremented is ABSENT from the scrape."""
    try:
        return _prom_value(text, name)
    except AssertionError:
        return default


INTEGRITY_WORKER = """\
import json, os, sys
sys.path.insert(0, os.getcwd())
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count=8"
).strip()
workdir = os.environ["CHAOS_SMOKE_DIR"]

import jax, jax.numpy as jnp, optax
from jax.sharding import PartitionSpec as P
import horovod_tpu as hvd
from horovod_tpu.common.compat import shard_map
from horovod_tpu.common.metrics import registry
from horovod_tpu.checkpoint import CheckpointManager
from horovod_tpu.common import telemetry
from horovod_tpu.testing import chaos

# the seeded integrity plan: NaN at training step 3, bitflip on the
# SECOND checkpoint save
chaos.configure("seed=11;train.nan@3:nan;checkpoint.save@2:bitflip")

hvd.init()
world = hvd.size()
mesh = hvd.mesh()
opt = hvd.DistributedOptimizer(
    optax.sgd(0.1), op=hvd.Sum, grad_guard=True, guard_max_skips=0,
    overlap_buckets=2,
)
# non-constant values: a constant array compresses to nothing and
# the bitflip would land in container slack instead of payload
params = {"w": jnp.linspace(1.0, 2.0, 4096, dtype=jnp.float32)}
state = opt.init(params)

@jax.jit
def step(grads, state, params):
    def body(g, s, p):
        g = jax.tree_util.tree_map(lambda x: x[0], g)
        u, s2 = opt.update(g, s, p)
        return jax.tree_util.tree_map(lambda a, b: a + b, p, u), s2
    return shard_map(
        body, mesh=mesh, in_specs=(P(hvd.WORLD_AXIS), P(), P()),
        out_specs=(P(), P()), check_vma=False,
    )(grads, state, params)

ckpt = CheckpointManager(os.path.join(workdir, "ckpt"), async_save=False)
losses = []
for i in range(1, 7):
    g = {"w": jnp.ones((world, 4096), jnp.float32)}
    if chaos.inject("train.nan") == "nan":
        g = {"w": g["w"].at[0, 0].set(jnp.nan)}
    params, state = step(g, state, params)
    jax.block_until_ready(params["w"])
    losses.append(float(params["w"][0]))
    if i in (2, 4):
        # save hit 2 (i == 4) eats the bitflip
        ckpt.save(i, {"params": params, "i": i})
ckpt.wait_until_finished()

# the NaN step was SKIPPED: params advanced 5 times, not 6
assert int(state.guard_skips) == 1, int(state.guard_skips)
assert abs(losses[-1] - (1.0 - 0.1 * 8 * 5)) < 1e-5, losses

# the bitflipped newest checkpoint is bypassed via digest verification
like = {"params": params, "i": 0}
got_step, _ = ckpt.restore_latest_good(like=like)
assert got_step == 2, f"expected fallback to step 2, got {got_step}"
snap = registry.snapshot()
assert snap.get("guard.nonfinite_steps", 0) >= 1, snap
assert snap.get("checkpoint.digest_mismatch", 0) >= 1, snap
assert snap.get("checkpoint.fallback", 0) >= 1, snap

# serve the counters for the gate's live scrape
server = telemetry.MetricsServer(port=0)
port = server.start()
port_file = os.path.join(workdir, "integrity_port")
with open(port_file + ".tmp", "w") as f:
    f.write(str(port))
os.replace(port_file + ".tmp", port_file)
import time
ack = os.path.join(workdir, "integrity.ok")
deadline = time.monotonic() + 30
while time.monotonic() < deadline and not os.path.exists(ack):
    time.sleep(0.1)
sys.exit(0)
"""


def integrity_drill() -> None:
    """One injected NaN step + one injected checkpoint bitflip in a
    guarded training loop; counters asserted over the live scrape."""
    import subprocess

    workdir = tempfile.mkdtemp(prefix="hvd-integrity-smoke-")
    script = os.path.join(workdir, "integrity_worker.py")
    with open(script, "w") as f:
        f.write(INTEGRITY_WORKER)
    env = dict(os.environ)
    env["CHAOS_SMOKE_DIR"] = workdir
    env.pop("HOROVOD_FAULT_PLAN", None)
    proc = subprocess.Popen([sys.executable, script], env=env)
    try:
        port_file = os.path.join(workdir, "integrity_port")
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline and not os.path.exists(port_file):
            if proc.poll() is not None:
                raise AssertionError(
                    f"integrity worker died rc={proc.returncode}"
                )
            time.sleep(0.1)
        assert os.path.exists(port_file), "integrity worker never served"
        with open(port_file) as f:
            port = int(f.read().strip())
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=10
        ) as resp:
            text = resp.read().decode()
        assert _prom_value(text, "hvd_guard_nonfinite_steps") >= 1
        assert _prom_value(text, "hvd_checkpoint_digest_mismatch") >= 1
        assert _prom_value(text, "hvd_checkpoint_fallback") >= 1
        assert _prom_value(text, "hvd_faults_injected") >= 2
        ack = os.path.join(workdir, "integrity.ok")
        with open(ack + ".tmp", "w") as f:
            f.write("ok")
        os.replace(ack + ".tmp", ack)
        proc.wait(timeout=30)
        assert proc.returncode == 0, f"integrity worker rc={proc.returncode}"
    finally:
        if proc.poll() is None:
            proc.kill()
    print(
        "integrity-drill OK: NaN step skipped, bitflipped checkpoint "
        "bypassed via digest, counters live on /metrics"
    )


STANDBY_WORKER = """\
import json, os, signal, sys, time
sys.path.insert(0, os.getcwd())
os.environ.setdefault("JAX_PLATFORMS", "cpu")
rank = int(os.environ["HOROVOD_RANK"])
epoch = int(os.environ.get("HOROVOD_ELASTIC_EPOCH", "0"))
host = os.environ.get("HOROVOD_HOSTNAME", "")
workdir = os.environ["CHAOS_SMOKE_DIR"]

from horovod_tpu.common import telemetry
from horovod_tpu.common.config import Config
from horovod_tpu.common.metrics import registry
from horovod_tpu.elastic.worker import WorkerNotificationManager
from horovod_tpu.runner.rendezvous import _client_from_cfg

import jax
import jax.numpy as jnp

def chain(x):
    for i in range(220):
        x = jnp.tanh(x @ x.T * (1.0 + 0.01 * i) + i) @ (x * 0.5 + 1.0)
        if i % 7 == 0:
            x = jax.nn.softmax(x, axis=-1) + x
    return x

# resolve the gang's one executable through the persistent cache: a
# cold worker pays the multi-second XLA compile, a warm-restarted one
# deserializes the epoch-0 entry in milliseconds — THE delta the
# restart clock below exists to show
t0 = time.time()
lowered = jax.jit(chain).lower(jnp.ones((48, 48), jnp.float32))
if os.environ.get("HOROVOD_EXE_CACHE"):
    from horovod_tpu.common import exe_cache
    exe, hit = exe_cache.get_or_compile(lowered, "smoke.chain")
    # drain the write-behind BEFORE parking: epoch-0 workers are
    # reaped by SIGTERM, which never runs atexit hooks
    assert exe_cache.flush(60), "exe-cache write-behind did not drain"
else:
    exe, hit = lowered.compile(), False
resolve_ms = (time.time() - t0) * 1e3

# the executable is READY: close the restart clock exactly the way a
# real worker's init does (the driver stamped wall time at teardown)
client = _client_from_cfg(Config.from_env())
WorkerNotificationManager.__new__(
    WorkerNotificationManager
)._publish_restart_ms(client, str(epoch))

out = os.path.join(workdir, f"result.e{epoch}.r{rank}.json")
with open(out + ".tmp", "w") as f:
    json.dump({
        "epoch": epoch, "rank": rank, "host": host, "hit": bool(hit),
        "resolve_ms": resolve_ms, "metrics": registry.snapshot(),
    }, f)
os.replace(out + ".tmp", out)

# exactly ONE victim: the 127.0.0.1 workers elect through an exclusive
# lock file (per-slot placement makes every process its own "host")
victim = False
if epoch == 0 and host == "127.0.0.1":
    try:
        fd = os.open(
            os.path.join(workdir, "victim.lock"),
            os.O_CREAT | os.O_EXCL | os.O_WRONLY,
        )
        os.close(fd)
        victim = True
    except FileExistsError:
        pass
if victim:
    # hold fire until every sibling has dumped its epoch-0 result AND
    # the gate has confirmed the standby is armed (kill.go) — the
    # contract under test is a SIGKILL *with one standby armed*
    world = int(os.environ["HOROVOD_SIZE"])
    deadline = time.monotonic() + 180
    while time.monotonic() < deadline:
        done = [
            n for n in os.listdir(workdir) if n.startswith("result.e0.")
        ]
        if len(done) >= world and os.path.exists(
            os.path.join(workdir, "kill.go")
        ):
            os.kill(os.getpid(), signal.SIGKILL)
        time.sleep(0.05)
    sys.exit(3)  # gate timed out; surface as a worker failure

if epoch >= 1 and rank == 0:
    # serve the live scrape endpoint until the gate has read it
    server = telemetry.MetricsServer(port=0)
    port = server.start()
    port_file = os.path.join(workdir, "standby_port")
    with open(port_file + ".tmp", "w") as f:
        f.write(str(port))
    os.replace(port_file + ".tmp", port_file)
    ack = os.path.join(workdir, "standby.ok")
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline and not os.path.exists(ack):
        time.sleep(0.1)
if epoch == 0:
    time.sleep(180)  # park; the gang restart reaps us
sys.exit(0)
"""


def _touch(path: str) -> None:
    with open(path + ".tmp", "w") as f:
        f.write("ok")
    os.replace(path + ".tmp", path)


def standby_swap_drill() -> None:
    """PR 18: SIGKILL a worker with one warm standby armed — the swap-in
    must cost zero additional gang restarts, the survivors must resolve
    their executables with ZERO new compiles, and the live-scraped
    ``elastic.restart_ms`` must beat a cold (no-cache, no-standby)
    baseline of the same drill."""
    import socket

    from horovod_tpu.elastic.discovery import FixedHosts
    from horovod_tpu.elastic.driver import ElasticDriver
    from horovod_tpu.runner.hosts import HostInfo

    cache = tempfile.mkdtemp(prefix="hvd-standby-exe-cache-")
    # three *local* host labels so both the gang and the warmer launch
    # as plain subprocesses; reservation takes the tail of the sorted
    # list, so the standby is never the victim host (letters sort above
    # "127.0.0.1")
    third = socket.gethostname()
    if third in ("localhost", "127.0.0.1", "::1"):
        third = "::1"

    def phase(warm: bool) -> float:
        workdir = tempfile.mkdtemp(prefix="hvd-standby-smoke-")
        script = os.path.join(workdir, "standby_worker.py")
        with open(script, "w") as f:
            f.write(STANDBY_WORKER)
        extra = {
            "CHAOS_SMOKE_DIR": workdir,
            "HOROVOD_RETRY_BACKOFF_MS": "10",
            # the warmer imports jax to preload cached executables; on
            # this CPU smoke box it must not probe for TPU metadata
            "JAX_PLATFORMS": "cpu",
        }
        if warm:
            extra["HOROVOD_EXE_CACHE"] = cache
            os.environ["HOROVOD_WARM_STANDBY"] = "1"
        else:
            os.environ.pop("HOROVOD_WARM_STANDBY", None)
        driver = ElasticDriver(
            FixedHosts([
                HostInfo("127.0.0.1", 2),
                HostInfo("localhost", 2),
                HostInfo(third, 2),
            ]),
            [sys.executable, script],
            min_np=4,  # epoch 1 (two hosts) must not re-reserve
            discovery_interval=0.2,
            output_filename=(
                os.path.join(workdir, "logs")
                if os.environ.get("CHAOS_SMOKE_LOGS")
                else None
            ),
            extra_env=extra,
        )
        result = {}
        try:
            driver.host_manager.refresh()
            t = threading.Thread(
                target=lambda: result.update(rc=driver.run())
            )
            t.start()
            if warm:
                # the kill lands only once the warmer has announced
                # ``armed`` over rendezvous KV (announce → stage → armed)
                armed = None
                deadline = time.monotonic() + 120
                while time.monotonic() < deadline and not armed:
                    armed = next((
                        hn
                        for hn, ann in driver.standby_status().items()
                        if ann.get("state") == "armed"
                    ), None)
                    time.sleep(0.2)
                assert armed, (
                    f"no armed standby before the kill: "
                    f"{driver.standby_status()}"
                )
                assert armed != "127.0.0.1", "standby on the victim host"
            _touch(os.path.join(workdir, "kill.go"))

            # the post-swap rank 0 publishes its ephemeral scrape port
            port_file = os.path.join(workdir, "standby_port")
            deadline = time.monotonic() + 240
            while (
                time.monotonic() < deadline
                and not os.path.exists(port_file)
            ):
                time.sleep(0.1)
            assert os.path.exists(port_file), (
                "post-swap gang never served /metrics"
            )
            with open(port_file) as f:
                port = int(f.read().strip())
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=10
            ) as resp:
                text = resp.read().decode()

            restart_ms = _prom_value(text, "hvd_elastic_restart_ms")
            assert restart_ms > 0, restart_ms
            assert _prom_value(text, "hvd_elastic_restart_warm") == (
                1.0 if warm else 0.0
            )
            if warm:
                # the scraped survivor resolved from disk: zero compiles
                assert _prom_value(text, "hvd_exe_cache_hits") >= 1
                assert _prom_value_or(
                    text, "hvd_exe_cache_misses", 0
                ) == 0

            _touch(os.path.join(workdir, "standby.ok"))
            t.join(timeout=120)
            assert not t.is_alive(), "driver did not converge"
        finally:
            driver.shutdown()
            os.environ.pop("HOROVOD_WARM_STANDBY", None)

        assert result.get("rc") == 0, f"driver exit {result.get('rc')}"
        # the swap-in cost ZERO additional gang restarts
        assert driver._resets == 1, driver._resets
        assert driver.host_manager.is_blacklisted("127.0.0.1")

        def _results(prefix):
            out = []
            for name in os.listdir(workdir):
                if name.startswith(prefix):
                    with open(os.path.join(workdir, name)) as f:
                        out.append(json.load(f))
            return out

        e0, e1 = _results("result.e0."), _results("result.e1.")
        # cold: all 6 slots active in epoch 0; warm: one host held out
        assert len(e0) == (4 if warm else 6), [r["rank"] for r in e0]
        assert len(e1) == 4, [r["rank"] for r in e1]
        if warm:
            assert driver._standby_swapins == 1, driver._standby_swapins
            # the released standby actually serves in the new gang
            assert driver._standby_released & {
                r["host"] for r in e1
            }, (driver._standby_released, [r["host"] for r in e1])
            for r in e1:  # zero new compiles on ANY survivor
                assert r["hit"], r
                assert r["metrics"].get("exe_cache.misses", 0) == 0, r
        else:
            assert all(not r["hit"] for r in e1)
        return restart_ms

    cold_ms = phase(False)
    warm_ms = phase(True)
    assert warm_ms < cold_ms, (
        f"warm swap-in restart ({warm_ms:.0f} ms) did not beat the "
        f"cold baseline ({cold_ms:.0f} ms)"
    )
    print(
        f"standby-swap OK: armed standby swapped in on 1 gang restart, "
        f"0 new compiles on survivors, restart_ms {warm_ms:.0f} warm "
        f"vs {cold_ms:.0f} cold"
    )


SERVE_WORKER = """\
import os, sys
sys.path.insert(0, os.getcwd())
os.environ.setdefault("JAX_PLATFORMS", "cpu")
workdir = os.environ["CHAOS_SMOKE_DIR"]
rank = int(os.environ["HOROVOD_RANK"])

import jax
import jax.numpy as jnp
import horovod_tpu as hvd
from horovod_tpu.models.transformer import Transformer, TransformerConfig

cfg = TransformerConfig(
    vocab_size=61, num_layers=1, d_model=16, num_heads=2, d_ff=32,
    max_len=256, causal=True, dtype=jnp.float32,
)
model = Transformer(cfg)
# every worker seeds the SAME params: a temperature-0 request must
# answer bit-identically wherever a replay or migration lands it
params = model.init(
    jax.random.PRNGKey(0), jnp.ones((1, 4), jnp.int32), train=False
)
handle = hvd.serve(
    model, params, port=0, slots=4, max_len=256, max_new_tokens=200,
    addr="127.0.0.1", handle_sigterm=True, paged=True,
)
port_file = os.path.join(workdir, f"serve_port.r{rank}")
with open(port_file + ".tmp", "w") as f:
    f.write(str(handle.port))
os.replace(port_file + ".tmp", port_file)
handle.wait(timeout=600)  # SIGTERM drains (and migrates) via the hook
sys.exit(0)
"""


def serve_failover_drill() -> None:
    """PR 19: SIGKILL a serving worker mid-burst — the Router replays
    its in-flight requests on the survivor with zero client-visible
    errors and bit-identical temperature-0 output; then SIGTERM a
    worker under a short drain deadline — its in-flight sequences
    live-migrate to the survivor and still answer the original
    clients."""
    import signal
    import subprocess

    import trace_assemble
    from horovod_tpu.analysis import trace_merge
    from horovod_tpu.common import tracing
    from horovod_tpu.common.metrics import registry
    from horovod_tpu.runner.rendezvous import (
        RendezvousClient,
        RendezvousServer,
    )
    from horovod_tpu.runner.secret import make_secret_key
    from horovod_tpu.serving.frontend import Router

    os.environ["HOROVOD_RENDEZVOUS_BACKEND"] = "python"
    key = make_secret_key()
    server = RendezvousServer(secret_key=key)
    rdv_port = server.start()
    workdir = tempfile.mkdtemp(prefix="hvd-serve-failover-")
    script = os.path.join(workdir, "serve_worker.py")
    with open(script, "w") as f:
        f.write(SERVE_WORKER)

    def spawn(rank, extra_env=None):
        env = dict(os.environ)
        env.update({
            "CHAOS_SMOKE_DIR": workdir,
            "JAX_PLATFORMS": "cpu",
            "HOROVOD_RANK": str(rank),
            "HOROVOD_RENDEZVOUS_BACKEND": "python",
            "HOROVOD_GLOO_RENDEZVOUS_ADDR": "127.0.0.1",
            "HOROVOD_GLOO_RENDEZVOUS_PORT": str(rdv_port),
            "HOROVOD_SECRET_KEY": key.hex(),
            # crash-safe span drain: a reaped worker leaves its trace
            # ring beside the flight recorder for the assembly below
            "HOROVOD_FLIGHT_RECORDER": os.path.join(
                workdir, f"flight.r{rank}.jsonl"
            ),
        })
        env.update(extra_env or {})
        return subprocess.Popen(
            [sys.executable, script], env=env, cwd=os.getcwd()
        )

    def wait_port(procs, rank):
        pf = os.path.join(workdir, f"serve_port.r{rank}")
        deadline = time.monotonic() + 180
        while time.monotonic() < deadline and not os.path.exists(pf):
            assert procs[rank].poll() is None, (
                f"serve worker {rank} died rc={procs[rank].returncode}"
            )
            time.sleep(0.1)
        assert os.path.exists(pf), f"worker {rank} never served"
        with open(pf) as f:
            return int(f.read().strip())

    prompt = [7, 11, 13]
    procs = {0: spawn(0), 1: spawn(1)}
    try:
        ports = {r: wait_port(procs, r) for r in (0, 1)}
        client = RendezvousClient("127.0.0.1", rdv_port, secret_key=key)
        router = Router(client)
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline and len(router.snapshot()) < 2:
            time.sleep(0.2)
        assert set(router.snapshot()) == {0, 1}, router.snapshot()

        # ---- hedge leg: one request with an aggressive hedge delay —
        # both arms fire, first writer wins, and the race must be
        # legible as two tagged SIBLING route.attempt spans under one
        # route root in this process's trace ring
        hres = router.route(
            prompt, timeout=240.0, hedge_ms=1.0, request_id="hedge-0"
        )
        assert hres["status"] == "done", hres
        htid = hres.get("trace_id")
        assert htid, f"hedged result carries no trace_id: {hres}"
        # the losing arm closes its leg when its response finally
        # lands — poll until both legs are in the ring
        hlegs = []
        hdeadline = time.monotonic() + 120
        while time.monotonic() < hdeadline:
            hlegs = [
                s for s in tracing.recorder().spans()
                if s["trace_id"] == htid
                and s["name"] == "route.attempt"
            ]
            if len(hlegs) >= 2:
                break
            time.sleep(0.2)
        assert len(hlegs) >= 2, f"hedge fired no backup leg: {hlegs}"
        assert {
            (s.get("tags") or {}).get("hedge") for s in hlegs
        } >= {"primary", "backup"}, hlegs
        assert len({s["parent_id"] for s in hlegs}) == 1, (
            f"hedge arms are not siblings: {hlegs}"
        )
        houtcomes = {
            (s.get("tags") or {}).get("outcome") for s in hlegs
        }
        assert "ok" in houtcomes and "discarded" in houtcomes, hlegs

        # ---- replay leg: SIGKILL worker 0 mid-burst
        results, errors = {}, []

        def one(i):
            try:
                results[i] = router.route(
                    prompt, timeout=240.0, attempts=4,
                    request_id=f"burst-{i}",
                )
            except Exception as e:  # noqa: BLE001 — a failure IS the signal
                errors.append((i, e))

        before = registry.snapshot().get("serve.replays", 0.0)
        threads = [
            threading.Thread(target=one, args=(i,)) for i in range(12)
        ]
        for t in threads:
            t.start()
        time.sleep(0.8)  # mid-burst: first requests still in flight
        os.kill(procs[0].pid, signal.SIGKILL)
        for t in threads:
            t.join(timeout=300)
        assert not errors, f"client-visible failures: {errors[:3]}"
        assert len(results) == 12
        assert all(r["status"] == "done" for r in results.values())
        outs = {tuple(r["tokens"]) for r in results.values()}
        assert len(outs) == 1, (
            f"temp-0 outputs diverged across replay: {len(outs)} variants"
        )
        replays = registry.snapshot().get("serve.replays", 0.0) - before
        assert replays >= 1, "the kill was absorbed without any replay"
        # the replays are visible as tagged sibling spans: the leg that
        # died on the SIGKILLed worker closed outcome="replayed", and a
        # mode="replay" sibling under the same route root won
        ring = tracing.recorder().spans()
        rep_legs = [
            s for s in ring
            if s["name"] == "route.attempt"
            and (s.get("tags") or {}).get("outcome") == "replayed"
        ]
        assert rep_legs, "no route.attempt leg tagged outcome=replayed"
        rep_tids = {s["trace_id"] for s in rep_legs}
        ok_replays = [
            s for s in ring
            if s["name"] == "route.attempt"
            and s["trace_id"] in rep_tids
            and (s.get("tags") or {}).get("mode") == "replay"
            and (s.get("tags") or {}).get("outcome") == "ok"
        ]
        assert ok_replays, (
            "no winning mode=replay sibling beside a replayed leg"
        )
        rep_parent = {s["trace_id"]: s["parent_id"] for s in rep_legs}
        assert any(
            s["parent_id"] == rep_parent[s["trace_id"]]
            for s in ok_replays
        ), "replay legs are not siblings under the same route root"

        # ---- migration leg: SIGTERM worker 2 under a short deadline.
        # A 5ms per-step chaos delay slows decode to ~1s/sequence:
        # without it, CPU decode outruns the 0.25s metrics publish
        # interval and all sequences finish before the SIGTERM gate
        # below can catch them in flight (nothing left to migrate)
        procs[2] = spawn(
            2, {
                "HOROVOD_SERVE_DRAIN_DEADLINE_S": "0.05",
                "HOROVOD_FAULT_PLAN": "serve.worker_kill:p=1:delay:ms=5",
            }
        )
        port2 = wait_port(procs, 2)
        mig_results, mig_errors, mig_traces = {}, [], {}

        def mig_one(i):
            # each migration client mints its own trace root: the
            # traceparent rides to the doomed worker, the migrate
            # frames carry it to the survivor, and the assembly below
            # must stitch all three processes back together
            tctx = tracing.mint()
            span = tracing.root_span(
                "client.generate", tctx, request_id=f"mig-{i}"
            )
            headers = {"Content-Type": "application/json"}
            if tctx is not None:
                headers["traceparent"] = tctx.to_traceparent()
            body = json.dumps(
                {"tokens": prompt, "request_id": f"mig-{i}"}
            ).encode()
            req = urllib.request.Request(
                f"http://127.0.0.1:{port2}/generate", data=body,
                headers=headers,
                method="POST",
            )
            try:
                t_send = time.time()
                with urllib.request.urlopen(req, timeout=300) as resp:
                    mig_results[i] = json.loads(resp.read().decode())
                    tracing.tag_hop(
                        span, t_send, time.time(), resp.headers
                    )
                    mig_traces[i] = resp.headers.get("X-Trace-Id")
            except Exception as e:  # noqa: BLE001 — a failure IS the signal
                mig_errors.append((i, e))
            finally:
                if span is not None:
                    span.end()

        mthreads = [
            threading.Thread(target=mig_one, args=(i,)) for i in range(3)
        ]
        for t in mthreads:
            t.start()
        # SIGTERM only once decode is well under way (>= ~10 tokens per
        # sequence): the drill is about IN-FLIGHT sequences, not queued
        # ones, and the depth makes the history-prefix check meaningful
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port2}/metrics", timeout=10
            ) as resp:
                text = resp.read().decode()
            if _prom_value_or(text, "hvd_serve_tokens_out", 0) >= 30:
                break
            time.sleep(0.1)
        procs[2].send_signal(signal.SIGTERM)
        for t in mthreads:
            t.join(timeout=300)
        assert not mig_errors, f"migration leg failures: {mig_errors[:3]}"
        assert len(mig_results) == 3
        assert all(r["status"] == "done" for r in mig_results.values())
        # migration streams over the default int8 KV wire — lossy, so
        # greedy argmax after the resume point is only approximately
        # stable. The hard guarantees: every client gets its FULL
        # answer, and the generated history carried over the wire is
        # verbatim (>= 8 matching tokens: the >=10/sequence decoded
        # pre-SIGTERM, minus admission stagger) — migrated sequences
        # resume, they are never re-decoded or re-sampled
        ref = list(outs)[0]
        for i, r in sorted(mig_results.items()):
            toks = r["tokens"]
            assert len(toks) == len(ref), (i, len(toks), len(ref))
            shared = sum(
                1 for _ in itertools.takewhile(
                    lambda ab: ab[0] == ab[1], zip(ref, toks)
                )
            )
            assert shared >= 8, (
                f"mig-{i} shares only {shared} leading tokens with the "
                f"uninterrupted reference: carried history was lost"
            )
        # the survivor's LIVE scrape proves where the sequences landed.
        # Engine counters reach /metrics on the batcher's publish
        # interval, so poll rather than one-shot assert
        migrations_in = 0.0
        poll_deadline = time.monotonic() + 60
        while time.monotonic() < poll_deadline:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{ports[1]}/metrics", timeout=10
            ) as resp:
                text = resp.read().decode()
            migrations_in = _prom_value_or(text, "hvd_serve_migrations_in", 0)
            if migrations_in >= 1:
                break
            time.sleep(0.25)
        assert migrations_in >= 1, migrations_in
        procs[2].wait(timeout=60)

        # ---- the migrated request is ONE connected trace spanning
        # >= 3 processes: this client (its own ring), the SIGTERMed
        # worker (crash-drained <flight>.spans file), and the survivor
        # (live /traces scrape — itself an NTP edge)
        w1_spans, w1_edge = trace_assemble.scrape(
            f"http://127.0.0.1:{ports[1]}/traces"
        )
        mig_tids = {
            s["trace_id"] for s in w1_spans if s["name"] == "kv.migrate"
        }
        ours = {t for t in mig_traces.values() if t}
        assert ours, f"no X-Trace-Id echoed: {mig_traces}"
        migrated = mig_tids & ours
        assert migrated, (
            f"no kv.migrate span on the survivor belongs to a drill "
            f"request: {mig_tids} vs {ours}"
        )
        mig_tid = sorted(migrated)[0]
        w2_file = os.path.join(workdir, "flight.r2.jsonl.spans")
        assert os.path.exists(w2_file), (
            "SIGTERMed worker drained no span ring"
        )
        spans = (
            tracing.recorder().spans()
            + w1_spans
            + trace_assemble.load_file(w2_file)
        )
        tspans = trace_merge.filter_trace(spans, mig_tid)
        corrected, offsets = trace_merge.assemble(
            tspans, edges=[w1_edge] if w1_edge else [],
        )
        mprocs = {trace_merge.proc_key(s) for s in tspans}
        assert len(mprocs) >= 3, (
            f"migrated trace spans only {len(mprocs)} process(es): "
            f"{mprocs}"
        )
        assert mprocs <= set(offsets), (
            f"migrated trace not connected on one clock: "
            f"{mprocs - set(offsets)} unreachable"
        )
        mnames = {s["name"] for s in tspans}
        for needle in ("client.generate", "http.generate", "kv.migrate"):
            assert needle in mnames, (needle, sorted(mnames))
        assert all(
            a["ts_corrected"] <= b["ts_corrected"]
            for a, b in zip(corrected, corrected[1:])
        ), "assemble() did not sort by corrected time"
    finally:
        for p in procs.values():
            if p.poll() is None:
                p.kill()
        server.stop()
    print(
        f"serve-failover OK: {int(replays)} replay(s) after SIGKILL with "
        f"12/12 bit-identical answers, {int(migrations_in)} live "
        f"migration(s) after SIGTERM with 3/3 answered, migrated trace "
        f"assembled across {len(mprocs)} processes"
    )


def main() -> int:
    # fleet trace plane ON (full sampling) for the whole gate: the
    # serve-failover drill asserts the migrated request's assembled
    # trace, and the elastic drills record their cycle spans along the
    # way — chaos with tracing on is exactly the combination to guard
    os.environ["HOROVOD_TRACE"] = "1"
    os.environ["HOROVOD_TRACE_SAMPLE"] = "1.0"
    integrity_drill()
    workdir = tempfile.mkdtemp(prefix="hvd-chaos-smoke-")
    script = os.path.join(workdir, "worker.py")
    with open(script, "w") as f:
        f.write(WORKER)

    os.environ.pop("XLA_FLAGS", None)
    os.environ.pop("JAX_PLATFORMS", None)
    os.environ["PALLAS_AXON_POOL_IPS"] = ""
    os.environ["HOROVOD_STRAGGLER_QUARANTINE_POLLS"] = "3"

    from horovod_tpu.elastic.discovery import FixedHosts
    from horovod_tpu.elastic.driver import ElasticDriver
    from horovod_tpu.runner.hosts import HostInfo

    driver = ElasticDriver(
        FixedHosts([HostInfo("127.0.0.1", 2), HostInfo("localhost", 6)]),
        [sys.executable, script],
        min_np=1,
        discovery_interval=0.2,
        # CHAOS_SMOKE_LOGS=1 keeps per-rank worker logs for debugging
        output_filename=(
            os.path.join(workdir, "logs")
            if os.environ.get("CHAOS_SMOKE_LOGS")
            else None
        ),
        extra_env={
            "CHAOS_SMOKE_DIR": workdir,
            # the seeded plan: one KV reset per process, absorbed
            "HOROVOD_FAULT_PLAN": "seed=11;kv.request@1:reset",
            "HOROVOD_RETRY_BACKOFF_MS": "10",
        },
    )
    result = {}
    try:
        driver.host_manager.refresh()
        t = threading.Thread(target=lambda: result.update(rc=driver.run()))
        t.start()

        # the post-restart rank 0 publishes its ephemeral scrape port
        port_file = os.path.join(workdir, "scrape_port")
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline and not os.path.exists(port_file):
            time.sleep(0.1)
        assert os.path.exists(port_file), "post-restart gang never served"
        with open(port_file) as f:
            port = int(f.read().strip())
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=10
        ) as resp:
            text = resp.read().decode()

        # the acceptance counters, read over the LIVE endpoint
        assert _prom_value(text, "hvd_retry_kv_request_attempts") > 0
        assert _prom_value(text, "hvd_retry_kv_request_retries") > 0, (
            "no absorbed retries on the scraped worker"
        )
        assert _prom_value(text, "hvd_faults_injected") >= 1
        assert _prom_value(text, "telemetry_step_ms_count") == 5

        # release the serving worker, then collect the driver
        ack = os.path.join(workdir, "scraped.ok")
        with open(ack + ".tmp", "w") as f:
            f.write("ok")
        os.replace(ack + ".tmp", ack)
        t.join(timeout=90)
        assert not t.is_alive(), "driver did not converge"
    finally:
        driver.shutdown()

    assert result.get("rc") == 0, f"driver exit {result.get('rc')}"
    assert driver._resets == 1, (
        f"expected exactly one gang restart, got {driver._resets}"
    )
    assert driver.host_manager.is_blacklisted("127.0.0.1")

    # epoch 0: the victim died at step 3 -> 7 of 8 results; epoch 1:
    # all 6 surviving slots (the victim's host lost BOTH) completed
    e0 = [n for n in os.listdir(workdir) if n.startswith("result.e0.")]
    e1 = [n for n in os.listdir(workdir) if n.startswith("result.e1.")]
    assert len(e0) == 7, e0
    assert len(e1) == 6, e1
    # every surviving worker absorbed its injected KV reset
    for name in e0 + e1:
        with open(os.path.join(workdir, name)) as f:
            snap = json.load(f)["metrics"]
        assert snap.get("retry.kv.request.retries", 0) > 0, name
        assert snap.get("faults_injected", 0) >= 1, name

    print(
        f"chaos-smoke OK: 1 gang restart (8->6), "
        f"{len(e0) + len(e1)} workers absorbed their KV flake, "
        f"scrape port {port}"
    )

    standby_swap_drill()
    serve_failover_drill()
    return 0


if __name__ == "__main__":
    sys.exit(main())
