"""Skew-corrected fleet trace assembly (stdlib + pure functions).

The trace plane (common/tracing.py) leaves one bounded span ring per
process — live at ``GET /traces`` on the MetricsServer, crash-drained
to ``<flight_recorder>.spans`` JSON-lines. Each process stamps spans
with ITS OWN wall clock, and commodity fleet hosts disagree by
milliseconds — enough to make a 2 ms KV-transfer hop appear to finish
before it started. This module merges the per-process rings into one
coherent timeline:

1. **Edges.** Every traced hop carries four stamps: the client's
   ``t_send``/``t_recv`` and the server's echoed
   ``peer_recv``/``peer_send`` (headers on HTTP hops, ``recv_ts`` /
   ``send_ts`` fields in kv_transfer and ``/traces`` replies). Each
   quadruple is one NTP edge: :func:`ntp_offset` estimates the server
   clock minus the client clock as the half-sum of the two one-way
   deltas, with the half-RTT as the error bound — exact under
   symmetric network delay, and the bound holds regardless (the true
   offset always lies within ±err of the estimate).
2. **Per-process offsets.** :func:`host_offsets` fuses parallel edges
   between the same process pair by inverse-error weighting, then runs
   a lowest-accumulated-error search (Dijkstra) from a reference
   process — offsets compose along paths, so a decode worker that only
   ever talked to the prefill worker still lands on the router's
   timeline.
3. **Assembly.** :func:`assemble` rewrites every span's epoch stamp
   into the reference clock; :func:`to_chrome` renders the result as
   chrome://tracing / Perfetto JSON with one process row per
   ``(host, role)`` and one thread row per pid.

Driven by ``scripts/trace_assemble.py`` (live ``/traces`` scrape or
post-mortem ``.spans`` files); the offset math is unit-tested on
synthetic two-host stamp pairs in tests/test_tracing.py.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Tuple

# err floor (seconds): a zero-RTT edge would get infinite weight and a
# zero Dijkstra cost; 1 µs is far below any real network RTT
_MIN_ERR = 1e-6

ProcKey = Tuple[str, int]  # (host, pid)


def proc_key(rec: dict) -> ProcKey:
    """The process identity a span (or /traces payload) belongs to.
    Local smoke fleets share a hostname, so the pid is part of the
    key; the ``role`` label is display-only."""
    return str(rec.get("host", "?")), int(rec.get("pid", 0))


def parse_peer(peer: str) -> Optional[ProcKey]:
    """``"host:pid"`` (tracing.server_stamps / json_stamps identity)
    → key; None on anything malformed."""
    if not peer or ":" not in peer:
        return None
    host, _, pid = peer.rpartition(":")
    try:
        return host, int(pid)
    except ValueError:
        return None


# ------------------------------------------------------------- NTP math


def ntp_offset(
    t_send: float, peer_recv: float, peer_send: float, t_recv: float
) -> Tuple[float, float]:
    """One NTP edge → ``(offset, err)``.

    ``offset`` estimates (server clock − client clock) as the half-sum
    of the request and response one-way deltas; ``err`` is the half-RTT
    bound: whatever the delay asymmetry, the true offset lies within
    ``offset ± err`` as long as each stamped interval really contains
    its network leg."""
    offset = ((peer_recv - t_send) + (peer_send - t_recv)) / 2.0
    rtt = (t_recv - t_send) - (peer_send - peer_recv)
    return offset, max(rtt, 0.0) / 2.0


def hop_edges(spans: Iterable[dict]) -> List[dict]:
    """Extract every NTP edge a span set carries. A hop span's tags
    hold the four stamps plus the server's ``peer`` identity
    (tracing.tag_hop / tag_hop_fields); the edge direction is client →
    server, offset = server clock − client clock."""
    edges: List[dict] = []
    for rec in spans:
        tags = rec.get("tags") or {}
        peer = parse_peer(str(tags.get("peer", "")))
        if peer is None:
            continue
        try:
            offset, err = ntp_offset(
                float(tags["t_send"]),
                float(tags["peer_recv"]),
                float(tags["peer_send"]),
                float(tags["t_recv"]),
            )
        except (KeyError, TypeError, ValueError):
            continue
        edges.append(
            {"a": proc_key(rec), "b": peer, "offset": offset, "err": err}
        )
    return edges


def host_offsets(
    edges: List[dict], reference: Optional[ProcKey] = None
) -> Dict[ProcKey, float]:
    """Per-process clock offsets RELATIVE to ``reference`` (its own
    offset is 0; subtracting a process's offset moves its stamps onto
    the reference clock).

    Parallel edges between the same pair fuse by inverse-error
    weighting — a tight 0.5 ms-RTT edge dominates a retried 2 s one —
    then Dijkstra by accumulated error bound picks the most trustworthy
    stamp path to each process. Unreachable processes are omitted (the
    caller treats them as offset 0). Default reference: the process on
    the most edges, ties broken lexicographically — in a serve fleet
    that is the router, which also took the client's request."""
    if not edges:
        return {}
    # fuse parallel edges (normalize direction to sorted key order)
    fused: Dict[Tuple[ProcKey, ProcKey], Tuple[float, float]] = {}
    acc: Dict[Tuple[ProcKey, ProcKey], List[Tuple[float, float]]] = {}
    for e in edges:
        a, b, off = e["a"], e["b"], float(e["offset"])
        if a == b:
            continue
        if b < a:
            a, b, off = b, a, -off
        acc.setdefault((a, b), []).append(
            (off, max(float(e["err"]), _MIN_ERR))
        )
    for pair, obs in acc.items():
        wsum = sum(1.0 / err for _, err in obs)
        fused[pair] = (
            sum(off / err for off, err in obs) / wsum,
            1.0 / wsum,
        )
    graph: Dict[ProcKey, List[Tuple[ProcKey, float, float]]] = {}
    for (a, b), (off, err) in fused.items():
        graph.setdefault(a, []).append((b, off, err))
        graph.setdefault(b, []).append((a, -off, err))
    if reference is None:
        reference = min(
            graph, key=lambda k: (-len(graph[k]), k)
        )
    # Dijkstra on accumulated error bound
    import heapq

    best: Dict[ProcKey, Tuple[float, float]] = {reference: (0.0, 0.0)}
    heap: List[Tuple[float, ProcKey, float]] = [(0.0, reference, 0.0)]
    while heap:
        cost, node, offset = heapq.heappop(heap)
        if best.get(node, (None, float("inf")))[1] < cost:
            continue
        for nxt, off, err in graph.get(node, ()):
            ncost = cost + err
            if nxt not in best or ncost < best[nxt][1]:
                best[nxt] = (offset + off, ncost)
                heapq.heappush(heap, (ncost, nxt, offset + off))
    return {k: v[0] for k, v in best.items()}


# -------------------------------------------------------------- assembly


def assemble(
    spans: List[dict],
    edges: Optional[List[dict]] = None,
    reference: Optional[ProcKey] = None,
) -> Tuple[List[dict], Dict[ProcKey, float]]:
    """Skew-correct a merged span set onto one clock.

    Returns ``(corrected, offsets)``: copies of the spans sorted by
    corrected start, each with a ``ts_corrected`` epoch stamp (the raw
    ``ts`` minus its process's offset; unreachable processes pass
    through uncorrected). Extra ``edges`` (e.g. the assembler's own
    scrape hops) augment what the spans themselves carry."""
    all_edges = hop_edges(spans) + list(edges or ())
    offsets = host_offsets(all_edges, reference=reference)
    corrected = []
    for rec in spans:
        out = dict(rec)
        out["ts_corrected"] = float(rec.get("ts", 0.0)) - offsets.get(
            proc_key(rec), 0.0
        )
        corrected.append(out)
    corrected.sort(key=lambda r: r["ts_corrected"])
    return corrected, offsets


def traces_in(spans: Iterable[dict]) -> Dict[str, int]:
    """{trace_id: span count} — the assembler CLI's listing."""
    counts: Dict[str, int] = {}
    for rec in spans:
        tid = rec.get("trace_id")
        if tid:
            counts[tid] = counts.get(tid, 0) + 1
    return counts


def filter_trace(spans: Iterable[dict], trace_id: str) -> List[dict]:
    return [r for r in spans if r.get("trace_id") == trace_id]


def to_chrome(
    corrected: List[dict], offsets: Optional[Dict[ProcKey, float]] = None
) -> dict:
    """Corrected spans → chrome://tracing / Perfetto JSON.

    One process row per ``(host, role)`` (the fleet view the ISSUE
    asks for: router / prefill / decode lanes per host), one thread
    row per pid inside it, ``ph="X"`` complete events in µs relative
    to the earliest corrected span. Tags ride ``args`` verbatim, so
    every event stays greppable by trace_id / request_id / outcome."""
    events: List[dict] = []
    if not corrected:
        return {"traceEvents": events, "displayTimeUnit": "ms"}
    t0 = min(r["ts_corrected"] for r in corrected)
    rows: Dict[Tuple[str, str], int] = {}
    tids: Dict[Tuple[int, int], int] = {}
    for rec in corrected:
        host, pid = proc_key(rec)
        role = str(rec.get("role", "") or "worker")
        row = (host, role)
        if row not in rows:
            cpid = rows[row] = len(rows) + 1
            events.append(
                {
                    "ph": "M", "name": "process_name", "pid": cpid,
                    "tid": 0,
                    "args": {"name": f"{host} [{role}]"},
                }
            )
        cpid = rows[row]
        if (cpid, pid) not in tids:
            ctid = tids[(cpid, pid)] = (
                sum(1 for k in tids if k[0] == cpid) + 1
            )
            events.append(
                {
                    "ph": "M", "name": "thread_name", "pid": cpid,
                    "tid": ctid, "args": {"name": f"pid {pid}"},
                }
            )
        ctid = tids[(cpid, pid)]
        args = dict(rec.get("tags") or {})
        args.update(
            trace_id=rec.get("trace_id", ""),
            span_id=rec.get("span_id", ""),
            parent_id=rec.get("parent_id") or "",
        )
        events.append(
            {
                "ph": "X",
                "name": str(rec.get("name", "span")),
                "pid": cpid,
                "tid": ctid,
                "ts": round((rec["ts_corrected"] - t0) * 1e6, 1),
                "dur": round(float(rec.get("dur_ms", 0.0)) * 1e3, 1),
                "args": args,
            }
        )
    out = {"traceEvents": events, "displayTimeUnit": "ms"}
    if offsets:
        out["otherData"] = {
            "clock_offsets_s": {
                f"{h}:{p}": round(o, 6) for (h, p), o in offsets.items()
            }
        }
    return out
