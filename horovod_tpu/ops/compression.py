"""Gradient wire compression.

API parity with the reference's compression module
(ref: horovod/torch/compression.py + horovod/tensorflow/compression.py [V],
SURVEY.md §2.4): ``Compression.none`` and ``Compression.fp16``, each a
(compress, decompress) pair applied around the allreduce.

On TPU the natural wire format is bfloat16 (same exponent range as fp32 —
no loss-scaling dance, and the MXU consumes it natively), so ``bf16`` is
added alongside the reference's fp16. XLA fuses the casts into the
collective's producer/consumer, so compression costs no extra HBM pass.
"""

from __future__ import annotations

import jax.numpy as jnp


class Compressor:
    """A (compress, decompress) pair. ``compress`` returns (tensor, ctx).

    ``wire_format`` names the fused-wire format this compressor maps to
    when handed to the EAGER path (``hvd.allreduce(...,
    compression=)``): instead of compressing tensor-by-tensor on the
    host, the fusion manager moves the whole fused buffer in that
    format inside the compiled executable (ops/fusion.py) — quantize
    once over the batch, one dispatch. ``None`` means the identity
    (fp32/payload-width) wire."""

    wire_format = None  # 'bf16' | 'int8' | 'int8_hier' | None

    @staticmethod
    def compress(tensor):
        raise NotImplementedError

    @staticmethod
    def decompress(tensor, ctx):
        raise NotImplementedError


class NoneCompressor(Compressor):
    # Explicitly "fp32": passing Compression.none must OPT OUT of a
    # globally configured quantized wire (HOROVOD_FUSION_WIRE=int8) on
    # the eager path — an exactness-sensitive reduction stays exact.
    # Leaving wire_format=None would be indistinguishable from not
    # passing compression at all (which defers to the manager knob).
    wire_format = "fp32"

    @staticmethod
    def compress(tensor):
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor


class FP16Compressor(Compressor):
    """Cast floating tensors to fp16 on the wire, restore original dtype
    after (ref: FP16Compressor [V]).

    On the EAGER fused path this maps to the ``bf16`` wire: the fused
    buffer has no fp16 format (bfloat16 is the TPU-native 2-byte wire —
    same width, fp32's exponent range, no loss-scaling dance), and
    silently moving full-width bytes for a caller who asked for
    half-width compression would be worse than the substitution."""

    wire_format = "bf16"

    @staticmethod
    def compress(tensor):
        ctx = tensor.dtype
        if jnp.issubdtype(tensor.dtype, jnp.floating):
            tensor = tensor.astype(jnp.float16)
        return tensor, ctx

    @staticmethod
    def decompress(tensor, ctx):
        return tensor.astype(ctx) if ctx != tensor.dtype else tensor


class BF16Compressor(Compressor):
    """TPU-native wire compression: bfloat16 keeps fp32's exponent range."""

    wire_format = "bf16"

    @staticmethod
    def compress(tensor):
        ctx = tensor.dtype
        if jnp.issubdtype(tensor.dtype, jnp.floating):
            tensor = tensor.astype(jnp.bfloat16)
        return tensor, ctx

    @staticmethod
    def decompress(tensor, ctx):
        return tensor.astype(ctx) if ctx != tensor.dtype else tensor


class Int8Compressor(Compressor):
    """4x wire compression: int8 values + one float32 scale, stochastic
    rounding (unbiased) via the Pallas quantizer (ops/pallas_kernels.py).

    Beyond reference parity (the reference stops at fp16 [V]). Two
    supported uses: (a) ``DistributedOptimizer(compression=
    Compression.int8)`` — the optimizer detects ``quantized_wire`` and
    routes gradients through ``traced.quantized_allreduce`` (raw int8
    must never be summed across ranks: it wraps, and each rank's scale
    differs); (b) manual compress/decompress around allgather/broadcast
    payloads, where no cross-rank arithmetic touches the wire values.
    Pass a fresh ``seed`` per call (e.g. the step counter) to keep the
    rounding unbiased over time rather than merely per-call.
    """

    # Signals _allreduce_grads to use the quantized collective instead
    # of compress -> psum -> decompress.
    quantized_wire = True
    wire_format = "int8"

    @staticmethod
    def compress(tensor, seed=0):
        from . import pallas_kernels

        ctx = tensor.dtype
        if jnp.issubdtype(tensor.dtype, jnp.floating):
            values, scale = pallas_kernels.int8_quantize(tensor, seed=seed)
            return values, (ctx, scale)
        return tensor, (ctx, None)

    @staticmethod
    def decompress(tensor, ctx):
        from . import pallas_kernels

        dtype, scale = ctx
        if scale is None:
            return tensor
        return pallas_kernels.int8_dequantize(tensor, scale, out_dtype=dtype)


class Int8BlockCompressor(Int8Compressor):
    """Block-scaled int8: one float32 scale per ``block_size`` elements
    instead of one per tensor, so mixed-magnitude regions (a fused
    buffer, a tensor with outlier rows) never share a dynamic range —
    the wire format the fused quantized path (ops/fusion.py) uses
    internally, exposed for manual compress/decompress use.

    ``block_size`` is also the granularity contract the BUCKETED
    exchange honors (ops/overlap.py): a bucket buffer concatenating
    several gradients is quantized with these block-wise scales, so
    bucketing never merges two tensors' dynamic ranges — the per-bucket
    edition of the fused wire's pad/outlier isolation."""

    block_size = 512

    @classmethod
    def with_block_size(cls, block_size: int) -> type:
        """A variant of this compressor with a custom scale granularity
        — e.g. a finer block for an outlier-heavy bucket, a coarser one
        to shave scale overhead on a smooth one. The returned class is
        a full Compressor (same quantized_wire routing), so it slots
        into ``DistributedOptimizer(compression=...)`` / the bucketed
        exchange / the eager fused path unchanged."""
        block_size = int(block_size)
        if block_size < 1:
            raise ValueError(
                f"block_size must be >= 1, got {block_size}"
            )
        return type(
            f"{cls.__name__}_b{block_size}",
            (cls,),
            {"block_size": block_size},
        )

    @classmethod
    def compress(cls, tensor, seed=0):
        from . import pallas_kernels

        ctx = tensor.dtype
        if jnp.issubdtype(tensor.dtype, jnp.floating):
            values, scales = pallas_kernels.int8_block_quantize(
                tensor, block_size=cls.block_size, seed=seed
            )
            return values, (ctx, scales)
        return tensor, (ctx, None)

    @classmethod
    def decompress(cls, tensor, ctx):
        from . import pallas_kernels

        dtype, scales = ctx
        if scales is None:
            return tensor
        return pallas_kernels.int8_block_dequantize(
            tensor, scales, block_size=cls.block_size, out_dtype=dtype
        )


class HierarchicalInt8Compressor(Int8BlockCompressor):
    """Hierarchical wire placement (EQuARX's insight, PAPERS.md): bf16
    on the intra-slice hops where ICI is fast, block-scaled int8 only
    on the cross-slice hop where DCN bytes are scarce. On the eager
    fused path (``hvd.allreduce(..., compression=
    Compression.hier_int8)``) AND on the traced/optimizer path
    (``DistributedOptimizer(compression=...)``, the bucketed exchange)
    this rides the real two-level recipe —
    ``traced.hierarchical_allreduce_groups``: intra RS -> int8 inter
    collective on the 1/L shard -> intra AG — whenever a slice split
    is resolvable (``common/topology.py hierarchy_stages``, an
    explicit request: HOROVOD_INTRA_SIZE works even single-host). On
    a genuinely single-slice topology the hierarchy degenerates and
    the flat block-scaled int8 wire is used. For explicit two-axis
    placement use ``traced.hierarchical_quantized_allreduce`` over a
    ``hierarchical_mesh()``."""

    wire_format = "int8_hier"


class Compression:
    """Namespace mirroring hvd.Compression [V] (+ TPU-native additions)."""

    none = NoneCompressor
    fp16 = FP16Compressor
    bf16 = BF16Compressor
    int8 = Int8Compressor
    int8_block = Int8BlockCompressor
    hier_int8 = HierarchicalInt8Compressor
