"""TPU slice topology discovery.

Replaces the reference's SSH-based NIC/interface probing (ref:
horovod/runner/driver/driver_service.py [V] — SURVEY.md §2.5): on TPU
the launcher doesn't need to elect network interfaces (ICI is the data
plane and fixed); it needs the list of worker hosts in the slice and the
chip count per host. Those come from TPU-VM environment metadata, with a
local fallback so the same code path works on a dev box.

Recognized sources, in order:
1. ``HOROVOD_TPU_HOSTS`` — explicit override, same syntax as ``-H``.
2. ``TPU_WORKER_HOSTNAMES`` + ``TPU_WORKER_ID`` — set on TPU VMs by the
   infrastructure (comma-separated host list).
3. The local JAX runtime (``jax.local_device_count()``) — single-host.
"""

from __future__ import annotations

import os
from typing import List, Optional

from .hosts import HostInfo, parse_hosts


def chips_per_host(default: int = 4, env: Optional[dict] = None) -> int:
    """Chips driven by each worker. TPU_CHIPS_PER_HOST_BOUNDS is
    "x,y,z" (product = chip count); fall back to asking JAX."""
    env = os.environ if env is None else env
    bounds = env.get("TPU_CHIPS_PER_HOST_BOUNDS")
    if bounds:
        n = 1
        for part in bounds.split(","):
            n *= int(part)
        return n
    # An explicit CPU request (simulation/tests) must never touch — or
    # wait on — a real accelerator, and answering it needs no jax at
    # all: the CPU "chip count" is the forced host-device count.
    if env.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
        import re

        match = re.search(
            r"xla_force_host_platform_device_count=(\d+)",
            env.get("XLA_FLAGS", ""),
        )
        return int(match.group(1)) if match else 1
    try:
        import jax

        return jax.local_device_count()
    except Exception:  # noqa: BLE001 — discovery must not hard-fail
        return default


def discover_hosts(env: Optional[dict] = None) -> List[HostInfo]:
    env = os.environ if env is None else env
    override = env.get("HOROVOD_TPU_HOSTS")
    if override:
        return parse_hosts(override)
    hostnames = env.get("TPU_WORKER_HOSTNAMES")
    if hostnames:
        per_host = chips_per_host(env=env)
        return [
            HostInfo(h.strip(), per_host)
            for h in hostnames.split(",")
            if h.strip()
        ]
    return [HostInfo("localhost", chips_per_host(default=1))]
