"""Traced-mode collective math: closed-form expectations across the mesh.

Reference model: test/parallel/test_torch.py / test_tensorflow.py — every op
x dtype x avg/sum x prescale with rank-dependent inputs and closed-form
expected values [V] (SURVEY.md §4.1). Here the per-rank program is the
shard_map body and ranks are chips.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd_mod
from horovod_tpu.ops import traced


def run_spmd(hvd, fn, *rank_inputs, out_specs=P(hvd_mod.WORLD_AXIS)):
    """Run fn as an 8-rank SPMD program.

    rank_inputs are rank-major [8, ...]; fn sees each rank's bare tensor
    (leading rank axis stripped), exactly like per-process code in the
    reference's test_torch.py, and its output gets the rank axis back.
    """
    mesh = hvd.mesh()

    def per_shard(*blocks):
        outs = fn(*(b[0] for b in blocks))
        if isinstance(outs, tuple):
            return tuple(o[None] for o in outs)
        return outs[None]

    mapped = jax.jit(
        jax.shard_map(
            per_shard,
            mesh=mesh,
            in_specs=P(hvd_mod.WORLD_AXIS),
            out_specs=out_specs,
            check_vma=False,
        )
    )
    return mapped(*rank_inputs)


def rank_major(fn, dtype=np.float32):
    return np.stack([np.asarray(fn(r), dtype=dtype) for r in range(8)])


@pytest.mark.parametrize("dtype", [np.float32, np.int32, jnp.bfloat16])
def test_allreduce_sum(hvd, dtype):
    x = rank_major(lambda r: np.full((4, 3), r + 1), dtype=np.float32).astype(
        dtype
    )
    out = run_spmd(hvd, lambda t: traced.allreduce(t, op=hvd_mod.Sum), x)
    expected = np.full((4, 3), sum(range(1, 9)), dtype=np.float32)
    for r in range(8):
        np.testing.assert_allclose(
            np.asarray(out[r], dtype=np.float32), expected
        )


def test_allreduce_average(hvd):
    x = rank_major(lambda r: np.full((5,), float(r)))
    out = run_spmd(hvd, lambda t: traced.allreduce(t, op=hvd_mod.Average), x)
    np.testing.assert_allclose(np.asarray(out[3]), np.full((5,), 3.5))


def test_allreduce_average_kwarg_conflict(hvd):
    with pytest.raises(ValueError):
        traced.allreduce(jnp.ones(3), average=True, op=hvd_mod.Sum)


def test_allreduce_prescale_postscale(hvd):
    x = rank_major(lambda r: np.ones(7))
    out = run_spmd(
        hvd,
        lambda t: traced.allreduce(
            t, op=hvd_mod.Sum, prescale_factor=0.5, postscale_factor=10.0
        ),
        x,
    )
    # sum(0.5 * 1 over 8 ranks) * 10 = 40
    np.testing.assert_allclose(np.asarray(out[0]), np.full(7, 40.0))


def test_allreduce_min_max(hvd):
    x = rank_major(lambda r: np.array([r, -r, r * 2.0]))
    out_min = run_spmd(hvd, lambda t: traced.allreduce(t, op=hvd_mod.Min), x)
    out_max = run_spmd(hvd, lambda t: traced.allreduce(t, op=hvd_mod.Max), x)
    np.testing.assert_allclose(np.asarray(out_min[4]), [0, -7, 0])
    np.testing.assert_allclose(np.asarray(out_max[4]), [7, 0, 14])


def test_allreduce_product(hvd):
    x = rank_major(lambda r: np.full((2,), 2.0))
    out = run_spmd(hvd, lambda t: traced.allreduce(t, op=hvd_mod.Product), x)
    np.testing.assert_allclose(np.asarray(out[1]), np.full(2, 2.0**8))


def test_allreduce_process_set(hvd):
    ps = hvd.add_process_set([0, 1, 2, 3])
    x = rank_major(lambda r: np.full((3,), float(r)))
    out = run_spmd(
        hvd,
        lambda t: traced.allreduce(t, op=hvd_mod.Sum, process_set=ps),
        x,
    )
    # members reduce among {0,1,2,3} → 0+1+2+3 = 6; non-members form
    # singleton groups and reduce with themselves only.
    np.testing.assert_allclose(np.asarray(out[2]), np.full(3, 6.0))
    np.testing.assert_allclose(np.asarray(out[6]), np.full(3, 6.0))
    np.testing.assert_allclose(np.asarray(out[5]), np.full(3, 5.0))


def test_grouped_allreduce(hvd):
    xs = [
        rank_major(lambda r: np.full((3,), float(r))),
        rank_major(lambda r: np.full((2, 2), 2.0 * r)),
    ]
    outs = run_spmd(
        hvd,
        lambda a, b: tuple(
            traced.grouped_allreduce([a, b], op=hvd_mod.Average)
        ),
        *xs,
        out_specs=(P(hvd_mod.WORLD_AXIS), P(hvd_mod.WORLD_AXIS)),
    )
    np.testing.assert_allclose(np.asarray(outs[0][0]), np.full(3, 3.5))
    np.testing.assert_allclose(np.asarray(outs[1][0]), np.full((2, 2), 7.0))


def test_allgather(hvd):
    x = rank_major(lambda r: np.full((2, 3), float(r)))
    out = run_spmd(hvd, lambda t: traced.allgather(t), x)
    # each rank's output: concat along dim0 → [16, 3]
    assert out.shape == (8, 16, 3)
    expected = np.concatenate([np.full((2, 3), float(r)) for r in range(8)])
    np.testing.assert_allclose(np.asarray(out[5]), expected)


def test_broadcast(hvd):
    x = rank_major(lambda r: np.full((4,), float(r + 1)))
    out = run_spmd(hvd, lambda t: traced.broadcast(t, root_rank=3), x)
    for r in range(8):
        np.testing.assert_allclose(np.asarray(out[r]), np.full(4, 4.0))


def test_alltoall(hvd):
    # rank r sends block j = [r*10 + j]; rank j receives [0*10+j, 1*10+j, ...]
    x = rank_major(lambda r: np.array([r * 10.0 + j for j in range(8)]))
    out = run_spmd(hvd, lambda t: traced.alltoall(t), x)
    np.testing.assert_allclose(
        np.asarray(out[2]), np.array([s * 10.0 + 2 for s in range(8)])
    )


def test_reducescatter(hvd):
    x = rank_major(lambda r: np.arange(16.0) + r)
    out = run_spmd(hvd, lambda t: traced.reducescatter(t, op=hvd_mod.Sum), x)
    # reduced = 8*arange(16) + sum(0..7); rank r gets shard [2r, 2r+2)
    reduced = 8 * np.arange(16.0) + 28.0
    assert out.shape == (8, 2)
    np.testing.assert_allclose(np.asarray(out[3]), reduced[6:8])


def test_reducescatter_average(hvd):
    x = rank_major(lambda r: np.arange(8.0))
    out = run_spmd(hvd, lambda t: traced.reducescatter(t, op=hvd_mod.Average), x)
    np.testing.assert_allclose(np.asarray(out[0]), [0.0])


def test_rank_size_in_trace(hvd):
    out = run_spmd(
        hvd,
        lambda t: t * 0
        + traced.rank()
        + 100 * traced.size(),
        rank_major(lambda r: np.zeros(1)),
    )
    np.testing.assert_allclose(np.asarray(out[:, 0]), 800 + np.arange(8.0))


# ---------------------------------------------------------------- process sets
# The traced set family is built from masked full-axis collectives and
# static ppermute routes — no axis_index_groups (XLA's TPU lowering
# rejects unequal replica groups; see ops/traced.py module docstring).


def test_allgather_process_set(hvd):
    ps = hvd.add_process_set([1, 3, 6])
    x = rank_major(lambda r: np.full((2, 3), float(r)))
    out = run_spmd(
        hvd, lambda t: traced.allgather(t, process_set=ps), x
    )
    expected = np.concatenate(
        [np.full((2, 3), float(r)) for r in (1, 3, 6)]
    )
    assert out.shape == (8, 6, 3)
    for r in range(8):  # members and outsiders both hold the set's gather
        np.testing.assert_allclose(np.asarray(out[r]), expected)


def test_alltoall_process_set(hvd):
    ps = hvd.add_process_set([0, 2, 5, 7])
    # member at set-position p sends block j to set-position j
    x = rank_major(lambda r: np.array([r * 10.0 + j for j in range(8)]))
    out = run_spmd(hvd, lambda t: traced.alltoall(t, process_set=ps), x)
    # set order (0,2,5,7): rank 5 is position 2; its block j=2 comes from
    # each member in set order with d=2: rank s's rows [4:6]
    expected = np.concatenate(
        [np.array([s * 10.0 + 4, s * 10.0 + 5]) for s in (0, 2, 5, 7)]
    )
    np.testing.assert_allclose(np.asarray(out[5]), expected)
    # non-member output is the untouched input
    np.testing.assert_allclose(
        np.asarray(out[3]), np.array([30.0 + j for j in range(8)])
    )


def test_reducescatter_process_set(hvd):
    ps = hvd.add_process_set([1, 2, 4, 6])
    x = rank_major(lambda r: np.arange(8.0) + r)
    out = run_spmd(
        hvd, lambda t: traced.reducescatter(t, op=hvd_mod.Sum, process_set=ps), x
    )
    # reduced over members = 4*arange(8) + (1+2+4+6); member at set
    # position p gets shard [2p, 2p+2)
    reduced = 4 * np.arange(8.0) + 13.0
    assert out.shape == (8, 2)
    np.testing.assert_allclose(np.asarray(out[2]), reduced[2:4])  # pos 1
    np.testing.assert_allclose(np.asarray(out[6]), reduced[6:8])  # pos 3


def test_adasum_process_set(hvd):
    from horovod_tpu.ops.adasum import adasum_tree_host

    ps = hvd.add_process_set([0, 3, 5])
    rng = np.random.default_rng(7)
    vals = rng.normal(size=(8, 6)).astype(np.float32)
    out = run_spmd(
        hvd,
        lambda t: traced.allreduce(t, op=hvd_mod.Adasum, process_set=ps),
        vals,
    )
    expected = adasum_tree_host(np.stack([vals[0], vals[3], vals[5]]))
    for r in (0, 3, 5):
        np.testing.assert_allclose(
            np.asarray(out[r]), expected, rtol=1e-5, atol=1e-6
        )
    np.testing.assert_allclose(np.asarray(out[4]), vals[4])


def test_allreduce_process_set_min_max_product(hvd):
    ps = hvd.add_process_set([2, 4, 7])
    x = rank_major(lambda r: np.full((3,), float(r + 1)))
    mn = run_spmd(
        hvd, lambda t: traced.allreduce(t, op=hvd_mod.Min, process_set=ps), x
    )
    mx = run_spmd(
        hvd, lambda t: traced.allreduce(t, op=hvd_mod.Max, process_set=ps), x
    )
    pr = run_spmd(
        hvd,
        lambda t: traced.allreduce(t, op=hvd_mod.Product, process_set=ps),
        x,
    )
    np.testing.assert_allclose(np.asarray(mn[2]), np.full(3, 3.0))
    np.testing.assert_allclose(np.asarray(mx[4]), np.full(3, 8.0))
    np.testing.assert_allclose(np.asarray(pr[7]), np.full(3, 3.0 * 5.0 * 8.0))
    # outsiders keep their input for every op
    np.testing.assert_allclose(np.asarray(mn[0]), np.full(3, 1.0))
    np.testing.assert_allclose(np.asarray(pr[5]), np.full(3, 6.0))


def test_grouped_allreduce_process_set(hvd):
    ps = hvd.add_process_set([0, 1, 4])
    xs = [
        rank_major(lambda r: np.full((3,), float(r))),
        rank_major(lambda r: np.full((2,), 10.0 * r)),
    ]
    outs = run_spmd(
        hvd,
        lambda a, b: tuple(
            traced.grouped_allreduce([a, b], op=hvd_mod.Average, process_set=ps)
        ),
        *xs,
        out_specs=(P(hvd_mod.WORLD_AXIS), P(hvd_mod.WORLD_AXIS)),
    )
    np.testing.assert_allclose(np.asarray(outs[0][1]), np.full(3, 5.0 / 3))
    np.testing.assert_allclose(np.asarray(outs[1][4]), np.full(2, 50.0 / 3))
    # outsider keeps both inputs
    np.testing.assert_allclose(np.asarray(outs[0][6]), np.full(3, 6.0))
    np.testing.assert_allclose(np.asarray(outs[1][6]), np.full(2, 60.0))


def test_broadcast_process_set(hvd):
    ps = hvd.add_process_set([1, 2, 6])
    x = rank_major(lambda r: np.full((4,), float(r)))
    out = run_spmd(
        hvd, lambda t: traced.broadcast(t, root_rank=2, process_set=ps), x
    )
    for r in (1, 2, 6):
        np.testing.assert_allclose(np.asarray(out[r]), np.full(4, 2.0))
    np.testing.assert_allclose(np.asarray(out[0]), np.full(4, 0.0))
    np.testing.assert_allclose(np.asarray(out[7]), np.full(4, 7.0))
