"""Error-feedback quantized compression (EF-SGD, beyond parity; the
reference's wire compression stops at fp16 [V]).

The load-bearing property: with EF the CUMULATIVE transmitted gradient
stays within a constant number of int8 quanta of the true cumulative
sum for any number of steps; without it the per-step quantization
errors random-walk. Plus plumbing tests: state threading, residual
round-trip, and the misuse guard."""

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd_pkg
from horovod_tpu.ops.compression import Compression


def test_error_feedback_requires_quantized_wire(hvd):
    with pytest.raises(ValueError, match="quantized-wire"):
        hvd_pkg.DistributedOptimizer(
            optax.sgd(1e-2), error_feedback=True
        )


def test_residual_reconstructs_wire_value(hvd):
    """quantized_allreduce(return_residual=True): local − residual must
    equal dequant(quant(local)) exactly (the stage-1 wire value)."""
    from horovod_tpu.ops import traced
    from horovod_tpu.ops.reduction_ops import Sum

    mesh = hvd_pkg.mesh()
    rng = np.random.default_rng(0)
    x = jnp.asarray(
        rng.normal(size=(8, 64)).astype(np.float32)
    )

    @partial(
        jax.shard_map, mesh=mesh, in_specs=P(hvd_pkg.WORLD_AXIS),
        out_specs=(P(hvd_pkg.WORLD_AXIS), P(hvd_pkg.WORLD_AXIS)),
        check_vma=False,
    )
    def body(t):
        out, res = traced.quantized_allreduce(
            t[0], op=Sum, seed=3, return_residual=True
        )
        return out[None], res[None]

    out, res = jax.jit(body)(x)
    res = np.asarray(res)
    # residual = stage-1 error (<= local quantum) everywhere, plus the
    # owned chunk's stage-2 error (<= reduced-shard quantum)
    total = np.asarray(x).sum(0)
    quantum2 = np.abs(total).max() / 127.0
    for r in range(8):
        quantum1 = np.abs(np.asarray(x[r])).max() / 127.0
        assert np.abs(res[r]).max() <= (quantum1 + quantum2) * 1.01


def _cumulative_error(mesh, ef: bool, steps: int, g_true):
    """Run `steps` quantized allreduce rounds of the SAME gradient and
    return |cumulative transmitted − cumulative true| in quanta."""
    opt = hvd_pkg.DistributedOptimizer(
        optax.sgd(1.0),  # update == -reduced gradient: easy bookkeeping
        compression=Compression.int8,
        op=hvd_pkg.Average,
        error_feedback=ef,
    )
    params = {"w": jnp.zeros_like(g_true)}
    state = opt.init(params)

    @partial(
        jax.shard_map, mesh=mesh,
        in_specs=(P(), P(), P(hvd_pkg.WORLD_AXIS)),
        out_specs=(P(), P()),
        check_vma=False,
    )
    def step(p, st, g):
        upd, st = opt.update({"w": g[0]}, st, p)
        return optax.apply_updates(p, upd), st

    js = jax.jit(step)
    g_stack = jnp.broadcast_to(g_true, (8,) + g_true.shape)
    for _ in range(steps):
        params, state = js(params, state, g_stack)
    # with lr=1 and identical grads per rank: -w == cumulative transmitted
    transmitted = -np.asarray(params["w"], np.float64)
    err = np.abs(transmitted - steps * np.asarray(g_true, np.float64))
    quantum = float(np.abs(np.asarray(g_true)).max()) / 127.0
    return float(err.max()) / quantum


def test_cumulative_error_bounded_with_ef(hvd):
    mesh = hvd_pkg.mesh()
    rng = np.random.default_rng(1)
    g = jnp.asarray(rng.normal(size=(96,)).astype(np.float32))
    steps = 40
    ef_err = _cumulative_error(mesh, True, steps, g)
    plain_err = _cumulative_error(mesh, False, steps, g)
    # EF compensates BOTH stages (traced.py return_residual), so the
    # error is bounded by ~one round's uncompensated carry regardless
    # of step count. Plain: the full error random-walks.
    assert ef_err < 8.0, f"EF cumulative error {ef_err} quanta"
    # and EF must be meaningfully tighter than the uncompensated wire
    assert ef_err < plain_err * 0.7, (ef_err, plain_err)


def test_training_converges_with_ef(hvd):
    mesh = hvd_pkg.mesh()
    rng = np.random.default_rng(2)
    w_true = jnp.asarray(rng.normal(size=(12,)).astype(np.float32))
    opt = hvd_pkg.DistributedOptimizer(
        optax.sgd(0.2), compression=Compression.int8, error_feedback=True
    )
    params = {"w": jnp.zeros((12,), jnp.float32)}
    state = opt.init(params)

    @partial(
        jax.shard_map, mesh=mesh, in_specs=(P(), P()),
        out_specs=(P(), P(), P()), check_vma=False,
    )
    def step(p, st):
        def loss_fn(p):
            return jnp.sum((p["w"] - w_true) ** 2)

        loss, g = jax.value_and_grad(loss_fn)(p)
        upd, st = opt.update(g, st, p)
        return optax.apply_updates(p, upd), st, loss

    js = jax.jit(step)
    losses = []
    for _ in range(40):
        params, state, loss = js(params, state)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 1e-3, (losses[0], losses[-1])


def test_ef_with_tuple_pytree_and_mixed_dtypes(hvd):
    """Review regressions: grads pytrees containing tuples must not
    collide with the (out, residual) pairs, and the residual carry must
    keep its init dtype across steps (lax-scan-stable state)."""
    mesh = hvd_pkg.mesh()
    opt = hvd_pkg.DistributedOptimizer(
        optax.sgd(0.1), compression=Compression.int8, error_feedback=True
    )
    params = (
        {"a": jnp.ones((8,), jnp.bfloat16)},
        jnp.ones((4,), jnp.float32),
    )
    state = opt.init(params)
    d0 = [l.dtype for l in jax.tree_util.tree_leaves(state.residual)]

    @partial(
        jax.shard_map, mesh=mesh, in_specs=(P(), P()),
        out_specs=(P(), P()), check_vma=False,
    )
    def step(p, st):
        g = jax.tree_util.tree_map(
            lambda x: jnp.ones_like(x, dtype=jnp.float32).astype(x.dtype),
            p,
        )
        upd, st = opt.update(g, st, p)
        return optax.apply_updates(p, upd), st

    js = jax.jit(step)
    for _ in range(3):
        params, state = js(params, state)
    d1 = [l.dtype for l in jax.tree_util.tree_leaves(state.residual)]
    assert d0 == d1, (d0, d1)
    # structure preserved: still (dict, array)
    assert isinstance(params, tuple) and isinstance(params[0], dict)
    assert np.isfinite(np.asarray(params[1], np.float32)).all()
