// Batched host-buffer pack/unpack.
//
// TPU-native rebuild of the reference's fusion-buffer memcpy machinery
// (ref: horovod/common/ops/collective_operations.cc
// MemcpyInFusionBuffer/MemcpyOutFusionBuffer and the batched-D2D kernel
// in horovod/common/ops/cuda/cuda_kernels.cu — SURVEY.md §2.2). On TPU
// the device-side fusion copy is XLA's problem (concatenation fuses into
// the collective); what remains native is the HOST staging copy: elastic
// state commit/restore snapshots (horovod_tpu/elastic/state.py) and any
// eager host-array fast path gather many small numpy buffers into one
// contiguous block. One C call replaces k Python-level copies.

#include "export.h"

#include <cstdint>
#include <cstring>

HVD_EXPORT void hvd_pack(const void** srcs, const long* nbytes, long k,
                         void* dst) {
  char* out = static_cast<char*>(dst);
  long off = 0;
  for (long i = 0; i < k; ++i) {
    std::memcpy(out + off, srcs[i], static_cast<size_t>(nbytes[i]));
    off += nbytes[i];
  }
}

HVD_EXPORT void hvd_unpack(const void* src, void** dsts, const long* nbytes,
                           long k) {
  const char* in = static_cast<const char*>(src);
  long off = 0;
  for (long i = 0; i < k; ++i) {
    std::memcpy(dsts[i], in + off, static_cast<size_t>(nbytes[i]));
    off += nbytes[i];
  }
}
