"""Cross-rank parameter audit: prove the replicas agree.

The Horovod premise is that data-parallel replicas stay BIT-IDENTICAL
after every allreduce (arXiv 1802.05799) — the whole stack (fused
wire, EF residuals, elastic restore, ZeRO reshard) is built on it, and
nothing so far ever *verified* it. A replica that diverges (memory
corruption, a non-deterministic kernel, a desynced RNG stream, a
checkpoint restored on one host but not another) keeps training
quietly wrong forever: every rank's loss looks plausible, and the
collectives happily average garbage with gold.

This module is the verification plane:

* :func:`tree_digest` — a canonical SHA-256 over a pytree (structure +
  per-leaf dtype/shape/bytes), cheap enough to run every few hundred
  steps on host copies.
* :func:`audit` — digest the tree, stamp ``audit.last_digest_step`` /
  ``audit.digests`` metrics, and — when running under the elastic
  runner — publish ``{step, digest}`` to the rendezvous KV
  (``runner/rendezvous.py`` ``put_audit``), where the driver compares
  the gang's digests.
* :func:`maybe_audit` — the rate-limited form: runs every
  ``HOROVOD_AUDIT_STEPS`` steps (0 = off), so a training loop can call
  it unconditionally per step.
* :func:`find_divergent` — the driver-side comparison: for the newest
  step reported by at least two ranks, the majority digest wins (ties
  break toward the LOWEST rank — the same root-wins arbitration as
  ``ObjectState.sync``); ranks holding any other digest are divergent.
  ``ElasticDriver`` quarantines their hosts and gang-restarts with
  reason ``divergence`` — the restore re-replicates state from the
  root, which IS the repair.

Single-controller jobs have one process speaking for every rank, so a
cross-rank mismatch cannot arise there; the audit still stamps its
metrics (so drills can assert cadence) and the driver-side comparison
is exercised by multi-process elastic jobs and by tests driving
``find_divergent`` directly.
"""

from __future__ import annotations

import hashlib
import threading
from typing import Any, Dict, Optional, Tuple

import jax
import numpy as np

from .common.logging import get_logger

_log = get_logger("audit")

_lock = threading.Lock()
_kv_client = None  # cached RendezvousClient (None until first publish)
_kv_unavailable = False


def digest_host_leaves(treedef, host_leaves) -> str:
    """The hashing core of :func:`tree_digest`, over already-fetched
    host leaves — split out so the checkpoint manager can pay the
    (donation-safe) device→host copy synchronously but run the SHA-256
    on a background thread."""
    h = hashlib.sha256(str(treedef).encode())
    for leaf in host_leaves:
        arr = np.ascontiguousarray(np.asarray(leaf))
        h.update(str(arr.dtype).encode())
        h.update(str(arr.shape).encode())
        h.update(arr.tobytes())
    return h.hexdigest()


def tree_digest(tree: Any) -> str:
    """Canonical SHA-256 of a pytree: the treedef string, then each
    leaf's dtype, shape, and raw bytes (host-fetched; device arrays
    are pulled once per call — run this at an audit cadence, not per
    step). Scalars/np/jax arrays all normalize through ``np.asarray``,
    so a restored-from-checkpoint tree and its live twin digest
    identically when (and only when) they are bit-identical."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    return digest_host_leaves(treedef, jax.device_get(leaves))


def tree_meta_digest(tree: Any) -> str:
    """SHA-256 of a pytree's SHAPE ONLY — treedef + per-leaf
    dtype/shape, no values, no device transfer. Two trees share a meta
    digest exactly when :func:`tree_digest` could meaningfully compare
    them; the checkpoint verifier uses it to tell 'the caller restored
    with a different dtype/structure on purpose' (verification
    inapplicable) apart from 'the bytes changed under the same shape'
    (corruption)."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    h = hashlib.sha256(str(treedef).encode())
    for leaf in leaves:
        if hasattr(leaf, "dtype") and hasattr(leaf, "shape"):
            dt, shape = np.dtype(leaf.dtype), tuple(leaf.shape)
        else:
            a = np.asarray(leaf)
            dt, shape = a.dtype, a.shape
        h.update(str(dt).encode())
        h.update(str(tuple(shape)).encode())
    return h.hexdigest()


def _cached_kv_client():
    """The process-wide rendezvous client for audit-plane publication
    (parameter digests here, schedule fingerprints in
    analysis/sched_audit.py — one connection, one failure posture).
    None when no rendezvous is configured (single-process runs,
    tests)."""
    global _kv_client, _kv_unavailable
    from .common.config import Config
    from .runner.rendezvous import _client_from_cfg

    with _lock:
        if _kv_unavailable:
            return None
        if _kv_client is None:
            cfg = Config.from_env()
            if not (cfg.rendezvous_addr and cfg.rendezvous_port):
                _kv_unavailable = True
                return None
            _kv_client = _client_from_cfg(cfg)
        return _kv_client


def _publish(rank: int, step: int, digest: str) -> bool:
    """Best-effort KV publication; False when there is no rendezvous
    to publish to (single-process runs, tests)."""
    from .runner.rendezvous import put_audit

    client = _cached_kv_client()
    if client is None:
        return False
    try:
        put_audit(client, rank, step, digest)
        return True
    except Exception:
        _log.debug("audit publish failed", exc_info=True)
        return False


def _reset_client() -> None:
    """Test hook / elastic reinit: drop the cached KV client so the
    next publish re-reads the (new gang's) rendezvous env."""
    global _kv_client, _kv_unavailable
    with _lock:
        _kv_client = None
        _kv_unavailable = False


def audit(tree: Any, step: int = 0, rank: Optional[int] = None) -> str:
    """``hvd.audit(params, step=...)`` — digest ``tree``, record the
    ``audit.*`` metrics, publish to the gang's rendezvous KV when one
    is configured. Returns the hex digest (callers can log or compare
    it themselves)."""
    from .common import basics
    from .common.metrics import registry as _metrics

    digest = tree_digest(tree)
    step = int(step)
    if rank is None:
        rank = basics.rank() if basics.is_initialized() else 0
    _metrics.counter("audit.digests")
    _metrics.gauge("audit.last_digest_step", step)
    _publish(int(rank), step, digest)
    # the collective-schedule fingerprint rides the same cadence and
    # the same KV (analysis/sched_audit.py): parameter divergence and
    # schedule divergence are the two halves of one audit plane
    from .analysis import sched_audit as _sched

    _sched.publish(step, rank=rank)
    _log.debug("audit step %d: %s", step, digest[:16])
    return digest


def default_audit_steps() -> int:
    from .common import basics

    return int(basics.live_config().audit_steps)


def maybe_audit(
    tree: Any, step: int, every: Optional[int] = None,
    rank: Optional[int] = None,
) -> Optional[str]:
    """Rate-limited :func:`audit`: runs when ``step`` lands on the
    ``HOROVOD_AUDIT_STEPS`` cadence (``every`` overrides; 0 = never).
    Call it unconditionally once per host-side step."""
    every = default_audit_steps() if every is None else int(every)
    if every <= 0 or int(step) % every != 0:
        return None
    return audit(tree, step=step, rank=rank)


def find_divergent(
    digests: Dict[int, dict],
) -> Optional[Tuple[int, Tuple[int, ...]]]:
    """Driver-side comparison over ``{rank: {"step", "digest"}}`` (the
    shape ``read_audit_digests`` returns). Looks at the NEWEST step
    reported by >= 2 ranks; if their digests disagree, returns
    ``(step, divergent_ranks)`` where the majority digest wins and a
    tie breaks toward the lowest-rank holder (root-wins, matching the
    elastic ``sync()`` broadcast direction). ``None`` = no quorum or
    full agreement."""
    by_step: Dict[int, Dict[int, str]] = {}
    for rank, payload in digests.items():
        try:
            step = int(payload["step"])
            digest = str(payload["digest"])
        except (KeyError, TypeError, ValueError):
            continue
        by_step.setdefault(step, {})[int(rank)] = digest
    for step in sorted(by_step, reverse=True):
        ranks = by_step[step]
        if len(ranks) < 2:
            continue
        counts: Dict[str, list] = {}
        for r, d in sorted(ranks.items()):
            counts.setdefault(d, []).append(r)
        if len(counts) == 1:
            return None  # newest quorum step agrees — healthy
        majority = max(
            counts.items(), key=lambda kv: (len(kv[1]), -min(kv[1]))
        )[0]
        divergent = tuple(
            sorted(r for d, rs in counts.items() if d != majority for r in rs)
        )
        return step, divergent
    return None
