"""Serving-plane tests (horovod_tpu/serving/): engine executor-cache
behavior and zero-retrace steady state, slot lifecycle, continuous
batching semantics, deadlines, SLO meters, HTTP frontend round-trip,
straggler-aware routing, and the SIGTERM drain contract."""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import jax
import jax.numpy as jnp


# Deliberately smaller than TransformerConfig.tiny(): every engine
# instance pays real XLA compiles, so the suite's model is minimal.
def _cfg(**kw):
    from horovod_tpu.models.transformer import TransformerConfig

    base = dict(
        vocab_size=61,
        num_layers=1,
        d_model=16,
        num_heads=2,
        d_ff=32,
        max_len=64,
        causal=True,
        dtype=jnp.float32,
    )
    base.update(kw)
    return TransformerConfig(**base)


@pytest.fixture(scope="module")
def toy():
    """(model, params) shared by every test in the module."""
    from horovod_tpu.models.transformer import Transformer

    model = Transformer(_cfg())
    params = model.init(
        jax.random.PRNGKey(0), jnp.ones((1, 4), jnp.int32), train=False
    )
    return model, params


@pytest.fixture(autouse=True)
def _clean_drain_hooks():
    yield
    from horovod_tpu import preemption

    for fn in preemption.drain_hooks():
        preemption.unregister_drain(fn)


def _engine(toy, **kw):
    from horovod_tpu.serving.engine import InferenceEngine

    model, params = toy
    kw.setdefault("slots", 4)
    kw.setdefault("max_len", 64)
    kw.setdefault("min_bucket", 4)
    return InferenceEngine(model, params, **kw)


def _greedy_ref(model, params, prompt, n):
    seq = list(map(int, prompt))
    for _ in range(n):
        lg = model.apply(params, jnp.asarray([seq]), train=False)
        seq.append(int(np.asarray(lg)[0, -1].argmax()))
    return seq[len(prompt):]


def _generate(engine, slot, prompt, n):
    out = [engine.prefill(slot, prompt)]
    for _ in range(n - 1):
        toks = np.zeros(engine.slots, np.int32)
        toks[slot] = out[-1]
        nxt = engine.decode_step(toks)
        engine.manager.advance(slot)
        out.append(int(nxt[slot]))
    return out


# --------------------------------------------------------------- engine


def test_engine_greedy_parity(toy):
    model, params = toy
    eng = _engine(toy)
    slot = eng.manager.alloc("r")
    out = _generate(eng, slot, [5, 7, 11], 6)
    assert out == _greedy_ref(model, params, [5, 7, 11], 6)


def test_prefill_two_tier_hit_miss_promotion(toy):
    eng = _engine(toy, promote_after=2)
    # length 5 -> bucket 8 compile (miss)
    eng.prefill(eng.manager.alloc(), [1, 2, 3, 4, 5])
    s = eng.stats()
    assert s["prefill_compiles"] == 1
    assert s["prefill_bucket_entries"] == 1
    assert s["prefill_pad_tokens"] == 3
    # length 6 -> same bucket, hit, no compile
    eng.prefill(eng.manager.alloc(), [1, 2, 3, 4, 5, 6])
    s = eng.stats()
    assert s["prefill_compiles"] == 1
    assert s["prefill_bucket_hits"] == 1
    # length 5 again -> second sighting kicks off a background promotion
    # while the request itself is still served off the bucket tier (the
    # serving hot path never blocks on a promotion compile).
    eng.prefill(eng.manager.alloc(), [9, 8, 7, 6, 5])
    s = eng.stats()
    assert s["prefill_bucket_hits"] == 2  # served padded, not blocked
    assert eng.drain_promotions()
    s = eng.stats()
    assert s["prefill_compiles"] == 2
    assert s["prefill_promotions"] == 1
    assert s["prefill_bg_promotions"] == 1
    assert s["prefill_exact_entries"] == 1
    # and a third length-5 prompt is an exact hit: no compile, no pad
    pad_before = s["prefill_pad_tokens"]
    eng.prefill(eng.manager.alloc(), [2, 2, 2, 2, 2])
    s = eng.stats()
    assert s["prefill_compiles"] == 2
    assert s["prefill_exact_hits"] == 1
    assert s["prefill_pad_tokens"] == pad_before  # exact tier: unpadded


def test_exact_tier_is_lru_bounded(toy):
    eng = _engine(toy, promote_after=1, exact_capacity=2)
    for ln in (3, 4, 5, 6):
        eng.prefill(eng.manager.alloc() or 0, list(range(1, ln + 1)))
        # slots exhaust; reuse slot 0 — allocator state is irrelevant here
    eng.drain_promotions()
    assert eng.stats()["prefill_exact_entries"] <= 2


def test_zero_retrace_steady_state_with_rolling_admissions(toy):
    """The acceptance property: after warmup, decode steps with
    admissions/evictions rolling through the slots trigger ZERO new
    compiles — shapes never change, only data."""
    model, params = toy
    eng = _engine(toy, promote_after=10)  # keep everything on one bucket
    # warmup: one prefill (bucket 4) + one decode step
    s0 = eng.manager.alloc("warm")
    eng.prefill(s0, [1, 2, 3])
    eng.decode_step(np.zeros(eng.slots, np.int32))
    eng.manager.advance(s0)
    warm = eng.stats()
    assert warm["decode_compiles"] == 1
    # steady state: admit/evict/decode across every slot repeatedly
    prompts = [[4, 5], [6, 7, 8], [9], [10, 11, 12]]
    for round_ in range(3):
        for p in prompts:
            slot = eng.manager.alloc(round_)
            if slot is None:
                slot = eng.manager.active_slots()[0]
                eng.manager.free(slot)
                slot = eng.manager.alloc(round_)
            eng.prefill(slot, p)
            for _ in range(2):
                eng.decode_step(np.zeros(eng.slots, np.int32))
                eng.manager.advance(slot)
    s = eng.stats()
    assert s["decode_compiles"] == 1, "decode retraced in steady state"
    # every prompt length above rides buckets 2/4 compiled in-round;
    # after the first round no prefill compiles either
    assert s["prefill_compiles"] <= warm["prefill_compiles"] + 2
    final_compiles = s["prefill_compiles"] + s["decode_compiles"]
    for p in prompts:  # one more full round: strictly zero compiles
        slot = eng.manager.active_slots()[0]
        eng.manager.free(slot)
        slot = eng.manager.alloc("again")
        eng.prefill(slot, p)
        eng.decode_step(np.zeros(eng.slots, np.int32))
        eng.manager.advance(slot)
    s = eng.stats()
    assert s["prefill_compiles"] + s["decode_compiles"] == final_compiles


def test_chunked_prefill_past_bucket_ceiling(toy):
    model, params = toy
    eng = _engine(toy, prefill_ceiling=8, max_len=64)
    prompt = list(
        np.random.default_rng(3).integers(1, 60, size=21)
    )  # 21 > 8: two full chunks + remainder 5
    slot = eng.manager.alloc()
    out = _generate(eng, slot, prompt, 4)
    assert out == _greedy_ref(model, params, prompt, 4)
    s = eng.stats()
    assert s["chunked_prefill_chunks"] == 2
    assert eng.manager.length(slot) == len(prompt) + 3


def test_prefill_ceiling_clamped_to_cache(toy):
    """An explicit ceiling must never round PAST a non-power-of-two
    max_len: a prefill width beyond the cache length would build kv
    updates larger than the cache leaf and fail at compile."""
    model, params = toy
    eng = _engine(toy, max_len=48, prefill_ceiling=64)
    assert eng.prefill_ceiling == 32  # largest pow2 <= 48
    prompt = list(np.random.default_rng(1).integers(1, 60, size=40))
    slot = eng.manager.alloc()
    out = _generate(eng, slot, prompt, 3)
    assert out == _greedy_ref(model, params, prompt, 3)


def test_slot_reuse_no_stale_leak(toy):
    """A freed slot is reused WITHOUT zeroing; the mask must make the
    previous occupant's kv unreachable — greedy output on the reused
    slot must match a fresh engine exactly."""
    model, params = toy
    eng = _engine(toy, slots=1)  # one slot: reuse is guaranteed
    slot = eng.manager.alloc("a")
    _generate(eng, slot, [31, 33, 35, 37, 39, 41, 43], 5)
    eng.manager.free(slot)
    slot2 = eng.manager.alloc("b")
    assert slot2 == slot
    out = _generate(eng, slot2, [2, 4], 6)
    assert out == _greedy_ref(model, params, [2, 4], 6)


# ------------------------------------------------------------- kv cache


def test_slot_allocator_lifecycle():
    from horovod_tpu.serving.kv_cache import KVCacheManager

    factory = lambda b, s: [
        {"k": jnp.zeros((b, s, 2, 4)), "v": jnp.zeros((b, s, 2, 4))}
    ]
    mgr = KVCacheManager(factory, slots=2, max_len=8)
    a = mgr.alloc("r1")
    b = mgr.alloc("r2")
    assert {a, b} == {0, 1}
    assert mgr.alloc() is None  # full
    assert mgr.stats()["slots_free"] == 0
    mgr.set_length(a, 5)
    assert mgr.capacity_left(a) == 3
    with pytest.raises(ValueError):
        mgr.set_length(a, 9)
    mgr.free(a)
    assert mgr.stats()["slots_active"] == 1
    assert mgr.length(a) == 0  # length resets on eviction
    c = mgr.alloc("r3")
    assert c == a  # reuse
    arr = mgr.lengths_array()
    arr[:] = 99  # a copy: bookkeeping can't be aliased
    assert mgr.length(b) == 0


def test_tp_sharded_cache_matches_unsharded(toy):
    from jax.sharding import Mesh

    model, params = toy
    mesh = Mesh(np.array(jax.devices()[:2]), ("tp",))
    eng_tp = _engine(toy, mesh=mesh)
    assert eng_tp.manager.sharding is not None
    slot = eng_tp.manager.alloc()
    out = _generate(eng_tp, slot, [7, 8, 9], 5)
    assert out == _greedy_ref(model, params, [7, 8, 9], 5)


def test_tp_sharding_requires_divisible_heads(toy):
    from jax.sharding import Mesh

    mesh = Mesh(np.array(jax.devices()[:8]).reshape(8), ("tp",))
    with pytest.raises(ValueError, match="divide"):
        _engine(toy, mesh=mesh)  # 2 kv heads % 8 != 0


# -------------------------------------------------------------- batcher


def _batcher(toy, **kw):
    from horovod_tpu.serving.batcher import ContinuousBatcher

    kw.setdefault("max_admit_per_step", 2)
    kw.setdefault("default_max_new_tokens", 4)
    eng = _engine(toy, slots=kw.pop("slots", 2))
    return ContinuousBatcher(eng, **kw)


def test_continuous_admission_mid_decode(toy):
    from horovod_tpu.common.metrics import registry

    model, params = toy
    b = _batcher(toy, default_max_new_tokens=8)
    base = registry.snapshot().get("serve.admitted_mid_decode", 0.0)
    r1 = b.submit([3, 5, 7], max_new_tokens=8)
    for _ in range(3):
        b.step()  # r1 admitted, decoding
    assert r1.status == "running" and len(r1.out_tokens) >= 2
    r2 = b.submit([11, 13], max_new_tokens=3)  # lands MID-decode
    while not (r1.finished() and r2.finished()):
        assert b.step(), "scheduler idled with work pending"
    # the admission neither flushed nor perturbed the in-flight stream
    assert r1.result()["tokens"] == _greedy_ref(model, params, [3, 5, 7], 8)
    assert r2.result()["tokens"] == _greedy_ref(model, params, [11, 13], 3)
    assert (
        registry.snapshot().get("serve.admitted_mid_decode", 0.0) > base
    )
    assert b.engine.stats()["decode_compiles"] == 1  # no retrace either


def test_queue_overflow_waits_for_free_slot(toy):
    b = _batcher(toy, slots=2, default_max_new_tokens=4)
    reqs = [b.submit([i + 1, i + 2]) for i in range(4)]
    b.step()
    assert b.active() == 2 and b.queue_depth() == 2  # slots gate admission
    while not all(r.finished() for r in reqs):
        b.step()
    assert all(r.status == "done" for r in reqs)
    assert {len(r.out_tokens) for r in reqs} == {4}


def test_deadline_expires_queued_request(toy):
    b = _batcher(toy)
    r = b.submit([1, 2], deadline_ms=1.0)
    time.sleep(0.02)
    b.step()
    assert r.finished() and r.status == "deadline"
    assert r.result()["tokens"] == []


def test_deadline_evicts_running_request_with_partial_output(toy):
    b = _batcher(toy, default_max_new_tokens=64)
    r = b.submit([1, 2, 3], deadline_ms=60_000.0)
    b.step()  # admit + first token (+ first decode)
    assert r.status == "running"
    # pull the deadline into the past (deterministic: wall-clock
    # deadlines under CPU compile jitter would flake either way)
    r.deadline_ts = time.monotonic() - 0.001
    b.step()
    assert r.finished() and r.status == "deadline"
    assert 0 < len(r.out_tokens) < 64  # partial output returned
    assert b.active() == 0  # slot evicted


def test_static_policy_is_a_batch_barrier(toy):
    b = _batcher(toy, slots=2, policy="static", default_max_new_tokens=4)
    r1 = b.submit([1, 2])
    b.step()
    assert b.active() == 1
    r2 = b.submit([3, 4])
    # the barrier: while the r1 batch is in flight, r2 stays queued
    while not r1.finished():
        assert r2.status == "queued"
        b.step()
    while not r2.finished():
        b.step()
    assert r1.status == r2.status == "done"


def test_reject_prompt_that_cannot_fit(toy):
    from horovod_tpu.serving.batcher import Rejected

    b = _batcher(toy)
    with pytest.raises(Rejected):
        b.submit(list(range(1, 65)))  # 64-token prompt: no room to gen
    with pytest.raises(Rejected):
        b.submit([])


def test_drain_completes_accepted_and_rejects_new(toy):
    from horovod_tpu.serving.batcher import Rejected

    b = _batcher(toy, default_max_new_tokens=5)
    reqs = [b.submit([i + 1, i + 2, i + 3]) for i in range(3)]
    assert b.drain(timeout=30)  # inline-steps without a loop thread
    assert all(r.status == "done" for r in reqs)
    assert all(len(r.out_tokens) == 5 for r in reqs)
    with pytest.raises(Rejected):
        b.submit([1, 2])


def test_scheduler_crash_aborts_accepted_requests(toy, monkeypatch):
    """An exception on the decode thread must not strand waiters: every
    accepted request fails loudly (status "error"), new submissions are
    refused — never a silent blackhole behind a live /healthz."""
    from horovod_tpu.serving.batcher import Rejected

    b = _batcher(toy, default_max_new_tokens=8)

    def _boom(tokens):
        raise RuntimeError("device fell over")

    monkeypatch.setattr(b.engine, "decode_step", _boom)
    b.start()
    try:
        r = b.submit([1, 2, 3])
        assert r.wait(timeout=30), "waiter stranded after scheduler crash"
        assert r.status == "error"
        with pytest.raises(Rejected):
            b.submit([4, 5])
    finally:
        b.stop()


def test_scheduler_crash_visible_at_frontend_and_fleet(toy, monkeypatch):
    """The crash-drain must propagate to every fleet surface: requests
    get 503 (Router fails over), /healthz flips not-ok, and the KV
    announcement flags draining — a crashed worker must never keep
    attracting traffic as the emptiest-looking rank."""
    import horovod_tpu as hvd

    model, params = toy
    handle = hvd.serve(
        model, params, port=0, slots=2, max_len=64,
        max_new_tokens=6, addr="127.0.0.1", handle_sigterm=False,
    )
    try:
        monkeypatch.setattr(
            handle.engine, "decode_step",
            lambda tokens: (_ for _ in ()).throw(
                RuntimeError("device fell over")
            ),
        )
        status, raw = _post_raw_error(
            handle.port, json.dumps({"tokens": [1, 2, 3]}).encode()
        )
        assert status == 500, status
        assert json.loads(raw)["status"] == "error"
        status, raw = _post_raw_error(
            handle.port, json.dumps({"tokens": [4, 5]}).encode()
        )
        assert status == 503, status  # failover signal, not 429
        with urllib.request.urlopen(
            f"http://127.0.0.1:{handle.port}/healthz", timeout=10
        ) as resp:
            health = json.load(resp)
        assert not health["ok"] and health["draining"]
    finally:
        handle.stop()


def test_init_cache_rejects_overlong_learned_position_cache():
    from horovod_tpu.models.transformer import init_cache

    cfg = _cfg()  # learned positions, max_len=64
    with pytest.raises(ValueError, match="position table"):
        init_cache(cfg, 2, 128)
    rope_cfg = _cfg(rope=True)
    init_cache(rope_cfg, 2, 128)  # rope: no table, any length


def test_decode_steps_land_in_flight_recorder(toy, monkeypatch):
    from horovod_tpu.common import telemetry

    monkeypatch.setenv("HOROVOD_TELEMETRY", "1")
    telemetry._reset_hub()
    try:
        b = _batcher(toy, default_max_new_tokens=4)
        r = b.submit([5, 6, 7])
        while not r.finished():
            b.step()
        recs = telemetry.hub().records()
        assert recs, "decode steps produced no StepStats records"
        assert sum(rec["serve.tokens_out"] for rec in recs) >= 3
    finally:
        telemetry._reset_hub()


def test_slo_recorder_quantiles():
    from horovod_tpu.common.metrics import registry
    from horovod_tpu.serving.slo import LatencyRecorder

    rec = LatencyRecorder(capacity=8)
    for v in (1.0, 2.0, 3.0, 4.0, 100.0):
        rec.record_ttft(v)
    rec.record_tpot(7.0)
    s = rec.summaries()
    assert s["ttft_ms"]["p50"] == 3.0
    assert s["ttft_ms"]["p95"] == 100.0
    assert s["ttft_ms"]["count"] == 5
    rec.publish()
    snap = registry.snapshot()
    assert snap["serve.ttft_ms_p50"] == 3.0
    assert snap["serve.tpot_ms_count"] == 1
    text = "\n".join(rec.render_prometheus_summaries())
    assert 'serve_ttft_ms{quantile="0.5"} 3' in text
    assert "# TYPE serve_tpot_ms summary" in text


# ------------------------------------------------------------- frontend


def _post(port, payload, timeout=60):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/generate",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return resp.status, json.load(resp)


def test_frontend_http_roundtrip(toy):
    import horovod_tpu as hvd

    model, params = toy
    handle = hvd.serve(
        model, params, port=0, slots=2, max_len=64,
        max_new_tokens=4, addr="127.0.0.1", handle_sigterm=False,
    )
    try:
        status, out = _post(handle.port, {"tokens": [9, 10, 11]})
        assert status == 200
        assert out["status"] == "done"
        assert out["tokens"] == _greedy_ref(model, params, [9, 10, 11], 4)
        assert out["ttft_ms"] > 0
        with urllib.request.urlopen(
            f"http://127.0.0.1:{handle.port}/healthz", timeout=10
        ) as resp:
            health = json.load(resp)
        assert health["ok"] and health["slots_total"] == 2
        with urllib.request.urlopen(
            f"http://127.0.0.1:{handle.port}/metrics", timeout=10
        ) as resp:
            text = resp.read().decode()
        assert 'serve_ttft_ms{quantile="0.5"}' in text
        assert "hvd_serve_slots_total" in text
        status, err = _post_raw_error(handle.port, b"not json")
        assert status == 400
        # valid JSON that is not an object, and object with bad field
        # types: still 400, never a torn socket
        for body in (b"[1,2,3]", b'{"tokens": "abc"}',
                     b'{"tokens": [1,2], "max_tokens": "x"}'):
            status, err = _post_raw_error(handle.port, body)
            assert status == 400, (body, status)
    finally:
        handle.stop()


def _post_raw_error(port, body):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/generate", data=body, method="POST"
    )
    try:
        with urllib.request.urlopen(req, timeout=10) as resp:
            return resp.status, resp.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


def test_frontend_drain_finishes_inflight_then_503(toy):
    import horovod_tpu as hvd

    model, params = toy
    handle = hvd.serve(
        model, params, port=0, slots=2, max_len=64,
        max_new_tokens=6, addr="127.0.0.1", handle_sigterm=False,
    )
    try:
        results = {}

        def client(key, tokens):
            results[key] = _post(handle.port, {"tokens": tokens})

        threads = [
            threading.Thread(target=client, args=(i, [i + 1, i + 2]))
            for i in range(3)
        ]
        for t in threads:
            t.start()
        # all three must be ACCEPTED (in a slot, queued, or already
        # finishing) before the drain starts — a drain may legitimately
        # 503 a request that has not been submitted yet
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            accepted = (
                handle.batcher.queue_depth()
                + handle.batcher.active()
                + len(results)
            )
            if accepted >= 3:
                break
            time.sleep(0.005)
        assert handle.drain(timeout=30)
        for t in threads:
            t.join(timeout=30)
        assert len(results) == 3
        for status, out in results.values():
            assert status == 200 and out["status"] == "done"
        status, _ = _post_raw_error(
            handle.port, json.dumps({"tokens": [1, 2]}).encode()
        )
        assert status == 503  # draining refuses new work
    finally:
        handle.stop()


def test_serve_registers_and_unregisters_drain_hook(toy):
    import horovod_tpu as hvd
    from horovod_tpu import preemption

    model, params = toy
    before = len(preemption.drain_hooks())
    handle = hvd.serve(
        model, params, port=0, slots=2, max_len=64,
        addr="127.0.0.1", handle_sigterm=False,
    )
    assert len(preemption.drain_hooks()) == before + 1
    handle.stop()
    assert len(preemption.drain_hooks()) == before


# --------------------------------------------------------------- router


def _announce(store, rank, port, free_slots, queue_depth=0,
              draining=False, ts=None):
    store.put(
        "serve",
        str(rank),
        json.dumps(
            {
                "rank": rank,
                "addr": "127.0.0.1",
                "port": port,
                "free_slots": free_slots,
                "queue_depth": queue_depth,
                "draining": draining,
                "ts": time.time() if ts is None else ts,
            }
        ).encode(),
    )


def test_router_picks_least_loaded(toy):
    from horovod_tpu.runner.rendezvous import KVStore
    from horovod_tpu.serving.frontend import Router

    store = KVStore()
    _announce(store, 0, 9000, free_slots=1, queue_depth=5)
    _announce(store, 1, 9001, free_slots=7, queue_depth=0)
    router = Router(store)
    assert router.pick()["rank"] == 1
    # local debits spread a burst between announcement refreshes
    picks = [router.pick()["rank"] for _ in range(7)]
    assert 0 in picks


def test_router_avoids_straggler_ranks(toy):
    from horovod_tpu.runner.rendezvous import KVStore, put_heartbeat
    from horovod_tpu.serving.frontend import Router

    store = KVStore()
    # rank 0 has MORE free slots but its heartbeat p50 is 10x the gang
    _announce(store, 0, 9000, free_slots=8)
    _announce(store, 1, 9001, free_slots=2)
    _announce(store, 2, 9002, free_slots=2)

    class _Client:
        def put(self, scope, key, value):
            store.put(scope, key, value)

    for rank, p50 in ((0, 500.0), (1, 50.0), (2, 55.0)):
        put_heartbeat(
            _Client(), rank,
            {"step": 100, "step_ms_p50": p50, "last_step_ts": time.time()},
        )
    router = Router(store)
    assert router.straggler_ranks() == [0]
    assert router.pick()["rank"] in (1, 2)  # flagged rank 0 bypassed


def test_router_skips_stale_and_draining(toy):
    from horovod_tpu.runner.rendezvous import KVStore
    from horovod_tpu.serving.frontend import Router

    store = KVStore()
    _announce(store, 0, 9000, free_slots=8, ts=time.time() - 60)  # stale
    _announce(store, 1, 9001, free_slots=1, draining=True)
    router = Router(store)
    assert router.pick() is None
    _announce(store, 2, 9002, free_slots=1)
    assert router.pick()["rank"] == 2


def test_router_routes_to_live_worker_with_failover(toy):
    import horovod_tpu as hvd
    from horovod_tpu.runner.rendezvous import KVStore
    from horovod_tpu.serving.frontend import Router

    model, params = toy
    handle = hvd.serve(
        model, params, port=0, slots=2, max_len=64,
        max_new_tokens=3, addr="127.0.0.1", handle_sigterm=False,
    )
    try:
        store = KVStore()
        _announce(store, 0, 1, free_slots=9)  # port 1: nothing listens
        _announce(store, 1, handle.port, free_slots=2)
        router = Router(store)
        out = router.route([4, 5, 6], attempts=3)
        assert out["status"] == "done"
        assert out["tokens"] == _greedy_ref(model, params, [4, 5, 6], 3)
        # a 4xx is the REQUEST's fault: surfaced, not failed-over
        with pytest.raises(RuntimeError, match="rejected"):
            router.route(list(range(1, 65)), attempts=3)
    finally:
        handle.stop()


# ---------------------------------------------------- crash-safe routing


def test_generate_dedupe_replays_cached_result(toy):
    """Satellite (timeout ambiguity): a replayed /generate carrying the
    same client request_id is answered from the completed-results cache
    — the work is NOT redone, so a client retry after a lost response
    can never double-generate."""
    import horovod_tpu as hvd
    from horovod_tpu.common.metrics import registry

    model, params = toy
    handle = hvd.serve(
        model, params, port=0, slots=2, max_len=64,
        max_new_tokens=4, addr="127.0.0.1", handle_sigterm=False,
    )
    try:
        before = registry.snapshot().get("serve.replay_dedupe_hits", 0.0)
        payload = {"tokens": [5, 6, 7], "request_id": "client-abc"}
        s1, out1 = _post(handle.port, payload)
        s2, out2 = _post(handle.port, payload)
        assert s1 == s2 == 200
        assert out1 == out2  # byte-identical replay, not a re-decode
        assert (
            registry.snapshot().get("serve.replay_dedupe_hits", 0.0)
            == before + 1
        )
        # a different id is fresh work, not a cache hit
        s3, out3 = _post(
            handle.port, {"tokens": [5, 6, 7], "request_id": "client-def"}
        )
        assert s3 == 200 and out3["tokens"] == out1["tokens"]
        assert (
            registry.snapshot().get("serve.replay_dedupe_hits", 0.0)
            == before + 1
        )
    finally:
        handle.stop()


def test_router_replays_on_dark_worker_and_tombstones(toy):
    """Tentpole: the routed payload IS the journal — a worker that
    goes dark mid-call gets the request replayed on a live peer, and
    its pre-crash announcement is tombstoned so the NEXT request does
    not walk into the same hole. A ts advance (proof of life) forgives
    the tombstone."""
    import horovod_tpu as hvd
    from horovod_tpu.common.metrics import registry
    from horovod_tpu.runner.rendezvous import KVStore
    from horovod_tpu.serving.frontend import Router

    model, params = toy
    handle = hvd.serve(
        model, params, port=0, slots=2, max_len=64,
        max_new_tokens=3, addr="127.0.0.1", handle_sigterm=False,
    )
    try:
        store = KVStore()
        _announce(store, 0, 1, free_slots=9)  # dark: nothing listens
        _announce(store, 1, handle.port, free_slots=2)
        router = Router(store)
        before = registry.snapshot().get("serve.replays", 0.0)
        out = router.route([4, 5, 6], attempts=3, request_id="rep-1")
        assert out["status"] == "done"
        assert out["tokens"] == _greedy_ref(model, params, [4, 5, 6], 3)
        assert (
            registry.snapshot().get("serve.replays", 0.0) == before + 1
        )
        # the dark worker's unchanged announcement is unroutable now
        assert set(router.snapshot()) == {1}
        # ...until it actually announces again
        _announce(store, 0, 1, free_slots=9)
        assert set(router.snapshot()) == {0, 1}
    finally:
        handle.stop()


def test_router_evicts_driver_declared_dead_hosts(toy):
    """Tentpole (failure detection feeds routing): the driver's
    published dead set evicts a worker's announcement immediately —
    no waiting out the freshness TTL — matched by rank or by host."""
    from horovod_tpu.runner.rendezvous import KVStore, put_dead_hosts
    from horovod_tpu.serving.frontend import Router

    store = KVStore()
    _announce(store, 0, 9000, free_slots=8)
    _announce(store, 1, 9001, free_slots=2)
    router = Router(store)
    assert set(router.snapshot()) == {0, 1}
    put_dead_hosts(store, [], ranks=[0])
    assert set(router.snapshot()) == {1}
    assert router.pick()["rank"] == 1
    # host/addr matching catches ranks the driver could not map
    put_dead_hosts(store, ["127.0.0.1"])
    assert router.snapshot() == {}
    assert router.pick() is None


def test_router_hedges_to_second_worker_when_primary_stalls(toy):
    """HOROVOD_SERVE_HEDGE_MS semantics: the primary accepts the POST
    but never answers (scheduler not running); after the hedge delay a
    backup fires on the second worker and its result wins."""
    import horovod_tpu as hvd
    from horovod_tpu.common.metrics import registry
    from horovod_tpu.runner.rendezvous import KVStore
    from horovod_tpu.serving.batcher import ContinuousBatcher
    from horovod_tpu.serving.frontend import Router, ServeFrontend

    model, params = toy
    stalled = ContinuousBatcher(
        _engine(toy, slots=2), default_max_new_tokens=3
    )
    sfe = ServeFrontend(stalled, port=0, addr="127.0.0.1")
    sfe.start()
    handle = hvd.serve(
        model, params, port=0, slots=2, max_len=64,
        max_new_tokens=3, addr="127.0.0.1", handle_sigterm=False,
    )
    try:
        store = KVStore()
        _announce(store, 0, sfe.port, free_slots=9)  # stall looks best
        _announce(store, 1, handle.port, free_slots=2)
        router = Router(store)
        before = registry.snapshot().get("serve.hedges", 0.0)
        out = router.route([4, 5, 6], hedge_ms=50.0, timeout=30.0)
        assert out["status"] == "done"
        assert out["tokens"] == _greedy_ref(model, params, [4, 5, 6], 3)
        assert (
            registry.snapshot().get("serve.hedges", 0.0) == before + 1
        )
    finally:
        sfe.stop()
        handle.stop()
