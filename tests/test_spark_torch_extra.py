"""Regression tests for TorchEstimator input-contract edges (review
findings: one-shot generators must train every epoch; an impossible
batch_size must fail loudly, not record nan losses)."""

import numpy as np
import pytest

torch = pytest.importorskip("torch")

from horovod_tpu.spark.torch import TorchEstimator


def _net():
    torch.manual_seed(0)
    return torch.nn.Sequential(torch.nn.Linear(4, 8), torch.nn.Linear(8, 1))


def test_one_shot_generator_trains_every_epoch(hvd):
    rng = np.random.default_rng(0)
    x = rng.normal(size=(96, 4)).astype(np.float32)
    y = rng.normal(size=(96, 1)).astype(np.float32)

    def gen():
        for i in range(0, 96, 32):
            yield x[i : i + 32], y[i : i + 32]

    est = TorchEstimator(model=_net(), epochs=3, batch_size=32)
    est.fit(gen())
    assert len(est.history) == 3
    assert all(np.isfinite(h["loss"]) for h in est.history)


def test_batch_size_larger_than_dataset_raises(hvd):
    x = np.zeros((8, 4), np.float32)
    y = np.zeros((8, 1), np.float32)
    est = TorchEstimator(model=_net(), epochs=1, batch_size=32)
    with pytest.raises(ValueError, match="exceeds dataset size"):
        est.fit(x, y)


def test_empty_iterable_raises(hvd):
    est = TorchEstimator(model=_net(), epochs=1)
    with pytest.raises(ValueError, match="empty batch iterable"):
        est.fit(iter([]))


def test_flush_applies_partial_window(hvd):
    """3 steps with backward_passes_per_step=2: the tail microbatch's
    gradient must land via flush(), not be silently discarded (review
    finding). Closed form with SGD lr and constant grads."""
    import horovod_tpu.torch as hvdt

    p = torch.nn.Parameter(torch.zeros(1))
    opt = hvdt.DistributedOptimizer(
        torch.optim.SGD([p], lr=1.0), backward_passes_per_step=2
    )
    for _ in range(3):
        opt.zero_grad()
        p.grad = torch.ones(1)
        opt.step()
    # boundary at step 2 applied sum of two unit grads: p = -2
    np.testing.assert_allclose(p.detach().numpy(), [-2.0])
    opt.flush()
    # flush applies the dangling third grad: p = -3
    np.testing.assert_allclose(p.detach().numpy(), [-3.0])
    # empty window: flush is a no-op
    opt.flush()
    np.testing.assert_allclose(p.detach().numpy(), [-3.0])


def test_estimator_flushes_tail_window(hvd):
    """96 samples / batch 32 / k=2 -> 3 steps per epoch: epoch loss must
    keep decreasing because the tail batch still contributes."""
    rng = np.random.default_rng(3)
    x = rng.normal(size=(96, 4)).astype(np.float32)
    w = rng.normal(size=(4, 1)).astype(np.float32)
    y = (x @ w).astype(np.float32)
    est = TorchEstimator(
        model=_net(),
        loss=torch.nn.MSELoss(),
        optimizer=lambda p: torch.optim.SGD(p, lr=1e-2),
        epochs=8,
        batch_size=32,
        backward_passes_per_step=2,
    )
    est.fit(x, y)
    assert est.history[-1]["loss"] < est.history[0]["loss"] * 0.5


def test_refit_resets_history(hvd):
    x = np.zeros((64, 4), np.float32)
    y = np.zeros((64, 1), np.float32)
    est = TorchEstimator(model=_net(), epochs=2, batch_size=32)
    est.fit(x, y)
    est.fit(x, y)
    assert len(est.history) == 2
    assert [h["epoch"] for h in est.history] == [0, 1]
