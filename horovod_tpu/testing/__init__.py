"""Test-support shims (conformance fakes for optional cluster deps)."""
