"""2-layer ConvNet: the reference's MNIST example model
(ref: examples/pytorch/pytorch_mnist.py Net — conv(10)→conv(20)→fc50→fc10
[V]; BASELINE.json config #1). Same capacity, TPU-idiomatic NHWC layout."""

import flax.linen as nn


class MNISTConvNet(nn.Module):
    num_classes: int = 10

    @nn.compact
    def __call__(self, x, train: bool = True):
        # x: [B, 28, 28, 1] NHWC (TPU-native layout)
        x = nn.Conv(10, (5, 5), padding="VALID")(x)
        x = nn.max_pool(nn.relu(x), (2, 2), (2, 2))
        x = nn.Conv(20, (5, 5), padding="VALID")(x)
        x = nn.max_pool(nn.relu(x), (2, 2), (2, 2))
        x = x.reshape(x.shape[0], -1)
        x = nn.relu(nn.Dense(50)(x))
        x = nn.Dropout(0.5, deterministic=not train)(x)
        return nn.Dense(self.num_classes)(x)
