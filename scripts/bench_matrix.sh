#!/usr/bin/env bash
# Capture the full benchmark matrix on the real TPU chip and commit-able
# artifacts under bench_results/ (round-N tag as $1, default r03).
#
# The chip is exclusive and a killed process wedges its claim for
# minutes (docs/perf.md), so: one bench at a time, no kills, generous
# waits between. Each bench prints ONE JSON line; we tee it into its
# artifact and fail loudly on empty output (the r02 lesson: an empty
# artifact is worse than none).

set -uo pipefail
cd "$(dirname "$0")/.."
tag="${1:-r03}"
mkdir -p bench_results

capture() {
  local name="$1"; shift
  local out="bench_results/${name}_${tag}.json"
  echo "=== $name -> $out" >&2
  "$@" > "$out".tmp 2> "bench_results/${name}_${tag}.err"
  local line
  line=$(grep -E '^\{' "$out".tmp | tail -1 || true)
  if [ -z "$line" ]; then
    echo "FAILED: $name produced no JSON line" >&2
    tail -5 "bench_results/${name}_${tag}.err" >&2
    rm -f "$out".tmp
    return 1
  fi
  # multi-line benches (allreduce sweep) keep every JSON line
  grep -E '^\{' "$out".tmp > "$out"
  rm -f "$out".tmp
  echo "$line" >&2
}

fail=0
capture resnet50    env BENCH_INNER=1 python bench.py        || fail=1
capture bert_large  env BENCH_MODEL=bert_large python bench_lm.py  || fail=1
capture gpt2_medium env BENCH_MODEL=gpt2_medium python bench_lm.py || fail=1
capture allreduce   python bench_allreduce.py                 || fail=1
# exploratory second pass: no-remat LM variants (kept as separate
# artifacts; the defaults above stay the comparable configuration)
capture bert_large_noremat  env BENCH_MODEL=bert_large BENCH_REMAT=0 python bench_lm.py || true
capture gpt2_medium_noremat env BENCH_MODEL=gpt2_medium BENCH_REMAT=0 python bench_lm.py || true
echo "matrix done (fail=$fail)" >&2
exit $fail
