#!/usr/bin/env bash
# Round-4 chip work, in value order (VERDICT r3 "Next round" #2/#3).
# Run unattended under nohup; waits for any round-3 loop to release the
# chip, then probes the backend until it answers (a failed claim takes
# ~25 min to report UNAVAILABLE — that IS the probe), then captures.
#
# Order rationale:
#   0. flash lse-layout smoke — round 4 changed the fwd<->bwd lse
#      interchange to width-1; it MUST be validated on real Mosaic
#      before any LM bench uses it (escape hatch:
#      HOROVOD_FLASH_LSE_BROADCAST=1).
#   1. resnet50 default fresh capture (the headline, stamps captured_at)
#   2. space_to_depth stem A/B — the named HBM-bound remedy
#   3. gpt2 default fresh + flash block sweep + no-remat batch probe —
#      the "LM MFU past 0.45" experiments
#   4. bert_large fresh (Adasum config)
#   5. vit_b16 (BASELINE config #5 — round-3 capture died in the outage)
#   6. allreduce busbw world=1 on the real chip
#   7. resnet batch-512 confirm + profile capture for the roofline note

set -uo pipefail
cd "$(dirname "$0")/.."
mkdir -p bench_results
R=r04

while pgrep -f "chipwork_r03.sh|capture_remaining_r03.sh" >/dev/null 2>&1; do
  echo "waiting for round-3 chip loop to exit..." >&2
  sleep 120
done

probe_backend() {
  # Untimed claim attempt, per the operational rules: killing a claiming
  # client wastes its queue slot, and a failed claim reports UNAVAILABLE
  # on its own after ~25 min — that report IS the backoff. The 2h
  # timeout is only a safety net against a never-returning half-dead
  # backend (kills were shown NOT to wedge the queue, just wasteful).
  timeout 7200 python - <<'PYEOF' >/dev/null 2>&1
import jax
assert jax.devices()[0].platform == "tpu"
PYEOF
}

echo "=== probing TPU backend (each failed probe ~25 min)" >&2
until probe_backend; do
  echo "backend still down $(date -u +%H:%M); retry in 300s" >&2
  sleep 300
done
echo "=== backend is UP $(date -u +%H:%M) — capturing" >&2

cap() {   # cap <name> <cmd...>  -> bench_results/<name>_r04.json
  # Two attempts with a pause: a mid-run backend drop must not burn the
  # rest of the unattended list (r03's try_capture discipline).
  local name="$1"; shift
  local out="bench_results/${name}_${R}.json"
  for attempt in 1 2; do
    echo "=== $name (attempt $attempt)" >&2
    "$@" > "$out.tmp" 2> "bench_results/${name}_${R}.err"
    if grep -qE '^\{' "$out.tmp"; then
      grep -E '^\{' "$out.tmp" > "$out"
      rm -f "$out.tmp" "bench_results/${name}_${R}.err"
      cat "$out" >&2
      return 0
    fi
    rm -f "$out.tmp"
    sleep 120
  done
  echo "FAILED $name (see bench_results/${name}_${R}.err)" >&2
  return 1
}

# 0. flash lse-layout smoke: both interchange layouts vs the dense
#    oracle ON THE REAL CHIP (fwd values + all three grads)
python - > bench_results/flash_lse_smoke_${R}.txt 2>&1 <<'EOF'
import os
import numpy as np
import jax, jax.numpy as jnp

def dense(q, k, v, causal):
    d = q.shape[-1]
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=jnp.float32) / jnp.sqrt(d).astype(jnp.float32)
    if causal:
        t = q.shape[1]
        s = jnp.where(jnp.tril(jnp.ones((t, t), bool))[None, None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32)).astype(q.dtype)

rng = np.random.default_rng(0)
b, t, h, d = 2, 256, 4, 64
q, k, v = (jnp.asarray(rng.normal(size=(b, t, h, d)), jnp.float32) for _ in range(3))

from horovod_tpu.ops import flash_attention as fa

rq, rk, rv = jax.grad(
    lambda q, k, v: dense(q, k, v, True).astype(jnp.float32).sum(),
    argnums=(0, 1, 2))(q, k, v)

results = {}
# broadcast FIRST (it is the fallback — a compact failure must never
# skip validating the layout we would fall back to), each layout
# isolated so one failure cannot abort the other's run
for layout, env in (("broadcast", "1"), ("compact", "")):
    # the layout env is read at trace time, and jax.grad retraces per
    # call, so flipping the env between iterations is sufficient
    os.environ["HOROVOD_FLASH_LSE_BROADCAST"] = env
    try:
        def loss(q, k, v):
            return fa.flash_attention(q, k, v, causal=True).astype(jnp.float32).sum()
        gq, gk, gv = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
        ok = True
        for name, a, bb in (("dq", gq, rq), ("dk", gk, rk), ("dv", gv, rv)):
            err = float(jnp.max(jnp.abs(a - bb)))
            print(layout, name, "maxerr", err)
            ok = ok and err < 2e-3
    except Exception as e:
        print(layout, "EXCEPTION", repr(e)[:300])
        ok = False
    results[layout] = ok
    print(layout, "PASS" if ok else "FAIL")
print("RESULT compact=%s broadcast=%s" % (
    "PASS" if results.get("compact") else "FAIL",
    "PASS" if results.get("broadcast") else "FAIL"))
if results.get("compact"):
    print("FLASH LSE LAYOUTS PASS ON TPU")
EOF
if ! grep -q "compact=PASS" bench_results/flash_lse_smoke_${R}.txt; then
  if grep -q "broadcast=PASS" bench_results/flash_lse_smoke_${R}.txt; then
    echo "compact lse layout FAILED on chip; broadcast validated — pinning it for all LM benches" >&2
    export HOROVOD_FLASH_LSE_BROADCAST=1
  else
    echo "BOTH lse layouts failed on chip — LM benches fall back to dense attention" >&2
    export BENCH_FLASH=0
  fi
fi
tail -2 bench_results/flash_lse_smoke_${R}.txt >&2

# 0b. pallas kernel on-chip smoke (scale_cast / int8_quantize /
#     adasum_pair vs oracles) — pending since the round-3 outage
python - > bench_results/pallas_smoke_${R}.txt 2>&1 <<'PYEOF'
import numpy as np
import jax, jax.numpy as jnp
from horovod_tpu.ops import pallas_kernels as pk

rng = np.random.default_rng(0)
x = jnp.asarray(rng.normal(size=(1000, 257)).astype(np.float32))
y = pk.scale_cast(x, 2.5, jnp.bfloat16)
ref = (np.asarray(x, np.float32) * 2.5).astype(jnp.bfloat16)
assert np.allclose(np.asarray(y, np.float32), np.asarray(ref, np.float32), rtol=1e-2)
vals, scale = pk.int8_quantize(x, seed=7)
deq = np.asarray(vals, np.float32) * float(scale)
assert np.abs(deq - np.asarray(x)).max() <= float(scale) * 1.01
a = jnp.asarray(rng.normal(size=(4096,)).astype(np.float32))
b = jnp.asarray(rng.normal(size=(4096,)).astype(np.float32))
got = np.asarray(pk.adasum_pair(a, b))
an, bn = np.asarray(a, np.float64), np.asarray(b, np.float64)
dot, asq, bsq = an @ bn, an @ an, bn @ bn
oracle = (1 - dot / (2 * asq)) * an + (1 - dot / (2 * bsq)) * bn
assert np.allclose(got, oracle, rtol=1e-4, atol=1e-5)
print("ALL PALLAS KERNELS PASS ON TPU")
PYEOF
tail -1 bench_results/pallas_smoke_${R}.txt >&2

# 1-2. ResNet-50: fresh default, then the space_to_depth A/B
cap resnet50           env BENCH_INNER=1 python bench.py
cap resnet50_s2d       env BENCH_INNER=1 BENCH_STEM=space_to_depth python bench.py

# 3. GPT-2 medium: fresh default; flash block sweep; no-remat big batch
cap gpt2_medium        env BENCH_MODEL=gpt2_medium python bench_lm.py
for blk in 64 256 512; do
  cap gpt2_blk${blk}   env BENCH_MODEL=gpt2_medium BENCH_FLASH_BLOCK=${blk} python bench_lm.py
done
cap gpt2_noremat_b16   env BENCH_MODEL=gpt2_medium BENCH_BATCH=16 BENCH_REMAT=0 python bench_lm.py
cap gpt2_seq1024       env BENCH_MODEL=gpt2_medium BENCH_BATCH=4 BENCH_SEQ=1024 python bench_lm.py

# 4. BERT-large: fresh default + the round-3 best config re-validated
cap bert_large         env BENCH_MODEL=bert_large python bench_lm.py
cap bert_noremat_b16   env BENCH_MODEL=bert_large BENCH_BATCH=16 BENCH_REMAT=0 python bench_lm.py

# 5. ViT-B/16 (config #5) — died in the round-3 outage
cap vit_b16            env BENCH_INNER=1 BENCH_MODEL=vit_b16 python bench.py

# 6. allreduce busbw on the real chip (world=1: single-device round trip)
cap allreduce          python bench_allreduce.py

# 7. batch-512 confirm (HBM-bound => flat) for the roofline note
cap resnet50_b512      env BENCH_INNER=1 BENCH_BATCH=512 python bench.py

echo "=== chipwork_r04 complete $(date -u +%H:%M)" >&2
