"""Sequence-length scaling sweep for the flash-attention kernels
(VERDICT r4 item 8 — long-context perf evidence).

Times ONE attention op (fwd, and fwd+bwd through the custom VJP) at
growing sequence lengths on the real chip, reporting achieved TFLOP/s
so the O(T²) compute scaling and the kernels' efficiency at long T are
visible in one table. The dense-path control runs where it fits in HBM
(the score matrix is b·h·t² fp32 — 16 GiB stops it well before the
flash path stops).

Causal attention FLOPs (the convention docs/perf.md uses): forward is
two t×t×d matmuls per (batch, head) halved by the causal mask —
2 · 2 · b·h·t²·d · ½. Backward recomputes P and runs five matmuls:
2.5× forward.

Per (engine, seq) prints one JSON line:
  {"metric": "attn_seq_sweep", "engine": "flash|dense", "seq": T,
   "value": ms fwd+bwd, "unit": "ms", "fwd_ms": ..., "tflops": ...}

Env: BENCH_SEQS (comma-sep, default 1024,2048,4096,8192), BENCH_BATCH
(default 4), BENCH_HEADS (16), BENCH_HEAD_DIM (64), BENCH_ITERS (10),
BENCH_DENSE_MAX_SEQ (default 4096), BENCH_PLATFORM=cpu for interpret-
mode logic validation (sim note attached).
"""

import json
import os
import time

from _benchlib import stamp as _stamp

_SIM_NOTE = (
    "logic-validation only (CPU interpret mode); NOT a TPU kernel "
    "number"
)


def main():
    import jax

    if os.environ.get("BENCH_PLATFORM"):
        jax.config.update("jax_platforms", os.environ["BENCH_PLATFORM"])

    import jax.numpy as jnp
    import numpy as np

    from _benchlib import sync as _sync
    from horovod_tpu.ops.flash_attention import flash_attention

    platform = jax.devices()[0].platform
    seqs = [
        int(s)
        for s in os.environ.get(
            "BENCH_SEQS", "1024,2048,4096,8192"
        ).split(",")
    ]
    b = int(os.environ.get("BENCH_BATCH", "4"))
    h = int(os.environ.get("BENCH_HEADS", "16"))
    d = int(os.environ.get("BENCH_HEAD_DIM", "64"))
    iters = int(os.environ.get("BENCH_ITERS", "10"))
    dense_max = int(os.environ.get("BENCH_DENSE_MAX_SEQ", "4096"))

    def dense(q, k, v):
        t = q.shape[1]
        s = jnp.einsum(
            "bqhd,bkhd->bhqk", q, k,
            preferred_element_type=jnp.float32,
        ) / np.sqrt(d)
        mask = jnp.tril(jnp.ones((t, t), bool))
        s = jnp.where(mask[None, None], s, -1e30)
        p = jax.nn.softmax(s, axis=-1)
        return jnp.einsum(
            "bhqk,bkhd->bqhd", p, v.astype(jnp.float32)
        ).astype(q.dtype)

    def run(engine, attn, t):
        rng = np.random.default_rng(0)
        q, k, v = (
            jnp.asarray(
                rng.normal(size=(b, t, h, d)), jnp.bfloat16
            )
            for _ in range(3)
        )

        fwd = jax.jit(lambda q, k, v: attn(q, k, v))
        loss_grad = jax.jit(
            jax.grad(
                lambda q, k, v: attn(q, k, v)
                .astype(jnp.float32)
                .sum(),
                argnums=(0, 1, 2),
            )
        )

        def timed(fn, args):
            out = fn(*args)
            _sync(jax.tree.leaves(out)[0])
            t0 = time.perf_counter()
            for _ in range(iters):
                out = fn(*args)
            _sync(jax.tree.leaves(out)[0])
            return (time.perf_counter() - t0) / iters * 1e3

        fwd_ms = timed(fwd, (q, k, v))
        # jax.grad re-runs the forward inside, so this IS fwd+bwd
        both_ms = timed(loss_grad, (q, k, v))
        fwd_flops = 2.0 * b * h * t * t * d  # 2 matmuls · ½ causal
        total_flops = fwd_flops * 3.5
        line = {
            "metric": "attn_seq_sweep",
            "engine": engine,
            "seq": t,
            "batch": b,
            "heads": h,
            "head_dim": d,
            "value": round(both_ms, 3),
            "unit": "ms",
            "fwd_ms": round(fwd_ms, 3),
            "fwd_tflops": round(fwd_flops / (fwd_ms / 1e3) / 1e12, 2),
            "tflops": round(total_flops / (both_ms / 1e3) / 1e12, 2),
            "platform": platform,
        }
        if platform != "tpu":
            line["note"] = _SIM_NOTE
        print(json.dumps(_stamp(line)), flush=True)

    for t in seqs:
        run(
            "flash",
            lambda q, k, v: flash_attention(q, k, v, causal=True),
            t,
        )
        if t <= dense_max:
            run("dense", dense, t)


if __name__ == "__main__":
    main()
