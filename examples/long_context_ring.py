"""Long-context training with the flash-block ring (sequence parallel).

The scaling story the reference cannot tell (Horovod is data-parallel
only — SURVEY.md §5.7): a context too long for ONE chip's memory,
sharded over the `sp` mesh axis, trained with EXACT attention. Each
hop of the ring runs the Pallas flash kernels on (q, k_hop, v_hop) and
merges the normalized partials online — per-chip attention memory is
O(T_local·Dh) + VMEM tiles, independent of the full context length; no
score matrix ever reaches HBM.

Run (8-way CPU simulation — interpret-mode kernels, logic only):
    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    JAX_PLATFORMS=cpu python examples/long_context_ring.py --seq-len 2048
Run (TPU slice): sp = number of chips; the same script, real kernels.
"""

import argparse
import os

import jax

# The sandbox's sitecustomize can force-select a TPU platform; honor an
# explicit JAX_PLATFORMS request at the config level (see tests/conftest.py).
if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from horovod_tpu.parallel import ring_flash_attention


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--seq-len", type=int, default=2048,
                        help="FULL context length (sharded over all devices)")
    parser.add_argument("--d-model", type=int, default=64)
    parser.add_argument("--heads", type=int, default=4)
    parser.add_argument("--steps", type=int, default=10)
    args = parser.parse_args()

    devices = jax.devices()
    sp = len(devices)
    if args.seq_len % sp:
        raise SystemExit(f"--seq-len must divide by {sp} devices")
    mesh = Mesh(np.asarray(devices), ("sp",))
    t_local = args.seq_len // sp
    d, h = args.d_model, args.heads
    hd = d // h
    print(f"{args.seq_len} tokens over {sp} chips -> {t_local}/chip")

    rng = np.random.default_rng(0)
    params = {
        "wqkv": jnp.asarray(rng.normal(size=(d, 3, h, hd)) * 0.05,
                            jnp.float32),
        "wo": jnp.asarray(rng.normal(size=(h, hd, d)) * 0.05, jnp.float32),
    }
    opt = optax.adam(1e-3)
    opt_state = opt.init(params)

    @jax.jit
    def train_step(params, opt_state, x, y):
        def loss_fn(p):
            def fwd(x, y):
                qkv = jnp.einsum("btd,dchx->btchx", x, p["wqkv"])
                q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
                a = ring_flash_attention(q, k, v, "sp", causal=True)
                out = jnp.einsum("bthx,hxd->btd", a, p["wo"])
                # mean over the GLOBAL sequence: local sum / global count
                err = jnp.sum((out - y) ** 2)
                return lax.psum(err, "sp") / (y.shape[0] * args.seq_len * d)

            return jax.shard_map(
                fwd, mesh=mesh,
                in_specs=(P(None, "sp"), P(None, "sp")),
                out_specs=P(),
                check_vma=False,
            )(x, y)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    x = jnp.asarray(rng.normal(size=(2, args.seq_len, d)), jnp.float32)
    y = jnp.roll(x, -1, axis=1)  # predict-next as a regression toy
    losses = []
    for _ in range(args.steps):
        params, opt_state, loss = train_step(params, opt_state, x, y)
        losses.append(float(loss))
    print(f"loss {losses[0]:.5f} -> {losses[-1]:.5f}")
    assert losses[-1] < losses[0], "training must reduce the loss"


if __name__ == "__main__":
    main()
