"""Adasum: scale-invariant gradient combination.

TPU-native rebuild of the reference's Adasum reducer
(ref: horovod/common/ops/adasum/adasum.h — the recursive
vector-halving-distance-doubling combiner — and adasum_mpi_operations.cc /
adasum_gpu_operations.cc [V], SURVEY.md §2.2).

The math (adasum.h [V]): two gradients a, b combine as

    adasum(a, b) = (1 - a·b / (2·‖a‖²)) · a  +  (1 - a·b / (2·‖b‖²)) · b

which removes each vector's projection onto the other before summing —
orthogonal gradients add, parallel gradients average, and the result is
invariant to rescaling either input. n ranks combine pairwise along a
binary tree (the reference's recursive halving-doubling).

The distributed algorithm is the reference's actual
vector-halving-distance-doubling (VHDD, adasum.h FusedAllreduce [V]):
stage k pairs rank r with r^2^k, the pair EXCHANGES HALVES of the
current piece (payload halves every stage), the three Adasum dot
products are completed by a 3-scalar ``psum`` over the 2^(k+1)-rank
block that jointly holds the two vectors, and the combine happens on
the half each rank kept. After log2(p) stages every rank owns 1/p of
the result; a distance-halving ``ppermute`` allgather reassembles it.

Wire bytes per rank (payload P): down sweep P/2 + P/4 + ... + P/p,
up sweep the same — ~2P(1-1/p) total, vs ~log2(p)·P for the naive
full-tensor XOR loop this replaced (at p=256: ~2P vs ~8P) — see
``vhdd_wire_bytes``. Non-power-of-two worlds pre-reduce the n-p excess
ranks into partners (one P-sized hop each way) exactly like
adasum_mpi_operations.cc [V], instead of materializing n·P via
all_gather. Dot products accumulate in float32 regardless of input
dtype, matching the reference's fp64/fp32 accumulation discipline.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
from jax import lax

from ..common.topology import WORLD_AXIS


def adasum_pair(a, b):
    """Combine two same-shaped gradient tensors by the Adasum rule.

    On TPU this dispatches to the two-pass Pallas kernel
    (ops/pallas_kernels.py — one VMEM traversal for the dots, one for
    the weighted sum); elsewhere the jnp formulation below is both the
    fallback and the numerics oracle the kernel is tested against."""
    import jax

    if jax.default_backend() == "tpu":
        from .pallas_kernels import adasum_pair as _pallas_pair

        return _pallas_pair(a, b)
    return _pair_f32(
        a.astype(jnp.float32), b.astype(jnp.float32)
    ).astype(a.dtype)


def _tree_combine(stack):
    """Pairwise-tree Adasum over a leading 'rank' axis. Odd counts carry the
    last element up a level (the reference pre-reduces to a power of two;
    same fixed combination order on every rank ⇒ deterministic)."""
    vals = list(stack)
    while len(vals) > 1:
        nxt = []
        for i in range(0, len(vals) - 1, 2):
            nxt.append(adasum_pair(vals[i], vals[i + 1]))
        if len(vals) % 2 == 1:
            nxt.append(vals[-1])
        vals = nxt
    return vals[0]


def adasum_allreduce(
    tensor,
    axis_name: str = WORLD_AXIS,
    process_set=None,
    groups: Optional[Sequence[Sequence[int]]] = None,
    hierarchical: bool = False,
    intra_axis: Optional[str] = None,
    inter_axis: Optional[str] = None,
    inter_wire: str = "fp32",
    seed: int = 0,
):
    """Adasum-allreduce across a mesh axis, for use inside jit/shard_map
    (ref: the Adasum path selected by hvd.DistributedOptimizer(op=hvd.Adasum)
    [V]). The full-axis path is VHDD (see module docstring); process sets
    keep the gather+tree formulation via a masked full-axis gather (XLA's
    TPU lowering rejects unequal replica groups, so a set+singletons
    partition can't be expressed with axis_index_groups) — sets are small
    by construction and correctness dominates there. Non-members return
    their input unchanged. ``groups`` (a single explicit rank list) is
    accepted for backward compatibility and treated like a process set.

    ``hierarchical=True`` is the reference's hierarchical Adasum
    (adasum_gpu_operations.cc [V]: NCCL sum within the node, Adasum
    across nodes) on the two-level scaffold, for use inside shard_map
    over a :func:`~horovod_tpu.ops.traced.hierarchical_mesh`: intra-axis
    SUM via reduce-scatter (each rank holds a 1/L shard of its slice's
    sum), then VHDD Adasum across the INTER axis on the shards — the
    three dot products of every combine are completed by an extra psum
    over the intra axis, so the math is the exact full-vector Adasum of
    the slice sums (host oracle: ``adasum_vhdd_host`` over per-slice
    sums) while every DCN hop moves 1/L of the bytes — then intra-axis
    all-gather. ``inter_wire='int8'`` additionally block-quantizes the
    VHDD half-exchanges with stochastic rounding (both sweeps; an owner
    consumes the self-dequantized value of any piece it kept, so all
    ranks still agree bit-for-bit); ``'bf16'`` casts them. Scale
    invariance survives any wire: Adasum's coefficients are computed on
    what actually arrived."""
    if hierarchical:
        if process_set is not None or groups is not None:
            raise NotImplementedError(
                "hierarchical Adasum composes with the full two-level "
                "mesh only (no process sets / explicit groups)"
            )
        from ..common.topology import INTER_AXIS, INTRA_AXIS

        return _hier_adasum(
            tensor,
            intra_axis or INTRA_AXIS,
            inter_axis or INTER_AXIS,
            inter_wire,
            seed,
        )
    ranks = None
    if process_set is not None and process_set.process_set_id != 0:
        ranks = list(process_set.ranks)
    elif groups is not None:
        member_groups = [g for g in groups if len(g) > 1]
        if len(member_groups) > 1:
            raise ValueError(
                "adasum_allreduce supports one member group per call"
            )
        if member_groups:
            ranks = list(member_groups[0])
    if ranks is not None and len(ranks) == int(lax.axis_size(axis_name)):
        ranks = None
    if ranks is not None:
        from ..common.process_sets import member_tables

        world = int(lax.axis_size(axis_name))
        mask, pos = member_tables(world, ranks)
        idx = lax.axis_index(axis_name)
        member = jnp.asarray(mask)[idx]
        p = jnp.asarray(pos)[idx]
        contrib = jnp.where(member, tensor, jnp.zeros_like(tensor))
        buf = jnp.zeros((len(ranks),) + tuple(tensor.shape), tensor.dtype)
        buf = lax.dynamic_update_slice_in_dim(buf, contrib[None], p, axis=0)
        gathered = lax.psum(buf, axis_name)
        out = _tree_combine([gathered[i] for i in range(len(ranks))])
        return jnp.where(member, out, tensor)
    n = lax.axis_size(axis_name)
    if n == 1:
        return tensor
    return _vhdd_allreduce(tensor, axis_name, n)


def _pair_f32(a, b):
    """The Adasum combine on float32 operands (no dtype round-trip) —
    the arithmetic core shared by the pre-reduction and the oracle."""
    dot = jnp.sum(a * b)
    asq = jnp.sum(a * a)
    bsq = jnp.sum(b * b)
    acoef = 1.0 - jnp.where(asq > 0, dot / (2.0 * asq), 0.0)
    bcoef = 1.0 - jnp.where(bsq > 0, dot / (2.0 * bsq), 0.0)
    return acoef * a + bcoef * b


def _hier_adasum(tensor, intra_axis, inter_axis, inter_wire, seed):
    """Intra Sum (reduce-scatter) -> VHDD Adasum across the inter axis
    on the 1/L shards (dots completed over intra) -> intra all-gather.
    See :func:`adasum_allreduce`'s ``hierarchical=True`` contract."""
    if inter_wire not in ("fp32", "bf16", "int8"):
        raise ValueError(f"unknown inter_wire {inter_wire!r}")
    L = int(lax.axis_size(intra_axis))
    H = int(lax.axis_size(inter_axis))
    shape, dtype = tensor.shape, tensor.dtype
    x = tensor.astype(jnp.float32).reshape(-1)
    m = x.shape[0]
    pad = (-m) % L
    if pad:
        x = jnp.concatenate([x, jnp.zeros((pad,), jnp.float32)])
    shard = lax.psum_scatter(
        x, intra_axis, scatter_dimension=0, tiled=True
    )  # 1/L of this slice's SUM
    if H > 1:
        shard = _vhdd_allreduce(
            shard, inter_axis, H, dot_axis=intra_axis,
            wire=inter_wire, seed=seed,
        ).reshape(-1)
    out = lax.all_gather(shard, intra_axis, tiled=True)
    return out[:m].reshape(shape).astype(dtype)


def _wire_exchange(send, perm, axis_name, wire, key):
    """One VHDD half-exchange over ``perm`` at the chosen wire.
    Returns ``(recv, self_wire)`` where ``self_wire`` is what the REST
    of the gang would reconstruct from this rank's transmission — an
    owner that keeps a piece must consume ``self_wire`` instead of the
    raw piece, or quantization would fork the replicas."""
    if wire == "fp32":
        return lax.ppermute(send, axis_name, perm), send
    if wire == "bf16":
        w = send.astype(jnp.bfloat16)
        return (
            lax.ppermute(w, axis_name, perm).astype(jnp.float32),
            w.astype(jnp.float32),
        )
    from .traced import _block_dequant, _stochastic_round_blocks

    block = min(512, max(send.shape[0], 1))
    q, s = _stochastic_round_blocks(send[None], block, key)
    self_deq = _block_dequant(q, s)[0][: send.shape[0]]
    rq = lax.ppermute(q, axis_name, perm)
    rs = lax.ppermute(s, axis_name, perm)
    recv = _block_dequant(rq, rs)[0][: send.shape[0]]
    return recv, self_deq


def _vhdd_allreduce(
    tensor, axis_name: str, n: int, dot_axis: Optional[str] = None,
    wire: str = "fp32", seed: int = 0,
):
    """Vector-halving distance-doubling Adasum over the full axis
    (ref: adasum.h FusedAllreduce + adasum_mpi_operations.cc [V]).

    ``dot_axis`` is the hierarchical extension: the operand is a
    1/L shard and every combine's three dot products are additionally
    ``psum``-completed over that axis, so the coefficients are the
    full-vector values (the intra members jointly hold the vector).
    ``wire`` ∈ {fp32, bf16, int8} applies to the half-exchanges of
    BOTH sweeps (the non-pow2 pre/post hops stay full precision —
    they exist only on unusual slice counts); owners consume the
    self-reconstructed wire value of any piece they kept, keeping
    replicas bit-identical under a lossy wire."""
    p = 1 << (n.bit_length() - 1)  # largest power of two <= n
    excess = n - p
    shape, dtype = tensor.shape, tensor.dtype
    r = lax.axis_index(axis_name)
    x = tensor.astype(jnp.float32).reshape(-1)
    payload = x.shape[0]
    pad = (-payload) % p  # so every halving stage splits evenly
    if pad:
        x = jnp.concatenate([x, jnp.zeros((pad,), jnp.float32)])
    key = jax.random.PRNGKey(seed) if wire == "int8" else None

    def _dots(dot, nk, nr):
        if dot_axis is None:
            return dot, nk, nr
        s = lax.psum(jnp.stack([dot, nk, nr]), dot_axis)
        return s[0], s[1], s[2]

    if excess:
        # Pre-reduction: ranks [p, n) fold their vector into partner
        # r-p, then sit out; results are sent back at the end. One
        # P-sized hop each way — not the all_gather n·P blowup.
        recv = lax.ppermute(
            x, axis_name, [(p + i, i) for i in range(excess)]
        )
        if dot_axis is None:
            x = jnp.where(r < excess, _pair_f32(x, recv), x)
        else:
            dot, asq, bsq = _dots(
                jnp.sum(x * recv), jnp.sum(x * x), jnp.sum(recv * recv)
            )
            acoef = 1.0 - jnp.where(asq > 0, dot / (2.0 * asq), 0.0)
            bcoef = 1.0 - jnp.where(bsq > 0, dot / (2.0 * bsq), 0.0)
            x = jnp.where(r < excess, acoef * x + bcoef * recv, x)

    stages = p.bit_length() - 1  # log2(p)
    piece = x
    for k in range(stages):
        d = 1 << k
        h = piece.shape[0] // 2
        low, high = piece[:h], piece[h:]
        bit = (r & d) != 0
        # bit clear: keep low, send high; bit set: keep high, send low.
        # The partner does the opposite, so each side receives exactly
        # the partner's piece for the half it kept.
        send = jnp.where(bit, low, high)
        keep = jnp.where(bit, high, low)
        perm = [(i, i ^ d) for i in range(p)]
        recv, _ = _wire_exchange(
            send, perm, axis_name, wire,
            None
            if key is None
            else jax.random.fold_in(jax.random.fold_in(key, 100 + k), r),
        )
        # Complete the three dots over the 2d-rank block that jointly
        # holds both vectors ('a' = the bit-clear side's vector).
        dot = jnp.sum(keep * recv)
        nk = jnp.sum(keep * keep)
        nr = jnp.sum(recv * recv)
        scal = jnp.stack(
            [dot, jnp.where(bit, nr, nk), jnp.where(bit, nk, nr)]
        )
        blocks = [
            list(range(g * 2 * d, (g + 1) * 2 * d))
            for g in range(p // (2 * d))
        ]
        if excess:
            # Unequal replica groups (2d-blocks + excess singletons) don't
            # lower on TPU; the scalars are tiny, so all_gather them and
            # select each rank's block sum with a static 0/1 matrix row.
            import numpy as np

            bmat = np.zeros((n, n), np.float32)
            for g in blocks:
                for a in g:
                    for b in g:
                        bmat[a, b] = 1.0
            for i in range(p, n):
                bmat[i, i] = 1.0
            gathered = lax.all_gather(scal, axis_name)  # [n, 3]
            tot = jnp.asarray(bmat)[r] @ gathered
        else:
            tot = lax.psum(scal, axis_name, axis_index_groups=blocks)
        if dot_axis is not None:
            # hierarchical completion: the 2d-block holds only 1/L of
            # each vector — finish the dots across the intra axis
            tot = lax.psum(tot, dot_axis)
        dot_t, asq, bsq = tot[0], tot[1], tot[2]
        acoef = 1.0 - jnp.where(asq > 0, dot_t / (2.0 * asq), 0.0)
        bcoef = 1.0 - jnp.where(bsq > 0, dot_t / (2.0 * bsq), 0.0)
        piece = (
            jnp.where(bit, bcoef, acoef) * keep
            + jnp.where(bit, acoef, bcoef) * recv
        )

    # Distance-halving allgather: reassemble the full vector. Under a
    # lossy wire the kept half is replaced by its self-reconstructed
    # wire value — every rank then assembles identical bits whether a
    # piece arrived over the wire or stayed home.
    for k in reversed(range(stages)):
        d = 1 << k
        perm = [(i, i ^ d) for i in range(p)]
        # Key by the piece's EQUIVALENCE CLASS, not the rank: after the
        # up-stages already run (distances > d), ranks equal mod 2d
        # hold identical pieces — they must emit identical wire bits,
        # or two receivers of "the same" piece would reconstruct
        # different stochastic roundings and fork the replicas.
        recv, self_wire = _wire_exchange(
            piece, perm, axis_name, wire,
            None
            if key is None
            else jax.random.fold_in(
                jax.random.fold_in(key, 200 + k), r & (2 * d - 1)
            ),
        )
        bit = (r & d) != 0
        piece = jnp.concatenate(
            [jnp.where(bit, recv, self_wire),
             jnp.where(bit, self_wire, recv)]
        )

    if excess:
        back = lax.ppermute(
            piece, axis_name, [(i, p + i) for i in range(excess)]
        )
        piece = jnp.where(r >= p, back, piece)
    if pad:
        piece = piece[:payload]
    return piece.reshape(shape).astype(dtype)


def adasum_allreduce_groups(
    tensor,
    axis_name: str = WORLD_AXIS,
    stages=None,
    inter_wire: str = "fp32",
    seed: int = 0,
    residual=None,
    return_residual: bool = False,
):
    """Hierarchical Adasum on the FLAT axis via replica groups — the
    local-SGD sync-round combiner (``topology.hierarchy_stages()``
    layout: rank ``r = h·L + i`` is slice ``h``, intra position ``i``).

    Contract: ``tensor`` is the SLICE's value (the parameter delta
    since the last round), replicated across the slice's L ranks —
    local-phase training keeps it so by construction. Each rank takes
    its intra-position chunk (a static slice, NO collective — the
    replication pays for itself here), the H slice values combine by
    VHDD Adasum across the inter groups with every dot product
    completed over the intra groups (exact full-vector coefficients),
    and an intra all-gather reassembles the merged result. DCN bytes
    per rank ≈ ``vhdd_wire_bytes(H, payload/L)`` — 1/L of the full
    payload halving-doubled across slices, times ~4x less again at
    ``inter_wire='int8'``.

    Error feedback (``inter_wire='int8'`` + ``return_residual=True``):
    the carry joins the chunk BEFORE the wire (``x_eff = chunk +
    residual_chunk``), the chunk is pre-quantized through the same
    block quantizer the wire uses, and ``residual' = x_eff −
    dequant(quant(x_eff))`` comes back FULL-geometry (intra
    all-gathered, so every rank of a slice holds the identical carry
    and the state stays replicated-consistent across topology
    changes). Conservation is bit-exact by construction:
    ``quantized + residual' == delta + residual``. The VHDD's own
    half-exchange roundings on intermediate COMBINED pieces are
    zero-mean stochastic noise outside the carry — EF bounds each
    slice's contribution error across rounds (docs/design.md,
    "semi-synchronous training")."""
    if stages is None:
        raise ValueError("stages is required (topology.hierarchy_stages)")
    if inter_wire not in ("fp32", "bf16", "int8"):
        raise ValueError(f"unknown inter_wire {inter_wire!r}")
    if return_residual and inter_wire != "int8":
        raise ValueError(
            "return_residual needs inter_wire='int8' (exact wires "
            "transmit everything; there is no residual to carry)"
        )
    intra_groups, inter_groups = stages
    L = len(intra_groups[0])
    H = len(inter_groups[0])
    shape, dtype = tensor.shape, tensor.dtype
    x = tensor.astype(jnp.float32).reshape(-1)
    m = x.shape[0]
    p = 1 << (H.bit_length() - 1)  # VHDD power-of-two core
    # pad so the per-rank chunk splits evenly across every halving stage
    unit = L * max(p, 1)
    pad = (-m) % unit
    if pad:
        x = jnp.concatenate([x, jnp.zeros((pad,), jnp.float32)])
    chunk = x.shape[0] // L
    idx = lax.axis_index(axis_name)
    from ..common.topology import stage_positions

    pos = jnp.asarray(stage_positions(intra_groups))[idx]
    piece = lax.dynamic_slice(x, (pos * chunk,), (chunk,))
    r_piece = None
    if residual is not None:
        r_flat = residual.astype(jnp.float32).reshape(-1)
        if pad:
            r_flat = jnp.concatenate(
                [r_flat, jnp.zeros((pad,), jnp.float32)]
            )
        r_piece = lax.dynamic_slice(r_flat, (pos * chunk,), (chunk,))
    want_res = return_residual and inter_wire == "int8"
    # ONE shard-level core serves both the replicated and the sharded
    # optimizers (adasum_sync_shard): pre-quantization is keyed by the
    # intra POSITION here — slice replicas hold identical chunks and
    # must pre-quantize identically, or the replicas would fork
    out = adasum_sync_shard(
        piece, stages, axis_name=axis_name, inter_wire=inter_wire,
        seed=seed, residual=r_piece, return_residual=want_res,
        key_index=pos,
    )
    if want_res:
        out, res_piece = out
        new_res = lax.all_gather(
            res_piece, axis_name, tiled=True,
            axis_index_groups=intra_groups,
        )[:m].reshape(shape).astype(dtype)
    else:
        new_res = None
    out = lax.all_gather(
        out, axis_name, tiled=True, axis_index_groups=intra_groups
    )[:m].reshape(shape).astype(dtype)
    if not return_residual:
        return out
    if new_res is None:
        new_res = jnp.zeros(shape, dtype)
    return out, new_res


def adasum_sync_shard(
    shard,
    stages,
    axis_name: str = WORLD_AXIS,
    inter_wire: str = "int8",
    seed=0,
    residual=None,
    return_residual: bool = False,
    key_index=None,
):
    """The shard-level local-SGD sync core — the ONE home of the
    EF-pre-quantization + pad + grouped-VHDD contract
    (:func:`adasum_allreduce_groups` delegates here for the replicated
    case; ``ShardedDistributedOptimizer.sync_round`` calls it directly
    on its intra-position shards). ``shard`` is this rank's ``[cols]``
    chunk of its slice's delta vector; the merged chunk comes back in
    the same geometry. With ``residual``/``return_residual`` (int8
    wire) the carry satisfies ``quantized + residual' == shard +
    residual`` bit-exactly. ``key_index`` overrides the
    pre-quantization RNG fold (default: the rank index); the
    replicated caller passes the intra POSITION so slice replicas
    holding identical chunks pre-quantize identically."""
    if stages is None:
        raise ValueError("stages is required (topology.hierarchy_stages)")
    if inter_wire not in ("fp32", "bf16", "int8"):
        raise ValueError(f"unknown inter_wire {inter_wire!r}")
    if return_residual and inter_wire != "int8":
        raise ValueError(
            "return_residual needs inter_wire='int8' (exact wires "
            "transmit everything)"
        )
    intra_groups, inter_groups = stages
    L = len(intra_groups[0])
    H = len(inter_groups[0])
    c = shard.shape[0]
    x = shard.astype(jnp.float32)
    new_res = None
    if inter_wire == "int8" and (residual is not None or return_residual):
        from .traced import _block_dequant, _stochastic_round_blocks

        if residual is not None:
            x = x + residual.astype(jnp.float32)
        fold = (
            lax.axis_index(axis_name) if key_index is None else key_index
        )
        key = jax.random.fold_in(
            jax.random.fold_in(jax.random.PRNGKey(seed), 3571), fold
        )
        # EF pre-quantization: what enters the combine IS the wire
        # resolution of this slice's signal; the carry is exactly what
        # the wire could not represent this round
        block = min(512, max(c, 1))
        q, s = _stochastic_round_blocks(x[None], block, key)
        q_x = _block_dequant(q, s)[0][:c]
        if return_residual:
            new_res = (x - q_x).astype(shard.dtype)
        x = q_x
    p = 1 << (H.bit_length() - 1)
    pad = (-c) % max(p, 1)
    if pad:
        x = jnp.concatenate([x, jnp.zeros((pad,), jnp.float32)])
    if H > 1:
        x = _vhdd_grouped(x, axis_name, L, H, inter_wire, seed)
    out = x[:c].astype(shard.dtype)
    if not return_residual:
        return out
    if new_res is None:
        new_res = jnp.zeros_like(shard)
    return out, new_res


def _vhdd_grouped(piece, axis_name: str, L: int, H: int, wire: str, seed):
    """VHDD Adasum across the INTER groups of the contiguous two-level
    layout (rank ``r = h·L + i``): the :func:`_vhdd_allreduce` dataflow
    with slice index ``h`` playing the rank role, every half-exchange a
    flat ``ppermute`` between same-intra-position ranks of partner
    slices, and every combine's three dot products completed over the
    (2d-slice-block × intra) replica groups — the full-vector Adasum of
    the slice values whose chunks the intra members jointly hold.
    ``piece`` is this rank's intra-position chunk; chunk length must be
    divisible by the power-of-two slice core (callers pad)."""
    p = 1 << (H.bit_length() - 1)
    excess = H - p
    world = L * H
    idx = lax.axis_index(axis_name)
    h = idx // L
    x = piece
    key = jax.random.PRNGKey(seed) if wire == "int8" else None

    def _block_dots(scal, d):
        """Complete [dot, nk, nr] over the 2d-slice block × intra."""
        if not excess:
            groups = [
                [hb * L + i2
                 for hb in range(g * 2 * d, (g + 1) * 2 * d)
                 for i2 in range(L)]
                for g in range(p // (2 * d))
            ]
            return lax.psum(scal, axis_name, axis_index_groups=groups)
        # unequal groups (blocks + excess singleton slices) don't lower
        # on TPU; the scalars are tiny — all_gather + static 0/1 row
        import numpy as np

        bmat = np.zeros((world, world), np.float32)
        for g in range(p // (2 * d)):
            hs = range(g * 2 * d, (g + 1) * 2 * d)
            ranks = [hb * L + i2 for hb in hs for i2 in range(L)]
            for a in ranks:
                for b in ranks:
                    bmat[a, b] = 1.0
        for r2 in range(p * L, world):
            bmat[r2, r2] = 1.0
        gathered = lax.all_gather(scal, axis_name)  # [world, 3]
        return jnp.asarray(bmat)[idx] @ gathered

    if excess:
        # pre-reduction: slices [p, H) fold into partner h-p chunk-wise;
        # dots completed via the static-matrix path (pair × intra)
        perm = [
            ((p + e) * L + i, e * L + i)
            for e in range(excess)
            for i in range(L)
        ]
        recv = lax.ppermute(x, axis_name, perm)
        import numpy as np

        bmat = np.zeros((world, world), np.float32)
        for e in range(excess):
            ranks = [e * L + i for i in range(L)]
            for a in ranks:
                for b in ranks:
                    bmat[a, b] = 1.0
        for r2 in range(world):
            if bmat[r2, r2] == 0.0:
                bmat[r2, r2] = 1.0
        scal = jnp.stack(
            [jnp.sum(x * recv), jnp.sum(x * x), jnp.sum(recv * recv)]
        )
        tot = jnp.asarray(bmat)[idx] @ lax.all_gather(scal, axis_name)
        dot, asq, bsq = tot[0], tot[1], tot[2]
        acoef = 1.0 - jnp.where(asq > 0, dot / (2.0 * asq), 0.0)
        bcoef = 1.0 - jnp.where(bsq > 0, dot / (2.0 * bsq), 0.0)
        x = jnp.where(h < excess, acoef * x + bcoef * recv, x)

    stages_n = p.bit_length() - 1  # log2(p)
    for k in range(stages_n):
        d = 1 << k
        half = x.shape[0] // 2
        low, high = x[:half], x[half:]
        bit = (h & d) != 0
        send = jnp.where(bit, low, high)
        keep = jnp.where(bit, high, low)
        perm = [
            (hh * L + i, (hh ^ d) * L + i)
            for hh in range(p)
            for i in range(L)
        ]
        recv, _ = _wire_exchange(
            send, perm, axis_name, wire,
            None
            if key is None
            else jax.random.fold_in(jax.random.fold_in(key, 100 + k), idx),
        )
        dot = jnp.sum(keep * recv)
        nk = jnp.sum(keep * keep)
        nr = jnp.sum(recv * recv)
        scal = jnp.stack(
            [dot, jnp.where(bit, nr, nk), jnp.where(bit, nk, nr)]
        )
        tot = _block_dots(scal, d)
        dot_t, asq, bsq = tot[0], tot[1], tot[2]
        acoef = 1.0 - jnp.where(asq > 0, dot_t / (2.0 * asq), 0.0)
        bcoef = 1.0 - jnp.where(bsq > 0, dot_t / (2.0 * bsq), 0.0)
        x = (
            jnp.where(bit, bcoef, acoef) * keep
            + jnp.where(bit, acoef, bcoef) * recv
        )

    for k in reversed(range(stages_n)):
        d = 1 << k
        perm = [
            (hh * L + i, (hh ^ d) * L + i)
            for hh in range(p)
            for i in range(L)
        ]
        # key by the piece's equivalence class: slices equal mod 2d at
        # the same intra position hold identical pieces and must emit
        # identical wire bits (the flat VHDD's fork-prevention rule,
        # extended by the intra coordinate)
        recv, self_wire = _wire_exchange(
            x, perm, axis_name, wire,
            None
            if key is None
            else jax.random.fold_in(
                jax.random.fold_in(key, 200 + k),
                (h & (2 * d - 1)) * L + (idx - h * L),
            ),
        )
        bit = (h & d) != 0
        x = jnp.concatenate(
            [jnp.where(bit, recv, self_wire),
             jnp.where(bit, self_wire, recv)]
        )

    if excess:
        back = lax.ppermute(
            x, axis_name,
            [(e * L + i, (p + e) * L + i)
             for e in range(excess)
             for i in range(L)],
        )
        x = jnp.where(h >= p, back, x)
    return x


def vhdd_wire_bytes(n: int, payload_bytes: int) -> int:
    """Modeled per-rank wire bytes of one VHDD Adasum (both sweeps +
    non-pow2 pre/post hops, excess ranks' worst case) — the ~2P claim,
    testable."""
    p = 1 << (n.bit_length() - 1)
    halving = sum(payload_bytes >> (k + 1) for k in range(p.bit_length() - 1))
    pre_post = 2 * payload_bytes if n != p else 0
    return 2 * halving + pre_post


# ---- host-side variants (ref: the reference's CPU Adasum path,
# adasum_mpi_operations.cc [V]) — native C++ when built, numpy fallback.
# These are the numerics oracle for the on-device path above and serve
# host-resident tensors (elastic state reconciliation, eager numpy).

def adasum_pair_host(a, b):
    """Adasum combine of two host arrays (numpy in, numpy out)."""
    import numpy as np

    try:
        from .._native import loader as _native

        out = _native.adasum_pair(np.asarray(a), np.asarray(b))
        if out is not None:
            return out.astype(np.asarray(a).dtype)
    except Exception:
        pass
    af = np.asarray(a, dtype=np.float64)
    bf = np.asarray(b, dtype=np.float64)
    dot = float((af * bf).sum())
    asq = float((af * af).sum())
    bsq = float((bf * bf).sum())
    acoef = 1.0 - (dot / (2.0 * asq) if asq > 0 else 0.0)
    bcoef = 1.0 - (dot / (2.0 * bsq) if bsq > 0 else 0.0)
    return (acoef * af + bcoef * bf).astype(np.asarray(a).dtype)


def adasum_vhdd_host(stack):
    """Host oracle for the distributed VHDD path: same combination
    order — excess ranks pre-reduce into partners (rank p+i → i), then
    an adjacent-pair binary tree over the power-of-two remainder."""
    import numpy as np

    vals = [np.asarray(stack[i]) for i in range(len(stack))]
    n = len(vals)
    p = 1 << (n.bit_length() - 1)
    for i in range(n - p):
        vals[i] = adasum_pair_host(vals[i], vals[p + i])
    vals = vals[:p]
    while len(vals) > 1:
        vals = [
            adasum_pair_host(vals[i], vals[i + 1])
            for i in range(0, len(vals), 2)
        ]
    return vals[0]


def adasum_tree_host(stack):
    """Pairwise-tree Adasum over ``stack[k, ...]`` host arrays — same
    combination order as ``_tree_combine`` (odd counts carry the last
    element up a level)."""
    import numpy as np

    stack = np.asarray(stack)
    try:
        from .._native import loader as _native

        out = _native.adasum_tree(stack)
        if out is not None:
            return out.astype(stack.dtype)
    except Exception:
        pass
    vals = [stack[i] for i in range(stack.shape[0])]
    while len(vals) > 1:
        nxt = [
            adasum_pair_host(vals[i], vals[i + 1])
            for i in range(0, len(vals) - 1, 2)
        ]
        if len(vals) % 2 == 1:
            nxt.append(vals[-1])
        vals = nxt
    return vals[0]
