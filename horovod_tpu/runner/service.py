"""Signed request/response RPC between driver and workers.

Rebuild of the reference's service plumbing (ref:
horovod/runner/common/service/*.py + common/util/{secret,codec,network}.py
[V] — SURVEY.md §2.5): length-prefixed payloads over TCP, authenticated
with the per-job HMAC secret. Differences by design: the wire format is
JSON, not pickle — pickle-over-TCP executes arbitrary code on
deserialization and the HMAC is the only thing standing between that and
an RCE; JSON carries everything these services actually exchange.

Frame format (both directions):
    4-byte big-endian length | 32-byte HMAC-SHA256 | JSON payload
The digest covers the JSON payload bytes.
"""

from __future__ import annotations

import json
import socket
import socketserver
import struct
import threading
from typing import Any, Callable, Dict, Optional

from .secret import DIGEST_BYTES, sign, verify
from ..common.retry import RetryPolicy
from ..testing import chaos as _chaos

_LEN = struct.Struct(">I")
MAX_FRAME = 64 * 1024 * 1024


def _read_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed mid-frame")
        buf += chunk
    return buf


def _send_frame(sock: socket.socket, key: bytes, obj: Any) -> None:
    payload = json.dumps(obj).encode()
    sock.sendall(_LEN.pack(len(payload)) + sign(key, payload) + payload)


def _recv_frame(sock: socket.socket, key: bytes) -> Any:
    (length,) = _LEN.unpack(_read_exact(sock, _LEN.size))
    if length > MAX_FRAME:
        raise ValueError(f"frame of {length} bytes exceeds limit")
    digest = _read_exact(sock, DIGEST_BYTES)
    payload = _read_exact(sock, length)
    if not verify(key, payload, digest):
        raise PermissionError("bad HMAC digest on RPC frame")
    return json.loads(payload)


class BasicService:
    """TCP server dispatching ``{"type": ...}`` requests to handlers.

    Mirrors the reference's ``network.BasicService`` shape: subclass (or
    register handlers), each request gets one response dict [V].
    """

    def __init__(self, name: str, secret_key: bytes, port: int = 0) -> None:
        self.name = name
        self._key = secret_key
        self._handlers: Dict[str, Callable[[dict], dict]] = {}
        outer = self

        class _Handler(socketserver.BaseRequestHandler):
            def handle(self) -> None:
                try:
                    # ``service.server`` injection site: reset/timeout
                    # tear the connection down before the frame is read
                    # (the client's RetryPolicy must absorb it); a 5xx
                    # is answered as a structured transient error below.
                    _chaos.inject("service.server")
                except _chaos.InjectedServerError as e:
                    try:
                        request = _recv_frame(self.request, outer._key)
                        _send_frame(
                            self.request, outer._key,
                            {"ok": False, "error": str(e), "retryable": True},
                        )
                    except (PermissionError, ValueError, ConnectionError):
                        pass
                    return
                except (ConnectionResetError, TimeoutError):
                    return  # abrupt close: client sees a dropped frame
                try:
                    request = _recv_frame(self.request, outer._key)
                except (PermissionError, ValueError, ConnectionError):
                    return  # unauthenticated/garbage: drop silently
                response = outer._dispatch(request)
                try:
                    _send_frame(self.request, outer._key, response)
                except ConnectionError:
                    pass

        class _Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = _Server(("0.0.0.0", port), _Handler)
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._server.server_address[1]

    def register(self, request_type: str, fn: Callable[[dict], dict]) -> None:
        self._handlers[request_type] = fn

    def _dispatch(self, request: dict) -> dict:
        rtype = request.get("type")
        fn = self._handlers.get(rtype)
        if fn is None:
            return {"ok": False, "error": f"unknown request type {rtype!r}"}
        try:
            out = fn(request)
            return {"ok": True, **(out or {})}
        except Exception as e:  # noqa: BLE001 — report, don't kill the server
            return {"ok": False, "error": f"{type(e).__name__}: {e}"}

    def start(self) -> int:
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            name=f"hvd-service-{self.name}",
            daemon=True,
        )
        self._thread.start()
        return self.port

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None


class BasicClient:
    """One-request-per-connection client, mirroring the reference's
    ``network.BasicClient`` [V].

    Requests run under the shared ``RetryPolicy`` (site
    ``service.client``): connection resets, timeouts, and transient
    server errors (a response carrying ``retryable: true``) are
    re-sent with jittered backoff; a peer whose rounds keep exhausting
    trips the circuit breaker and subsequent requests fail fast with
    ``CircuitOpenError``. Callers must only send idempotent requests
    through this client — every service in the repo (notifications,
    heartbeats, shutdown pings) is."""

    def __init__(
        self,
        addr: str,
        port: int,
        secret_key: bytes,
        timeout: float = 30.0,
        retry: Optional[RetryPolicy] = None,
    ) -> None:
        self._addr = addr
        self._port = port
        self._key = secret_key
        self._timeout = timeout
        self._retry = retry or RetryPolicy.from_env(
            "service.client", attempt_timeout_s=timeout
        )

    def _request_once(self, obj: dict) -> dict:
        _chaos.inject("service.client")
        with socket.create_connection(
            (self._addr, self._port), timeout=self._timeout
        ) as sock:
            _send_frame(sock, self._key, obj)
            response = _recv_frame(sock, self._key)
        if isinstance(response, dict) and response.get("retryable"):
            raise _TransientServiceError(response.get("error", "transient"))
        return response

    def request(self, obj: dict) -> dict:
        return self._retry.call(
            self._request_once, obj, peer=f"{self._addr}:{self._port}"
        )


class _TransientServiceError(ConnectionError):
    """A structured 'try again' from the server (``retryable: true`` in
    the response) — the RPC analog of an HTTP 503."""

    retryable = True
