#!/usr/bin/env bash
# CI entrypoint (ref: the reference's buildkite pipeline,
# .buildkite/gen-pipeline.sh + docker test matrix [V], SURVEY.md §2.7 —
# scaled to this repo: one host, no docker matrix, same three gates).
#
#   1. lint        — compile-level hygiene over the package and tests
#   2. native+TSAN — csrc/ builds clean AND passes a ThreadSanitizer
#                    stress of its concurrent pieces (SURVEY.md §5.2)
#   3. tests       — the full CPU suite on the virtual 8-device mesh
#   4. bench-smoke — bench_fusion.py dryrun: the fusion A/B measurement
#                    harness (host-pack vs in-JIT, bucketing, gather
#                    fusion) must run green and emit per-leg artifacts,
#                    so the engine's premise-measurement can't rot
#   5. telemetry-smoke — 5-step CPU loop with the live /metrics
#                    endpoint on an ephemeral port: Prometheus scrape
#                    (step p50/p95 + registry gauges) and the
#                    flight-recorder JSON-lines dump must both work
#   6. serve-smoke — scripts/serve_smoke.py: a 2-worker inference
#                    fleet on a toy transformer — concurrent
#                    mixed-length prompts routed through the
#                    rendezvous-KV capacity announcements, TTFT/TPOT
#                    quantiles + slot gauges asserted on the live
#                    /metrics scrape; then a role-split fleet (1
#                    prefill + 2 decode workers) streams KV pages over
#                    the transfer wire with per-role routing asserted
#                    on live scrapes — this is also the TRACE-SMOKE
#                    gate: with HOROVOD_TRACE=1 a crafted traceparent
#                    must round-trip as X-Trace-Id and one routed
#                    request must assemble (trace_assemble over live
#                    /traces scrapes) into a single skew-corrected
#                    trace covering router->prefill->transfer->decode
#                    in monotonic order — then one decode worker is
#                    SIGTERMed mid-burst (reservations fail over);
#                    finally SIGTERM the unified workers and assert
#                    the drain completed every accepted request (exit
#                    143) — the serving plane can't silently rot
#   7. audit-smoke — scripts/hlo_audit.py: the lowered-program
#                    invariant catalog over the canonical roster
#                    (fused fp32/int8 wire, overlap buckets, ZeRO-2/3,
#                    guard overhead, two-level + MoE routing, serve
#                    donation/compile budget) must run green AND the
#                    auditor must exit nonzero on a deliberately
#                    broken invariant (int8 forced onto an intra hop)
#                    — an auditor that cannot fail is not evidence
#   8. chaos-smoke — scripts/chaos_smoke.py: an integrity drill (one
#                    injected NaN training step that the grad guard
#                    must SKIP and count, one injected checkpoint
#                    bitflip that digest verification must bypass via
#                    fallback restore, both asserted over the live
#                    /metrics scrape) followed by a short
#                    multi-process elastic job under a seeded
#                    FaultPlan (one KV connection reset per worker +
#                    one mid-run worker SIGKILL) that must complete
#                    with exactly one gang restart and nonzero
#                    retry.* counters scraped from the live /metrics
#                    endpoint — neither the chaos hardening nor the
#                    integrity plane can silently rot; the serve
#                    failover drill runs with tracing ON and asserts
#                    hedge/replay legs as tagged sibling spans plus a
#                    live-migrated request assembling into one
#                    connected trace spanning >= 3 processes
#
# Usage: ./ci.sh [lint|native|tests|bench-smoke|telemetry-smoke|serve-smoke|audit-smoke|chaos-smoke|all]
# (default: all)

set -euo pipefail
cd "$(dirname "$0")"

step() { printf '\n=== %s ===\n' "$*"; }

lint() {
  step "lint: AST-based convention lint (scripts/lint.py)"
  # scripts/lint.py parses every file (so it subsumes compileall's
  # syntax check) and enforces the repo conventions: no os.environ
  # reads outside common/config.py (the basics.live_config() contract),
  # no bare except, no unused imports, no jax.debug.callback outside
  # the approved guard/telemetry sites.
  python scripts/lint.py
  # Import must succeed without TPU hardware.
  JAX_PLATFORMS=cpu python -c "import horovod_tpu"
}

native() {
  step "native: release build"
  make -C csrc clean >/dev/null
  make -C csrc
  step "native: ThreadSanitizer stress (kvstore + timeline)"
  local tsan_bin
  tsan_bin="$(mktemp -d)/tsan_stress"
  g++ -std=c++17 -g -O1 -fsanitize=thread -pthread \
    csrc/timeline.cc csrc/kvstore.cc csrc/sha256.cc csrc/tsan_stress.cc \
    -o "$tsan_bin"
  TSAN_OPTIONS="halt_on_error=1" "$tsan_bin"
  step "native: AddressSanitizer stress (same driver)"
  local asan_bin
  asan_bin="$(mktemp -d)/asan_stress"
  g++ -std=c++17 -g -O1 -fsanitize=address,undefined -pthread \
    csrc/timeline.cc csrc/kvstore.cc csrc/sha256.cc csrc/tsan_stress.cc \
    -o "$asan_bin"
  ASAN_OPTIONS="halt_on_error=1" "$asan_bin"
}

tests() {
  step "tests: full CPU suite (8-device virtual mesh)"
  python -m pytest tests/ -q
}

bench_smoke() {
  step "bench-smoke: bench_fusion.py dryrun (A/B harness + artifacts)"
  local art_dir
  art_dir="$(mktemp -d)"
  JAX_PLATFORMS=cpu XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    BENCH_PLATFORM=cpu BENCH_DRYRUN=1 BENCH_ARTIFACT_DIR="$art_dir" \
    python bench_fusion.py
  # the A/B legs must have produced their per-leg JSON artifacts
  for leg in ab_pack ab_bucketing ab_gather; do
    test -s "$art_dir/fusion_${leg}.json" \
      || { echo "missing artifact: fusion_${leg}.json" >&2; exit 1; }
  done
  step "bench-smoke: bench_int8.py dryrun (fused-vs-per-tensor leg)"
  JAX_PLATFORMS=cpu XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    BENCH_PLATFORM=cpu BENCH_DRYRUN=1 BENCH_ARTIFACT_DIR="$art_dir" \
    python bench_int8.py
  test -s "$art_dir/int8_ab_fused.json" \
    || { echo "missing artifact: int8_ab_fused.json" >&2; exit 1; }
  step "bench-smoke: bench_overlap.py dryrun (bucketed-exchange A/B)"
  JAX_PLATFORMS=cpu XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    BENCH_PLATFORM=cpu BENCH_DRYRUN=1 BENCH_ARTIFACT_DIR="$art_dir" \
    python bench_overlap.py
  for leg in ab_monolithic ab_bucketed ab_bucketed_rs; do
    test -s "$art_dir/overlap_${leg}.json" \
      || { echo "missing artifact: overlap_${leg}.json" >&2; exit 1; }
  done
  step "bench-smoke: bench_zero.py dryrun (ZeRO-1/2/3 A/B + live-buffer gate)"
  JAX_PLATFORMS=cpu XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    BENCH_PLATFORM=cpu BENCH_DRYRUN=1 BENCH_ARTIFACT_DIR="$art_dir" \
    python bench_zero.py
  for leg in ab_zero1 ab_zero2 ab_zero3; do
    test -s "$art_dir/zero_${leg}.json" \
      || { echo "missing artifact: zero_${leg}.json" >&2; exit 1; }
  done
  step "bench-smoke: bench_hier.py dryrun (two-level wire A/B + DCN-byte gate)"
  JAX_PLATFORMS=cpu XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    BENCH_PLATFORM=cpu BENCH_DRYRUN=1 BENCH_ARTIFACT_DIR="$art_dir" \
    python bench_hier.py
  for leg in ab_flat ab_hier ab_hier_int8; do
    test -s "$art_dir/hier_${leg}.json" \
      || { echo "missing artifact: hier_${leg}.json" >&2; exit 1; }
  done
  step "bench-smoke: bench_moe.py dryrun (expert-wire A/B + DCN-byte + capacity-tuner gates)"
  JAX_PLATFORMS=cpu XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    BENCH_PLATFORM=cpu BENCH_DRYRUN=1 BENCH_ARTIFACT_DIR="$art_dir" \
    python bench_moe.py
  for leg in ab_flat ab_hier_int8 ab_captuned; do
    test -s "$art_dir/moe_${leg}.json" \
      || { echo "missing artifact: moe_${leg}.json" >&2; exit 1; }
  done
  step "bench-smoke: bench_lm.py ab_local_sgd dryrun (K=1 vs K=8 inter-byte + loss-parity gates)"
  JAX_PLATFORMS=cpu XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    BENCH_PLATFORM=cpu BENCH_DRYRUN=1 BENCH_AB=local_sgd \
    BENCH_ARTIFACT_DIR="$art_dir" \
    python bench_lm.py
  for leg in k1 k8; do
    test -s "$art_dir/lm_ab_local_sgd_${leg}.json" \
      || { echo "missing artifact: lm_ab_local_sgd_${leg}.json" >&2; exit 1; }
  done
  step "bench-smoke: bench_serve.py dryrun (static-vs-continuous + paged-KV + prefix-cache + disaggregated + paged-attention + warm-cache + failover A/B)"
  JAX_PLATFORMS=cpu \
    BENCH_PLATFORM=cpu BENCH_DRYRUN=1 BENCH_ARTIFACT_DIR="$art_dir" \
    python bench_serve.py
  for leg in static continuous paged prefix disagg paged_attn warm_cache \
             failover; do
    test -s "$art_dir/serve_ab_${leg}.json" \
      || { echo "missing artifact: serve_ab_${leg}.json" >&2; exit 1; }
  done
  echo "bench-smoke artifacts OK: $art_dir"
}

serve_smoke() {
  step "serve-smoke: routed fleet (unified + role-split prefill/decode), SLO + transfer scrapes, trace-plane assembly, SIGTERM drains"
  python scripts/serve_smoke.py
}

telemetry_smoke() {
  step "telemetry-smoke: /metrics scrape + flight-recorder dump"
  JAX_PLATFORMS=cpu XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    python scripts/telemetry_smoke.py
}

chaos_smoke() {
  step "chaos-smoke: integrity drill (NaN skip + ckpt bitflip) + seeded FaultPlan gang drill (KV reset + SIGKILL) + traced failover/migration drill"
  python scripts/chaos_smoke.py
}

audit_smoke() {
  step "audit-smoke: lowered-program invariant roster (scripts/hlo_audit.py)"
  local art_dir
  art_dir="$(mktemp -d)"
  JAX_PLATFORMS=cpu python scripts/hlo_audit.py \
    --json "$art_dir/hlo_audit.json"
  test -s "$art_dir/hlo_audit.json" \
    || { echo "missing artifact: hlo_audit.json" >&2; exit 1; }
  step "audit-smoke: auditor must FAIL a deliberately broken invariant"
  # assert the SPECIFIC rejection (rule finding + violation exit), not
  # just any nonzero exit — a breaker that crashes before evaluating
  # the rule must not pass as "the auditor can fail"
  local break_out
  break_out="$art_dir/break_int8_intra.log"
  if JAX_PLATFORMS=cpu python scripts/hlo_audit.py --break int8-intra \
      >"$break_out" 2>&1; then
    echo "hlo_audit accepted int8 on an intra hop — the auditor cannot fail" >&2
    exit 1
  fi
  grep -q "invariant violation(s) found" "$break_out" \
    && grep -q "WireDtype" "$break_out" \
    || { echo "hlo_audit --break exited nonzero WITHOUT a WireDtype finding (crash, not rejection):" >&2
         tail -20 "$break_out" >&2; exit 1; }
  echo "audit-smoke OK: roster green, broken invariant rejected ($art_dir)"
}

case "${1:-all}" in
  lint)        lint ;;
  native)      native ;;
  tests)       tests ;;
  bench-smoke) bench_smoke ;;
  telemetry-smoke) telemetry_smoke ;;
  serve-smoke) serve_smoke ;;
  audit-smoke) audit_smoke ;;
  chaos-smoke) chaos_smoke ;;
  all)         lint; native; tests; bench_smoke; telemetry_smoke; serve_smoke; audit_smoke; chaos_smoke ;;
  *) echo "usage: $0 [lint|native|tests|bench-smoke|telemetry-smoke|serve-smoke|audit-smoke|chaos-smoke|all]" >&2; exit 2 ;;
esac
