"""Logging subsystem: HOROVOD_LOG_LEVEL / HOROVOD_LOG_TIMESTAMP contract
(ref: horovod/common/logging.cc [V], SURVEY.md §2.1 logging row)."""

import io
import logging

import numpy as np

import horovod_tpu as hvd_mod
from horovod_tpu.common import logging as hvd_logging


def _fresh(level, timestamp, stream):
    return hvd_logging.configure(
        level=level, timestamp=timestamp, stream=stream, force=True
    )


def test_parse_level_contract():
    assert hvd_logging.parse_level("debug") == logging.DEBUG
    assert hvd_logging.parse_level("TRACE") == hvd_logging.TRACE
    assert hvd_logging.parse_level("fatal") == logging.CRITICAL
    # unknown / empty fall back to warning, like the reference
    assert hvd_logging.parse_level("bogus") == logging.WARNING
    assert hvd_logging.parse_level(None) == logging.WARNING


def test_level_filters_messages():
    buf = io.StringIO()
    _fresh("warning", False, buf)
    log = hvd_logging.get_logger("testcase")
    log.debug("hidden")
    log.warning("shown")
    out = buf.getvalue()
    assert "hidden" not in out
    assert "shown" in out


def test_timestamp_toggle():
    buf = io.StringIO()
    _fresh("info", True, buf)
    hvd_logging.get_logger("ts").info("stamped")
    stamped = buf.getvalue()
    assert stamped.startswith("[")  # [2026-...] prefix
    assert "stamped" in stamped

    buf2 = io.StringIO()
    _fresh("info", False, buf2)
    hvd_logging.get_logger("ts").info("bare")
    bare = buf2.getvalue()
    assert bare.startswith("[INFO]")


def test_env_var_behavior(monkeypatch):
    monkeypatch.setenv("HOROVOD_LOG_LEVEL", "debug")
    monkeypatch.setenv("HOROVOD_LOG_TIMESTAMP", "0")
    buf = io.StringIO()
    root = hvd_logging.configure(stream=buf, force=True)
    assert root.level == logging.DEBUG
    hvd_logging.get_logger("env").debug("visible at debug")
    assert "visible at debug" in buf.getvalue()
    assert buf.getvalue().startswith("[DEBUG]")  # no timestamp


def test_init_configures_from_config(monkeypatch, capsys):
    monkeypatch.setenv("HOROVOD_LOG_LEVEL", "info")
    hvd_mod.shutdown()
    buf = io.StringIO()
    # pre-seed handler capture: init() calls configure(force=False) via
    # cfg, so force our stream first and verify init logs through it
    hvd_logging.configure(level="info", timestamp=False, stream=buf,
                          force=True)
    hvd_logging._configured = False  # let init re-run configure
    hvd_mod.init()
    try:
        root = logging.getLogger("horovod_tpu")
        assert root.level == logging.INFO
    finally:
        hvd_mod.shutdown()


def test_fusion_cycle_debug_stats():
    buf = io.StringIO()
    _fresh("debug", False, buf)
    hvd_mod.shutdown()
    hvd_mod.init()
    try:
        x = np.stack([np.full((4,), float(r)) for r in range(8)])
        hvd_mod.allreduce(x, op=hvd_mod.Sum)
        hvd_mod.common.basics.state().fusion.flush()
        out = buf.getvalue()
        assert "cycle" in out and "cache" in out
    finally:
        hvd_mod.shutdown()


def test_stall_inspector_heartbeat_staleness():
    """Signal #2: a rank whose heartbeat goes stale past
    warning_seconds is reported (the cross-process half the round-2
    verdict asked for)."""
    import io
    import time

    from horovod_tpu.common import logging as hvd_logging
    from horovod_tpu.common.stall_inspector import StallInspector

    buf = io.StringIO()
    hvd_logging.configure(level="warning", timestamp=False, stream=buf,
                          force=True)
    insp = StallInspector(warning_seconds=0.05)
    now = time.time()  # heartbeats are epoch-domain (they cross machines)
    insp.record_heartbeat(0, now)
    insp.record_heartbeat(3, now - 10.0)  # silent for 10s
    assert insp.stale_ranks(now) == [3]
    insp.check()
    out = buf.getvalue()
    assert "Rank 3" in out and "heartbeat" in out
    # fresh heartbeat clears the warning state
    insp.record_heartbeat(3)
    assert insp.stale_ranks() == []


def test_heartbeat_kv_roundtrip():
    """Workers PUT heartbeat/<rank>; the driver reads {rank: ts} back
    through the same KV the rendezvous already runs."""
    from horovod_tpu.runner.rendezvous import (
        RendezvousClient,
        RendezvousServer,
        put_heartbeat,
        read_heartbeats,
    )

    server = RendezvousServer(secret_key=b"k", backend="python")
    port = server.start()
    try:
        client = RendezvousClient("127.0.0.1", port, secret_key=b"k")
        put_heartbeat(client, 0)
        put_heartbeat(client, 5)
        hb = read_heartbeats(client)
        assert set(hb) == {0, 5}
        import time

        assert all(abs(time.time() - t) < 60 for t in hb.values())
    finally:
        server.stop()


def test_metrics_registry_and_export(tmp_path):
    from horovod_tpu.common.metrics import MetricsRegistry

    reg = MetricsRegistry()
    reg.counter("a.calls")
    reg.counter("a.calls", 2)
    reg.gauge("b.depth", 7)
    reg.update("cache", {"hits": 3, "misses": 1})
    snap = reg.snapshot()
    assert snap["a.calls"] == 3.0
    assert snap["b.depth"] == 7.0
    assert snap["cache.hits"] == 3.0
    # no sink configured → dump is a no-op
    assert reg.dump() is None
    path = str(tmp_path / "metrics.jsonl")
    reg.configure_export(path)
    assert reg.dump() == path
    import json

    lines = [json.loads(l) for l in open(path)]
    assert {l["name"] for l in lines} >= {"a.calls", "b.depth", "cache.hits"}


def test_fusion_publishes_metrics(hvd, monkeypatch, tmp_path):
    """Every flush publishes cycle/cache gauges; HOROVOD_METRICS_FILE
    exports them as JSON lines (SURVEY §5.5 metrics row)."""
    import json

    import horovod_tpu as hvd_mod
    from horovod_tpu.common.metrics import registry

    path = str(tmp_path / "m.jsonl")
    registry.configure_export(path)
    try:
        x = np.stack([np.full((4,), float(r)) for r in range(8)])
        hvd_mod.allreduce(x, op=hvd_mod.Sum)
        hvd_mod.common.basics.state().fusion.flush()
        snap = registry.snapshot()
        assert snap.get("fusion.cycles", 0) >= 1
        lines = [json.loads(l) for l in open(path)]
        assert any(l["name"] == "fusion.cycles" for l in lines)
    finally:
        registry.configure_export("")  # clear sink
