"""Eager-mode dispatch: tensor queue, fusion buffer, cycle batching, handles.

This module is the TPU re-design of the reference's core machine —
background loop + tensor queue + fusion buffer + response cache
(ref: horovod/common/operations.cc RunLoopOnce, tensor_queue.cc,
fusion_buffer_manager.cc, response_cache.cc [V]; SURVEY.md §2.1, §3.2) —
re-thought for a single controller:

* No negotiation: every process sees the same eager dispatch order, so
  tensor-readiness agreement is structural. What the reference's controller
  negotiates dynamically, the single controller knows trivially.
* Fusion survives: many small eager collectives are still slow if dispatched
  one XLA executable each. Entries accumulate in a queue; a *cycle* flush
  concatenates same-typed allreduces into one flat [world, N] buffer and
  dispatches ONE fused collective (`HOROVOD_FUSION_THRESHOLD` caps each
  fused batch, `HOROVOD_CYCLE_TIME` bounds queue latency — same env
  contract, same semantics).
* The response cache's job (skip re-negotiation for repeating tensor sets)
  is played by the executor cache: repeated (op, dtype, shape) batches hit
  an already-compiled XLA executable.
* Flushing is cooperative (on enqueue-over-threshold, cycle expiry at next
  enqueue, or synchronize()) — there is no background thread to race with
  JAX dispatch.

Handles reproduce the async API: `allreduce_async_` returns a handle;
`synchronize(handle)` blocks (ref: horovod/torch/handle_manager.cc [V]).
"""

from __future__ import annotations

import dataclasses
import time
from collections import OrderedDict
from functools import partial
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax import shard_map

from ..common.topology import WORLD_AXIS, rank_sharding
from ..common.process_sets import ProcessSet
from ..common.logging import get_logger
from .reduction_ops import Average, Sum, Adasum, Min, Max, Product, ReduceOp

_log = get_logger("fusion")


@dataclasses.dataclass
class _Entry:
    """One pending collective (ref: TensorTableEntry in common.h [V])."""

    name: str
    kind: str  # 'allreduce' | 'allgather' | 'broadcast' | 'alltoall' | 'reducescatter'
    payload: Any  # rank-major jax.Array [world, ...]
    op: ReduceOp = Average
    prescale: float = 1.0
    postscale: float = 1.0
    root_rank: int = 0
    process_set: Optional[ProcessSet] = None
    mask: Optional[np.ndarray] = None  # [world] bool; False = rank joined
    extra: Any = None  # op-specific (e.g. uneven-length info)
    handle: "Handle" = None
    enqueue_t: float = 0.0
    group_id: Optional[int] = None  # grouped_allreduce membership


class Handle:
    """Async completion handle (ref: handle_manager.cc [V])."""

    def __init__(self, fusion: "FusionManager", entry: _Entry):
        self._fusion = fusion
        self._entry = entry
        self._result = None
        self._done = False

    def _fulfill(self, result) -> None:
        self._result = result
        self._done = True

    def poll(self) -> bool:
        """Non-blocking done check; also drives a cooperative cycle tick."""
        if not self._done:
            self._fusion.maybe_cycle()
        return self._done

    def wait(self):
        if not self._done:
            self._fusion.flush()
        assert self._done, "flush did not fulfill handle"
        return self._result


def _group_key(e: _Entry) -> Tuple:
    mask_key = None if e.mask is None else e.mask.tobytes()
    pset = 0 if e.process_set is None else e.process_set.process_set_id
    return (
        e.kind,
        int(e.op),
        e.payload.dtype.name,
        e.prescale,
        e.postscale,
        e.root_rank,
        pset,
        mask_key,
    )




class FusionManager:
    def __init__(
        self,
        mesh: Mesh,
        threshold_bytes: int,
        cycle_time_ms: float,
        cache_capacity: Optional[int] = None,
    ):
        self.mesh = mesh
        self.threshold_bytes = threshold_bytes
        self.cycle_time_ms = cycle_time_ms
        self.world = int(mesh.devices.size)
        self.pending: List[_Entry] = []
        self.pending_bytes = 0
        self.cycle_start: Optional[float] = None
        # attached by basics.init:
        self.timeline = None
        self.stall_inspector = None
        self.parameter_manager = None
        # Executor cache — the response-cache analog, with the
        # reference's HOROVOD_CACHE_CAPACITY semantics enforced (ref:
        # response_cache.cc [V]): LRU-bounded so a long eager job with
        # varying shapes cannot leak compiled executables; capacity 0
        # disables caching entirely.
        if cache_capacity is None:
            from ..common.config import Config

            cache_capacity = Config.from_env().cache_capacity
        self.cache_capacity = max(int(cache_capacity), 0)
        self._executors: "OrderedDict[Tuple, Callable]" = OrderedDict()
        self.cache_hits = 0
        self.cache_misses = 0
        self.cache_evictions = 0
        self.cycles = 0
        self._group_depth = 0
        self._next_group_id = 0

    # ------------------------------------------------------------------ queue

    def begin_group(self) -> int:
        """Start an atomic enqueue group (ref: group_table.cc — a group
        is fused and reduced as one unit [V]): threshold/cycle flush
        triggers are deferred until the matching end_group(), so a group
        larger than the fusion threshold cannot be split mid-group."""
        self._group_depth += 1
        gid = self._next_group_id
        self._next_group_id += 1
        return gid

    def abort_group(self, gid: int) -> None:
        """Drop an incompletely-enqueued group (a member failed
        validation): its entries must not dispatch at end_group."""
        kept = [e for e in self.pending if e.group_id != gid]
        dropped = len(self.pending) - len(kept)
        if dropped:
            self.pending = kept
            self.pending_bytes = sum(
                int(e.payload.nbytes) for e in self.pending
            )

    def end_group(self) -> None:
        self._group_depth = max(self._group_depth - 1, 0)
        if self._group_depth == 0 and (
            self.pending_bytes >= self.threshold_bytes
            or self._cycle_expired()
        ):
            self.flush()

    def enqueue(self, entry: _Entry) -> Handle:
        entry.enqueue_t = time.monotonic()
        entry.handle = Handle(self, entry)
        if self.timeline is not None:
            self.timeline.begin(entry.name, "QUEUE")
        if self.stall_inspector is not None:
            self.stall_inspector.record_enqueue(entry.name)
        if self.cycle_start is None:
            self.cycle_start = entry.enqueue_t
        self.pending.append(entry)
        self.pending_bytes += int(entry.payload.nbytes)
        if self._group_depth == 0 and (
            self.pending_bytes >= self.threshold_bytes
            or self._cycle_expired()
        ):
            self.flush()
        return entry.handle

    def _cycle_expired(self) -> bool:
        return (
            self.cycle_start is not None
            and (time.monotonic() - self.cycle_start) * 1e3 >= self.cycle_time_ms
        )

    def maybe_cycle(self) -> None:
        if self.pending and self._cycle_expired():
            self.flush()

    # ------------------------------------------------------------------ flush

    def flush(self) -> None:
        if not self.pending:
            return
        t0 = time.monotonic()
        entries, self.pending = self.pending, []
        flushed_bytes, self.pending_bytes = self.pending_bytes, 0
        self.cycle_start = None
        self.cycles += 1
        if self.timeline is not None:
            self.timeline.mark_cycle()
        if self.stall_inspector is not None:
            self.stall_inspector.check()

        # Group fusable entries; preserve dispatch order within groups.
        groups: Dict[Tuple, List[_Entry]] = {}
        for e in entries:
            groups.setdefault(_group_key(e), []).append(e)
        for key, group in groups.items():
            kind = key[0]
            if kind == "allreduce":
                if ReduceOp(key[1]) == Adasum:
                    # Adasum's dot-product coefficients are per-tensor;
                    # concatenating entries would compute joint projections
                    # over the fused buffer. Execute one entry at a time.
                    for e in group:
                        self._execute_fused_allreduce([e])
                else:
                    for batch in self._batches_by_threshold(group):
                        self._execute_fused_allreduce(batch)
            else:
                for e in group:
                    self._execute_single(e)

        for e in entries:
            if self.timeline is not None:
                self.timeline.end(e.name, "QUEUE")
            if self.stall_inspector is not None:
                self.stall_inspector.record_complete(e.name)
        if _log.isEnabledFor(10):  # DEBUG — cycle + cache stats
            _log.debug(
                "cycle %d: %d entries, %dB, %.2fms; cache "
                "hits=%d misses=%d evictions=%d size=%d",
                self.cycles,
                len(entries),
                flushed_bytes,
                (time.monotonic() - t0) * 1e3,
                self.cache_hits,
                self.cache_misses,
                self.cache_evictions,
                len(self._executors),
            )
        from ..common.metrics import registry as _metrics

        _metrics.update("fusion", self.cache_stats())
        _metrics.gauge("fusion.cycles", self.cycles)
        _metrics.gauge("fusion.last_flush_bytes", flushed_bytes)
        _metrics.maybe_dump()
        if self.parameter_manager is not None:
            self.parameter_manager.record(
                bytes_=flushed_bytes, seconds=time.monotonic() - t0
            )
            self.threshold_bytes, self.cycle_time_ms = (
                self.parameter_manager.current()
            )

    def _batches_by_threshold(self, group: List[_Entry]):
        """Split a fusable group into batches of <= threshold bytes,
        mirroring the fusion buffer's capacity (fusion_buffer_manager.cc
        [V]). A single over-threshold entry still goes alone, and a
        grouped_allreduce group is one indivisible unit — its members
        always share one fused collective (group_table.cc [V])."""
        units: List[List[_Entry]] = []
        for e in group:
            if (
                e.group_id is not None
                and units
                and units[-1][0].group_id == e.group_id
            ):
                units[-1].append(e)
            else:
                units.append([e])
        batch, batch_bytes = [], 0
        for unit in units:
            nbytes = sum(int(e.payload.nbytes) for e in unit)
            if batch and batch_bytes + nbytes > self.threshold_bytes:
                yield batch
                batch, batch_bytes = [], 0
            batch.extend(unit)
            batch_bytes += nbytes
        if batch:
            yield batch

    # ------------------------------------------------------------- executors

    def _pset_mask(self, e: _Entry):
        """Static [world] membership tuple for a proper-subset process
        set, else None. Masked full-axis collectives replace
        axis_index_groups here: XLA's TPU lowering requires equal-sized
        replica groups, which a set+singletons partition can never be
        (ref: per-set communicators in process_set.cc [V])."""
        if e.process_set is None or e.process_set.process_set_id == 0:
            return None
        if e.process_set.size == self.world:
            return None
        members = set(e.process_set.ranks)
        return tuple(r in members for r in range(self.world))

    def _pset_ranks(self, e: _Entry) -> Optional[Tuple[int, ...]]:
        if e.process_set is None or e.process_set.process_set_id == 0:
            return None
        return tuple(e.process_set.ranks)

    def _executor(self, key: Tuple, builder: Callable) -> Callable:
        if self.cache_capacity == 0:
            self.cache_misses += 1
            return builder()
        fn = self._executors.get(key)
        if fn is not None:
            self.cache_hits += 1
            self._executors.move_to_end(key)
            return fn
        self.cache_misses += 1
        fn = builder()
        self._executors[key] = fn
        while len(self._executors) > self.cache_capacity:
            self._executors.popitem(last=False)
            self.cache_evictions += 1
        return fn

    def cache_stats(self) -> Dict[str, int]:
        return {
            "capacity": self.cache_capacity,
            "size": len(self._executors),
            "hits": self.cache_hits,
            "misses": self.cache_misses,
            "evictions": self.cache_evictions,
        }

    def _shard_map(self, fn, out_specs=P(WORLD_AXIS)):
        return shard_map(
            fn,
            mesh=self.mesh,
            in_specs=P(WORLD_AXIS),
            out_specs=out_specs,
            check_vma=False,
        )

    def _execute_fused_allreduce(self, batch: List[_Entry]) -> None:
        e0 = batch[0]
        for e in batch:
            if self.timeline is not None and len(batch) > 1:
                self.timeline.begin(e.name, "MEMCPY_IN_FUSION_BUFFER")
        # Fusion buffer: flatten each per-rank tensor and concat → [world, N].
        flats = [
            e.payload.reshape(self.world, -1) for e in batch
        ]
        sizes = [f.shape[1] for f in flats]
        buf = flats[0] if len(flats) == 1 else jnp.concatenate(flats, axis=1)
        if self.timeline is not None:
            for e in batch:
                if len(batch) > 1:
                    self.timeline.end(e.name, "MEMCPY_IN_FUSION_BUFFER")
                self.timeline.begin(e.name, "ALLREDUCE")

        pset_mask = self._pset_mask(e0)
        mask = None if e0.mask is None else tuple(bool(b) for b in e0.mask)
        if e0.op == Adasum and pset_mask is not None:
            # Adasum over a process set rides adasum_allreduce's masked
            # full-axis formulation (gather members + in-jit tree
            # combine); non-members pass their input through unchanged.
            # A join mask composes by zeroing the joined members'
            # contributions (zero is Adasum's identity). Full-axis is
            # the MULTI-PROCESS-safe shape: a sub-mesh launch would be
            # a computation the non-member processes never join, and
            # the surrounding take/scatter on the global buffer would
            # diverge across processes (found by the 3-process parity
            # suite, tests/test_multiprocess_ops.py).
            ranks = self._pset_ranks(e0)
            # mask deliberately NOT in the key: joined MEMBERS' rows are
            # zeroed on the global buffer before the call (zero is
            # Adasum's identity; a uniform op every process executes
            # identically) so one compiled program serves every join
            # pattern. Joined NON-members keep their rows — their
            # pass-through must return the original input.
            key = ("adasum_pset", e0.prescale, e0.postscale, ranks,
                   buf.shape, buf.dtype.name)
            buf_in = buf
            if mask is not None:
                member_set = set(ranks)
                keep = jnp.asarray(
                    [
                        not (r in member_set and not mask[r])
                        for r in range(self.world)
                    ]
                )[:, None]
                buf_in = jnp.where(keep, buf, jnp.zeros_like(buf))
            fn = self._executor(
                key,
                lambda: self._build_adasum_pset(
                    e0.prescale, e0.postscale, ranks
                ),
            )
            out = fn(buf_in)
        else:
            # Shape/dtype are part of the key: one executor == one
            # compiled program, so the LRU bound really bounds compiled
            # code (the response cache is keyed per tensor too [V]).
            key = (
                "allreduce", int(e0.op), e0.prescale, e0.postscale,
                pset_mask, mask, buf.shape, buf.dtype.name,
            )
            fn = self._executor(key, lambda: self._build_allreduce(
                e0.op, e0.prescale, e0.postscale, pset_mask, mask))
            out = fn(buf)
        # Scatter results back out of the fusion buffer.
        offset = 0
        for e, n in zip(batch, sizes):
            piece = out[:, offset : offset + n].reshape(e.payload.shape)
            offset += n
            if self.timeline is not None:
                self.timeline.end(e.name, "ALLREDUCE")
            e.handle._fulfill(piece)

    def _build_allreduce(self, op, prescale, postscale, pset_mask, mask):
        world = self.world
        op = ReduceOp(op)
        mask_arr = (
            None if mask is None else np.asarray(mask, dtype=bool)
        )
        pset_arr = (
            None if pset_mask is None else np.asarray(pset_mask, dtype=bool)
        )
        # Effective participation = joined AND in the process set; the
        # two masks share one identity-masked full-axis collective.
        if mask_arr is not None and pset_arr is not None:
            active_arr = mask_arr & pset_arr
        else:
            active_arr = mask_arr if mask_arr is not None else pset_arr

        # HOROVOD_HIERARCHICAL_ALLREDUCE (ref: nccl_operations.cc [V]):
        # decompose the world psum into an intra-host stage + a
        # cross-host stage via replica groups, letting XLA emit the
        # ICI-local collective separately from the DCN hop. Only the
        # unrestricted Sum/Average path qualifies.
        hier_stages = None
        from ..common import basics as _basics

        cfg = _basics.get_config()
        local = _basics.topology().local_size if _basics.is_initialized() else 1
        if cfg.hierarchical_allreduce and active_arr is None:
            hier_stages = hierarchical_stage_groups(world, local)

        def per_shard(x):  # x: [1, N] — this rank's slice of the buffer
            idx = lax.axis_index(WORLD_AXIS)
            raw = x
            if prescale != 1.0:
                x = x * jnp.asarray(prescale, x.dtype)
            if active_arr is not None:
                active = jnp.asarray(active_arr)[idx]
                contrib = jnp.where(active, x, jnp.zeros_like(x))
            else:
                active = jnp.asarray(True)
                contrib = x
            if op in (Average, Sum) and hier_stages is not None:
                intra_groups, inter_groups = hier_stages
                out = lax.psum(
                    contrib, WORLD_AXIS, axis_index_groups=intra_groups
                )
                out = lax.psum(
                    out, WORLD_AXIS, axis_index_groups=inter_groups
                )
                if op == Average:
                    out = out / jnp.asarray(world, out.dtype)
            elif op in (Average, Sum):
                out = lax.psum(contrib, WORLD_AXIS)
                if op == Average:
                    count = lax.psum(active.astype(x.dtype), WORLD_AXIS)
                    out = out / jnp.maximum(count, 1)
            elif op == Min:
                big = jnp.full_like(x, _max_value(x.dtype))
                contrib = (
                    jnp.where(active, x, big)
                    if active_arr is not None
                    else x
                )
                out = lax.pmin(contrib, WORLD_AXIS)
            elif op == Max:
                small = jnp.full_like(x, _min_value(x.dtype))
                contrib = (
                    jnp.where(active, x, small)
                    if active_arr is not None
                    else x
                )
                out = lax.pmax(contrib, WORLD_AXIS)
            elif op == Product:
                contrib = (
                    jnp.where(active, x, jnp.ones_like(x))
                    if active_arr is not None
                    else x
                )
                gathered = lax.all_gather(contrib, WORLD_AXIS)
                out = jnp.prod(gathered, axis=0)
            elif op == Adasum:
                from .adasum import adasum_allreduce

                # Zero is Adasum's identity (a zero vector has no
                # projection to remove and adds nothing), so the same
                # contribution masking covers joined ranks here too.
                out = adasum_allreduce(contrib, axis_name=WORLD_AXIS)
            else:
                raise ValueError(f"unsupported op {op}")
            if postscale != 1.0:
                out = out * jnp.asarray(postscale, out.dtype)
            # Ranks outside the process set keep their input untouched
            # (reference: non-members don't participate at all). Joined
            # ranks (join mask) DO take the result — that's the point
            # of join().
            if pset_arr is not None:
                out = jnp.where(jnp.asarray(pset_arr)[idx], out, raw)
            return out

        return jax.jit(self._shard_map(per_shard))

    def _execute_single(self, e: _Entry) -> None:
        if self.timeline is not None:
            self.timeline.begin(e.name, e.kind.upper())
        if e.kind == "broadcast":
            pset_mask = self._pset_mask(e)
            key = ("broadcast", e.root_rank, pset_mask,
                   e.payload.shape, e.payload.dtype.name)
            fn = self._executor(
                key, lambda: self._build_broadcast(e.root_rank, pset_mask)
            )
            out = fn(e.payload)
        elif e.kind in ("allgather", "alltoall", "reducescatter"):
            # Gather-family ops on a process set run as MASKED FULL-AXIS
            # collectives (XLA needs equal-sized replica groups, and a
            # sub-mesh launch would diverge across processes in
            # multi-controller mode — tests/test_multiprocess_ops.py);
            # non-member output rows are zeros — they receive nothing.
            ranks = self._pset_ranks(e)
            n_ranks = self.world if ranks is None else len(ranks)
            payload = e.payload
            if e.kind == "allgather":
                key = ("allgather", ranks,
                       payload.shape, payload.dtype.name)
                fn = self._executor(
                    key, lambda: self._build_allgather(ranks)
                )
            elif e.kind == "alltoall":
                if payload.shape[1] % n_ranks != 0:
                    raise ValueError(
                        f"equal-split alltoall needs dim1 divisible by the "
                        f"participating rank count {n_ranks}"
                    )
                key = ("alltoall", ranks,
                       payload.shape, payload.dtype.name)
                fn = self._executor(
                    key, lambda: self._build_alltoall(ranks)
                )
            else:
                key = ("reducescatter", int(e.op), e.prescale,
                       e.postscale, ranks,
                       payload.shape, payload.dtype.name)
                fn = self._executor(
                    key,
                    lambda: self._build_reducescatter(
                        e.op, e.prescale, e.postscale, ranks
                    ),
                )
            out = fn(payload)
            if e.kind == "allgather" and e.extra is not None:
                # Uneven dim0: rows were padded to max length; slice each
                # rank's valid prefix and concat (MPI_Allgatherv parity).
                lengths = e.extra
                srcs = range(self.world) if ranks is None else ranks
                pieces = [out[:, i, : lengths[s]] for i, s in enumerate(srcs)]
                out = jnp.concatenate(pieces, axis=1)
        else:
            raise ValueError(f"unknown kind {e.kind}")
        if self.timeline is not None:
            self.timeline.end(e.name, e.kind.upper())
        e.handle._fulfill(out)

    def _build_broadcast(self, root_rank, pset_mask):
        pset_arr = (
            None if pset_mask is None else np.asarray(pset_mask, dtype=bool)
        )

        def per_shard(x):
            idx = lax.axis_index(WORLD_AXIS)
            contrib = jnp.where(idx == root_rank, x, jnp.zeros_like(x))
            out = lax.psum(contrib, WORLD_AXIS)
            # Non-members of the process set keep their input unchanged
            # (reference: they don't participate at all).
            if pset_arr is not None:
                out = jnp.where(jnp.asarray(pset_arr)[idx], out, x)
            return out

        return jax.jit(self._shard_map(per_shard))

    def _member_tables(self, ranks):
        from ..common.process_sets import member_tables

        return member_tables(self.world, ranks)

    def _build_allgather(self, ranks=None):
        ranks_t = None if ranks is None else tuple(ranks)
        member = None
        if ranks_t is not None:
            member, _ = self._member_tables(ranks_t)

        def per_shard(x):  # [1, n, ...] → [1, n_ranks, n, ...]
            g = lax.all_gather(x[0], WORLD_AXIS)  # [world, n, ...]
            if ranks_t is None:
                return g[None]
            mg = g[jnp.asarray(ranks_t)]  # static member selection
            is_m = jnp.asarray(member)[lax.axis_index(WORLD_AXIS)]
            return jnp.where(is_m, mg, jnp.zeros_like(mg))[None]

        return jax.jit(self._shard_map(per_shard))

    def _build_alltoall(self, ranks=None):
        if ranks is None:
            def per_shard(x):  # [1, n, ...]; n % world == 0
                return lax.all_to_all(
                    x, WORLD_AXIS, split_axis=1, concat_axis=1, tiled=True
                )
        else:
            ranks_t = tuple(ranks)
            n_ranks = len(ranks_t)
            member, pos = self._member_tables(ranks_t)

            def per_shard(x):  # [1, n, ...]; n % n_ranks == 0
                # Masked full-axis formulation: gather every row, select
                # the member block addressed to this rank's member
                # position. More wire than a member-only exchange, but
                # expressible with equal replica groups AND launched
                # identically by every process.
                row = x[0]
                k = row.shape[0] // n_ranks
                g = lax.all_gather(row, WORLD_AXIS)  # [world, n, ...]
                mg = g[jnp.asarray(ranks_t)]         # [n_ranks, n, ...]
                blocks = mg.reshape(
                    (n_ranks, n_ranks, k) + row.shape[1:]
                )
                idx = lax.axis_index(WORLD_AXIS)
                mine = lax.dynamic_index_in_dim(
                    blocks, jnp.asarray(pos)[idx], axis=1, keepdims=False
                )  # [n_ranks, k, ...]
                mine = mine.reshape((n_ranks * k,) + row.shape[1:])
                is_m = jnp.asarray(member)[idx]
                return jnp.where(is_m, mine, jnp.zeros_like(mine))[None]

        return jax.jit(self._shard_map(per_shard))

    def _build_reducescatter(self, op, prescale, postscale, ranks=None):
        op = ReduceOp(op)
        if ranks is None:
            n_ranks = self.world

            def per_shard(x):  # [1, n, ...]; n % n_ranks == 0
                if prescale != 1.0:
                    x = x * jnp.asarray(prescale, x.dtype)
                out = lax.psum_scatter(
                    x, WORLD_AXIS, scatter_dimension=1, tiled=True
                )
                if op == Average:
                    out = out / jnp.asarray(n_ranks, out.dtype)
                if postscale != 1.0:
                    out = out * jnp.asarray(postscale, out.dtype)
                return out
        else:
            ranks_t = tuple(ranks)
            n_ranks = len(ranks_t)
            member, pos = self._member_tables(ranks_t)

            def per_shard(x):  # [1, n, ...]; n % n_ranks == 0
                if prescale != 1.0:
                    x = x * jnp.asarray(prescale, x.dtype)
                idx = lax.axis_index(WORLD_AXIS)
                is_m = jnp.asarray(member)[idx]
                contrib = jnp.where(is_m, x, jnp.zeros_like(x))
                total = lax.psum(contrib, WORLD_AXIS)  # member sum
                k = x.shape[1] // n_ranks
                mine = lax.dynamic_slice_in_dim(
                    total, jnp.asarray(pos)[idx] * k, k, axis=1
                )
                if op == Average:
                    mine = mine / jnp.asarray(n_ranks, mine.dtype)
                if postscale != 1.0:
                    mine = mine * jnp.asarray(postscale, mine.dtype)
                return jnp.where(is_m, mine, jnp.zeros_like(mine))

        return jax.jit(self._shard_map(per_shard))

    def _build_adasum_pset(self, prescale, postscale, ranks):
        """Adasum over a process set as a masked full-axis program
        (adasum_allreduce's gather+tree formulation); non-members keep
        their input. Join masking happens on the buffer BEFORE the call
        (see the call site) so the compiled program is mask-independent."""
        from .adasum import adasum_allreduce

        ranks_l = list(ranks)
        member, _ = self._member_tables(ranks_l)

        def per_shard(x):  # [1, N]
            idx = lax.axis_index(WORLD_AXIS)
            raw = x
            if prescale != 1.0:
                x = x * jnp.asarray(prescale, x.dtype)
            out = adasum_allreduce(
                x[0], WORLD_AXIS, groups=[ranks_l]
            )[None]
            if postscale != 1.0:
                out = out * jnp.asarray(postscale, out.dtype)
            return jnp.where(jnp.asarray(member)[idx], out, raw)

        return jax.jit(self._shard_map(per_shard))


def hierarchical_stage_groups(world: int, local: int):
    """Replica groups for the two-level decomposition, or None when the
    hierarchy degenerates (single host, or hosts of one chip): stage 1 =
    one group per host (intra, ICI), stage 2 = one group per local slot
    across hosts (inter, DCN). Summing stage 1 then stage 2 equals the
    flat world sum."""
    if local <= 1 or world <= local or world % local:
        return None
    hosts = world // local
    intra = [list(range(h * local, (h + 1) * local)) for h in range(hosts)]
    inter = [[i + h * local for h in range(hosts)] for i in range(local)]
    return intra, inter


def _max_value(dtype):
    if jnp.issubdtype(dtype, jnp.floating):
        return jnp.finfo(dtype).max
    return jnp.iinfo(dtype).max


def _min_value(dtype):
    if jnp.issubdtype(dtype, jnp.floating):
        return jnp.finfo(dtype).min
    return jnp.iinfo(dtype).min
