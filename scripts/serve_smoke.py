"""Serve smoke gate (ci.sh): the inference plane end-to-end.

Starts a 2-worker serve fleet on a toy transformer (each worker a real
subprocess: its own engine, batcher, HTTP frontend, and rendezvous-KV
capacity announcements against a driver-hosted RendezvousServer), then:

1. routes concurrent prompts of MIXED lengths through the
   straggler-aware ``Router`` (reading live announcements from the KV)
   and asserts every completion, plus that the load actually spread
   across both workers;
2. scrapes each worker's live ``/metrics`` and asserts the TTFT/TPOT
   summary quantiles and the slot-occupancy/queue/page gauges;
3. sends a shared-prefix burst (same system prompt, distinct tails) to
   ONE worker and asserts ``hvd_serve_prefix_hits`` > 0 on its live
   ``/metrics`` scrape — the paged memory plane's prefix cache can't
   silently rot;
4. stands up a SECOND, role-split fleet (1 prefill + 2 decode workers,
   ``HOROVOD_SERVE_ROLE`` via env, own rendezvous KV): a routed burst
   must land every prompt on the prefill worker, stream its finished
   KV pages over the transfer wire (``hvd_serve_kv_transfer_pages`` >
   0 on the prefill worker's live scrape, transfer admits spread over
   BOTH decode workers); the fleet TRACE plane is then asserted
   end-to-end — a crafted ``traceparent`` round-trips as
   ``X-Trace-Id``, and one routed request assembles (live ``/traces``
   scrapes + this process's span ring, through
   scripts/trace_assemble.py) into a single skew-corrected trace
   covering router → prefill → KV transfer → decode in monotonic
   order; then one decode worker is SIGTERMed mid-burst —
   reservations fail over, every accepted request still completes,
   the killed worker exits 143;
5. fires a burst of in-flight requests at the unified fleet, SIGTERMs
   both workers mid-service, and asserts the drain contract: every
   ACCEPTED request completes with its full token budget, both
   workers exit 143.

Exit 0 on success; any assertion failure is a CI failure.
"""

import json
import os
import signal
import subprocess
import sys
import tempfile
import threading
import time
import urllib.request

# runnable as `python scripts/serve_smoke.py` from the repo root
sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
# the trace phase drives scripts/trace_assemble.py as a library
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

GEN_TOKENS = 6
BURST_TOKENS = 16

WORKER = """\
import os, sys
sys.path.insert(0, os.getcwd())
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import horovod_tpu as hvd
from horovod_tpu.models.transformer import Transformer, TransformerConfig

cfg = TransformerConfig(
    vocab_size=61, num_layers=1, d_model=16, num_heads=2, d_ff=32,
    max_len=64, causal=True, dtype=jnp.float32,
)
model = Transformer(cfg)
params = model.init(
    jax.random.PRNGKey(0), jnp.ones((1, 4), jnp.int32), train=False
)
handle = hvd.serve(
    model, params, port=0, slots=4, max_new_tokens=8,
    addr="127.0.0.1", advertise_addr="127.0.0.1",
)
print("SERVING", handle.port, flush=True)
handle.wait()  # SIGTERM: drain hook finishes accepted work, exit 143
"""


def _get_json(url, timeout=10):
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return json.load(resp)


def _get_text(url, timeout=10):
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.read().decode()


def _scrape_counter(port, name):
    """One ``hvd_*`` gauge/counter value off a live /metrics scrape."""
    for line in _get_text(f"http://127.0.0.1:{port}/metrics").splitlines():
        if line.startswith(name + " "):
            return float(line.split()[1])
    return 0.0


def main() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    # trace plane ON (full sampling) for the whole smoke: phase 3.5
    # asserts the assembled cross-process trace, and every other phase
    # doubles as proof that tracing-on changes no serving behavior
    os.environ["HOROVOD_TRACE"] = "1"
    os.environ["HOROVOD_TRACE_SAMPLE"] = "1.0"
    from horovod_tpu.common import tracing
    from horovod_tpu.runner.rendezvous import RendezvousServer
    from horovod_tpu.serving.frontend import Router, read_announcements

    tracing.set_role("router")

    workdir = tempfile.mkdtemp(prefix="hvd-serve-smoke-")
    server = RendezvousServer()
    port = server.start()
    env = dict(os.environ)
    env["PYTHONPATH"] = os.getcwd() + os.pathsep + env.get("PYTHONPATH", "")
    env["HOROVOD_GLOO_RENDEZVOUS_ADDR"] = "127.0.0.1"
    env["HOROVOD_GLOO_RENDEZVOUS_PORT"] = str(port)

    script = os.path.join(workdir, "worker.py")
    with open(script, "w") as f:
        f.write(WORKER)
    procs = []
    for rank in range(2):
        wenv = dict(env, HOROVOD_RANK=str(rank))
        procs.append(
            subprocess.Popen(
                [sys.executable, script],
                env=wenv,
                stdout=subprocess.PIPE,
                stderr=subprocess.PIPE,
                text=True,
            )
        )
    try:
        ports = {}
        for rank, proc in enumerate(procs):
            line = proc.stdout.readline()
            assert "SERVING" in line, (
                f"worker {rank} failed to start: {line!r}\n"
                f"{proc.stderr.read()[-2000:]}"
            )
            ports[rank] = int(line.split()[1])
        # both workers announced into the KV
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            anns = read_announcements(server.store)
            if set(anns) >= {0, 1}:
                break
            time.sleep(0.05)
        anns = read_announcements(server.store)
        assert set(anns) >= {0, 1}, f"announcements missing: {anns}"
        assert anns[0]["port"] == ports[0] and anns[1]["port"] == ports[1]

        router = Router(server.store)

        # ---- phase 1: concurrent mixed-length prompts via the router
        prompts = [
            [3, 5, 7],
            [4, 6, 8, 10, 12, 14],
            [9] * 17,
            list(range(1, 31)),
            [11, 13, 15, 17, 19],
            [2] * 9,
        ]
        results = [None] * len(prompts)

        def route_one(i):
            results[i] = router.route(
                prompts[i], max_tokens=GEN_TOKENS, timeout=120
            )

        threads = [
            threading.Thread(target=route_one, args=(i,))
            for i in range(len(prompts))
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=180)
        for i, res in enumerate(results):
            assert res is not None, f"request {i} never completed"
            assert res["status"] == "done", res
            assert len(res["tokens"]) == GEN_TOKENS, res
            assert res["ttft_ms"] > 0, res
        per_worker = {}
        for rank, p in ports.items():
            stats = _get_json(f"http://127.0.0.1:{p}/stats")
            per_worker[rank] = stats["prefills"]
        assert sum(per_worker.values()) == len(prompts), per_worker
        assert all(v > 0 for v in per_worker.values()), (
            f"routing did not spread: {per_worker}"
        )
        print(f"phase 1 OK: {len(prompts)} completions, "
              f"spread {per_worker}")

        # ---- phase 2: SLO quantiles + slot/page gauges on the live scrape
        for rank, p in ports.items():
            text = _get_text(f"http://127.0.0.1:{p}/metrics")
            for needle in (
                'serve_ttft_ms{quantile="0.5"}',
                'serve_ttft_ms{quantile="0.95"}',
                'serve_tpot_ms{quantile="0.5"}',
                'serve_tpot_ms{quantile="0.95"}',
                "hvd_serve_slots_total 4",
                "hvd_serve_slots_free",
                "hvd_serve_queue_depth",
                "hvd_serve_tokens_out",
                "hvd_serve_pages_total",
                "hvd_serve_pages_free",
            ):
                assert needle in text, (
                    f"worker {rank} /metrics missing {needle!r}:\n"
                    + text[:800]
                )
            assert "NaN" not in text
        # /healthz carries the page headroom the Router now prefers
        h = _get_json(f"http://127.0.0.1:{ports[0]}/healthz")
        assert "free_pages" in h and h["pages_total"] > 0, h
        print("phase 2 OK: TTFT/TPOT quantiles + slot/page gauges scraped")

        # ---- phase 2.5: shared-prefix burst → prefix-cache hits
        # (all to ONE worker so the shared pages are actually local)
        sys_prefix = [7, 11, 13, 17, 19, 23, 29, 31] * 2  # one full page
        tails = [[41, 43], [47, 53, 2], [3, 5]]
        for tail in tails:
            body = json.dumps(
                {"tokens": sys_prefix + tail, "max_tokens": 4}
            ).encode()
            req = urllib.request.Request(
                f"http://127.0.0.1:{ports[0]}/generate",
                data=body, method="POST",
            )
            with urllib.request.urlopen(req, timeout=120) as resp:
                out = json.load(resp)
            assert out["status"] == "done", out
        text = _get_text(f"http://127.0.0.1:{ports[0]}/metrics")
        hits = 0.0
        for line in text.splitlines():
            if line.startswith("hvd_serve_prefix_hits "):
                hits = float(line.split()[1])
        assert hits > 0, (
            "shared-prefix burst produced no prefix hits:\n"
            + "\n".join(
                ln for ln in text.splitlines() if "prefix" in ln
            )
        )
        print(f"phase 2.5 OK: shared-prefix burst hit the prefix cache "
              f"({int(hits)} pages attached)")

        # ---- phase 3: role-split fleet — prefill/decode disaggregation
        # (own rendezvous KV so unified announcements can't leak in)
        server2 = RendezvousServer()
        port2 = server2.start()
        env2 = dict(env)
        env2["HOROVOD_GLOO_RENDEZVOUS_PORT"] = str(port2)
        roles = {0: "prefill", 1: "decode", 2: "decode"}
        fleet = {}
        try:
            for rank, role in roles.items():
                wenv = dict(
                    env2, HOROVOD_RANK=str(rank), HOROVOD_SERVE_ROLE=role,
                )
                fleet[rank] = subprocess.Popen(
                    [sys.executable, script],
                    env=wenv,
                    stdout=subprocess.PIPE,
                    stderr=subprocess.PIPE,
                    text=True,
                )
            fports = {}
            for rank, proc in fleet.items():
                line = proc.stdout.readline()
                assert "SERVING" in line, (
                    f"{roles[rank]} worker {rank} failed to start: "
                    f"{line!r}\n{proc.stderr.read()[-2000:]}"
                )
                fports[rank] = int(line.split()[1])
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                anns = read_announcements(server2.store)
                if set(anns) >= set(roles):
                    break
                time.sleep(0.05)
            anns = read_announcements(server2.store)
            assert set(anns) >= set(roles), f"fleet missing: {anns}"
            for rank, role in roles.items():
                assert anns[rank].get("role") == role, (rank, anns[rank])
            assert all(
                anns[r].get("transfer_port") for r in (1, 2)
            ), anns

            router2 = Router(server2.store)
            dis_prompts = [
                [3 + i, 5, 7, 11, 13, 17][: 3 + i % 4]
                for i in range(8)
            ]
            dis_results = [None] * len(dis_prompts)

            def dis_one(i):
                dis_results[i] = router2.route(
                    dis_prompts[i], max_tokens=GEN_TOKENS, timeout=120
                )

            dthreads = [
                threading.Thread(target=dis_one, args=(i,))
                for i in range(len(dis_prompts))
            ]
            for t in dthreads:
                t.start()
            for t in dthreads:
                t.join(timeout=180)
            for i, res in enumerate(dis_results):
                assert res is not None, f"disagg request {i} never done"
                assert res["status"] == "done", res
                assert len(res["tokens"]) == GEN_TOKENS, res
            # per-role routing on the LIVE scrapes: every prompt hit
            # the prefill worker, its pages left over the wire, and
            # the streamed admissions spread across BOTH decode workers
            # (engine stats publish on an interval — poll, don't race)
            deadline = time.monotonic() + 15
            while time.monotonic() < deadline:
                if _scrape_counter(
                    fports[0], "hvd_serve_prefills"
                ) >= len(dis_prompts):
                    break
                time.sleep(0.1)
            assert _scrape_counter(
                fports[0], "hvd_serve_prefills"
            ) >= len(dis_prompts), "prompts leaked past the prefill worker"
            pages_out = _scrape_counter(
                fports[0], "hvd_serve_kv_transfer_pages"
            )
            assert pages_out > 0, (
                "prefill worker streamed no KV pages:\n" + "\n".join(
                    ln for ln in _get_text(
                        f"http://127.0.0.1:{fports[0]}/metrics"
                    ).splitlines() if "transfer" in ln
                )
            )
            admits = {
                r: _scrape_counter(fports[r], "hvd_serve_transfer_admits")
                for r in (1, 2)
            }
            assert all(v > 0 for v in admits.values()), (
                f"streamed admissions did not spread: {admits}"
            )
            for r in (1, 2):
                assert _scrape_counter(
                    fports[r], "hvd_serve_prefills"
                ) == 0, f"decode worker {r} ran a prefill"
            print(f"phase 3 OK: {len(dis_prompts)} disagg completions, "
                  f"{int(pages_out)} pages streamed, "
                  f"decode spread {admits}")

            # ---- phase 3.5: fleet trace plane on the disagg fleet.
            # First the header contract: a crafted traceparent must
            # round-trip as X-Trace-Id on the reply.
            import trace_assemble
            from horovod_tpu.analysis import trace_merge

            want = "ab" * 16
            treq = urllib.request.Request(
                f"http://127.0.0.1:{fports[0]}/generate",
                data=json.dumps(
                    {"tokens": [3, 5, 7], "max_tokens": 4}
                ).encode(),
                headers={"traceparent": f"00-{want}-{'cd' * 8}-01"},
                method="POST",
            )
            with urllib.request.urlopen(treq, timeout=120) as resp:
                echoed = resp.headers.get("X-Trace-Id")
                tout = json.load(resp)
            assert tout["status"] == "done", tout
            assert echoed == want, (
                f"X-Trace-Id did not round-trip: {echoed!r}"
            )

            # one routed request: the Router (THIS process) mints the
            # root, the traceparent header carries it to the prefill
            # worker, and the kv_transfer meta frames carry it on to
            # whichever decode worker admits the streamed pages
            tres = router2.route(
                [5, 9, 13, 17], max_tokens=GEN_TOKENS, timeout=120
            )
            assert tres["status"] == "done", tres
            tid = tres.get("trace_id")
            assert tid, f"routed result carries no trace_id: {tres}"

            # scrape every worker's live /traces (each scrape is an
            # NTP edge) + this process's own ring; span records land
            # moments after the reply, so poll briefly
            need = {
                "route", "route.attempt", "http.generate",
                "serve.prefill", "kv.reserve", "kv.stream",
                "kv.ingest", "serve.decode",
            }
            deadline = time.monotonic() + 15
            while True:
                spans = tracing.recorder().spans()
                edges = []
                for r in roles:
                    got, edge = trace_assemble.scrape(
                        f"http://127.0.0.1:{fports[r]}/traces"
                    )
                    spans.extend(got)
                    if edge is not None:
                        edges.append(edge)
                tspans = trace_merge.filter_trace(spans, tid)
                names = {s["name"] for s in tspans}
                if need <= names or time.monotonic() > deadline:
                    break
                time.sleep(0.1)
            assert need <= names, (
                f"assembled trace missing {sorted(need - names)} "
                f"(has {sorted(names)})"
            )
            assert len(trace_merge.traces_in(tspans)) == 1

            corrected, offsets = trace_merge.assemble(
                tspans, edges=edges
            )
            tprocs = {trace_merge.proc_key(s) for s in corrected}
            assert len(tprocs) >= 3, (
                f"trace does not span router+prefill+decode: {tprocs}"
            )
            assert tprocs <= set(offsets), (
                f"skew graph not connected: {tprocs - set(offsets)} "
                f"unreachable from the reference clock"
            )

            def first_ts(name):
                return min(
                    s["ts_corrected"] for s in corrected
                    if s["name"] == name
                )

            milestones = [
                first_ts(n) for n in (
                    "route", "serve.prefill", "kv.stream",
                    "serve.decode",
                )
            ]
            assert milestones == sorted(milestones), (
                f"skew-corrected trace out of monotonic order: "
                f"{milestones}"
            )
            assert all(
                a["ts_corrected"] <= b["ts_corrected"]
                for a, b in zip(corrected, corrected[1:])
            ), "assemble() did not sort by corrected time"

            # the CLI end-to-end: live scrapes + this process's ring
            # dump -> one chrome://tracing JSON with one row per
            # (host, role)
            ring_file = os.path.join(workdir, "router.spans")
            tracing.recorder().dump(ring_file)
            chrome_out = os.path.join(workdir, "fleet_trace.json")
            argv = ["--file", ring_file, "--trace", tid,
                    "--out", chrome_out]
            for r in roles:
                argv += [
                    "--url", f"http://127.0.0.1:{fports[r]}/traces",
                ]
            assert trace_assemble.main(argv) == 0
            with open(chrome_out) as f:
                chrome = json.load(f)
            rows = {
                e["args"]["name"]
                for e in chrome["traceEvents"]
                if e.get("ph") == "M" and e.get("name") == "process_name"
            }
            for frag in ("[router]", "[prefill]", "[decode]"):
                assert any(frag in r for r in rows), (frag, rows)
            print(f"phase 3.5 OK: trace {tid[:8]} assembled across "
                  f"{len(tprocs)} processes ({len(corrected)} spans), "
                  f"X-Trace-Id round-tripped")

            # mid-burst decode-worker death: reservations fail over,
            # every accepted request still completes
            kill_results = [None] * 6

            def kill_one(i):
                kill_results[i] = router2.route(
                    [2 + i, 4, 6, 8][: 2 + i % 3],
                    max_tokens=GEN_TOKENS, timeout=120,
                )

            kthreads = [
                threading.Thread(target=kill_one, args=(i,))
                for i in range(len(kill_results))
            ]
            for t in kthreads:
                t.start()
            # SIGTERM a decode worker once the burst is in flight on
            # the prefill side (accepted = occupied slots + queue)
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                h = _get_json(f"http://127.0.0.1:{fports[0]}/healthz")
                if h["slots_total"] - h["free_slots"] + h["queue_depth"]:
                    break
                time.sleep(0.01)
            fleet[1].send_signal(signal.SIGTERM)
            for t in kthreads:
                t.join(timeout=180)
            for i, res in enumerate(kill_results):
                assert res is not None, f"failover request {i} lost"
                assert res["status"] == "done", res
                assert len(res["tokens"]) == GEN_TOKENS, res
            assert fleet[1].wait(timeout=120) == 143, (
                "SIGTERMed decode worker did not drain-exit 143"
            )
            print(f"phase 3 OK: decode worker SIGTERM mid-burst, "
                  f"{len(kill_results)}/{len(kill_results)} completions "
                  f"after failover")
            for rank in (0, 2):
                fleet[rank].send_signal(signal.SIGTERM)
            rcs2 = [fleet[r].wait(timeout=120) for r in (0, 2)]
            assert rcs2 == [143, 143], f"fleet exit codes: {rcs2}"
        finally:
            for proc in fleet.values():
                if proc.poll() is None:
                    proc.kill()
            server2.stop()

        # ---- phase 4: SIGTERM drain — every accepted request finishes
        burst = [[5, 6], [7, 8, 9], [1] * 12, [2, 3, 4, 5]]
        burst_results = [None] * len(burst)

        def burst_one(i):
            # split the burst across the two workers directly — the
            # drain contract is per-worker, and routing is phase 1's
            rank = i % 2
            body = json.dumps(
                {"tokens": burst[i], "max_tokens": BURST_TOKENS}
            ).encode()
            req = urllib.request.Request(
                f"http://127.0.0.1:{ports[rank]}/generate",
                data=body, method="POST",
            )
            with urllib.request.urlopen(req, timeout=120) as resp:
                burst_results[i] = json.load(resp)

        bthreads = [
            threading.Thread(target=burst_one, args=(i,))
            for i in range(len(burst))
        ]
        for t in bthreads:
            t.start()
        # SIGTERM only once every burst request is ACCEPTED (in a slot
        # or queued) — a drain may legitimately 503 un-submitted work
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            accepted = 0
            for rank, p in ports.items():
                h = _get_json(f"http://127.0.0.1:{p}/healthz")
                accepted += (
                    h["slots_total"] - h["free_slots"] + h["queue_depth"]
                )
            if accepted >= len(burst):
                break
            time.sleep(0.02)
        for proc in procs:
            proc.send_signal(signal.SIGTERM)
        for t in bthreads:
            t.join(timeout=120)
        for i, res in enumerate(burst_results):
            assert res is not None, f"burst request {i} lost in drain"
            assert res["status"] == "done", res
            assert len(res["tokens"]) == BURST_TOKENS, res
        rcs = [proc.wait(timeout=120) for proc in procs]
        assert rcs == [143, 143], f"worker exit codes: {rcs}"
        print(f"phase 4 OK: drain completed {len(burst)}/{len(burst)} "
              f"in-flight requests, workers exited {rcs}")
        print("serve-smoke OK")
        return 0
    finally:
        for proc in procs:
            if proc.poll() is None:
                proc.kill()
        server.stop()


if __name__ == "__main__":
    sys.exit(main())
