"""Telemetry smoke gate (ci.sh): the observability acceptance loop.

Runs a 5-step CPU training loop with the live scrape endpoint on an
ephemeral port (the HOROVOD_METRICS_PORT env path, exactly as a launch
script would set it), scrapes ``/metrics`` via urllib (no curl), and
asserts:

* Prometheus text exposition with the step-time p50/p95 summary and
  registry gauges, correct content type, no NaN;
* ``/telemetry`` JSON carries one record per step;
* the flight-recorder JSON-lines file is written with <= ring-size
  records, monotonically increasing step ids, and the per-step
  exposed/hidden collective + wire-byte fields.

Exit 0 on success; any assertion failure is a CI failure.
"""

import json
import os
import socket
import sys
import tempfile
import urllib.request

# runnable as `python scripts/telemetry_smoke.py` from the repo root
sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def main() -> int:
    port = _free_port()
    flight = os.path.join(
        tempfile.mkdtemp(prefix="hvd-telemetry-smoke-"), "flight.jsonl"
    )
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    os.environ["HOROVOD_METRICS_PORT"] = str(port)
    os.environ["HOROVOD_FLIGHT_RECORDER"] = flight
    os.environ["HOROVOD_TELEMETRY_STEPS"] = "64"

    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    import horovod_tpu as hvd

    hvd.init()
    x = np.stack(
        [np.full((128,), float(r), np.float32) for r in range(hvd.size())]
    )
    for _ in range(5):
        hvd.step_begin()
        hvd.allreduce(x, op=hvd.Sum, name="smoke")
        hvd.step_end()

    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}/metrics", timeout=10
    ) as resp:
        ctype = resp.headers.get("Content-Type", "")
        text = resp.read().decode()
    assert ctype.startswith("text/plain"), f"content-type: {ctype}"
    assert 'telemetry_step_ms{quantile="0.5"}' in text, text[:400]
    assert 'telemetry_step_ms{quantile="0.95"}' in text, text[:400]
    assert "telemetry_step_ms_count 5" in text, text[:400]
    assert "hvd_fusion_cycles" in text, "registry gauges missing"
    assert "# TYPE hvd_fusion_cycles gauge" in text
    assert "NaN" not in text

    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}/telemetry", timeout=10
    ) as resp:
        tele = json.load(resp)
    assert len(tele["steps"]) == 5, tele["steps"]

    hvd.shutdown()  # stops the server and dumps the flight recorder
    with open(flight) as f:
        records = [json.loads(line) for line in f]
    assert 0 < len(records) <= 64, len(records)
    steps = [r["step"] for r in records]
    assert steps == sorted(steps), steps
    for rec in records:
        for key in (
            "wall_ms",
            "exposed_collective_ms",
            "hidden_collective_ms",
            "wire_bytes",
            "wire_format",
        ):
            assert key in rec, (key, rec)
    print(f"telemetry-smoke OK: {len(records)} records, port {port}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
