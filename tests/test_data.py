"""Data-sharding utilities: DistributedSampler-contract tests
(ref: the reference examples' DistributedSampler idiom [V])."""

import numpy as np
import pytest

from horovod_tpu.data import (
    ShardedIndexSampler,
    prefetch_to_device,
    shard_array,
)


def test_sampler_partitions_all_indices(hvd):
    n, world = 103, 8
    seen = []
    for r in range(world):
        s = ShardedIndexSampler(n, num_replicas=world, rank=r,
                                shuffle=False)
        idx = list(s)
        assert len(idx) == len(s) == 13  # ceil(103/8)
        seen.extend(idx)
    # every index appears; padding wraps around the head
    assert set(seen) == set(range(n))
    assert len(seen) == 13 * world


def test_sampler_epoch_shuffling_deterministic(hvd):
    a = ShardedIndexSampler(64, num_replicas=8, rank=3, seed=7)
    a.set_epoch(1)
    first = list(a)
    a.set_epoch(2)
    second = list(a)
    assert first != second
    a.set_epoch(1)
    assert list(a) == first


def test_sampler_drop_last(hvd):
    s = ShardedIndexSampler(103, num_replicas=8, rank=0, shuffle=False,
                            drop_last=True)
    assert len(s) == 12  # floor


def test_sampler_defaults_from_runtime(hvd):
    s = ShardedIndexSampler(32)
    assert s.num_replicas == hvd.size()
    assert s.rank == hvd.rank()


def test_shard_array(hvd):
    x = np.arange(17)
    shard = shard_array(x, num_replicas=8, rank=2)
    np.testing.assert_array_equal(shard, [4, 5])
    with pytest.raises(ValueError, match="cannot shard"):
        shard_array(np.arange(3), num_replicas=8, rank=0)


def test_prefetch_to_device_preserves_order_and_moves(hvd):
    import jax

    batches = [{"x": np.full((2,), i)} for i in range(5)]
    out = list(prefetch_to_device(iter(batches), size=3))
    assert len(out) == 5
    for i, b in enumerate(out):
        assert isinstance(b["x"], jax.Array)
        np.testing.assert_array_equal(np.asarray(b["x"]), [i, i])


def test_sampler_fewer_items_than_replicas(hvd):
    """n < num_replicas must still give every rank an equal, non-empty
    shard (an empty shard would deadlock the first SPMD collective)."""
    lens = set()
    for r in range(8):
        s = ShardedIndexSampler(3, num_replicas=8, rank=r, shuffle=False)
        idx = list(s)
        assert len(idx) == len(s) == 1
        assert 0 <= idx[0] < 3
        lens.add(len(idx))
    assert lens == {1}
