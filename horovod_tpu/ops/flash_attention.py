"""Blockwise (flash) attention as Pallas TPU kernels, fwd + bwd.

The hot op of the LM benchmark family (BASELINE.json configs #3/#4 —
BERT-large, GPT-2 medium). The reference leans on cuDNN/torch SDPA for
this (its CUDA kernels live outside the framework, cuda_kernels.cu is
only scale/memcpy [V]); the TPU-native answer is a Pallas kernel pair
implementing the FlashAttention-2 formulation:

* forward: one pass over K/V blocks per Q block with the online
  softmax (running max ``m``, running denominator ``l``), emitting the
  output block and the per-row logsumexp. Attention probabilities are
  never materialized in HBM — O(T) memory instead of O(T²).
* backward: the standard two-kernel split — a dQ kernel gridded over Q
  blocks and a dK/dV kernel gridded over K blocks — each recomputing
  P = exp(S − lse) blockwise from the saved logsumexp (recompute beats
  storing T² probabilities on an HBM-bound chip).

Softmax statistics and accumulators run in fp32 regardless of input
dtype (the MXU consumes bf16 operands; the VPU accumulates fp32).
Kernels run in interpret mode off-TPU, so CPU tests exercise the same
code path bit-for-bit (tests/test_flash_attention.py checks fwd+grads
against the dense jnp oracle).

Used by models.Transformer when ``TransformerConfig.flash_attention``
is on (default: auto — enabled when no padding mask is passed).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _lens_spec():
    """BlockSpec for the per-program (bh, 1) valid-length scalars.
    They live in SMEM: a (1, 1) VMEM tile would violate Mosaic's
    sublane rule (module header), and the value drives loop bounds —
    scalar memory is where the official TPU flash kernels keep
    sequence lengths."""
    return pl.BlockSpec(
        (1, 1), lambda b, i: (b, 0), memory_space=pltpu.SMEM
    )


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


_NEG_INF = -1e30

# Mosaic requires the last two dims of every block to be (8k, 128k) or
# the full array dims. Row statistics (lse) are per-Q-row scalars, so
# inside the kernels they ride a broadcast lane minor dim — the same
# layout the official jax.experimental.pallas.ops.tpu.flash_attention
# uses (MIN_BLOCK_SIZE trailing dim on l/m). ACROSS kernels, though,
# the lse lives width-1 (minor dim 1 = the full array dim, which
# Mosaic's block rule also accepts): materializing the broadcast as a
# (bh, seq, 128) HBM array made bwd lse traffic and the dkv kernel's
# VMEM footprint 128x larger than needed (ADVICE r3).
# HOROVOD_FLASH_LSE_BROADCAST=1 restores the broadcast interchange
# layout (escape hatch while the width-1 layout awaits real-TPU
# validation; interpret-mode tests cover both).
_STATS_LANES = 128


def _interchange_lanes() -> int:
    import os

    flag = os.environ.get("HOROVOD_FLASH_LSE_BROADCAST", "")
    return _STATS_LANES if flag not in ("", "0", "false", "off") else 1


def _causal_bound(qi, block_q, block_k, n_blocks):
    """K-block iteration bound for causal masking: ceil((qi+1)·BQ / BK)
    covers exactly the unmasked columns."""
    return jnp.minimum(
        n_blocks, ((qi + 1) * block_q + block_k - 1) // block_k
    )


def _apply_causal_mask(s, qi, j, block_q, block_k):
    """Mask scores above the diagonal using global row/col indices."""
    rows = qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0
    )
    cols = j * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1
    )
    return jnp.where(rows >= cols, s, _NEG_INF)


def _apply_length_mask(s, j, block_k, kv_len):
    """Mask key columns at or beyond the sequence's valid length."""
    cols = j * block_k + jax.lax.broadcasted_iota(
        jnp.int32, s.shape, 1
    )
    return jnp.where(cols < kv_len, s, _NEG_INF)


def _apply_window_mask(s, qi, j, block_q, block_k, window):
    """Causal sliding window: row attends cols in (row-window, row] —
    mask row - col >= window (the >= diagonal side is the causal
    mask's job). Every row keeps its own diagonal, so no row is ever
    fully masked."""
    rows = qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, s.shape, 0
    )
    cols = j * block_k + jax.lax.broadcasted_iota(
        jnp.int32, s.shape, 1
    )
    return jnp.where(rows - cols < window, s, _NEG_INF)


def _window_start(qi, block_q, block_k, window):
    """First K block any row of Q block qi can see: lowest needed col
    is qi*BQ - window + 1."""
    return jnp.maximum(0, (qi * block_q - window + 1) // block_k)


def _length_bound(kv_len, block_k, n_blocks):
    """K-block iteration bound under padding: blocks wholly past the
    valid length contribute nothing."""
    return jnp.minimum(n_blocks, (kv_len + block_k - 1) // block_k)


def _fwd_kernel(q_ref, k_ref, v_ref, *rest, scale, causal,
                block_q, block_k, padded=False, window=None):
    if padded:
        len_ref, o_ref, lse_ref = rest
        kv_len = len_ref[0, 0]
    else:
        o_ref, lse_ref = rest
        kv_len = None
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * scale  # [BQ, D]
    seq_k = k_ref.shape[1]
    n_blocks = seq_k // block_k
    start = 0
    if causal:
        n_blocks = _causal_bound(qi, block_q, block_k, n_blocks)
    if padded:
        n_blocks = _length_bound(kv_len, block_k, n_blocks)
    if window is not None:
        start = _window_start(qi, block_q, block_k, window)
    d = q_ref.shape[-1]

    def body(j, carry):
        m, l, acc = carry
        k = k_ref[0, pl.dslice(j * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.dslice(j * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [BQ, BK]
        if causal:
            s = _apply_causal_mask(s, qi, j, block_q, block_k)
        if padded:
            s = _apply_length_mask(s, j, block_k, kv_len)
        if window is not None:
            s = _apply_window_mask(s, qi, j, block_q, block_k, window)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return m_new, l, acc

    # stats stay 2-D [BQ, 1] throughout — Mosaic vectorizes 2-D shapes;
    # 1-D vectors hit lowering gaps
    m0 = jnp.full((block_q, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc0 = jnp.zeros((block_q, d), jnp.float32)
    m, l, acc = jax.lax.fori_loop(start, n_blocks, body, (m0, l0, acc0))
    l_safe = jnp.maximum(l, 1e-30)
    o_ref[0] = (acc / l_safe).astype(o_ref.dtype)
    # lane width comes from the out spec: 128 broadcast lanes or the
    # compact width-1 interchange layout (module docstring)
    lse_ref[0] = jnp.broadcast_to(
        m + jnp.log(l_safe), (block_q, lse_ref.shape[-1])
    )


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, o_ref, lse_ref, *rest,
               scale, causal, block_q, block_k, padded=False,
               window=None):
    if padded:
        len_ref, dq_ref = rest
        kv_len = len_ref[0, 0]
    else:
        (dq_ref,) = rest
        kv_len = None
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32)
    do = do_ref[0].astype(jnp.float32)
    lse = lse_ref[0][:, 0:1]  # [BQ, 1] — lanes are broadcast copies
    # delta[i] = rowsum(dO ⊙ O), computed in-kernel: cheaper than a
    # broadcast [seq, 128] HBM array and the O block is already small
    delta = jnp.sum(
        do * o_ref[0].astype(jnp.float32), axis=-1, keepdims=True
    )
    seq_k = k_ref.shape[1]
    n_blocks = seq_k // block_k
    start = 0
    if causal:
        n_blocks = _causal_bound(qi, block_q, block_k, n_blocks)
    if padded:
        n_blocks = _length_bound(kv_len, block_k, n_blocks)
    if window is not None:
        start = _window_start(qi, block_q, block_k, window)
    d = q_ref.shape[-1]

    def body(j, dq):
        k = k_ref[0, pl.dslice(j * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.dslice(j * block_k, block_k), :].astype(jnp.float32)
        s = scale * jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        if causal:
            s = _apply_causal_mask(s, qi, j, block_q, block_k)
        if padded:
            s = _apply_length_mask(s, j, block_k, kv_len)
        if window is not None:
            s = _apply_window_mask(s, qi, j, block_q, block_k, window)
        p = jnp.exp(s - lse)
        if padded:
            # Defense in depth, NOT load-bearing: padded query rows
            # attend finitely over the valid keys (only COLUMNS are
            # masked), so their lse is ordinary and p <= ~1; their
            # contributions already vanish because the wrapper's
            # `where` zeroes the incoming do at padded rows (making
            # do, dp, delta all zero there). Zeroing p keeps dq at
            # padded rows exactly 0 even if a caller bypasses the
            # wrapper. The only degenerate-lse case, kv_len == 0, is
            # excluded by the loop bound clamp (n_blocks == 0).
            rows = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, p.shape, 0
            )
            p = jnp.where(rows < kv_len, p, 0.0)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta)
        return dq + scale * jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    dq = jax.lax.fori_loop(
        start, n_blocks, body, jnp.zeros((block_q, d), jnp.float32)
    )
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, o_ref, lse_ref,
                *rest, scale, causal, block_q, block_k, padded=False,
                group=1, window=None):
    """dK/dV over one K block. With grouped-query attention
    (``group`` = q heads per kv head > 1) the q/do/o/lse blocks carry
    the kv head's whole GROUP of q heads in their leading dim, and
    dk/dv accumulate over the group (a static Python loop — group is
    small)."""
    if padded:
        len_ref, dk_ref, dv_ref = rest
        kv_len = len_ref[0, 0]
    else:
        dk_ref, dv_ref = rest
        kv_len = None
    ki = pl.program_id(1)
    k = k_ref[0].astype(jnp.float32)  # [BK, D]
    v = v_ref[0].astype(jnp.float32)
    seq_q = q_ref.shape[1]
    n_blocks = seq_q // block_q
    start = 0
    if causal:
        # Q blocks strictly before this K block see none of it.
        start = ki * block_k // block_q
    if padded:
        # Q blocks wholly past the valid length have do == 0 (zeroed by
        # the wrapper) and masked p — skip them.
        n_blocks = _length_bound(kv_len, block_q, n_blocks)
    if window is not None:
        # Sliding window adds an END bound over Q blocks: the last row
        # that sees any col of this K block is (ki+1)*BK - 1 + W - 1.
        n_blocks = jnp.minimum(
            n_blocks,
            ((ki + 1) * block_k - 1 + window - 1) // block_q + 1,
        )
    d = k_ref.shape[-1]

    def member_body(gm, i, dk, dv):
        q = q_ref[gm, pl.dslice(i * block_q, block_q), :].astype(
            jnp.float32
        )
        do = do_ref[gm, pl.dslice(i * block_q, block_q), :].astype(
            jnp.float32
        )
        lse = lse_ref[gm, pl.dslice(i * block_q, block_q), :][:, 0:1]
        delta = jnp.sum(
            do
            * o_ref[gm, pl.dslice(i * block_q, block_q), :].astype(
                jnp.float32
            ),
            axis=-1,
            keepdims=True,
        )
        s = scale * jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [BQ, BK]
        if causal:
            s = _apply_causal_mask(s, i, ki, block_q, block_k)
        if padded:
            # Mask key columns past the length so their dk/dv stay 0.
            s = _apply_length_mask(s, ki, block_k, kv_len)
        if window is not None:
            s = _apply_window_mask(s, i, ki, block_q, block_k, window)
        p = jnp.exp(s - lse)
        if padded:
            # Same defense-in-depth row zeroing as _dq_kernel (see the
            # comment there — padded-row lse is finite; this guards
            # wrapper-bypassing callers, it does not prevent NaNs).
            rows = i * block_q + jax.lax.broadcasted_iota(
                jnp.int32, p.shape, 0
            )
            p = jnp.where(rows < kv_len, p, 0.0)
        dv = dv + jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta)
        dk = dk + scale * jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return dk, dv

    def body(i, carry):
        dk, dv = carry
        for gm in range(group):  # static unroll; group == 1 for MHA
            dk, dv = member_body(gm, i, dk, dv)
        return dk, dv

    dk0 = jnp.zeros((block_k, d), jnp.float32)
    dv0 = jnp.zeros((block_k, d), jnp.float32)
    dk, dv = jax.lax.fori_loop(start, n_blocks, body, (dk0, dv0))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


# The preferred block size everywhere (kernel defaults, model config,
# the ring-flash hop engine): won the r04 on-chip sweep on GPT-2-medium
# seq-512 (MFU 0.563 vs 0.409 at 128). Auto-shrunk per sequence by
# _pick_block; retune HERE so the gate (supports_seq) and every engine
# stay in agreement.
DEFAULT_BLOCK = 512


def _pick_block(seq: int, preferred: int = DEFAULT_BLOCK) -> int:
    b = min(preferred, seq)
    while seq % b:
        b //= 2
    return max(b, 1)


def supports_seq(
    t: int, block_q: int = DEFAULT_BLOCK, block_k: int = DEFAULT_BLOCK
) -> bool:
    """Whether the kernels can tile this sequence length. Mosaic needs
    each block's trailing dims to be (8k, 128k)-aligned or the full
    array dim; we additionally require the chosen block to be 8-aligned
    (sublane) unless the whole sequence is shorter than one sublane —
    full-dim unaligned tiles (e.g. ViT's 14*14+1 = 197 tokens) were
    never validated on hardware and take the dense path. (Before r04
    the check accepted ANY t <= preferred via the full-dim early-out;
    raising the preferred block to 512 would have silently routed 197
    through the kernel.)"""

    def ok(b: int) -> bool:
        return b % 8 == 0 or (b == t and t < 8)

    return ok(_pick_block(t, block_q)) and ok(_pick_block(t, block_k))


_VMEM_BUDGET_DEFAULT = 12 * 2**20  # headroom under a v5e core's ~16 MiB


def _vmem_budget() -> int:
    import os

    return int(
        os.environ.get("HOROVOD_FLASH_VMEM_BUDGET", _VMEM_BUDGET_DEFAULT)
    )


def bwd_vmem_bytes(
    seq: int,
    d: int,
    h_per_kv: int = 1,
    itemsize: int = 2,
    block_k: int = None,
) -> int:
    """Per-program VMEM staging estimate for the dK/dV backward kernel
    — the family's largest stager. With grouped-query attention it
    fetches the KV row's whole q-head group whole-sequence ((r, seq, d)
    blocks for q/do/o plus an (r, seq, lanes) fp32 lse), so the
    footprint grows r-fold on top of the whole-sequence staging the
    module header documents (ADVICE r4). e.g. r=8, seq=4096, d=128,
    bf16: ~25 MiB — past a v5e core's ~16 MiB."""
    lanes = _interchange_lanes()
    bk = _pick_block(seq, block_k if block_k else DEFAULT_BLOCK)
    stage = h_per_kv * seq * (3 * d * itemsize + 4 * lanes)  # q/do/o+lse
    stage += 4 * bk * d * itemsize  # k/v in-blocks + dk/dv out-blocks
    return stage


def fits_vmem(
    seq: int,
    d: int,
    h_per_kv: int = 1,
    itemsize: int = 2,
    block_k: int = None,
) -> bool:
    """Whether the backward kernels' per-program staging fits the
    per-core VMEM budget (HOROVOD_FLASH_VMEM_BUDGET bytes, default
    12 MiB of a v5e core's ~16). TransformerConfig.uses_flash and the
    ulysses/ring auto-gates fall back to the dense engines when this
    fails; direct ``flash_attention``/``ring_flash_attention`` callers
    get a warning rather than an error (forward-only use stages ~3x
    less and may still compile)."""
    return (
        bwd_vmem_bytes(seq, d, h_per_kv, itemsize, block_k)
        <= _vmem_budget()
    )


def _warn_vmem(seq, d, h_per_kv, itemsize, block_k=None, what=""):
    import warnings

    warnings.warn(
        f"{what or 'flash_attention'} backward staging estimate "
        f"{bwd_vmem_bytes(seq, d, h_per_kv, itemsize, block_k) / 2**20:.0f}"
        f" MiB (seq={seq}, head_dim={d}, q-heads-per-kv={h_per_kv}) "
        f"exceeds the VMEM budget ({_vmem_budget() / 2**20:.0f} MiB)"
        f"; Mosaic compilation of the dK/dV kernel will likely fail"
        f" — use ring attention over more chips, more KV heads, or the"
        f" dense path.",
        stacklevel=3,
    )


@functools.partial(
    jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6)
)
def _flash_bhtd(q, k, v, causal, block_q, block_k, window):
    o, _ = _flash_fwd(
        q, k, v, causal, block_q, block_k, window=window
    )
    return o


@functools.partial(
    jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7)
)
def _flash_bhtd_padded(q, k, v, lens, causal, block_q, block_k, window):
    """Padded variant: ``lens`` is a (bh, 1) int32 of valid key/query
    lengths. Separate custom_vjp so the unpadded path's compiled
    artifacts are untouched."""
    o, _ = _flash_fwd(
        q, k, v, causal, block_q, block_k, lens=lens, window=window
    )
    return o


def _flash_fwd(q, k, v, causal, block_q, block_k, lens=None, h_per_kv=1,
               window=None):
    """``h_per_kv`` > 1 = grouped-query attention: k/v carry bh//r rows
    (r = h_per_kv) and each q row p reads kv row p // r — exact because
    rows are batch-major/head-minor with kv-head groups contiguous.
    ``window`` = causal sliding window width (requires causal)."""
    bh, seq, d = q.shape
    scale = 1.0 / (d ** 0.5)
    n_q = seq // block_q
    lanes = _interchange_lanes()
    r = h_per_kv
    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal,
        block_q=block_q, block_k=block_k, padded=lens is not None,
        window=window,
    )
    in_specs = [
        pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
        pl.BlockSpec((1, seq, d), lambda b, i: (b // r, 0, 0)),
        pl.BlockSpec((1, seq, d), lambda b, i: (b // r, 0, 0)),
    ]
    operands = [q, k, v]
    if lens is not None:
        in_specs.append(_lens_spec())
        operands.append(lens)
    o, lse = pl.pallas_call(
        kernel,
        grid=(bh, n_q),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec(
                (1, block_q, lanes), lambda b, i: (b, i, 0)
            ),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(q.shape, q.dtype),
            jax.ShapeDtypeStruct((bh, seq, lanes), jnp.float32),
        ],
        interpret=_interpret(),
    )(*operands)
    return o, lse


def _flash_fwd_vjp(q, k, v, causal, block_q, block_k, window):
    o, lse = _flash_fwd(
        q, k, v, causal, block_q, block_k, window=window
    )
    # Keep ONE lane as the residual — the broadcast 128-lane layout is a
    # Mosaic in-kernel constraint, not something worth holding across
    # the whole forward pass (24 BERT-large layers of (bh, seq, 128)
    # fp32 would be ~800 MB); re-broadcast transiently in the bwd.
    return o, (q, k, v, o, lse[..., 0])


def _flash_bwd_vjp_w(causal, block_q, block_k, window, res, do):
    q, k, v, o, lse_lane = res
    return _flash_bwd_impl(
        q, k, v, o, lse_lane, do, causal, block_q, block_k,
        window=window,
    )


def _flash_fwd_vjp_padded(q, k, v, lens, causal, block_q, block_k,
                          window):
    o, lse = _flash_fwd(
        q, k, v, causal, block_q, block_k, lens=lens, window=window
    )
    return o, (q, k, v, o, lse[..., 0], lens)


def _flash_bwd_vjp_padded(causal, block_q, block_k, window, res, do):
    q, k, v, o, lse_lane, lens = res
    dq, dk, dv = _flash_bwd_impl(
        q, k, v, o, lse_lane, do, causal, block_q, block_k, lens=lens,
        window=window,
    )
    return dq, dk, dv, None  # int lengths carry no cotangent


def _flash_bwd_impl(
    q, k, v, o, lse_lane, do, causal, block_q, block_k, lens=None,
    h_per_kv=1, window=None,
):
    lanes = _interchange_lanes()
    if lanes == 1:
        # compact interchange: (bh, seq, 1) — the kernels' [:, 0:1]
        # slices read it unchanged, at 1/128th the HBM traffic and
        # dkv VMEM of the broadcast layout
        lse = lse_lane[..., None]
    else:
        lse = jnp.broadcast_to(
            lse_lane[..., None], (*lse_lane.shape, lanes)
        )
    bh, seq, d = q.shape
    scale = 1.0 / (d ** 0.5)
    n_q = seq // block_q
    n_k = seq // block_k
    padded = lens is not None
    r = h_per_kv
    kv_rows = bh // r
    dq_in_specs = [
        pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
        pl.BlockSpec((1, seq, d), lambda b, i: (b // r, 0, 0)),
        pl.BlockSpec((1, seq, d), lambda b, i: (b // r, 0, 0)),
        pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
        pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
        pl.BlockSpec(
            (1, block_q, lanes), lambda b, i: (b, i, 0)
        ),
    ]
    dq_operands = [q, k, v, do, o, lse]
    # dkv grids over KV rows; with GQA (r > 1) the q/do/o/lse blocks
    # carry the kv row's whole contiguous group of q-head rows (leading
    # block dim r) and the kernel accumulates over the group.
    dkv_in_specs = [
        pl.BlockSpec((r, seq, d), lambda b, i: (b, 0, 0)),
        pl.BlockSpec((1, block_k, d), lambda b, i: (b, i, 0)),
        pl.BlockSpec((1, block_k, d), lambda b, i: (b, i, 0)),
        pl.BlockSpec((r, seq, d), lambda b, i: (b, 0, 0)),
        pl.BlockSpec((r, seq, d), lambda b, i: (b, 0, 0)),
        pl.BlockSpec(
            (r, seq, lanes), lambda b, i: (b, 0, 0)
        ),
    ]
    dkv_operands = [q, k, v, do, o, lse]
    if padded:
        dq_in_specs.append(_lens_spec())
        dq_operands.append(lens)
        dkv_in_specs.append(_lens_spec())
        # per-KV-row lengths: every r-th q row's entry (lengths are
        # per-batch, so the group's rows all agree)
        dkv_operands.append(lens[::r])
    dq = pl.pallas_call(
        functools.partial(
            _dq_kernel, scale=scale, causal=causal,
            block_q=block_q, block_k=block_k, padded=padded,
            window=window,
        ),
        grid=(bh, n_q),
        in_specs=dq_in_specs,
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        interpret=_interpret(),
    )(*dq_operands)
    dk, dv = pl.pallas_call(
        functools.partial(
            _dkv_kernel, scale=scale, causal=causal,
            block_q=block_q, block_k=block_k, padded=padded, group=r,
            window=window,
        ),
        grid=(kv_rows, n_k),
        in_specs=dkv_in_specs,
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(k.shape, k.dtype),
            jax.ShapeDtypeStruct(v.shape, v.dtype),
        ],
        interpret=_interpret(),
    )(*dkv_operands)
    return dq, dk, dv


_flash_bhtd.defvjp(_flash_fwd_vjp, _flash_bwd_vjp_w)
_flash_bhtd_padded.defvjp(_flash_fwd_vjp_padded, _flash_bwd_vjp_padded)


# Grouped-query attention entry points (additive — the MHA custom_vjps
# above keep their arity so existing callers and compiled paths are
# untouched).


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash_bhtd_gqa(q, k, v, causal, block_q, block_k, h_per_kv, window):
    o, _ = _flash_fwd(
        q, k, v, causal, block_q, block_k, h_per_kv=h_per_kv,
        window=window,
    )
    return o


def _flash_fwd_vjp_gqa(
    q, k, v, causal, block_q, block_k, h_per_kv, window
):
    o, lse = _flash_fwd(
        q, k, v, causal, block_q, block_k, h_per_kv=h_per_kv,
        window=window,
    )
    return o, (q, k, v, o, lse[..., 0])


def _flash_bwd_vjp_gqa(
    causal, block_q, block_k, h_per_kv, window, res, do
):
    q, k, v, o, lse_lane = res
    return _flash_bwd_impl(
        q, k, v, o, lse_lane, do, causal, block_q, block_k,
        h_per_kv=h_per_kv, window=window,
    )


_flash_bhtd_gqa.defvjp(_flash_fwd_vjp_gqa, _flash_bwd_vjp_gqa)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def _flash_bhtd_gqa_padded(
    q, k, v, lens, causal, block_q, block_k, h_per_kv, window
):
    o, _ = _flash_fwd(
        q, k, v, causal, block_q, block_k, lens=lens,
        h_per_kv=h_per_kv, window=window,
    )
    return o


def _flash_fwd_vjp_gqa_padded(
    q, k, v, lens, causal, block_q, block_k, h_per_kv, window
):
    o, lse = _flash_fwd(
        q, k, v, causal, block_q, block_k, lens=lens,
        h_per_kv=h_per_kv, window=window,
    )
    return o, (q, k, v, o, lse[..., 0], lens)


def _flash_bwd_vjp_gqa_padded(
    causal, block_q, block_k, h_per_kv, window, res, do
):
    q, k, v, o, lse_lane, lens = res
    dq, dk, dv = _flash_bwd_impl(
        q, k, v, o, lse_lane, do, causal, block_q, block_k, lens=lens,
        h_per_kv=h_per_kv, window=window,
    )
    return dq, dk, dv, None


_flash_bhtd_gqa_padded.defvjp(
    _flash_fwd_vjp_gqa_padded, _flash_bwd_vjp_gqa_padded
)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = False,
    block_q: int = DEFAULT_BLOCK,
    block_k: int = DEFAULT_BLOCK,
    lengths: Optional[jax.Array] = None,
    window: Optional[int] = None,
) -> jax.Array:
    """Attention over [batch, seq, heads, head_dim] tensors (the model
    layout), softmax scale 1/√d. Differentiable (custom VJP, blockwise
    recompute). Sequence length must be divisible by the chosen block
    sizes; blocks shrink automatically for short sequences.

    ``lengths`` ([batch] int): per-sequence valid token counts for
    right-padded batches — keys at or beyond a sequence's length are
    masked out of its softmax, outputs at padded query positions are
    zero, and the VJP routes no gradient through padded positions.
    Equivalent to the dense path's key-validity mask
    ``iota(t) < lengths[:, None]``, without leaving the kernel.

    Grouped-query attention: k/v may carry FEWER heads than q
    ([batch, seq, kv_heads, head_dim] with q heads % kv_heads == 0) —
    each group of q heads reads one kv head, Llama/Mistral-style. The
    kernels read the shared kv rows directly (no repeat/broadcast of
    K/V ever materializes), so the HBM savings GQA exists for are
    preserved.

    ``window`` (int, requires ``causal=True``): Mistral-style causal
    sliding window — row r attends cols in (r-window, r], masked
    in-kernel with the block loops clamped to the band on both sides,
    so COMPUTE scales with the window. K/V are still staged
    whole-sequence per program (the BlockSpecs fetch (1, seq, d)), so
    HBM->VMEM traffic and VMEM footprint remain O(seq) — at extreme
    sequence lengths use ring attention for the memory win. Composes
    with lengths and GQA."""
    b, t, h, d = q.shape
    if window is not None:
        if not causal:
            raise ValueError("window= requires causal=True")
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
        window = int(window)
        if window >= t:
            window = None  # full causal attention; skip the band masks
    kv_h = k.shape[2]
    if v.shape[2] != kv_h or h % kv_h:
        raise ValueError(
            f"kv heads must match and divide q heads: q={h}, "
            f"k={k.shape[2]}, v={v.shape[2]}"
        )
    h_per_kv = h // kv_h
    if not fits_vmem(t, d, h_per_kv, q.dtype.itemsize, block_k):
        _warn_vmem(t, d, h_per_kv, q.dtype.itemsize, block_k)
    block_q = _pick_block(t, block_q)
    block_k = _pick_block(t, block_k)

    def to_bhtd(x):
        hh = x.shape[2]
        return x.transpose(0, 2, 1, 3).reshape(b * hh, t, d)

    if lengths is None:
        if h_per_kv == 1:
            out = _flash_bhtd(
                to_bhtd(q), to_bhtd(k), to_bhtd(v),
                causal, block_q, block_k, window,
            )
        else:
            out = _flash_bhtd_gqa(
                to_bhtd(q), to_bhtd(k), to_bhtd(v),
                causal, block_q, block_k, h_per_kv, window,
            )
        return out.reshape(b, h, t, d).transpose(0, 2, 1, 3)

    lens = jnp.asarray(lengths, jnp.int32)
    if lens.shape != (b,):
        raise ValueError(
            f"lengths must be [batch]=({b},), got {lens.shape}"
        )
    lens_bh = jnp.repeat(lens, h)[:, None]  # (bh, 1)
    if h_per_kv == 1:
        out = _flash_bhtd_padded(
            to_bhtd(q), to_bhtd(k), to_bhtd(v), lens_bh,
            causal, block_q, block_k, window,
        )
    else:
        out = _flash_bhtd_gqa_padded(
            to_bhtd(q), to_bhtd(k), to_bhtd(v), lens_bh,
            causal, block_q, block_k, h_per_kv, window,
        )
    out = out.reshape(b, h, t, d).transpose(0, 2, 1, 3)
    # Zero padded QUERY rows OUTSIDE the custom_vjp. The kernel's raw
    # output there is ordinary finite attention over the valid keys
    # (rows are never masked, only columns) — zeroing is the API
    # contract, so padding can't leak downstream. Just as important,
    # this `where`'s transpose zeroes the incoming cotangent at padded
    # rows, which is what makes their dq/dk/dv contributions vanish in
    # the backward kernels.
    valid = jnp.arange(t)[None, :] < lens[:, None]  # [b, t]
    return jnp.where(valid[..., None, None], out, 0.0)
