"""Direct pipeline_1f1b usage: a 4-stage (or interleaved 2x4-stage)
MLP trained with the bounded-memory 1F1B schedule.

The composed transformer (`parallel.transformer.make_train_step`) uses
this schedule automatically for pp>1 meshes; this example shows the
raw API for CUSTOM stacks — including the pieces the composed model
exercises implicitly: a parameterized loss tail (``loss_params``), an
embedding-style front driven by the returned input cotangents
(``return_dx``), and Megatron-interleaved chunking
(``virtual_stages``).

Run (4-way CPU simulation):
    XLA_FLAGS=--xla_force_host_platform_device_count=4 \
    JAX_PLATFORMS=cpu python examples/pipeline_1f1b_train.py
    ... --virtual-stages 2     # interleaved: 8 global stages
On a TPU pod: one device per pipeline stage along the 'pp' axis.
"""

import argparse
import os

import jax

if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from horovod_tpu.parallel import pipeline_1f1b


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--pp", type=int, default=4)
    parser.add_argument("--virtual-stages", type=int, default=1)
    parser.add_argument("--n-micro", type=int, default=8)
    parser.add_argument("--steps", type=int, default=20)
    parser.add_argument("--dim", type=int, default=32)
    args = parser.parse_args()
    pp, v, d = args.pp, args.virtual_stages, args.dim
    if len(jax.devices()) < pp:
        raise SystemExit(f"need {pp} devices for pp={pp}")
    mesh = Mesh(np.asarray(jax.devices()[:pp]), ("pp",))

    rng = np.random.default_rng(0)
    # toy regression: y = tanh-stack(x) @ w_tail should match targets
    x = rng.normal(size=(args.n_micro, 4, d)).astype(np.float32)
    targets = np.tanh(x @ rng.normal(size=(d, d)).astype(np.float32))

    # device-major params: w[s, c] is global stage c*pp + s
    n_global = pp * v
    w_global = (
        0.3 * rng.normal(size=(n_global, d, d)) / np.sqrt(d)
    ).astype(np.float32)
    w = np.stack(
        [[w_global[c * pp + s] for c in range(v)] for s in range(pp)]
    )
    w_tail = (0.3 * rng.normal(size=(d, d))).astype(np.float32)

    def stage_fn(params, xb):  # params: this chunk's [d, d]
        # residual form: gradients survive v*pp stages of depth
        return xb + 0.5 * jnp.tanh(xb @ params)

    def tail_loss(tail, out, tgt):
        return jnp.mean((out @ tail - tgt) ** 2)

    lr = 0.2

    def per_device_step(x, tgt, w_shard, w_tail):
        loss, grads, tail_grads = pipeline_1f1b(
            stage_fn,
            tail_loss,
            w_shard[0] if v > 1 else w_shard[0, 0],
            x,
            tgt,
            axis_name="pp",
            loss_params=w_tail,
            virtual_stages=v,
        )
        g = grads if v > 1 else grads[None]
        return (
            loss,
            (w_shard - lr * g[None])[0][None],
            w_tail - lr * tail_grads,
        )

    step = jax.jit(
        jax.shard_map(
            per_device_step,
            mesh=mesh,
            in_specs=(P(), P(), P("pp"), P()),
            out_specs=(P(), P("pp"), P()),
            check_vma=False,
        )
    )

    losses = []
    for i in range(args.steps):
        loss, w, w_tail = step(x, targets, w, w_tail)
        losses.append(float(loss))
        if i % 5 == 0 or i == args.steps - 1:
            print(f"step {i}: loss {losses[-1]:.6f}")
    assert losses[-1] < losses[0], losses
    print(
        f"loss decreased {losses[0]:.6f} -> {losses[-1]:.6f} — "
        f"1F1B (pp={pp}, v={v}, {n_global} global stages) works"
    )


if __name__ == "__main__":
    main()
