"""InferenceEngine: prefill/decode split over compiled executables.

The serving analog of the PR 1 fusion-executor rework, with sequence
length where byte size was:

* **Prefill** is shape-polymorphic in the prompt length, so it compiles
  through a two-tier executor cache: a *bucket* tier keyed by the
  power-of-two padded length (any prompt length runs immediately, pad
  tokens are masked garbage the causal mask never attends), and an
  *exact* tier a recurring length is promoted into after
  ``promote_after`` sightings (no pad FLOPs for the lengths a workload
  actually serves). Prompts past the bucket ceiling run as successive
  ceiling-sized chunks through the SAME cache-threaded executables
  (each chunk attends to everything before it), so long prompts cost
  compile entries only for the ceiling and the remainder bucket.
* **Decode** is ONE fixed-shape jitted step — ``[slots]`` last tokens +
  ``[slots]`` cache indices in, ``[slots]`` next tokens + the updated
  cache out — over the slot-batched KV cache, which is DONATED through
  every prefill/decode executable so steady-state serving allocates no
  new cache buffers and never retraces: admissions, evictions and slot
  reuse change data, never shapes.
* **Memory plane**: the cache behind those executables is the paged
  block pool by default (`serving/paged_kv.py` — page tables ride the
  executables as extra int32 DATA inputs, so the zero-retrace invariant
  is untouched; prompt prefixes shared with the hash-keyed cache skip
  their prefill chunks outright). ``paged=False`` keeps the PR 8
  contiguous slab — the A/B baseline, bit-identical greedy output.

Executables are built ahead-of-time (``jit(...).lower(...).compile()``)
and held in engine-owned tables, so compile counts are exact, assertable
numbers (``stats()``), not inferences about jit's internal cache.

The model contract (``models/transformer.py``): ``model_fn(params,
tokens, cache, cache_index) -> (logits, new_cache)`` with per-slot
write positions and the global causal mask — any model implementing it
serves; flax Transformer modules are adapted automatically.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Callable, Dict, Optional

import numpy as np

from ..common import tracing as _tracing
from ..common.logging import get_logger
from ..common.metrics import registry as _metrics
from .paged_kv import PagePoolExhausted  # noqa: F401  (engine API)

_log = get_logger("serve.engine")

DEFAULT_MIN_BUCKET = 8
DEFAULT_PROMOTE_AFTER = 2
# exact-tier LRU bound: one executable per distinct recurring prompt
# length; the bucket tier below it is bounded by log2(ceiling) anyway
DEFAULT_EXACT_CAPACITY = 32


def next_pow2(n: int) -> int:
    return 1 << max(int(n) - 1, 0).bit_length()


def _as_model_fn(model) -> Callable:
    """Adapt a flax module (``.apply``; params or full variables dict)
    to the positional model contract; pass callables through. With the
    paged memory plane the contract grows a ``pages=`` kwarg (the
    per-row page table, `serving/paged_kv.py`) — custom callables only
    need to accept it when they are served with ``paged=True``, and a
    ``paged_attn=`` kwarg only when the fused pool-read kernel is
    resolved on (``HOROVOD_SERVE_PAGED_ATTN``) — both are forwarded
    only when engaged, so existing callables keep working."""
    apply = getattr(model, "apply", None)
    if apply is None:
        if not callable(model):
            raise TypeError(
                f"model must be a flax module or a model_fn callable, "
                f"got {type(model)!r}"
            )

        def passthrough(params, tokens, cache, cache_index, pages=None,
                        paged_attn=False):
            if pages is None:
                return model(params, tokens, cache, cache_index)
            if paged_attn:
                return model(
                    params, tokens, cache, cache_index, pages=pages,
                    paged_attn=True,
                )
            return model(params, tokens, cache, cache_index, pages=pages)

        return passthrough

    def model_fn(params, tokens, cache, cache_index, pages=None,
                 paged_attn=False):
        variables = (
            params
            if isinstance(params, dict) and "params" in params
            else {"params": params}
        )
        kwargs = dict(train=False, cache=cache, cache_index=cache_index)
        if pages is not None:
            kwargs["pages"] = pages
        if paged_attn:
            kwargs["paged_attn"] = True
        return apply(variables, tokens, **kwargs)

    return model_fn


def _sample_next(row, greedy, temps, topks, keys):
    """Per-slot sampled next token as pure DATA inside the ONE decode
    executable (the ROADMAP "parallel sampling" on-ramp): ``temps`` /
    ``topks`` are per-slot ``[slots]`` inputs, ``keys`` are per-slot
    raw uint32 PRNG keys riding the donated carry. Temperature 0 takes
    the UNTOUCHED greedy argmax branch through a ``jnp.where`` — the
    greedy token stream is bit-identical to the pre-sampling engine —
    and top-k 0 means no truncation. Keys split every step regardless
    of temperature (a constant-shape op; sampled slots stay
    reproducible however their neighbors are configured). Returns
    ``(next_tokens, new_keys)``."""
    import jax
    import jax.numpy as jnp

    vocab = row.shape[-1]
    # top-k truncation as data: threshold at the k-th largest logit
    # (k<=0 disables), then mask below it before temperature scaling
    srt = jnp.sort(row, axis=-1)[:, ::-1]
    kk = jnp.clip(topks, 1, vocab) - 1
    thr = jnp.take_along_axis(srt, kk[:, None], axis=-1)
    keep = jnp.where(topks[:, None] > 0, row >= thr, True)
    scaled = jnp.where(keep, row, -1e30) / jnp.maximum(
        temps, 1e-6
    )[:, None]

    def one(key, logits):
        next_key, sample_key = jax.random.split(key)
        return next_key, jax.random.categorical(sample_key, logits)

    new_keys, sampled = jax.vmap(one)(keys, scaled)
    nxt = jnp.where(temps > 0, sampled.astype(jnp.int32), greedy)
    return nxt, new_keys


def _default_cache_factory(model):
    cfg = getattr(model, "cfg", None)
    if cfg is None:
        raise TypeError(
            "cache_factory= is required when the model does not carry "
            "a TransformerConfig (.cfg) to derive the KV layout from"
        )
    from ..models.transformer import init_cache

    return lambda batch, max_len: init_cache(cfg, batch, max_len)


class InferenceEngine:
    """Compiled prefill/decode over a slot-batched, donated KV cache.

    Not thread-safe by design: exactly one consumer (the batcher's step
    loop) drives it, which is also what makes the donated cache carry
    sound — there is never a second reference to consume.
    """

    def __init__(
        self,
        model,
        params,
        *,
        slots: int,
        max_len: int,
        cache_factory=None,
        min_bucket: int = DEFAULT_MIN_BUCKET,
        prefill_ceiling: Optional[int] = None,
        promote_after: int = DEFAULT_PROMOTE_AFTER,
        exact_capacity: int = DEFAULT_EXACT_CAPACITY,
        donate: Optional[bool] = None,
        mesh=None,
        tp_axis: str = "tp",
        ep_axis: str = "ep",
        paged: Optional[bool] = None,
        page_tokens: Optional[int] = None,
        pages: Optional[int] = None,
        prefix_cache: Optional[bool] = None,
        page_watermark: Optional[int] = None,
        role: str = "unified",
        paged_attn=None,
    ) -> None:
        if role not in ("unified", "prefill", "decode"):
            raise ValueError(
                f"role must be unified/prefill/decode, got {role!r}"
            )
        # Role-gated executable tables (disaggregated fleet,
        # serving/kv_transfer.py): a decode-role engine NEVER builds
        # prefill executables (its sequences arrive as ingested pages —
        # prefill() raises), and a prefill-role engine compiles the
        # decode step only on the transfer-fallback path (normal
        # operation hands finished pages off before any decode, so
        # ``decode_compiles == 0`` is the assertable steady state —
        # scripts/hlo_audit.py serve_prefill_role). Each role carries
        # only its own tables: half the compile time and executable HBM.
        self.role = role
        self._model_fn = _as_model_fn(model)
        # MoE decode (PR 12): a model whose config carries an expert
        # bank gets it sharded over the mesh's ep axis up front —
        # GSPMD then partitions the expert einsums inside the SAME
        # fixed-shape prefill/decode executables (routing is data, so
        # the zero-retrace invariant is untouched; tests assert
        # decode_compiles==1 across rolling admissions with MoE on).
        model_cfg = getattr(model, "cfg", None)
        if (
            mesh is not None
            and model_cfg is not None
            and getattr(model_cfg, "moe_experts", 0)
        ):
            from ..models.transformer import shard_moe_params

            params = shard_moe_params(params, mesh, ep_axis)
        self._params = params
        if cache_factory is None:
            cache_factory = _default_cache_factory(model)
        # memory plane: paged block pool + prefix cache by default
        # (serving/paged_kv.py); paged=False keeps the PR 8 contiguous
        # slab — the A/B baseline (bench_serve.py ab_paged). None knobs
        # resolve from the env contract (docs/env_vars.md).
        from ..common import basics
        from .kv_cache import create_kv_manager

        cfg = basics.live_config()
        self.paged = True if paged is None else bool(paged)
        self.manager = create_kv_manager(
            cache_factory, slots, max_len,
            paged=self.paged,
            page_tokens=(
                cfg.serve_page_tokens if page_tokens is None
                else int(page_tokens)
            ),
            num_pages=cfg.serve_pages if pages is None else int(pages),
            prefix_cache=(
                cfg.serve_prefix_cache if prefix_cache is None
                else bool(prefix_cache)
            ),
            watermark=(
                cfg.serve_page_watermark if page_watermark is None
                else int(page_watermark)
            ),
            mesh=mesh, tp_axis=tp_axis,
        )
        self.slots = self.manager.slots
        self.max_len = self.manager.max_len
        self.min_bucket = max(int(min_bucket), 1)
        # bucket ceiling: a power of two that FITS the cache — clamp to
        # the largest pow2 <= max_len, never round past it (a prefill
        # width beyond max_len would build kv updates larger than the
        # cache leaf and fail at compile)
        floor_pow2 = 1 << (self.max_len.bit_length() - 1)
        ceiling = int(prefill_ceiling) if prefill_ceiling else floor_pow2
        self.prefill_ceiling = min(next_pow2(ceiling), floor_pow2)
        self.promote_after = max(int(promote_after), 1)
        self._mesh = mesh
        if donate is None:
            import jax

            donate = jax.devices()[0].platform in (
                "tpu", "gpu", "cuda", "rocm",
            )
        self.donate = bool(donate)
        # two-tier prefill executor cache (PR 1 design on the length
        # axis) + the one decode executable
        self._prefill_exact: "collections.OrderedDict" = (
            collections.OrderedDict()
        )
        self._prefill_bucket: Dict[int, object] = {}
        self._seen: "collections.OrderedDict" = collections.OrderedDict()
        self._exact_capacity = max(int(exact_capacity), 1)
        self._decode_exe = None
        self._decode_swept = False
        self._lock = threading.Lock()  # guards counters for stats readers
        self._counters = collections.Counter()
        # per-slot sampling state (DATA through the one decode
        # executable — see _sample_next): temperature 0 / top-k 0 =
        # greedy, the boot default for every slot
        import jax.numpy as jnp

        self._sample_temps = np.zeros((self.slots,), np.float32)
        self._sample_topks = np.zeros((self.slots,), np.int32)
        self._sample_keys = jnp.zeros((self.slots, 2), jnp.uint32)
        # fused paged-attention read (ops/paged_attention.py): resolve
        # the tri-state once — the decision is baked into the traced
        # executables, so it cannot flip mid-flight and retrace
        self.paged_attn = self._resolve_paged_attn(
            cfg.serve_paged_attn if paged_attn is None else paged_attn,
            model_cfg,
        )
        # persistent executable disk tier (common/exe_cache.py): below
        # the in-memory exact/bucket tables. When HOROVOD_EXE_CACHE is
        # unset every path below is byte-identical to the memory-only
        # engine. ``_promoting`` tracks in-flight background
        # bucket→exact promotions (the PR 17 hot-path-compile fix).
        from ..common import exe_cache as _exe_cache

        self._exe_base = _exe_cache.cache_dir()
        self._exe_fp = (
            _exe_cache.topology_fingerprint() if self._exe_base else None
        )
        self._promoting: set = set()
        self._promote_threads: list = []
        if self._exe_base:
            self._warm_start()

    def _resolve_paged_attn(self, requested, model_cfg) -> bool:
        """Resolve the ``HOROVOD_SERVE_PAGED_ATTN`` tri-state against
        the fallback ladder (ops/paged_attention.py): ``auto`` engages
        the kernel only on real TPU backends (interpret mode is for
        tests, not production CPU decode — and the gather oracle keeps
        CPU serving bit-comparable with the slab baseline), ``on``
        forces it anywhere Pallas can run it, ``off`` — and the slab
        plane — always ride the gather read. A requested-but-impossible
        kernel falls back LOUDLY: warn log + the
        ``serve.paged_attn_fallbacks`` counter. The check here uses the
        decode geometry (one token per slot); wider prefill chunks are
        re-checked per trace inside ``_cached_attention`` and fall back
        per-executable the same loud way."""
        if isinstance(requested, bool):
            requested = "on" if requested else "off"
        requested = str(requested).lower()
        if requested not in ("auto", "on", "off"):
            raise ValueError(
                f"paged_attn must be auto/on/off, got {requested!r}"
            )
        if not self.paged or requested == "off":
            return False
        import jax

        backend = jax.default_backend()
        if requested == "auto" and backend != "tpu":
            return False
        from ..ops import paged_attention as _pa

        leaf = jax.tree_util.tree_leaves(self.manager.cache)[0]
        page_tokens, kv_heads, head_dim = leaf.shape[1:4]
        heads = (
            getattr(model_cfg, "num_heads", 0) or kv_heads
            if model_cfg is not None else kv_heads
        )
        group = max(int(heads) // int(kv_heads), 1)
        reason = _pa.unsupported_reason(
            int(head_dim), int(page_tokens), queries=group,
            backend=backend,
        )
        if reason is None and model_cfg is not None and getattr(
            model_cfg, "sliding_window", 0
        ):
            reason = "sliding_window is not implemented by the paged kernel"
        if reason is None:
            return True
        _log.warning(
            "paged_attn=%s requested but the kernel path is "
            "unsupported (%s); serving on the gather read",
            requested, reason,
        )
        with self._lock:
            self._counters["paged_attn_fallbacks"] += 1
        _metrics.counter("serve.paged_attn_fallbacks")
        return False

    # -------------------------------------------------------- compile layer

    def _out_shardings(self, decode: bool = False):
        """With a tp-sharded cache, pin the outputs: the cache keeps
        its sharding (a changed output sharding would break the donated
        carry on the NEXT call), the token output — and the decode
        step's PRNG-key carry — replicated."""
        if self.manager.sharding is None:
            return None
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        rep = NamedSharding(self._mesh, P())
        cache_sh = jax.tree_util.tree_map(
            lambda _: self.manager.sharding, self.manager.cache
        )
        return (rep, cache_sh, rep) if decode else (rep, cache_sh)

    def _lower(self, fn, args, decode: bool = False):
        """THE one jit-option assembly (donated cache carry at arg 1,
        pinned out-shardings): ``_compile`` finishes it into the
        executable, ``lowered_decode``/``lowered_prefill`` hand the
        Lowered to the static-analysis surface — one builder, so the
        audited program can never drift from the executed one. The
        decode step additionally donates its last argument — the
        per-slot PRNG keys, which carry exactly like the cache — and
        returns ``(tokens, cache, keys)``."""
        import jax

        kwargs = {}
        if self.donate:
            donate = (1,)  # the cache carry
            if decode:
                donate = donate + (len(args) - 1,)  # the key carry
            kwargs["donate_argnums"] = donate
        out_sh = self._out_shardings(decode=decode)
        if out_sh is not None:
            kwargs["out_shardings"] = out_sh
        return jax.jit(fn, **kwargs).lower(*args)

    def _donation_sig(self, n_args: int, decode: bool) -> str:
        from ..common import exe_cache as _exe_cache

        if not self.donate:
            return "none"
        donate = (1,) + ((n_args - 1,) if decode else ())
        return _exe_cache.donation_signature(donate)

    def _compile(self, fn, args, kind: str, decode: bool = False,
                 meta=None):
        """Compile through the disk tier when one is configured: a hit
        deserializes a previously-persisted executable
        (``{kind}_disk_hits``, NOT a compile — warm processes assert
        ``decode_compiles == 0``), a miss compiles and persists for
        the next process/standby."""
        lowered = self._lower(fn, args, decode=decode)
        if self._exe_base is not None:
            from ..common import exe_cache as _exe_cache

            exe, hit = _exe_cache.get_or_compile(
                lowered,
                family=f"serve.{kind}",
                donation=self._donation_sig(len(args), decode),
                meta=meta,
                fingerprint=self._exe_fp,
                base=self._exe_base,
            )
            with self._lock:
                self._counters[
                    f"{kind}_disk_hits" if hit else f"{kind}_compiles"
                ] += 1
            return exe
        exe = lowered.compile()
        with self._lock:
            self._counters[f"{kind}_compiles"] += 1
        return exe

    def _decode_args(self, tokens):
        lengths = self.manager.lengths_array()
        args = (self._params, self.manager.cache, tokens, lengths)
        if self.paged:
            args = args + (self.manager.tables_array(),)
        return args + (
            self._sample_temps.copy(),
            self._sample_topks.copy(),
            self._sample_keys,
        )

    def lowered_decode(self):
        """The decode step's ``jax.stages.Lowered`` under exactly the
        jit options the engine compiles with (shared :meth:`_lower`) —
        the static-analysis surface ``horovod_tpu.analysis`` parses
        for the donation / collective invariants
        (scripts/hlo_audit.py roster)."""
        return self._lower(
            self._decode_fn(),
            self._decode_args(np.zeros((self.slots,), np.int32)),
            decode=True,
        )

    def lowered_prefill(self, width: int):
        """A prefill executable's Lowered at ``width`` tokens, same
        contract as :meth:`lowered_decode`."""
        return self._lower(
            self._prefill_fn(int(width)), self._prefill_args(int(width))
        )

    def _prefill_fn(self, width: int):
        """Build the prefill computation for a fixed token width: run
        the cache-threaded model over the chunk, emit the greedy next
        token at ``last_pos`` (pad positions beyond it are causal-masked
        junk a later write overwrites before it is ever attendable).

        Slab layout: slice the slot's cache row, model over the row,
        write the row back. Paged layout: the model scatters straight
        into the donated block pool through the slot's page-table row
        (no slice/write-back — the table IS the slot)."""
        import jax
        import jax.numpy as jnp
        from jax import lax

        model_fn = self._model_fn

        if self.paged:
            paged_attn = self.paged_attn

            def fn(params, cache, tokens, table_row, start, last_pos):
                logits, cache = model_fn(
                    params, tokens, cache, jnp.reshape(start, (1,)),
                    pages=table_row[None], paged_attn=paged_attn,
                )
                row = lax.dynamic_index_in_dim(
                    logits[0], last_pos, axis=0, keepdims=False
                )
                return jnp.argmax(row).astype(jnp.int32), cache

            return fn

        def fn(params, cache, tokens, slot, start, last_pos):
            slot_cache = jax.tree_util.tree_map(
                lambda leaf: lax.dynamic_slice_in_dim(leaf, slot, 1, 0),
                cache,
            )
            logits, new_slot = model_fn(
                params, tokens, slot_cache, jnp.reshape(start, (1,))
            )
            cache = jax.tree_util.tree_map(
                lambda leaf, upd: lax.dynamic_update_slice_in_dim(
                    leaf, upd, slot, 0
                ),
                cache,
                new_slot,
            )
            row = lax.dynamic_index_in_dim(
                logits[0], last_pos, axis=0, keepdims=False
            )
            return jnp.argmax(row).astype(jnp.int32), cache

        return fn

    def _decode_fn(self):
        import jax.numpy as jnp

        model_fn = self._model_fn

        if self.paged:
            paged_attn = self.paged_attn

            def fn(params, cache, tokens, lengths, tables, temps, topks,
                   keys):
                logits, cache = model_fn(
                    params, tokens[:, None], cache, lengths,
                    pages=tables, paged_attn=paged_attn,
                )
                row = logits[:, 0, :]
                greedy = jnp.argmax(row, axis=-1).astype(jnp.int32)
                nxt, keys = _sample_next(row, greedy, temps, topks, keys)
                return nxt, cache, keys

            return fn

        def fn(params, cache, tokens, lengths, temps, topks, keys):
            logits, cache = model_fn(
                params, tokens[:, None], cache, lengths
            )
            row = logits[:, 0, :]
            greedy = jnp.argmax(row, axis=-1).astype(jnp.int32)
            nxt, keys = _sample_next(row, greedy, temps, topks, keys)
            return nxt, cache, keys

        return fn

    def _prefill_args(self, width: int):
        if self.paged:
            return (
                self._params,
                self.manager.cache,
                np.zeros((1, width), np.int32),
                np.full(
                    self.manager.pages_per_slot,
                    self.manager.sentinel, np.int32,
                ),
                np.int32(0),
                np.int32(0),
            )
        return (
            self._params,
            self.manager.cache,
            np.zeros((1, width), np.int32),
            np.int32(0),
            np.int32(0),
            np.int32(0),
        )

    def _bucket_exe(self, width: int):
        """Bucket-tier lookup/compile for an executable of exactly
        ``width`` tokens (shared by the two-tier path and the
        chunked-prefill loop — one home for the hit accounting)."""
        exe = self._prefill_bucket.get(width)
        if exe is None:
            exe = self._compile(
                self._prefill_fn(width),
                self._prefill_args(width),
                "prefill",
                meta={"width": int(width), "tier": "bucket"},
            )
            self._prefill_bucket[width] = exe
        else:
            self._counters["prefill_bucket_hits"] += 1
        return exe

    def _get_prefill_exe(self, length: int, avail: Optional[int] = None):
        """Two-tier lookup for the final (or only) chunk of ``length``
        tokens: exact executable if promoted, else the power-of-two
        bucket. Returns ``(exe, width)``. ``avail`` is the room left in
        the slot (max_len − start): when the padded bucket would
        overrun it (possible only for a non-pow2-multiple max_len
        tail), the chunk compiles at its exact width instead — padding
        past the slot would clamp-shift the slab write or drop the pad
        pages' worth of paged writes."""
        exact = self._prefill_exact
        if length in exact:
            exact.move_to_end(length)
            self._counters["prefill_exact_hits"] += 1
            return exact[length], length
        count = self._seen.get(length, 0) + 1
        self._seen[length] = count
        self._seen.move_to_end(length)
        while len(self._seen) > 4 * self._exact_capacity:
            self._seen.popitem(last=False)  # bounded, PR 1 lesson
        bucket = min(
            max(next_pow2(length), self.min_bucket), self.prefill_ceiling
        )
        forced = avail is not None and bucket > avail
        if count >= self.promote_after or forced:
            # disk tier FIRST: a recurring prompt length a prior run
            # promoted deserializes instead of paying the promotion
            # compile (the PR 17 hot-path latency spike)
            exe = self._disk_prefill_exact(length)
            if exe is not None:
                self._install_exact(length, exe)
                return exe, length
            if forced:
                # the padded bucket would overrun the slot — no bucket
                # executable CAN serve this chunk, so the compile has
                # to happen here, synchronously
                exe = self._compile(
                    self._prefill_fn(length),
                    self._prefill_args(length),
                    "prefill",
                    meta={"width": int(length), "tier": "exact"},
                )
                self._install_exact(length, exe)
                return exe, length
            # off the hot path: the bucket executable keeps serving
            # while a background thread compiles (and persists) the
            # exact one; it installs under the lock when ready
            self._spawn_promotion(length)
        exe = self._bucket_exe(bucket)
        self._counters["prefill_pad_tokens"] += bucket - length
        return exe, bucket

    def _install_exact(self, length: int, exe) -> None:
        with self._lock:
            self._prefill_exact[length] = exe
            self._counters["prefill_promotions"] += 1
            while len(self._prefill_exact) > self._exact_capacity:
                self._prefill_exact.popitem(last=False)

    def _disk_prefill_exact(self, length: int):
        """Exact-width prefill entry from the disk tier, or None.
        Costs one trace (no XLA compile) + one file read — scheduler-
        thread safe."""
        if self._exe_base is None or self.role == "decode":
            return None
        from ..common import exe_cache as _exe_cache

        args = self._abstract_prefill_args(length)
        lowered = self._lower(self._prefill_fn(length), args)
        exe = _exe_cache.load(
            "serve.prefill",
            _exe_cache.hlo_fingerprint(lowered),
            donation=self._donation_sig(len(args), False),
            fingerprint=self._exe_fp,
            base=self._exe_base,
        )
        if exe is not None:
            with self._lock:
                self._counters["prefill_disk_hits"] += 1
        return exe

    def _spawn_promotion(self, length: int) -> None:
        """Background bucket→exact promotion: lowers from ABSTRACT
        avals (the live donated cache buffers are never touched off
        the scheduler thread), compiles, persists to the disk tier,
        installs under the lock. Deduplicated per length."""
        with self._lock:
            if length in self._promoting:
                return
            self._promoting.add(length)

        def work():
            try:
                exe = self._compile(
                    self._prefill_fn(length),
                    self._abstract_prefill_args(length),
                    "prefill",
                    meta={"width": int(length), "tier": "exact"},
                )
                self._install_exact(length, exe)
                with self._lock:
                    self._counters["prefill_bg_promotions"] += 1
            except Exception:  # pragma: no cover — keep serving on the
                _log.exception(  # bucket tier; promotion is an upgrade
                    "background promotion for width %d failed", length
                )
            finally:
                with self._lock:
                    self._promoting.discard(length)

        t = threading.Thread(
            target=work, daemon=True, name=f"serve-promote-{length}"
        )
        self._promote_threads.append(t)
        t.start()

    def drain_promotions(self, timeout: float = 60.0) -> bool:
        """Join outstanding background promotions (tests/bench warmup:
        deterministic compile counts need a join point). True when
        everything landed."""
        deadline = time.monotonic() + timeout
        for t in list(self._promote_threads):
            t.join(max(deadline - time.monotonic(), 0.0))
        self._promote_threads = [
            t for t in self._promote_threads if t.is_alive()
        ]
        return not self._promote_threads

    def _abstract_prefill_args(self, width: int):
        """:meth:`_prefill_args` as avals: background/warm-start
        lowering must not hold references to the donated cache carry
        (a decode step may consume it mid-trace)."""
        import jax

        from jax.sharding import NamedSharding

        def _sds(leaf):
            # keep a leaf's MESH sharding only: the abstract lowering
            # must hash to the same HLO fingerprint as the concrete
            # one, and an explicit SingleDeviceSharding on the aval
            # stamps mhlo.sharding attrs a committed array doesn't.
            # shape/dtype/sharding attributes survive donation (only
            # the buffer is deleted).
            sh = getattr(leaf, "sharding", None)
            if isinstance(sh, NamedSharding):
                return jax.ShapeDtypeStruct(
                    leaf.shape, leaf.dtype, sharding=sh
                )
            return jax.ShapeDtypeStruct(np.shape(leaf), np.asarray(leaf).dtype)

        params = jax.tree_util.tree_map(_sds, self._params)
        cache = jax.tree_util.tree_map(_sds, self.manager.cache)
        concrete = self._prefill_args(width)
        return (params, cache) + concrete[2:]

    # ----------------------------------------------------------- warm start

    def _warm_start(self) -> None:
        """Role-gated table warm-start from the disk tier at init: the
        decode executable loads by exact key; prefill entries are
        enumerated from the cache headers (the engine cannot know
        which widths prior runs promoted), each candidate re-lowered
        at its recorded width and loaded by key — an entry from a
        different model, world size, or JAX version simply misses
        (the invalidation rules live in ``exe_cache.load``). Decode
        workers load ONLY decode entries; prefill workers only
        prefill ones. Zero compiles happen here by construction: a
        miss leaves the table cold for the normal lazy path."""
        from ..common import exe_cache as _exe_cache

        t0 = time.monotonic()
        loaded = 0
        if self.role in ("unified", "decode"):
            args = self._decode_args(np.zeros((self.slots,), np.int32))
            lowered = self._lower(self._decode_fn(), args, decode=True)
            exe = _exe_cache.load(
                "serve.decode",
                _exe_cache.hlo_fingerprint(lowered),
                donation=self._donation_sig(len(args), True),
                fingerprint=self._exe_fp,
                base=self._exe_base,
            )
            if exe is not None:
                self._decode_exe = exe
                with self._lock:
                    self._counters["decode_disk_hits"] += 1
                loaded += 1
        if self.role in ("unified", "prefill"):
            candidates = []
            seen = set()
            for header in _exe_cache.scan(
                "serve.prefill", fingerprint=self._exe_fp,
                base=self._exe_base,
            ):
                meta = header.get("meta") or {}
                width, tier = meta.get("width"), meta.get("tier")
                if (
                    not isinstance(width, int)
                    or tier not in ("bucket", "exact")
                    or not 0 < width <= self.max_len
                    or (width, tier) in seen
                ):
                    continue
                seen.add((width, tier))
                candidates.append((width, tier))
            for width, tier in candidates[: self._exact_capacity + 16]:
                args = self._prefill_args(width)
                lowered = self._lower(self._prefill_fn(width), args)
                exe = _exe_cache.load(
                    "serve.prefill",
                    _exe_cache.hlo_fingerprint(lowered),
                    donation=self._donation_sig(len(args), False),
                    fingerprint=self._exe_fp,
                    base=self._exe_base,
                )
                if exe is None:
                    continue
                with self._lock:
                    self._counters["prefill_disk_hits"] += 1
                    if tier == "exact":
                        self._prefill_exact[width] = exe
                        while (
                            len(self._prefill_exact) > self._exact_capacity
                        ):
                            self._prefill_exact.popitem(last=False)
                    else:
                        self._prefill_bucket[width] = exe
                loaded += 1
        if loaded:
            ms = (time.monotonic() - t0) * 1e3
            _metrics.gauge("serve.warm_start_ms", ms)
            _metrics.counter("serve.warm_started_exes", loaded)
            _log.info(
                "warm-started %d executable(s) from %s in %.0f ms",
                loaded, self._exe_base, ms,
            )

    # ------------------------------------------------------------ execution

    def _slot_arg(self, slot: int):
        """The per-slot routing argument of a prefill executable: the
        page-table row under paging (re-fetched every chunk — earlier
        chunks may have allocated), the slot index for the slab."""
        if self.paged:
            return self.manager.table_row(slot)
        return np.int32(slot)

    def prefill(self, slot: int, prompt, trace=None) -> int:
        """Run the prompt through the slot's cache; returns the first
        greedy token. Prompts past the bucket ceiling stream as
        ceiling-sized chunks (each attends to the cache written so
        far), the remainder through the two-tier cache like any short
        prompt.

        Paged plane: the prompt's leading full pages are first looked
        up in the prefix cache — every hit is attached by page-table
        pointer write and its prefill chunk NEVER RUNS (the
        ``prefill_chunks_skipped`` counter). The final prompt token is
        always recomputed even on a full-prefix hit, so the first
        greedy token's logits exist and shared pages stay immutable.
        The remaining pages are allocated here (allocate-on-write);
        after the prefill the slot's full prompt pages are published
        back into the prefix index."""
        if self.role == "decode":
            raise RuntimeError(
                "prefill on a decode-role engine: decode workers take "
                "finished KV pages over the transfer wire "
                "(serving/kv_transfer.py), never prompts — the prefill "
                "executable table is role-gated out"
            )
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        n = prompt.size
        if not 0 < n <= self.max_len:
            raise ValueError(
                f"prompt length {n} outside (0, {self.max_len}]"
            )
        start = 0
        hashes = []
        if self.paged:
            from .paged_kv import page_hashes

            mgr = self.manager
            if mgr.prefix_cache_enabled:
                hashes = page_hashes(prompt, mgr.page_tokens)
                hits = mgr.lookup_prefix(hashes)
                # cap: the LAST prompt token is always recomputed (its
                # logits produce the first output; recomputing it also
                # means no write ever targets a shared page)
                k = min(len(hits), (n - 1) // mgr.page_tokens)
                if k:
                    mgr.attach_prefix(slot, hits[:k])
                    start = k * mgr.page_tokens
                    self._counters["prefill_chunks_skipped"] += k
                    self._counters["prefill_tokens_skipped"] += start
            if not mgr.ensure_pages(slot, n, write_from=start):
                raise PagePoolExhausted([slot])
        ceiling = self.prefill_ceiling
        while n - start > ceiling:
            exe = self._bucket_exe(ceiling)
            self._counters["chunked_prefill_chunks"] += 1
            # trace plane: one span per streamed chunk — spans open
            # only for traced requests (trace=None ⇒ start_span is a
            # no-op returning None), so the default path is untouched
            cspan = _tracing.start_span(
                "engine.prefill_chunk", trace,
                start=int(start), width=int(ceiling), slot=slot,
            )
            tok, self.manager.cache = exe(
                self._params,
                self.manager.cache,
                prompt[None, start:start + ceiling],
                self._slot_arg(slot),
                np.int32(start),
                np.int32(ceiling - 1),
            )
            if cspan is not None:
                cspan.end()
            if self.paged_attn:
                self._counters["paged_attn_calls"] += 1
            start += ceiling
        tail = n - start
        exe, width = self._get_prefill_exe(tail, avail=self.max_len - start)
        tokens = np.zeros((1, width), np.int32)
        tokens[0, :tail] = prompt[start:]
        cspan = _tracing.start_span(
            "engine.prefill_chunk", trace,
            start=int(start), width=int(width), slot=slot, tail=True,
        )
        tok, self.manager.cache = exe(
            self._params,
            self.manager.cache,
            tokens,
            self._slot_arg(slot),
            np.int32(start),
            np.int32(tail - 1),
        )
        if cspan is not None:
            cspan.end()
        if self.paged_attn:
            self._counters["paged_attn_calls"] += 1
        self.manager.set_length(slot, n)
        self._counters["prefills"] += 1
        if self.paged and hashes:
            self.manager.publish_prefix(slot, hashes)
        return int(tok)

    def prepare_decode(self) -> list:
        """Pre-decode page sweep (paged plane): allocate each active
        slot's next-token page; returns the slots the pool could NOT
        supply (always ``[]`` for the slab). The batcher calls this
        BEFORE :meth:`decode_step` and pauses requests until the list
        is empty — exhaustion is a scheduling event, not an error."""
        if not self.paged:
            return []
        starved = self.manager.ensure_decode_pages()
        # a clean sweep is remembered so the next decode_step doesn't
        # repeat it (the batcher sweeps right before stepping); any
        # starvation leaves the flag down and decode_step re-checks
        self._decode_swept = not starved
        return starved

    def decode_step(self, tokens: np.ndarray) -> np.ndarray:
        """ONE fixed-shape step over every slot: feed each slot's last
        token at its cache index, return each slot's greedy next token.
        Inactive slots (length 0) compute masked junk at position 0
        that the next occupant's prefill overwrites — the price of a
        shape that never changes is a little wasted compute, never a
        retrace. (Paged: an inactive slot's page table is all sentinel,
        so even its junk write is dropped.)"""
        tokens = np.asarray(tokens, np.int32).reshape(self.slots)
        if self.paged:
            if not self._decode_swept:
                starved = self.prepare_decode()
                if starved:
                    raise PagePoolExhausted(starved)
            self._decode_swept = False
        args = self._decode_args(tokens)
        if self._decode_exe is None:
            self._decode_exe = self._compile(
                self._decode_fn(), args, "decode", decode=True,
                meta={"slots": int(self.slots)},
            )
        out, self.manager.cache, self._sample_keys = self._decode_exe(
            *args
        )
        self._counters["decode_steps"] += 1
        if self.paged_attn:
            self._counters["paged_attn_calls"] += 1
        return np.asarray(out)

    # ------------------------------------------------------------- sampling

    def set_sampling(self, slot: int, temperature: float = 0.0,
                     top_k: int = 0, seed: Optional[int] = None) -> None:
        """Arm a slot's sampling knobs (pure DATA into the one decode
        executable — never a retrace): ``temperature<=0`` keeps the
        bit-identical greedy branch, ``top_k<=0`` disables truncation.
        ``seed`` re-seeds the slot's PRNG key (an eager ``.at[].set``
        data op on the key carry); the batcher derives a stable
        per-request default so replays reproduce."""
        import jax

        self._sample_temps[slot] = float(temperature)
        self._sample_topks[slot] = int(top_k)
        if seed is not None:
            self._sample_keys = self._sample_keys.at[int(slot)].set(
                jax.random.key_data(jax.random.PRNGKey(int(seed)))
            )

    def clear_sampling(self, slot: int) -> None:
        """Back to greedy on slot free — the next occupant inherits
        nothing."""
        self._sample_temps[slot] = 0.0
        self._sample_topks[slot] = 0

    def export_sampling(self, slot: int) -> dict:
        """Snapshot a slot's armed sampling state for live migration:
        knobs plus the RAW mid-stream PRNG key (NOT the seed — the key
        has been split once per decode step, so re-seeding on the
        receiver would fork the sampled sequence; importing the key
        data continues it bit-identically)."""
        key = np.asarray(self._sample_keys[int(slot)], np.uint32)
        return {
            "temperature": float(self._sample_temps[int(slot)]),
            "top_k": int(self._sample_topks[int(slot)]),
            "key": [int(x) for x in key.reshape(-1)],
        }

    def import_sampling(self, slot: int, state: dict) -> None:
        """Arm a slot from an :meth:`export_sampling` snapshot — data
        ops only (host arrays + an eager ``.at[].set`` on the key
        carry), so a migrated resume never retraces."""
        self._sample_temps[int(slot)] = float(state.get("temperature", 0.0))
        self._sample_topks[int(slot)] = int(state.get("top_k", 0))
        key = state.get("key")
        if key is not None:
            self._sample_keys = self._sample_keys.at[int(slot)].set(
                np.asarray(key, np.uint32)
            )

    # ----------------------------------------------- KV transfer primitives

    def gather_pages(self, kept):
        """Device-side gather of a detached slot's pages — the cheap,
        scheduler-thread half of :meth:`extract_pages`: one indexed
        read per cache leaf, dispatched asynchronously, materializing
        FRESH device buffers that share no storage with the
        executables' donated carry (so later decode steps can donate
        the pool away freely while these wait to be serialized).
        Returns per-leaf device arrays in ``tree_leaves`` order; hand
        them to :meth:`pages_to_host` OFF the scheduler thread."""
        if not self.paged:
            raise RuntimeError("gather_pages needs the paged plane")
        import jax

        idx = np.asarray([p for _, p in kept], np.int32)
        return [
            leaf[idx] for leaf in jax.tree_util.tree_leaves(
                self.manager.cache
            )
        ]

    def pages_to_host(self, raw, kept, length: int):
        """The blocking half of :meth:`extract_pages`: ONE batched
        ``jax.device_get`` over every leaf's gathered pages (not a
        device round-trip per page or per leaf), then zero the tail
        page at and past ``length`` — garbage rows must not travel and
        must not raise an int8 block scale (zeros never move an
        absmax). Thread-safe: ``raw`` are the fresh buffers
        :meth:`gather_pages` made, so this runs on the transfer
        handoff thread without touching engine state — an in-flight
        transfer can no longer stall decode admission rounds."""
        import jax

        pt = self.manager.page_tokens
        tail_valid = int(length) - (len(kept) - 1) * pt
        out = []
        for arr in jax.device_get(raw):
            arr = np.asarray(arr)
            if 0 <= tail_valid < pt:
                if not arr.flags.writeable:
                    arr = arr.copy()
                arr[-1, tail_valid:] = 0
            out.append(arr)
        return out

    def extract_pages(self, kept, length: int):
        """Host copies of a detached slot's pages for the transfer wire
        (serving/kv_transfer.py): one ``[n_pages, page_tokens, kv_heads,
        head_dim]`` ndarray per cache leaf, in ``tree_leaves`` order,
        with every position at or past ``length`` zeroed. Composed from
        :meth:`gather_pages` (scheduler-thread device gather) +
        :meth:`pages_to_host` (one batched ``device_get``) — the
        transfer sender splits the two halves across threads so only
        the async gather rides the scheduler hot path; this one-call
        form serves synchronous users (pack_pages, the audit
        roster)."""
        return self.pages_to_host(self.gather_pages(kept), kept, length)

    def ingest_attach(self, slot, logical, arrays, length, hashes=()):
        """Receiver side of a KV transfer: land foreign page payloads
        as refcounted LOCAL pages and point the slot's table at them.
        ``arrays`` are the per-leaf ``[n_pages, page_tokens, ...]``
        payloads (``extract_pages`` order, already dequantized to the
        pool dtype); ``hashes`` are the sender's chained prefix hashes
        so this worker's prefix cache warms from the transfer.

        Returns the kept-pages list now backing the slot, or None when
        the pool is dry (the server's 503 → the sender falls back).
        Pure data plane: the writes are eager device ops on the pool
        (the ``_cow`` pattern) and the table update is bookkeeping —
        shapes never change, so the decode executable compiled for the
        first admission serves every later ingest (zero retraces).
        Scheduler-thread only (single consumer of the pool)."""
        if not self.paged:
            raise RuntimeError("ingest_attach needs the paged plane")
        import jax

        mgr = self.manager
        phys = mgr.ingest_alloc(len(logical))
        if phys is None:
            return None
        idx = np.asarray(phys, np.int32)
        leaves = jax.tree_util.tree_leaves(mgr.cache)
        treedef = jax.tree_util.tree_structure(mgr.cache)
        new_leaves = [
            leaf.at[idx].set(np.asarray(arr, dtype=leaf.dtype))
            for leaf, arr in zip(leaves, arrays)
        ]
        mgr.cache = jax.tree_util.tree_unflatten(treedef, new_leaves)
        kept = list(zip([int(lp) for lp in logical], phys))
        mgr.reattach(slot, kept, int(length))
        if hashes:
            mgr.publish_hashes(kept, list(hashes))
        with self._lock:
            self._counters["transfer_ingests"] += 1
        return kept

    # ----------------------------------------------------------------- stats

    def stats(self) -> Dict[str, float]:
        with self._lock:
            out = dict(self._counters)
        for key in (
            "prefill_compiles", "decode_compiles", "prefills",
            "decode_steps", "prefill_exact_hits", "prefill_bucket_hits",
            "prefill_promotions", "prefill_pad_tokens",
            "chunked_prefill_chunks", "prefill_chunks_skipped",
            "prefill_tokens_skipped", "transfer_ingests",
            "paged_attn_calls", "paged_attn_fallbacks",
            "prefill_disk_hits", "decode_disk_hits",
            "prefill_bg_promotions",
        ):
            out.setdefault(key, 0)
        out["prefill_exact_entries"] = len(self._prefill_exact)
        out["prefill_bucket_entries"] = len(self._prefill_bucket)
        return out

    def publish(self) -> None:
        _metrics.update("serve", self.stats())
