"""SLO instrumentation: TTFT / TPOT summaries for the serving plane.

Two latency families, the ones the Gemma-on-TPU serving paper meters
(PAPERS.md, arXiv 2605.25645):

* **TTFT** (time to first token): request submission → the first
  generated token leaving prefill. Queue wait is INCLUDED by design —
  it is what the user feels, and the difference between TTFT and
  prefill wall time is exactly the admission policy's cost.
* **TPOT** (time per output token): the decode-step wall time each
  subsequent token rode.

Samples land in bounded rings (newest ``capacity``), and ``publish()``
pushes p50/p95/count gauges into the metrics registry under ``serve.``
— so they appear on the existing ``/metrics`` endpoint
(common/telemetry.py MetricsServer) next to the training gauges, and
in flight-recorder StepStats via the registry snapshot.
``render_prometheus_summaries()`` additionally renders the two
families as proper Prometheus ``summary`` types for the serve
frontend's own ``/metrics`` route.
"""

from __future__ import annotations

import collections
import threading
from typing import Dict, List

from ..common.metrics import registry as _metrics
from ..common.telemetry import _percentile

DEFAULT_CAPACITY = 1024


class LatencyRecorder:
    """Bounded-ring p50/p95 for the two serving latency families."""

    FAMILIES = ("ttft_ms", "tpot_ms")

    def __init__(self, capacity: int = DEFAULT_CAPACITY) -> None:
        self._lock = threading.Lock()
        self._rings = {
            fam: collections.deque(maxlen=max(int(capacity), 1))
            for fam in self.FAMILIES
        }
        self._counts = {fam: 0 for fam in self.FAMILIES}
        self._sums = {fam: 0.0 for fam in self.FAMILIES}

    def record_ttft(self, ms: float) -> None:
        self._record("ttft_ms", ms)

    def record_tpot(self, ms: float) -> None:
        self._record("tpot_ms", ms)

    def _record(self, fam: str, ms: float) -> None:
        with self._lock:
            self._rings[fam].append(float(ms))
            self._counts[fam] += 1
            self._sums[fam] += float(ms)

    def summaries(self) -> Dict[str, Dict[str, float]]:
        """{family: {p50, p95, count, sum}}. The quantiles are
        ring-windowed (newest ``capacity`` samples, like the step-time
        summary in common/telemetry.py); count AND sum are lifetime
        cumulative — the Prometheus summary pair, so sum/count is a
        true mean for any consumer computing rate(sum)/rate(count)."""
        out: Dict[str, Dict[str, float]] = {}
        with self._lock:
            snap = {
                fam: (sorted(ring), self._counts[fam], self._sums[fam])
                for fam, ring in self._rings.items()
            }
        for fam, (vals, count, total) in snap.items():
            out[fam] = {
                "p50": _percentile(vals, 0.50),
                "p95": _percentile(vals, 0.95),
                "count": count,
                "sum": total,
            }
        return out

    def publish(self) -> None:
        """serve.ttft_ms_p50 / _p95 / _count (+ tpot) registry gauges —
        the existing /metrics endpoint picks them up as hvd_serve_*."""
        stats = {}
        for fam, s in self.summaries().items():
            stats[f"{fam}_p50"] = s["p50"]
            stats[f"{fam}_p95"] = s["p95"]
            stats[f"{fam}_count"] = s["count"]
        _metrics.update("serve", stats)

    def render_prometheus_summaries(self) -> List[str]:
        """Prometheus text lines rendering both families as real
        ``summary`` types (quantile labels), for the serve frontend's
        /metrics route."""
        lines: List[str] = []
        helps = {
            "ttft_ms": "Time to first token (submission -> first "
            "generated token, queue wait included), ms.",
            "tpot_ms": "Per-output-token latency (decode-step wall "
            "time per generated token), ms.",
        }
        for fam, s in self.summaries().items():
            name = f"serve_{fam}"
            lines.append(f"# HELP {name} {helps[fam]}")
            lines.append(f"# TYPE {name} summary")
            lines.append(f'{name}{{quantile="0.5"}} {s["p50"]:.10g}')
            lines.append(f'{name}{{quantile="0.95"}} {s["p95"]:.10g}')
            lines.append(f"{name}_sum {s['sum']:.10g}")
            lines.append(f"{name}_count {s['count']:.10g}")
        return lines
