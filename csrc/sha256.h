// SHA-256 + HMAC-SHA256, implemented from FIPS 180-4 / RFC 2104 for the
// native KV rendezvous server's request authentication (see kvstore.cc).
// The Python side signs with hmac/hashlib (horovod_tpu/runner/secret.py);
// this must produce identical digests.
#pragma once

#include <cstddef>
#include <cstdint>

namespace hvd {

// out: 32 bytes.
void sha256(const uint8_t* data, size_t len, uint8_t* out);

// out: 32 bytes. key/msg arbitrary length.
void hmac_sha256(const uint8_t* key, size_t key_len, const uint8_t* msg,
                 size_t msg_len, uint8_t* out);

}  // namespace hvd
