"""ZeRO-style sharded weight update for data-parallel training.

Beyond-parity, TPU-first (the reference has no analog): three sharding
stages over one optimizer, selected by ``zero_stage`` (default
``HOROVOD_ZERO_STAGE``; the staging mirrors DeepSpeed's ZeRO and the
XLA "automatic cross-replica sharding of weight update" recipe —
PAPERS.md: Xu et al., arXiv:2004.13336, pattern reference only):

* ``zero_stage=1`` — optimizer-state sharding (the original contract):
  each rank **reduce-scatters** the gradients (1/N shard each, half the
  wire bytes of a ring allreduce), runs the inner transform on its
  shard only (Adam moments etc. live 1/N-sharded), then **all-gathers**
  the parameter updates. Total communication equals one ring allreduce;
  optimizer math and state memory drop to 1/N.
* ``zero_stage=2`` — gradient sharding on top: grads taken through
  :meth:`value_and_grad` are reduce-scattered **per overlap bucket
  inside backprop** (a ``custom_vjp`` boundary — the mirror of
  ``hvd.overlap_boundary``), so each bucket's reduce-scatter output IS
  the per-rank shard slice and no reduced full-gradient buffer ever
  materializes. The int8/bf16 quantized wire applies to both exchange
  legs (``wire=``, per-bucket resolution via
  ``ops.overlap.resolve_wire``/WireTuner) with error-feedback residual
  rows carried in the optimizer state (``error_feedback=True``).
* ``zero_stage=3`` — parameter sharding: params live as
  ``[world, cols]`` shard rows between steps (:meth:`init_params`,
  layout: ``parallel.fsdp.host_shard_rows``). The forward all-gathers
  each parameter bucket through a ``custom_vjp`` boundary at its
  forward dataflow frontier — the compiled HLO carries N INDEPENDENT
  all-gathers interleaved into compute, not one up-front unshard — and
  the backward's cotangent leaves through the same bucketed
  reduce-scatter, landing gradients directly in shard geometry.
  :meth:`update` then updates the local shard with NO collective (the
  next forward's gathers re-publish the new params), so replicated
  param+grad residency drops world-fold.

Contract (all stages):

* ``opt = ShardedDistributedOptimizer(optax.adam(1e-3), zero_stage=s)``
* ``state = opt.init(params)`` — OUTSIDE jit/shard_map. Every state
  leaf gains a leading ``world`` axis (rank r's shard at index r;
  scalar leaves like Adam's ``count`` are broadcast), so the whole
  state threads through ``jax.shard_map`` with a uniform
  ``P(WORLD_AXIS)`` spec. Stage 3 adds
  ``pstate = opt.init_params(params)`` with the same convention.
* ``updates, state = opt.update(grads, state, params)`` — INSIDE
  ``shard_map`` over the world axis. Stages 1-2 accept full
  (replicated-shape) grads/params and return full updates; grads
  produced by :meth:`value_and_grad` arrive pre-scattered (per-leaf
  shard slices) and skip the internal reduce-scatter. Stage 3 takes
  shard grads + ``opt.local_shards(pstate)`` and returns SHARD
  updates — apply them with ``optax.apply_updates`` on the local
  shards and re-stack with ``opt.as_rows``.

Supported inner transforms: elementwise ones (sgd, momentum, adam,
adamw, rmsprop, ...). Norm-based transforms like
``clip_by_global_norm`` would compute shard-LOCAL norms inside the
sharded update and silently train wrong; apply gradient clipping to
the full gradients BEFORE this wrapper instead. Construction runs a
**differential probe** (VERDICT r3 #5): the inner transform is applied
to a fixed pytree both whole and shard-wise — a mismatch means the
update is not elementwise and raises ``ValueError`` with the
clip-before-wrapper recipe instead of letting training silently
diverge. ``HOROVOD_SHARDED_OPT_PROBE=0`` skips the probe (e.g. for a
deliberately stochastic transform that the probe cannot compare).

Shard layout is owned by ``parallel/fsdp.py`` (ONE source of truth for
the flat pad/split geometry — this module holds no private copy), and
the bucketed exchange legs by ``ops/overlap.py``
(``bucketed_reduce_scatter`` / ``bucketed_shard_all_gather``).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from .common.topology import WORLD_AXIS
from .ops.reduction_ops import Average, ReduceOp, Sum, resolve_op
from .parallel.fsdp import (
    dyn_shard as _shard_dyn_impl,
    host_shard as _shard_host_impl,
    host_shard_rows,
    host_unshard,
    pad_to as _pad_to_impl,
    reshard_rows,
    shard_cols,
)

_WIRE_FORMATS = ("fp32", "bf16", "int8", "auto")


def _pad_to(flat, n):
    return _pad_to_impl(flat, n)


def _shard_host(x, n, r):
    """Host-side shard r of array x (init path, outside jit)."""
    return _shard_host_impl(x, n, r)


def _shard_dyn(x, n, idx):
    """Traced shard selection by the rank's axis_index (update path)."""
    return _shard_dyn_impl(x, n, idx)


def _probe_nonelementwise(inner: optax.GradientTransformation) -> bool:
    """Differential probe: does `inner` give different updates when its
    inputs are sharded? Applies the transform to a fixed two-leaf pytree
    (values chosen so a global-norm clip at any common max_norm actually
    fires) once whole and once split into 2 shards per leaf — exactly
    the flatten-and-split geometry `update` uses. Elementwise chains
    (sgd/momentum/adam/adamw/rmsprop/weight-decay/schedules) match to
    float tolerance; anything coupling elements across the tree
    (clip_by_global_norm, adaptive_grad_clip, centralization) does not.

    Returns True when a mismatch is detected; False when the transform
    matches or cannot be probed (an inner transform that rejects the
    probe shapes is left to the docstring contract).
    """
    # The (128, 128) leaf exists for SHAPE-GATED couplings: adafactor
    # factors its second moment only when both dims >= 128, and the
    # sharded path always flattens to 1-D (where it falls back to
    # unfactored RMS) — a tiny-leaf probe would let it through.
    _det = np.linspace(-1.0, 1.0, 128 * 128, dtype=np.float32)
    params = {
        "w": jnp.asarray([1.0, -2.0, 3.0, -4.0], jnp.float32),
        "b": jnp.asarray([0.5, 0.25], jnp.float32),
        "m": jnp.asarray(_det.reshape(128, 128)),
    }
    # THREE steps with shard-norm ratios that shift every step: a
    # one-step probe misses transforms whose first update is
    # scale-invariant (clip→adam: Adam's step-1 update is ~sign(g), so
    # shard-local clip factors cancel until the moments carry history).
    # Norms ~10 ensure any realistic clip threshold actually fires.
    gm = jnp.asarray((_det + 0.37).reshape(128, 128))
    # top/bottom row-halves land in different shards after the flatten
    half = jnp.concatenate(
        [
            jnp.full((64, 128), 0.05, jnp.float32),
            jnp.full((64, 128), 6.0, jnp.float32),
        ]
    )
    grad_steps = [
        {
            "w": jnp.asarray([6.0, -8.0, 0.5, 2.0], jnp.float32),
            "b": jnp.asarray([-3.0, 1.5], jnp.float32),
            "m": gm * 3.0,
        },
        {  # shard-norm pattern reversed vs step 1
            "w": jnp.asarray([0.1, 0.2, 9.0, -7.0], jnp.float32),
            "b": jnp.asarray([4.0, -0.05], jnp.float32),
            "m": gm * half,
        },
        {
            "w": jnp.asarray([-5.0, 0.3, 0.4, 6.0], jnp.float32),
            "b": jnp.asarray([0.2, -8.0], jnp.float32),
            "m": gm * half[::-1],
        },
    ]

    def _split(tree, r):
        return jax.tree_util.tree_map(
            lambda x: x.reshape(2, -1)[r], tree
        )

    try:
        full_state = inner.init(params)
        full_upds = []
        for g in grad_steps:
            u, full_state = inner.update(g, full_state, params)
            full_upds.append(u)
        shard_upds = [[] for _ in grad_steps]
        for r in range(2):
            p_r = _split(params, r)
            state_r = inner.init(p_r)
            for step, g in enumerate(grad_steps):
                u_r, state_r = inner.update(_split(g, r), state_r, p_r)
                shard_upds[step].append(u_r)
        recombined = [
            jax.tree_util.tree_map(
                lambda a, b: jnp.concatenate(
                    [a.reshape(-1), b.reshape(-1)]
                ),
                *pair,
            )
            for pair in shard_upds
        ]
    except Exception:
        return False  # unprobeable shapes: fall back to the documented contract
    for full_u, shard_u in zip(full_upds, recombined):
        leaves_f = jax.tree_util.tree_leaves(full_u)
        leaves_s = jax.tree_util.tree_leaves(shard_u)
        if any(
            not np.allclose(
                np.asarray(a, np.float32).reshape(-1),
                np.asarray(b, np.float32).reshape(-1),
                rtol=1e-5,
                atol=1e-6,
            )
            for a, b in zip(leaves_f, leaves_s)
        ):
            return True
    return False


class ShardedDistributedOptimizer:
    """Data-parallel optimizer with reduce-scatter/all-gather weight
    update and ZeRO-1/2/3 sharding stages (module docstring)."""

    def __init__(
        self,
        optimizer: optax.GradientTransformation,
        op: Optional[ReduceOp] = None,
        average: Optional[bool] = None,
        axis_name: str = WORLD_AXIS,
        world: Optional[int] = None,
        overlap_buckets: Optional[int] = None,
        overlap_min_bytes: Optional[int] = None,
        grad_guard: Optional[bool] = None,
        guard_max_skips: Optional[int] = None,
        zero_stage: Optional[int] = None,
        wire: Optional[str] = None,
        wire_block: Optional[int] = None,
        error_feedback: bool = False,
        hierarchical: Optional[bool] = None,
        local_sgd_steps: Optional[int] = None,
        local_sgd_inter_wire: str = "int8",
        local_sgd_intra: Optional[int] = None,
    ):
        """``zero_stage`` selects the sharding stage (module docstring);
        ``None`` defers to ``HOROVOD_ZERO_STAGE`` (default 1). Stage 3
        always runs the bucketed exchange (``overlap_buckets`` floors
        at 1 — its schedule IS the parameter gather plan).

        ``overlap_buckets=N`` buckets the exchange (ops/overlap.py):
        gradients reduce-scatter as N independent per-bucket collectives
        (member leaves' padded [n, ·] panes concatenated column-wise —
        elementwise identical to the per-leaf scatter, so the shard
        values are bit-exact) and parameter updates all-gather the same
        way. Because the inner transform is ELEMENTWISE (the probe
        enforces it), the single ``inner.update`` call decomposes into
        per-leaf dataflow: bucket k's update math depends only on
        bucket k's reduce-scatter output, so XLA overlaps the update
        compute with the tail of the exchange — the shard-by-shard
        interleave of arXiv 2004.13336, with state/checkpoint layout
        unchanged. ``None`` defers to ``HOROVOD_OVERLAP``/
        ``HOROVOD_OVERLAP_BUCKETS``; 0 keeps the per-leaf collectives.

        ``wire`` picks the exchange wire format per bucket
        (``fp32``/``bf16``/``int8``/``auto``; ``None`` defers to
        ``HOROVOD_ZERO_WIRE``, default fp32 — deliberately NOT
        ``HOROVOD_FUSION_WIRE``, the eager fused-wire knob). ``auto``
        resolves per bucket through
        ``ops.overlap.resolve_wire`` (size floor + WireTuner).
        ``error_feedback=True`` (stages 1-2, quantized-capable wire,
        full-gradient update path) carries both legs' quantization
        errors in the optimizer state — ``rs`` rows in full gradient
        geometry, ``ag`` rows in shard geometry (1/N per rank) — plus a
        per-step wire-seed counter, all riding the same
        leading-world-axis convention so ``reshard_state`` carries them
        elastically. Pad positions hold zero residual by construction
        (``parallel.fsdp.pad_to`` contract).

        ``hierarchical`` controls the two-level routing of the exchange
        legs: ``None`` (default) defers to ``HOROVOD_HIERARCHICAL`` —
        when the topology resolves an inter axis, every per-bucket
        reduce-scatter / all-gather decomposes into intra RS -> inter
        hop on the 1/L panes -> intra AG (the ZeRO wire's DCN bytes
        drop L-fold; an int8 ``wire`` quantizes the inter hop only);
        ``False`` pins the flat wire regardless of topology.
        Error-feedback buckets always ride the flat wire (the carry is
        defined against the flat pane quantization).

        ``local_sgd_steps=K`` (``None`` defers to
        ``HOROVOD_LOCAL_SGD_STEPS``; the mode engages at K > 1)
        switches stages 1-2 into local-SGD mode
        (horovod_tpu/local_sgd.py): optimizer state shards over the
        INTRA axis only (each slice's L ranks jointly hold that
        slice's moments — slices' trajectories diverge during the
        local phase), every exchange leg routes over the intra
        replica groups (the compiled step carries zero inter-slice
        groups), and :meth:`sync_round` — a SEPARATE traced program —
        reconciles parameter deltas since the last round across the
        inter axis with hierarchical Adasum on
        ``local_sgd_inter_wire`` (EF residuals carried across rounds
        in the state's ``"local"`` layout family, which
        ``reshard_state`` migrates across world changes). Stage 3 is
        rejected: its parameters shard over the WORLD axis, so a
        slice cannot even hold its own model during an independent
        local phase. Params must ride the training loop rank-major
        (``P(hvd.WORLD_AXIS)``) — slices diverge, so a replicated
        spec would be a lie. ``hierarchical`` two-level routing is
        moot in local mode (there IS no inter hop in the local
        phase). ``local_sgd_intra`` injects an explicit
        chips-per-slice (tests/bench on single-slice hosts).

        ``grad_guard=True`` (``None`` defers to ``HOROVOD_GUARD``)
        adds the non-finite skip-step sentinel (common/guard.py).
        Unlike the replicated optimizer the reduce-scattered shards
        DIVERGE per rank — a NaN lands in exactly one rank's shard —
        so the flag costs one extra 4-byte scalar ``psum`` per step
        (DeepSpeed/AMP's overflow-flag allreduce) to keep the skip
        decision uniform across the gang. Skip semantics are gated by
        ``where`` selects: bad steps feed the inner transform zeroed
        gradients, discard its state delta, and emit zero updates;
        the guard counters ride the state under a ``"guard"`` key —
        an OPT-IN layout change (``reshard_state`` carries it across
        world changes; unguarded jobs keep the flat layout)."""
        self._inner = optimizer
        self._op = resolve_op(op, average)
        if self._op not in (Sum, Average):
            raise NotImplementedError(
                "ShardedDistributedOptimizer supports op=Sum/Average "
                "(Adasum's recursive combine needs full gradients)"
            )
        self._axis = axis_name
        self._world = world
        from .common import basics

        cfg = basics.live_config()
        self._stage = int(
            zero_stage if zero_stage is not None else cfg.zero_stage
        )
        if self._stage not in (1, 2, 3):
            raise ValueError(
                f"zero_stage must be 1, 2 or 3, got {self._stage}"
            )
        # wire=None defers to the DEDICATED sharded-wire knob
        # (HOROVOD_ZERO_WIRE, default fp32) — never to
        # HOROVOD_FUSION_WIRE, which governs the eager fused wire and
        # predates ZeRO-2/3: inheriting it would silently quantize the
        # sharded exchange (and flip the state layout) under existing
        # deployments' env
        self._wire = wire if wire is not None else cfg.zero_wire
        if self._wire not in _WIRE_FORMATS:
            raise ValueError(
                f"wire must be one of {_WIRE_FORMATS}, got {self._wire!r}"
            )
        self._wire_block = int(
            wire_block if wire_block is not None else cfg.fusion_wire_block
        )
        # two-level routing of the exchange legs: "auto" = the
        # HOROVOD_HIERARCHICAL topology decision; None pins flat
        self._hier_arg = None if hierarchical is False else "auto"
        from . import local_sgd as _local_sgd

        self._local_k = int(
            local_sgd_steps
            if local_sgd_steps is not None
            else _local_sgd.default_steps()
        )
        self._local_on = self._local_k > 1
        self._local_wire = local_sgd_inter_wire
        self._local_intra = local_sgd_intra
        if self._local_on:
            if local_sgd_steps is None:
                # engaged via env: warn once — the mode needs a loop
                # that drives sync_round (see local_sgd.maybe_sync)
                _local_sgd.warn_env_engaged(self._local_k)
            if self._stage >= 3:
                raise NotImplementedError(
                    "local_sgd_steps composes with zero_stage<=2 only: "
                    "stage-3 parameters shard over the WORLD axis, so "
                    "a slice cannot hold its own model during an "
                    "independent local phase — run stage 1/2, or keep "
                    "every-step sync at stage 3"
                )
            if local_sgd_inter_wire not in _local_sgd.INTER_WIRES:
                raise ValueError(
                    f"unknown local_sgd_inter_wire "
                    f"{local_sgd_inter_wire!r}"
                )
            # the local phase has no inter hop; two-level routing of
            # the exchange legs would reintroduce one
            self._hier_arg = None
        self._ef = bool(error_feedback)
        if self._ef and self._wire not in ("int8", "auto"):
            raise ValueError(
                "error_feedback requires a quantized-capable wire "
                "(wire='int8' or 'auto'); fp32/bf16 residuals drain to "
                "the exact cast error and buy nothing"
            )
        if self._ef and self._stage >= 3:
            raise ValueError(
                "error_feedback composes with zero_stage<=2 only: the "
                "stage-3 gather/scatter boundary is a stateless "
                "custom_vjp and cannot thread residual carries; run "
                "stage 3 with wire='fp32'/'bf16' or plain int8"
            )
        from .ops import overlap as _overlap

        if overlap_buckets is None:
            overlap_buckets = _overlap.default_buckets()
        self._overlap_buckets = int(overlap_buckets)
        if self._stage >= 3:
            # the schedule IS the parameter gather/scatter plan
            self._overlap_buckets = max(self._overlap_buckets, 1)
        self._overlap_min_bytes = (
            _overlap.default_min_bytes()
            if overlap_min_bytes is None
            else int(overlap_min_bytes)
        )
        from .common import guard as _guard

        self._guard_on = (
            bool(grad_guard)
            if grad_guard is not None
            else _guard.default_enabled()
        )
        self._max_skips = int(
            guard_max_skips
            if guard_max_skips is not None
            else _guard.default_max_skips()
        )
        self._guard_src = _guard.new_source() if self._guard_on else 0
        self._pmeta = None  # stage-3 full-parameter geometry
        import os

        if os.environ.get(
            "HOROVOD_SHARDED_OPT_PROBE", "1"
        ) not in ("0", "false") and _probe_nonelementwise(optimizer):
            raise ValueError(
                "ShardedDistributedOptimizer: the inner optax transform "
                "is not elementwise — its update changes when gradients "
                "are sharded (differential probe mismatch). Norm-based "
                "transforms (clip_by_global_norm, adaptive_grad_clip, "
                "...) would compute shard-LOCAL norms and silently train "
                "wrong. Apply clipping to the FULL gradients before this "
                "wrapper instead, e.g.:\n"
                "    clipped, _ = optax.clip_by_global_norm(c).update("
                "grads, None)\n"
                "    updates, state = sharded_opt.update(clipped, state, "
                "params)\n"
                "or set HOROVOD_SHARDED_OPT_PROBE=0 to accept the risk "
                "for a transform the probe cannot compare (e.g. "
                "stochastic noise)."
            )

    # -- local-SGD topology ------------------------------------------------
    def _local_stages(self, world: int):
        from . import local_sgd as _local_sgd

        return _local_sgd.resolve_stages(
            int(world), intra=self._local_intra
        )

    def _shard_width(self, world: int) -> int:
        """How many ways the flat shard geometry splits: the whole
        world normally; the intra size L in local-SGD mode (each
        slice's L ranks jointly hold that slice's state)."""
        if not self._local_on:
            return int(world)
        return len(self._local_stages(world)[0][0])

    # -- init (outside jit) ------------------------------------------------
    def init(self, params):
        from .common import basics

        n = self._world or basics.size()
        self._world = n
        width = self._shard_width(n)
        shard_states = [
            self._inner.init(
                jax.tree_util.tree_map(
                    lambda p: _shard_host(p, width, r % width), params
                )
            )
            for r in range(n)
        ]
        # stack rank-major: every leaf gets a leading world axis, so the
        # state rides shard_map with ONE spec: P(axis_name)
        stacked = jax.tree_util.tree_map(
            lambda *xs: jnp.stack([jnp.asarray(x) for x in xs]),
            *shard_states,
        )
        guard_rows = None
        if self._guard_on:
            # guard counters ride the same rank-major convention
            # ([world] rows of replicated scalars) so the whole state
            # still threads through shard_map with the single P(axis)
            # spec
            z = jnp.zeros((n,), jnp.int32)
            guard_rows = {"skips": z, "streak": z, "step": z}
        wire_rows = (
            self._init_wire_rows(params, n, width)
            if self._wants_wire_rows()
            else None
        )
        local_rows = (
            self._init_local_rows(params, n, width)
            if self._local_on
            else None
        )
        return self._compose_state(
            stacked, guard_rows, wire_rows, local_rows
        )

    def _wants_wire_rows(self) -> bool:
        """A quantized-capable wire on the update-internal legs needs
        state: a per-step seed counter (a FIXED stochastic-rounding
        seed would repeat the same realized error every step — a
        directional drift instead of an unbiased walk), plus the EF
        residual rows when error_feedback is on. Stage 3 has no wire
        leg inside update (the boundary carries the exchange), so its
        state stays wire-free."""
        return self._stage <= 2 and (
            self._ef or self._wire in ("int8", "auto")
        )

    def _init_wire_rows(self, params, n, width: Optional[int] = None):
        """Wire-seed counter (+ error-feedback carries when EF is on),
        rank-major: ``rs`` rows mirror the FULL gradient geometry (each
        rank's quantization error is over its own full local
        contribution), ``ag`` rows the shard geometry (the update-leg
        error lives on the shard its rank owns — genuinely 1/N, or 1/L
        in local-SGD mode where the shard splits intra-slice)."""
        if width is None:
            width = n
        rows = {"step": jnp.zeros((n,), jnp.int32)}
        if not self._ef:
            return rows

        # shape/dtype only — a jax.eval_shape template works here too
        def _full_rows(p):
            return jnp.zeros(
                (n,) + tuple(np.shape(p)), jnp.result_type(p)
            )

        def _shard_rows(p):
            shape = tuple(np.shape(p))
            if not shape:
                return jnp.zeros((n,), jnp.result_type(p))
            size = int(np.prod(shape, dtype=np.int64))
            return jnp.zeros(
                (n, shard_cols(size, width)), jnp.result_type(p)
            )

        rows["rs"] = jax.tree_util.tree_map(_full_rows, params)
        rows["ag"] = jax.tree_util.tree_map(_shard_rows, params)
        return rows

    def _init_local_rows(self, params, n, width):
        """The ``"local"`` layout family (local-SGD mode): the anchor —
        params at the last sync round — in intra-position-major shard
        rows (rank ``r`` holds chunk ``r % L``; every slice's L ranks
        jointly hold one full anchor copy, 1/L per rank), the EF
        residual of the int8 inter wire in the same geometry, the
        round counter, and the split width the rows were cut at (the
        ``reshard_state`` migration reads it back — an 8→6 resize may
        change L)."""
        def _rows(p):
            if np.ndim(p) == 0:
                return jnp.stack(
                    [jnp.asarray(p) for _ in range(n)]
                )
            return jnp.stack(
                [_shard_host(jnp.asarray(p), width, r % width)
                 for r in range(n)]
            )

        rows = {
            "anchor": jax.tree_util.tree_map(_rows, params),
            "round": jnp.zeros((n,), jnp.int32),
            "intra": jnp.full((n,), width, jnp.int32),
        }
        if self._local_wire == "int8":
            rows["residual"] = jax.tree_util.tree_map(
                lambda a: jnp.zeros_like(a), rows["anchor"]
            )
        return rows

    # -- state layout ------------------------------------------------------
    @staticmethod
    def _layout(state):
        """Decompose a state into (inner, guard_rows, wire_rows,
        local_rows) without enforcing the optimizer's flags (the
        reshard migration point)."""
        if (
            isinstance(state, dict)
            and "state" in state
            and set(state) <= {"state", "guard", "wire", "local"}
        ):
            return (
                state["state"], state.get("guard"),
                state.get("wire"), state.get("local"),
            )
        return state, None, None, None

    @staticmethod
    def _compose_state(inner, guard_rows, wire_rows, local_rows=None):
        extras = {}
        if guard_rows is not None:
            extras["guard"] = guard_rows
        if wire_rows is not None:
            extras["wire"] = wire_rows
        if local_rows is not None:
            extras["local"] = local_rows
        if not extras:
            return inner
        return {"state": inner, **extras}

    @staticmethod
    def _is_guarded_layout(state) -> bool:
        guard_rows = ShardedDistributedOptimizer._layout(state)[1]
        return guard_rows is not None

    def _split_state(self, state):
        """Layout split + flag validation (update path: mismatches are
        hard errors pointing at the reshard_state migration)."""
        inner, guard_rows, wire_rows, local_rows = self._layout(state)
        if self._local_on and local_rows is None:
            raise ValueError(
                "local_sgd_steps > 1 but the optimizer state has no "
                '"local" layout family (anchor/residual/round rows) — '
                "it was created without local-SGD mode. Migrate it "
                "once with reshard_state(state, params, world) "
                "(params must carry concrete values: the anchor IS "
                "the params), or re-run init(params)."
            )
        if not self._local_on and local_rows is not None:
            raise ValueError(
                'the optimizer state carries a "local" layout family '
                "but local_sgd_steps <= 1 — it was checkpointed by a "
                "local-SGD run. Re-enable local_sgd_steps, or "
                "downgrade the state once with reshard_state(state, "
                "params, world) (which strips the family AND its "
                "intra-width shard geometry — the moments are re-cut "
                "to the flat world split)."
            )
        if self._guard_on and guard_rows is None:
            raise ValueError(
                "grad_guard is on but the optimizer state has the "
                "flat (unguarded) layout — it was created before "
                "the guard was enabled. Migrate it once with "
                "reshard_state(state, params, world) (which "
                "synthesizes zero guard counters), or re-run "
                "init(params)."
            )
        if not self._guard_on and guard_rows is not None:
            raise ValueError(
                "the optimizer state carries guard counters but "
                "grad_guard is off — it was checkpointed by a GUARDED "
                "run. Re-enable the guard, or downgrade the state once "
                "with reshard_state(state, params, world) (which "
                "strips the counters when the guard is off)."
            )
        wants = self._wants_wire_rows()
        if wants and wire_rows is None:
            raise ValueError(
                "the quantized wire needs wire state rows (per-step "
                "seed counter and, with error_feedback, the wire "
                "residual rows) but the optimizer state has none — "
                "migrate it once with reshard_state(state, params, "
                "world) (which synthesizes them), or re-run "
                "init(params)."
            )
        if not wants and wire_rows is not None:
            raise ValueError(
                "the optimizer state carries wire residual/seed rows "
                "but this optimizer's wire is exact (fp32/bf16, no "
                "error_feedback) — re-enable the quantized wire, or "
                "downgrade the state once with reshard_state(state, "
                "params, world)."
            )
        if self._ef and wire_rows is not None and "rs" not in wire_rows:
            raise ValueError(
                "error_feedback is on but the optimizer state carries "
                "no wire residual rows (seed-only wire state from a "
                "plain-int8 run) — migrate it once with "
                "reshard_state(state, params, world)."
            )
        if (
            not self._ef
            and wire_rows is not None
            and "rs" in wire_rows
        ):
            raise ValueError(
                "the optimizer state carries wire residual rows but "
                "error_feedback is off — re-enable it, or downgrade "
                "the state once with reshard_state(state, params, "
                "world)."
            )
        return inner, guard_rows, wire_rows, local_rows

    # -- gradient classification -------------------------------------------
    def _grads_are_shards(self, grads, params, n) -> bool:
        """Static (trace-time) classification: did ``grads`` come from
        the in-backprop scatter boundary (per-leaf shard slices) or
        from plain backprop (full leaves)? Shapes decide: a shard leaf
        is 1-D of length ``ceil(size/world)``. Leaves where both
        readings coincide (``p.size <= 1``) follow the unambiguous
        majority; an all-ambiguous tree reads as full (legacy)."""
        g_l, g_def = jax.tree_util.tree_flatten(grads)
        p_l = g_def.flatten_up_to(params)
        kinds = []
        for g, p in zip(g_l, p_l):
            if np.ndim(p) == 0:
                continue
            if jnp.result_type(g) == jax.dtypes.float0:
                continue  # non-differentiable leaf: passthrough either way
            gs, ps = tuple(np.shape(g)), tuple(np.shape(p))
            size = int(np.prod(ps, dtype=np.int64))
            sc = (shard_cols(size, n),)
            if gs == ps and gs != sc:
                kinds.append(False)
            elif gs == sc and gs != ps:
                kinds.append(True)
            elif gs == ps == sc:
                continue  # ambiguous corner (size <= 1-ish leaves)
            else:
                raise ValueError(
                    f"gradient leaf shape {gs} matches neither the "
                    f"param shape {ps} nor its shard shape {sc}"
                )
        if not kinds:
            return False
        if all(kinds):
            return True
        if not any(kinds):
            return False
        raise ValueError(
            "gradient tree mixes full and shard leaves — pass either "
            "raw backprop gradients or the tree from opt.value_and_grad"
        )

    # -- update (inside shard_map over axis_name) --------------------------
    def update(self, grads, state, params):
        inner_rows, guard_rows, wire_rows, local_rows = (
            self._split_state(state)
        )
        n = jax.lax.axis_size(self._axis)
        if self._world is not None and n != self._world:
            raise ValueError(
                f"world changed between init ({self._world}) and update "
                f"({n}): call reshard_state(state, params, {n}) after a "
                "topology change — it carries the optimizer moments "
                "over (re-running init would reset them)"
            )
        idx = jax.lax.axis_index(self._axis)
        # local-SGD mode: shard geometry and every collective restrict
        # to the intra groups — the compiled step carries ZERO
        # inter-slice replica groups (hloaudit-asserted)
        if self._local_on:
            from .common.topology import stage_positions

            intra_groups = self._local_stages(n)[0]
            width = len(intra_groups[0])
            pos = jnp.asarray(stage_positions(intra_groups))[idx]
        else:
            intra_groups = None
            width = n
            pos = idx
        # shard_map hands each rank its [1, ...] state slice
        local_state = jax.tree_util.tree_map(lambda x: x[0], inner_rows)
        local_wire = (
            jax.tree_util.tree_map(lambda x: x[0], wire_rows)
            if wire_rows is not None
            else None
        )
        wire_seed = local_wire["step"] if local_wire is not None else 0

        if self._stage >= 3:
            bad = [
                p for p in jax.tree_util.tree_leaves(params)
                if np.ndim(p) > 1
            ]
            if bad:
                raise ValueError(
                    "zero_stage=3 update expects LOCAL parameter shards "
                    "(opt.local_shards(pstate) inside shard_map), got a "
                    f"leaf of shape {np.shape(bad[0])} — full params "
                    "never exist at stage 3"
                )
            p_sh = params
            shard_in = True
        else:
            shard_in = self._grads_are_shards(grads, params, width)
            p_sh = jax.tree_util.tree_map(
                lambda p: p if p.ndim == 0 else _shard_dyn(p, width, pos),
                params,
            )
        if shard_in and self._ef:
            raise ValueError(
                "error_feedback rides the full-gradient update path "
                "(the reduce-scatter happens inside update, where the "
                "residual rows live); grads from opt.value_and_grad "
                "arrive pre-scattered — pass raw backprop gradients "
                "instead, or drop error_feedback"
            )

        from .ops import overlap as _overlap

        new_rs_res = None
        if shard_in:
            g_sh = grads
        elif self._overlap_buckets or self._wire != "fp32":
            buckets = max(self._overlap_buckets, 1)
            if self._ef:
                g_sh, new_rs_res = _overlap.bucketed_reduce_scatter(
                    grads, op=self._op, n_buckets=buckets,
                    axis_name=self._axis, wire=self._wire,
                    wire_block=self._wire_block, seed=wire_seed,
                    residuals=local_wire["rs"],
                    min_bucket_bytes=self._overlap_min_bytes,
                    hier_stages=self._hier_arg,
                    groups=intra_groups,
                )
            else:
                g_sh = _overlap.bucketed_reduce_scatter(
                    grads, op=self._op, n_buckets=buckets,
                    axis_name=self._axis, wire=self._wire,
                    wire_block=self._wire_block, seed=wire_seed,
                    min_bucket_bytes=self._overlap_min_bytes,
                    hier_stages=self._hier_arg,
                    groups=intra_groups,
                )
        else:
            # 0-d leaves (scalar temperature etc.) stay replicated —
            # exactly like init's _shard_host — so state shapes are
            # stable step-over-step (a shape flip would force a retrace
            # and break donation)
            def rs(g):
                if g.ndim == 0:
                    red = jax.lax.psum(
                        g, self._axis, axis_index_groups=intra_groups
                    )
                    return red / width if self._op == Average else red
                flat = _pad_to(g.reshape(-1), width).reshape(width, -1)
                red = jax.lax.psum_scatter(
                    flat, self._axis, scatter_dimension=0, tiled=False,
                    axis_index_groups=intra_groups,
                )
                if self._op == Average:
                    red = red / width
                return red

            g_sh = jax.tree_util.tree_map(rs, grads)

        finite = None
        if self._guard_on:
            from .ops.traced import tree_finite

            # the scattered shards DIVERGE per rank (a NaN lands in
            # exactly one shard), so the flag must be agreed: one
            # 4-byte scalar psum — the only collective the guard adds
            # Local-SGD mode agrees the flag INTRA-slice only: slices
            # train independently, so a slice skips its own poisoned
            # step without stalling the others (and the local-phase
            # program stays free of inter-slice groups).
            ok_local = tree_finite(g_sh)
            bad = jax.lax.psum(
                jnp.where(ok_local, 0.0, 1.0).astype(jnp.float32),
                self._axis,
                axis_index_groups=intra_groups,
            )
            finite = bad == 0
            # feed the inner transform clean zeros on a bad step; its
            # output and state delta are discarded below anyway, this
            # just keeps NaNs out of user transforms entirely
            g_sh = jax.tree_util.tree_map(
                lambda g: jnp.where(finite, g, jnp.zeros_like(g)), g_sh
            )
        upd_sh, new_local = self._inner.update(g_sh, local_state, p_sh)
        if self._guard_on:
            # skip-step semantics by selection: zero updates, state of
            # the last APPLIED step (where, not multiply — selects are
            # NaN-safe)
            upd_sh = jax.tree_util.tree_map(
                lambda u: jnp.where(finite, u, jnp.zeros_like(u)), upd_sh
            )
            new_local = jax.tree_util.tree_map(
                lambda nl, ol: jnp.where(finite, nl, ol),
                new_local, local_state,
            )

        new_ag_res = None
        if self._stage >= 3:
            # Shard updates out: the next forward's gathers re-publish
            # the new params. Rounding note: XLA contracts the inner
            # transform's final multiply into the caller's
            # `params + update` add as an FMA (one rounding, not two —
            # verified on XLA:CPU, where even optimization_barrier is
            # stripped before fusion), so stage-3 PARAMS can sit 1 ulp
            # from the stage-1 trajectory, whose add consumes an
            # all-gather output and cannot contract. Gradient shards,
            # moments and updates stay bit-exact; the FMA'd apply is
            # the MORE accurate of the two (tests/test_zero.py pins
            # the <=1-ulp bound).
            upd = upd_sh
        elif self._overlap_buckets or self._wire != "fp32":
            buckets = max(self._overlap_buckets, 1)
            if self._ef:
                upd, new_ag_res = _overlap.bucketed_shard_all_gather(
                    upd_sh, params, n_buckets=buckets,
                    axis_name=self._axis, wire=self._wire,
                    wire_block=self._wire_block, seed=wire_seed,
                    residuals=local_wire["ag"],
                    min_bucket_bytes=self._overlap_min_bytes,
                    hier_stages=self._hier_arg,
                    groups=intra_groups,
                )
            else:
                upd = _overlap.bucketed_shard_all_gather(
                    upd_sh, params, n_buckets=buckets,
                    axis_name=self._axis, wire=self._wire,
                    wire_block=self._wire_block, seed=wire_seed,
                    min_bucket_bytes=self._overlap_min_bytes,
                    hier_stages=self._hier_arg,
                    groups=intra_groups,
                )
        else:
            def gather(u, p):
                if p.ndim == 0:
                    return u
                full = jax.lax.all_gather(
                    u, self._axis, axis=0,
                    axis_index_groups=intra_groups,
                ).reshape(-1)
                return full[: p.size].reshape(p.shape).astype(u.dtype)

            upd = jax.tree_util.tree_map(gather, upd_sh, params)
        if self._guard_on and self._stage < 3:
            # a lossy AG leg transmits quantize(0 + residual) on a
            # skipped step; the post-gather gate discards it so skipped
            # steps move nothing (shard updates were gated above)
            upd = jax.tree_util.tree_map(
                lambda u: jnp.where(finite, u, jnp.zeros_like(u)), upd
            )

        new_inner = jax.tree_util.tree_map(lambda x: x[None], new_local)
        new_wire = None
        if local_wire is not None:
            def _gate(new_r, old_r):
                if finite is None:
                    return new_r
                return jnp.where(finite, new_r, old_r)

            # the seed counter advances even on skips — rounding stays
            # decorrelated across retries of a bad region
            new_wire = {
                "step": (local_wire["step"] + jnp.int32(1))[None]
            }
            if self._ef:
                new_wire["rs"] = jax.tree_util.tree_map(
                    lambda a, b: _gate(a, b)[None],
                    new_rs_res, local_wire["rs"],
                )
                new_wire["ag"] = jax.tree_util.tree_map(
                    lambda a, b: _gate(a, b)[None],
                    new_ag_res, local_wire["ag"],
                )
        if not self._guard_on:
            return upd, self._compose_state(
                new_inner, None, new_wire, local_rows
            )
        import functools

        from .common import guard as _guard

        skips = guard_rows["skips"][0]
        streak = guard_rows["streak"][0]
        step = guard_rows["step"][0]
        streak_next = streak + 1

        def _quiet(_):
            return jnp.int32(0)

        def _fire(_):
            # skip branch only: the healthy path never reaches the host
            jax.debug.callback(
                functools.partial(
                    _guard.record_skip, max_skips=self._max_skips,
                    source=self._guard_src,
                ),
                streak_next, step,
            )
            return jnp.int32(0)

        jax.lax.cond(finite, _quiet, _fire, operand=None)
        one = jnp.ones((), jnp.int32)
        zero = jnp.zeros((), jnp.int32)
        new_guard = {
            "skips": jnp.where(finite, skips, skips + one)[None],
            "streak": jnp.where(finite, zero, streak_next)[None],
            "step": (step + one)[None],
        }
        return upd, self._compose_state(
            new_inner, new_guard, new_wire, local_rows
        )

    # -- local-SGD sync round (inside shard_map, its OWN program) ----------
    def sync_round(self, params, state):
        """The K-step reconciliation round for local-SGD mode (stages
        1-2): parameter deltas since the last anchor — computed in the
        intra-shard geometry the ``"local"`` family stores (each
        slice's L ranks jointly hold one delta copy, 1/L per rank) —
        merge across slices by VHDD Adasum over the inter groups
        (:func:`horovod_tpu.local_sgd.adasum_sync_shard`: dots
        completed over intra, ``local_sgd_inter_wire`` on the DCN
        half-exchanges, EF residuals chained across rounds), then one
        intra all-gather reassembles the consensus parameters. Call
        INSIDE shard_map over the world axis, but compile it as a
        SEPARATE program from ``update`` — the local-phase step must
        carry zero inter-slice replica groups. Returns
        ``(new_params, new_state)``; drive the cadence and the
        retry/defer robustness contract with
        :func:`horovod_tpu.local_sgd.maybe_sync`."""
        if not self._local_on:
            raise ValueError(
                "sync_round requires local_sgd_steps > 1"
            )
        from . import local_sgd as _local_sgd
        from .common.topology import stage_positions

        inner_rows, guard_rows, wire_rows, local_rows = (
            self._split_state(state)
        )
        n = jax.lax.axis_size(self._axis)
        stages = self._local_stages(n)
        intra_groups = stages[0]
        L = len(intra_groups[0])
        idx = jax.lax.axis_index(self._axis)
        pos = jnp.asarray(stage_positions(intra_groups))[idx]
        local = jax.tree_util.tree_map(lambda x: x[0], local_rows)
        anchor = local["anchor"]
        residual = local.get("residual")
        rnd = local["round"]
        p_leaves, treedef = jax.tree_util.tree_flatten(params)
        a_leaves = treedef.flatten_up_to(anchor)
        r_leaves = (
            treedef.flatten_up_to(residual)
            if residual is not None
            else None
        )
        # per-leaf shard deltas; 0-d leaves ride at intra position 0
        # only (zeros elsewhere — the concat across positions must
        # contain each scalar exactly once, or its dot-product weight
        # would inflate L-fold)
        segs, a_segs, meta = [], [], []
        for p, a in zip(p_leaves, a_leaves):
            if p.ndim == 0:
                d = (p - a).astype(jnp.float32).reshape(1)
                segs.append(jnp.where(pos == 0, d, jnp.zeros_like(d)))
                a_segs.append(a.astype(jnp.float32).reshape(1))
                meta.append((True, 1, 1, (), p.dtype))
            else:
                sh = _shard_dyn(p, L, pos).astype(jnp.float32)
                a_segs.append(a.astype(jnp.float32))
                segs.append(sh - a_segs[-1])
                meta.append(
                    (False, int(a.shape[0]), int(p.size), p.shape,
                     p.dtype)
                )
        flat = jnp.concatenate(segs)
        a_flat = jnp.concatenate(a_segs)
        r_flat = None
        if r_leaves is not None:
            rsegs = []
            for r, m in zip(r_leaves, meta):
                rr = r.astype(jnp.float32).reshape(-1)
                if m[0]:
                    rr = jnp.where(pos == 0, rr, jnp.zeros_like(rr))
                rsegs.append(rr)
            r_flat = jnp.concatenate(rsegs)
        want_res = self._local_wire == "int8"
        if want_res:
            merged, new_r = _local_sgd.adasum_sync_shard(
                flat, stages, axis_name=self._axis,
                inter_wire=self._local_wire, seed=rnd,
                residual=r_flat, return_residual=True,
            )
        else:
            merged = _local_sgd.adasum_sync_shard(
                flat, stages, axis_name=self._axis,
                inter_wire=self._local_wire, seed=rnd,
            )
            new_r = None
        new_anchor_flat = a_flat + merged
        gathered = jax.lax.all_gather(
            new_anchor_flat, self._axis, axis_index_groups=intra_groups
        )  # [L, C] — position-major chunks of the consensus params
        new_p, new_a, new_res = [], [], []
        off = 0
        for (p, a), m in zip(zip(p_leaves, a_leaves), meta):
            is_scalar, cols, size, shape, dtype = m
            seg = gathered[:, off : off + cols]
            if is_scalar:
                val = seg[0, 0]  # position 0 holds the scalar
                new_p.append(val.astype(dtype))
                new_a.append(val.astype(jnp.result_type(a)))
                if new_r is not None:
                    new_res.append(
                        new_r[off].astype(jnp.result_type(a))
                    )
            else:
                full = seg.reshape(-1)[:size].reshape(shape)
                new_p.append(full.astype(dtype))
                new_a.append(
                    new_anchor_flat[off : off + cols].astype(
                        jnp.result_type(a)
                    )
                )
                if new_r is not None:
                    new_res.append(
                        new_r[off : off + cols].astype(
                            jnp.result_type(a)
                        )
                    )
            off += cols
        new_local = {
            "anchor": jax.tree_util.tree_unflatten(treedef, new_a),
            "round": rnd + jnp.int32(1),
            "intra": local["intra"],
        }
        if residual is not None:
            new_local["residual"] = jax.tree_util.tree_unflatten(
                treedef, new_res
            )
        new_local_rows = jax.tree_util.tree_map(
            lambda x: jnp.asarray(x)[None], new_local
        )
        new_state = self._compose_state(
            inner_rows, guard_rows, wire_rows, new_local_rows
        )
        return jax.tree_util.tree_unflatten(treedef, new_p), new_state

    # -- in-backprop scatter / forward gather boundaries -------------------
    def _traced_intra_groups(self):
        """The intra groups for this trace's axis size (local mode),
        or None — resolved lazily so the boundary kwargs can be built
        inside shard_map where the axis exists."""
        if not self._local_on:
            return None
        return self._local_stages(
            int(jax.lax.axis_size(self._axis))
        )[0]

    def _scatter_kw(self, seed):
        return dict(
            op=self._op,
            n_buckets=max(self._overlap_buckets, 1),
            axis_name=self._axis,
            wire=self._wire,
            wire_block=self._wire_block,
            seed=seed,
            min_bucket_bytes=self._overlap_min_bytes,
            hier_stages=self._hier_arg,
            groups=self._traced_intra_groups(),
        )

    def _gather_kw(self, seed):
        return dict(
            n_buckets=max(self._overlap_buckets, 1),
            axis_name=self._axis,
            wire=self._wire,
            wire_block=self._wire_block,
            seed=seed,
            min_bucket_bytes=self._overlap_min_bytes,
            hier_stages=self._hier_arg,
            groups=self._traced_intra_groups(),
        )

    def _carrier_call(self, psh, pfull, seed):
        """Stage-1/2 boundary: the full params pass through untouched
        on the forward (their shard slices are dead forward values XLA
        DCEs away), and the COTANGENT tree leaves through the bucketed
        reduce-scatter — each overlap bucket's reduce-scatter output IS
        the gradient shard slice, emitted at its backward dataflow
        frontier. The full params ride as an explicit operand (zero
        cotangent) because custom_vjp cannot close over tracers; the
        wire seed rides the same way (an int32 operand whose cotangent
        is float0 — kept integer so step counters never collapse to
        shared float32 values past 2^24), so a TRACED per-step seed
        decorrelates a quantized wire's stochastic rounding across
        steps instead of replaying one fixed realization."""
        from .ops import overlap as _overlap

        kw = self._scatter_kw(0)
        kw.pop("seed")
        s = jnp.asarray(seed, jnp.int32)

        @jax.custom_vjp
        def _carrier(q, pf, sv):
            return pf

        def _fwd(q, pf, sv):
            return pf, sv

        def _bwd(sv, ct):
            g_sh = _overlap.bucketed_reduce_scatter(ct, seed=sv, **kw)
            zeros = jax.tree_util.tree_map(jnp.zeros_like, ct)
            return g_sh, zeros, np.zeros(sv.shape, jax.dtypes.float0)

        _carrier.defvjp(_fwd, _bwd)
        return _carrier(psh, pfull, s)

    def _gather_call(self, psh, seed, differentiable=True):
        """Stage-3 boundary: per-bucket all-gathers reconstruct the full
        params at their forward dataflow frontiers (N INDEPENDENT
        collectives — XLA interleaves each into the compute that first
        consumes its bucket, no monolithic unshard); the backward's
        cotangents leave through the matching bucketed reduce-scatter,
        landing gradients directly in shard geometry. The wire seed is
        a traced int32 operand (see _carrier_call). To re-gather
        instead of keeping the full params live across backward, wrap
        per-layer blocks in ``jax.checkpoint`` — the boundary composes
        with remat (the gathers rerun inside the rematerialized
        block)."""
        from .ops import overlap as _overlap

        self._require_meta()
        meta = self._pmeta
        ag_kw = self._gather_kw(0)
        ag_kw.pop("seed")
        rs_kw = self._scatter_kw(0)
        rs_kw.pop("seed")
        s = jnp.asarray(seed, jnp.int32)

        def _ag(q, sv):
            return _overlap.bucketed_shard_all_gather(
                q, meta, seed=sv, **ag_kw
            )

        if not differentiable:
            return _ag(psh, s)

        @jax.custom_vjp
        def _gather(q, sv):
            return _ag(q, sv)

        def _fwd(q, sv):
            return _ag(q, sv), sv

        def _bwd(sv, ct):
            g_sh = _overlap.bucketed_reduce_scatter(
                ct, seed=sv, **rs_kw
            )
            return g_sh, np.zeros(sv.shape, jax.dtypes.float0)

        _gather.defvjp(_fwd, _bwd)
        return _gather(psh, s)

    def value_and_grad(self, fn, has_aux: bool = False, seed: int = 0):
        """The sharded tape: ``opt.value_and_grad(loss_fn)`` returns a
        function whose gradients arrive as per-leaf SHARD slices,
        reduce-scattered per overlap bucket INSIDE backprop (no reduced
        full-gradient tree ever materializes — the ZeRO-2/3 gradient
        leg). Call INSIDE shard_map:

        * stages 1-2: ``loss, g_sh = vg(params, *args)`` with FULL
          params — forward is untouched; the exchange rides the
          backward.
        * stage 3: ``loss, g_sh = vg(opt.local_shards(pstate), *args)``
          — the forward all-gathers each parameter bucket on demand
          (:meth:`gather_params` dataflow) and ``fn`` receives the full
          params.

        Feed the result straight to :meth:`update` (the shard shapes
        are detected statically and the internal reduce-scatter is
        skipped). Quantized-wire seeding: ``seed`` is the per-trace
        default; the returned function also takes ``wire_seed=`` at
        CALL time, which may be a TRACED value (thread your step
        counter through it) — a fixed seed would replay the identical
        stochastic-rounding realization every step, turning unbiased
        rounding noise into a directional drift. fp32/bf16 wires
        ignore it."""

        def vg(p, *args, wire_seed=None, **kwargs):
            sv = seed if wire_seed is None else wire_seed
            if self._stage >= 3:
                def wrapped(q):
                    return fn(self._gather_call(q, sv), *args, **kwargs)

                return jax.value_and_grad(wrapped, has_aux=has_aux)(p)
            n = jax.lax.axis_size(self._axis)
            idx = jax.lax.axis_index(self._axis)
            if self._local_on:
                from .common.topology import stage_positions

                intra_groups = self._local_stages(n)[0]
                width = len(intra_groups[0])
                pos = jnp.asarray(stage_positions(intra_groups))[idx]
            else:
                width, pos = n, idx
            pc = jax.tree_util.tree_map(jax.lax.stop_gradient, p)
            psh = jax.tree_util.tree_map(
                lambda x: x if x.ndim == 0 else _shard_dyn(x, width, pos),
                pc,
            )

            def wrapped(q):
                return fn(
                    self._carrier_call(q, pc, sv), *args, **kwargs
                )

            return jax.value_and_grad(wrapped, has_aux=has_aux)(psh)

        return vg

    def grad(self, fn, has_aux: bool = False, seed: int = 0):
        vg = self.value_and_grad(fn, has_aux=has_aux, seed=seed)

        def g(*args, **kwargs):
            out = vg(*args, **kwargs)
            return out[1]

        return g

    # -- stage-3 parameter storage -----------------------------------------
    def _bind_meta(self, params) -> None:
        self._pmeta = jax.tree_util.tree_map(
            lambda p: jax.ShapeDtypeStruct(
                np.shape(p), jnp.result_type(p)
            ),
            params,
        )

    def _require_meta(self):
        if self._pmeta is None:
            raise ValueError(
                "stage-3 parameter geometry is unbound: call "
                "init_params(params) (fresh start) or "
                "bind_params_like(params_template) (elastic/checkpoint "
                "resume — shapes only, jax.eval_shape output works) "
                "before gathering"
            )

    def init_params(self, params):
        """Stage-3 parameter storage: every leaf becomes its
        ``[world, cols]`` rank-major shard rows (0-d leaves broadcast
        to ``[world]``), the layout of ``parallel.fsdp.host_shard_rows``
        — the same leading-world-axis convention as the optimizer
        state, so BOTH thread through shard_map with ``state_spec()``
        and checkpoint/reshard with the same machinery. Call OUTSIDE
        jit. Also binds the full-parameter geometry used by
        :meth:`gather_params` and the stage-3 boundary."""
        from .common import basics

        n = self._world or basics.size()
        self._world = n
        self._bind_meta(params)
        return jax.tree_util.tree_map(
            lambda p: host_shard_rows(p, n), params
        )

    def bind_params_like(self, params) -> "ShardedDistributedOptimizer":
        """Record the full-parameter geometry (shapes/dtypes only —
        ``jax.eval_shape`` output is fine) without building storage:
        the elastic-resume path, where the shard rows come back from a
        checkpoint but the optimizer object is fresh. Returns self."""
        self._bind_meta(params)
        return self

    @staticmethod
    def local_shards(pstate):
        """Inside shard_map: strip the ``[1, ...]`` world slice off
        every leaf of the parameter storage (or any state-convention
        tree) — the local shard view ``update`` and
        ``optax.apply_updates`` operate on."""
        return jax.tree_util.tree_map(lambda x: x[0], pstate)

    @staticmethod
    def as_rows(local):
        """Inverse of :meth:`local_shards`: re-add the leading world
        axis so the updated shards flow out through ``state_spec()``."""
        return jax.tree_util.tree_map(lambda x: x[None], local)

    def gather_params(self, shards, seed: int = 0):
        """Traced full-parameter reconstruction from local shard leaves
        (inside shard_map): the stage-3 forward unshard as N
        independent per-bucket all-gathers, without the gradient
        boundary — for eval/inference steps. Pass
        ``opt.local_shards(pstate)``."""
        return self._gather_call(shards, seed, differentiable=False)

    def unshard_params(self, pstate):
        """HOST-side full parameter tree from the ``[world, cols]``
        shard rows (outside jit; export/eval/debug). The training path
        never needs this — checkpoints save the shard rows directly."""
        self._require_meta()
        return jax.tree_util.tree_map(
            lambda rows, m: host_unshard(rows, m.shape, m.dtype),
            pstate, self._pmeta,
        )

    def reshard_params(self, pstate, params, new_world: int):
        """Host-side elastic reshard of the stage-3 parameter storage:
        ``[old_world, cols]`` rows → ``[new_world, cols']`` PRESERVING
        every parameter value bit-exactly (only zero-pad tail is
        re-cut). ``params`` is the full-parameter template (shapes —
        ``jax.eval_shape`` output works). Call OUTSIDE jit after the
        new gang forms, alongside ``reshard_state``."""
        if new_world < 1:
            raise ValueError(f"new_world must be >= 1, got {new_world}")
        self._bind_meta(params)

        def _re(rows, p):
            shape = np.shape(p)
            if len(shape) == 0:
                return jnp.broadcast_to(
                    jnp.asarray(np.asarray(rows).reshape(-1)[0]),
                    (new_world,),
                )
            size = int(np.prod(shape, dtype=np.int64))
            return reshard_rows(
                rows, size, new_world, jnp.result_type(p)
            )

        self._world = new_world
        return jax.tree_util.tree_map(_re, pstate, params)

    def state_spec(self):
        """The single PartitionSpec for the whole state pytree in
        shard_map in_specs/out_specs (the stage-3 parameter storage
        uses the same spec)."""
        from jax.sharding import PartitionSpec as P

        return P(self._axis)

    # -- elastic -----------------------------------------------------------
    def reshard_state(self, state, params, new_world: int):
        """Host-side elastic reshard: convert the [old_world, ...]
        stacked state into [new_world, ...] PRESERVING optimizer
        moments across a gang restart — the elastic alternative to
        the "re-run init(params)" error, which would reset Adam
        moments on every world change. Call OUTSIDE jit, with the
        restored full params (a shape template suffices), after the
        new gang forms::

            state = opt.reshard_state(state, params, hvd.size())

        Mechanics: every sharded leaf is the optimizer moment over the
        param's zero-padded flat vector, split rank-major; resharding
        concatenates the old shards and re-splits at the new padding
        (tail entries beyond the param's size are padding positions —
        zeros that no update ever reads back). Replicated leaves
        (scalars like Adam's ``count``; 0-d params) re-broadcast.

        Layout migration happens HERE: guard counters and wire
        (error-feedback) residual rows are carried when the optimizer
        still wants them, synthesized as zeros when newly enabled, and
        stripped when disabled. ``ag`` residuals are shard-major and
        re-split bit-exactly like the moments; ``rs`` residuals are
        per-rank FULL-geometry errors, so the carry preserves the
        TOTAL un-transmitted signal exactly (summed onto rank 0 — the
        reduction only ever consumes the sum).

        Local-SGD (``"local"`` family): the anchor and EF-residual
        rows are re-cut from the OLD split width (read back from the
        family's ``intra`` leaf) to the new topology's — every
        parameter value carries over bit-exactly (only zero-pad tail
        is re-cut). Optimizer MOMENTS under local mode diverge per
        slice; a resize cannot preserve every slice's trajectory, so
        the new gang seeds every slice from OLD SLICE 0's moments
        (deterministic, and consistent with the post-restart rejoin
        round that re-syncs params from the Adasum consensus —
        docs/design.md)."""
        if new_world < 1:
            raise ValueError(f"new_world must be >= 1, got {new_world}")
        inner, guard_rows, wire_rows, local_rows = self._layout(state)
        _lead = jax.tree_util.tree_leaves(
            (inner, guard_rows, wire_rows, local_rows)
        )
        old_world = (
            int(np.asarray(_lead[0]).shape[0]) if _lead else new_world
        )
        old_width = (
            int(np.asarray(local_rows["intra"]).reshape(-1)[0])
            if local_rows is not None
            else old_world
        )
        new_width = self._shard_width(new_world)

        def _rows_recut(rows, cols_new, dtype):
            """[old_world, cols_old] rows (chunks repeat every
            ``old_width`` rows) → [new_world, cols_new]: slice 0's
            chunks reassemble the full padded vector, re-cut at the
            new width and tiled across the new slices. Bit-exact for
            every real entry (only zero-pad tail moves)."""
            rows = np.asarray(rows)
            full = np.concatenate(
                [np.asarray(rows[i]).reshape(-1) for i in range(old_width)]
            )
            need = int(cols_new) * new_width
            flat = np.zeros((need,), rows.dtype)
            k = min(full.shape[0], need)
            flat[:k] = full[:k]
            chunks = flat.reshape(new_width, int(cols_new))
            return jnp.asarray(
                np.stack([chunks[r % new_width] for r in range(new_world)])
            ).astype(dtype)
        if self._guard_on and guard_rows is None:
            # legacy flat state under a NEWLY-enabled guard: resharding
            # is the migration point — synthesize zero counters so the
            # resumed job starts guarded instead of crashing at its
            # first update
            zero = np.zeros((1,), np.int64)
            guard_rows = {"skips": zero, "streak": zero, "step": zero}
        elif not self._guard_on:
            # guard turned OFF against a guarded checkpoint: the same
            # migration point downgrades — strip the counters
            guard_rows = None
        wants_wire = self._wants_wire_rows()
        synthesize_wire = wants_wire and wire_rows is None
        if not wants_wire:
            wire_rows = None

        # shard-geometry zeros, not a value shard: only leaf
        # size/dtype/structure are read off the template, and zeros
        # keep a jax.eval_shape params template working (the
        # documented elastic-resume path never materializes values)
        def _shard_zeros(p):
            shape = tuple(np.shape(p))
            dt = jnp.result_type(p)
            if not shape:
                return jnp.zeros((), dt)
            size = int(np.prod(shape, dtype=np.int64))
            return jnp.zeros((shard_cols(size, new_width),), dt)

        template = self._inner.init(
            jax.tree_util.tree_map(_shard_zeros, params)
        )
        old_leaves = jax.tree_util.tree_leaves(inner)
        tmpl_leaves, treedef = jax.tree_util.tree_flatten(template)
        if len(old_leaves) != len(tmpl_leaves):
            raise ValueError(
                "state does not match this optimizer's structure "
                f"({len(old_leaves)} leaves vs {len(tmpl_leaves)})"
            )
        out = []
        for o, t in zip(old_leaves, tmpl_leaves):
            o = np.asarray(o)
            t = jnp.asarray(t)
            if t.ndim == 0:
                # replicated leaf, stacked [old_world] -> [new_world]
                out.append(
                    jnp.broadcast_to(
                        jnp.asarray(o.reshape(-1)[0]), (new_world,)
                    )
                )
                continue
            if old_width == old_world and new_width == new_world:
                # flat → flat: per-rank re-split lands exactly on the
                # template's shard size (parallel.fsdp.reshard_rows —
                # the ONE re-split implementation, shared with
                # reshard_params and the ag residuals)
                out.append(
                    reshard_rows(o, t.size * new_world, new_world, t.dtype)
                )
            else:
                # a local-SGD split is involved (either side): re-cut
                # from slice 0's chunks at the new width (moments
                # diverge per slice — see the docstring's policy)
                out.append(_rows_recut(o, t.size, t.dtype))
        self._world = new_world
        resharded = jax.tree_util.tree_unflatten(treedef, out)
        new_guard = None
        if guard_rows is not None:
            new_guard = {
                key: jnp.broadcast_to(
                    jnp.asarray(
                        np.asarray(val).reshape(-1)[0], jnp.int32
                    ),
                    (new_world,),
                )
                for key, val in guard_rows.items()
            }
        new_wire = None
        if synthesize_wire:
            new_wire = self._init_wire_rows(params, new_world, new_width)
        elif wire_rows is not None:
            new_wire = self._reshard_wire_rows(
                wire_rows, params, new_world, new_width, _rows_recut,
                flat_ok=(old_width == old_world and new_width == new_world),
                old_width=old_width,
            )
        new_local = None
        if self._local_on:
            if local_rows is None:
                # local mode newly enabled: the anchor IS the params,
                # so the migration needs concrete values
                if any(
                    not isinstance(l, (jnp.ndarray, np.ndarray))
                    and not hasattr(l, "__array__")
                    for l in jax.tree_util.tree_leaves(params)
                ):
                    raise ValueError(
                        "enabling local_sgd_steps against a state "
                        "without the \"local\" family needs concrete "
                        "parameter VALUES (the anchor is the params); "
                        "a jax.eval_shape template cannot seed it"
                    )
                new_local = self._init_local_rows(
                    params, new_world, new_width
                )
            else:
                new_local = self._reshard_local_rows(
                    local_rows, params, new_world, new_width, _rows_recut
                )
        return self._compose_state(
            resharded, new_guard, new_wire, new_local
        )

    def _reshard_local_rows(
        self, local_rows, params, new_world, new_width, recut
    ):
        """Migrate the ``"local"`` family across a topology change:
        anchor chunks re-cut bit-exactly at the new width (anchors are
        identical across slices by the sync contract — slice 0's rows
        reassemble the one true copy); EF residual chunks re-cut the
        same way, which ADOPTS slice 0's carry (per-slice carries
        cannot survive a re-slicing; the loss is bounded by one
        quantum per element); the round counter re-broadcast; the
        width leaf refreshed."""
        def _leaf(rows, p):
            if np.ndim(p) == 0:
                return jnp.broadcast_to(
                    jnp.asarray(np.asarray(rows).reshape(-1)[0]),
                    (new_world,),
                )
            size = int(np.prod(np.shape(p), dtype=np.int64))
            return recut(
                rows, shard_cols(size, new_width),
                jnp.result_type(np.asarray(rows)),
            )

        out = {
            "anchor": jax.tree_util.tree_map(
                _leaf, local_rows["anchor"], params
            ),
            "round": jnp.broadcast_to(
                jnp.asarray(
                    np.asarray(local_rows["round"]).reshape(-1)[0],
                    jnp.int32,
                ),
                (new_world,),
            ),
            "intra": jnp.full((new_world,), new_width, jnp.int32),
        }
        if self._local_wire == "int8":
            if "residual" in local_rows:
                out["residual"] = jax.tree_util.tree_map(
                    _leaf, local_rows["residual"], params
                )
            else:
                out["residual"] = jax.tree_util.tree_map(
                    lambda a: jnp.zeros_like(a), out["anchor"]
                )
        return out

    def _reshard_wire_rows(
        self, wire_rows, params, new_world: int,
        new_width: Optional[int] = None, recut=None, flat_ok: bool = True,
        old_width: Optional[int] = None,
    ):
        if new_width is None:
            new_width = new_world
        step = jnp.broadcast_to(
            jnp.asarray(
                np.asarray(wire_rows["step"]).reshape(-1)[0], jnp.int32
            ),
            (new_world,),
        )
        if not self._ef:
            return {"step": step}  # seed-only (plain quantized wire)
        if "rs" not in wire_rows:
            # EF newly enabled against a seed-only wire state: the
            # migration point synthesizes zero carries, keeping the
            # seed counter
            out = self._init_wire_rows(params, new_world, new_width)
            out["step"] = step
            return out

        def _re_rs(rows, p):
            # per-rank FULL-geometry error: the future wire only ever
            # consumes the cross-rank SUM, so carrying Σ over the old
            # gang onto rank 0 (zeros elsewhere) preserves the
            # un-transmitted signal exactly across the resize. Under a
            # LOCAL-SGD split the carry is defined against each
            # slice's OWN intra sum — a gang-wide Σ would inject
            # foreign slices' error into slice 0's next reduction —
            # so only slice 0's rows are summed (its total preserved;
            # other slices' carries are dropped like their moments,
            # the documented resize policy).
            rows = np.asarray(rows)
            if np.ndim(p) == 0:
                return jnp.broadcast_to(
                    jnp.asarray(rows.reshape(-1)[0]), (new_world,)
                )
            n_sum = (
                rows.shape[0]
                if flat_ok or old_width is None
                else old_width
            )
            total = rows[:n_sum].sum(axis=0)
            out = np.zeros((new_world,) + total.shape, rows.dtype)
            out[0] = total
            return jnp.asarray(out)

        def _re_ag(rows, p):
            if np.ndim(p) == 0:
                return jnp.broadcast_to(
                    jnp.asarray(np.asarray(rows).reshape(-1)[0]),
                    (new_world,),
                )
            size = int(np.prod(np.shape(p), dtype=np.int64))
            if flat_ok or recut is None:
                return reshard_rows(
                    rows, size, new_world, np.asarray(rows).dtype
                )
            # a local-SGD width is involved: re-cut from slice 0's
            # chunks like the moments (per-slice carries cannot
            # survive a re-slicing; the loss is bounded by one quantum)
            return recut(
                rows, shard_cols(size, new_width),
                np.asarray(rows).dtype,
            )

        return {
            "step": step,
            "rs": jax.tree_util.tree_map(
                _re_rs, wire_rows["rs"], params
            ),
            "ag": jax.tree_util.tree_map(
                _re_ag, wire_rows["ag"], params
            ),
        }
