"""Gradient wire compression.

API parity with the reference's compression module
(ref: horovod/torch/compression.py + horovod/tensorflow/compression.py [V],
SURVEY.md §2.4): ``Compression.none`` and ``Compression.fp16``, each a
(compress, decompress) pair applied around the allreduce.

On TPU the natural wire format is bfloat16 (same exponent range as fp32 —
no loss-scaling dance, and the MXU consumes it natively), so ``bf16`` is
added alongside the reference's fp16. XLA fuses the casts into the
collective's producer/consumer, so compression costs no extra HBM pass.
"""

from __future__ import annotations

import jax.numpy as jnp


class Compressor:
    """A (compress, decompress) pair. ``compress`` returns (tensor, ctx)."""

    @staticmethod
    def compress(tensor):
        raise NotImplementedError

    @staticmethod
    def decompress(tensor, ctx):
        raise NotImplementedError


class NoneCompressor(Compressor):
    @staticmethod
    def compress(tensor):
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor


class FP16Compressor(Compressor):
    """Cast floating tensors to fp16 on the wire, restore original dtype
    after (ref: FP16Compressor [V])."""

    @staticmethod
    def compress(tensor):
        ctx = tensor.dtype
        if jnp.issubdtype(tensor.dtype, jnp.floating):
            tensor = tensor.astype(jnp.float16)
        return tensor, ctx

    @staticmethod
    def decompress(tensor, ctx):
        return tensor.astype(ctx) if ctx != tensor.dtype else tensor


class BF16Compressor(Compressor):
    """TPU-native wire compression: bfloat16 keeps fp32's exponent range."""

    @staticmethod
    def compress(tensor):
        ctx = tensor.dtype
        if jnp.issubdtype(tensor.dtype, jnp.floating):
            tensor = tensor.astype(jnp.bfloat16)
        return tensor, ctx

    @staticmethod
    def decompress(tensor, ctx):
        return tensor.astype(ctx) if ctx != tensor.dtype else tensor


class Int8Compressor(Compressor):
    """4x wire compression: int8 values + one float32 scale, stochastic
    rounding (unbiased) via the Pallas quantizer (ops/pallas_kernels.py).

    Beyond reference parity (the reference stops at fp16 [V]). Two
    supported uses: (a) ``DistributedOptimizer(compression=
    Compression.int8)`` — the optimizer detects ``quantized_wire`` and
    routes gradients through ``traced.quantized_allreduce`` (raw int8
    must never be summed across ranks: it wraps, and each rank's scale
    differs); (b) manual compress/decompress around allgather/broadcast
    payloads, where no cross-rank arithmetic touches the wire values.
    Pass a fresh ``seed`` per call (e.g. the step counter) to keep the
    rounding unbiased over time rather than merely per-call.
    """

    # Signals _allreduce_grads to use the quantized collective instead
    # of compress -> psum -> decompress.
    quantized_wire = True

    @staticmethod
    def compress(tensor, seed=0):
        from . import pallas_kernels

        ctx = tensor.dtype
        if jnp.issubdtype(tensor.dtype, jnp.floating):
            values, scale = pallas_kernels.int8_quantize(tensor, seed=seed)
            return values, (ctx, scale)
        return tensor, (ctx, None)

    @staticmethod
    def decompress(tensor, ctx):
        from . import pallas_kernels

        dtype, scale = ctx
        if scale is None:
            return tensor
        return pallas_kernels.int8_dequantize(tensor, scale, out_dtype=dtype)


class Compression:
    """Namespace mirroring hvd.Compression [V] (+ TPU-native additions)."""

    none = NoneCompressor
    fp16 = FP16Compressor
    bf16 = BF16Compressor
    int8 = Int8Compressor
