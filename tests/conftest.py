"""Test harness: simulate an 8-chip slice on CPU.

Mirrors the reference's test strategy (SURVEY.md §4): the reference runs
`horovodrun -np 2` multi-process on localhost; we run an 8-device
host-platform mesh in one process — same closed-form collective math, real
XLA collectives, no TPU hardware needed.
"""

import os

# Must happen before jax initializes its backends.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# The sandbox's sitecustomize force-selects the axon TPU platform; override
# it back to CPU before any backend initializes.
jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running multi-process integration test"
    )
    config.addinivalue_line(
        "markers", "ray: needs the real ray package (optional integration)"
    )


@pytest.fixture
def hvd():
    """Initialized horovod_tpu with clean state per test."""
    import horovod_tpu as hvd

    hvd.shutdown()
    hvd.init()
    yield hvd
    hvd.shutdown()


@pytest.fixture
def rng():
    return np.random.default_rng(42)


def dense_attention_oracle(q, k, v, causal):
    """Shared dense-attention reference for the kernel/parallel tests:
    fp32 scores, -1e30 causal fill (matching the flash kernels'
    finite mask constant)."""
    import jax
    import jax.numpy as jnp

    d = q.shape[-1]
    s = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
    ) / jnp.sqrt(d).astype(jnp.float32)
    if causal:
        t = q.shape[1]
        mask = jnp.tril(jnp.ones((t, t), bool))
        s = jnp.where(mask[None, None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum(
        "bhqk,bkhd->bqhd", p, v.astype(jnp.float32)
    ).astype(q.dtype)
