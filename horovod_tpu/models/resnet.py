"""ResNet-v1.5 family — the reference's headline benchmark model
(ref: examples/pytorch/pytorch_synthetic_benchmark.py uses
torchvision resnet50; docs/benchmarks.rst scaling figures [V];
BASELINE.md north star: ResNet-50 synthetic img/s).

TPU-first choices: NHWC layout (TPU conv native), bfloat16 compute with
fp32 params/batch-stats, fused conv+BN+relu left to XLA, optional
SyncBatchNorm that reduces batch statistics across the world axis the way
the reference's hvd.SyncBatchNorm does (horovod/torch/sync_batch_norm.py
[V]) — expressed as a psum inside the traced step instead of a custom
autograd function.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Optional, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp
from jax import lax


class SyncBatchNorm(nn.Module):
    """Cross-replica batch norm (ref: horovod/torch/sync_batch_norm.py [V]):
    batch statistics are psum-averaged over the mesh axis so every replica
    normalizes with global-batch statistics."""

    axis_name: Optional[str] = None
    momentum: float = 0.9
    epsilon: float = 1e-5
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, use_running_average: bool = False):
        features = x.shape[-1]
        ra_mean = self.variable(
            "batch_stats", "mean", lambda: jnp.zeros(features, jnp.float32)
        )
        ra_var = self.variable(
            "batch_stats", "var", lambda: jnp.ones(features, jnp.float32)
        )
        scale = self.param("scale", nn.initializers.ones, (features,))
        bias = self.param("bias", nn.initializers.zeros, (features,))

        if use_running_average:
            mean, var = ra_mean.value, ra_var.value
        else:
            axes = tuple(range(x.ndim - 1))
            # Stats accumulate in fp32 (dtype= on the reduction — XLA
            # fuses the widening into the reduce, no fp32 copy of the
            # activation is materialized).
            mean = jnp.mean(x, axis=axes, dtype=jnp.float32)
            mean2 = jnp.mean(jnp.square(x), axis=axes, dtype=jnp.float32)
            # Skip the collective while flax builds shapes: init() runs
            # outside shard_map, where the mesh axis is unbound.
            if self.axis_name is not None and not self.is_initializing():
                mean = lax.pmean(mean, self.axis_name)
                mean2 = lax.pmean(mean2, self.axis_name)
            var = mean2 - mean * mean
            if not self.is_initializing():
                m = self.momentum
                ra_mean.value = m * ra_mean.value + (1 - m) * mean
                ra_var.value = m * ra_var.value + (1 - m) * var

        # Normalize as ONE fused multiply-add in the compute dtype:
        # y = x·inv + (bias − mean·inv), with inv/mean folded in fp32
        # first ([C]-sized, free). The previous elementwise-fp32
        # formulation doubled the HBM bytes of every BN — measured
        # +2.8% step throughput on the v5e chip from this change alone
        # (docs/perf.md round-3 profile).
        inv = lax.rsqrt(var + self.epsilon) * scale
        y = x * inv.astype(x.dtype) + (bias - mean * inv).astype(x.dtype)
        return y.astype(self.dtype)


class Bottleneck(nn.Module):
    features: int
    strides: Tuple[int, int] = (1, 1)
    axis_name: Optional[str] = None
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        norm = partial(
            SyncBatchNorm, axis_name=self.axis_name, dtype=self.dtype
        )
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype)
        residual = x
        y = conv(self.features, (1, 1))(x)
        y = nn.relu(norm()(y, use_running_average=not train))
        y = conv(self.features, (3, 3), strides=self.strides)(y)
        y = nn.relu(norm()(y, use_running_average=not train))
        y = conv(self.features * 4, (1, 1))(y)
        y = norm()(y, use_running_average=not train)
        if residual.shape != y.shape:
            residual = conv(
                self.features * 4, (1, 1), strides=self.strides,
                name="proj_conv",
            )(residual)
            residual = norm(name="proj_bn")(
                residual, use_running_average=not train
            )
        return nn.relu(y + residual)


class ResNet(nn.Module):
    """``stem``: 'conv7' is the textbook 7×7/s2 stem; 'space_to_depth'
    is the MXU-shaped reformulation (the standard MLPerf ResNet trick on
    TPU): the image is space-to-depth'd 2× to [H/2, W/2, 4C] and the
    stem becomes a 4×4/s1 conv — same receptive field and output grid,
    but the contraction dim grows 3→12 channels, which packs the MXU's
    128-lane tiles far better than a 3-channel conv ever can."""

    stage_sizes: Sequence[int]
    num_classes: int = 1000
    width: int = 64
    axis_name: Optional[str] = None
    dtype: Any = jnp.bfloat16
    stem: str = "conv7"

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = x.astype(self.dtype)
        if self.stem == "space_to_depth":
            n, h, w, c = x.shape
            if h % 2 or w % 2:
                raise ValueError(
                    f"space_to_depth stem needs even spatial dims, got "
                    f"{(h, w)}"
                )
            x = (
                x.reshape(n, h // 2, 2, w // 2, 2, c)
                .transpose(0, 1, 3, 2, 4, 5)
                .reshape(n, h // 2, w // 2, 4 * c)
            )
            # 4×4/s1 with pad (2,1): exactly the 7×7/s2 output grid
            # (offsets {-2,-1,0,1} in s2d coordinates).
            x = nn.Conv(
                self.width, (4, 4), strides=(1, 1),
                padding=[(2, 1), (2, 1)], use_bias=False, dtype=self.dtype,
            )(x)
        elif self.stem == "conv7":
            x = nn.Conv(
                self.width, (7, 7), strides=(2, 2),
                padding=[(3, 3), (3, 3)], use_bias=False, dtype=self.dtype,
            )(x)
        else:
            raise ValueError(f"unknown stem {self.stem!r}")
        x = SyncBatchNorm(axis_name=self.axis_name, dtype=self.dtype)(
            x, use_running_average=not train
        )
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), (2, 2), padding=((1, 1), (1, 1)))
        for i, n_blocks in enumerate(self.stage_sizes):
            for j in range(n_blocks):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = Bottleneck(
                    self.width * 2**i,
                    strides=strides,
                    axis_name=self.axis_name,
                    dtype=self.dtype,
                )(x, train=train)
        x = jnp.mean(x, axis=(1, 2))
        # Classifier head in fp32 for numerically stable softmax/loss.
        return nn.Dense(self.num_classes, dtype=jnp.float32)(
            x.astype(jnp.float32)
        )


def ResNet50(**kwargs) -> ResNet:
    return ResNet(stage_sizes=(3, 4, 6, 3), **kwargs)


def ResNet101(**kwargs) -> ResNet:
    """ref benchmark family member (docs/benchmarks.rst [V])."""
    return ResNet(stage_sizes=(3, 4, 23, 3), **kwargs)
