"""Expert parallelism: switch-style MoE FFN over the 'ep' axis.

The reference ships only the building block — the alltoall collective
(SURVEY.md §2.6: "the alltoall collective is the EP building block;
reference ships the primitive only"). Here it becomes the real thing:
experts are sharded across the 'ep' mesh axis, tokens are routed top-1
(switch transformer style) with a fixed capacity per expert (static
shapes — XLA requirement), dispatched to their expert's chip with
`lax.all_to_all`, transformed, and returned by the inverse all_to_all.

The dispatch WIRE rides the same stack every other byte family got
(PR 12): ``wire=`` selects fp32 / bf16 / block-scaled int8 with
stochastic rounding (``ops/traced.py quantized_alltoall`` — dropped
and pad slots are all-zero rows with a ``-1`` expert sentinel, so they
are excluded from every block scale by construction), ``hier=`` routes
the exchange through the two-level (intra-ICI / inter-DCN) recipe of
``traced.hierarchical_alltoall`` — tokens bound for intra-slice
experts move bf16/fp32, only the DCN hop rides int8 (the PR 10
placement rule), and ``wire="auto"`` consults the shared WireTuner's
``("alltoall", payload-bucket, dtype, hop)`` keys at trace time.
Routing decisions are computed on fp32 logits BEFORE any wire cast,
so they are identical across wires — the lossy wire moves the same
tokens to the same experts, a few quanta noisier.

Per-device code for use inside shard_map.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax


class MoEParams(NamedTuple):
    router: jnp.ndarray  # [D, E_total]
    w1: jnp.ndarray  # [E_local, D, F]
    b1: jnp.ndarray  # [E_local, F]
    w2: jnp.ndarray  # [E_local, F, D]
    b2: jnp.ndarray  # [E_local, D]


class MoEStats(NamedTuple):
    """Per-step expert-load counters (global — psum'd over the axis),
    the feed for the capacity-factor autotuner (common/autotune.py
    CapacityTuner) and the per-rank expert-load summaries published
    through the rendezvous KV (elastic/worker.py publish_expert_load):
    hot experts ARE stragglers, and these are how the scheduler sees
    them."""

    expert_tokens: jnp.ndarray  # [E_total] f32 — kept tokens per expert
    dropped: jnp.ndarray  # scalar f32 — tokens past capacity (zero out)
    total: jnp.ndarray  # scalar f32 — live tokens routed


def init_moe_params(key, d_model: int, d_ff: int, n_experts_local: int,
                    n_experts_total: int, dtype=jnp.float32) -> MoEParams:
    k1, k2, k3 = jax.random.split(key, 3)
    s1 = 1.0 / jnp.sqrt(d_model)
    s2 = 1.0 / jnp.sqrt(d_ff)
    return MoEParams(
        router=(jax.random.normal(k1, (d_model, n_experts_total)) * s1).astype(dtype),
        w1=(jax.random.normal(k2, (n_experts_local, d_model, d_ff)) * s1).astype(dtype),
        b1=jnp.zeros((n_experts_local, d_ff), dtype),
        w2=(jax.random.normal(k3, (n_experts_local, d_ff, d_model)) * s2).astype(dtype),
        b2=jnp.zeros((n_experts_local, d_model), dtype),
    )


def _resolve_hier(hier, ep: int):
    """The two-level routing decision for the expert wire: explicit
    ``(intra_groups, inter_groups)`` stages pass through; ``None``
    consults the HOROVOD_HIERARCHICAL default tri-state (the same
    decision the fused dispatcher and the overlap buckets ride);
    "on"/True force any resolvable split; "off"/False keep it flat."""
    from ..common import topology as _topo

    if hier is None:
        return _topo.hierarchy_stages(world=ep)
    if hier in ("off", False):
        return None
    if hier in ("on", True):
        return _topo.hierarchy_stages(world=ep, mode="on")
    return hier  # explicit stages


def _resolve_wire(wire, intra_wire, payload_bytes: int, hier):
    """Trace-time wire choice. ``auto`` asks the shared WireTuner's
    ``(alltoall, hop)`` key family — a compile-time decision like the
    OverlapTuner's bucket count: the harness feeds goodput across
    recompiles (bench_moe.py shows the loop), the choice is frozen
    into this trace."""
    from ..common import basics as _basics

    cfg = _basics.live_config()
    if wire is None:
        wire = cfg.moe_wire
    if intra_wire is None:
        intra_wire = cfg.moe_intra_wire
    if wire not in ("fp32", "bf16", "int8", "auto"):
        raise ValueError(
            f"moe wire must be fp32/bf16/int8/auto, got {wire!r}"
        )
    if intra_wire not in ("fp32", "bf16"):
        raise ValueError(
            f"moe intra_wire must be fp32/bf16, got {intra_wire!r}"
        )
    if wire == "auto":
        from ..common.autotune import shared_wire_tuner

        tuner = shared_wire_tuner()
        bucket = 1 << max(int(payload_bytes) - 1, 1).bit_length()
        if hier is not None:
            H = len(hier[1][0])
            wire = tuner.choose(
                ("alltoall", bucket, "float32", "inter"),
                payload_bytes=payload_bytes * (H - 1) // max(H, 1),
                itemsize=4,
            )
            intra_wire = tuner.choose(
                ("alltoall", bucket, "float32", "intra"),
                payload_bytes=payload_bytes,
                itemsize=4,
                candidates=("fp32", "bf16"),
            )
        else:
            wire = tuner.choose(
                ("alltoall", bucket, "float32", "flat"),
                payload_bytes=payload_bytes,
                itemsize=4,
            )
    return wire, intra_wire


def _cast_wire(x, wire):
    return x.astype(jnp.bfloat16) if wire == "bf16" else x


def moe_ffn(
    params: MoEParams,
    x,
    axis_name: str = "ep",
    capacity_factor: Optional[float] = None,
    wire: Optional[str] = None,
    intra_wire: Optional[str] = None,
    hier=None,
    seed: int = 0,
    block_size: Optional[int] = None,
    mask=None,
    process_set=None,
    return_stats: bool = False,
):
    """x: [T_local, D] tokens on this chip → [T_local, D].

    Routing: top-1 over E_total experts; expert e lives on chip
    e // E_local of the 'ep' axis. Tokens over capacity are dropped
    (switch-style; their output is zero and the residual connection
    carries them).

    ``capacity_factor`` (None = HOROVOD_MOE_CAPACITY_FACTOR) sizes the
    static per-destination buffer; for a measured choice drive the
    step harness through ``common.autotune.CapacityTuner`` — capacity
    is a compile-time shape, so tuning happens across recompiles.

    ``wire`` ∈ {fp32, bf16, int8, auto} (None = HOROVOD_MOE_WIRE) is
    the dispatch+return wire; with a two-level split (``hier``) it
    names the INTER hop and ``intra_wire`` ∈ {fp32, bf16} the ICI
    legs. The expert-index map always moves exact int32. ``seed``
    decorrelates the stochastic rounding (thread a step counter for
    unbiasedness over time).

    ``mask`` is the traced join mask ([world] bool, ``mask[r] ==
    False`` = rank r ran out of data): a masked rank contributes no
    tokens (its output rows are zeros) while its EXPERTS keep serving
    the live ranks. ``process_set`` restricts routing to the member
    ranks' experts (non-members return zeros; the wire degenerates to
    the flat masked/ring formulation — hier and int8 need the full
    axis). ``return_stats=True`` additionally returns :class:`MoEStats`.
    """
    from ..ops import traced as _traced
    from ..common import basics as _basics

    ep = lax.axis_size(axis_name)
    t_local, d = x.shape
    e_local = params.w1.shape[0]
    e_total = e_local * ep

    if capacity_factor is None:
        capacity_factor = _basics.live_config().moe_capacity_factor
    if block_size is None:
        block_size = _basics.live_config().moe_wire_block

    info = _traced._set_info(process_set, axis_name)
    member = None
    pos = None
    if info is not None:
        member, pos = _traced._member(info, axis_name)
    live = None
    if mask is not None:
        live = jnp.asarray(mask)[lax.axis_index(axis_name)]

    # participating-rank count and expert universe
    k = info.size if info is not None else ep
    hier_stages = None if info is not None else _resolve_hier(hier, ep)
    capacity = int(max(1, round(float(capacity_factor) * t_local / k)))
    payload_bytes = k * capacity * d * 4
    wire, intra_wire = _resolve_wire(
        wire, intra_wire, payload_bytes, hier_stages
    )
    if info is not None and wire == "int8":
        # the ring formulation moves raw blocks; quantized + pset is
        # not a supported combination — degrade loudly-documented
        wire = "fp32"

    logits = jnp.einsum("td,de->te", x.astype(jnp.float32),
                        params.router.astype(jnp.float32))
    if info is not None:
        # non-member ranks' experts are outside the set: route over
        # member experts only (set order = member rank order)
        owner = jnp.arange(e_total) // e_local  # [E_total] owning rank
        allowed = jnp.asarray(info.mask)[owner]
        logits = jnp.where(allowed[None, :], logits, -jnp.inf)
    probs = jax.nn.softmax(logits, axis=-1)
    expert_idx = jnp.argmax(probs, axis=-1)  # [T]
    gate = jnp.take_along_axis(probs, expert_idx[:, None], axis=-1)[:, 0]

    owner_rank = expert_idx // e_local  # [T] flat owning rank
    if info is not None:
        # position of the owning rank within the set = dispatch slot
        dest_chip = jnp.asarray(info.pos)[owner_rank]
    else:
        dest_chip = owner_rank

    # position of each token within its destination chip's buffer
    onehot_chip = jax.nn.one_hot(dest_chip, k, dtype=jnp.int32)  # [T, k]
    pos_in_chip = (jnp.cumsum(onehot_chip, axis=0) - 1)  # [T, k]
    my_pos = jnp.take_along_axis(
        pos_in_chip, dest_chip[:, None], axis=1
    )[:, 0]  # [T]
    keep = my_pos < capacity
    if member is not None:
        keep = jnp.logical_and(keep, member)
    if live is not None:
        keep = jnp.logical_and(keep, live)

    # Scatter tokens into the dispatch buffer [k, capacity, D]. Dropped
    # tokens get an out-of-range index → mode='drop' discards them, so
    # empty slots keep their init value (zeros in the payload, -1
    # sentinel in the expert map — the pad-exclusion contract of the
    # quantized wire).
    idx_chip = jnp.where(keep, dest_chip, k)
    idx_pos = jnp.where(keep, my_pos, 0)
    dispatch = (
        jnp.zeros((k, capacity, d), x.dtype)
        .at[idx_chip, idx_pos]
        .set(x, mode="drop")
    )
    token_expert = (
        jnp.full((k, capacity), -1, jnp.int32)
        .at[idx_chip, idx_pos]
        .set((expert_idx % e_local).astype(jnp.int32), mode="drop")
    )

    def _quantized_fwd(b3, step_seed):
        """The int8-bearing exchange of one [k, C, d] float buffer —
        wrapped in a custom_vjp below: stochastic rounding has no
        useful gradient (floor/compare are piecewise-flat, and the
        absmax scales would leak a spurious one), so the cotangent
        rides the EXACT inverse exchange instead — the alltoall's own
        transpose, straight-through on the quantizer. Training with
        the lossy wire therefore costs exactly one extra fp32 exchange
        in backward, never a poisoned gradient."""
        if hier_stages is not None:
            return _traced.hierarchical_alltoall(
                b3, axis_name=axis_name, stages=hier_stages,
                intra_wire=intra_wire, inter_wire=wire,
                seed=step_seed, block_size=block_size,
            )
        return _traced.quantized_alltoall(
            b3, axis_name=axis_name, seed=step_seed,
            block_size=block_size,
        ).astype(b3.dtype)

    @jax.custom_vjp
    def _st_exchange(b3, step_seed):
        return _quantized_fwd(b3, step_seed)

    def _st_fwd(b3, step_seed):
        return _quantized_fwd(b3, step_seed), None

    def _st_bwd(_, ct):
        # the exact exchange is its own transpose for the symmetric
        # [k, C, d] split0/concat0 layout (block (i, j) ↔ (j, i));
        # hierarchical-exact keeps the cotangent's DCN legs two-level
        if hier_stages is not None:
            back = _traced.hierarchical_alltoall(
                ct, axis_name=axis_name, stages=hier_stages
            )
        else:
            back = lax.all_to_all(
                ct, axis_name, split_axis=0, concat_axis=0, tiled=True
            )
        return back, None

    _st_exchange.defvjp(_st_fwd, _st_bwd)

    def exchange(buf, step_seed):
        """One dispatch-shaped hop of the expert wire ([k, C, ·])."""
        floaty = jnp.issubdtype(buf.dtype, jnp.floating)
        if info is not None:
            flat = buf.reshape(k * capacity, -1)
            if wire == "bf16" and floaty:
                flat = _cast_wire(flat, wire)
            out = _traced.alltoall(
                flat, process_set=process_set, axis_name=axis_name
            ).astype(buf.dtype)
            return out.reshape(buf.shape)
        b3 = buf.reshape(k, capacity, -1)
        if hier_stages is not None:
            if wire == "int8" and floaty:
                return _st_exchange(b3, step_seed).reshape(buf.shape)
            return _traced.hierarchical_alltoall(
                b3, axis_name=axis_name, stages=hier_stages,
                intra_wire=intra_wire if floaty else "fp32",
                inter_wire=wire if floaty else "fp32",
                seed=step_seed, block_size=block_size,
            ).reshape(buf.shape)
        if wire == "int8" and floaty:
            return _st_exchange(b3, step_seed).reshape(buf.shape)
        cast = _cast_wire(b3, wire) if floaty else b3
        return lax.all_to_all(
            cast, axis_name, split_axis=0, concat_axis=0, tiled=True
        ).astype(buf.dtype).reshape(buf.shape)

    # To each chip its tokens: [k, C, D] exchanged over the wire; the
    # expert map rides exact int32 alongside.
    recv = exchange(dispatch, seed)
    recv_expert = exchange(token_expert[..., None], seed)[..., 0]
    # recv: [k*C, D] tokens for MY local experts (concat over sources).
    recv = recv.reshape(k * capacity, d)
    which_expert = recv_expert.reshape(k * capacity)

    # Apply each local expert to its tokens (dense einsum over one-hot —
    # MXU-friendly, no gather/scatter in the hot loop).
    sel = jax.nn.one_hot(which_expert, e_local, dtype=recv.dtype)  # [N, E_l]
    h = jnp.einsum("nd,edf,ne->nf", recv, params.w1, sel)
    h = h + jnp.einsum("ef,ne->nf", params.b1, sel)
    h = jax.nn.gelu(h)
    y = jnp.einsum("nf,efd,ne->nd", h, params.w2, sel)
    y = y + jnp.einsum("ed,ne->nd", params.b2, sel)
    # tokens that carried expert=-1 (padding) produce zeros — which
    # also keeps pad slots out of the return wire's block scales
    y = y * (which_expert >= 0)[:, None]

    # Return to origin chips: inverse exchange over the same wire.
    y_back = exchange(
        y.reshape(k, capacity, d), seed + 0x9E37
    ).reshape(k, capacity, d)

    # Un-scatter: token i's result sits at [dest_chip[i], my_pos[i]].
    out = y_back[idx_chip, idx_pos]
    out = jnp.where(keep[:, None], out, 0.0)
    out = (out * gate[:, None]).astype(x.dtype)
    if member is not None:
        out = jnp.where(member, out, jnp.zeros_like(out))
    if live is not None:
        out = jnp.where(live, out, jnp.zeros_like(out))
    if not return_stats:
        return out

    # Expert-load counters, psum'd so every rank holds the global view
    # (the capacity tuner / KV publisher feed). ``total`` counts live
    # routed tokens; ``dropped`` the capacity-gate losses among them.
    routed = jnp.ones((t_local,), jnp.float32)
    if member is not None:
        routed = jnp.where(member, routed, 0.0)
    if live is not None:
        routed = jnp.where(live, routed, 0.0)
    kept = jnp.where(keep, routed, 0.0)
    hist = jnp.sum(
        jax.nn.one_hot(expert_idx, e_total, dtype=jnp.float32)
        * kept[:, None],
        axis=0,
    )
    stats = MoEStats(
        expert_tokens=lax.psum(hist, axis_name),
        dropped=lax.psum(jnp.sum(routed - kept), axis_name),
        total=lax.psum(jnp.sum(routed), axis_name),
    )
    return out, stats
