"""Tensor parallelism: Megatron-style column/row parallel matmuls.

Absent from the reference (SURVEY.md §2.6 lists TP as ❌); built here
because on TPU it falls out of the same collectives the reference ships —
a row-parallel matmul is a matmul plus the reference's allreduce.
Functions are per-device code for use inside shard_map: weight shards live
on the 'tp' axis, activations stay replicated across it.

- column parallel: W split along output features → local matmul, no comm;
  activations become tp-sharded on the feature dim.
- row parallel: W split along input features → local matmul + psum('tp')
  (one ICI all-reduce, exactly where Megatron places it).
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax


def column_parallel_dense(x, w_shard, b_shard=None):
    """x: [..., D]; w_shard: [D, F/tp] → [..., F/tp]. No communication."""
    y = jnp.einsum("...d,df->...f", x, w_shard)
    if b_shard is not None:
        y = y + b_shard
    return y


def row_parallel_dense(x_shard, w_shard, b=None, axis_name: str = "tp"):
    """x_shard: [..., F/tp]; w_shard: [F/tp, D] → psum over tp → [..., D].

    The bias is added after the reduce on every rank (it is replicated)."""
    y = jnp.einsum("...f,fd->...d", x_shard, w_shard)
    y = lax.psum(y, axis_name)
    if b is not None:
        y = y + b
    return y
