"""ctypes bindings for libhvd_native.so.

The Python mirror of the reference's ``HorovodBasics`` ctypes bootstrap
(ref: horovod/common/basics.py [V] — SURVEY.md §2.4): one place loads
the shared library, declares every C signature, and exposes typed
wrappers. Set ``HOROVOD_NATIVE=0`` to force the pure-Python fallbacks
everywhere (useful for differential testing; the test suite runs both).
"""

from __future__ import annotations

import ctypes
import os
import threading
from typing import Any, List, Optional, Sequence, Tuple

import numpy as np

_lock = threading.Lock()
# name ("lib"/"ext") -> loaded object, or None after a failed attempt
_cache: dict = {}


def _native_disabled() -> bool:
    return (os.environ.get("HOROVOD_NATIVE", "1") == "0"
            or os.environ.get("HOROVOD_TPU_NATIVE", "1") == "0")


def _load_once(name: str, load) -> Optional[Any]:
    """Env-gated, lock-guarded, attempt-once loader cache shared by the
    ctypes library and the CPython extension halves."""
    if _native_disabled():
        return None
    with _lock:
        if name in _cache:
            return _cache[name]
        try:
            _cache[name] = load()
        except (ImportError, OSError):
            _cache[name] = None
        return _cache[name]


def _declare(lib: ctypes.CDLL) -> None:
    c = ctypes
    lib.hvd_tl_create.restype = c.c_void_p
    lib.hvd_tl_destroy.argtypes = [c.c_void_p]
    lib.hvd_tl_emit.argtypes = [c.c_void_p, c.c_char_p]
    lib.hvd_tl_count.argtypes = [c.c_void_p]
    lib.hvd_tl_count.restype = c.c_long
    lib.hvd_tl_drain_size.argtypes = [c.c_void_p]
    lib.hvd_tl_drain_size.restype = c.c_long
    lib.hvd_tl_drain.argtypes = [c.c_void_p, c.c_char_p, c.c_long]
    lib.hvd_tl_drain.restype = c.c_long

    for suffix, ptr in (("f32", c.POINTER(c.c_float)),
                        ("f64", c.POINTER(c.c_double))):
        pair = getattr(lib, f"hvd_adasum_pair_{suffix}")
        pair.argtypes = [ptr, ptr, ptr, c.c_long]
        tree = getattr(lib, f"hvd_adasum_tree_{suffix}")
        tree.argtypes = [ptr, c.c_long, c.c_long, ptr]

    dp = c.POINTER(c.c_double)
    lib.hvd_gp_create.argtypes = [c.c_double, c.c_double]
    lib.hvd_gp_create.restype = c.c_void_p
    lib.hvd_gp_destroy.argtypes = [c.c_void_p]
    lib.hvd_gp_fit.argtypes = [c.c_void_p, dp, dp, c.c_long, c.c_long]
    lib.hvd_gp_fit.restype = c.c_int
    lib.hvd_gp_predict.argtypes = [c.c_void_p, dp, c.c_long, dp, dp]
    lib.hvd_gp_predict.restype = c.c_int

    vp = c.POINTER(c.c_void_p)
    lp = c.POINTER(c.c_long)
    lib.hvd_pack.argtypes = [vp, lp, c.c_long, c.c_void_p]
    lib.hvd_unpack.argtypes = [c.c_void_p, vp, lp, c.c_long]

    lib.hvd_npy_open.argtypes = [c.c_char_p]
    lib.hvd_npy_open.restype = c.c_void_p
    lib.hvd_npy_rows.argtypes = [c.c_void_p]
    lib.hvd_npy_rows.restype = c.c_long
    lib.hvd_npy_row_bytes.argtypes = [c.c_void_p]
    lib.hvd_npy_row_bytes.restype = c.c_long
    lib.hvd_npy_gather.argtypes = [c.c_void_p, lp, c.c_long, c.c_void_p]
    lib.hvd_npy_gather.restype = c.c_long
    lib.hvd_npy_gather_scattered.argtypes = [vp, lp, lp, c.c_long,
                                             c.c_void_p]
    lib.hvd_npy_gather_scattered.restype = c.c_long
    lib.hvd_npy_close.argtypes = [c.c_void_p]

    u8p = c.POINTER(c.c_uint8)
    lib.hvd_kv_start.argtypes = [c.c_int, u8p, c.c_long, c.POINTER(c.c_int)]
    lib.hvd_kv_start.restype = c.c_void_p
    lib.hvd_kv_port.argtypes = [c.c_void_p]
    lib.hvd_kv_port.restype = c.c_int
    lib.hvd_kv_stop.argtypes = [c.c_void_p]
    lib.hvd_kv_put.argtypes = [c.c_void_p, c.c_char_p, c.c_char_p, u8p,
                               c.c_long]
    lib.hvd_kv_get.argtypes = [c.c_void_p, c.c_char_p, c.c_char_p, u8p,
                               c.c_long]
    lib.hvd_kv_get.restype = c.c_long
    lib.hvd_kv_keys.argtypes = [c.c_void_p, c.c_char_p, u8p, c.c_long]
    lib.hvd_kv_keys.restype = c.c_long
    lib.hvd_kv_drop_scope.argtypes = [c.c_void_p, c.c_char_p]


def _load_lib() -> Optional[ctypes.CDLL]:
    from . import build

    path = build.lib_path()
    if path is None:
        return None
    lib = ctypes.CDLL(path)
    _declare(lib)
    return lib


def get_lib() -> Optional[ctypes.CDLL]:
    """The loaded library, building it on first call; None if disabled
    (HOROVOD_NATIVE=0; HOROVOD_TPU_NATIVE=0 is honored as an alias) or
    unbuildable."""
    return _load_once("lib", _load_lib)


def available() -> bool:
    return get_lib() is not None


def _load_ext() -> Optional[Any]:
    import importlib.util

    from . import build

    path = build.ext_path()
    if path is None:
        return None
    spec = importlib.util.spec_from_file_location(
        "horovod_tpu._native._hvd_cext", path
    )
    if spec is None or spec.loader is None:
        return None
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def get_ext() -> Optional[Any]:
    """The ``_hvd_cext`` CPython extension module (csrc/cext.cc) —
    the native binding half that reads framework tensors through the
    buffer protocol (zero-copy, GIL released during staging copies).
    None when native is disabled (same env gate as :func:`get_lib`) or
    unbuildable (e.g. no Python dev headers)."""
    return _load_once("ext", _load_ext)


def ext_available() -> bool:
    return get_ext() is not None


# ---------------------------------------------------------------- timeline

class TimelineBuffer:
    """Native event sink for common/timeline.py."""

    def __init__(self, lib: ctypes.CDLL):
        self._lib = lib
        self._h = lib.hvd_tl_create()

    def emit(self, json_str: str) -> None:
        self._lib.hvd_tl_emit(self._h, json_str.encode())

    def drain(self) -> List[str]:
        # An emit can land between the size query and the drain, making
        # hvd_tl_drain return -1 with the buffer intact — re-probe and
        # retry (mirrors NativeKVServer._read) so a final shutdown drain
        # never drops buffered events.
        for _ in range(8):
            size = self._lib.hvd_tl_drain_size(self._h)
            if size <= 0:
                return []
            buf = ctypes.create_string_buffer(size)
            n = self._lib.hvd_tl_drain(self._h, buf, size)
            if n >= 0:
                text = buf.raw[:n].decode()
                return [line for line in text.split("\n") if line]
        return []

    def __len__(self) -> int:
        return self._lib.hvd_tl_count(self._h)

    def __del__(self):
        lib, h = getattr(self, "_lib", None), getattr(self, "_h", None)
        if lib is not None and h:
            lib.hvd_tl_destroy(h)


def timeline_buffer() -> TimelineBuffer:
    lib = get_lib()
    if lib is None:
        raise RuntimeError("native library unavailable")
    return TimelineBuffer(lib)


# ------------------------------------------------------------------ adasum

def adasum_pair(a: np.ndarray, b: np.ndarray) -> Optional[np.ndarray]:
    """Native Adasum combine of two host vectors; None if unavailable."""
    lib = get_lib()
    if lib is None:
        return None
    dtype = np.result_type(a.dtype, b.dtype)
    if dtype == np.float64:
        fn, ct = lib.hvd_adasum_pair_f64, ctypes.c_double
        dtype = np.float64
    else:
        fn, ct = lib.hvd_adasum_pair_f32, ctypes.c_float
        dtype = np.float32
    af = np.ascontiguousarray(a, dtype=dtype).ravel()
    bf = np.ascontiguousarray(b, dtype=dtype).ravel()
    out = np.empty_like(af)
    p = ctypes.POINTER(ct)
    fn(af.ctypes.data_as(p), bf.ctypes.data_as(p), out.ctypes.data_as(p),
       af.size)
    return out.reshape(a.shape)


def adasum_tree(stack: np.ndarray) -> Optional[np.ndarray]:
    """Pairwise-tree Adasum over stack[k, n]; None if unavailable."""
    lib = get_lib()
    if lib is None:
        return None
    if stack.dtype == np.float64:
        fn, ct = lib.hvd_adasum_tree_f64, ctypes.c_double
        dtype = np.float64
    else:
        fn, ct = lib.hvd_adasum_tree_f32, ctypes.c_float
        dtype = np.float32
    k = stack.shape[0]
    flat = np.ascontiguousarray(stack, dtype=dtype).reshape(k, -1)
    out = np.empty(flat.shape[1], dtype=dtype)
    p = ctypes.POINTER(ct)
    fn(flat.ctypes.data_as(p), k, flat.shape[1], out.ctypes.data_as(p))
    return out.reshape(stack.shape[1:])


# ---------------------------------------------------------------------- GP

class NativeGaussianProcess:
    """Drop-in for common/autotune.py::GaussianProcess (same model)."""

    def __init__(self, noise: float = 0.8, length_scale: float = 0.2):
        lib = get_lib()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._lib = lib
        self._h = lib.hvd_gp_create(noise, length_scale)
        self.noise = noise
        self.length_scale = length_scale

    def fit(self, x: np.ndarray, y: np.ndarray) -> None:
        x = np.ascontiguousarray(np.atleast_2d(x), dtype=np.float64)
        y = np.ascontiguousarray(y, dtype=np.float64).ravel()
        dp = ctypes.POINTER(ctypes.c_double)
        rc = self._lib.hvd_gp_fit(
            self._h, x.ctypes.data_as(dp), y.ctypes.data_as(dp),
            x.shape[0], x.shape[1],
        )
        if rc != 0:
            raise np.linalg.LinAlgError("kernel matrix not positive definite")

    def predict(self, x: np.ndarray):
        x = np.ascontiguousarray(np.atleast_2d(x), dtype=np.float64)
        m = x.shape[0]
        mu = np.empty(m, dtype=np.float64)
        sigma = np.empty(m, dtype=np.float64)
        dp = ctypes.POINTER(ctypes.c_double)
        rc = self._lib.hvd_gp_predict(
            self._h, x.ctypes.data_as(dp), m,
            mu.ctypes.data_as(dp), sigma.ctypes.data_as(dp),
        )
        if rc != 0:
            raise RuntimeError("predict before fit")
        return mu, sigma

    def __del__(self):
        lib, h = getattr(self, "_lib", None), getattr(self, "_h", None)
        if lib is not None and h:
            lib.hvd_gp_destroy(h)


# -------------------------------------------------------------------- pack

def pack(arrays: List[np.ndarray]) -> Optional[np.ndarray]:
    """Concatenate the raw bytes of host arrays into one uint8 buffer
    with a single C call; None if unavailable. Prefers the CPython
    extension (buffer protocol, GIL released); falls back to the ctypes
    pointer-array path."""
    ext = get_ext()
    lib = None if ext is not None else get_lib()
    if ext is None and lib is None:
        return None
    arrays = [np.ascontiguousarray(a) for a in arrays]
    total = sum(a.nbytes for a in arrays)
    if ext is not None:
        out = np.empty(total, dtype=np.uint8)
        ext.pack_into(out, [a.view(np.uint8).reshape(-1) for a in arrays])
        return out
    k = len(arrays)
    out = np.empty(total, dtype=np.uint8)
    srcs = (ctypes.c_void_p * k)(*[a.ctypes.data for a in arrays])
    sizes = (ctypes.c_long * k)(*[a.nbytes for a in arrays])
    lib.hvd_pack(srcs, sizes, k, out.ctypes.data_as(ctypes.c_void_p))
    return out


def unpack(buf: np.ndarray, like: List[np.ndarray]) -> Optional[List[np.ndarray]]:
    """Split a packed uint8 buffer back into arrays shaped/typed like
    ``like``; None if unavailable."""
    ext = get_ext()
    if ext is None and get_lib() is None:
        return None
    buf = np.ascontiguousarray(buf)
    outs = [np.empty_like(np.ascontiguousarray(a)) for a in like]
    if ext is not None:
        ext.unpack_into(
            buf.view(np.uint8).reshape(-1),
            [o.view(np.uint8).reshape(-1) for o in outs],
        )
        return outs
    lib = get_lib()
    if lib is None:
        return None
    k = len(outs)
    dsts = (ctypes.c_void_p * k)(*[o.ctypes.data for o in outs])
    sizes = (ctypes.c_long * k)(*[o.nbytes for o in outs])
    lib.hvd_unpack(
        buf.ctypes.data_as(ctypes.c_void_p),
        dsts, sizes, k,
    )
    return outs


class PackedSnapshot:
    """One contiguous host block holding the raw bytes of a sequence of
    arrays — the native in-memory checkpoint behind the elastic State
    commit (ref: horovod/torch/adapter_v2.cc's zero-copy tensor access
    feeding the C core's staging buffers [V] — SURVEY.md §2.3). Commit
    cost is one allocation plus a GIL-released memcpy sweep instead of
    one Python-level clone per tensor; ``view(i)`` returns a zero-copy
    numpy window into the block (callers that hand views to consumers
    that copy anyway — e.g. ``Module.load_state_dict`` — never copy the
    snapshot at all)."""

    def __init__(self, buf: np.ndarray,
                 metas: List[Tuple[Tuple[int, ...], np.dtype, int]]):
        self.buf = buf
        self.metas = metas  # (shape, dtype, byte offset) per array

    def __len__(self) -> int:
        return len(self.metas)

    @property
    def nbytes(self) -> int:
        return self.buf.nbytes

    def view(self, i: int) -> np.ndarray:
        shape, dtype, off = self.metas[i]
        n = int(np.prod(shape, dtype=np.int64)) * dtype.itemsize
        return self.buf[off:off + n].view(dtype).reshape(shape)

    def arrays(self) -> List[np.ndarray]:
        """Fresh copies of every array (restore-to-owned-memory)."""
        return [self.view(i).copy() for i in range(len(self.metas))]


def snapshot_arrays(
    arrays: Sequence[np.ndarray],
) -> Optional[PackedSnapshot]:
    """Pack host arrays into a :class:`PackedSnapshot`; None when the
    native layer is unavailable (callers keep their pure-Python clone
    path)."""
    ext = get_ext()
    if ext is None and get_lib() is None:
        return None
    # Record shapes BEFORE ascontiguousarray: it promotes 0-d arrays to
    # (1,), and the snapshot must restore the original shape exactly
    # (e.g. Adam's 0-d 'step' tensors).
    shapes = [np.asarray(a).shape for a in arrays]
    arrays = [np.ascontiguousarray(a) for a in arrays]
    metas: List[Tuple[Tuple[int, ...], np.dtype, int]] = []
    off = 0
    for shape, a in zip(shapes, arrays):
        metas.append((shape, a.dtype, off))
        off += a.nbytes
    buf = pack(arrays)
    if buf is None:
        return None
    return PackedSnapshot(buf, metas)


# ----------------------------------------------------------------- kvstore

class NativeKVServer:
    """Native rendezvous server + direct store access (the ``.store``
    surface the elastic driver uses on the Python server)."""

    def __init__(self, port: int = 0, secret_key: Optional[bytes] = None):
        lib = get_lib()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._lib = lib
        secret = secret_key or b""
        sec = (ctypes.c_uint8 * max(len(secret), 1))(*secret)
        out_port = ctypes.c_int(0)
        self._h = lib.hvd_kv_start(
            port, sec, len(secret), ctypes.byref(out_port)
        )
        if not self._h:
            raise OSError(f"native KV server failed to bind port {port}")
        self.port = out_port.value

    # -- KVStore-compatible surface --

    def put(self, scope: str, key: str, value: bytes) -> None:
        if value:
            buf = (ctypes.c_uint8 * len(value)).from_buffer_copy(value)
        else:
            buf = (ctypes.c_uint8 * 1)()
        self._lib.hvd_kv_put(
            self._h, scope.encode(), key.encode(), buf, len(value)
        )

    def _read(self, fn, *args) -> Optional[bytes]:
        """Size-probe-then-copy, retried: the two C calls lock
        separately, so a concurrent writer can change the length between
        them. The copy call reports the length it saw under its own
        lock — accept only a copy whose reported length fits the buffer
        we handed it (shorter is fine: the C side copied exactly that
        many bytes atomically)."""
        cap = fn(self._h, *args, None, 0)
        while True:
            if cap < 0:
                return None
            if cap == 0:
                return b""
            buf = (ctypes.c_uint8 * cap)()
            n = fn(self._h, *args, buf, cap)
            if n < 0:
                return None
            if n <= cap:
                return bytes(buf)[:n]
            cap = n  # grew underneath us — retry with the larger size

    def get(self, scope: str, key: str) -> Optional[bytes]:
        return self._read(self._lib.hvd_kv_get, scope.encode(), key.encode())

    def keys(self, scope: str) -> List[str]:
        joined = self._read(self._lib.hvd_kv_keys, scope.encode())
        if not joined:
            return []
        return joined.decode().split("\n")

    def drop_scope(self, scope: str) -> None:
        self._lib.hvd_kv_drop_scope(self._h, scope.encode())

    def stop(self) -> None:
        if self._h:
            self._lib.hvd_kv_stop(self._h)
            self._h = None

    def __del__(self):
        try:
            self.stop()
        except Exception:
            pass


# -------------------------------------------------------------------- npy IO

class NpyReader:
    """mmap'd row-gather view of a C-order .npy file (csrc/npyio.cc) —
    the native data-loader half behind ``data.ShardedFileDataset``'s
    uncompressed fast path. ``None`` from :func:`npy_reader` means no
    native library (or an unsupported file); callers fall back to
    ``np.load(mmap_mode='r')`` fancy indexing."""

    _native_gather = True  # data.ShardedFileDataset dispatch marker

    def __init__(self, lib, handle, path: str):
        # Validate BEFORE taking ownership of the handle: if anything
        # here raises (numpy rejecting a descr the C parser skipped,
        # stride disagreement), self._h is never set, __del__ is a
        # no-op, and npy_reader closes the handle exactly once.
        mm = np.load(path, mmap_mode="r")
        shape, dtype = mm.shape, mm.dtype
        del mm
        row_bytes = int(np.prod(shape[1:], dtype=np.int64)) * dtype.itemsize
        if (
            lib.hvd_npy_rows(handle) != shape[0]
            or lib.hvd_npy_row_bytes(handle) != row_bytes
        ):
            raise ValueError(f"native/numpy header disagreement: {path}")
        self.shape = shape
        self.dtype = dtype
        self._lib = lib
        self._h = handle

    def take(self, idx: np.ndarray) -> np.ndarray:
        """Rows ``idx`` as one contiguous array (single C gather)."""
        idx = np.ascontiguousarray(idx, dtype=np.int64)
        out = np.empty((len(idx),) + self.shape[1:], self.dtype)
        copied = self._lib.hvd_npy_gather(
            self._h,
            idx.ctypes.data_as(ctypes.POINTER(ctypes.c_long)),
            len(idx),
            out.ctypes.data_as(ctypes.c_void_p),
        )
        if copied != len(idx):
            raise IndexError(
                f"row index {int(idx[copied])} out of range "
                f"[0, {self.shape[0]})"
            )
        return out

    def close(self) -> None:
        if self._h:
            self._lib.hvd_npy_close(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def npy_reader(path: str) -> Optional[NpyReader]:
    """Open ``path`` with the native reader; None when the library is
    unavailable or the file is unsupported (compressed, Fortran-order,
    0-d)."""
    lib = get_lib()
    if lib is None:
        return None
    handle = lib.hvd_npy_open(os.fsencode(path))
    if not handle:
        return None
    try:
        return NpyReader(lib, handle, path)
    except Exception:
        lib.hvd_npy_close(handle)  # __init__ raised before taking ownership
        return None


def npy_gather_scattered(readers, hsel: np.ndarray, local: np.ndarray,
                         out: np.ndarray) -> bool:
    """One C call gathering out[i] = readers[hsel[i]].row(local[i])
    across many mapped shards (csrc/npyio.cc). All readers must share
    the row stride (caller-validated). False when unavailable."""
    lib = get_lib()
    if lib is None or not readers:
        return False
    handles = (ctypes.c_void_p * len(readers))(*[r._h for r in readers])
    hsel = np.ascontiguousarray(hsel, dtype=np.int64)
    local = np.ascontiguousarray(local, dtype=np.int64)
    lp = ctypes.POINTER(ctypes.c_long)
    copied = lib.hvd_npy_gather_scattered(
        handles,
        hsel.ctypes.data_as(lp),
        local.ctypes.data_as(lp),
        len(local),
        out.ctypes.data_as(ctypes.c_void_p),
    )
    if copied != len(local):
        raise IndexError(
            f"scattered gather stopped at position {int(copied)} "
            "(row index out of range or stride mismatch)"
        )
    return True
