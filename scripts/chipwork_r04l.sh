#!/usr/bin/env bash
# Round-4 chip work, part l: GQA LM A/B (BENCH_KV_HEADS).
# the kernels' native lengths= path under real load) after parts g/h/i
# drain. Same discipline.
set -uo pipefail
cd "$(dirname "$0")/.."
mkdir -p bench_results
R=r04

while pgrep -f "chipwork_r04[ghijk].sh" >/dev/null 2>&1 \
      || pgrep -f "python bench(_lm|_allreduce)?.py" >/dev/null 2>&1; do
  sleep 120
done

probe_backend() {
  timeout 7200 python - <<'PYEOF' >/dev/null 2>&1
import jax
assert jax.devices()[0].platform == "tpu"
PYEOF
}
wait_backend() {
  echo "=== probing TPU backend $(date -u +%H:%M)" >&2
  until probe_backend; do
    echo "backend still down $(date -u +%H:%M); retry in 300s" >&2
    sleep 300
  done
  echo "=== backend UP $(date -u +%H:%M)" >&2
}
run_one() {
  local name="$1"; shift
  local out="bench_results/${name}_${R}.json"
  echo "=== $name $(date -u +%H:%M)" >&2
  "$@" > "$out.tmp" 2> "bench_results/${name}_${R}.err"
  if grep -qE '^\{' "$out.tmp"; then
    grep -E '^\{' "$out.tmp" > "$out"
    rm -f "$out.tmp" "bench_results/${name}_${R}.err"
    cat "$out" >&2
    return 0
  fi
  rm -f "$out.tmp"
  return 1
}
cap() {
  local name="$1"
  local out="bench_results/${name}_${R}.json"
  if [ -s "$out" ]; then
    echo "=== $name already captured, skipping" >&2
    return 0
  fi
  if run_one "$@"; then return 0; fi
  echo "=== $name failed; gating on backend health before one retry" >&2
  wait_backend
  if run_one "$@"; then return 0; fi
  echo "FAILED $name twice with backend up (see .err)" >&2
  return 1
}

cap gpt2_gqa4 env BENCH_MODEL=gpt2_medium BENCH_KV_HEADS=4 python bench_lm.py
cap gpt2_gqa8 env BENCH_MODEL=gpt2_medium BENCH_KV_HEADS=8 python bench_lm.py

echo "=== chipwork_r04l complete $(date -u +%H:%M)" >&2
