"""Estimator-layer tests (ref: horovod/spark/ Estimator + Store [V],
SURVEY.md §2.5): declare-fit-predict contract, store layout,
checkpointing, batch-iterable input."""

import os

import numpy as np
import optax
import pytest

import flax.linen as nn

from horovod_tpu.spark import LocalStore, Store, TpuEstimator, TpuModel


class _MLP(nn.Module):
    @nn.compact
    def __call__(self, x):
        x = nn.Dense(16)(x)
        x = nn.relu(x)
        return nn.Dense(1)(x)


def _mse(preds, y):
    import jax.numpy as jnp

    return jnp.mean((preds - y) ** 2)


def _data(n=256, d=4, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, d)).astype(np.float32)
    w = rng.normal(size=(d, 1)).astype(np.float32)
    y = x @ w + 0.01 * rng.normal(size=(n, 1)).astype(np.float32)
    return x, y


def test_store_layout(tmp_path):
    store = Store.create(str(tmp_path / "store"))
    assert isinstance(store, LocalStore)
    assert store.checkpoint_dir("job1").endswith(
        os.path.join("job1", "checkpoints")
    )
    assert store.logs_dir("job1").endswith(os.path.join("job1", "logs"))


def test_fit_learns_and_returns_model(hvd, tmp_path):
    x, y = _data()
    est = TpuEstimator(
        model=_MLP(),
        loss=_mse,
        optimizer=optax.adam(1e-2),
        store=LocalStore(str(tmp_path / "store")),
        run_id="fit1",
        epochs=12,
        batch_size=64,
    )
    model = est.fit(x, y)
    assert isinstance(model, TpuModel)
    # loss must drop hard on this noiseless-ish linear target
    assert est.history[-1]["loss"] < est.history[0]["loss"] * 0.1
    preds = model.predict(x[:8])
    assert preds.shape == (8, 1)
    # checkpoints landed in the store
    ckpt_dir = est.store.checkpoint_dir("fit1")
    assert os.path.isdir(ckpt_dir) and os.listdir(ckpt_dir)


def test_fit_with_batch_iterable(hvd):
    x, y = _data(n=128)
    batches = [
        (x[i : i + 32], y[i : i + 32]) for i in range(0, 128, 32)
    ]
    est = TpuEstimator(
        model=_MLP(), loss=_mse, epochs=2, batch_size=32
    )
    model = est.fit(batches * 1)
    assert len(est.history) == 2


def test_model_save_load_roundtrip(hvd, tmp_path):
    x, y = _data(n=64)
    est = TpuEstimator(model=_MLP(), loss=_mse, epochs=1, batch_size=32)
    model = est.fit(x, y)
    path = str(tmp_path / "served")
    model.save(path)
    loaded = TpuModel.load(_MLP(), path)
    np.testing.assert_allclose(
        loaded.predict(x[:4]), model.predict(x[:4]), rtol=1e-6
    )


def test_uneven_batch_replicates_with_warning(hvd):
    import io

    from horovod_tpu.common import logging as hvd_logging

    x, y = _data(n=30)
    est = TpuEstimator(model=_MLP(), loss=_mse, epochs=1, batch_size=10)
    buf = io.StringIO()
    hvd_logging.configure(level="warning", timestamp=False, stream=buf,
                          force=True)
    est.fit(x, y)
    assert "not divisible" in buf.getvalue()


def test_fit_with_one_shot_generator(hvd):
    """A generator (one-shot iterable) must train on ALL batches,
    including the one peeked for shapes, across every epoch."""
    x, y = _data(n=96)

    def gen():
        for i in range(0, 96, 32):
            yield x[i : i + 32], y[i : i + 32]

    est = TpuEstimator(model=_MLP(), loss=_mse, epochs=3, batch_size=32)
    est.fit(gen())
    assert len(est.history) == 3
    # every epoch saw all 3 batches — no nan, no empty epochs
    assert all(np.isfinite(h["loss"]) for h in est.history)


class _BNNet(nn.Module):
    """BatchNorm + Dropout model — the stateful-collections case the
    round-3 review flagged (batch_stats must thread through fit)."""

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = nn.Dense(16)(x)
        x = nn.BatchNorm(use_running_average=not train, momentum=0.9)(x)
        x = nn.relu(x)
        x = nn.Dropout(0.1, deterministic=not train)(x)
        return nn.Dense(1)(x)


def test_fit_stateful_model_with_batchnorm_and_dropout(hvd, tmp_path):
    x, y = _data(n=128)
    est = TpuEstimator(
        model=_BNNet(), loss=_mse, epochs=6, batch_size=32,
        store=LocalStore(str(tmp_path / "s")), run_id="bn",
    )
    model = est.fit(x, y)
    assert all(np.isfinite(h["loss"]) for h in est.history)
    assert est.history[-1]["loss"] < est.history[0]["loss"]
    # batch_stats came back and predict uses running averages
    assert model.batch_stats is not None
    preds = model.predict(x[:4])
    assert preds.shape == (4, 1)
    # save/load round-trips the collections too
    p = str(tmp_path / "served")
    model.save(p)
    loaded = TpuModel.load(_BNNet(), p)
    np.testing.assert_allclose(
        loaded.predict(x[:4]), preds, rtol=1e-6
    )


def test_fit_from_on_disk_shards(hvd, tmp_path):
    """The Petastorm slot end-to-end (VERDICT r4 #9): materialize shards
    with write_shards, stream them through ShardedFileDataset into
    fit(), training must converge and epochs must reshuffle."""
    from horovod_tpu.data import ShardedFileDataset, write_shards

    x, y = _data(n=512)
    data_dir = str(tmp_path / "shards")
    write_shards(data_dir, x, y, rows_per_shard=100)
    ds = ShardedFileDataset(
        data_dir, batch_size=32, num_replicas=1, rank=0, seed=1
    )
    est = TpuEstimator(
        model=_MLP(), loss=_mse, optimizer=optax.adam(1e-2),
        epochs=3, batch_size=32,
    )
    model = est.fit(ds)
    assert len(est.history) == 3
    assert est.history[-1]["loss"] < est.history[0]["loss"]
    preds = np.asarray(model.predict(x[:64]))
    assert float(np.mean((preds - y[:64]) ** 2)) < 0.5


def _need_fake_ray():
    """The conformance shim refuses to shadow a real ray install; where
    real ray exists the @pytest.mark.ray real-backend test covers the
    path instead. The executor's _worker is a closure, so the shim's
    subprocess payloads need cloudpickle."""
    from horovod_tpu.executor import _ray_or_none

    if _ray_or_none() is not None:
        pytest.skip("real ray installed; covered by the ray-marked test")
    pytest.importorskip("cloudpickle")


def test_ray_executor_fake_ray_conformance():
    """The REAL ray code path (`use_ray=True`: placement group, per-rank
    remote tasks, rank->IP registry actor, env contract) executed
    against the conformance shim (horovod_tpu.testing.fake_ray) —
    remote tasks are genuine subprocesses, the registry actor a genuine
    cross-process RPC, so this is the ray path running, not a mock of
    it (VERDICT r4 item 6)."""
    _need_fake_ray()
    from horovod_tpu.executor import RayExecutor
    from horovod_tpu.testing import fake_ray

    def probe():
        import os

        return {
            "rank": int(os.environ["HOROVOD_RANK"]),
            "size": int(os.environ["HOROVOD_SIZE"]),
            "local_rank": int(os.environ["HOROVOD_LOCAL_RANK"]),
            "local_size": int(os.environ["HOROVOD_LOCAL_SIZE"]),
            "cross_size": int(os.environ["HOROVOD_CROSS_SIZE"]),
            "pid": os.getpid(),
        }

    with fake_ray.installed():
        with RayExecutor(num_workers=2, use_ray=True) as ex:
            assert ex.use_ray is True
            assert ex._pg is not None  # placement group reserved
            results = ex.run(probe)
    assert sorted(r["rank"] for r in results) == [0, 1]
    assert all(r["size"] == 2 for r in results)
    # both tasks report 127.0.0.1 -> one host, local ranks 0 and 1
    assert all(r["cross_size"] == 1 for r in results)
    assert all(r["local_size"] == 2 for r in results)
    assert sorted(r["local_rank"] for r in results) == [0, 1]
    # separate worker processes (and separate from the driver)
    import os as _os

    pids = {r["pid"] for r in results}
    assert len(pids) == 2 and _os.getpid() not in pids


def test_ray_executor_fake_ray_surfaces_worker_exception():
    _need_fake_ray()
    from horovod_tpu.executor import RayExecutor
    from horovod_tpu.testing import fake_ray

    def boom():
        raise ValueError("worker 2 exploded")

    with fake_ray.installed():
        with RayExecutor(num_workers=2, use_ray=True) as ex:
            with pytest.raises(ValueError, match="exploded"):
                ex.run(boom)


def test_ray_host_discovery_fake_ray_conformance():
    """RayHostDiscovery over the shim's live `ray.nodes()` — the real
    import path (`_ray_or_none`), not a monkeypatched module object."""
    _need_fake_ray()
    from horovod_tpu.executor import RayHostDiscovery
    from horovod_tpu.testing import fake_ray

    with fake_ray.installed() as ray:
        ray.init()
        hosts = RayHostDiscovery(
            slots_per_host=4
        ).find_available_hosts_and_slots()
        assert [(h.hostname, h.slots) for h in hosts] == [
            ("127.0.0.1", 4)
        ]
        ray.shutdown()
    # uninstalled: no ray -> empty discovery again
    assert RayHostDiscovery().find_available_hosts_and_slots() == []


@pytest.mark.ray
def test_ray_executor_real_backend():
    """Exercised only where ray is installed (the sandbox has no ray):
    placement group + per-rank remote tasks + env contract."""
    ray = pytest.importorskip("ray")
    from horovod_tpu.executor import RayExecutor

    def probe():
        import os

        return (
            int(os.environ["HOROVOD_RANK"]),
            int(os.environ["HOROVOD_SIZE"]),
        )

    with RayExecutor(num_workers=2, use_ray=True) as ex:
        results = ex.run(probe)
    assert sorted(results) == [(0, 2), (1, 2)]
    ray.shutdown()
