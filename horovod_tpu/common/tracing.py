"""Fleet trace plane: cross-host request/step spans (stdlib only).

The flight recorder (common/telemetry.py) answers "what did THIS worker
do on step N"; since the serving plane went disaggregated a single
request traverses client → Router → prefill worker → int8 KV transfer →
decode worker, and may be replayed, hedged, or live-migrated mid-decode
— no per-worker instrument can say where ITS time went. This module is
the correlation layer: W3C-traceparent-style contexts minted at
``POST /generate`` (or adopted from an incoming header), child spans
recorded into a bounded per-worker ring, and NTP-style clock stamps on
every hop so ``analysis/trace_merge.py`` can assemble one
skew-corrected chrome://tracing view of the whole fleet.

Design constraints, in order:

1. **Zero cost when off.** ``HOROVOD_TRACE`` defaults off and sampling
   is decided ONCE at mint — every downstream carrier holds an
   ``Optional[TraceContext]`` and skips span creation entirely on
   ``None``. A span costs two ``time.monotonic()`` stamps and a dict;
   nothing here runs on the decode hot path per token, so the
   zero-retrace invariant (decode_compiles==1) is untouched.
2. **Stdlib only.** Contexts ride HTTP headers (``traceparent``) and a
   ``trace`` field in the kv_transfer JSON meta frames; no OTLP, no
   exporter threads.
3. **Crash-safe.** The span ring drains beside the StepStats ring: the
   telemetry hub's atexit/SIGTERM dump also writes
   ``<flight_recorder>.spans`` as JSON-lines, so a SIGTERM'd worker
   leaves its spans on disk for ``scripts/trace_assemble.py``.

Knobs (typed in common/config.py, read via ``basics.live_config()``):
``HOROVOD_TRACE`` (master switch), ``HOROVOD_TRACE_SAMPLE`` (fraction
of minted roots that are sampled; descendants inherit the decision),
``HOROVOD_TRACE_SPANS`` (ring bound).
"""

from __future__ import annotations

import json
import os
import secrets
import socket
import threading
import time
from collections import deque
from typing import Dict, List, Optional

DEFAULT_SPAN_RING = 2048

TRACEPARENT_HEADER = "traceparent"
TRACE_ID_HEADER = "X-Trace-Id"
# hop skew stamps: servers echo their recv/send wall clocks + identity
# so clients can tag the NTP edge onto their hop span
TS_RECV_HEADER = "X-Trace-Ts-Recv"
TS_SEND_HEADER = "X-Trace-Ts-Send"
PEER_HEADER = "X-Trace-Peer"


class TraceContext:
    """trace_id / span_id pair in W3C trace-context shape.

    ``span_id`` is the id of the span this context BELONGS to — a child
    span minted under it uses it as ``parent_id``. ``sampled`` is the
    root's coin flip, inherited by every descendant so a trace is
    all-or-nothing across the fleet.
    """

    __slots__ = ("trace_id", "span_id", "sampled")

    def __init__(self, trace_id: str, span_id: str, sampled: bool = True):
        self.trace_id = trace_id
        self.span_id = span_id
        self.sampled = bool(sampled)

    def to_traceparent(self) -> str:
        flag = "01" if self.sampled else "00"
        return f"00-{self.trace_id}-{self.span_id}-{flag}"

    def to_dict(self) -> Dict[str, object]:
        """Wire form for JSON payloads (kv_transfer meta frames,
        migrate records)."""
        return {
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "sampled": self.sampled,
        }

    @classmethod
    def from_dict(cls, d) -> Optional["TraceContext"]:
        if not isinstance(d, dict):
            return None
        tid = d.get("trace_id")
        sid = d.get("span_id")
        if not tid or not sid:
            return None
        return cls(str(tid), str(sid), bool(d.get("sampled", True)))

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"TraceContext({self.to_traceparent()})"


def parse_traceparent(header: Optional[str]) -> Optional[TraceContext]:
    """``00-{32 hex}-{16 hex}-{flags}`` → context; None on anything
    malformed (a bad header must never fail a request)."""
    if not header:
        return None
    parts = header.strip().split("-")
    if len(parts) != 4:
        return None
    version, trace_id, span_id, flags = parts
    if len(version) != 2 or len(trace_id) != 32 or len(span_id) != 16:
        return None
    try:
        int(trace_id, 16)
        int(span_id, 16)
        sampled = bool(int(flags, 16) & 1)
    except ValueError:
        return None
    if int(trace_id, 16) == 0 or int(span_id, 16) == 0:
        return None
    return TraceContext(trace_id.lower(), span_id.lower(), sampled)


def _new_trace_id() -> str:
    return secrets.token_hex(16)


def _new_span_id() -> str:
    return secrets.token_hex(8)


# ------------------------------------------------------------------ spans

_tls = threading.local()


class Span:
    """One timed operation on one worker.

    Two monotonic stamps and a dict: ``begin`` records epoch + monotonic
    start, ``end`` closes the duration and appends the record to the
    process ring. Usable as a context manager (pushes itself onto the
    thread-local active stack so RetryPolicy can annotate the hop it is
    retrying under), or held across threads and ended manually.
    """

    __slots__ = (
        "name", "ctx", "parent_id", "tags", "ts", "_t0", "_done",
    )

    def __init__(
        self,
        name: str,
        ctx: TraceContext,
        parent_id: Optional[str],
        tags: Optional[dict] = None,
    ) -> None:
        self.name = name
        self.ctx = ctx  # ctx.span_id is THIS span's id
        self.parent_id = parent_id
        self.tags = dict(tags) if tags else {}
        self.ts = time.time()
        self._t0 = time.monotonic()
        self._done = False

    def annotate(self, note: str) -> None:
        """Append a breadcrumb (the retry ladder's site#attempt@backoff
        entries) without touching timing."""
        notes = self.tags.setdefault("notes", [])
        if len(notes) < 64:  # bounded — a hot retry loop can't balloon a span
            notes.append(note)

    def tag(self, **kv) -> None:
        self.tags.update(kv)

    def end(self, **kv) -> None:
        if self._done:
            return
        self._done = True
        if kv:
            self.tags.update(kv)
        dur_ms = (time.monotonic() - self._t0) * 1e3
        recorder().record(
            {
                "trace_id": self.ctx.trace_id,
                "span_id": self.ctx.span_id,
                "parent_id": self.parent_id,
                "name": self.name,
                "ts": self.ts,
                "dur_ms": round(dur_ms, 3),
                "tags": self.tags,
            }
        )

    # -- thread-local active-span stack (for retry annotations) --

    def __enter__(self) -> "Span":
        push_active(self)
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        pop_active(self)
        if exc_type is not None and "outcome" not in self.tags:
            self.tags["outcome"] = "error"
            self.tags["error"] = f"{exc_type.__name__}: {exc}"
        self.end()


def push_active(span: Span) -> None:
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    stack.append(span)


def pop_active(span: Span) -> None:
    stack = getattr(_tls, "stack", None)
    if stack and stack[-1] is span:
        stack.pop()
    elif stack and span in stack:  # out-of-order end: drop it anyway
        stack.remove(span)


def current() -> Optional[Span]:
    """The innermost active span on THIS thread (None when tracing is
    off or no span is open) — the retry ladder's annotation target."""
    stack = getattr(_tls, "stack", None)
    return stack[-1] if stack else None


def annotate(note: str) -> None:
    """Annotate the active span, if any — safe to call unconditionally
    (the no-trace path is one thread-local read)."""
    span = current()
    if span is not None:
        span.annotate(note)


class active(object):
    """Context manager adopting an EXISTING span as this thread's
    active span (the kv_transfer handoff thread runs under the
    request's span without owning its lifetime)."""

    __slots__ = ("_span",)

    def __init__(self, span: Optional[Span]) -> None:
        self._span = span

    def __enter__(self):
        if self._span is not None:
            push_active(self._span)
        return self._span

    def __exit__(self, exc_type, exc, tb) -> None:
        if self._span is not None:
            pop_active(self._span)


# -------------------------------------------------------------- recorder


class SpanRecorder:
    """Bounded per-process span ring beside the StepStats ring.

    ``deque(maxlen=N)`` appends are atomic under the GIL, so concurrent
    emitters never grow past the bound; the lock only guards reads and
    reconfiguration. Drained by the telemetry hub's atexit/SIGTERM dump
    into ``<flight_recorder>.spans``.
    """

    def __init__(self, capacity: int = DEFAULT_SPAN_RING) -> None:
        self.capacity = max(int(capacity), 1)
        self._ring: "deque" = deque(maxlen=self.capacity)
        self._lock = threading.Lock()
        self.host = socket.gethostname()
        self.pid = os.getpid()
        self.role = ""

    def configure(
        self, capacity: Optional[int] = None, role: Optional[str] = None
    ) -> None:
        with self._lock:
            if capacity is not None and int(capacity) != self.capacity:
                self.capacity = max(int(capacity), 1)
                self._ring = deque(self._ring, maxlen=self.capacity)
            if role is not None:
                self.role = role

    def record(self, span_rec: dict) -> None:
        span_rec.setdefault("host", self.host)
        span_rec.setdefault("pid", self.pid)
        if self.role:
            span_rec.setdefault("role", self.role)
        self._ring.append(span_rec)  # atomic; no lock on the emit path

    def spans(self) -> List[dict]:
        with self._lock:
            for _ in range(3):
                try:
                    return [dict(r) for r in list(self._ring)]
                except RuntimeError:  # mutated during iteration
                    continue
            return []

    def __len__(self) -> int:
        return len(self._ring)

    def dump(self, path: str) -> Optional[str]:
        """JSON-lines, oldest first, tmp+rename (same crash discipline
        as the flight recorder)."""
        spans = self.spans()
        if not spans:
            return None
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            for rec in spans:
                f.write(json.dumps(rec) + "\n")
        os.replace(tmp, path)
        return path


# ------------------------------------------------------------- singleton

_recorder: Optional[SpanRecorder] = None
_rec_lock = threading.Lock()
# settings cache: (enabled, sample) — resolved once, reset by tests
_settings: Optional[tuple] = None


def _load_settings() -> tuple:
    global _settings
    cached = _settings
    if cached is not None:
        return cached
    from . import basics

    cfg = basics.live_config()
    _settings = (bool(cfg.trace), float(cfg.trace_sample))
    return _settings


def recorder() -> SpanRecorder:
    global _recorder
    with _rec_lock:
        if _recorder is None:
            from . import basics

            cfg = basics.live_config()
            _recorder = SpanRecorder(capacity=cfg.trace_spans)
        return _recorder


def set_role(role: str) -> None:
    """Stamp this process's serving role (prefill/decode/unified/…)
    onto every span it records — the assembler's row key."""
    recorder().configure(role=role)


def _reset() -> None:
    """Test hook: drop the recorder + settings cache so the next call
    re-reads config."""
    global _recorder, _settings
    with _rec_lock:
        _recorder = None
        _settings = None


def enabled() -> bool:
    return _load_settings()[0]


def mint(sampled: Optional[bool] = None) -> Optional[TraceContext]:
    """Mint a ROOT context, deciding sampling once for the whole trace.
    None when tracing is off or the coin came up tails — callers treat
    None as 'no tracing for this request' everywhere downstream."""
    on, sample = _load_settings()
    if not on:
        return None
    if sampled is None:
        if sample >= 1.0:
            sampled = True
        elif sample <= 0.0:
            sampled = False
        else:
            # secrets over random: no seed-correlation with user code
            sampled = secrets.randbelow(1_000_000) < sample * 1_000_000
    if not sampled:
        return None
    return TraceContext(_new_trace_id(), _new_span_id(), True)


def adopt(header: Optional[str]) -> Optional[TraceContext]:
    """Adopt an incoming traceparent header (or mint, when absent and
    tracing is on). The caller's sampling decision wins: an explicit
    sampled=0 header stays untraced."""
    if not enabled():
        return None
    ctx = parse_traceparent(header)
    if ctx is not None:
        return ctx if ctx.sampled else None
    return mint()


def start_span(
    name: str,
    parent: Optional[TraceContext],
    **tags,
) -> Optional[Span]:
    """Child span under ``parent``; None propagates (untraced request
    ⇒ no span, no cost). The returned span's ``.ctx`` is the context to
    hand the NEXT hop."""
    if parent is None or not parent.sampled:
        return None
    child = TraceContext(parent.trace_id, _new_span_id(), True)
    return Span(name, child, parent.span_id, tags)


def root_span(name: str, ctx: Optional[TraceContext], **tags):
    """The span a freshly-minted context BELONGS to (parent None) —
    the route/request root every leg hangs off. None propagates."""
    if ctx is None or not ctx.sampled:
        return None
    return Span(name, ctx, None, tags)


def server_stamps(peer_recv_ts: float) -> Dict[str, str]:
    """Headers a server echoes so the client can skew-correct this hop:
    its recv/send wall stamps and its process identity."""
    rec = recorder()
    return {
        TS_RECV_HEADER: f"{peer_recv_ts:.6f}",
        TS_SEND_HEADER: f"{time.time():.6f}",
        PEER_HEADER: f"{rec.host}:{rec.pid}",
    }


def json_stamps(peer_recv_ts: float) -> Dict[str, object]:
    """The :func:`server_stamps` echo for JSON-body protocols (the
    kv_transfer replies carry stamps as fields, not headers)."""
    rec = recorder()
    return {
        "recv_ts": round(peer_recv_ts, 6),
        "send_ts": round(time.time(), 6),
        "peer": f"{rec.host}:{rec.pid}",
    }


def tag_hop_fields(
    span: Optional[Span], t_send: float, t_recv: float, obj
) -> None:
    """:func:`tag_hop` for JSON-body echoes — the peer stamps arrive as
    ``recv_ts``/``send_ts``/``peer`` fields in the reply object."""
    if span is None or not isinstance(obj, dict):
        return
    peer_recv = obj.get("recv_ts")
    peer_send = obj.get("send_ts")
    if peer_recv is None or peer_send is None:
        return
    try:
        span.tag(
            t_send=round(t_send, 6),
            t_recv=round(t_recv, 6),
            peer_recv=round(float(peer_recv), 6),
            peer_send=round(float(peer_send), 6),
            peer=str(obj.get("peer", "")),
        )
    except (TypeError, ValueError):
        pass


def tag_hop(span: Optional[Span], t_send: float, t_recv: float, headers) -> None:
    """Tag the four NTP stamps + peer identity onto a client hop span
    from the server's echo headers (no-op on missing echo/span)."""
    if span is None or headers is None:
        return
    try:
        peer_recv = headers.get(TS_RECV_HEADER)
        peer_send = headers.get(TS_SEND_HEADER)
        peer = headers.get(PEER_HEADER)
    except AttributeError:
        return
    if not peer_recv or not peer_send:
        return
    try:
        span.tag(
            t_send=round(t_send, 6),
            t_recv=round(t_recv, 6),
            peer_recv=round(float(peer_recv), 6),
            peer_send=round(float(peer_send), 6),
            peer=peer or "",
        )
    except (TypeError, ValueError):
        pass
