"""Device topology discovery and world-mesh construction.

TPU-native replacement for the reference's rank/communicator bootstrap
(ref: horovod/common/mpi/mpi_context.cc + horovod/common/gloo/gloo_context.cc
[V], SURVEY.md §2.1): where the reference derives (rank, local_rank,
cross_rank) from MPI communicators or rendezvous env vars, we derive them from
the JAX runtime's view of the TPU slice, with the ``HOROVOD_*`` env contract
as an override so the runner keeps working.

Rank semantics on TPU (documented divergence, SURVEY.md §7.1): Horovod runs
one process per accelerator; single-controller JAX runs one process per host
driving ``local_size`` chips. We keep Horovod's *one rank per chip* contract:

- ``size``        = total chips in the slice (the parallel width),
- ``local_size``  = chips driven by this process,
- ``rank``        = global index of this process's lead chip,
- ``cross_rank``  = this process's index among processes (one per host),
- ``cross_size``  = number of processes.

Per-chip rank identity inside a collective is ``lax.axis_index('hvd')`` in
traced code; eager helpers (`shard_from_rank_fn`) construct rank-dependent
global arrays.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .config import Config

# The canonical data-parallel ("world") mesh axis name, used everywhere the
# reference would say "the global communicator".
WORLD_AXIS = "hvd"


@dataclasses.dataclass(frozen=True)
class Topology:
    """Immutable view of the slice this job runs on."""

    devices: tuple  # all addressable + non-addressable devices, rank order
    process_index: int
    process_count: int
    local_device_count: int

    @property
    def size(self) -> int:
        return len(self.devices)

    @property
    def local_size(self) -> int:
        return self.local_device_count

    @property
    def rank(self) -> int:
        return self.process_index * self.local_device_count

    @property
    def local_rank(self) -> int:
        return 0

    @property
    def cross_rank(self) -> int:
        return self.process_index

    @property
    def cross_size(self) -> int:
        return self.process_count

    def world_mesh(self) -> Mesh:
        """1-D mesh over every chip: the global communicator equivalent."""
        return Mesh(np.asarray(self.devices), (WORLD_AXIS,))

    def sub_mesh(self, ranks: Sequence[int]) -> Mesh:
        """Mesh over a subset of chips — the process-set communicator
        equivalent (ref: horovod/common/process_set.cc [V])."""
        devs = np.asarray([self.devices[r] for r in ranks])
        return Mesh(devs, (WORLD_AXIS,))


def discover(config: Optional[Config] = None) -> Topology:
    """Build the topology from the JAX runtime and validate it against the
    HOROVOD_* env contract.

    The reference learns world shape from MPI_Init or rendezvous env
    (HOROVOD_RANK/SIZE/...); under JAX those arrive via
    ``jax.distributed.initialize``, which the runner performs before user
    code. When the launcher additionally exported HOROVOD_RANK/SIZE/...,
    they must agree with what the runtime reports — a silent mismatch
    would mean the job is running on a different slice than the launcher
    assigned, so it is an error.
    """
    devices = tuple(jax.devices())
    topo = Topology(
        devices=devices,
        process_index=jax.process_index(),
        process_count=jax.process_count(),
        local_device_count=jax.local_device_count(),
    )
    if config is not None:
        checks = [
            ("HOROVOD_SIZE", config.size, topo.size),
            ("HOROVOD_LOCAL_SIZE", config.local_size, topo.local_size),
            ("HOROVOD_CROSS_SIZE", config.cross_size, topo.cross_size),
            ("HOROVOD_RANK", config.rank, topo.rank),
            ("HOROVOD_LOCAL_RANK", config.local_rank, topo.local_rank),
            ("HOROVOD_CROSS_RANK", config.cross_rank, topo.cross_rank),
        ]
        mismatches = [
            f"{name}={want} but the JAX runtime reports {got}"
            for name, want, got in checks
            if want is not None and want != got
        ]
        if mismatches:
            raise ValueError(
                "HOROVOD_* env contract does not match the discovered "
                "slice topology: " + "; ".join(mismatches)
            )
    return topo


# ---------------------------------------------------------------------------
# Rank-major global arrays: the eager-mode data model.
#
# An eager Horovod collective sees one same-shaped tensor per rank. Under a
# single controller the natural representation is one global jax.Array with a
# leading "rank" axis of length `size`, sharded over the world mesh so row r
# lives on chip r. Collectives over it lower to real ICI collectives.
# ---------------------------------------------------------------------------


def rank_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P(WORLD_AXIS))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_from_rank_fn(
    fn: Callable[[int], np.ndarray], mesh: Mesh, dtype=None
) -> jax.Array:
    """Build a rank-major global array where row r = fn(r), placed on chip r.

    Test/benchmark helper mirroring the reference's per-rank tensor
    construction pattern (`tensor = torch.ones(...) * hvd.rank()` in
    test/parallel/test_torch.py [V]).
    """
    n = mesh.devices.size
    rows = [np.asarray(fn(r), dtype=dtype) for r in range(n)]
    stacked = np.stack(rows, axis=0)
    return jax.device_put(stacked, rank_sharding(mesh))
