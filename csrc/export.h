// Common export macro for the horovod_tpu native runtime library.
//
// TPU-native rebuild of the reference's native core (ref:
// horovod/common/*.cc — SURVEY.md §2.1/§2.7; the reference ships its
// runtime as a C++ shared library with a C API consumed over
// ctypes/pybind, and so do we: every entry point here is extern "C"
// and loaded via ctypes from horovod_tpu/_native/loader.py).
#pragma once

#define HVD_EXPORT extern "C" __attribute__((visibility("default")))
