"""ShardedDistributedOptimizer (ZeRO-1 weight-update sharding): the
sharded reduce-scatter/update/all-gather path must produce EXACTLY the
params trajectory of the replicated DistributedOptimizer for
elementwise inner transforms, while its state leaves carry a leading
world axis (1/N per rank). Pattern ref: PAPERS.md arXiv:2004.13336."""

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd_pkg


def _problem(rng, d_in=5, d_out=3):
    # deliberately awkward sizes: 5*3 and 3 don't divide 8 -> padding path
    w = rng.normal(size=(d_in, d_out)).astype(np.float32)
    params = {
        "w": jnp.asarray(rng.normal(size=(d_in, d_out)), jnp.float32),
        "b": jnp.zeros((d_out,), jnp.float32),
    }
    x = rng.normal(size=(8, 16, d_in)).astype(np.float32)
    y = np.einsum("wbi,io->wbo", x, w).astype(np.float32)
    return params, jnp.asarray(x), jnp.asarray(y)


def _loss(params, xb, yb):
    pred = xb @ params["w"] + params["b"]
    return jnp.mean((pred - yb) ** 2)


def _make_sharded_step(opt):
    """The canonical ZeRO-1 train step over the world mesh (shared by
    the trajectory and checkpoint tests so the protocol can't drift)."""

    @partial(
        jax.shard_map, mesh=hvd_pkg.mesh(),
        in_specs=(P(), opt.state_spec(), P(hvd_pkg.WORLD_AXIS),
                  P(hvd_pkg.WORLD_AXIS)),
        out_specs=(P(), opt.state_spec(), P()),
        check_vma=False,
    )
    def step(p, st, xb, yb):
        loss, g = jax.value_and_grad(_loss)(p, xb[0], yb[0])
        u, st = opt.update(g, st, p)
        return optax.apply_updates(p, u), st, jax.lax.pmean(
            loss, hvd_pkg.WORLD_AXIS
        )

    return jax.jit(step)


@pytest.mark.parametrize(
    "inner", ["adam", "sgd_momentum"], ids=str
)
def test_matches_replicated_optimizer(hvd, inner):
    mesh = hvd_pkg.mesh()
    rng = np.random.default_rng(0)
    params, x, y = _problem(rng)
    make = {
        "adam": lambda: optax.adam(1e-2),
        "sgd_momentum": lambda: optax.sgd(1e-2, momentum=0.9),
    }[inner]

    sharded = hvd_pkg.ShardedDistributedOptimizer(make())
    replicated = hvd_pkg.DistributedOptimizer(make())
    s_state = sharded.init(params)
    r_state = replicated.init(params)

    js = _make_sharded_step(sharded)
    @partial(
        jax.shard_map, mesh=mesh,
        in_specs=(P(), P(), P(hvd_pkg.WORLD_AXIS), P(hvd_pkg.WORLD_AXIS)),
        out_specs=(P(), P(), P()),
        check_vma=False,
    )
    def r_step(p, st, xb, yb):
        loss, grads = jax.value_and_grad(_loss)(p, xb[0], yb[0])
        upd, st = replicated.update(grads, st, p)
        return optax.apply_updates(p, upd), st, jax.lax.pmean(
            loss, hvd_pkg.WORLD_AXIS
        )

    sp, rp = params, params
    s_losses, r_losses = [], []
    jr = jax.jit(r_step)
    for _ in range(10):
        sp, s_state, sl = js(sp, s_state, x, y)
        rp, r_state, rl = jr(rp, r_state, x, y)
        s_losses.append(float(sl))
        r_losses.append(float(rl))

    # identical trajectories (elementwise transforms, same arithmetic)
    np.testing.assert_allclose(s_losses, r_losses, rtol=1e-5)
    for k in params:
        np.testing.assert_allclose(
            np.asarray(sp[k]), np.asarray(rp[k]), rtol=1e-5, atol=1e-6
        )
    # and training actually progressed
    assert s_losses[-1] < s_losses[0] * 0.9


def test_state_is_sharded_with_leading_world_axis(hvd):
    rng = np.random.default_rng(1)
    params, _, _ = _problem(rng)
    opt = hvd_pkg.ShardedDistributedOptimizer(optax.adam(1e-3))
    state = opt.init(params)
    world = hvd_pkg.size()
    n_param = sum(p.size for p in jax.tree_util.tree_leaves(params))
    for leaf in jax.tree_util.tree_leaves(state):
        assert leaf.shape[0] == world  # uniform world-major leading axis
    # Adam: mu + nu sharded -> per-rank state elements ~= 2 * n_param / world
    # (plus padding and the count scalar); the STACKED total stays ~2x
    # n_param, not 2x * world
    arr = [
        leaf for leaf in jax.tree_util.tree_leaves(state) if leaf.ndim > 1
    ]
    per_rank = sum(l[0].size for l in arr)
    assert per_rank <= (2 * n_param) / world + 2 * world
    assert per_rank >= (2 * n_param) / world


def test_adasum_rejected(hvd):
    with pytest.raises(NotImplementedError):
        hvd_pkg.ShardedDistributedOptimizer(
            optax.adam(1e-3), op=hvd_pkg.Adasum
        )


def test_scalar_param_leaf_stable_state_shapes(hvd):
    """0-d param leaves stay replicated: state shapes must be identical
    step-over-step (a shape flip would retrace and break donation)."""
    mesh = hvd_pkg.mesh()
    params = {
        "w": jnp.asarray(np.arange(12, dtype=np.float32).reshape(4, 3)),
        "temp": jnp.asarray(1.0),  # scalar leaf
    }
    opt = hvd_pkg.ShardedDistributedOptimizer(optax.adam(1e-2))
    state = opt.init(params)
    shapes0 = [l.shape for l in jax.tree_util.tree_leaves(state)]

    @partial(
        jax.shard_map, mesh=mesh,
        in_specs=(P(), opt.state_spec()),
        out_specs=(P(), opt.state_spec()),
        check_vma=False,
    )
    def step(p, st):
        g = jax.tree_util.tree_map(jnp.ones_like, p)
        u, st = opt.update(g, st, p)
        return optax.apply_updates(p, u), st

    js = jax.jit(step)
    for _ in range(2):
        params, state = js(params, state)
    shapes1 = [l.shape for l in jax.tree_util.tree_leaves(state)]
    assert shapes0 == shapes1
    assert np.isfinite(float(params["temp"]))


def test_world_mismatch_raises_clearly(hvd):
    """Stale init world vs the actual mesh axis must fail loudly."""
    params = {"w": jnp.ones((4, 3), jnp.float32)}
    opt = hvd_pkg.ShardedDistributedOptimizer(optax.sgd(1e-2), world=4)
    state = opt.init(params)
    mesh = hvd_pkg.mesh()  # 8-way axis != init's world=4

    @partial(
        jax.shard_map, mesh=mesh,
        in_specs=(P(), P(hvd_pkg.WORLD_AXIS)),
        out_specs=(P(), P(hvd_pkg.WORLD_AXIS)),
        check_vma=False,
    )
    def step(p, st):
        g = jax.tree_util.tree_map(jnp.ones_like, p)
        u, st = opt.update(g, st, p)
        return optax.apply_updates(p, u), st

    with pytest.raises(ValueError, match="world changed"):
        jax.jit(step)(params, state)


def test_sharded_state_checkpoints_roundtrip(hvd, tmp_path):
    """ZeRO-1 state (leading world axis on every leaf) must survive an
    Orbax CheckpointManager save/restore — the elastic-resume path."""
    from horovod_tpu.checkpoint import CheckpointManager

    rng = np.random.default_rng(5)
    params, x, y = _problem(rng)
    opt = hvd_pkg.ShardedDistributedOptimizer(optax.adam(1e-2))
    state = opt.init(params)
    js = _make_sharded_step(opt)
    for _ in range(3):
        params, state, _ = js(params, state, x, y)

    with CheckpointManager(str(tmp_path / "ckpt"), async_save=False) as m:
        m.save(3, {"params": params, "opt_state": state})
        # restore with `like`: structure (optax NamedTuples) + the
        # LIVE trees' shardings; values come from disk — the documented
        # elastic-resume pattern
        restored = m.restore(
            like={"params": params, "opt_state": state}
        )
    r_params, r_state = restored["params"], restored["opt_state"]
    for a, b in zip(
        jax.tree_util.tree_leaves(state),
        jax.tree_util.tree_leaves(r_state),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    # restored state keeps training identically to the uninterrupted run
    p1, s1, _ = js(params, state, x, y)
    p2, s2, _ = js(r_params, r_state, x, y)
    for a, b in zip(
        jax.tree_util.tree_leaves(p1), jax.tree_util.tree_leaves(p2)
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-6
        )


class TestNonElementwiseGuard:
    """VERDICT r3 #5: the init-time differential probe must reject
    norm-coupled inner transforms and accept elementwise ones."""

    @pytest.mark.parametrize(
        "make",
        [
            lambda: optax.clip_by_global_norm(1.0),
            lambda: optax.chain(
                optax.clip_by_global_norm(1.0), optax.sgd(0.1)
            ),
            lambda: optax.adaptive_grad_clip(0.01),
            # Adam's step-1 update is scale-invariant: only a
            # multi-step probe catches clip composed with it
            lambda: optax.chain(
                optax.clip_by_global_norm(1.0), optax.adam(1e-3)
            ),
            # shape-gated coupling: factored second moment engages only
            # for dims >= 128, and shards are flattened 1-D
            lambda: optax.adafactor(1e-3),
        ],
        ids=["clip_global_norm", "clip_then_sgd", "adaptive_grad_clip",
             "clip_then_adam", "adafactor"],
    )
    def test_rejects_norm_based_transforms(self, make):
        with pytest.raises(ValueError, match="not elementwise"):
            hvd_pkg.ShardedDistributedOptimizer(make())

    @pytest.mark.parametrize(
        "make",
        [
            lambda: optax.sgd(0.1, momentum=0.9),
            lambda: optax.adam(1e-3),
            lambda: optax.adamw(1e-3, weight_decay=1e-2),
            lambda: optax.rmsprop(1e-3),
            lambda: optax.chain(
                optax.clip(1.0),  # per-element clip IS elementwise
                optax.sgd(0.1),
            ),
        ],
        ids=["sgd_momentum", "adam", "adamw", "rmsprop", "clip_elementwise"],
    )
    def test_accepts_elementwise_transforms(self, make):
        hvd_pkg.ShardedDistributedOptimizer(make())  # must not raise

    def test_probe_escape_hatch(self, monkeypatch):
        monkeypatch.setenv("HOROVOD_SHARDED_OPT_PROBE", "0")
        hvd_pkg.ShardedDistributedOptimizer(
            optax.clip_by_global_norm(1.0)
        )  # caller accepted the risk; construction proceeds


def _full_moments(state, params):
    """Reconstruct each sharded moment's full (unpadded) vector."""
    leaves = jax.tree_util.tree_leaves(state)
    sizes = sorted({int(np.asarray(p).size) for p in params.values()})
    out = []
    for leaf in leaves:
        a = np.asarray(leaf)
        if a.ndim == 1:  # replicated scalar stack
            out.append(a[:1])
            continue
        full = a.reshape(-1)
        # trim to the matching param size (padding tail is zeros)
        for s in sizes:
            if s <= full.size and full.size - s < a.shape[0]:
                full = full[:s]
                break
        out.append(full)
    return out


@pytest.mark.parametrize("new_world", [4, 2])
def test_elastic_reshard_preserves_moments(hvd, new_world):
    """Gang restart with a different world size: reshard_state must
    carry Adam moments over EXACTLY (not reset them), and training
    must continue on the new, smaller mesh."""
    from jax.sharding import Mesh

    rng = np.random.default_rng(0)
    params, x, y = _problem(rng)
    opt = hvd_pkg.ShardedDistributedOptimizer(optax.adam(1e-2))
    state = opt.init(params)
    step8 = _make_sharded_step(opt)
    losses = []
    for _ in range(3):
        params, state, loss = step8(params, state, x, y)
        losses.append(float(loss))

    before = _full_moments(jax.device_get(state), params)
    state2 = opt.reshard_state(state, params, new_world)
    after = _full_moments(jax.device_get(state2), params)
    assert len(before) == len(after)
    for b, a in zip(before, after):
        np.testing.assert_allclose(a, b, rtol=0, atol=0)

    # continue on the new world: a fresh mesh of new_world devices.
    # An elastic restart passes state through the host (checkpoint /
    # DurableJaxState), so uncommit from the old mesh the same way.
    params = jax.tree_util.tree_map(np.asarray, jax.device_get(params))
    state2 = jax.tree_util.tree_map(np.asarray, jax.device_get(state2))
    mesh_small = Mesh(
        np.asarray(jax.devices()[:new_world]), (hvd_pkg.WORLD_AXIS,)
    )

    @partial(
        jax.shard_map, mesh=mesh_small,
        in_specs=(P(), opt.state_spec(), P(hvd_pkg.WORLD_AXIS),
                  P(hvd_pkg.WORLD_AXIS)),
        out_specs=(P(), opt.state_spec(), P()),
        check_vma=False,
    )
    def step_small(p, st, xb, yb):
        loss, g = jax.value_and_grad(_loss)(p, xb[0], yb[0])
        u, st = opt.update(g, st, p)
        return optax.apply_updates(p, u), st, jax.lax.pmean(
            loss, hvd_pkg.WORLD_AXIS
        )

    xs = x[:new_world]
    ys = y[:new_world]
    for _ in range(5):
        params, state2, loss = jax.jit(step_small)(
            params, state2, xs, ys
        )
        losses.append(float(loss))
    assert losses[-1] < losses[2], losses  # still learning post-reshard

    # resharding BACK up restores the full-vector moments again
    state3 = opt.reshard_state(state2, params, 8)
    up = _full_moments(jax.device_get(state3), params)
    mid = _full_moments(jax.device_get(state2), params)
    for a, b in zip(up, mid):
        np.testing.assert_allclose(a, b, rtol=0, atol=0)


def test_reshard_rejects_bad_world(hvd):
    params = {"w": jnp.ones((3, 2))}
    opt = hvd_pkg.ShardedDistributedOptimizer(optax.sgd(1e-2))
    state = opt.init(params)
    with pytest.raises(ValueError, match="new_world"):
        opt.reshard_state(state, params, 0)
