"""Preemption handling: the TPU-native failure mode, handled first-class.

The reference's elastic stack reacts to failures AFTER they break a
collective (`HorovodInternalError` → rollback, SURVEY.md §3.4/§5.3);
preemptible TPU VMs instead deliver an ADVANCE signal (SIGTERM from the
infrastructure, typically ~30s of grace). This module turns that grace
window into a durable checkpoint:

    state = DurableJaxState(checkpoint_dir=..., params=..., step=0)
    with hvd.preemption.GracefulShutdown(state):
        train(state)   # on SIGTERM: finish persisting, then exit(143)

or cooperatively:

    handler = hvd.preemption.PreemptionHandler()
    for step in range(...):
        ...
        if handler.should_stop():   # signal arrived: wind down in-loop
            state.commit(); state.wait_until_finished(); break

After the restart (same or re-acquired slice), ``resume_latest()`` on a
fresh ``DurableJaxState`` continues from the persisted step — the
slice-re-acquisition recovery the survey calls for (§5.3: "elastic on
TPU is restart-with-different-slice").
"""

from __future__ import annotations

import os
import signal
import threading
from typing import Callable, Iterable, Optional

_DEFAULT_SIGNALS = (signal.SIGTERM,)


class PreemptionHandler:
    """Latches preemption signals; query with :meth:`should_stop`.

    Chains any previously-installed handler, so stacking on top of a
    launcher's own SIGTERM handling keeps both behaviors.
    """

    def __init__(
        self,
        signals: Iterable[int] = _DEFAULT_SIGNALS,
        on_preempt: Optional[Callable[[], None]] = None,
    ) -> None:
        self._event = threading.Event()
        self._on_preempt = on_preempt
        self._previous = {}
        for sig in signals:
            self._previous[sig] = signal.signal(sig, self._handle)

    def _handle(self, signum, frame) -> None:
        self._event.set()
        # Unstick any KV poll loop first: a preempted worker blocked in
        # a rendezvous wait() must notice the shutdown at its next poll
        # instead of spending the grace window spinning on HTTP.
        try:
            from .runner import rendezvous as _rdv

            _rdv.request_poll_shutdown()
        except Exception:
            pass
        if self._on_preempt is not None:
            self._on_preempt()
        prev = self._previous.get(signum)
        if callable(prev):
            prev(signum, frame)

    def should_stop(self) -> bool:
        return self._event.is_set()

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._event.wait(timeout)

    def uninstall(self) -> None:
        for sig, prev in self._previous.items():
            signal.signal(sig, prev if prev is not None else signal.SIG_DFL)
        self._previous.clear()


class GracefulShutdown:
    """Context manager: on preemption, persist the state and exit.

    ``state`` needs the DurableJaxState surface (``commit()`` +
    ``wait_until_finished()``); any object with those methods works.
    ``exit_code`` defaults to 143 (128+SIGTERM), which launchers read as
    "killed by infrastructure", not a software fault.
    """

    def __init__(
        self,
        state,
        signals: Iterable[int] = _DEFAULT_SIGNALS,
        exit_code: int = 143,
    ) -> None:
        self._state = state
        self._signals = tuple(signals)
        self._exit_code = exit_code
        self._handler: Optional[PreemptionHandler] = None

    def __enter__(self) -> "GracefulShutdown":
        self._handler = PreemptionHandler(
            signals=self._signals, on_preempt=self._drain_and_exit
        )
        return self

    def _drain_and_exit(self) -> None:
        try:
            # Flight recorder first (common/telemetry.py): the ring dump
            # is a bounded tmp+rename write, so it cannot eat the grace
            # window the checkpoint needs — and a failed checkpoint
            # still leaves the last-N-steps post-mortem on disk.
            try:
                from .common import telemetry as _telemetry

                _telemetry.hub().dump()
            except Exception:
                pass
            # ``preemption.drain`` injection site: the deterministic
            # mid-save kill window — a chaos plan SIGKILLs here to
            # prove a kill landing between the flight-recorder dump and
            # the durable persist can never leave a truncated artifact
            # the restore path later trusts (tests/test_chaos.py).
            try:
                from .testing import chaos as _chaos

                _chaos.inject("preemption.drain")
            except Exception:
                pass  # injected transport faults don't fit this site
            # Prefer the unconditional durable path: commit() may batch
            # (save_interval) or raise HostsUpdatedInterrupt before the
            # write — either loses the grace window's whole purpose.
            persist = getattr(self._state, "persist", None)
            if persist is not None:
                persist()
            else:
                self._state.commit()
            wait = getattr(self._state, "wait_until_finished", None)
            if wait is not None:
                wait()
        finally:
            # os._exit: a signal can arrive mid-collective; running
            # normal interpreter teardown over wedged device state can
            # hang past the grace window, and the checkpoint is already
            # durable.
            os._exit(self._exit_code)

    def __exit__(self, *exc) -> None:
        if self._handler is not None:
            self._handler.uninstall()
            self._handler = None
