"""Local SGD: ICI-only local steps, K-step hierarchical-Adasum
reconciliation across the DCN axis (ROADMAP item 3).

Every training step used to pay the inter-slice DCN hop —
hierarchically and in int8 after the two-level wire (PR 10) and the
quantized inter formats (PR 2/12), but still EVERY step. This module
turns the two-level world from a latency optimization into a training
REGIME: slices train independently on their ICI-only wire for K
micro-steps (``DistributedOptimizer(local_sgd_steps=K)`` /
``ShardedDistributedOptimizer(local_sgd_steps=K)``, env
``HOROVOD_LOCAL_SGD_STEPS``), then reconcile **parameter deltas since
the last round** across the inter axis with hierarchical Adasum over
the int8 inter wire. Inter-DCN bytes drop ~K-fold on top of the
hierarchical+int8 wire (docs/perf.md carries the pre-registered
prediction table).

Why Adasum as the merge operator (Sergeev & Del Balso, arXiv
1802.05799 — PAPERS.md): after K local steps the slice deltas are no
longer IID gradient samples — they are correlated trajectories whose
naive average shrinks the step and whose naive sum overshoots.
Adasum's combine removes each delta's projection onto the other
before summing: orthogonal progress adds, redundant progress
averages, and the result is invariant to each slice's local scale —
exactly the convergence argument the reference makes for hierarchical
allreduce + Adasum, applied at round granularity
(docs/design.md "semi-synchronous training").

Three layers live here:

* **Phase routing** — :func:`local_phase` /
  :func:`active_intra_groups`: while a local phase is active, the
  eager fused dispatcher (``ops/fusion.py``) restricts every fused
  allreduce to the intra-slice replica groups, and the optimizers
  pass the same groups to their bucketed/monolithic exchange legs.
  Lowered local-phase step programs contain ZERO inter-spanning
  replica groups (hloaudit-asserted:
  ``scripts/hlo_audit.py local_sgd_phase``).
* **The sync round** — :func:`sync_tree` (replicated params) and
  :func:`adasum_sync_shard` (intra-sharded deltas): the traced
  reconciliation bodies over
  :func:`~horovod_tpu.ops.adasum.adasum_allreduce_groups`'s grouped
  VHDD, with error-feedback residuals carried ACROSS rounds in the
  optimizer state (the ``"local"`` layout family;
  ``reshard_state`` migrates it across world changes).
* **The round driver** — :func:`run_round` / :func:`maybe_sync`:
  host-side cadence + robustness. A DCN outage during a sync round
  retries the round WHOLE under the PR 6 ``RetryPolicy``
  (``local_sgd.sync`` chaos site), and exhaustion DEFERS the round —
  the local phase extends, ``local_sgd.rounds_deferred`` counts it,
  and training continues on the ICI wire with zero gang restarts.
  An elastic rejoin re-syncs the newcomer from the Adasum consensus:
  a slice restored at the last anchor contributes a zero delta
  (Adasum's identity), so the first round after the join hands it
  the surviving slices' combined progress instead of a root
  broadcast (:func:`rejoin_sync`).
"""

from __future__ import annotations

import contextlib
from typing import Optional

from .common.logging import get_logger

_log = get_logger("local_sgd")

#: wire formats the sync round's inter hop accepts
INTER_WIRES = ("fp32", "bf16", "int8")


def default_steps() -> int:
    """``HOROVOD_LOCAL_SGD_STEPS`` through the live config (1 = the
    existing every-step sync path; the mode engages at K > 1)."""
    from .common import basics

    return basics.live_config().local_sgd_steps


_env_warned = [False]


def warn_env_engaged(k: int) -> None:
    """One loud warning when the env knob (not an explicit
    ``local_sgd_steps=``) flips an optimizer into local mode: the mode
    is only HALF a training loop — a loop that never drives the sync
    round trains silently diverged slices forever, and an operator
    flipping the env under an existing script is exactly the caller
    who may not know that."""
    if _env_warned[0]:
        return
    _env_warned[0] = True
    import warnings

    warnings.warn(
        f"HOROVOD_LOCAL_SGD_STEPS={k} engaged local-SGD mode: gradient "
        "exchange is now INTRA-SLICE ONLY, and parameters only "
        "reconcile across slices when the training loop drives the "
        "sync round (hvd.local_sgd.maybe_sync every step, or "
        "opt.sync/sync_round every K-th). A loop that never syncs "
        "trains silently diverged slices. Pass local_sgd_steps= "
        "explicitly to silence this warning.",
        stacklevel=3,
    )


def resolve_stages(world: int, intra: Optional[int] = None):
    """The two-level ``(intra_groups, inter_groups)`` split a local-SGD
    job trains over — ``topology.hierarchy_stages`` in explicit mode
    (local SGD is a per-job request, not an auto decision), or a loud
    error when no split resolves: with a single slice there is no
    inter axis to reconcile across and the mode is meaningless."""
    from .common import topology as _topo

    stages = _topo.hierarchy_stages(world=world, mode="on", intra=intra)
    if stages is None:
        raise ValueError(
            f"local_sgd_steps > 1 needs a resolvable two-level topology "
            f"(world={world}, intra={intra}): set HOROVOD_INTRA_SIZE "
            "(or pass local_sgd_intra=) on single-slice runtimes, or "
            "run on a multi-slice TPU — with one slice there is no "
            "inter (DCN) axis to reconcile across"
        )
    return stages


# ------------------------------------------------------- phase routing
# The eager fused dispatcher cannot see the optimizer's knobs — it
# serves hvd.allreduce calls from anywhere in the process — so the
# local phase is a process-wide flag it consults per dispatch
# (ops/fusion.py folds it into the executor cache key, so flipping the
# phase can never reuse a flat-wire executable).

_phase = {"groups": None}


def set_local_phase(stages) -> None:
    """Activate local-phase routing for the EAGER fused dispatcher:
    ``stages`` is the ``(intra_groups, inter_groups)`` pair (or the
    intra groups alone); until cleared, every eligible fused allreduce
    reduces within its intra group only."""
    groups = stages[0] if isinstance(stages, tuple) and len(stages) == 2 else stages
    _phase["groups"] = tuple(tuple(int(r) for r in g) for g in groups)


def clear_local_phase() -> None:
    _phase["groups"] = None


def active_intra_groups():
    """The intra groups of the active local phase, or None — the hook
    ``FusionManager`` consults per allreduce dispatch."""
    return _phase["groups"]


@contextlib.contextmanager
def local_phase(stages):
    """Scoped :func:`set_local_phase`::

        with hvd.local_sgd.local_phase(stages):
            hvd.allreduce(grad)   # reduces intra-slice only
    """
    set_local_phase(stages)
    try:
        yield
    finally:
        clear_local_phase()


def reset() -> None:
    """Drop phase + driver state (gang restart / tests): the new gang
    resolves its own split and retry ladder."""
    clear_local_phase()
    _round_policy[0] = None


# ------------------------------------------------------ traced bodies


def adasum_sync_shard(
    shard,
    stages,
    axis_name: Optional[str] = None,
    inter_wire: str = "int8",
    seed=0,
    residual=None,
    return_residual: bool = False,
):
    """Reconcile ONE intra-position shard across slices: ``shard`` is
    this rank's ``[cols]`` chunk of its slice's delta vector (the
    sharded optimizer's ``"local"`` anchor geometry — each slice's
    vector is jointly held by its L ranks). VHDD Adasum runs across
    the inter groups with the dot products completed over the intra
    groups, so the coefficients are exact full-vector values while
    every DCN hop moves 1/L of the bytes. Returns the merged shard
    (same geometry); with ``residual``/``return_residual`` the
    error-feedback pre-quantization carry rides in shard geometry
    (``quantized + residual' == shard + residual`` bit-exact).

    Thin alias of :func:`horovod_tpu.ops.adasum.adasum_sync_shard` —
    ONE implementation serves this and the replicated
    :func:`~horovod_tpu.ops.adasum.adasum_allreduce_groups` path."""
    from .common.topology import WORLD_AXIS
    from .ops.adasum import adasum_sync_shard as _core

    return _core(
        shard, stages,
        axis_name=axis_name if axis_name is not None else WORLD_AXIS,
        inter_wire=inter_wire, seed=seed, residual=residual,
        return_residual=return_residual,
    )


def sync_tree(
    params,
    anchor,
    residual=None,
    stages=None,
    axis_name: Optional[str] = None,
    inter_wire: str = "int8",
    seed=0,
    return_residual: bool = False,
):
    """The replicated-optimizer sync round body (traced, inside
    shard_map over the flat axis): parameter deltas since the last
    round (``params − anchor``, replicated within each slice by
    local-phase construction) merge across slices through ONE
    concatenated :func:`~horovod_tpu.ops.adasum.adasum_allreduce_groups`
    and the new params land on ``anchor + merged``. Returns
    ``(new_params, new_residual_or_None)`` — the caller re-anchors on
    the result."""
    import jax
    import jax.numpy as jnp

    from .common.topology import WORLD_AXIS
    from .ops.adasum import adasum_allreduce_groups

    if stages is None:
        raise ValueError("stages is required (resolve_stages)")
    if axis_name is None:
        axis_name = WORLD_AXIS
    p_leaves, treedef = jax.tree_util.tree_flatten(params)
    a_leaves = treedef.flatten_up_to(anchor)
    sizes = [leaf.size for leaf in p_leaves]
    flat = jnp.concatenate(
        [
            (p - a.astype(p.dtype)).reshape(-1).astype(jnp.float32)
            for p, a in zip(p_leaves, a_leaves)
        ]
    )
    r_flat = None
    if residual is not None:
        r_leaves = treedef.flatten_up_to(residual)
        r_flat = jnp.concatenate(
            [r.reshape(-1).astype(jnp.float32) for r in r_leaves]
        )
    want_res = return_residual and inter_wire == "int8"
    if want_res or r_flat is not None:
        merged, new_r = adasum_allreduce_groups(
            flat, axis_name=axis_name, stages=stages,
            inter_wire=inter_wire, seed=seed, residual=r_flat,
            return_residual=True,
        )
    else:
        merged = adasum_allreduce_groups(
            flat, axis_name=axis_name, stages=stages,
            inter_wire=inter_wire, seed=seed,
        )
        new_r = None
    new_p, new_res, off = [], [], 0
    for p, a, sz in zip(p_leaves, a_leaves, sizes):
        d = merged[off : off + sz].reshape(p.shape)
        new_p.append((a.astype(jnp.float32) + d).astype(p.dtype))
        if new_r is not None:
            new_res.append(
                new_r[off : off + sz].reshape(p.shape).astype(p.dtype)
            )
        off += sz
    new_params = jax.tree_util.tree_unflatten(treedef, new_p)
    if new_r is None:
        return new_params, None
    return new_params, jax.tree_util.tree_unflatten(treedef, new_res)


# -------------------------------------------------------- round driver

_round_policy = [None]


def _policy():
    """One RetryPolicy for the whole process's sync rounds (site
    ``local_sgd.sync`` — the PR 6 ladder: jittered backoff, deadline,
    HOROVOD_RETRY_* knobs). Rounds are retried WHOLE: the VHDD's
    internal state never partially commits, so re-running the compiled
    round is idempotent by construction."""
    if _round_policy[0] is None:
        from .common.retry import RetryPolicy

        _round_policy[0] = RetryPolicy.from_env("local_sgd.sync")
    return _round_policy[0]


def round_inter_bytes(payload_bytes: int, stages, inter_wire: str = "int8") -> int:
    """Modeled per-rank DCN bytes of ONE sync round: the VHDD
    halving-doubling over H slices on the 1/L shard at the inter
    wire's width (``ops.adasum.vhdd_wire_bytes`` — the same
    payload-width model as ``FusionManager._hop_bytes``; ring/topology
    factors cancel in every ratio docs/perf.md gates on)."""
    from .ops.adasum import vhdd_wire_bytes

    intra_groups, inter_groups = stages
    L = len(intra_groups[0])
    H = len(inter_groups[0])
    elems = -(-int(payload_bytes) // 4)  # fp32 payload elements
    width = {"int8": 1, "bf16": 2}.get(inter_wire, 4)
    shard_wire_bytes = -(-elems // L) * width
    return vhdd_wire_bytes(H, shard_wire_bytes)


def due(step: int, k: int) -> bool:
    """Sync cadence: True on every K-th step (0-based ``step``; the
    round runs AFTER the step that completes a window)."""
    return int(k) > 1 and (int(step) + 1) % int(k) == 0


def run_round(
    sync_step,
    *args,
    policy=None,
    payload_bytes: Optional[int] = None,
    stages=None,
    inter_wire: str = "int8",
):
    """Execute one compiled sync round under the robustness plane.

    ``sync_step(*args)`` is the jitted reconciliation program (the
    optimizer's ``sync`` inside the caller's shard_map). Each attempt
    first passes the ``local_sgd.sync`` chaos site (the DCN-hop fault
    surface — testing/chaos.py) and then blocks on the round's result
    so a transport fault surfaces INSIDE the attempt; retryable
    failures re-run the round whole under the PR 6 RetryPolicy.
    Exhaustion DEFERS: returns ``(None, False)``, counts
    ``local_sgd.rounds_deferred``, and the caller keeps training on
    the ICI wire — a DCN outage degrades to a longer local phase
    instead of a stall or a gang restart. Success returns
    ``(result, True)``, counts ``local_sgd.sync_rounds``, and (when
    ``payload_bytes``/``stages`` are given) advances the
    ``local_sgd.inter_bytes`` ledger by :func:`round_inter_bytes`."""
    import jax

    from .common.metrics import registry as _metrics
    from .common.retry import CircuitOpenError, RetryError
    from .testing import chaos as _chaos

    pol = policy if policy is not None else _policy()

    def _attempt():
        _chaos.inject("local_sgd.sync")
        out = sync_step(*args)
        jax.block_until_ready(out)
        return out

    try:
        out = pol.call(_attempt)
    except (RetryError, CircuitOpenError) as e:
        _metrics.counter("local_sgd.rounds_deferred")
        _log.warning(
            "local_sgd: sync round deferred (%s) — local phase "
            "extends, training continues on the ICI wire", e,
        )
        return None, False
    _metrics.counter("local_sgd.sync_rounds")
    if payload_bytes is not None and stages is not None:
        _metrics.counter(
            "local_sgd.inter_bytes",
            round_inter_bytes(payload_bytes, stages, inter_wire),
        )
    return out, True


def maybe_sync(
    sync_step,
    *args,
    step: int,
    k: Optional[int] = None,
    policy=None,
    payload_bytes: Optional[int] = None,
    stages=None,
    inter_wire: str = "int8",
):
    """The per-step cadence driver a local-SGD training loop calls
    after every optimizer step::

        out, synced = hvd.local_sgd.maybe_sync(
            sync_step, params, state, step=i, k=8)
        if synced:
            params, state = out

    Counts ``local_sgd.local_steps`` every call; on every K-th step
    runs :func:`run_round` (retry / defer semantics above). Returns
    ``(result_or_None, synced)``."""
    from .common.metrics import registry as _metrics

    if k is None:
        k = default_steps()
    _metrics.counter("local_sgd.local_steps")
    if not due(step, k):
        return None, False
    return run_round(
        sync_step, *args, policy=policy, payload_bytes=payload_bytes,
        stages=stages, inter_wire=inter_wire,
    )


def rejoin_sync(sync_step, *args, policy=None):
    """Elastic-rejoin consensus re-sync: run ONE immediate round after
    a membership change instead of broadcasting root's parameters. A
    slice that restored at the last committed anchor contributes a
    ZERO delta — Adasum's identity, so the round hands it the
    surviving slices' combined progress while contributing nothing
    stale; slices that kept training fold their in-flight progress in
    at the same time. Unlike a root broadcast, no single rank's
    trajectory is privileged. Retry/defer semantics are
    :func:`run_round`'s — a deferred rejoin round simply leaves the
    newcomer at the anchor until the next scheduled round."""
    return run_round(sync_step, *args, policy=policy)
