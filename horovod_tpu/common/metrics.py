"""Structured metrics: counters/gauges + JSON-lines export.

Closes SURVEY.md §5.5's metrics half (the reference exposes its
equivalents through the timeline + TensorBoard callbacks and buildkite
perf jobs [V]; the rebuild's observability stack is logging.py for
text, timeline/traced_timeline for traces, and this module for
numbers). One process-wide registry; subsystems register or bump
metrics by dotted name, and ``HOROVOD_METRICS_FILE`` (or an explicit
``dump``/``start_export`` call) writes JSON lines:

    {"ts": <unix>, "seq": <monotonic>, "name": "fusion.cycles", "value": 17}

Dumps are delta-aware: after the first full snapshot, only changed
values are appended (``dump(force=True)`` re-emits everything).

The fusion manager publishes its cycle/cache counters after every
flush; anything else (user code included) can publish through
``metrics.gauge``/``metrics.counter``.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, Optional

# Enum gauges are exported as integer codes (JSON-lines values are
# floats); this is the shared wire-format legend — fusion's
# ``fusion.wire_format`` gauge and the timeline's counter track both
# use it, so a trace and a metrics dump decode identically.
WIRE_FORMAT_CODES = {"fp32": 0, "bf16": 1, "int8": 2}
WIRE_FORMAT_NAMES = {v: k for k, v in WIRE_FORMAT_CODES.items()}

# Two-level (topology-aware) wire metric families — the per-hop split
# of the fused dispatcher's wire ledger plus the driver's
# straggler-rebalance surface. Emitters: ops/fusion.py cache_stats
# (fusion.*), elastic/driver.py (driver.rebalance.*). Kept here as the
# single legend so dashboards and tests never re-derive the spelling:
#   fusion.hier_dispatches         fused batches that rode the two-level
#                                  recipe (counter)
#   fusion.wire_bytes_saved_intra  intra-hop (ICI) bytes removed vs the
#                                  flat fp32 baseline (counter)
#   fusion.wire_bytes_saved_inter  inter-hop (DCN) bytes removed — the
#                                  scarce-hop meter (counter)
#   fusion.wire_format_intra/inter last dispatch's per-hop wire, as a
#                                  WIRE_FORMAT_CODES code (gauge)
#   driver.rebalance.active        ranks currently down-weighted (gauge)
#   driver.rebalance.updates       weight-map publications (counter)
HIERARCHY_METRICS = (
    "fusion.hier_dispatches",
    "fusion.wire_bytes_saved_intra",
    "fusion.wire_bytes_saved_inter",
    "fusion.wire_format_intra",
    "fusion.wire_format_inter",
    "driver.rebalance.active",
    "driver.rebalance.updates",
)

# Local-SGD metric family (horovod_tpu/local_sgd.py — the K-step
# semi-synchronous regime). Emitters: the host round driver
# (local_sgd.maybe_sync / run_round), the fused dispatcher's phase
# routing, and the elastic driver's heartbeat aggregation. One legend:
#   local_sgd.local_steps       optimizer steps taken under the mode
#                               (counter; local_steps / sync_rounds
#                               ≈ the effective K)
#   local_sgd.sync_rounds       reconciliation rounds completed
#                               (counter)
#   local_sgd.rounds_deferred   rounds pushed out by a DCN failure
#                               after the retry ladder (counter —
#                               degraded-not-stalled evidence)
#   local_sgd.inter_bytes       modeled per-rank DCN bytes the rounds
#                               that RAN moved (counter; the ÷K lever)
#   fusion.local_dispatches     eager fused allreduces routed
#                               intra-only under an active phase
#                               (counter)
#   driver.local_sgd.rounds_deferred  gang-max deferral count from the
#                               heartbeat ledger (gauge)
LOCAL_SGD_METRICS = (
    "local_sgd.local_steps",
    "local_sgd.sync_rounds",
    "local_sgd.rounds_deferred",
    "local_sgd.inter_bytes",
    "fusion.local_dispatches",
    "driver.local_sgd.rounds_deferred",
)

# Expert-wire metric families (PR 12 — parallel/moe.py +
# ops/fusion.py eager alltoall). Emitters: the fusion manager's flush
# (alltoall.*, cumulative — closes the observability gap where eager
# alltoall dispatches were counted in cache_stats but never reached a
# legend or the flight recorder) and :func:`publish_moe` (moe.*, the
# step harness / serving loop publishes the MoEStats counters plus the
# capacity decision in force). Kept here as the single legend so
# dashboards and tests never re-derive the spelling:
#   alltoall.dispatches       eager alltoall executor invocations
#                             (counter)
#   alltoall.wire_bytes       cumulative (n-1)/n-model bytes those
#                             dispatches moved (counter)
#   moe.dropped_tokens        tokens past the capacity gate (counter)
#   moe.routed_tokens         live tokens routed (counter)
#   moe.expert_tokens_max     hottest expert's kept tokens, last step
#                             (gauge)
#   moe.imbalance             hottest / mean kept tokens (gauge; 1.0 =
#                             balanced — hot experts ARE stragglers)
#   moe.drop_rate             dropped / routed, last step (gauge)
#   moe.capacity_factor       the factor in force (gauge; the
#                             CapacityTuner's decision when tuned)
MOE_METRICS = (
    "alltoall.dispatches",
    "alltoall.wire_bytes",
    "moe.dropped_tokens",
    "moe.routed_tokens",
    "moe.expert_tokens_max",
    "moe.imbalance",
    "moe.drop_rate",
    "moe.capacity_factor",
)

# Training-state integrity metric families (PR 7 — the names the
# runbook in docs/robustness.md documents; emitters: common/guard.py,
# audit.py, checkpoint.py, elastic/driver.py). Kept here as the single
# legend so dashboards and tests never re-derive the spelling:
#   guard.nonfinite_steps    skipped optimizer updates (counter)
#   guard.nonfinite_batches  non-finite fused eager batches (counter)
#   guard.skip_streak        consecutive skips at last skip (gauge)
#   audit.digests            parameter digests computed (counter)
#   audit.last_digest_step   step of the newest digest (gauge)
#   checkpoint.digest_mismatch  corrupt-but-parseable restores (counter)
#   driver.divergence_restarts  gang restarts for replica divergence
INTEGRITY_METRICS = (
    "guard.nonfinite_steps",
    "guard.nonfinite_batches",
    "guard.skip_streak",
    "audit.digests",
    "audit.last_digest_step",
    "checkpoint.digest_mismatch",
    "driver.divergence_restarts",
)

# Serving memory-plane metric families (serving/paged_kv.py — the
# names the docs/serving.md "memory plane" runbook documents; emitter:
# PagedKVCacheManager.stats → the `serve.` registry prefix, rendered
# as `hvd_serve_*` on /metrics). Kept here as the single legend so
# dashboards and tests never re-derive the spelling:
#   serve.pages_total / pages_free   pool size / free-list pages (gauge)
#   serve.pages_active               pages held by live slots (gauge)
#   serve.pages_cached               pages held ONLY by the prefix
#                                    index — reclaimable (gauge)
#   serve.page_allocs                pages taken at write frontiers
#                                    (counter)
#   serve.page_evictions             LRU index evictions at refcount 0
#                                    (counter)
#   serve.page_cow                   copy-on-write page copies (counter;
#                                    0 under the shipped sharing policy)
#   serve.prefix_hits                cached pages attached instead of
#                                    prefilled (counter)
#   serve.prefix_hit_requests / prefix_lookups / prefix_hit_rate
#                                    request-level hit accounting
#   serve.prefix_published           pages published into the index
#   serve.paused / serve.resumed     pool-exhaustion preemptions and
#                                    their resumes (counters)
#   serve.paused_pages_reclaimed     paused requests whose kept pages
#                                    were reclaimed past the deadline
#                                    (counter; they re-prefill)
SERVING_PAGE_METRICS = (
    "serve.pages_total",
    "serve.pages_free",
    "serve.pages_active",
    "serve.pages_cached",
    "serve.page_allocs",
    "serve.page_evictions",
    "serve.page_cow",
    "serve.prefix_hits",
    "serve.prefix_hit_requests",
    "serve.prefix_lookups",
    "serve.prefix_hit_rate",
    "serve.prefix_published",
    "serve.paused",
    "serve.resumed",
    "serve.paused_pages_reclaimed",
)

# KV-transfer wire families (serving/kv_transfer.py — the
# disaggregated-fleet stream; legend for docs/observability.md's
# transfer table, rendered as `hvd_serve_*` on /metrics):
#   sender (prefill worker):
#   serve.kv_transfer_bytes / _pages / _ms   framed bytes, pages and
#                                    wall-ms streamed out (counters —
#                                    bytes/pages is the wire's realized
#                                    compression ratio)
#   serve.transfers                  requests successfully streamed out
#   serve.transfer_local             no decode capacity at reserve time
#                                    → decoded locally, never streamed
#   serve.transfer_fallbacks         stream/decode FAILED after
#                                    prefill → request came home for a
#                                    pointer-cheap local decode
#   serve.handed_off                 remote decode completed and the
#                                    waiter was released
#   receiver (decode worker):
#   serve.kv_transfer_bytes_in / _pages_in   framed bytes / pages landed
#   serve.transfer_admits            ingested requests pointer-attached
#                                    into decode slots (counter)
#   serve.transfer_reservations / _reserve_denied
#                                    page reservations granted / denied
#   serve.transfer_pages_in          pool pages taken by ingests
#                                    (PagedKVCacheManager counter)
#   serve.transfer_ingests           engine-level ingest writes
SERVING_TRANSFER_METRICS = (
    "serve.kv_transfer_bytes",
    "serve.kv_transfer_pages",
    "serve.kv_transfer_ms",
    "serve.transfers",
    "serve.transfer_local",
    "serve.transfer_fallbacks",
    "serve.handed_off",
    "serve.kv_transfer_bytes_in",
    "serve.kv_transfer_pages_in",
    "serve.transfer_admits",
    "serve.transfer_reservations",
    "serve.transfer_reserve_denied",
    "serve.transfer_pages_in",
    "serve.transfer_ingests",
)

# Paged-attention kernel path (ops/paged_attention.py through
# serving/engine.py and models/transformer.py — legend for the
# docs/observability.md counter table):
#   serve.paged_attn_calls       executable invocations (decode steps +
#                                prefill chunks) that ran the fused
#                                pool-read kernel (counter; engine
#                                stats → `serve.` prefix)
#   serve.paged_attn_fallbacks   kernel requested but the fallback
#                                ladder rode the gather read instead —
#                                bumped once at engine resolution and
#                                at model trace time (counter)
SERVING_PAGED_ATTN_METRICS = (
    "serve.paged_attn_calls",
    "serve.paged_attn_fallbacks",
)

# Crash-safe serving families (PR 19 — router durability + live
# migration, serving/frontend.py + serving/kv_transfer.py; the
# docs/robustness.md "serving failure ladder" runbook, rendered as
# `hvd_serve_*` on /metrics):
#   serve.replay_dedupe_hits     /generate answered from the TTL ledger
#                                by client request_id — a retry or a
#                                hedge loser absorbed without recompute
#   serve.replays                routed payloads replayed on a live
#                                peer after a DARK worker failure (an
#                                orderly 503 fails over without one)
#   serve.hedges                 hedged second launches past
#                                HOROVOD_SERVE_HEDGE_MS (first writer
#                                wins)
#   serve.migrations             in-flight sequences streamed OUT past
#                                the drain deadline (sender counter)
#   serve.migrations_in          migrated sequences landed and resumed
#                                mid-decode (receiver counter)
#   serve.migration_ms           pack + wire wall-ms per migration
#                                (sender counter)
SERVING_FAILOVER_METRICS = (
    "serve.replay_dedupe_hits",
    "serve.replays",
    "serve.hedges",
    "serve.migrations",
    "serve.migrations_in",
    "serve.migration_ms",
)

# Persistent-executable-cache + warm-restart families (PR 18 —
# common/exe_cache.py, elastic/driver.py + standby.py, elastic/worker
# init; legend for docs/observability.md's warm-restart table):
#   exe_cache.hits / misses       disk-tier lookups that deserialized /
#                                 found no entry (counters)
#   exe_cache.corrupt             torn/bitflipped entries degraded to a
#                                 cold compile (counter; chaos site
#                                 `exe_cache.load`)
#   exe_cache.rejected            entries refused by the invalidation
#                                 rules (version/platform/topology/
#                                 wire/donation skew) — never
#                                 deserialized (counter)
#   exe_cache.stores              entries serialized + queued (counter)
#   exe_cache.bytes               bytes deserialized on hits (counter)
#   exe_cache.deserialize_ms      wall-ms spent deserializing (counter)
#   elastic.restart_ms            gang-teardown → this worker's re-init
#                                 wall-ms (gauge, per worker)
#   elastic.restart_warm          1.0 when a warm standby absorbed the
#                                 restart (gauge)
#   serve.scaleup_ms              restart_ms of a serve-saturation
#                                 grow restart (gauge)
#   serve.warm_start_ms / warm_started_exes
#                                 engine init disk warm-start cost and
#                                 entries loaded (gauge / counter)
#   driver.standby.reserved       hosts currently held as warm
#                                 standbys (gauge)
#   driver.standby.swapins        standbys released into a gang
#                                 (counter)
EXE_CACHE_METRICS = (
    "exe_cache.hits",
    "exe_cache.misses",
    "exe_cache.corrupt",
    "exe_cache.rejected",
    "exe_cache.stores",
    "exe_cache.bytes",
    "exe_cache.deserialize_ms",
    "elastic.restart_ms",
    "elastic.restart_warm",
    "serve.scaleup_ms",
    "serve.warm_start_ms",
    "serve.warm_started_exes",
    "driver.standby.reserved",
    "driver.standby.swapins",
)


class MetricsRegistry:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._values: Dict[str, float] = {}
        self._path: Optional[str] = None
        self._last_dump = 0.0
        # delta-aware export state: what the sink last saw, plus a
        # monotonic per-line sequence number so readers can totally
        # order lines even when ts collides
        self._last_dumped: Optional[Dict[str, float]] = None
        self._seq = 0

    # -- write side ---------------------------------------------------

    def counter(self, name: str, inc: float = 1.0) -> None:
        with self._lock:
            self._values[name] = self._values.get(name, 0.0) + inc

    def gauge(self, name: str, value: float) -> None:
        with self._lock:
            self._values[name] = float(value)

    def update(self, prefix: str, stats: Dict[str, float]) -> None:
        """Publish a dict of gauges under a common prefix (the shape
        fusion.cache_stats() and autotune samples come in)."""
        with self._lock:
            for k, v in stats.items():
                self._values[f"{prefix}.{k}"] = float(v)

    # -- read side ----------------------------------------------------

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            return dict(self._values)

    def reset(self) -> None:
        with self._lock:
            self._values.clear()
            # the sink's view is stale too: next dump re-baselines with
            # a full snapshot (seq stays monotonic across resets)
            self._last_dumped = None

    # -- export -------------------------------------------------------

    @property
    def exporting(self) -> bool:
        """True when a JSON-lines sink is configured — subsystems use
        this to skip observability work that forces a device sync
        (e.g. the fusion manager's EF-residual norm)."""
        return self._path is not None

    def configure_export(self, path: Optional[str] = None) -> None:
        """Set (or clear) the JSON-lines sink. Defaults from
        HOROVOD_METRICS_FILE; explicit path wins."""
        if path is None:
            path = os.environ.get("HOROVOD_METRICS_FILE") or None
        with self._lock:
            if path != self._path:
                # a fresh sink has seen nothing: first write is full
                self._last_dumped = None
            self._path = path

    def maybe_dump(self, min_interval: float = 1.0) -> Optional[str]:
        """Rate-limited dump for hot paths (the fusion flush calls
        this): at most one append per ``min_interval`` seconds, nothing
        when no sink is configured."""
        if not self._path:
            return None
        now = time.monotonic()
        with self._lock:
            if now - self._last_dump < min_interval:
                return None
            self._last_dump = now
        return self.dump()

    def dump(
        self, path: Optional[str] = None, force: bool = False
    ) -> Optional[str]:
        """Append metric lines to the sink; returns the path written
        (None when no sink is configured).

        Delta-aware: only metrics whose value CHANGED since the last
        dump are appended — a long run's periodic export stops paying
        O(total metrics) lines per interval. The first write to a sink
        and ``dump(force=True)`` emit the full snapshot (so a reader can
        always reconstruct state from the last full snapshot forward);
        an explicit ``path`` different from the configured sink also
        gets a full snapshot, without disturbing the sink's delta state.
        Every line carries a monotonic ``seq``."""
        explicit = path is not None and path != self._path
        path = path or self._path
        if not path:
            return None
        now = time.time()
        snap = self.snapshot()
        with self._lock:
            prev = self._last_dumped
            if force or explicit or prev is None:
                items = sorted(snap.items())
            else:
                items = sorted(
                    (k, v) for k, v in snap.items() if prev.get(k) != v
                )
            if not explicit:
                self._last_dumped = dict(snap)
            lines = []
            for name, value in items:
                lines.append(
                    json.dumps(
                        {
                            "ts": now,
                            "seq": self._seq,
                            "name": name,
                            "value": value,
                        }
                    )
                )
                self._seq += 1
        if lines:
            # One O_APPEND write per dump (audited for the chaos
            # drill, docs/robustness.md): the JSON-lines sink is an
            # append log, so tmp+rename doesn't apply — instead the
            # whole batch lands in a single atomic append, and a
            # SIGKILL can at worst tear the final line of the final
            # batch, which any JSON-lines reader skips. Never a
            # half-interleaved record from two processes either.
            payload = ("\n".join(lines) + "\n").encode()
            fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
            try:
                os.write(fd, payload)
            finally:
                os.close(fd)
        return path


registry = MetricsRegistry()


def publish_moe(
    expert_tokens,
    dropped: float,
    total: float,
    capacity_factor: Optional[float] = None,
) -> None:
    """Publish one step's expert-load counters (``moe.*`` — the
    MOE_METRICS legend) from a fetched ``MoEStats``: the step harness
    or serving loop calls this with host floats, so it costs no device
    sync of its own. Counters accumulate (dropped/routed); the
    histogram summaries and capacity decision are gauges."""
    tokens = [float(t) for t in expert_tokens]
    hot = max(tokens, default=0.0)
    kept = float(total) - float(dropped)
    mean = kept / len(tokens) if tokens and kept > 0 else 0.0
    registry.counter("moe.dropped_tokens", float(dropped))
    registry.counter("moe.routed_tokens", float(total))
    registry.gauge("moe.expert_tokens_max", hot)
    registry.gauge("moe.imbalance", hot / mean if mean > 0 else 1.0)
    registry.gauge(
        "moe.drop_rate",
        float(dropped) / float(total) if float(total) > 0 else 0.0,
    )
    if capacity_factor is not None:
        registry.gauge("moe.capacity_factor", float(capacity_factor))


def publish_overlap(
    n_buckets: int,
    bucket_bytes,
    total_bytes: Optional[int] = None,
) -> None:
    """Publish the bucketed-gradient-exchange schedule shape
    (``overlap.*`` gauges — ops/overlap.py). One call per schedule
    build/lookup; values are static host-side ints, so this costs no
    device sync. The exposed/hidden collective-time estimate rides the
    same prefix but is produced by the traced timeline
    (``traced_timeline.collective_overlap_stats``), which owns the
    device spans it is computed from."""
    bucket_bytes = list(bucket_bytes)
    registry.update(
        "overlap",
        {
            "buckets": n_buckets,
            "bucket_bytes_total": (
                total_bytes
                if total_bytes is not None
                else sum(bucket_bytes)
            ),
            "bucket_bytes_max": max(bucket_bytes, default=0),
            "bucket_bytes_min": min(bucket_bytes, default=0),
        },
    )
