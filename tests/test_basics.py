"""Init/lifecycle/rank/size/process-set tests.

Reference model: test/parallel/test_torch.py's basics section + process-set
tests in test/parallel/test_process_sets*.py [V] (SURVEY.md §4.1), adapted
to the 8-device single-controller world.
"""


import pytest


def test_init_idempotent(hvd):
    assert hvd.is_initialized()
    hvd.init()  # second init is a no-op like InitializeHorovodOnce [V]
    assert hvd.is_initialized()


def test_world_shape(hvd):
    assert hvd.size() == 8
    assert hvd.local_size() == 8
    assert hvd.cross_size() == 1
    assert hvd.rank() == 0
    assert hvd.local_rank() == 0
    assert hvd.cross_rank() == 0
    assert hvd.is_homogeneous()


def test_mesh_axis(hvd):
    mesh = hvd.mesh()
    assert mesh.axis_names == (hvd.WORLD_AXIS,)
    assert mesh.devices.size == 8


def test_build_predicates(hvd):
    assert hvd.xla_built()
    assert hvd.tpu_enabled()
    assert not hvd.mpi_enabled()
    assert not hvd.nccl_built()
    assert not hvd.gloo_enabled()


def test_not_initialized_raises():
    import horovod_tpu as hvd

    hvd.shutdown()
    with pytest.raises(RuntimeError):
        hvd.size()


def test_config_env_roundtrip(monkeypatch):
    import horovod_tpu as hvd

    hvd.shutdown()
    monkeypatch.setenv("HOROVOD_FUSION_THRESHOLD", str(1 << 20))
    monkeypatch.setenv("HOROVOD_CYCLE_TIME", "2.5")
    monkeypatch.setenv("HOROVOD_CACHE_CAPACITY", "99")
    monkeypatch.setenv("HOROVOD_TIMELINE_MARK_CYCLES", "1")
    monkeypatch.setenv("HOROVOD_LOG_LEVEL", "DEBUG")
    hvd.init()
    cfg = hvd.get_config()
    assert cfg.fusion_threshold_bytes == 1 << 20
    assert cfg.cycle_time_ms == 2.5
    assert cfg.cache_capacity == 99
    assert cfg.timeline_mark_cycles is True
    assert cfg.log_level == "debug"
    hvd.shutdown()


def test_process_set_registration(hvd):
    ps = hvd.add_process_set([0, 2, 4])
    assert ps.process_set_id is not None and ps.process_set_id > 0
    assert ps.size == 3
    assert ps.rank_in_set(4) == 2
    # duplicate registration returns the existing set
    again = hvd.add_process_set([4, 0, 2])
    assert again.process_set_id == ps.process_set_id
    assert 0 in hvd.get_process_set_ids()
    hvd.remove_process_set(ps)
    assert ps.process_set_id is None


def test_process_set_axis_groups(hvd):
    ps = hvd.add_process_set([1, 3])
    groups = ps.axis_index_groups(8)
    assert [1, 3] in groups
    flat = sorted(r for g in groups for r in g)
    assert flat == list(range(8))  # a full partition of the axis


def test_global_process_set(hvd):
    gps = hvd.global_process_set()
    assert gps.process_set_id == 0
    assert gps.size == 8
    assert gps.axis_index_groups(8) is None


def test_allgather_object(hvd):
    out = hvd.allgather_object({"rank_payload": 42})
    assert isinstance(out, list) and len(out) == hvd.size()
    assert all(o == {"rank_payload": 42} for o in out)
