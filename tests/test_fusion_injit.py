"""Compile-fused eager cycles: in-JIT pack/unpack, donated fusion
buffers, shape-bucketed executor cache, gather-family fusion.

Acceptance tests for the core-runtime rework (ISSUE 1): one fused cycle
dispatches as ONE cached executable (pack + collective + unpack inside
`jax.jit`), the executor cache stays stable under batch-composition
churn via power-of-two bucketing, and broadcast/allgather/reducescatter
groups batch through the same machinery as allreduce — with numerical
parity against the host-pack (pre-rework) path everywhere, process-set
and join-mask cases included.
"""

import numpy as np
import pytest

import horovod_tpu as hvd_mod
from horovod_tpu.ops import fusion as fusion_mod


def rank_major(fn, dtype=np.float32):
    return np.stack([np.asarray(fn(r), dtype=dtype) for r in range(8)])


def _fusion():
    return hvd_mod.common.basics.state().fusion


def _freeze_cycle(fusion):
    fusion.cycle_time_ms = 1e6
    fusion.threshold_bytes = 1 << 30


def _batch_allreduce(hvd, sizes, op=None, **kw):
    op = op if op is not None else hvd_mod.Sum
    handles = [
        hvd.allreduce_async(
            rank_major(lambda r, n=n: np.arange(n, dtype=np.float32) + r),
            op=op,
            name=f"b{i}",
            **kw,
        )
        for i, n in enumerate(sizes)
    ]
    return [h.wait() for h in handles]


# ------------------------------------------------------- single executable


def test_one_executor_invocation_per_fused_flush(hvd):
    """A fused batch — pack, collective, unpack included — is ONE
    executor invocation, and the dispatch path performs zero host-side
    jnp.concatenate once the executable is cached."""
    fusion = _fusion()
    _freeze_cycle(fusion)
    sizes = [3, 5, 2, 7]
    _batch_allreduce(hvd, sizes)  # warm: compiles the fused executable

    d0, inv0 = fusion.dispatches, fusion.cache_hits
    real_concat = fusion_mod.jnp.concatenate
    calls = []

    def spy(*a, **k):
        calls.append(a)
        return real_concat(*a, **k)

    fusion_mod.jnp.concatenate = spy
    try:
        outs = _batch_allreduce(hvd, sizes)
    finally:
        fusion_mod.jnp.concatenate = real_concat
    assert fusion.dispatches == d0 + 1  # one invocation for the batch
    assert fusion.cache_hits == inv0 + 1  # served by the exact tier
    assert calls == []  # pack ran inside the compiled program
    for i, (n, out) in enumerate(zip(sizes, outs)):
        np.testing.assert_allclose(
            np.asarray(out[0]), 8 * np.arange(n) + 28.0
        )


def test_donation_plumbing_and_stats(hvd):
    """donate_argnums reaches the fused executable (observable through
    the donated-bytes counter) without breaking results. On CPU the
    backend ignores donation, which is exactly why `donate` defaults
    off here — this test forces the plumbing on."""
    fusion = _fusion()
    _freeze_cycle(fusion)
    fusion.donate = True
    fusion._executors.clear()
    import warnings

    d0 = fusion.donated_bytes_total
    with warnings.catch_warnings():
        # CPU: "Some donated buffers were not usable" — expected noise
        warnings.simplefilter("ignore")
        outs = _batch_allreduce(hvd, [4, 4])
    assert fusion.donated_bytes_total == d0 + 2 * 8 * 4 * 4
    np.testing.assert_allclose(np.asarray(outs[0][0]), 8 * np.arange(4) + 28.0)


# --------------------------------------------------------- bucketed cache


def test_bucket_reuses_executor_across_compositions(hvd):
    """≥3 distinct batch compositions inside one bucket run on ONE
    bucket-tier program: after the first composition compiles its exact
    executable and the second composition compiles the shared core,
    further compositions add ZERO compiles."""
    fusion = _fusion()
    _freeze_cycle(fusion)
    m0, b0 = fusion.cache_misses, fusion.bucket_hits
    _batch_allreduce(hvd, [2, 3])  # 5 elems → bucket 8: exact compile
    _batch_allreduce(hvd, [1, 4])  # same bucket: core compile, fallback
    _batch_allreduce(hvd, [5])     # fallback, no compile
    outs = _batch_allreduce(hvd, [4, 1])  # fallback, no compile
    assert fusion.cache_misses == m0 + 2
    assert fusion.bucket_hits == b0 + 3
    np.testing.assert_allclose(np.asarray(outs[0][0]), 8 * np.arange(4) + 28.0)


def test_hot_composition_promoted_to_exact_executable(hvd):
    """A composition seen promote_after times graduates from the
    bucket-tier fallback to its own single-dispatch fused executable."""
    fusion = _fusion()
    _freeze_cycle(fusion)
    assert fusion.promote_after == 2
    _batch_allreduce(hvd, [6, 2])  # bucket 8 first seen: exact compile
    _batch_allreduce(hvd, [3, 5])  # sighting 1: core compile + fallback
    p0 = fusion.promotions
    _batch_allreduce(hvd, [3, 5])  # sighting 2: promoted
    assert fusion.promotions == p0 + 1
    h0 = fusion.cache_hits
    outs = _batch_allreduce(hvd, [3, 5])  # exact hit from here on
    assert fusion.cache_hits == h0 + 1
    np.testing.assert_allclose(np.asarray(outs[1][0]), 8 * np.arange(5) + 28.0)


def test_cache_stats_expose_bucketing_counters(hvd):
    fusion = _fusion()
    _freeze_cycle(fusion)
    pad0 = fusion.pad_bytes_total
    # First composition in the bucket rides the EXACT tier, which is
    # keyed on full shapes and therefore packs unpadded — no dead zeros
    # on the wire for a stable job.
    _batch_allreduce(hvd, [5])  # 5 elems → bucket 8, exact tier: no pad
    assert fusion.pad_bytes_total == pad0
    # A second composition in the same bucket rides the padded
    # bucket-tier core: 3 pad elems × 8 rank rows × 4 bytes.
    _batch_allreduce(hvd, [3, 2])
    stats = fusion.cache_stats()
    for key in (
        "hits",
        "misses",
        "evictions",
        "bucket_hits",
        "promotions",
        "recompiles",
        "dispatches",
        "bucket_pad_bytes",
        "donated_bytes",
    ):
        assert key in stats, key
    assert fusion.pad_bytes_total == pad0 + 3 * 8 * 4
    assert fusion.last_cycle_pad_bytes == 3 * 8 * 4
    from horovod_tpu.common.metrics import registry

    snap = registry.snapshot()
    assert snap.get("fusion.bucket_pad_bytes") == float(fusion.pad_bytes_total)
    assert "fusion.last_cycle_dispatches" in snap


def test_bucketing_off_pads_nothing(hvd):
    fusion = _fusion()
    _freeze_cycle(fusion)
    fusion.bucketing = False
    pad0 = fusion.pad_bytes_total
    outs = _batch_allreduce(hvd, [5, 3])
    assert fusion.pad_bytes_total == pad0
    np.testing.assert_allclose(np.asarray(outs[0][0]), 8 * np.arange(5) + 28.0)


def test_capacity_zero_still_fuses_without_caching(hvd):
    fusion = _fusion()
    _freeze_cycle(fusion)
    fusion.cache_capacity = 0
    fusion._executors.clear()
    outs = _batch_allreduce(hvd, [2, 2])
    assert fusion.cache_stats()["size"] == 0
    np.testing.assert_allclose(np.asarray(outs[0][0]), 8 * np.arange(2) + 28.0)


# ------------------------------------------------- parity: in-JIT vs host


def _parity_legs(hvd, run):
    """Run `run(hvd)` under the in-JIT leg and the host-pack leg and
    compare results elementwise."""
    fusion = _fusion()
    _freeze_cycle(fusion)
    fusion.injit_pack = True
    injit = [np.asarray(o) for o in run(hvd)]
    fusion.injit_pack = False
    host = [np.asarray(o) for o in run(hvd)]
    fusion.injit_pack = True
    assert len(injit) == len(host)
    for a, b in zip(injit, host):
        np.testing.assert_allclose(a, b, rtol=1e-6)
    return injit


def test_parity_allreduce_mixed_shapes_and_scales(hvd):
    def run(hvd):
        handles = [
            hvd.allreduce_async(
                rank_major(lambda r: np.full((3, 2), float(r + 1))),
                op=hvd_mod.Sum,
                prescale_factor=0.5,
                postscale_factor=2.0,
            ),
            hvd.allreduce_async(
                rank_major(lambda r: np.arange(7.0) * (r + 1)),
                op=hvd_mod.Average,
            ),
        ]
        return [h.wait() for h in handles]

    outs = _parity_legs(hvd, run)
    np.testing.assert_allclose(outs[0][0], np.full((3, 2), 36.0))


@pytest.mark.parametrize("op_name", ["Min", "Max", "Product"])
def test_parity_minmaxprod_with_bucket_padding(hvd, op_name):
    op = getattr(hvd_mod, op_name)

    def run(hvd):
        # 5 elems → bucket 8: the zero tail must not leak into min/prod
        handles = [
            hvd.allreduce_async(
                rank_major(lambda r: np.arange(1.0, 6.0) + r), op=op
            )
        ]
        return [h.wait() for h in handles]

    _parity_legs(hvd, run)


def test_parity_fused_broadcast_group_vs_serial(hvd):
    fusion = _fusion()
    _freeze_cycle(fusion)
    tensors = [
        rank_major(lambda r, i=i: np.full((2 + i,), float(r * 10 + i)))
        for i in range(3)
    ]

    # fused: all three share one cycle, same root → one batch
    handles = [
        hvd.broadcast_async(t, root_rank=5, name=f"bc{i}")
        for i, t in enumerate(tensors)
    ]
    d0 = fusion.dispatches
    fused = [np.asarray(h.wait()) for h in handles]
    assert fusion.dispatches == d0 + 1

    # serial: threshold 1 byte → every enqueue flushes alone
    fusion.threshold_bytes = 1
    serial = [
        np.asarray(hvd.broadcast(t, root_rank=5)) for t in tensors
    ]
    for a, b in zip(fused, serial):
        np.testing.assert_allclose(a, b)
        np.testing.assert_allclose(a[2], a[5])  # every row = root's row


def test_parity_fused_allgather_group_vs_serial(hvd):
    fusion = _fusion()
    _freeze_cycle(fusion)
    tensors = [
        rank_major(lambda r, i=i: np.full((1 + i, 2), float(r + i)))
        for i in range(3)
    ]
    handles = [
        hvd.allgather_async(t, name=f"ag{i}") for i, t in enumerate(tensors)
    ]
    d0 = fusion.dispatches
    fused = [np.asarray(h.wait()) for h in handles]
    assert fusion.dispatches == d0 + 1  # one executable for the trio

    fusion.threshold_bytes = 1
    serial = [np.asarray(hvd.allgather(t)) for t in tensors]
    for a, b in zip(fused, serial):
        np.testing.assert_allclose(a, b)


def test_parity_fused_reducescatter_group_vs_serial(hvd):
    fusion = _fusion()
    _freeze_cycle(fusion)
    tensors = [
        rank_major(lambda r, i=i: np.arange(16.0 + 8 * i) + r)
        for i in range(2)
    ]
    handles = [
        hvd.reducescatter_async(t, op=hvd_mod.Sum, name=f"rs{i}")
        for i, t in enumerate(tensors)
    ]
    d0 = fusion.dispatches
    fused = [np.asarray(h.wait()) for h in handles]
    assert fusion.dispatches == d0 + 1

    fusion.threshold_bytes = 1
    serial = [
        np.asarray(hvd.reducescatter(t, op=hvd_mod.Sum)) for t in tensors
    ]
    for a, b in zip(fused, serial):
        np.testing.assert_allclose(a, b)


def test_parity_process_set_gather_family(hvd):
    ps = hvd.add_process_set([1, 3, 5])

    def run(hvd):
        ag = hvd.allgather_async(
            rank_major(lambda r: np.full((2,), float(r))), process_set=ps
        )
        rs = hvd.reducescatter_async(
            rank_major(lambda r: np.arange(6.0) + r),
            op=hvd_mod.Sum,
            process_set=ps,
        )
        bc = hvd.broadcast_async(
            rank_major(lambda r: np.full((3,), float(r))),
            root_rank=3,
            process_set=ps,
        )
        return [h.wait() for h in (ag, rs, bc)]

    ag, rs, bc = _parity_legs(hvd, run)
    # members gather member rows; non-members receive zeros
    np.testing.assert_allclose(ag[1][0], np.full(2, 1.0))
    np.testing.assert_allclose(ag[0], np.zeros_like(ag[0]))
    # broadcast: members take root 3's row, non-members keep their own
    np.testing.assert_allclose(bc[5], np.full(3, 3.0))
    np.testing.assert_allclose(bc[2], np.full(3, 2.0))


def test_parity_join_mask_and_process_set_allreduce(hvd):
    ps = hvd.add_process_set([0, 1, 2, 3])

    def run(hvd):
        outs = []
        with hvd.join_ranks([2]):
            outs.append(
                hvd.allreduce(
                    rank_major(lambda r: np.full((4,), float(r))),
                    op=hvd_mod.Average,
                    process_set=ps,
                )
            )
        outs.append(
            hvd.allreduce(
                rank_major(lambda r: np.full((3,), float(r + 1))),
                op=hvd_mod.Adasum,
                process_set=ps,
            )
        )
        with hvd.join_ranks([1]):
            outs.append(
                hvd.allreduce(
                    rank_major(lambda r: np.full((3,), float(r + 1))),
                    op=hvd_mod.Adasum,
                    process_set=ps,
                )
            )
        return outs

    avg, adasum, adasum_join = _parity_legs(hvd, run)
    # joined rank 2 excluded: mean of {0,1,3} = 4/3 for members
    np.testing.assert_allclose(avg[0], np.full(4, 4.0 / 3.0), rtol=1e-6)
    np.testing.assert_allclose(avg[6], np.full(4, 6.0))  # non-member
    np.testing.assert_allclose(adasum[7], np.full(3, 8.0))  # non-member


def test_fused_engine_survives_composition_churn_correctly(hvd):
    """Regression: drifting compositions (the bucket-fallback path) and
    repeated compositions (the exact path) interleave with identical
    numerics."""
    fusion = _fusion()
    _freeze_cycle(fusion)
    rng = np.random.default_rng(7)
    for trial in range(6):
        sizes = rng.integers(1, 9, size=rng.integers(1, 4)).tolist()
        outs = _batch_allreduce(hvd, sizes)
        for n, out in zip(sizes, outs):
            np.testing.assert_allclose(
                np.asarray(out[0]), 8 * np.arange(n) + 28.0, rtol=1e-6
            )
