"""Virtual-mesh sanity check: ring-attention work scales with hop
count (VERDICT r4 item 8's simulation leg).

Fixes the LOCAL sequence shard (t_local) and grows the ring (sp):
each device runs sp hops of t_local-sized block attention, so TOTAL
simulated compute grows ~sp² (sp devices x sp hops) — and on the
CPU mesh all "devices" share the same host cores, so WALL time should
track that sp² total, not the flat per-hop time real chips would show.
Observed (2026-07-31 capture): dense ring 3.9 -> 207.8 ms going sp
1 -> 8 (53x vs the 64x ideal — sublinear from host-thread overlap);
flash ring 3.66 -> 134.2 ms. That's the hop-count structure scaling as
designed, with the flash engine uniformly cheaper per hop. CPU-
simulated (sim_ prefix: logic validation, quarantined from the
stale-artifact fallback; per-hop flatness and ICI overlap need real
multi-chip).

Writes bench_results/sim_ring_hops.json: one line per (engine, sp)
with ms/step and ms/hop.
"""

import json
import os
import sys
import time

os.environ.setdefault(
    "XLA_FLAGS", "--xla_force_host_platform_device_count=8"
)

import jax

jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from horovod_tpu.parallel.ring_attention import (  # noqa: E402
    ring_attention,
    ring_flash_attention,
)

_SIM_NOTE = "logic-validation only (CPU simulation)"


def main():
    # setdefault above is a no-op when the caller exported XLA_FLAGS —
    # refuse to record hop counts against a shrunken mesh
    if len(jax.devices()) < 8:
        raise SystemExit(
            f"need 8 virtual CPU devices, have {len(jax.devices())} — "
            "run with XLA_FLAGS=--xla_force_host_platform_device_count=8"
        )
    b, t_local, h, d = 1, 256, 4, 64
    iters = int(os.environ.get("BENCH_ITERS", "5"))
    rng = np.random.default_rng(0)
    out_path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "bench_results",
        "sim_ring_hops.json",
    )
    lines = []
    for engine, fn in (
        ("ring_dense", ring_attention),
        ("ring_flash", ring_flash_attention),
    ):
        for sp in (1, 2, 4, 8):
            mesh = Mesh(np.asarray(jax.devices()[:sp]), ("sp",))
            # global batch of shards: [sp, b, t_local, h, d] -> each
            # device holds [b, t_local, h, d]
            qkv = [
                jnp.asarray(
                    rng.normal(size=(sp * b, t_local, h, d)),
                    jnp.float32,
                )
                for _ in range(3)
            ]

            @jax.jit
            @jax.shard_map(
                mesh=mesh,
                in_specs=(P("sp"), P("sp"), P("sp")),
                out_specs=P("sp"),
                check_vma=False,
            )
            def step(q, k, v):
                return fn(q, k, v, axis_name="sp", causal=True)

            out = step(*qkv)
            jax.block_until_ready(out)
            t0 = time.perf_counter()
            for _ in range(iters):
                out = step(*qkv)
            jax.block_until_ready(out)
            ms = (time.perf_counter() - t0) / iters * 1e3
            line = {
                "metric": "sim_ring_hops",
                "engine": engine,
                "sp": sp,
                "t_local": t_local,
                "value": round(ms, 2),
                "unit": "ms",
                "ms_per_hop": round(ms / sp, 2),
                "platform": "cpu",
                "note": _SIM_NOTE,
            }
            lines.append(line)
            print(json.dumps(line), flush=True)
    with open(out_path, "w") as f:
        for line in lines:
            f.write(json.dumps(line) + "\n")


if __name__ == "__main__":
    main()
