"""Elastic training — fault tolerance and dynamic worker membership.

TPU-native rebuild of Elastic Horovod (ref: horovod/runner/elastic/* +
horovod/common/elastic.py + horovod/torch/elastic/ [V] — SURVEY.md §2.5,
§3.4; empty mount, structural citations).

Semantic divergence, by design (SURVEY.md §5.3): on GPU clusters the
reference resizes the world in place by rebuilding NCCL communicators.
A TPU slice has fixed ICI topology, so "elastic" here means *slice
re-acquisition*: on preemption or membership change the driver restarts
workers on the surviving/new hosts and the training loop resumes from
the last committed ``State``. The user-facing API is unchanged:

    import horovod_tpu as hvd
    from horovod_tpu import elastic

    state = elastic.JaxState(params=params, opt_state=opt_state, step=0)

    @elastic.run
    def train(state):
        while state.step < total_steps:
            ...
            state.step += 1
            if state.step % 100 == 0:
                state.commit()

    train(state)
"""

from .discovery import (  # noqa: F401
    FixedHosts,
    HostDiscovery,
    HostDiscoveryScript,
    HostManager,
)
from .driver import ElasticDriver, SlotAssignment  # noqa: F401
from .state import JaxState, ObjectState, State  # noqa: F401
from .worker import (  # noqa: F401
    WorkerNotificationManager,
    WorkerNotificationService,
    expert_loads,
    notification_manager,
    publish_expert_load,
    rebalance_weight,
    rebalance_weights,
    run,
)
