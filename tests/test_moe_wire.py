"""Expert wire (PR 12): quantized + two-level MoE alltoall, moe_ffn
edge cases against a host oracle, the capacity-factor autotuner,
persistent tuner state, the eager-alltoall observability fix, the
expert-load KV plumbing, and MoE decode in the serving plane.

Bit-exactness methodology follows tests/test_hier_wire.py: the
hierarchical alltoall is a pure permutation for exact wires, so
fp32/int32 equality vs the flat ``lax.all_to_all`` is asserted
BITWISE on arbitrary data (no reassociation exists to excuse); the
int8 wire is bounded in quanta of the per-block absmax, with
self-slice blocks bit-exact (they never cross the lossy hop).
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from horovod_tpu import analysis
from horovod_tpu.common.compat import shard_map
from horovod_tpu.common import topology as topo_mod
from horovod_tpu.ops import traced
from horovod_tpu.parallel.moe import MoEParams, init_moe_params, moe_ffn

STAGES_84 = topo_mod.hierarchical_stage_groups(8, 4)
STAGES_82 = topo_mod.hierarchical_stage_groups(8, 2)


def _mesh(axis="ep"):
    return Mesh(np.asarray(jax.devices()[:8]), (axis,))


def _sm(fn, ins=P("ep"), outs=P("ep"), axis="ep"):
    return jax.jit(
        shard_map(
            fn,
            mesh=_mesh(axis),
            in_specs=ins,
            out_specs=outs,
            check_vma=False,
        )
    )


def _flat_a2a(axis="ep"):
    return _sm(
        lambda v: jax.lax.all_to_all(v[0], axis, 0, 0, tiled=True)[None],
        axis=axis,
    )


def _a2a_group_sizes(lowered):
    """Replica-group row lengths of every all_to_all in a lowered
    module (the monolithic-flat-alltoall detector) — via the shared
    ``horovod_tpu.analysis`` parser, not regex."""
    return analysis.parse_module(lowered).group_sizes("all_to_all")


# ---------------------------------------------------- wire primitives


class TestQuantizedAlltoall:
    def test_parity_and_pad_exclusion(self, hvd):
        rng = np.random.default_rng(0)
        x = rng.normal(size=(8, 8, 4, 64)).astype(np.float32)
        x[:, :, 3, :] = 0.0  # an empty (dropped/pad) dispatch slot
        q = np.asarray(
            _sm(
                lambda v: traced.quantized_alltoall(
                    v[0], axis_name="ep", seed=1, block_size=32
                )[None]
            )(x)
        )
        f = np.asarray(_flat_a2a()(x))
        # pad slots arrive as exact zeros — excluded from every scale
        np.testing.assert_array_equal(q[:, :, 3, :], 0.0)
        bound = 2.5 * np.abs(f).max() / 127.0
        assert np.abs(q - f).max() <= bound
        # unbiased-ish: the mean error is far below one quantum
        assert abs((q - f).mean()) < bound / 20

    def test_groups_restrict_exchange(self, hvd):
        rng = np.random.default_rng(1)
        groups = STAGES_84[1]  # [[0,4],[1,5],[2,6],[3,7]]
        x = rng.normal(size=(8, 2, 3, 32)).astype(np.float32)
        q = np.asarray(
            _sm(
                lambda v: traced.quantized_alltoall(
                    v[0], axis_name="ep", seed=2, block_size=16,
                    groups=groups,
                )[None]
            )(x)
        )
        f = np.asarray(
            _sm(
                lambda v: jax.lax.all_to_all(
                    v[0], "ep", 0, 0, tiled=True,
                    axis_index_groups=groups,
                )[None]
            )(x)
        )
        assert np.abs(q - f).max() <= 2.5 * np.abs(f).max() / 127.0

    def test_block_wider_than_row_clamps(self, hvd):
        """block_size > d must clamp to the row width — otherwise the
        zero-pad up to the block would make the int8 wire move MORE
        bytes than fp32 (the review-caught default-block-512 trap)."""
        rng = np.random.default_rng(12)
        x = rng.normal(size=(8, 8, 2, 64)).astype(np.float32)

        def run(bs):
            return np.asarray(
                _sm(
                    lambda v: traced.quantized_alltoall(
                        v[0], axis_name="ep", seed=4, block_size=bs
                    )[None]
                )(x)
            )

        np.testing.assert_array_equal(run(512), run(64))

    def test_shape_validation(self, hvd):
        with pytest.raises(ValueError, match="slots"):
            _sm(
                lambda v: traced.quantized_alltoall(
                    v[0].reshape(4, -1)[None][0], axis_name="ep"
                )[None]
            )(np.zeros((8, 4, 2, 8), np.float32))


class TestHierarchicalAlltoall:
    @pytest.mark.parametrize("stages", [STAGES_84, STAGES_82])
    def test_fp32_bitexact_vs_flat(self, hvd, stages):
        rng = np.random.default_rng(2)
        x = rng.normal(size=(8, 8, 4, 16)).astype(np.float32)
        hier = np.asarray(
            _sm(
                lambda v: traced.hierarchical_alltoall(
                    v[0], axis_name="ep", stages=stages
                )[None]
            )(x)
        )
        np.testing.assert_array_equal(hier, np.asarray(_flat_a2a()(x)))

    def test_int32_map_bitexact(self, hvd):
        rng = np.random.default_rng(3)
        xi = rng.integers(-1, 7, size=(8, 8, 4, 1)).astype(np.int32)
        hier = np.asarray(
            _sm(
                lambda v: traced.hierarchical_alltoall(
                    v[0], axis_name="ep", stages=STAGES_84,
                    intra_wire="bf16", inter_wire="int8",  # ignored: int
                )[None]
            )(xi)
        )
        np.testing.assert_array_equal(hier, np.asarray(_flat_a2a()(xi)))

    @pytest.mark.parametrize("inter_wire", ["int8", "bf16"])
    def test_lossy_inter_spares_intra_blocks(self, hvd, inter_wire):
        rng = np.random.default_rng(4)
        x = rng.normal(size=(8, 8, 4, 64)).astype(np.float32)
        out = np.asarray(
            _sm(
                lambda v: traced.hierarchical_alltoall(
                    v[0], axis_name="ep", stages=STAGES_84,
                    inter_wire=inter_wire, seed=5, block_size=32,
                )[None]
            )(x)
        )
        f = np.asarray(_flat_a2a()(x))
        L = 4
        for r in range(8):
            h = r // L
            sl = slice(h * L, (h + 1) * L)
            # blocks from same-slice sources never crossed DCN: exact
            np.testing.assert_array_equal(out[r][sl], f[r][sl])
        bound = (
            2.5 * np.abs(f).max() / 127.0
            if inter_wire == "int8"
            else 0.01 * np.abs(f).max()
        )
        assert np.abs(out - f).max() <= bound

    def test_lowered_no_monolithic_alltoall(self, hvd):
        x = np.zeros((8, 8, 4, 64), np.float32)
        low = _sm(
            lambda v: traced.hierarchical_alltoall(
                v[0], axis_name="ep", stages=STAGES_84,
                inter_wire="int8", block_size=32,
            )[None]
        ).lower(jnp.asarray(x))
        sizes = _a2a_group_sizes(low)
        assert sizes, "expected group-limited all_to_all ops"
        assert all(s < 8 for s in sizes), sizes

    def test_validation(self, hvd):
        x = np.zeros((8, 8, 4, 8), np.float32)
        with pytest.raises(ValueError, match="stages"):
            _sm(
                lambda v: traced.hierarchical_alltoall(
                    v[0], axis_name="ep"
                )[None]
            )(x)


# ------------------------------------------------------- moe_ffn core


def _full_params(rng_key, d=16, f=32, e_total=16):
    return init_moe_params(rng_key, d, f, e_total, e_total)


_PARAM_SPEC = MoEParams(
    router=P(), w1=P("ep"), b1=P("ep"), w2=P("ep"), b2=P("ep")
)


def _run_moe(params, x, stats=False, **kw):
    def body(p, v):
        out = moe_ffn(p, v[0], return_stats=stats, **kw)
        if stats:
            o, s = out
            return o[None], s
        return out[None]

    outs = (P("ep"), P()) if stats else P("ep")
    return _sm(body, (_PARAM_SPEC, P("ep")), outs)(params, x)


def _oracle(params, x, capacity_factor, member_ranks=None, live=None):
    """Host top-1 switch router + per-token expert FFN: routing from
    fp32 logits (argmax of logits == argmax of softmax), gate from the
    fp32 softmax, capacity filled in token order per (source, dest)
    pair, dropped tokens output zero. Returns (out, hist, dropped)."""
    ep, t, d = x.shape
    e_total = params.router.shape[1]
    e_local = e_total // ep
    k = ep if member_ranks is None else len(member_ranks)
    members = (
        list(range(ep)) if member_ranks is None else list(member_ranks)
    )
    capacity = int(max(1, round(capacity_factor * t / k)))
    out = np.zeros_like(x)
    hist = np.zeros(e_total)
    dropped = 0
    router = np.asarray(params.router, np.float32)
    for r in range(ep):
        if live is not None and not live[r]:
            continue
        if member_ranks is not None and r not in members:
            continue
        logits = x[r].astype(np.float32) @ router
        if member_ranks is not None:
            allowed = np.isin(np.arange(e_total) // e_local, members)
            logits = np.where(allowed[None], logits, -np.inf)
        m = logits.max(axis=1, keepdims=True)
        pr = np.exp(logits - m)
        pr /= pr.sum(axis=1, keepdims=True)
        e = logits.argmax(axis=1)
        fills = {}
        for i in range(t):
            dest = e[i] // e_local
            pos = fills.get(dest, 0)
            fills[dest] = pos + 1
            if pos >= capacity:
                dropped += 1
                continue
            hist[e[i]] += 1
            xe = x[r, i].astype(np.float32)
            h = jax.nn.gelu(
                xe @ np.asarray(params.w1[e[i]], np.float32)
                + np.asarray(params.b1[e[i]], np.float32)
            )
            y = np.asarray(h, np.float32) @ np.asarray(
                params.w2[e[i]], np.float32
            ) + np.asarray(params.b2[e[i]], np.float32)
            out[r, i] = pr[i, e[i]] * y
    return out, hist, dropped


class TestMoEFFN:
    @pytest.mark.parametrize("t_local", [8, 10])  # 10: not % ep == 0
    def test_host_oracle_gate_and_output(self, hvd, t_local):
        rng = np.random.default_rng(5)
        params = _full_params(jax.random.PRNGKey(0))
        x = rng.normal(size=(8, t_local, 16)).astype(np.float32)
        out, st = _run_moe(
            params, x, stats=True, capacity_factor=2.0, wire="fp32"
        )
        want, hist, dropped = _oracle(params, x, 2.0)
        np.testing.assert_allclose(
            np.asarray(out), want, rtol=2e-4, atol=2e-5
        )
        np.testing.assert_array_equal(np.asarray(st.expert_tokens), hist)
        assert float(st.dropped) == dropped
        assert float(st.total) == 8 * t_local

    def test_capacity_overflow_drop_parity(self, hvd):
        """Dropped tokens output EXACT zeros (the residual connection
        carries them), and the drop counter matches the oracle."""
        rng = np.random.default_rng(6)
        params = _full_params(jax.random.PRNGKey(1))
        x = rng.normal(size=(8, 12, 16)).astype(np.float32)
        out, st = _run_moe(
            params, x, stats=True, capacity_factor=0.5, wire="fp32"
        )
        want, hist, dropped = _oracle(params, x, 0.5)
        assert dropped > 0  # the gate actually bites at cf=0.5
        out = np.asarray(out)
        drop_rows = np.all(want == 0.0, axis=2)
        np.testing.assert_array_equal(out[drop_rows], 0.0)
        assert float(st.dropped) == dropped
        np.testing.assert_array_equal(np.asarray(st.expert_tokens), hist)

    def test_routing_identical_across_wires(self, hvd):
        """The acceptance gate: flat-fp32 vs hier-int8 route the SAME
        tokens to the SAME experts (stats bitwise equal) and outputs
        agree within the documented quanta bound (docs/perf.md)."""
        rng = np.random.default_rng(7)
        params = _full_params(jax.random.PRNGKey(2))
        x = rng.normal(size=(8, 8, 16)).astype(np.float32)
        base, st0 = _run_moe(
            params, x, stats=True, capacity_factor=1.25, wire="fp32"
        )
        out8, st8 = _run_moe(
            params, x, stats=True, capacity_factor=1.25,
            wire="int8", hier=STAGES_84, seed=3,
        )
        np.testing.assert_array_equal(
            np.asarray(st0.expert_tokens), np.asarray(st8.expert_tokens)
        )
        assert float(st0.dropped) == float(st8.dropped)
        base, out8 = np.asarray(base), np.asarray(out8)
        # two lossy hops (dispatch + return) on inter-slice tokens:
        # a few quanta through a Lipschitz FFN — bounded loosely but
        # far below the signal scale
        scale = np.abs(base).max()
        assert np.abs(out8 - base).max() <= 0.15 * scale
        assert np.abs(out8 - base).mean() <= 0.01 * scale

    def test_hier_fp32_bitexact_vs_flat(self, hvd):
        rng = np.random.default_rng(8)
        params = _full_params(jax.random.PRNGKey(3))
        x = rng.normal(size=(8, 8, 16)).astype(np.float32)
        a = _run_moe(params, x, capacity_factor=1.25, wire="fp32")
        b = _run_moe(
            params, x, capacity_factor=1.25, wire="fp32",
            hier=STAGES_84,
        )
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_join_mask(self, hvd):
        """A masked-out rank contributes no tokens and outputs zeros;
        live ranks are bit-identical to the unmasked run (their
        routing and capacity fills are local)."""
        rng = np.random.default_rng(9)
        params = _full_params(jax.random.PRNGKey(4))
        x = rng.normal(size=(8, 6, 16)).astype(np.float32)
        mask = np.array([True] * 7 + [False])
        base = np.asarray(_run_moe(params, x, capacity_factor=2.0))
        out, st = _run_moe(
            params, x, stats=True, capacity_factor=2.0, mask=mask
        )
        out = np.asarray(out)
        np.testing.assert_array_equal(out[7], 0.0)
        np.testing.assert_array_equal(out[:7], base[:7])
        assert float(st.total) == 7 * 6

    def test_process_set(self, hvd):
        ps = hvd.add_process_set([0, 2, 4, 5])
        rng = np.random.default_rng(10)
        params = _full_params(jax.random.PRNGKey(5))
        x = rng.normal(size=(8, 8, 16)).astype(np.float32)
        out, st = _run_moe(
            params, x, stats=True, capacity_factor=2.0,
            process_set=ps,
        )
        out = np.asarray(out)
        for r in (1, 3, 6, 7):
            np.testing.assert_array_equal(out[r], 0.0)
        want, hist, dropped = _oracle(
            params, x, 2.0, member_ranks=[0, 2, 4, 5]
        )
        np.testing.assert_allclose(out, want, rtol=2e-4, atol=2e-5)
        np.testing.assert_array_equal(np.asarray(st.expert_tokens), hist)
        # experts used all belong to member ranks
        used = np.nonzero(np.asarray(st.expert_tokens))[0]
        assert set(used // 2) <= {0, 2, 4, 5}
        hvd.remove_process_set(ps)

    def test_lowered_hier_int8_structure(self, hvd):
        """The compiled MoE step's dispatch is two-level: every
        all_to_all is group-limited (intra or inter), none spans the
        world — the acceptance criterion's structural gate."""
        params = _full_params(jax.random.PRNGKey(6))
        x = np.zeros((8, 8, 16), np.float32)

        def body(p, v):
            return moe_ffn(
                p, v[0], capacity_factor=1.25, wire="int8",
                hier=STAGES_84,
            )[None]

        low = _sm(body, (_PARAM_SPEC, P("ep")), P("ep")).lower(
            params, jnp.asarray(x)
        )
        sizes = _a2a_group_sizes(low)
        assert sizes, "expected group-limited all_to_all ops"
        assert all(s < 8 for s in sizes), sizes

    def test_int8_wire_differentiates_straight_through(self, hvd):
        """grad through the int8 wire: the custom_vjp routes the
        cotangent through the exact inverse exchange — gradients are
        finite, nonzero, and close to the fp32 wire's."""
        params = _full_params(jax.random.PRNGKey(7))
        rng = np.random.default_rng(11)
        x = rng.normal(size=(8, 8, 16)).astype(np.float32)

        def make(wire, hier):
            def body(p, v):
                def loss(vv):
                    o = moe_ffn(
                        p, vv, capacity_factor=2.0, wire=wire,
                        hier=hier, seed=2,
                    )
                    return jnp.sum(o * o)

                l, g = jax.value_and_grad(loss)(v[0])
                return jax.lax.psum(l, "ep")[None], g[None]

            return _sm(body, (_PARAM_SPEC, P("ep")), (P("ep"), P("ep")))

        _, g_fp = make("fp32", None)(params, x)
        _, g_q = make("int8", STAGES_84)(params, x)
        g_fp, g_q = np.asarray(g_fp), np.asarray(g_q)
        assert np.isfinite(g_q).all()
        assert np.abs(g_q).max() > 0
        scale = np.abs(g_fp).max()
        assert np.abs(g_q - g_fp).max() <= 0.25 * scale


# ------------------------------------------- capacity-factor autotune


class TestCapacityTuner:
    def _feed(self, tuner, key, cand, drop_frac, seconds):
        hist = [10.0, 10.0, 40.0, 10.0]
        total = 100.0
        tuner.observe_load(
            key, cand, hist, dropped=total * drop_frac, total=total,
            seconds=seconds,
        )

    def test_explore_then_exploit_by_goodput(self):
        from horovod_tpu.common.autotune import CapacityTuner

        t = CapacityTuner(trials=2, candidates=(1.0, 2.0))
        key = ("moe", 64)
        seen = [t.choose(key) for _ in range(1)]
        # explore: feed both candidates their trials; 1.0 keeps fewer
        # tokens but is MUCH faster -> higher kept-token goodput
        for _ in range(2):
            self._feed(t, key, 1.0, drop_frac=0.1, seconds=0.1)
            self._feed(t, key, 2.0, drop_frac=0.0, seconds=1.0)
        assert t.choose(key) == 1.0
        assert seen[0] in (1.0, 2.0)

    def test_drop_rate_prior_overrides_goodput(self):
        from horovod_tpu.common.autotune import CapacityTuner

        t = CapacityTuner(
            trials=1, candidates=(1.0, 2.0), max_drop_rate=0.2
        )
        key = ("moe", 64)
        # 1.0 is faster but drops 40% — past the bound, never exploited
        self._feed(t, key, 1.0, drop_frac=0.4, seconds=0.1)
        self._feed(t, key, 2.0, drop_frac=0.0, seconds=1.0)
        assert t.choose(key) == 2.0
        assert t.drop_rate(key, 1.0) == pytest.approx(0.4)

    def test_all_over_bound_takes_largest(self):
        from horovod_tpu.common.autotune import CapacityTuner

        t = CapacityTuner(
            trials=1, candidates=(1.0, 1.5), max_drop_rate=0.05
        )
        key = ("k",)
        self._feed(t, key, 1.0, drop_frac=0.5, seconds=0.1)
        self._feed(t, key, 1.5, drop_frac=0.3, seconds=0.1)
        assert t.choose(key) == 1.5

    def test_imbalance_meter(self):
        from horovod_tpu.common.autotune import CapacityTuner

        t = CapacityTuner(trials=1)
        key = ("k",)
        t.observe_load(key, 1.25, [10.0, 10.0, 40.0, 10.0], 30.0, 100.0)
        # hottest expert 40 vs mean kept 70/4
        assert t.imbalance(key, 1.25) == pytest.approx(40.0 / 17.5)

    def test_state_roundtrip(self, tmp_path, monkeypatch):
        from horovod_tpu.common.autotune import (
            CapacityTuner,
            persist,
            warm_start,
        )

        monkeypatch.setenv("HOROVOD_TUNER_CACHE", str(tmp_path))
        t = CapacityTuner(trials=1, candidates=(1.0, 2.0))
        key = ("moe", 64)
        self._feed(t, key, 1.0, drop_frac=0.1, seconds=0.1)
        self._feed(t, key, 2.0, drop_frac=0.0, seconds=1.0)
        path = persist(t, "capacity")
        assert path and os.path.exists(path)
        t2 = CapacityTuner(trials=1, candidates=(1.0, 2.0))
        assert warm_start(t2, "capacity") > 0
        # warm-started: no candidate needs a trial, drop ledger intact
        assert not t2.needs_trial(key, 1.0)
        assert not t2.needs_trial(key, 2.0)
        assert t2.drop_rate(key, 1.0) == pytest.approx(0.1)
        assert t2.choose(key) == t.choose(key)


class TestTunerPersistence:
    def test_wire_tuner_roundtrip_skips_trials(self, tmp_path, monkeypatch):
        from horovod_tpu.common.autotune import (
            WireTuner,
            persist,
            warm_start,
        )

        monkeypatch.setenv("HOROVOD_TUNER_CACHE", str(tmp_path))
        t = WireTuner(min_int8_bytes=0, trials=2)
        key = ("alltoall", 1 << 20, "float32", "inter")
        for cand, secs in (("fp32", 1.0), ("bf16", 0.6), ("int8", 0.3)):
            for _ in range(2):
                t.record(key, cand, 1 << 20, secs)
        assert persist(t, "wire") is not None
        t2 = WireTuner(min_int8_bytes=0, trials=2)
        assert warm_start(t2, "wire") == 3
        for cand in ("fp32", "bf16", "int8"):
            assert not t2.needs_trial(key, cand)
        assert t2.choose(key, payload_bytes=1 << 20) == "int8"

    def test_live_observations_beat_disk(self, tmp_path, monkeypatch):
        from horovod_tpu.common.autotune import (
            WireTuner,
            persist,
            warm_start,
        )

        monkeypatch.setenv("HOROVOD_TUNER_CACHE", str(tmp_path))
        t = WireTuner(min_int8_bytes=0, trials=1)
        t.record(("k",), "fp32", 100, 1.0)
        persist(t, "wire")
        t2 = WireTuner(min_int8_bytes=0, trials=1)
        t2.record(("k",), "fp32", 999, 1.0)  # live entry
        warm_start(t2, "wire")
        assert t2.goodput(("k",), "fp32") == pytest.approx(999.0)

    def test_persist_merges_with_disk(self, tmp_path, monkeypatch):
        """Two tuners legitimately share the ``wire`` file (fused
        allreduce keys + trace-time alltoall keys); the second atexit
        writer must MERGE, not clobber, the first's observations."""
        from horovod_tpu.common.autotune import (
            WireTuner,
            persist,
            warm_start,
        )

        monkeypatch.setenv("HOROVOD_TUNER_CACHE", str(tmp_path))
        a = WireTuner(min_int8_bytes=0, trials=1)
        a.record(("allreduce", 4096, "float32"), "bf16", 4096, 0.1)
        persist(a, "wire")
        b = WireTuner(min_int8_bytes=0, trials=1)
        b.record(("alltoall", 4096, "float32", "inter"), "int8", 4096, 0.1)
        persist(b, "wire")  # never saw a's entry
        c = WireTuner(min_int8_bytes=0, trials=1)
        assert warm_start(c, "wire") == 2
        assert not c.needs_trial(("allreduce", 4096, "float32"), "bf16")
        assert not c.needs_trial(
            ("alltoall", 4096, "float32", "inter"), "int8"
        )

    def test_overlap_tuner_persistence_parity(
        self, tmp_path, monkeypatch
    ):
        """PR 14 satellite (ROADMAP item 1a slice): the OverlapTuner
        rides the same warm_start/persist machinery as the WireTuner —
        roundtrip skips trials, and persist MERGES with disk (the
        WireTuner merge test, overlap edition)."""
        from horovod_tpu.common.autotune import (
            OverlapTuner,
            persist,
            warm_start,
        )

        monkeypatch.setenv("HOROVOD_TUNER_CACHE", str(tmp_path))
        a = OverlapTuner(min_bucket_bytes=0, trials=1, candidates=(1, 4))
        a.record(("step",), 1, 1 << 20, 2.0)
        a.record(("step",), 4, 1 << 20, 1.0)
        assert persist(a, "overlap") is not None
        b = OverlapTuner(min_bucket_bytes=0, trials=1, candidates=(1, 8))
        b.record(("step",), 8, 1 << 20, 0.5)
        persist(b, "overlap")  # never saw a's entries: must merge
        c = OverlapTuner(
            min_bucket_bytes=0, trials=1, candidates=(1, 4, 8)
        )
        assert warm_start(c, "overlap") == 3
        for cand in (1, 4, 8):
            assert not c.needs_trial(("step",), cand)
        assert c.choose(("step",), 1 << 20) == 8

    def test_capacity_tuner_merge_on_persist(
        self, tmp_path, monkeypatch
    ):
        """Capacity edition of the merge test — including the load
        ledger (drop-rate prior survives the merge)."""
        from horovod_tpu.common.autotune import (
            CapacityTuner,
            persist,
            warm_start,
        )

        monkeypatch.setenv("HOROVOD_TUNER_CACHE", str(tmp_path))
        a = CapacityTuner(trials=1, candidates=(1.0, 2.0))
        a.observe_load(("m",), 1.0, [50.0, 50.0], 30.0, 130.0, seconds=0.1)
        persist(a, "capacity")
        b = CapacityTuner(trials=1, candidates=(1.0, 2.0))
        b.observe_load(("m",), 2.0, [65.0, 65.0], 0.0, 130.0, seconds=0.2)
        persist(b, "capacity")
        c = CapacityTuner(trials=1, candidates=(1.0, 2.0))
        assert warm_start(c, "capacity") == 2
        assert not c.needs_trial(("m",), 1.0)
        assert not c.needs_trial(("m",), 2.0)
        assert c.drop_rate(("m",), 1.0) == pytest.approx(30.0 / 130.0)

    def test_shared_accessors_warm_start_and_register(
        self, tmp_path, monkeypatch
    ):
        """shared_overlap_tuner / shared_capacity_tuner warm-start
        from the fingerprinted cache on first use and are registered
        for persist-at-exit (the FusionManager's WireTuner contract,
        extended)."""
        from horovod_tpu.common import autotune
        from horovod_tpu.common.autotune import (
            CapacityTuner,
            OverlapTuner,
            persist,
        )

        monkeypatch.setenv("HOROVOD_TUNER_CACHE", str(tmp_path))
        seed_o = OverlapTuner(min_bucket_bytes=0, trials=1)
        seed_o.record(("k",), 4, 100, 1.0)
        persist(seed_o, "overlap")
        seed_c = CapacityTuner(trials=1)
        seed_c.record(("k",), 1.25, 100, 1.0)
        persist(seed_c, "capacity")
        autotune.reset_shared_tuners()
        try:
            ot = autotune.shared_overlap_tuner(
                min_bucket_bytes=0, trials=1
            )
            assert not ot.needs_trial(("k",), 4)
            assert autotune.shared_overlap_tuner() is ot
            ct = autotune.shared_capacity_tuner(trials=1)
            assert not ct.needs_trial(("k",), 1.25)
            registered = {
                name for _, (_, name) in autotune._persist_registry
            }
            assert {"overlap", "capacity"} <= registered
        finally:
            autotune.reset_shared_tuners()

    def test_corrupt_cache_reads_zero(self, tmp_path, monkeypatch):
        from horovod_tpu.common.autotune import (
            WireTuner,
            tuner_cache_path,
            warm_start,
        )

        monkeypatch.setenv("HOROVOD_TUNER_CACHE", str(tmp_path))
        path = tuner_cache_path("wire")
        with open(path, "w") as f:
            f.write("\xff not json {")
        assert warm_start(WireTuner(), "wire") == 0

    def test_no_cache_dir_is_noop(self, monkeypatch):
        from horovod_tpu.common.autotune import (
            WireTuner,
            persist,
            tuner_cache_path,
            warm_start,
        )

        monkeypatch.delenv("HOROVOD_TUNER_CACHE", raising=False)
        assert tuner_cache_path("wire") is None
        assert persist(WireTuner(), "wire") is None
        assert warm_start(WireTuner(), "wire") == 0

    def test_fingerprint_pins_topology(self, hvd):
        from horovod_tpu.common.autotune import topology_fingerprint

        fp = topology_fingerprint()
        assert fp.startswith("w8-") and fp.endswith("-cpu")

    def test_fusion_manager_warm_starts(self, tmp_path, monkeypatch, hvd):
        from horovod_tpu.common.autotune import WireTuner, persist
        from horovod_tpu.ops.fusion import FusionManager

        monkeypatch.setenv("HOROVOD_TUNER_CACHE", str(tmp_path))
        seed_tuner = WireTuner(trials=3)
        key = ("allreduce", 4096, "float32")
        for _ in range(3):
            seed_tuner.record(key, "bf16", 4096, 0.1)
            seed_tuner.record(key, "fp32", 4096, 0.5)
            seed_tuner.record(key, "int8", 4096, 0.9)
        persist(seed_tuner, "wire")
        mgr = FusionManager(
            hvd.mesh(), threshold_bytes=1 << 20, cycle_time_ms=1.0,
            wire="auto",
        )
        assert mgr.wire_tuner is not None
        assert not mgr.wire_tuner.needs_trial(key, "bf16")
        assert mgr.wire_tuner.choose(key, payload_bytes=4096) == "bf16"


# --------------------------------------------- alltoall observability


class TestAlltoallObservability:
    def test_eager_alltoall_reaches_registry(self, hvd):
        from horovod_tpu.common import basics
        from horovod_tpu.common.metrics import registry

        registry.reset()
        x = np.stack(
            [np.full((8, 4), r, np.float32) for r in range(8)]
        )
        hvd.alltoall(x)
        snap = registry.snapshot()
        assert snap.get("alltoall.dispatches", 0) >= 1
        assert snap.get("alltoall.wire_bytes", 0) > 0
        stats = basics.state().fusion.cache_stats()
        assert stats["alltoall_dispatches"] >= 1
        assert stats["alltoall_wire_bytes"] > 0

    def test_legend_and_counter_keys(self):
        from horovod_tpu.common.metrics import MOE_METRICS
        from horovod_tpu.common.telemetry import _COUNTER_KEYS

        assert "alltoall.dispatches" in MOE_METRICS
        assert "alltoall.wire_bytes" in MOE_METRICS
        assert "alltoall.dispatches" in _COUNTER_KEYS
        assert "alltoall.wire_bytes" in _COUNTER_KEYS
        assert "moe.dropped_tokens" in _COUNTER_KEYS

    def test_publish_moe(self):
        from horovod_tpu.common.metrics import publish_moe, registry

        registry.reset()
        publish_moe(
            [10.0, 30.0, 10.0, 10.0], dropped=5.0, total=65.0,
            capacity_factor=1.5,
        )
        snap = registry.snapshot()
        assert snap["moe.dropped_tokens"] == 5.0
        assert snap["moe.routed_tokens"] == 65.0
        assert snap["moe.expert_tokens_max"] == 30.0
        assert snap["moe.imbalance"] == pytest.approx(30.0 / 15.0)
        assert snap["moe.drop_rate"] == pytest.approx(5.0 / 65.0)
        assert snap["moe.capacity_factor"] == 1.5

    def test_step_record_carries_alltoall_delta(self, hvd):
        from horovod_tpu.common.telemetry import TelemetryHub

        hub = TelemetryHub(capacity=8)
        hub.step_begin(step=1)
        x = np.stack(
            [np.full((8, 4), r, np.float32) for r in range(8)]
        )
        hvd.alltoall(x)
        rec = hub.step_end()
        assert rec["alltoall.dispatches"] >= 1
        assert rec["alltoall.wire_bytes"] > 0


# ------------------------------------------------ expert-load KV feed


class TestExpertLoadKV:
    def test_roundtrip_and_malformed(self):
        from horovod_tpu.runner.rendezvous import (
            EXPERT_LOAD_SCOPE,
            KVStore,
            put_expert_load,
            read_expert_loads,
        )

        store = KVStore()
        put_expert_load(
            store, 3, [1.0, 2.0], dropped=1.0, total=4.0,
            capacity_factor=1.5,
        )
        store.put(EXPERT_LOAD_SCOPE, "9", b"\xff not json")
        store.put(
            EXPERT_LOAD_SCOPE, "bad", json.dumps({"x": 1}).encode()
        )
        loads = read_expert_loads(store)
        assert list(loads) == [3]
        assert loads[3]["expert_tokens"] == [1.0, 2.0]
        assert loads[3]["capacity_factor"] == 1.5

    def test_worker_helpers_degrade_outside_elastic(self, monkeypatch):
        from horovod_tpu.elastic import worker as worker_mod

        monkeypatch.delenv("HOROVOD_GLOO_RENDEZVOUS_ADDR", raising=False)
        worker_mod._reset_rebalance_cache()
        assert not worker_mod.publish_expert_load([1.0], 0.0, 1.0)
        assert worker_mod.expert_loads() == {}

    def test_driver_aggregates_gauges(self, monkeypatch):
        import types

        from horovod_tpu.common.metrics import registry
        from horovod_tpu.elastic.discovery import HostDiscovery
        from horovod_tpu.elastic.driver import ElasticDriver
        from horovod_tpu.runner.hosts import HostInfo
        from horovod_tpu.runner.rendezvous import (
            KVStore,
            put_expert_load,
        )

        class Disc(HostDiscovery):
            def find_available_hosts_and_slots(self):
                return [HostInfo("a", 4)]

        d = ElasticDriver(Disc(), ["true"], min_np=1)
        d._server = types.SimpleNamespace(store=KVStore())
        put_expert_load(
            d._server.store, 0, [10.0, 30.0], dropped=10.0, total=50.0
        )
        put_expert_load(
            d._server.store, 1, [0.0, 40.0], dropped=0.0, total=40.0
        )
        registry.reset()
        d._poll_expert_loads()
        snap = registry.snapshot()
        assert snap["driver.expert_load.ranks"] == 2
        # fleet hist [10, 70], kept 80, mean 40 -> imbalance 1.75
        assert snap["driver.expert_load.imbalance"] == pytest.approx(1.75)
        assert snap["driver.expert_load.drop_rate"] == pytest.approx(
            10.0 / 90.0
        )
        # staleness: a rank whose ts stops ADVANCING ages out of the
        # gauges (departed-rank blob must not skew the fleet forever)
        from horovod_tpu.elastic import driver as driver_mod

        monkeypatch.setattr(driver_mod, "_EXPERT_LOAD_STALE_S", 0.0)
        put_expert_load(
            d._server.store, 0, [20.0, 20.0], dropped=0.0, total=40.0
        )  # rank 0 advances; rank 1's blob is frozen
        d._poll_expert_loads()
        snap = registry.snapshot()
        assert snap["driver.expert_load.ranks"] == 1
        assert snap["driver.expert_load.drop_rate"] == 0.0


# --------------------------------------------------- serve MoE decode


def _moe_model(vocab=64):
    from horovod_tpu.models.transformer import (
        Transformer,
        TransformerConfig,
    )

    cfg = TransformerConfig(
        vocab_size=vocab, num_layers=2, d_model=32, num_heads=4,
        d_ff=64, max_len=64, causal=True, dtype=jnp.float32,
        flash_attention=False, moe_experts=4,
    )
    model = Transformer(cfg)
    params = model.init(
        jax.random.PRNGKey(0), np.zeros((1, 4), np.int32)
    )["params"]
    return model, params


class TestServeMoE:
    def test_zero_retrace_across_rolling_admissions(self, hvd):
        from horovod_tpu.serving.batcher import ContinuousBatcher
        from horovod_tpu.serving.engine import InferenceEngine

        model, params = _moe_model()
        eng = InferenceEngine(model, params, slots=4, max_len=64)
        b = ContinuousBatcher(eng)
        rng = np.random.default_rng(0)
        reqs = [
            b.submit(
                rng.integers(0, 64, size=n).tolist(), max_new_tokens=6
            )
            for n in (5, 9, 3)
        ]
        for _ in range(40):
            b.step()
        # rolling admissions into freed slots: still ONE decode program
        reqs += [
            b.submit(
                rng.integers(0, 64, size=n).tolist(), max_new_tokens=4
            )
            for n in (7, 2)
        ]
        for _ in range(40):
            b.step()
        s = eng.stats()
        assert s["decode_compiles"] == 1, s
        assert all(r.status == "done" for r in reqs)
        assert all(len(r.out_tokens) > 0 for r in reqs)

    def test_paged_slab_parity(self, hvd):
        """MoE decode is bit-identical between the paged pool and the
        slab — routing is a pure function of values the two layouts
        agree on."""
        from horovod_tpu.serving.engine import InferenceEngine

        model, params = _moe_model()
        rng = np.random.default_rng(1)
        prompts = [rng.integers(0, 64, size=n).tolist() for n in (6, 11)]

        def run(paged):
            eng = InferenceEngine(
                model, params, slots=2, max_len=64, paged=paged
            )
            toks = np.zeros(2, np.int32)
            for slot, p in enumerate(prompts):
                toks[slot] = eng.prefill(slot, p)
            outs = [list() for _ in prompts]
            for _ in range(8):
                for s in range(2):
                    outs[s].append(int(toks[s]))
                    eng.manager.advance(s)
                toks = eng.decode_step(toks)
            return outs

        assert run(True) == run(False)

    def test_shard_moe_params(self, hvd):
        from horovod_tpu.models.transformer import shard_moe_params

        model, params = _moe_model()
        mesh = Mesh(np.asarray(jax.devices()[:4]), ("ep",))
        sharded = shard_moe_params(params, mesh, "ep")
        leaf = sharded["block_0"]["moe"]["w1"]
        assert leaf.sharding.spec == P("ep")
        # the router stays replicated
        router = sharded["block_0"]["moe"]["router"]["kernel"]
        assert getattr(router.sharding, "spec", P()) in (P(), P(None))
        # outputs match the replicated params bitwise on one forward
        toks = np.zeros((1, 4), np.int32)
        a = model.apply({"params": params}, toks, train=False)
        b_ = model.apply({"params": sharded}, toks, train=False)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b_))
        # a mesh without the axis is a no-op; non-dividing is loud
        assert shard_moe_params(params, None, "ep") is params
        mesh3 = Mesh(np.asarray(jax.devices()[:3]), ("ep",))
        with pytest.raises(ValueError, match="divide"):
            shard_moe_params(params, mesh3, "ep")

    def test_moe_ffn_emits_cfg_dtype(self, hvd):
        """The MoE branch must honor the dense branch's activation
        contract: cfg.dtype out, not the fp32 LayerNorm input dtype."""
        from horovod_tpu.models.transformer import (
            MoEFFN,
            TransformerConfig,
        )

        cfg = TransformerConfig(
            vocab_size=32, num_layers=1, d_model=16, num_heads=2,
            d_ff=32, max_len=16, dtype=jnp.bfloat16,
            flash_attention=False, moe_experts=4,
        )
        m = MoEFFN(cfg)
        x = jnp.zeros((1, 4, 16), jnp.float32)  # the LN output dtype
        params = m.init(jax.random.PRNGKey(0), x)
        out = m.apply(params, x)
        assert out.dtype == jnp.bfloat16

    def test_moe_off_keeps_param_tree(self, hvd):
        """moe_experts=0 is the exact pre-PR model — checkpoints stay
        layout-compatible."""
        from horovod_tpu.models.transformer import (
            Transformer,
            TransformerConfig,
        )

        cfg = TransformerConfig(
            vocab_size=32, num_layers=1, d_model=16, num_heads=2,
            d_ff=32, max_len=16, dtype=jnp.float32,
            flash_attention=False,
        )
        params = Transformer(cfg).init(
            jax.random.PRNGKey(0), np.zeros((1, 4), np.int32)
        )["params"]
        assert "moe" not in params["block_0"]
        assert "Dense_0" in params["block_0"]


# ------------------------------------- parallel transformer threading


class TestParallelThreading:
    @pytest.mark.parametrize("wire", ["fp32", "int8"])
    def test_train_step_with_expert_wire(self, hvd, wire):
        from horovod_tpu.parallel import transformer as ptf

        stages = topo_mod.hierarchical_stage_groups(4, 2)
        cfg = ptf.ParallelTransformerConfig(
            vocab_size=64, num_layers=2, d_model=32, num_heads=2,
            d_ff=64, max_len=32, n_experts=4, n_microbatches=1,
            moe_wire=wire, moe_hier=stages if wire == "int8" else None,
        )
        mesh = Mesh(
            np.asarray(jax.devices()[:8]).reshape(2, 1, 4, 1, 1),
            ("dp", "pp", "ep", "sp", "tp"),
        )
        params = ptf.make_sharded_params(cfg, mesh, jax.random.PRNGKey(0))
        step = ptf.make_train_step(cfg, mesh)
        rng = np.random.default_rng(0)
        toks = rng.integers(0, 64, size=(8, 32)).astype(np.int32)
        labs = rng.integers(0, 64, size=(8, 32)).astype(np.int32)
        params, loss = step(params, toks, labs)
        l0 = float(loss)
        assert np.isfinite(l0)
        for _ in range(3):
            params, loss = step(params, toks, labs)
        assert float(loss) < l0
