"""Flight-recorder telemetry: per-step stats hub + live scrape endpoint.

The reference's observability story is its timeline — the paper credits
it as the tool that made fusion and straggler problems *visible* before
they were fixable (arXiv 1802.05799 §4; SURVEY.md §5). The rebuild's
instruments so far are all *trace-shaped* (chrome-trace files you open
after the run) or *stream-shaped* (JSON-lines metric appends). This
module adds the third shape a production fleet needs: a bounded
**per-step record** that is queryable live and survives a kill.

Three faces, one hub:

1. **StepStats ring / flight recorder** — ``hvd.step_begin()`` /
   ``hvd.step_end()`` close a per-step record (wall time, exposed vs
   hidden collective device time from the traced-timeline ledger, wire
   bytes + format, fusion cache hits/dispatches, tuner decisions) into
   a bounded ring of the last ``HOROVOD_TELEMETRY_STEPS`` (default 256)
   steps. With ``HOROVOD_FLIGHT_RECORDER=/path`` set, ``atexit`` and a
   chained SIGTERM hook dump the ring as JSON-lines, so a preempted or
   killed worker leaves its last N steps on disk for post-mortem — the
   black-box recorder a SIGKILL'd timeline never writes.
2. **Live scrape endpoint** — :class:`MetricsServer`, a stdlib
   ``http.server`` thread per worker (``HOROVOD_METRICS_PORT``; 0 = off)
   serving ``/metrics`` in Prometheus text exposition (the metrics
   registry snapshot plus step-time p50/p95 from the ring) and
   ``/telemetry`` as JSON. No new dependencies — same raw-socket
   discipline as the rendezvous KV server (csrc/kvstore.cc).
3. **Cross-rank straggler feed** — :func:`heartbeat_stats` distills the
   ring into the ``{step, step_ms_p50, last_step_ts}`` payload the
   elastic worker piggybacks onto its rendezvous-KV heartbeat
   (runner/rendezvous.py ``put_heartbeat``); the driver aggregates the
   gang's payloads in ``StallInspector.straggler_ranks()``.

Auto-threading: ``hvd.value_and_grad`` opens/closes an auto step around
each (non-traced) call, and ``DistributedOptimizer`` emits a
``jax.debug.callback`` tick per update so fully-jitted loops still
produce step records — both only when telemetry is enabled
(flight recorder path, metrics port, or ``HOROVOD_TELEMETRY=1``), so
the default path costs nothing.
"""

from __future__ import annotations

import atexit
import collections
import itertools
import json
import os
import re
import signal
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional

from .logging import TRACE as _TRACE, get_logger
from .metrics import WIRE_FORMAT_NAMES, registry as _metrics

_log = get_logger("telemetry")

DEFAULT_RING_STEPS = 256

# Registry names treated as CUMULATIVE counters: a StepStats record
# carries their step_begin→step_end DELTA (what THIS step did), not the
# running total. Everything else of interest is a gauge read at close.
_COUNTER_KEYS = (
    "fusion.dispatches",
    "fusion.hits",
    "fusion.bucket_hits",
    "fusion.cycles",
    "fusion.flushed_bytes",
    "fusion.bucket_pad_bytes",
    "fusion.wire_bytes_saved",
    # two-level wire: per-hop split of the saved-bytes ledger (inter =
    # the DCN hop — the scarce one; advanced by hierarchical
    # dispatches only, so a step's inter delta IS its DCN saving)
    "fusion.wire_bytes_saved_intra",
    "fusion.wire_bytes_saved_inter",
    "fusion.hier_dispatches",
    "fusion.quant_blocks",
    # expert wire (parallel/moe.py + eager alltoall — the PR 12
    # observability fix): a step's alltoall deltas attribute its
    # expert-dispatch bytes, and a nonzero dropped-tokens delta marks
    # a capacity overflow on exactly that step
    "alltoall.dispatches",
    "alltoall.wire_bytes",
    "moe.dropped_tokens",
    "moe.routed_tokens",
    # chaos-hardened control plane (common/retry.py, testing/chaos.py):
    # per-step deltas let a postmortem correlate a slow step with the
    # hop that was retrying under it (attempts_total is deliberately
    # absent — the record emits only the fields it carries)
    "retry.retries_total",
    "retry.exhausted_total",
    "faults_injected",
    # training-state integrity plane (common/guard.py, audit.py): a
    # step whose record shows a nonzero guard delta SKIPPED its
    # update; an audit.digests delta marks the digest cadence, so the
    # flight recorder pins integrity events to exact steps
    "guard.nonfinite_steps",
    "audit.digests",
    # collective-schedule audit (analysis/sched_audit.py): a nonzero
    # sched_published delta marks the steps whose records carried a
    # schedule-fingerprint publish — the cadence evidence for the
    # sched_divergence detector
    "audit.sched_published",
    # local-SGD regime (horovod_tpu/local_sgd.py): local_steps is the
    # host-driver cadence meter, a sync_rounds delta marks the steps
    # that closed a reconciliation round, a rounds_deferred delta pins
    # a DCN outage to the exact step whose round it pushed out, and
    # inter_bytes is the modeled DCN ledger of the rounds that DID run
    # (÷K is the whole point — docs/perf.md prediction table)
    "local_sgd.local_steps",
    "local_sgd.sync_rounds",
    "local_sgd.rounds_deferred",
    "local_sgd.inter_bytes",
    # serving plane (horovod_tpu/serving/): a decode-step record's
    # tokens-out delta is its realized batch occupancy, and a nonzero
    # admitted_mid_decode delta pins a TPOT blip to the prefill that
    # caused it
    "serve.tokens_out",
    "serve.admitted_mid_decode",
    # paged memory plane (serving/paged_kv.py): page_allocs deltas mark
    # the steps whose slots crossed page boundaries (allocation IS the
    # write frontier), prefix_hits deltas mark admissions that attached
    # cached prefix pages instead of prefilling them
    "serve.page_allocs",
    "serve.prefix_hits",
    # KV-transfer wire (serving/kv_transfer.py): bytes/pages/ms deltas
    # meter the inter-slice KV stream a disaggregated fleet pays per
    # handed-off request (the int8-vs-fp32 wire trade in byte units),
    # and a transfer_fallbacks delta pins a decode-capacity outage to
    # the step whose request came home to decode locally
    "serve.kv_transfer_bytes",
    "serve.kv_transfer_pages",
    "serve.kv_transfer_ms",
    "serve.transfer_fallbacks",
    # persistent executable cache (common/exe_cache.py): a step whose
    # record shows a hits/misses delta paid a disk-tier lookup (a
    # promotion or a fresh bucket landed on that step), and a corrupt
    # delta pins a degraded-to-cold-compile entry to the exact step
    # that read it
    "exe_cache.hits",
    "exe_cache.misses",
    "exe_cache.corrupt",
    "exe_cache.rejected",
    "exe_cache.stores",
    "exe_cache.bytes",
    "exe_cache.deserialize_ms",
)

# Gauges copied into the record's ``tuner`` dict — the autotune /
# wire-format / overlap decisions in force when the step closed, so a
# post-mortem can correlate a regression with the knob flip that
# caused it.
_TUNER_PREFIXES = ("autotune.",)
_TUNER_KEYS = (
    "fusion.wire_format",
    "fusion.wire_format_intra",
    "fusion.wire_format_inter",
    "overlap.buckets",
    "moe.capacity_factor",
)


def _percentile(sorted_vals: List[float], q: float) -> float:
    """Nearest-rank percentile over an already-sorted list."""
    if not sorted_vals:
        return 0.0
    idx = min(int(q * (len(sorted_vals) - 1) + 0.5), len(sorted_vals) - 1)
    return sorted_vals[idx]


class TelemetryHub:
    """Per-process step-stats ring (the flight recorder).

    Thread-safe; always constructible (no ``hvd.init()`` required) so a
    bare training script — or a test — can drive it directly. One open
    record at a time; records are opened by :meth:`step_begin` (or the
    auto/tick variants) and closed into the ring by :meth:`step_end`.
    """

    def __init__(
        self,
        capacity: Optional[int] = None,
        flight_path: Optional[str] = None,
    ) -> None:
        env = os.environ
        if capacity is None:
            raw = env.get("HOROVOD_TELEMETRY_STEPS", "")
            capacity = int(raw) if raw.strip() else DEFAULT_RING_STEPS
        if flight_path is None:
            flight_path = env.get("HOROVOD_FLIGHT_RECORDER") or None
        self.capacity = max(int(capacity), 1)
        self.flight_path = flight_path
        self.forced = env.get("HOROVOD_TELEMETRY", "").strip().lower() in (
            "1", "true", "yes", "on",
        )
        self._lock = threading.Lock()
        self._ring: "collections.deque" = collections.deque(
            maxlen=self.capacity
        )
        # the one in-flight record: (record dict, base snapshot, t0
        # monotonic, kind) — kind ∈ {"manual", "auto", "tick"}
        self._open = None
        self._ids = itertools.count()
        self._last_step_id = -1
        # ticks (DistributedOptimizer's debug-callback path) stand down
        # whenever another instrumentation source closed a record since
        # the previous tick — otherwise an eager loop would record every
        # step twice (once per hook).
        self._non_tick_closed = False
        # last step id a tick HANDLED (opened, deduped, or stood down
        # for) — duplicate per-shard callbacks of one step must be
        # no-ops even after the record they'd duplicate was closed
        self._last_tick_step = None
        # one tick source drives the recorder: when both value_and_grad
        # (threaded hvd_step, source "tape") and DistributedOptimizer
        # (internal counter, source "opt") emit ticks in one program,
        # their ids can diverge and would split every step into two
        # fragment records. The tape source outranks the optimizer's
        # (its ids are the caller's real step counter).
        self._tick_source = None
        # attached by basics.init(); both optional
        self.timeline = None
        self.stall_inspector = None
        # bench↔flight-recorder correlation: when a bench harness
        # stamps a run id, every record closed while it is set carries
        # it, so on-chip captures are attributable after the fact
        self.run_id: Optional[str] = None
        # bumped by MetricsServer.start()/stop() — a live scraper turns
        # the auto hooks on even without a flight-recorder path
        self.scrapers = 0
        self._hooks_installed = False
        self._prev_sigterm = None
        if self.flight_path:
            self._install_hooks()

    # ------------------------------------------------------------ config

    def configure(
        self,
        capacity: Optional[int] = None,
        flight_path: Optional[str] = None,
    ) -> None:
        """Re-read knobs at ``hvd.init()`` time (the hub is process-wide
        and may predate init). Shrinking the ring keeps the newest
        records."""
        with self._lock:
            if capacity is not None and int(capacity) != self.capacity:
                self.capacity = max(int(capacity), 1)
                self._ring = collections.deque(
                    self._ring, maxlen=self.capacity
                )
            if flight_path is not None:
                self.flight_path = flight_path or None
        if self.flight_path:
            self._install_hooks()

    @property
    def enabled(self) -> bool:
        """True when some consumer exists (flight recorder, scraper, or
        HOROVOD_TELEMETRY=1) — gates the implicit per-step hooks."""
        return bool(self.flight_path or self.scrapers or self.forced)

    # -------------------------------------------------------- step faces

    def step_begin(self, step: Optional[int] = None) -> int:
        """Open a step record; returns its step id. An already-open
        record (any kind) is closed first — a forgiving contract, so a
        loop that misses one ``step_end`` degrades to tick semantics
        instead of wedging."""
        return self._begin(step, kind="manual")

    def step_end(self) -> Optional[dict]:
        """Close the open record into the ring; returns the record (or
        None when no step is open)."""
        return self._end(kinds=("manual", "auto", "tick"))

    def auto_step_begin(self, step: Optional[int] = None) -> bool:
        """Implicit open from ``hvd.value_and_grad`` — no-op (False)
        when any record is already open, so explicit instrumentation
        always wins over the auto hook."""
        with self._lock:
            if self._open is not None:
                return False
        self._begin(step, kind="auto")
        return True

    def auto_step_end(self) -> Optional[dict]:
        return self._end(kinds=("auto",))

    def tick(self, step: Optional[int] = None, source: str = "opt") -> None:
        """One step boundary from the traced path (the per-update
        ``jax.debug.callback`` of ``DistributedOptimizer`` — source
        "opt" — or of ``value_and_grad`` with a threaded ``hvd_step`` —
        source "tape"). A tick closes the previous tick-opened record
        and opens the next; it stands down entirely while manual/auto
        records are flowing, dedupes per-shard duplicates by step id,
        and only ONE source drives the recorder ("tape" outranks "opt",
        adopted on first sight)."""
        sid = None if step is None else int(step)
        with self._lock:
            if self._tick_source is None or (
                source == "tape" and self._tick_source == "opt"
            ):
                self._tick_source = source
            if source != self._tick_source:
                return
            open_rec = self._open
            open_kind = open_rec[3] if open_rec is not None else None
            if sid is not None and sid == self._last_tick_step:
                # duplicate tick for an already-HANDLED step (shard_map
                # runs the callback once per local shard, and the dups
                # may drain after the record closed) — one tick wins
                return
            if sid is not None:
                self._last_tick_step = sid
            if open_kind in ("manual", "auto"):
                return
            stand_down = self._non_tick_closed and open_kind is None
            self._non_tick_closed = False
        if stand_down:
            return
        if open_kind == "tick":
            self._end(kinds=("tick",))
        self._begin(sid, kind="tick")

    # ----------------------------------------------------- record plumbing

    def _begin(self, step: Optional[int], kind: str) -> int:
        snap = _metrics.snapshot()
        now = time.time()
        t0 = time.monotonic()
        closed = None
        with self._lock:
            if self._open is not None:
                closed = self._close_locked(time.monotonic(), time.time())
            if step is None:
                step_id = next(self._ids)
                # explicit ids may have advanced past the internal
                # counter; keep auto ids monotonic with them
                if step_id <= self._last_step_id:
                    step_id = self._last_step_id + 1
                    self._ids = itertools.count(step_id + 1)
            else:
                step_id = int(step)
                self._ids = itertools.count(step_id + 1)
            self._open = ({"step": step_id, "ts": now}, snap, t0, kind)
        if closed is not None:
            self._publish(closed)
        return step_id

    def _end(self, kinds) -> Optional[dict]:
        with self._lock:
            if self._open is None or self._open[3] not in kinds:
                return None
            rec = self._close_locked(time.monotonic(), time.time())
        self._publish(rec)
        return rec

    def _close_locked(self, t1: float, now: float) -> dict:
        rec, base, t0, kind = self._open
        self._open = None
        if kind != "tick":
            self._non_tick_closed = True
        snap = _metrics.snapshot()
        deltas = {
            k: snap.get(k, 0.0) - base.get(k, 0.0) for k in _COUNTER_KEYS
        }
        # wire footprint this step: payload + bucket padding − quantized
        # savings (the fusion manager's byte model, per-step delta)
        wire = (
            deltas["fusion.flushed_bytes"]
            + deltas["fusion.bucket_pad_bytes"]
            - deltas["fusion.wire_bytes_saved"]
        )
        tuner = {
            k: v
            for k, v in snap.items()
            if k in _TUNER_KEYS or k.startswith(_TUNER_PREFIXES)
        }
        rec.update(
            {
                "wall_ms": round((t1 - t0) * 1e3, 3),
                # exposed/hidden collective device time: the traced
                # timeline's overlap ledger
                # (traced_timeline.collective_overlap_stats) — the
                # LATEST session's values, since the profiler measures
                # windows, not single steps
                "collective_ms": snap.get("overlap.collective_ms", 0.0),
                "exposed_collective_ms": snap.get(
                    "overlap.exposed_collective_ms", 0.0
                ),
                "hidden_collective_ms": snap.get(
                    "overlap.hidden_collective_ms", 0.0
                ),
                "wire_bytes": max(wire, 0.0),
                "wire_bytes_saved": deltas["fusion.wire_bytes_saved"],
                # two-level wire: the per-hop split (inter = the DCN
                # hop). Advanced only by hierarchical dispatches, so a
                # step's inter delta IS its DCN saving
                # (docs/observability.md)
                "wire_bytes_saved_intra": deltas[
                    "fusion.wire_bytes_saved_intra"
                ],
                "wire_bytes_saved_inter": deltas[
                    "fusion.wire_bytes_saved_inter"
                ],
                "hier_dispatches": deltas["fusion.hier_dispatches"],
                "wire_format": WIRE_FORMAT_NAMES.get(
                    int(snap.get("fusion.wire_format", 0)), "fp32"
                ),
                "fusion_dispatches": deltas["fusion.dispatches"],
                "fusion_cache_hits": deltas["fusion.hits"]
                + deltas["fusion.bucket_hits"],
                "fusion_cycles": deltas["fusion.cycles"],
                # expert wire (PR 12): eager alltoall dispatch/byte
                # deltas — expert-dispatch traffic attributed to THIS
                # step — plus the MoE capacity-gate counters the step
                # harness published (0s without MoE traffic)
                "alltoall.dispatches": deltas["alltoall.dispatches"],
                "alltoall.wire_bytes": deltas["alltoall.wire_bytes"],
                "moe.dropped_tokens": deltas["moe.dropped_tokens"],
                "moe.routed_tokens": deltas["moe.routed_tokens"],
                # control-plane weather during THIS step: retries the
                # transports absorbed, rounds that exhausted, and any
                # chaos-layer faults injected (0s on a healthy step)
                "retries": deltas["retry.retries_total"],
                "retry_exhausted": deltas["retry.exhausted_total"],
                "faults_injected": deltas["faults_injected"],
                # integrity plane (PR 7): a nonzero guard delta means
                # THIS step's update was skipped for non-finite
                # gradients; audit_ran marks the digest cadence
                # landing on this step, and audit.last_digest_step is
                # the GAUGE (the newest digest's step id), not a delta
                "guard.nonfinite_steps": deltas["guard.nonfinite_steps"],
                "audit_ran": 1.0 if deltas["audit.digests"] else 0.0,
                "audit.last_digest_step": snap.get(
                    "audit.last_digest_step", 0.0
                ),
                # local-SGD regime (horovod_tpu/local_sgd.py): a
                # sync_rounds delta marks the step that closed a
                # reconciliation round, rounds_deferred pins a DCN
                # outage to the step whose round it pushed out, and
                # inter_bytes is the modeled DCN cost of the rounds
                # that ran (all 0 outside the mode)
                "local_sgd.local_steps": deltas["local_sgd.local_steps"],
                "local_sgd.sync_rounds": deltas["local_sgd.sync_rounds"],
                "local_sgd.rounds_deferred": deltas[
                    "local_sgd.rounds_deferred"
                ],
                "local_sgd.inter_bytes": deltas["local_sgd.inter_bytes"],
                # serving plane: tokens this record emitted and the
                # mid-decode admissions that landed inside it (both 0
                # on training steps)
                "serve.tokens_out": deltas["serve.tokens_out"],
                "serve.admitted_mid_decode": deltas[
                    "serve.admitted_mid_decode"
                ],
                "serve.page_allocs": deltas["serve.page_allocs"],
                "serve.prefix_hits": deltas["serve.prefix_hits"],
                "tuner": tuner,
            }
        )
        if self.run_id:
            rec["run_id"] = self.run_id
        self._last_step_id = max(self._last_step_id, rec["step"])
        self._ring.append(rec)
        return rec

    def _publish(self, rec: dict) -> None:
        """Per-step gauges into the registry + the trace counter track,
        and the stall check every traced/eager step goes through."""
        pct = self.percentiles()
        _metrics.update(
            "telemetry",
            {
                "step": rec["step"],
                "step_ms": rec["wall_ms"],
                "step_ms_p50": pct.get("p50", 0.0),
                "step_ms_p95": pct.get("p95", 0.0),
                "steps_recorded": pct.get("count", 0),
            },
        )
        tl = self.timeline
        if tl is not None:
            # aligns traces with StepStats records: the same step id on
            # a counter track next to the per-tensor lifecycle rows
            tl.counter("telemetry.step", rec["step"])
        insp = self.stall_inspector
        if insp is not None:
            # steady-state stall coverage for traced jobs that never
            # run an eager fusion cycle; may raise the shutdown
            # escalation, which is the point
            insp.check()

    # ----------------------------------------------------------- read side

    def records(self) -> List[dict]:
        with self._lock:
            return [dict(r) for r in self._ring]

    def _snapshot_records(self, timeout: float = 1.0) -> List[dict]:
        """Ring copy that NEVER deadlocks: the SIGTERM/preemption dump
        runs in a signal handler ON the main thread, and if the signal
        landed while that same thread held ``_lock`` inside
        step_begin/step_end, a blocking acquire would hang the handler
        forever (threading.Lock is not reentrant) — the grace window
        and the checkpoint behind it would be lost. Bounded acquire,
        then a lock-free best-effort copy: in the contended case the
        holder is the interrupted (frozen) frame, so the ring is
        quiescent; a racing mutation from another thread at worst
        raises mid-iteration, which we retry and then accept losing."""
        acquired = self._lock.acquire(timeout=timeout)
        try:
            for _ in range(3):
                try:
                    return [dict(r) for r in list(self._ring)]
                except RuntimeError:  # deque mutated during iteration
                    continue
            return []
        finally:
            if acquired:
                self._lock.release()

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    def percentiles(self) -> Dict[str, float]:
        """step-time p50/p95 (+count/sum) over the ring; {} when empty."""
        with self._lock:
            walls = sorted(r["wall_ms"] for r in self._ring)
        if not walls:
            return {}
        return {
            "p50": _percentile(walls, 0.50),
            "p95": _percentile(walls, 0.95),
            "count": len(walls),
            "sum": sum(walls),
        }

    def heartbeat_stats(self) -> Dict[str, float]:
        """The straggler-ledger payload piggybacked onto the rendezvous
        heartbeat: this worker's last closed step id, its ring p50, and
        when that step closed (epoch seconds). {} before the first
        step."""
        with self._lock:
            last = self._ring[-1] if self._ring else None
        if last is None:
            return {}
        pct = self.percentiles()
        out = {
            "step": last["step"],
            "step_ms_p50": pct.get("p50", 0.0),
            "last_step_ts": last["ts"] + last["wall_ms"] / 1e3,
        }
        # local-SGD deferral ledger piggybacks the heartbeat: the
        # driver's gang view shows which workers' DCN rounds are being
        # pushed out (degraded inter axis) while every beat stays fresh
        deferred = _metrics.snapshot().get("local_sgd.rounds_deferred")
        if deferred:
            out["local_sgd_rounds_deferred"] = float(deferred)
        return out

    # -------------------------------------------------- flight recorder

    def dump(self, path: Optional[str] = None) -> Optional[str]:
        """Write the ring as JSON-lines (one record per line, oldest
        first) to ``path`` / the configured flight-recorder path.
        Whole-file replace via tmp+rename: a dump interrupted by the
        next signal can't leave a torn file."""
        path = path or self.flight_path
        if not path:
            return None
        # signal-safe snapshot: dump() is reached from SIGTERM handlers
        # (ours and preemption.GracefulShutdown's) — see _snapshot_records
        records = self._snapshot_records()
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            for rec in records:
                f.write(json.dumps(rec) + "\n")
        os.replace(tmp, path)
        self._dump_spans(path)
        return path

    def _dump_spans(self, path: str) -> None:
        """Drain the trace-plane span ring beside the StepStats dump —
        ``<flight_recorder>.spans`` — on the same atexit/SIGTERM hooks,
        so a killed worker's spans survive for trace_assemble. Never
        lets a tracing bug spoil the step-record dump."""
        try:
            from . import tracing

            rec = tracing._recorder  # don't construct one just to drain it
            if rec is not None and len(rec):
                rec.dump(path + ".spans")
        except Exception:
            _log.debug("span-ring dump failed", exc_info=True)

    def set_run_id(self, run_id: Optional[str]) -> None:
        """Stamp (or clear) the bench run id carried by every record
        closed from now on."""
        self.run_id = run_id or None

    def _install_hooks(self) -> None:
        """atexit + chained SIGTERM dump — the 'killed worker leaves its
        last N steps on disk' guarantee. SIGTERM keeps its fatal
        semantics: after dumping, the previous handler runs, or the
        process exits 143 when the previous disposition was default
        (preemption.GracefulShutdown installed LATER chains us and owns
        the exit instead)."""
        with self._lock:
            if self._hooks_installed:
                return
            self._hooks_installed = True
        atexit.register(self._atexit_dump)
        try:
            if threading.current_thread() is threading.main_thread():
                self._prev_sigterm = signal.signal(
                    signal.SIGTERM, self._on_sigterm
                )
        except ValueError:
            pass  # non-main-thread import: atexit still covers us

    def _atexit_dump(self) -> None:
        try:
            if len(self):
                self.dump()
            elif self.flight_path:
                # no step records, but the span ring may still hold a
                # trace worth keeping (e.g. a pure-routing worker)
                self._dump_spans(self.flight_path)
        except Exception:
            _log.debug("flight-recorder atexit dump failed", exc_info=True)

    def _on_sigterm(self, signum, frame) -> None:
        try:
            self.dump()
        except Exception:
            pass
        prev = self._prev_sigterm
        if callable(prev):
            prev(signum, frame)
            return
        if prev is signal.SIG_IGN:
            return
        # default disposition: die like a SIGTERM'd process (128+15);
        # os._exit because the signal may have landed mid-collective
        # and interpreter teardown over wedged device state can hang
        os._exit(143)


# ---------------------------------------------------------------- singleton

_hub: Optional[TelemetryHub] = None
_hub_lock = threading.Lock()


def hub() -> TelemetryHub:
    """The process-wide hub (created lazily from env)."""
    global _hub
    with _hub_lock:
        if _hub is None:
            _hub = TelemetryHub()
        return _hub


def _reset_hub() -> None:
    """Test hook: drop the singleton so the next hub() re-reads env.
    Installed signal/atexit hooks of the old hub stay installed (they
    are idempotent dumps of a now-empty ring)."""
    global _hub
    with _hub_lock:
        _hub = None


def auto_enabled() -> bool:
    """Gate for the implicit hooks (value_and_grad / optimizer tick):
    cheap, and False unless someone is actually consuming telemetry."""
    h = _hub
    if h is None:
        # don't force-create the hub on the hot path; construct only if
        # env says telemetry is on at all
        env = os.environ
        if not (
            env.get("HOROVOD_FLIGHT_RECORDER")
            or env.get("HOROVOD_TELEMETRY", "").strip().lower()
            in ("1", "true", "yes", "on")
        ):
            return False
        h = hub()
    return h.enabled


def step_begin(step: Optional[int] = None) -> int:
    """``hvd.step_begin()`` — open a per-step flight-recorder record."""
    return hub().step_begin(step)


def step_end() -> Optional[dict]:
    """``hvd.step_end()`` — close the record into the ring."""
    return hub().step_end()


def device_step_tick(step, source: str = "opt") -> None:
    """jax.debug.callback target: one step boundary per compiled
    optimizer update / tape call (works inside fully-jitted loops,
    where no host code runs per step). Telemetry bugs must never kill
    a training step — EXCEPT the stall inspector's shutdown
    escalation, which exists precisely to kill a wedged job and rides
    the per-step check inside the record close."""
    from .basics import HorovodInternalError

    try:
        hub().tick(int(step), source=source)
    except HorovodInternalError:
        raise
    except Exception:
        _log.debug("telemetry tick failed", exc_info=True)


def set_run_id(run_id: Optional[str]) -> None:
    """Module-level convenience for bench harnesses: stamp every
    flight-recorder record closed from now on with ``run_id``."""
    hub().set_run_id(run_id)


def heartbeat_stats() -> Dict[str, float]:
    """Module-level convenience for the elastic worker's heartbeat."""
    h = _hub
    return h.heartbeat_stats() if h is not None else hub().heartbeat_stats()


# ------------------------------------------------------- prometheus render

_PROM_BAD = re.compile(r"[^a-zA-Z0-9_:]")
PROM_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def _prom_name(name: str) -> str:
    out = _PROM_BAD.sub("_", name)
    if out and out[0].isdigit():
        out = "_" + out
    return "hvd_" + out


def _prom_value(v: float) -> Optional[str]:
    v = float(v)
    if v != v or v in (float("inf"), float("-inf")):
        return None  # exposition must not carry NaN/Inf from gauges
    return f"{v:.10g}"


def render_prometheus(
    snapshot: Dict[str, float], percentiles: Dict[str, float]
) -> str:
    """Prometheus text exposition v0.0.4: the step-time summary first,
    then every registry metric as a ``hvd_``-prefixed gauge with
    HELP/TYPE lines. Pure function so tests can feed it directly."""
    lines = [
        "# HELP telemetry_step_ms Per-step wall time over the "
        "flight-recorder ring (HOROVOD_TELEMETRY_STEPS newest steps).",
        "# TYPE telemetry_step_ms summary",
    ]
    def _v(x) -> str:
        return _prom_value(x) or "0"

    if percentiles:
        lines.append(
            'telemetry_step_ms{quantile="0.5"} ' + _v(percentiles["p50"])
        )
        lines.append(
            'telemetry_step_ms{quantile="0.95"} ' + _v(percentiles["p95"])
        )
    lines.append("telemetry_step_ms_sum " + _v(percentiles.get("sum", 0.0)))
    lines.append(
        "telemetry_step_ms_count " + _v(percentiles.get("count", 0))
    )
    seen = set()
    for name in sorted(snapshot):
        prom = _prom_name(name)
        if prom in seen:  # two dotted names collapsing onto one
            continue
        val = _prom_value(snapshot[name])
        if val is None:
            continue
        seen.add(prom)
        lines.append(f"# HELP {prom} horovod_tpu metric {name!r}")
        lines.append(f"# TYPE {prom} gauge")
        lines.append(f"{prom} {val}")
    return "\n".join(lines) + "\n"


# ------------------------------------------------------------ scrape server


class MetricsServer:
    """Per-worker live scrape endpoint on a stdlib http.server thread.

    Routes: ``/metrics`` (Prometheus text), ``/telemetry`` (JSON ring +
    registry snapshot), ``/traces`` (trace-plane span ring +
    worker identity + clock stamps), ``/healthz``. Read-only and unauthenticated by
    design — it exposes numbers, not control; bind it to an interface
    your scraper can reach (default all interfaces, matching the
    rendezvous server)."""

    def __init__(
        self,
        port: int = 0,
        addr: str = "0.0.0.0",
        hub_instance: Optional[TelemetryHub] = None,
    ) -> None:
        self._hub = hub_instance
        outer = self

        class _Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):
                _log.log(_TRACE, "http " + fmt, *args)

            def _reply(self, code, body: bytes, ctype: str) -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                h = outer.hub
                path = self.path.split("?", 1)[0]
                if path == "/traces":
                    # span ring + this worker's identity + recv/send
                    # wall stamps: the scrape itself is an NTP edge the
                    # assembler can estimate this host's offset from
                    recv_ts = time.time()
                    from . import tracing

                    rec = tracing.recorder()
                    body = json.dumps(
                        {
                            "spans": rec.spans(),
                            "capacity": rec.capacity,
                            "host": rec.host,
                            "pid": rec.pid,
                            "role": rec.role,
                            "recv_ts": recv_ts,
                            "send_ts": time.time(),
                        }
                    ).encode()
                    return self._reply(200, body, "application/json")
                if path == "/metrics":
                    body = render_prometheus(
                        _metrics.snapshot(), h.percentiles()
                    ).encode()
                    return self._reply(200, body, PROM_CONTENT_TYPE)
                if path == "/telemetry":
                    body = json.dumps(
                        {
                            "steps": h.records(),
                            "percentiles": h.percentiles(),
                            "metrics": _metrics.snapshot(),
                            "ring_capacity": h.capacity,
                        }
                    ).encode()
                    return self._reply(200, body, "application/json")
                if path == "/healthz":
                    return self._reply(
                        200, b"ok\n", "text/plain; charset=utf-8"
                    )
                return self._reply(
                    404, b"not found\n", "text/plain; charset=utf-8"
                )

        class _Server(ThreadingHTTPServer):
            daemon_threads = True
            allow_reuse_address = True

        self._httpd = _Server((addr, port), _Handler)
        self._thread: Optional[threading.Thread] = None

    @property
    def hub(self) -> TelemetryHub:
        return self._hub if self._hub is not None else hub()

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    def start(self) -> int:
        if self._thread is not None:
            return self.port
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name="hvd-telemetry-scrape",
            daemon=True,
        )
        self._thread.start()
        self.hub.scrapers += 1
        _log.info("telemetry /metrics endpoint on port %d", self.port)
        return self.port

    def stop(self) -> None:
        if self._thread is None:
            self._httpd.server_close()
            return
        self.hub.scrapers = max(self.hub.scrapers - 1, 0)
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)
        self._thread = None
