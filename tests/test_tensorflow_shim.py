"""horovod_tpu.tensorflow binding tests — the core cases of the
reference's test/parallel/test_tensorflow.py [V]: collective ops,
broadcast_variables, DistributedGradientTape grad equivalence."""

import numpy as np
import pytest

tf = pytest.importorskip("tensorflow")

import horovod_tpu.tensorflow as hvd_tf  # noqa: E402


@pytest.fixture
def hvdtf(hvd):
    """JAX-side fixture brings the mesh up; the TF shim shares it."""
    return hvd_tf


def test_identity(hvdtf):
    assert hvdtf.is_initialized()
    assert hvdtf.size() >= 1
    assert hvdtf.rank() == 0


def test_allreduce_sum(hvdtf):
    x = tf.constant([1.0, 2.0, 3.0])
    out = hvdtf.allreduce(x, op=hvdtf.Sum)
    np.testing.assert_allclose(out.numpy(), x.numpy() * hvdtf.size())
    assert out.dtype == x.dtype


def test_allreduce_average(hvdtf):
    x = tf.constant([[2.0, 4.0]])
    out = hvdtf.allreduce(x, op=hvdtf.Average)
    np.testing.assert_allclose(out.numpy(), x.numpy())


def test_allreduce_async_poll_wait(hvdtf):
    x = tf.ones((2, 2))
    handle = hvdtf.allreduce_async(x, op=hvdtf.Sum)
    out = handle.wait()
    np.testing.assert_allclose(
        out.numpy(), np.full((2, 2), float(hvdtf.size()))
    )


def test_allgather_concatenates_dim0(hvdtf):
    x = tf.reshape(tf.range(6, dtype=tf.float32), (2, 3))
    out = hvdtf.allgather(x)
    assert out.shape == (2 * hvdtf.size(), 3)
    np.testing.assert_allclose(out.numpy()[:2], x.numpy())


def test_broadcast_and_variables(hvdtf):
    x = tf.constant([5.0, 6.0])
    out = hvdtf.broadcast(x, root_rank=0)
    np.testing.assert_allclose(out.numpy(), x.numpy())

    v = tf.Variable([1.0, 2.0, 3.0])
    hvdtf.broadcast_variables([v], root_rank=0)
    np.testing.assert_allclose(v.numpy(), [1.0, 2.0, 3.0])


def test_distributed_gradient_tape_equivalence(hvdtf):
    """Tape-wrapped grads must equal manual grad x (Average over an
    all-same world = identity), the reference's core TF2 contract."""
    w = tf.Variable([[1.0], [2.0]])
    x = tf.constant([[3.0, 4.0]])

    with tf.GradientTape() as tape:
        loss = tf.reduce_sum(tf.matmul(x, w))
    ref_grads = tape.gradient(loss, [w])

    with tf.GradientTape() as tape2:
        loss2 = tf.reduce_sum(tf.matmul(x, w))
    dtape = hvdtf.DistributedGradientTape(tape2)
    grads = dtape.gradient(loss2, [w])

    np.testing.assert_allclose(grads[0].numpy(), ref_grads[0].numpy())


def test_gradient_tape_single_source(hvdtf):
    """A single (non-list) source returns a single tensor, mirroring
    tf.GradientTape semantics."""
    w = tf.Variable([2.0, 3.0])
    with tf.GradientTape() as tape:
        loss = tf.reduce_sum(w * w)
    dtape = hvdtf.DistributedGradientTape(tape)
    g = dtape.gradient(loss, w)
    assert not isinstance(g, (list, tuple))
    np.testing.assert_allclose(g.numpy(), [4.0, 6.0])


def test_gradient_tape_sparse_densifies_with_warning(hvdtf):
    """IndexedSlices gradients densify-and-reduce with a one-time
    warning (the reference's sparse_as_dense behavior [V]) — embedding
    gradients must not break the drop-in contract."""
    import horovod_tpu.tensorflow as mod

    mod._sparse_warned = False
    v = tf.Variable(tf.ones((4, 2)))
    with tf.GradientTape() as tape:
        loss = tf.reduce_sum(tf.gather(v, [0, 2]))
    dtape = hvdtf.DistributedGradientTape(tape)
    with pytest.warns(UserWarning, match="IndexedSlices"):
        g = dtape.gradient(loss, v)
    expected = np.zeros((4, 2))
    expected[0] = expected[2] = 1.0
    np.testing.assert_allclose(np.asarray(g), expected)


def test_gradient_tape_none_grad_passthrough(hvdtf):
    """Sources not on the tape produce None grads; the wrapper must
    pass them through instead of crashing."""
    w = tf.Variable([1.0])
    unused = tf.Variable([2.0])
    with tf.GradientTape() as tape:
        loss = tf.reduce_sum(w * 3.0)
    dtape = hvdtf.DistributedGradientTape(tape)
    grads = dtape.gradient(loss, [w, unused])
    assert grads[1] is None
    np.testing.assert_allclose(grads[0].numpy(), [3.0])


def test_alltoall_even(hvdtf):
    n = hvdtf.size()
    x = tf.constant(np.arange(n, dtype=np.float32))
    out = hvdtf.alltoall(x)
    # rank j receives block j from every peer; the shim replicates this
    # process's tensor to all ranks, so rank 0 gets x[0] from each
    np.testing.assert_allclose(out.numpy(), np.full(n, x.numpy()[0]))


def test_alltoall_uneven_splits(hvdtf):
    n = hvdtf.size()
    # send 1 row to rank 0 and 0 rows to everyone else
    splits = [1] + [0] * (n - 1)
    x = tf.constant([[7.0, 8.0]])
    out, recv = hvdtf.alltoall(x, splits=splits)
    # we are rank 0: every rank sent us its 1 row (identical inputs)
    assert out.shape == (n, 2)
    np.testing.assert_allclose(out.numpy()[0], [7.0, 8.0])
    assert recv.numpy().tolist() == [1] * n


def test_reducescatter(hvdtf):
    n = hvdtf.size()
    x = tf.constant(np.arange(2.0 * n, dtype=np.float32))
    out = hvdtf.reducescatter(x, op=hvdtf.Sum)
    # rank 0's shard: first 2 elements of the world sum
    np.testing.assert_allclose(out.numpy(), np.arange(2.0) * n)


def test_join(hvdtf):
    assert hvdtf.join() == -1
    assert hvdtf.join([1, 2]) == 2


def test_keras_distributed_optimizer(hvdtf):
    """apply_gradients allreduces first (Average over an all-same world
    = identity): one SGD step must equal the undistributed step, the
    reference's Keras contract (keras/__init__.py [V])."""
    keras = tf.keras
    v = tf.Variable([1.0, 2.0])
    opt = hvdtf.DistributedOptimizer(keras.optimizers.SGD(learning_rate=0.5))
    assert type(opt).__name__ == "DistributedSGD"
    grads = [tf.constant([1.0, 1.0])]
    opt.apply_gradients(zip(grads, [v]))
    np.testing.assert_allclose(v.numpy(), [0.5, 1.5])


def test_keras_distributed_optimizer_config_roundtrip(hvdtf):
    keras = tf.keras
    opt = hvdtf.DistributedOptimizer(
        keras.optimizers.Adam(learning_rate=0.01)
    )
    cfg = opt.get_config()
    assert abs(float(cfg["learning_rate"]) - 0.01) < 1e-9


def test_keras_callbacks_fit_roundtrip(hvdtf):
    """The four Keras callbacks ride a real model.fit (ref:
    horovod/tensorflow/keras/callbacks.py [V])."""
    from horovod_tpu.tensorflow import callbacks as hvd_cb

    keras = tf.keras
    # seed the kernel init: at lr 0.4 an unlucky unseeded init can
    # diverge and flip the loss-decrease assertion (observed flaky)
    keras.utils.set_random_seed(7)
    model = keras.Sequential([keras.layers.Dense(4, input_shape=(3,)),
                              keras.layers.Dense(1)])
    model.compile(optimizer=keras.optimizers.SGD(learning_rate=0.4),
                  loss="mse")
    x = np.random.default_rng(0).normal(size=(64, 3)).astype(np.float32)
    y = x.sum(axis=1, keepdims=True).astype(np.float32)
    cbs = [
        hvd_cb.BroadcastGlobalVariablesCallback(0),
        hvd_cb.MetricAverageCallback(),
        hvd_cb.LearningRateWarmupCallback(initial_lr=0.4, warmup_epochs=2,
                                          steps_per_epoch=4),
        hvd_cb.LearningRateScheduleCallback(initial_lr=0.4,
                                            multiplier=lambda e: 0.5 ** e,
                                            start_epoch=2),
    ]
    hist = model.fit(x, y, epochs=3, batch_size=16, verbose=0,
                     callbacks=cbs)
    # schedule took over after warmup: epoch 2 multiplier 0.25
    lr = float(model.optimizer.learning_rate.numpy())
    assert abs(lr - 0.4 * 0.25) < 1e-6
    # metrics were averaged (world of identical replicas → unchanged
    # but numeric), and loss decreased
    losses = hist.history["loss"]
    assert losses[-1] < losses[0]


def test_grouped_ops_tf(hvdtf):
    n = hvdtf.size()
    outs = hvdtf.grouped_allreduce(
        [tf.ones((2,)), tf.fill((3,), 2.0)], op=hvdtf.Sum
    )
    np.testing.assert_allclose(outs[0].numpy(), np.full(2, float(n)))
    np.testing.assert_allclose(outs[1].numpy(), np.full(3, 2.0 * n))

    gathered = hvdtf.grouped_allgather([tf.constant([[1.0, 2.0]])])
    assert gathered[0].shape == (n, 2)

    rs = hvdtf.grouped_reducescatter(
        [tf.constant(np.arange(2.0 * n, dtype=np.float32))], op=hvdtf.Sum
    )
    np.testing.assert_allclose(rs[0].numpy(), np.arange(2.0) * n)


def test_alltoall_v_over_process_set_tf(hvdtf):
    """Uneven alltoall scoped to a set through the TF shim (the former
    NotImplementedError path)."""
    ps = hvdtf.add_process_set([0, 2, 4])
    try:
        x = tf.reshape(tf.range(12, dtype=tf.float32), (6, 2))
        out, recv = hvdtf.alltoall(x, splits=[1, 2, 3], process_set=ps)
        assert out.shape == (3, 2)
        assert recv.numpy().tolist() == [1, 1, 1]
        np.testing.assert_allclose(out[0].numpy(), x[0].numpy())
    finally:
        hvdtf.remove_process_set(ps)


class TestKerasModules:
    """Import-compat modules (ref: horovod/tensorflow/keras/__init__.py
    + horovod/keras/__init__.py [V]): one-import porting for Keras
    scripts, never a narrower surface than the TF shim."""

    def test_tensorflow_keras_surface(self, hvd):
        import horovod_tpu.tensorflow.keras as hvd_k

        assert hvd_k.is_initialized()
        assert hvd_k.size() >= 1
        # keras flavor carries the optimizer, callbacks and load_model
        assert callable(hvd_k.DistributedOptimizer)
        assert callable(hvd_k.load_model)
        assert hasattr(hvd_k.callbacks, "BroadcastGlobalVariablesCallback")
        assert hasattr(hvd_k.callbacks, "MetricAverageCallback")

    def test_forwarding_covers_parent_surface(self, hvd):
        import horovod_tpu.tensorflow as hvd_tf
        import horovod_tpu.tensorflow.keras as hvd_k

        # everything the TF shim exposes is reachable from the keras
        # module (the reference keeps the two surfaces in lockstep)
        for name in ("alltoall", "reducescatter", "grouped_allreduce",
                     "join", "add_process_set", "elastic"):
            assert getattr(hvd_k, name) is getattr(hvd_tf, name)

    def test_standalone_keras_alias(self, hvd):
        import horovod_tpu.keras as hvd_sk
        import horovod_tpu.tensorflow.keras as hvd_k

        assert hvd_sk.DistributedOptimizer is hvd_k.DistributedOptimizer
        assert hvd_sk.callbacks is hvd_k.callbacks
        assert hvd_sk.elastic is hvd_k.elastic

    def test_keras_allreduce_runs(self, hvd):
        import numpy as np

        import horovod_tpu.tensorflow.keras as hvd_k

        tf = pytest.importorskip("tensorflow")
        x = tf.constant([1.0, 2.0])
        out = hvd_k.allreduce(x, op=hvd_k.Sum)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(x) * hvd_k.size()
        )


def test_tf_allreduce_prescale_postscale(hvdtf):
    x = tf.constant([2.0, 2.0])
    out = hvdtf.allreduce(
        x, op=hvdtf.Sum, prescale_factor=0.5, postscale_factor=3.0
    )
    want = 2.0 * 0.5 * hvdtf.size() * 3.0
    np.testing.assert_allclose(np.asarray(out), np.full(2, want))


def test_tf_compression_fp16_round_trip(hvdtf):
    x = tf.constant([1.5, -2.25, 3.0])
    tape_like, ctx = hvdtf.Compression.fp16.compress(x)
    assert tape_like.dtype == tf.float16
    back = hvdtf.Compression.fp16.decompress(tape_like, ctx)
    assert back.dtype == tf.float32
    np.testing.assert_allclose(np.asarray(back), np.asarray(x))


def test_tf_tape_with_fp16_compression(hvdtf):
    x = tf.Variable([2.0, 4.0])
    with tf.GradientTape() as tape:
        y = tf.reduce_sum(x * x)
    dtape = hvdtf.DistributedGradientTape(
        tape, compression=hvdtf.Compression.fp16
    )
    g = dtape.gradient(y, x)
    assert g.dtype == tf.float32  # decompressed back
    np.testing.assert_allclose(np.asarray(g), [4.0, 8.0])


def test_tf_barrier_and_object_helpers(hvdtf):
    """hvd.tensorflow barrier/broadcast_object/allgather_object parity
    (ref: horovod/tensorflow/__init__.py [V])."""
    hvdtf.barrier()
    obj = {"epoch": 3, "name": "x"}
    assert hvdtf.broadcast_object(obj, root_rank=0) == obj
    gathered = hvdtf.allgather_object(obj)
    assert isinstance(gathered, list) and gathered[0] == obj
