"""Backward-interleaved gradient exchange A/B (ops/overlap.py).

Measures whether bucketing the gradient exchange — N independent
collectives at their backward dataflow frontiers instead of one
terminal exchange — buys wall-clock on a real backend, the measured
form of the reference's autograd-hook overlap claim (arXiv 1802.05799
§3; the pre-registered exposed-time model is in docs/perf.md
§"Backward-interleaved gradient exchange").

Three legs over the SAME deep-MLP training step (many equal layers, so
backward compute exists to hide wire time behind), each appending one
JSON artifact under BENCH_ARTIFACT_DIR (default bench_results/overlap/):

* ``ab_monolithic``   — hvd.value_and_grad, post-hoc exchange (the
  barrier baseline: every collective waits for the full grad tree).
* ``ab_bucketed``     — hvd.value_and_grad(overlap_buckets=N): the
  in-backprop bucketed exchange via the overlap_boundary custom_vjp.
* ``ab_bucketed_rs``  — ShardedDistributedOptimizer(overlap_buckets=N):
  bucketed reduce-scatter feeding the ZeRO-1 shard update, bucketed
  all-gather of the updates.

Each artifact records ms/step plus the compiled-program evidence the
wall clock alone can't carry on CPU: the count of independent
collective ops in the lowered step (all_reduce / reduce_scatter /
all_gather) and the schedule's bucket byte split. BENCH_DRYRUN=1 is
the CI smoke shape (tiny model, 2 iters; `./ci.sh bench-smoke` gates
on the artifacts existing). CPU lines carry the quarantine note —
overlap is a scheduler property, so only the on-chip capture decides
the wall-clock claim; the dryrun validates harness + HLO shape.

Env: BENCH_LAYERS / BENCH_WIDTH / BENCH_BUCKETS / BENCH_ITERS.
"""

import json
import os
import time

from _benchlib import stamp as _stamp
from functools import partial

_SIM_NOTE = (
    "logic-validation only (CPU simulation); overlap is an XLA "
    "scheduler property — NOT a TPU wall-clock number"
)


def _collective_counts(lowered_text: str) -> dict:
    return {
        "all_reduce": lowered_text.count('"stablehlo.all_reduce"'),
        "reduce_scatter": lowered_text.count(
            '"stablehlo.reduce_scatter"'
        ),
        "all_gather": lowered_text.count('"stablehlo.all_gather"'),
    }


def main():
    import jax

    if os.environ.get("BENCH_PLATFORM"):
        jax.config.update("jax_platforms", os.environ["BENCH_PLATFORM"])

    import jax.numpy as jnp
    import numpy as np
    import optax
    from jax.sharding import PartitionSpec as P

    import horovod_tpu as hvd
    from _benchlib import sync as _sync
    from horovod_tpu.ops import overlap

    dryrun = os.environ.get("BENCH_DRYRUN", "").strip() in ("1", "true")
    iters = int(os.environ.get("BENCH_ITERS", "2" if dryrun else "30"))
    layers = int(os.environ.get("BENCH_LAYERS", "4" if dryrun else "24"))
    width = int(os.environ.get("BENCH_WIDTH", "32" if dryrun else "1024"))
    n_buckets = int(os.environ.get("BENCH_BUCKETS", "4"))
    batch = 8 if dryrun else 64

    artifact_dir = os.environ.get(
        "BENCH_ARTIFACT_DIR", os.path.join("bench_results", "overlap")
    )
    os.makedirs(artifact_dir, exist_ok=True)

    hvd.init()
    mesh = hvd.mesh()
    world = hvd.size()
    platform = jax.devices()[0].platform
    rng = np.random.default_rng(0)
    # host arrays: every leg builds its own device copies, so the
    # donated carries can never consume a buffer another leg reuses
    # (the bench_fusion.py discipline)
    params_host = {
        f"w{i:02d}": (
            rng.normal(size=(width, width)) / np.sqrt(width)
        ).astype(np.float32)
        for i in range(layers)
    }
    x = jnp.asarray(
        rng.normal(size=(world, batch, width)), jnp.float32
    )
    y = jnp.asarray(rng.normal(size=(world, batch, width)), jnp.float32)
    grad_bytes = sum(
        int(np.prod(p.shape)) * 4 for p in params_host.values()
    )

    def fresh_params():
        return {k: jnp.asarray(v) for k, v in params_host.items()}

    def loss_fn(p, xb, yb):
        h = xb
        for k in sorted(p):
            h = jnp.tanh(h @ p[k])
        return jnp.mean((h - yb) ** 2)

    def emit(leg, ms, counts, extra=None):
        line = {
            "metric": "overlap_ab",
            "leg": leg,
            "world": world,
            "layers": layers,
            "width": width,
            "grad_bytes": grad_bytes,
            "n_buckets": n_buckets,
            "value": round(ms, 3),
            "unit": "ms/step",
            "platform": platform,
            "collectives": counts,
        }
        if extra:
            line.update(extra)
        if platform != "tpu":
            line["note"] = _SIM_NOTE
        print(json.dumps(_stamp(line)), flush=True)
        with open(
            os.path.join(artifact_dir, f"overlap_{leg}.json"), "a"
        ) as f:
            f.write(json.dumps(_stamp(line)) + "\n")

    def timed(step, carry):
        carry = step(carry)  # compile + warm
        _sync(carry)
        t0 = time.perf_counter()
        for _ in range(iters):
            carry = step(carry)
        _sync(carry)
        return (time.perf_counter() - t0) / iters * 1e3

    # ---- legs 1+2: tape exchange, monolithic vs in-backprop bucketed
    def make_tape_step(buckets):
        vg = hvd.value_and_grad(
            loss_fn, op=hvd.Average, overlap_buckets=buckets,
            overlap_min_bytes=0,
        )
        opt = optax.sgd(1e-3)

        @partial(
            jax.shard_map, mesh=mesh,
            in_specs=((P(), P()), P(hvd.WORLD_AXIS), P(hvd.WORLD_AXIS)),
            out_specs=(P(), P()),
            check_vma=False,
        )
        def step(carry, xb, yb):
            p, ost = carry
            _, g = vg(p, xb[0], yb[0])
            u, ost = opt.update(g, ost, p)
            return optax.apply_updates(p, u), ost

        return jax.jit(step, donate_argnums=0), opt

    leg_ms = {}
    for leg, buckets in (
        ("ab_monolithic", 0),
        ("ab_bucketed", n_buckets),
    ):
        step, opt = make_tape_step(buckets)
        p0 = fresh_params()
        carry = (p0, optax.sgd(1e-3).init(p0))
        counts = _collective_counts(
            step.lower(carry, x, y).as_text()
        )
        ms = timed(lambda c: step(c, x, y), carry)
        leg_ms[max(buckets, 1)] = ms
        emit(leg, ms, counts)

    # the OverlapTuner consumes exactly these whole-step observations:
    # feed it the two tape legs and report its verdict (the harness IS
    # the tuner's driver — a bucket count is a compile-time property,
    # so candidates are separate jitted steps)
    # durable instance (HOROVOD_TUNER_CACHE): warm-started from prior
    # runs' observations and persisted at exit — the WireTuner's
    # persistence parity, extended to the bucket-count decision
    from horovod_tpu.common.autotune import shared_overlap_tuner

    tuner = shared_overlap_tuner(
        min_bucket_bytes=0, trials=1, candidates=(1, n_buckets)
    )
    for n, ms in leg_ms.items():
        tuner.record("bench", n, grad_bytes, ms / 1e3)
    choice = tuner.choose("bench", grad_bytes)
    print(
        json.dumps(
            {
                "metric": "overlap_tuner",
                "candidates": sorted(leg_ms),
                "choice": choice,
                "goodputs": {
                    str(n): round(tuner.goodput("bench", n), 1)
                    for n in leg_ms
                },
            }
        ),
        flush=True,
    )

    # ---- leg 3: bucketed reduce-scatter into the ZeRO-1 shard update
    sopt = hvd.ShardedDistributedOptimizer(
        optax.sgd(1e-3), overlap_buckets=n_buckets, overlap_min_bytes=0
    )
    p0 = fresh_params()
    sstate = sopt.init(p0)

    @partial(
        jax.shard_map, mesh=mesh,
        in_specs=(
            (P(), sopt.state_spec()),
            P(hvd.WORLD_AXIS),
            P(hvd.WORLD_AXIS),
        ),
        out_specs=(P(), sopt.state_spec()),
        check_vma=False,
    )
    def zstep(carry, xb, yb):
        p, st = carry
        g = jax.grad(loss_fn)(p, xb[0], yb[0])
        u, st = sopt.update(g, st, p)
        return optax.apply_updates(p, u), st

    zstep = jax.jit(zstep, donate_argnums=0)
    carry = (p0, sstate)
    counts = _collective_counts(zstep.lower(carry, x, y).as_text())
    ms = timed(lambda c: zstep(c, x, y), carry)
    emit(
        "ab_bucketed_rs", ms, counts,
        extra={"schedule_cache": overlap.schedule_cache_stats()},
    )


if __name__ == "__main__":
    main()
