"""Language-model pretraining throughput — BERT-large / GPT-2-medium.

The two tracked LM configs from BASELINE.json [V]: BERT-large with
Adasum gradient combination (config #3) and GPT-2 medium with
hierarchical allreduce (config #4). Prints ONE JSON line:
  {"metric": "<model>_samples_per_sec", "value": N, "unit": "samples/s"}

Env: BENCH_MODEL=bert_large|gpt2_medium (default bert_large),
BENCH_BATCH (default 8), BENCH_SEQ (default: model max 512/1024 capped
at 512), BENCH_ITERS (default 10), BENCH_PLATFORM=cpu + tiny model for
the harness smoke test (BENCH_TINY=1).

``BENCH_AB=local_sgd`` runs the local-SGD A/B instead
(``ab_local_sgd`` legs, PR 14 / ROADMAP item 3): the SAME tiny-LM
training loop twice — ``k1`` (the existing path: hierarchical int8
allreduce every step, the PR 10 wire) vs ``k8``
(``DistributedOptimizer(local_sgd_steps=K)``: ICI-only local steps, a
hierarchical-Adasum int8 reconciliation round every K steps via
``hvd.local_sgd.maybe_sync``). Each leg appends one JSON artifact
(``lm_ab_local_sgd_<leg>.json`` under BENCH_ARTIFACT_DIR) with
ms/step, the full loss trajectory, the lowered step program's
collective counts, and the per-hop byte ledger from the shared
payload-width model (``FusionManager._hop_bytes`` for the every-step
wire, ``local_sgd.round_inter_bytes`` — the VHDD model — for the
rounds): ``inter_bytes_per_step`` and ``inter_ratio_vs_k1``.
BENCH_DRYRUN=1 is the CI smoke shape and gates the two pre-registered
predictions (docs/perf.md): inter bytes/step drop ≥ K/2× vs the k1
hier-int8 row, and the K-step leg keeps ≥ half of k1's loss
improvement. The k8 step program is additionally asserted to carry
ZERO inter-slice replica groups (the hloaudit rule, run inline).
Env: BENCH_LOCAL_K (default 8), BENCH_INTRA (default 4),
BENCH_AB_STEPS (default 2·K), BENCH_BATCH/BENCH_SEQ as above.
"""

import json
import os
import time

from _benchlib import stamp as _stamp
from functools import partial

import numpy as np

_SIM_NOTE = (
    "logic-validation only (CPU simulation); step-time is NOT a TPU "
    "wall-clock number — byte accounting, loss math and HLO shape are "
    "exact"
)


def run_ab_local_sgd():
    """The ``ab_local_sgd`` A/B legs (module docstring)."""
    import jax

    if os.environ.get("BENCH_PLATFORM"):
        jax.config.update("jax_platforms", os.environ["BENCH_PLATFORM"])

    import jax.numpy as jnp
    import optax
    from jax.sharding import PartitionSpec as P

    import horovod_tpu as hvd
    from _benchlib import sync as _sync
    from horovod_tpu import analysis, local_sgd
    from horovod_tpu.analysis import rules
    from horovod_tpu.common.topology import hierarchical_stage_groups
    from horovod_tpu.models import Transformer, TransformerConfig
    from horovod_tpu.ops.fusion import FusionManager

    dryrun = os.environ.get("BENCH_DRYRUN", "").strip() in ("1", "true")
    k = int(os.environ.get("BENCH_LOCAL_K", "8"))
    intra = int(os.environ.get("BENCH_INTRA", "4"))
    batch = int(os.environ.get("BENCH_BATCH", "2" if dryrun else "8"))
    hvd.init()
    mesh = hvd.mesh()
    world = hvd.size()
    if world % intra:
        intra = 2 if world % 2 == 0 else 1
    stages = hierarchical_stage_groups(world, intra)
    if stages is None:
        raise SystemExit(
            f"no two-level split for world={world} intra={intra}"
        )
    L, H = intra, world // intra
    intra_groups = tuple(tuple(g) for g in stages[0])
    steps = int(os.environ.get("BENCH_AB_STEPS", str(2 * k)))
    steps = max(steps, k)  # at least one full round
    platform = jax.devices()[0].platform
    artifact_dir = os.environ.get(
        "BENCH_ARTIFACT_DIR", os.path.join("bench_results", "lm")
    )
    os.makedirs(artifact_dir, exist_ok=True)

    cfg = TransformerConfig.tiny(causal=True) if dryrun else (
        TransformerConfig.gpt2_medium()
    )
    seq = int(os.environ.get("BENCH_SEQ", str(min(cfg.max_len, 32 if dryrun else 512))))
    model = Transformer(cfg)
    tokens0 = jnp.zeros((batch, seq), jnp.int32)
    params0 = jax.jit(
        lambda: model.init(jax.random.PRNGKey(0), tokens0, train=False)
    )()
    grad_bytes = sum(
        int(np.prod(np.shape(l))) * 4
        for l in jax.tree_util.tree_leaves(params0)
    )
    rng = np.random.default_rng(0)
    # per-rank data: slices see DIFFERENT streams, so local phases
    # genuinely diverge before each round reconciles them
    toks = jnp.asarray(
        rng.integers(0, cfg.vocab_size, size=(steps, world, batch, seq)),
        jnp.int32,
    )
    labels = jnp.asarray(
        rng.integers(0, cfg.vocab_size, size=(steps, world, batch, seq)),
        jnp.int32,
    )

    def make_leg(leg_k):
        if leg_k > 1:
            opt = hvd.DistributedOptimizer(
                optax.sgd(0.05, momentum=0.9), op=hvd.Average,
                local_sgd_steps=leg_k, local_sgd_intra=intra,
            )
        else:
            # the existing path: the PR 10 two-level wire, int8 on the
            # DCN hop, EVERY step — the baseline the ÷K claim is
            # measured against
            opt = hvd.DistributedOptimizer(
                optax.sgd(0.05, momentum=0.9), op=hvd.Average,
                compression=hvd.Compression.hier_int8,
            )

        @partial(
            jax.shard_map, mesh=mesh,
            in_specs=(
                P(hvd.WORLD_AXIS), P(hvd.WORLD_AXIS),
                P(hvd.WORLD_AXIS), P(hvd.WORLD_AXIS),
            ),
            out_specs=(
                P(hvd.WORLD_AXIS), P(hvd.WORLD_AXIS), P(hvd.WORLD_AXIS),
            ),
            check_vma=False,
        )
        def step(pm, sm, tk, lb):
            p = jax.tree_util.tree_map(lambda x: x[0], pm)
            s = jax.tree_util.tree_map(lambda x: x[0], sm)
            tk, lb = tk[0], lb[0]

            def loss_fn(q):
                logits = model.apply(q, tk, train=True)
                return optax.softmax_cross_entropy_with_integer_labels(
                    logits.astype(jnp.float32), lb
                ).mean()

            loss, grads = jax.value_and_grad(loss_fn)(p)
            u, s = opt.update(grads, s, p)
            p = optax.apply_updates(p, u)
            add = jax.tree_util.tree_map(lambda x: x[None], (p, s))
            # per-rank loss rides home rank-major: a cross-slice mean
            # would put an inter-spanning collective INSIDE the
            # local-phase program — the host averages the rows instead
            return add[0], add[1], loss[None]

        sync_step = None
        if leg_k > 1:
            @partial(
                jax.shard_map, mesh=mesh,
                in_specs=(P(hvd.WORLD_AXIS), P(hvd.WORLD_AXIS)),
                out_specs=(P(hvd.WORLD_AXIS), P(hvd.WORLD_AXIS)),
                check_vma=False,
            )
            def sync_step(pm, sm):
                p = jax.tree_util.tree_map(lambda x: x[0], pm)
                s = jax.tree_util.tree_map(lambda x: x[0], sm)
                p, s = opt.sync(p, s)
                return jax.tree_util.tree_map(
                    lambda x: x[None], (p, s)
                )

            sync_step = jax.jit(sync_step)
        return opt, jax.jit(step), sync_step

    def rank_major(tree):
        return jax.tree_util.tree_map(
            lambda x: jnp.broadcast_to(
                jnp.asarray(x)[None],
                (world,) + tuple(np.shape(x)),
            ),
            tree,
        )

    block = 512
    results = {}
    for leg_k, leg in ((1, "k1"), (k, "k8")):
        opt, step, sync_step = make_leg(leg_k)
        pm = rank_major(params0)
        sm = rank_major(opt.init(params0))
        g = analysis.parse_module(step.lower(pm, sm, toks[0], labels[0]))
        counts = g.counts()
        if leg_k > 1:
            # the lowered local-phase program must carry ZERO
            # inter-slice replica groups (the hloaudit invariant,
            # asserted inline on the real bench program)
            for kind in (
                "all_reduce", "reduce_scatter", "all_gather",
                "all_to_all", "collective_permute",
            ):
                analysis.expect(
                    g,
                    rules.ReplicaGroupStructure(
                        kind, groups_any_of=(intra_groups,),
                        forbid_world_spanning=True,
                    ),
                )
        losses = []
        rounds = 0
        # warm (compile) outside the timed loop
        pm_w, sm_w, l0 = step(pm, sm, toks[0], labels[0])
        _sync(l0)
        pm, sm = pm_w, sm_w
        losses.append(float(np.mean(np.asarray(l0))))
        t0 = time.perf_counter()
        for i in range(1, steps):
            pm, sm, loss = step(pm, sm, toks[i], labels[i])
            losses.append(float(np.mean(np.asarray(loss))))
            if leg_k > 1 and local_sgd.due(i, leg_k):
                out, synced = local_sgd.run_round(
                    sync_step, pm, sm,
                    payload_bytes=grad_bytes, stages=stages,
                )
                if synced:
                    pm, sm = out
                    rounds += 1
        _sync(pm)
        ms = (time.perf_counter() - t0) * 1e3 / max(steps - 1, 1)
        # per-hop byte ledger, shared payload-width models
        elems = grad_bytes // 4
        if leg_k == 1:
            # hier-int8 every step: bf16 intra legs + int8 inter on
            # the 1/L shard (bench_hier's accounting)
            ib, _ = FusionManager._hop_bytes(
                -(-elems // L), "int8", 4, H, block
            )
            inter_per_step = ib
        else:
            round_bytes = local_sgd.round_inter_bytes(
                grad_bytes, stages, "int8"
            )
            inter_per_step = round_bytes / leg_k
        line = {
            "metric": "lm_ab_local_sgd",
            "leg": leg,
            "k": leg_k,
            "world": world,
            "intra": L,
            "slices": H,
            "steps": steps,
            "rounds": rounds,
            "grad_bytes": grad_bytes,
            "value": round(ms, 3),
            "unit": "ms/step",
            "platform": platform,
            "collectives": counts,
            "inter_bytes_per_step": round(inter_per_step, 1),
            "loss_first": round(losses[0], 4),
            "loss_final": round(losses[-1], 4),
            "losses": [round(x, 4) for x in losses],
        }
        if platform != "tpu":
            line["note"] = _SIM_NOTE
        results[leg] = line

    r1, r8 = results["k1"], results["k8"]
    ratio = (
        r1["inter_bytes_per_step"] / r8["inter_bytes_per_step"]
        if r8["inter_bytes_per_step"]
        else float("inf")
    )
    r8["inter_ratio_vs_k1"] = round(ratio, 2)
    r1["inter_ratio_vs_k1"] = 1.0
    for leg, line in results.items():
        print(json.dumps(_stamp(line)), flush=True)
        with open(
            os.path.join(artifact_dir, f"lm_ab_local_sgd_{leg}.json"), "a"
        ) as f:
            f.write(json.dumps(_stamp(line)) + "\n")
    # pre-registered gates (docs/perf.md): the sync rounds moved the
    # expected ÷K of the every-step wire's DCN bytes, and the K-step
    # leg kept at least half of k1's loss improvement
    assert r8["rounds"] >= 1, "no sync round ran"
    assert ratio >= k / 2, (
        f"inter-byte drop {ratio:.2f}x < pre-registered K/2 = {k / 2}"
    )
    imp1 = r1["loss_first"] - r1["loss_final"]
    imp8 = r8["loss_first"] - r8["loss_final"]
    assert imp1 > 0, f"k1 leg did not learn: {imp1}"
    assert imp8 >= 0.5 * imp1, (
        f"k8 loss improvement {imp8:.4f} < half of k1's {imp1:.4f}"
    )


def main():
    if os.environ.get("BENCH_AB", "").strip() == "local_sgd":
        return run_ab_local_sgd()
    import jax

    if os.environ.get("BENCH_PLATFORM"):
        jax.config.update("jax_platforms", os.environ["BENCH_PLATFORM"])

    import jax.numpy as jnp
    import optax
    from jax.sharding import PartitionSpec as P

    import horovod_tpu as hvd
    from horovod_tpu.models import Transformer, TransformerConfig

    model_name = os.environ.get("BENCH_MODEL", "bert_large")
    batch = int(os.environ.get("BENCH_BATCH", "8"))
    iters = int(os.environ.get("BENCH_ITERS", "10"))

    hvd.init()
    mesh = hvd.mesh()

    if os.environ.get("BENCH_TINY"):
        cfg = TransformerConfig.tiny(causal=(model_name == "gpt2_medium"))
    elif model_name == "gpt2_medium":
        cfg = TransformerConfig.gpt2_medium()
    else:
        cfg = TransformerConfig.bert_large()
    # remat trades FLOPs for memory; at bench batch sizes the model may
    # fit without it, making it pure recompute overhead — BENCH_REMAT=0
    # measures that. Default stays on (the large-model-safe setting).
    remat = not os.environ.get("BENCH_TINY") and os.environ.get(
        "BENCH_REMAT", "1"
    ) not in ("0", "false", "off")
    cfg = dataclasses_replace(cfg, remat=remat)
    if os.environ.get("BENCH_FLASH", "auto") in ("0", "false", "off"):
        # escape hatch: dense attention (e.g. if the Pallas kernel
        # misbehaves on a new libtpu)
        cfg = dataclasses_replace(cfg, flash_attention=False)
    if os.environ.get("BENCH_HEAD") == "fp32":
        # A/B escape hatch for the mixed-precision LM head default
        cfg = dataclasses_replace(cfg, head_mixed_precision=False)
    if os.environ.get("BENCH_KV_HEADS"):
        # grouped-query attention A/B: fewer KV heads (must divide the
        # model's head count); the kernels read shared KV rows directly
        cfg = dataclasses_replace(
            cfg, num_kv_heads=int(os.environ["BENCH_KV_HEADS"])
        )
    if os.environ.get("BENCH_FLASH_BLOCK"):
        bq = int(os.environ["BENCH_FLASH_BLOCK"])
        if bq < 8 or (bq & (bq - 1)) != 0:
            raise SystemExit(
                f"BENCH_FLASH_BLOCK={bq}: must be a power of two >= 8 "
                "(Mosaic tiling; see ops/flash_attention.py)"
            )
        cfg = dataclasses_replace(cfg, flash_block_q=bq, flash_block_k=bq)
    seq = int(os.environ.get("BENCH_SEQ", str(min(cfg.max_len, 512))))

    # The BASELINE pairing: BERT-large exercises Adasum, GPT-2 medium the
    # hierarchical two-level reduction (BASELINE.json configs [V]).
    if model_name == "bert_large":
        reduce_op = hvd.Adasum
    else:
        reduce_op = hvd.Average
        os.environ.setdefault("HOROVOD_HIERARCHICAL_ALLREDUCE", "1")

    model = Transformer(cfg)
    tokens = jnp.zeros((batch, seq), jnp.int32)
    params = jax.jit(
        lambda: model.init(jax.random.PRNGKey(0), tokens, train=False)
    )()
    opt = hvd.DistributedOptimizer(
        optax.sgd(0.01, momentum=0.9), op=reduce_op
    )
    opt_state = opt.init(params)

    # Chunked fused linear-cross-entropy (ops/fused_xent.py): never
    # materializes the (batch·seq, vocab) logits — the step's largest
    # activation (~823 MB fp32 at GPT-2-medium b8/s512) — at the cost
    # of one logits recompute in backward. BENCH_FUSED_XENT=1 enables
    # it for the on-chip A/B; BENCH_XENT_CHUNK tunes the vocab chunk.
    fused_xent = os.environ.get("BENCH_FUSED_XENT", "0") not in (
        "0", "false", "off"
    )
    xent_chunk = int(os.environ.get("BENCH_XENT_CHUNK", "8192"))
    # BENCH_PADDED=1: right-padded batch (uniform lengths in
    # [seq*3/4, seq]) driven through the kernels' native lengths=
    # support — measures the padded-path overhead vs the dense-mask
    # alternative the reference-style stack would pay. Loss masks
    # padded positions.
    padded = os.environ.get("BENCH_PADDED", "0") not in (
        "0", "false", "off"
    )

    # Padded mode: fixed synthetic lengths (the bench reuses one batch,
    # so a closed-over constant is consistent with its style). Loss
    # averages over valid positions only — the fused loss composes
    # because it returns per-token losses (masking the reduction zeroes
    # the masked tokens' cotangents through the custom VJP).
    bench_lens = (
        jnp.asarray(
            np.random.default_rng(7).integers(
                3 * seq // 4, seq + 1, size=(batch,)
            ),
            jnp.int32,
        )
        if padded
        else None
    )

    @partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(P(), P(), P(hvd.WORLD_AXIS), P(hvd.WORLD_AXIS)),
        out_specs=(P(), P(), P()),
        check_vma=False,
    )
    def train_step(params, opt_state, tokens, labels):
        tokens, labels = tokens[0], labels[0]

        def loss_fn(p):
            if fused_xent:
                from horovod_tpu.ops.fused_xent import (
                    fused_linear_cross_entropy,
                )

                hidden = model.apply(
                    p, tokens, train=True, return_hidden=True,
                    lengths=bench_lens,
                )
                head = p["params"]["lm_head"]
                per_tok = fused_linear_cross_entropy(
                    hidden.reshape(-1, cfg.d_model),
                    head["kernel"],
                    head["bias"],
                    labels.reshape(-1),
                    chunk=xent_chunk,
                    compute_dtype=(
                        cfg.dtype if cfg.head_mixed_precision else None
                    ),
                )
                if padded:
                    valid = (
                        jnp.arange(tokens.shape[1])[None, :]
                        < bench_lens[:, None]
                    ).reshape(-1)
                    return jnp.sum(
                        jnp.where(valid, per_tok, 0.0)
                    ) / jnp.sum(valid)
                return per_tok.mean()
            if padded:
                logits = model.apply(
                    p, tokens, train=True, lengths=bench_lens
                )
                per_tok = optax.softmax_cross_entropy_with_integer_labels(
                    logits.astype(jnp.float32), labels
                )
                valid = (
                    jnp.arange(tokens.shape[1])[None, :]
                    < bench_lens[:, None]
                )
                return jnp.sum(
                    jnp.where(valid, per_tok, 0.0)
                ) / jnp.sum(valid)
            logits = model.apply(p, tokens, train=True)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits.astype(jnp.float32), labels
            ).mean()

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = opt.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, jax.lax.pmean(loss, hvd.WORLD_AXIS)

    # No donation here: fresh-initialized params contain aliased
    # (deduplicated) zero buffers, and donating the same buffer twice is
    # an XLA error.
    step = jax.jit(train_step)
    rng = np.random.default_rng(0)
    world = hvd.size()
    toks = jnp.asarray(
        rng.integers(0, cfg.vocab_size, size=(world, batch, seq)), jnp.int32
    )
    labels = jnp.asarray(
        rng.integers(0, cfg.vocab_size, size=(world, batch, seq)), jnp.int32
    )

    from _benchlib import aot_compile, bytes_accessed, mfu_fields

    step, flops = aot_compile(step, params, opt_state, toks, labels)
    step_bytes = bytes_accessed(step)
    flops_note = None
    if flops and cfg.uses_flash(seq=seq):
        # The Pallas flash-attention kernels are custom calls — invisible
        # to XLA cost analysis — so add their matmul FLOPs analytically:
        # fwd 2 matmuls (QKᵀ, PV) = 4·b·s²·d, bwd ≈ 2× fwd (dq/dk/dv +
        # blockwise recompute), halved for causal masking.
        attn = 12.0 * batch * world * (seq**2) * cfg.d_model * cfg.num_layers
        if cfg.causal:
            attn /= 2.0
        flops += attn
        flops_note = (
            "xla_cost_analysis + analytic flash-attention matmul flops"
        )
    from _benchlib import sync as _sync

    params, opt_state, loss = step(params, opt_state, toks, labels)
    _sync(loss)  # warm; host transfer is the only trustworthy sync
    t0 = time.perf_counter()
    for _ in range(iters):
        params, opt_state, loss = step(params, opt_state, toks, labels)
    _sync(loss)  # loss chains through every step's params
    dt = time.perf_counter() - t0
    samples_per_sec = batch * world * iters / dt
    result = {
        "metric": f"{model_name}_samples_per_sec",
        "value": round(samples_per_sec, 2),
        "unit": "samples/s",
        "batch": batch,
        "seq": seq,
        "world": world,
        "remat": remat,
        "head": "mixed" if cfg.head_mixed_precision else "fp32",
        "xent": "fused" if fused_xent else "dense",
        # padded mode: samples/s counts whole padded rows; MFU uses the
        # full-seq analytic attention flops, so it UNDERSTATES true
        # utilization on the valid tokens (conservative)
        "padded": padded,
        "kv_heads": cfg.num_kv_heads or cfg.num_heads,
        # provenance: the kernel auto-shrinks to the sequence, so record
        # the EFFECTIVE block, not the config ask (r04 flipped the
        # default 128->512 mid-capture-chain; without this field those
        # artifacts would be indistinguishable)
        "flash_block": (
            _effective_block(seq, cfg) if cfg.uses_flash(seq=seq) else None
        ),
        "platform": jax.devices()[0].platform,
    }
    result.update(mfu_fields(flops, iters, dt, jax.devices()[0].platform,
                             step_bytes=step_bytes))
    if flops_note:
        result["flops_note"] = flops_note
    print(json.dumps(_stamp(result)))


def _effective_block(seq, cfg):
    from horovod_tpu.ops.flash_attention import _pick_block

    return _pick_block(seq, cfg.flash_block_q)


def dataclasses_replace(cfg, **kw):
    import dataclasses

    return dataclasses.replace(cfg, **kw)


if __name__ == "__main__":
    main()
