"""Eager collective API: async handles + blocking wrappers.

API-parity layer with the reference's per-framework op modules
(ref: horovod/torch/mpi_ops.py — allreduce/allreduce_async/allreduce_/
allgather/broadcast/alltoall/reducescatter/synchronize/poll [V],
SURVEY.md §2.4), dispatching into the fusion manager (fusion.py).

Data model (single controller): each eager collective operates on a
**rank-major global array** — leading axis of length ``hvd.size()``, row r
being rank r's tensor, sharded one row per chip (see
common/topology.py). Helpers:

* ``hvd.replicate(x)``      — every rank contributes the same ``x``.
* ``hvd.shard_from_rank_fn``— row r = fn(r)  (test/benchmark pattern).
* Results are rank-major too; ``result[r]`` is what rank r receives.

Uneven-shape support (allgather-v, alltoall-v) follows the reference's
MPI_*v semantics via padding on the fused path or host repack.

Buffer donation: on backends with aliasing support (TPU/GPU), the fused
dispatch path DONATES its input buffers to the compiled executable so
the fusion buffer aliases the argument storage instead of doubling peak
HBM (``HOROVOD_FUSION_DONATE``; see ops/fusion.py). Treat eager
collectives as CONSUMING their inputs — the reference's in-place
``allreduce_`` contract — and use the returned array; re-reading a
donated ``jax.Array`` input afterwards raises. Inputs passed as numpy
are staged to fresh device buffers first and are never affected. Set
``HOROVOD_FUSION_DONATE=0`` for strict functional semantics.
"""

from __future__ import annotations

import itertools
from typing import List, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from ..common import basics
from ..common.process_sets import ProcessSet
from .fusion import Handle, _Entry
from .reduction_ops import Average, ReduceOp, Sum, resolve_op

_name_counter = itertools.count()


def _auto_name(prefix: str, name: Optional[str]) -> str:
    if name is not None:
        return name
    return f"{prefix}.noname.{next(_name_counter)}"


def _fusion():
    return basics._require_init().fusion


def _world() -> int:
    return basics.size()


def _as_rank_major(tensor, world: int) -> jax.Array:
    arr = jnp.asarray(tensor)
    if arr.ndim == 0 or arr.shape[0] != world:
        raise ValueError(
            f"eager collectives take rank-major input with leading axis "
            f"hvd.size()={world}; got shape {arr.shape}. Wrap per-rank-"
            f"identical input with hvd.replicate(x)."
        )
    return arr


def replicate(tensor) -> jax.Array:
    """Rank-major array where every rank contributes the same value."""
    st = basics._require_init()
    arr = jnp.asarray(tensor)
    return jnp.broadcast_to(arr[None], (st.topology.size,) + arr.shape)


def first(result) -> jax.Array:
    """Rank 0's view of a rank-major result."""
    return result[0]


def my_row(result) -> np.ndarray:
    """THIS process's row of a rank-major result — the multi-process-safe
    read (each process gets what its rank received, like the reference's
    per-process return value [V]).

    Under multi-controller JAX every process must run the SAME program
    on a global array, so ``result[hvd.rank()]`` — a different index per
    process — is divergent and silently returns garbage. This reads the
    locally-addressable shard instead: no cross-process computation at
    all. Single-process (controller) callers get rank 0's row, same as
    ``first``.
    """
    r = basics.rank()
    shards = getattr(result, "addressable_shards", None)
    if shards:
        for s in shards:
            idx = s.index[0] if s.index else slice(None)
            if not isinstance(idx, slice):
                continue
            start = idx.start or 0
            # an open slice means the row dim is replicated on this
            # shard — it covers every row
            stop = idx.stop if idx.stop is not None else result.shape[0]
            if start <= r < stop:
                return np.asarray(s.data)[r - start]
    return np.asarray(result[r])


# ----------------------------------------------------------------- allreduce


def _wire_of(compression, return_residual: bool) -> Optional[str]:
    """Map an eager ``compression=`` argument to the fused wire format
    (the eager path compresses the whole fused BUFFER inside the
    compiled executable rather than tensor-by-tensor on the host; see
    ops/fusion.py). ``None`` defers to ``HOROVOD_FUSION_WIRE``."""
    wire = (
        None if compression is None
        else getattr(compression, "wire_format", None)
    )
    if return_residual and wire not in (None, "int8", "int8_hier"):
        raise ValueError(
            "return_residual=True needs the int8 quantized wire "
            "(Compression.int8 / int8_block, or no compression= with "
            "HOROVOD_FUSION_WIRE=int8); the error-feedback residual IS "
            "the quantization error"
        )
    if return_residual and wire is None:
        wire = "int8"
    return wire


def _check_residual_eligible(op, payload) -> None:
    """return_residual's op/dtype constraints, enforced at ENQUEUE: a
    flush-time failure would abort the whole cycle and strand every
    other pending entry's handle — the caller who passed the bad
    argument must be the one who gets the exception."""
    if op not in (Average, Sum):
        raise ValueError(
            f"return_residual needs the int8 quantized wire, which "
            f"supports Sum/Average only (got op={op!r})"
        )
    if not jnp.issubdtype(payload.dtype, jnp.floating):
        raise ValueError(
            f"return_residual needs a floating payload (got "
            f"{payload.dtype}); integer tensors ride the exact fp32 "
            f"wire, which has no quantization residual"
        )


def allreduce_async(
    tensor,
    average: Optional[bool] = None,
    name: Optional[str] = None,
    op: Optional[ReduceOp] = None,
    prescale_factor: float = 1.0,
    postscale_factor: float = 1.0,
    process_set: Optional[ProcessSet] = None,
    mask: Optional[np.ndarray] = None,
    compression=None,
    return_residual: bool = False,
) -> Handle:
    """``compression=`` (Compression.bf16/int8/int8_block/hier_int8;
    fp16 maps to the bf16 wire — TPU's native 2-byte format) selects
    the WIRE FORMAT of the fused buffer — the whole batch is cast or
    block-quantized inside the one compiled executable, not compressed
    per tensor on the host. ``return_residual=True`` (int8 wire only)
    makes the handle resolve to ``(output, residual)``, the
    error-feedback carry sliced from the fused residual buffer — add
    it to the next step's tensor (EF-SGD)."""
    op = resolve_op(op, average)
    fusion = _fusion()
    payload = _as_rank_major(tensor, fusion.world)
    wire = _wire_of(compression, return_residual)
    if return_residual:
        _check_residual_eligible(op, payload)
    if mask is None:
        mask = JoinContext._active_mask
    entry = _Entry(
        name=_auto_name("allreduce", name),
        kind="allreduce",
        payload=payload,
        op=op,
        prescale=float(prescale_factor),
        postscale=float(postscale_factor),
        process_set=process_set,
        mask=None if mask is None else np.asarray(mask, dtype=bool),
        wire=wire,
        wire_block=getattr(compression, "block_size", None),
        want_residual=bool(return_residual),
    )
    return fusion.enqueue(entry)


def allreduce(tensor, *args, **kwargs):
    return allreduce_async(tensor, *args, **kwargs).wait()


# In-place spellings: JAX arrays are immutable, so the _ variants return the
# new value like their functional counterparts (documented divergence).
allreduce_ = allreduce
allreduce_async_ = allreduce_async


def grouped_allreduce_async(
    tensors: Sequence,
    average: Optional[bool] = None,
    name: Optional[str] = None,
    op: Optional[ReduceOp] = None,
    prescale_factor: float = 1.0,
    postscale_factor: float = 1.0,
    process_set: Optional[ProcessSet] = None,
    compression=None,
    return_residual: bool = False,
) -> List[Handle]:
    """Enqueue a list atomically (ref: hvd.grouped_allreduce /
    group_table.cc [V]): all members land in the same cycle, so the fusion
    pass reduces them in one fused collective. With ``compression=``
    the members share ONE wire-format pass — quantize once over the
    fused buffer (see allreduce_async); ``return_residual=True`` makes
    each handle resolve to ``(output, residual)``."""
    base = _auto_name("grouped_allreduce", name)
    fusion = _fusion()
    mask = JoinContext._active_mask
    wire = _wire_of(compression, return_residual)
    handles = []
    entries = []
    for i, t in enumerate(tensors):
        payload = _as_rank_major(t, fusion.world)
        resolved = resolve_op(op, average)
        if return_residual:
            _check_residual_eligible(resolved, payload)
        entry = _Entry(
            name=f"{base}.{i}",
            kind="allreduce",
            payload=payload,
            op=resolved,
            prescale=float(prescale_factor),
            postscale=float(postscale_factor),
            process_set=process_set,
            mask=None if mask is None else np.asarray(mask, dtype=bool),
            wire=wire,
            wire_block=getattr(compression, "block_size", None),
            want_residual=bool(return_residual),
        )
        entries.append(entry)
    # Atomic enqueue: begin_group() defers threshold/cycle flushes until
    # every member is queued, and the shared group_id keeps the members
    # in one fused collective through batch splitting (group_table.cc
    # semantics [V]; members of mixed dtype still share the cycle but
    # fuse per-dtype, like the reference's typed fusion buffers).
    gid = fusion.begin_group()
    try:
        for entry in entries:
            entry.group_id = gid
            handles.append(fusion.enqueue(entry))
    finally:
        fusion.end_group()
    return handles


def grouped_allreduce(tensors, *args, **kwargs):
    return [h.wait() for h in grouped_allreduce_async(tensors, *args, **kwargs)]


def grouped_allgather_async(
    tensors: Sequence,
    name: Optional[str] = None,
    process_set: Optional[ProcessSet] = None,
) -> List[Handle]:
    """Atomic multi-tensor allgather (ref: hvd.grouped_allgather,
    upstream v0.28+ [V]): all members land in one cycle — begin_group
    defers the threshold/cycle flush until the whole list is queued."""
    fusion = _fusion()
    base = _auto_name("grouped_allgather", name)
    gid = fusion.begin_group()
    handles: List[Handle] = []
    try:
        for i, t in enumerate(tensors):
            h = allgather_async(
                t, name=f"{base}.{i}", process_set=process_set
            )
            if h._entry is not None:
                h._entry.group_id = gid
            handles.append(h)
    except Exception:
        # a member failed validation: the group must not partially
        # dispatch at end_group
        fusion.abort_group(gid)
        raise
    finally:
        fusion.end_group()
    return handles


def grouped_allgather(tensors, *args, **kwargs):
    return [
        h.wait() for h in grouped_allgather_async(tensors, *args, **kwargs)
    ]


def grouped_reducescatter_async(
    tensors: Sequence,
    op: Optional[ReduceOp] = None,
    name: Optional[str] = None,
    process_set: Optional[ProcessSet] = None,
) -> List[Handle]:
    """Atomic multi-tensor reduce-scatter (ref: hvd.grouped_reducescatter,
    upstream v0.28+ [V]): all members complete in one cycle. Even-shape
    members share the group's indivisible fused unit; members taking the
    uneven (v-variant) fallback reduce via allreduce entries that may
    fuse separately WITHIN the same cycle."""
    fusion = _fusion()
    base = _auto_name("grouped_reducescatter", name)
    gid = fusion.begin_group()
    handles: List[Handle] = []
    try:
        for i, t in enumerate(tensors):
            h = reducescatter_async(
                t, op=op, name=f"{base}.{i}", process_set=process_set
            )
            if getattr(h, "_entry", None) is not None:
                h._entry.group_id = gid
            handles.append(h)
    except Exception:
        fusion.abort_group(gid)
        raise
    finally:
        fusion.end_group()
    return handles


def grouped_reducescatter(tensors, *args, **kwargs):
    return [
        h.wait()
        for h in grouped_reducescatter_async(tensors, *args, **kwargs)
    ]


# ----------------------------------------------------------------- allgather


def allgather_async(
    tensor: Union[jax.Array, Sequence],
    name: Optional[str] = None,
    process_set: Optional[ProcessSet] = None,
) -> Handle:
    """Gather-v (ref: hvd.allgather / MPI_Allgatherv [V]). Input is either a
    rank-major array (equal dim0 per rank) or a list of per-rank arrays with
    possibly different dim0 — the v-case, handled by padding to the max and
    slicing after the fused gather."""
    fusion = _fusion()
    world = fusion.world
    lengths = None
    if isinstance(tensor, (list, tuple)):
        if len(tensor) != world:
            raise ValueError(
                f"allgather list input must have hvd.size()={world} entries"
            )
        rows = [jnp.asarray(t) for t in tensor]
        lengths = [int(r.shape[0]) for r in rows]
        if len(set(lengths)) == 1:
            payload = jnp.stack(rows)
            lengths = None
        else:
            max_n = max(lengths)
            padded = [
                jnp.pad(r, [(0, max_n - r.shape[0])] + [(0, 0)] * (r.ndim - 1))
                for r in rows
            ]
            payload = jnp.stack(padded)
    else:
        payload = _as_rank_major(tensor, world)
    entry = _Entry(
        name=_auto_name("allgather", name),
        kind="allgather",
        payload=payload,
        process_set=process_set,
        extra=lengths,
    )
    return fusion.enqueue(entry)


def allgather(tensor, *args, **kwargs):
    return allgather_async(tensor, *args, **kwargs).wait()


# ----------------------------------------------------------------- broadcast


def broadcast_async(
    tensor,
    root_rank: int,
    name: Optional[str] = None,
    process_set: Optional[ProcessSet] = None,
) -> Handle:
    fusion = _fusion()
    entry = _Entry(
        name=_auto_name("broadcast", name),
        kind="broadcast",
        payload=_as_rank_major(tensor, fusion.world),
        root_rank=int(root_rank),
        process_set=process_set,
    )
    return fusion.enqueue(entry)


def broadcast(tensor, root_rank, *args, **kwargs):
    return broadcast_async(tensor, root_rank, *args, **kwargs).wait()


broadcast_ = broadcast
broadcast_async_ = broadcast_async


# ------------------------------------------------------------------ alltoall


def alltoall_async(
    tensor,
    splits: Optional[Sequence[Sequence[int]]] = None,
    name: Optional[str] = None,
    process_set: Optional[ProcessSet] = None,
) -> Handle:
    """All-to-all (ref: hvd.alltoall / MPI_Alltoallv [V]).

    Equal-split case (no ``splits``): rank-major input [world, n, ...] with
    n % world == 0 → one fused XLA all_to_all on ICI.
    Uneven case: ``splits[r]`` = dim0 split sizes rank r sends to each peer;
    handled by a host-side repack (the v-variant is control-plane-bound in
    the reference too). Returns (output, received_splits) via the handle
    when splits are given.
    """
    fusion = _fusion()
    world = fusion.world
    if splits is None:
        payload = _as_rank_major(tensor, world)
        # Divisibility by the participating rank count (world or process-set
        # size) is validated at dispatch in the fusion manager.
        entry = _Entry(
            name=_auto_name("alltoall", name),
            kind="alltoall",
            payload=payload,
            process_set=process_set,
        )
        return fusion.enqueue(entry)
    # Uneven: repack on host, fulfill immediately. With a process set,
    # the exchange is scoped to the members (splits indexed by member
    # position, set-size entries per member row); non-members pass
    # their input through unchanged — the same contract as the traced
    # set alltoall (ref: process-set Alltoallv [V]).
    rows = (
        [np.asarray(t) for t in tensor]
        if isinstance(tensor, (list, tuple))
        else [np.asarray(tensor[r]) for r in range(world)]
    )
    if process_set is not None and process_set.process_set_id != 0:
        members = list(process_set.ranks)
    else:
        members = list(range(world))
    if len(splits) != world:
        raise ValueError(
            f"splits must have exactly one row per WORLD rank ({world}; "
            f"non-member rows are ignored), got {len(splits)} rows"
        )

    # convert/validate MEMBER rows only — non-member rows really are
    # ignored (placeholders like None are fine there)
    def _member_row(r, s):
        try:
            row = [int(v) for v in s]
        except (TypeError, ValueError):
            raise ValueError(
                f"alltoall splits row for member rank {r} must be a "
                f"sequence of ints, got {s!r}"
            ) from None
        if len(row) != len(members):
            raise ValueError(
                f"alltoall splits for rank {r} has {len(row)} "
                f"entries; expected one per participant ({len(members)})"
            )
        if sum(row) != rows[r].shape[0]:
            # numpy slicing clamps out-of-range offsets silently, which
            # would truncate data while recv_splits claims otherwise
            raise ValueError(
                f"alltoall splits for rank {r} sum to {sum(row)} but "
                f"that rank's tensor dim0 is {rows[r].shape[0]}"
            )
        return row

    member_set = set(members)
    splits = [
        _member_row(r, s) if r in member_set else None
        for r, s in enumerate(splits)
    ]
    outputs: list = [None] * world
    recv_splits: list = [None] * world
    offsets = {
        r: np.concatenate([[0], np.cumsum(splits[r])]) for r in members
    }
    for j, dst in enumerate(members):
        pieces = [
            rows[src][offsets[src][j] : offsets[src][j + 1]]
            for src in members
        ]
        outputs[dst] = jnp.concatenate(pieces, axis=0)
        recv_splits[dst] = [splits[src][j] for src in members]
    for r in range(world):
        if outputs[r] is None:  # non-member: input passes through
            outputs[r] = jnp.asarray(rows[r])
            recv_splits[r] = [rows[r].shape[0]]
    handle = Handle(fusion, None)
    handle._fulfill((outputs, recv_splits))
    return handle


def alltoall(tensor, *args, **kwargs):
    return alltoall_async(tensor, *args, **kwargs).wait()


# ------------------------------------------------------------- reducescatter


def reducescatter_async(
    tensor,
    op: Optional[ReduceOp] = None,
    name: Optional[str] = None,
    prescale_factor: float = 1.0,
    postscale_factor: float = 1.0,
    process_set: Optional[ProcessSet] = None,
) -> Handle:
    """Reduce-scatter (ref: hvd.reducescatter, upstream v0.27+ [V]).

    Return type depends on divisibility, because per-rank shard shapes do:
    when dim1 divides by the rank count every rank's shard is the same
    shape and the result is one rank-major array [world, n/world, ...];
    in the uneven case (MPI_Reduce_scatter-v parity: earlier ranks get one
    extra element) shard shapes differ per rank, so the result is a
    *list* of per-rank arrays — the honest representation of a
    heterogeneous result under a single controller."""
    fusion = _fusion()
    payload = _as_rank_major(tensor, fusion.world)
    op = resolve_op(op, None)
    participants = (
        list(range(fusion.world))
        if process_set is None or process_set.process_set_id == 0
        else list(process_set.ranks)
    )
    if payload.shape[1] % len(participants) != 0:
        h = allreduce_async(
            tensor,
            op=op,
            name=name,
            prescale_factor=prescale_factor,
            postscale_factor=postscale_factor,
            process_set=process_set,
        )

        class _SliceHandle(Handle):
            def wait(self_inner):
                full = h.wait()
                n = full.shape[1]
                base, rem = divmod(n, len(participants))
                out_rows = []
                off = 0
                for i, r in enumerate(participants):
                    ln = base + (1 if i < rem else 0)
                    out_rows.append(full[r, off : off + ln])
                    off += ln
                return out_rows

            def poll(self_inner):
                return h.poll()

        return _SliceHandle(fusion, None)
    entry = _Entry(
        name=_auto_name("reducescatter", name),
        kind="reducescatter",
        payload=payload,
        op=op,
        prescale=float(prescale_factor),
        postscale=float(postscale_factor),
        process_set=process_set,
    )
    return fusion.enqueue(entry)


def reducescatter(tensor, *args, **kwargs):
    return reducescatter_async(tensor, *args, **kwargs).wait()


# ------------------------------------------------------------- sync / poll


def synchronize(handle: Handle):
    """Block until the handle's collective completes (ref:
    horovod/torch/mpi_ops.py::synchronize → WaitAndClear [V])."""
    return handle.wait()


def poll(handle: Handle) -> bool:
    return handle.poll()


def flush() -> None:
    """Force an eager fusion cycle now (no direct reference analog — the
    background thread did this on a timer)."""
    _fusion().flush()


# ------------------------------------------------------------------- join


class JoinContext:
    """Masked participation for uneven data (ref: hvd.join / JoinOp in
    collective_operations.cc [V], SURVEY.md §7.3 hard part #3).

    The reference's join lets a rank that ran out of data drop out of
    subsequent allreduces; averages divide by the number of non-joined
    ranks. Under a single controller the set of joined ranks is known, so
    join becomes a mask applied to eager allreduces:

        with hvd.join_ranks([3]):         # rank 3 has no more data
            out = hvd.allreduce(x)        # rows averaged over ranks != 3
    """

    _active_mask: Optional[np.ndarray] = None

    def __init__(self, joined_ranks: Sequence[int]):
        world = _world()
        mask = np.ones(world, dtype=bool)
        for r in joined_ranks:
            mask[r] = False
        self._mask = mask
        self._prev = None

    def __enter__(self):
        self._prev = JoinContext._active_mask
        JoinContext._active_mask = self._mask
        return self

    def __exit__(self, *exc):
        JoinContext._active_mask = self._prev
        return False


def join_ranks(joined: Sequence[int]) -> JoinContext:
    return JoinContext(joined)


def current_join_mask() -> Optional[np.ndarray]:
    return JoinContext._active_mask


def join(joined_ranks: Optional[Sequence[int]] = None) -> int:
    """API-parity join. With ``joined_ranks`` returns the last joined rank
    (matching the reference's return of last_joined_rank [V]); bare
    ``join()`` is a no-op barrier under a single controller."""
    _fusion().flush()
    if joined_ranks:
        return max(joined_ranks)
    return -1


def barrier(process_set: Optional[ProcessSet] = None) -> None:
    """Block until every process in ``process_set`` (default: all) has
    entered the barrier (ref: horovod/common/basics.py ``barrier`` and
    its torch/TF bindings [V]).

    Implemented the reference's way — as a degenerate collective: a
    one-element allreduce over the set, fetched to the host. Pending
    fused work flushes first (enqueue-then-wait drives the cycle), and
    under multi-controller ``jax.distributed`` the global-array result
    cannot materialize until every participating process has
    contributed its shard, which is exactly the barrier."""
    st = basics._require_init()
    token = jnp.zeros((st.topology.size, 1), jnp.float32)
    result = allreduce(
        token, op=Average,
        name=_auto_name("barrier", None),
        process_set=process_set,
    )
    np.asarray(my_row(result))  # host fetch = the synchronization point
