"""Timeline for the TRACED (jit/shard_map) path — the fast path.

The reference's timeline instruments its background loop per collective
(ref: horovod/common/timeline.cc hooks + NVTX ranges,
nvtx_op_range.h [V] — SURVEY.md §5.1). Under jit there is no per-op
dispatch to hook: XLA runs the whole step as one executable. The honest
TPU equivalent is the XLA profiler itself — it records every compiled
op (collectives included) with real device timestamps. This module
wraps ``jax.profiler`` so the traced path gets the same user surface as
the eager timeline:

    hvd.start_timeline("/tmp/tl.json", traced=True)
    for i in range(steps):
        with hvd.timeline_step("train", i):   # NVTX-range analog
            params, loss = step(params, batch)
    hvd.stop_timeline()                        # writes chrome-trace JSON

``stop()`` post-processes the profiler's ``*.trace.json.gz`` into one
plain chrome://tracing JSON at the requested path; the raw TensorBoard
logdir (XPlane protos) is kept next to it for users who want the full
TB profile UI.
"""

from __future__ import annotations

import glob
import gzip
import json
import os
import shutil
from contextlib import contextmanager
from typing import Optional


class TracedTimeline:
    """jax.profiler session shaped like the eager Timeline."""

    def __init__(self, path: str):
        self._path = os.path.abspath(path)
        # TB logdir kept alongside the requested JSON for the full UI.
        self._logdir = self._path + ".profile"
        self._active = False

    @property
    def active(self) -> bool:
        return self._active

    @property
    def logdir(self) -> str:
        return self._logdir

    def start(self) -> None:
        if self._active:
            return
        import jax

        shutil.rmtree(self._logdir, ignore_errors=True)
        os.makedirs(self._logdir, exist_ok=True)
        jax.profiler.start_trace(self._logdir)
        self._active = True

    @contextmanager
    def step(self, name: str = "step", step_num: Optional[int] = None):
        """Mark one training step in the trace (the NVTX-range analog,
        nvtx_op_range.h [V]). No-op overhead when the timeline is off."""
        if not self._active:
            yield
            return
        import jax

        kwargs = {} if step_num is None else {"step_num": step_num}
        with jax.profiler.StepTraceAnnotation(name, **kwargs):
            yield

    @contextmanager
    def annotate(self, name: str):
        """Free-form range annotation inside a step."""
        if not self._active:
            yield
            return
        import jax

        with jax.profiler.TraceAnnotation(name):
            yield

    def stop(self) -> None:
        if not self._active:
            return
        import jax

        jax.profiler.stop_trace()
        self._active = False
        self._export_chrome_trace()

    # close() aliases stop() so GlobalState teardown treats eager and
    # traced timelines uniformly.
    def close(self) -> None:
        self.stop()

    def _export_chrome_trace(self) -> None:
        """Merge the profiler's per-host trace.json.gz into one plain
        chrome://tracing JSON at the requested path.

        Multi-host traces reuse pid numbers (each host's profiler
        starts from the same ids), so each source file's pids are
        remapped into a disjoint range and the host is recorded in the
        process_name metadata — without this, chrome://tracing renders
        every host's processes overlapped."""
        events = []
        pattern = os.path.join(
            self._logdir, "plugins", "profile", "*", "*.trace.json.gz"
        )
        files = sorted(glob.glob(pattern))
        pid_stride = 10_000
        for host_idx, fname in enumerate(files):
            try:
                with gzip.open(fname, "rt") as f:
                    data = json.load(f)
            except (OSError, json.JSONDecodeError):
                continue
            host = os.path.basename(fname).split(".")[0]
            offset = host_idx * pid_stride
            for ev in data.get("traceEvents", []):
                if "pid" in ev:
                    ev = dict(ev)
                    ev["pid"] = int(ev["pid"]) + offset
                    if (
                        len(files) > 1
                        and ev.get("ph") == "M"
                        and ev.get("name") == "process_name"
                    ):
                        args = dict(ev.get("args", {}))
                        args["name"] = f"{host}: {args.get('name', '')}"
                        ev["args"] = args
                events.append(ev)
        tmp = f"{self._path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump({"traceEvents": events}, f)
        os.replace(tmp, self._path)
