"""Slot-based KV-cache manager: the serving plane's memory plane.

The decode batch is a fixed array of ``slots`` — one slot per in-flight
sequence — so the decode step's shapes never change and the executable
compiled once serves forever (the PR 1 executor-cache lesson applied to
inference). This manager owns:

* the cache pytree itself (``[slots, max_len, kv_heads, head_dim]`` per
  layer, from the model's ``init_cache`` factory) — the DONATED carry
  the engine threads through successive prefill/decode executables;
* the batch-slot allocator (free list, per-slot owner/length), so the
  continuous batcher can admit a queued request into a freed slot
  between decode steps without touching any other slot;
* per-slot length tracking (the ``cache_index`` the model contract
  masks attention by) and eviction on completion/deadline — freeing a
  slot is O(1) bookkeeping, NO cache zeroing: positions at or beyond a
  slot's length are masked to exact zeros by the model, and every
  attended position is overwritten by the next occupant's prefill or
  decode write before it first becomes attendable;
* tensor-parallel sharding: on a mesh with a ``tp`` axis the cache is
  placed with the kv-heads dimension sharded (`parallel/tp.py`'s axis
  contract), so a GSPMD-compiled decode step partitions attention by
  head exactly like Megatron partitions the matmuls.

Prompts longer than the engine's prefill-bucket ceiling are fed through
the same cache in ceiling-sized chunks (`InferenceEngine._chunked
prefill`); on a mesh with a sequence axis the chunk attention could
instead ride `parallel/ring_attention.py` — the cache layout is
compatible (kv stream per slot), left as the documented long-context
extension (docs/serving.md).

This slab manager is now the A/B *baseline*: the default memory plane
is the paged block pool + prefix cache in `paged_kv.py` (same slot
API, HBM scaling with tokens in flight instead of slots × max_len);
`create_kv_manager` below is where the engine picks.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional

import numpy as np

from ..common.logging import get_logger
from ..common.metrics import registry as _metrics

_log = get_logger("serve.kv")


def create_kv_manager(
    cache_factory,
    slots: int,
    max_len: int,
    *,
    paged: bool = True,
    page_tokens: int = 16,
    num_pages: int = 0,
    prefix_cache: bool = True,
    watermark: int = -1,
    mesh=None,
    tp_axis: str = "tp",
):
    """The one place the engine picks its memory plane: the paged
    block-pool manager (`paged_kv.PagedKVCacheManager`, the default —
    HBM scales with pages/tokens-in-flight, prefix cache available) or
    the PR 8 contiguous slab (`KVCacheManager`, the A/B baseline —
    HBM scales with slots × max_len). Both speak the same slot API."""
    if paged:
        from .paged_kv import PagedKVCacheManager

        return PagedKVCacheManager(
            cache_factory, slots, max_len,
            page_tokens=page_tokens, num_pages=num_pages,
            prefix_cache=prefix_cache, watermark=watermark,
            mesh=mesh, tp_axis=tp_axis,
        )
    return KVCacheManager(
        cache_factory, slots=slots, max_len=max_len,
        mesh=mesh, tp_axis=tp_axis,
    )


class KVCacheManager:
    """Fixed-slot KV cache + allocator. Thread-safe bookkeeping; the
    cache pytree itself is only ever touched by the engine's compiled
    executables (single consumer: the batcher's step loop)."""

    def __init__(
        self,
        cache_factory,
        slots: int,
        max_len: int,
        mesh=None,
        tp_axis: str = "tp",
    ) -> None:
        if slots < 1:
            raise ValueError(f"need at least one slot, got {slots}")
        self.slots = int(slots)
        self.max_len = int(max_len)
        self.cache = cache_factory(self.slots, self.max_len)
        self.sharding = None
        if mesh is not None and tp_axis in getattr(mesh, "axis_names", ()):
            self.sharding = self._shard(mesh, tp_axis)
        self._lock = threading.Lock()
        self._free: List[int] = list(range(self.slots))
        self._owner: Dict[int, object] = {}
        self._lengths = np.zeros(self.slots, np.int32)

    # ------------------------------------------------------------ sharding

    def _shard(self, mesh, tp_axis: str):
        """Place every cache leaf with its kv-heads axis (#2 of
        [slots, seq, kv_heads, head_dim]) on the mesh's tensor-parallel
        axis. With the params sharded the same way by the caller, the
        jitted prefill/decode steps compile to per-head-shard attention
        plus exactly the row-parallel psum `parallel/tp.py` places."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        heads = {leaf.shape[2] for layer in self.cache
                 for leaf in layer.values()}
        tp = mesh.shape[tp_axis]
        for h in heads:
            if h % tp:
                raise ValueError(
                    f"the '{tp_axis}' axis size ({tp}) must divide the "
                    f"kv head count ({h}) to shard the cache"
                )
        sharding = NamedSharding(mesh, P(None, None, tp_axis, None))
        self.cache = jax.tree_util.tree_map(
            lambda leaf: jax.device_put(leaf, sharding), self.cache
        )
        return sharding

    # ----------------------------------------------------------- allocator

    def alloc(self, owner=None) -> Optional[int]:
        """Claim a free slot (length 0) or None when full."""
        with self._lock:
            if not self._free:
                return None
            slot = self._free.pop(0)
            self._owner[slot] = owner
            self._lengths[slot] = 0
        self._publish()
        return slot

    def free(self, slot: int) -> None:
        """Evict a slot (completion or deadline): O(1), no cache write —
        see the module docstring for why stale contents are safe."""
        with self._lock:
            if slot in self._owner:
                del self._owner[slot]
                self._lengths[slot] = 0
                self._free.append(slot)
        self._publish()

    def owner(self, slot: int):
        with self._lock:
            return self._owner.get(slot)

    def active_slots(self) -> List[int]:
        with self._lock:
            return sorted(self._owner)

    # ------------------------------------------------------------- lengths

    def length(self, slot: int) -> int:
        return int(self._lengths[slot])

    def set_length(self, slot: int, n: int) -> None:
        if not 0 <= n <= self.max_len:
            raise ValueError(
                f"slot length {n} outside [0, {self.max_len}]"
            )
        self._lengths[slot] = n

    def advance(self, slot: int, n: int = 1) -> int:
        new = int(self._lengths[slot]) + n
        self.set_length(slot, new)
        return new

    def lengths_array(self) -> np.ndarray:
        """The [slots] int32 ``cache_index`` vector the decode step
        takes — a copy, so the executable's donated input can't alias
        bookkeeping."""
        return self._lengths.copy()

    def capacity_left(self, slot: int) -> int:
        return self.max_len - int(self._lengths[slot])

    # --------------------------------------------------------------- stats

    def stats(self) -> Dict[str, int]:
        with self._lock:
            active = len(self._owner)
        return {
            "slots_total": self.slots,
            "slots_active": active,
            "slots_free": self.slots - active,
            "kv_max_len": self.max_len,
        }

    def _publish(self) -> None:
        _metrics.update("serve", self.stats())
