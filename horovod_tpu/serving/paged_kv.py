"""Paged KV memory plane: fixed-size page block-pool + prefix cache.

PR 8's `KVCacheManager` backs every decode slot with a contiguous
``[slots, max_len, ...]`` slab, so HBM scales with ``slots × max_len``
even when most sequences are short. This module replaces the slab with
a **block pool** (the vLLM/Gemma-serving design, PAPERS.md arXiv
2605.25645, re-derived for the repo's donated-carry invariants):

* Physical storage is ``[num_pages, page_tokens, kv_heads, head_dim]``
  per layer — ONE pytree, still the single donated carry through every
  prefill/decode executable. Its size is set by ``num_pages`` (tokens
  in flight), **independent of max_len**.
* Each slot owns a **page table**: an int32 row of physical page ids,
  one per ``page_tokens``-sized logical chunk of its sequence. Tables
  are DATA fed to the fixed-shape executables (never shapes), so
  admissions, evictions, page reuse and prefix sharing can never
  retrace. Unallocated entries hold the sentinel ``num_pages`` — an
  out-of-range index the in-JIT scatter drops and the gather clamps
  into mask-unreachable garbage.
* Allocation is **on write**: prefill takes the prompt's pages at
  admission, decode takes one page each time a slot's frontier crosses
  a page boundary. Freeing a retired slot is O(1) refcount
  bookkeeping — NO zeroing, stale pages stay mask-unreachable exactly
  like stale slab rows did (kv_cache.py docstring), and are fully
  overwritten by their next owner's writes before any position in them
  becomes attendable.

On top of the pool sits the **prefix cache** — the PR 1/PR 8 two-tier
exact/bucket *promotion* design reapplied to cache *content*: prompt
token-chunks are chain-hashed per page boundary, finished prefill
pages are published into a refcounted ``hash → page`` index, and a
request sharing a cached prefix attaches those physical pages by
pointer-write instead of recomputing their prefill chunks. Shared
pages are immutable by construction (sequences are append-only and
only FULLY-written pages are ever published or attached; the final
prompt token is always recomputed so logits exist), with a
copy-on-write guard for any future partial-page sharing. Index
entries are LRU-evicted only at refcount 0 — i.e. only once no slot
references the page.

Admission control (`serving/batcher.py`) gates on free *pages* with a
reserve watermark; `admission_headroom()` is the gate's single source
of truth. Pool exhaustion mid-decode is survivable: the batcher pauses
the youngest request (`detach_keep`/`reattach`) instead of raising.

Memory model note (docs/serving.md "memory plane"): the *persistent*
KV residency is the pool — that is what scales with pages. Each
attention read still gathers a slot's pages into a transient
contiguous view inside the executable (exact-parity dense attention);
fusing the gather into a paged-attention kernel is the documented
follow-up, orthogonal to this allocator.
"""

from __future__ import annotations

import collections
import hashlib
import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..common.logging import get_logger
from ..common.metrics import registry as _metrics
from .kv_cache import KVCacheManager

_log = get_logger("serve.paged")


class PagePoolExhausted(RuntimeError):
    """A decode step found slots whose next write has no page and the
    pool could not supply one. The batcher catches this *before* it can
    happen (``ensure_decode_pages`` + pause-youngest); seeing it raised
    means the engine was driven directly past capacity."""

    def __init__(self, slots: List[int]):
        super().__init__(
            f"page pool exhausted: no page for the next token of "
            f"slots {slots}"
        )
        self.slots = list(slots)


def page_hashes(prompt: np.ndarray, page_tokens: int) -> List[bytes]:
    """Chained per-page digests of a prompt: ``h[i]`` commits to the
    FULL prefix ``prompt[: (i+1) * page_tokens]`` (each digest chains
    the previous one), so equal hashes ⇒ equal prefixes and a cached
    page can never be attached under a different history. Only FULL
    pages are hashed — a partial final chunk is never shareable."""
    prompt = np.ascontiguousarray(np.asarray(prompt, np.int32))
    out: List[bytes] = []
    h = b""
    for i in range(prompt.size // page_tokens):
        chunk = prompt[i * page_tokens:(i + 1) * page_tokens]
        h = hashlib.sha256(h + chunk.tobytes()).digest()
        out.append(h)
    return out


class PagedKVCacheManager(KVCacheManager):
    """Block-pool KV manager behind the slot API the engine/batcher
    already speak (`kv_cache.KVCacheManager`), plus the page-table /
    prefix-cache surface the paged executables and the scheduler use.

    Same threading contract as the slab manager: bookkeeping is
    lock-guarded, the pool pytree itself is only touched by the
    engine's compiled executables (single consumer: the batcher's step
    loop)."""

    def __init__(
        self,
        cache_factory,
        slots: int,
        max_len: int,
        *,
        page_tokens: int = 16,
        num_pages: int = 0,
        mesh=None,
        tp_axis: str = "tp",
        prefix_cache: bool = True,
        watermark: int = -1,
    ) -> None:
        if slots < 1:
            raise ValueError(f"need at least one slot, got {slots}")
        if page_tokens < 1:
            raise ValueError(f"page_tokens must be >= 1, got {page_tokens}")
        if max_len % page_tokens:
            # divisibility keeps the paged logical sequence EXACTLY
            # max_len tokens long, so the paged attention runs the same
            # shapes (and the same reductions) as the slab path — the
            # bit-parity contract. Loud here, not wrong logits later.
            raise ValueError(
                f"page_tokens ({page_tokens}) must divide max_len "
                f"({max_len}) — pick a divisor (the paged attention "
                f"view must tile the slot exactly)"
            )
        self.slots = int(slots)
        self.max_len = int(max_len)
        self.page_tokens = int(page_tokens)
        self.pages_per_slot = self.max_len // self.page_tokens
        full_backing = self.slots * self.pages_per_slot
        self.num_pages = int(num_pages) if num_pages else full_backing
        if self.num_pages < 1:
            raise ValueError(f"need at least one page, got {self.num_pages}")
        # reserve watermark: pages admission must leave free so
        # mid-decode allocation cannot strand in-flight sequences.
        # auto (-1): zero at full backing (starvation impossible — every
        # slot's worst case is covered), one page per slot otherwise
        # (one decode round's worst-case frontier crossings).
        if watermark < 0:
            watermark = 0 if self.num_pages >= full_backing else self.slots
        self.watermark = min(int(watermark), max(self.num_pages - 1, 0))
        self.prefix_cache_enabled = bool(prefix_cache)
        # the pool: same leaf structure as the slab (list of {"k","v"}),
        # batch axis = pages, seq axis = page_tokens — init_cache's
        # signature serves both layouts
        self.cache = cache_factory(self.num_pages, self.page_tokens)
        self.sharding = None
        if mesh is not None and tp_axis in getattr(mesh, "axis_names", ()):
            self.sharding = self._shard(mesh, tp_axis)
        self._lock = threading.Lock()
        self._owner: Dict[int, object] = {}
        self._lengths = np.zeros(self.slots, np.int32)
        # sentinel == num_pages: out of range, so in-JIT writes drop
        # and gathers clamp into masked garbage
        self.sentinel = self.num_pages
        self._tables = np.full(
            (self.slots, self.pages_per_slot), self.sentinel, np.int32
        )
        self._free: "collections.deque[int]" = collections.deque(
            range(self.num_pages)
        )
        self._ref = np.zeros(self.num_pages, np.int32)
        # prefix index: hash -> physical page, LRU-ordered (move_to_end
        # on every hit); _page_hash is the reverse map for eviction
        self._index: "collections.OrderedDict[bytes, int]" = (
            collections.OrderedDict()
        )
        self._page_hash: Dict[int, bytes] = {}
        # incremental count of index entries whose page is held ONLY
        # by the index (ref == 1) — the reclaimable pool. Maintained at
        # every ref transition of an indexed page so the admission gate
        # and /healthz never rescan the index (O(1), not O(entries)).
        self._reclaimable = 0
        self._counters = collections.Counter()

    # ----------------------------------------------------------- page pool

    def _alloc_page_locked(self) -> Optional[int]:
        """One free page, evicting LRU refcount-0 index entries if the
        free list is dry. Caller holds the lock."""
        if self._free:
            return self._free.popleft()
        # LRU sweep: an index entry whose page is referenced ONLY by
        # the index (ref == 1) is reclaimable; entries still attached
        # to live slots are skipped — eviction only at refcount 0
        for h in list(self._index):
            page = self._index[h]
            if self._ref[page] == 1:
                del self._index[h]
                del self._page_hash[page]
                self._ref[page] = 0
                self._reclaimable -= 1
                self._counters["page_evictions"] += 1
                return page
        return None

    def _unref_locked(self, page: int) -> None:
        self._ref[page] -= 1
        if self._ref[page] == 1 and page in self._page_hash:
            self._reclaimable += 1
        if self._ref[page] <= 0:
            # published pages always keep the index's own hold, so a
            # zero refcount means nobody (slot or index) wants it
            self._ref[page] = 0
            self._free.append(page)

    def free_pages_available(self) -> int:
        """Free-list pages plus index entries reclaimable right now
        (refcount 0 once the index's own hold is dropped)."""
        with self._lock:
            return len(self._free) + self._reclaimable

    def admission_headroom(self) -> int:
        """Pages the admission gate may spend: available minus the
        reserve watermark. THE number `/healthz`, the KV announcement
        and the batcher's gate all read."""
        return max(self.free_pages_available() - self.watermark, 0)

    def pages_needed(self, tokens: int) -> int:
        return -(-int(tokens) // self.page_tokens)

    def ensure_pages(
        self, slot: int, upto: int, write_from: int = 0,
        start_page: int = 0,
    ) -> bool:
        """Allocate logical pages so positions ``[0, upto)`` are
        mapped, and make every page that will be WRITTEN (covering
        positions >= ``write_from``) exclusively owned — a shared page
        in the write range is copied first (copy-on-write). Returns
        False when the pool cannot supply a page; allocations made so
        far stay owned by the slot (freed with it). ``start_page``
        skips logical pages the caller KNOWS are already mapped (the
        decode sweep's frontier fast path — don't rescan a long
        sequence's whole table every token)."""
        needed = self.pages_needed(upto)
        first_write = int(write_from) // self.page_tokens
        for lp in range(start_page, min(needed, self.pages_per_slot)):
            with self._lock:
                phys = int(self._tables[slot, lp])
                if phys == self.sentinel:
                    page = self._alloc_page_locked()
                    if page is None:
                        return False
                    self._tables[slot, lp] = page
                    self._ref[page] = 1
                    self._counters["page_allocs"] += 1
                    continue
                shared = lp >= first_write and self._ref[phys] > 1
            if shared and not self._cow(slot, lp):
                return False
        return True

    def _cow(self, slot: int, lp: int) -> bool:
        """Copy-on-write: give ``slot`` a private copy of logical page
        ``lp`` before it writes into it. Never taken by the shipped
        sharing policy (only full, immutable pages are shared and the
        last prompt token is always recomputed) — this is the safety
        valve that keeps any future partial-page sharing correct. The
        copy is one eager device op on the pool (outside the compiled
        step; the manager re-binds ``self.cache`` like the executables
        do)."""
        import jax

        with self._lock:
            old = int(self._tables[slot, lp])
            new = self._alloc_page_locked()
            if new is None:
                return False
            self._tables[slot, lp] = new
            self._ref[new] = 1
            self._unref_locked(old)
            self._counters["page_cow"] += 1
        self.cache = jax.tree_util.tree_map(
            lambda leaf: leaf.at[new].set(leaf[old]), self.cache
        )
        return True

    def ensure_decode_pages(self) -> List[int]:
        """Pre-decode allocation sweep: every active slot's next write
        position (its length) must land in an owned page. Returns the
        slots that could NOT be supplied — the batcher's cue to pause
        the youngest request rather than let the step raise."""
        starved: List[int] = []
        with self._lock:
            active = sorted(self._owner)
        for slot in active:
            n = int(self._lengths[slot])
            if n >= self.max_len:
                continue  # full slot: retires this round, writes drop
            # pages below the frontier are mapped by the slot's own
            # prefill/decode history — only the frontier page can need
            # a page, so start the scan there (O(1) per slot per step)
            if not self.ensure_pages(
                slot, n + 1, write_from=n,
                start_page=n // self.page_tokens,
            ):
                starved.append(slot)
        return starved

    # -------------------------------------------------------- prefix cache

    def lookup_prefix(self, hashes: List[bytes]) -> List[int]:
        """Longest cached run of leading full pages: physical ids for
        ``hashes[0..k-1]``, stopping at the first miss. Touches LRU
        recency on every hit."""
        self._counters["prefix_lookups"] += 1
        if not self.prefix_cache_enabled:
            return []
        out: List[int] = []
        with self._lock:
            for h in hashes:
                page = self._index.get(h)
                if page is None:
                    break
                self._index.move_to_end(h)
                out.append(page)
        return out

    def attach_prefix(self, slot: int, pages: List[int]) -> None:
        """Point the slot's leading page-table entries at cached
        physical pages — the prefill those pages carry is skipped
        entirely. Refcounts pin the pages for the slot's lifetime."""
        with self._lock:
            for lp, page in enumerate(pages):
                if self._tables[slot, lp] != self.sentinel:
                    raise ValueError(
                        f"slot {slot} logical page {lp} already mapped"
                    )
                self._tables[slot, lp] = page
                if self._ref[page] == 1:
                    # was index-only: a slot hold makes it unreclaimable
                    self._reclaimable -= 1
                self._ref[page] += 1
            self._counters["prefix_hits"] += len(pages)
            if pages:
                self._counters["prefix_hit_requests"] += 1

    def publish_prefix(self, slot: int, hashes: List[bytes]) -> None:
        """After a prefill completes, publish the slot's full prompt
        pages into the index (first publisher wins — an existing entry
        for the same hash keeps its page). The index takes its own
        refcount hold, so a published page survives its slot."""
        if not self.prefix_cache_enabled:
            return
        with self._lock:
            for lp, h in enumerate(hashes[: self.pages_per_slot]):
                phys = int(self._tables[slot, lp])
                if phys == self.sentinel or h in self._index:
                    continue
                self._index[h] = phys
                self._index.move_to_end(h)
                self._page_hash[phys] = h
                self._ref[phys] += 1
                self._counters["prefix_published"] += 1

    # ------------------------------------------------ transfer-ingest surface

    def ingest_alloc(self, count: int) -> Optional[List[int]]:
        """Allocate ``count`` pool pages for a KV transfer ingest
        (serving/kv_transfer.py), each with ONE caller-held refcount —
        the same convention as :meth:`detach_keep`'s kept pages, so the
        ingested pages slot straight into :meth:`reattach`. All-or-
        nothing: on a dry pool every page allocated so far goes back
        and None is returned (the sender's cue to fall back)."""
        got: List[int] = []
        with self._lock:
            for _ in range(int(count)):
                page = self._alloc_page_locked()
                if page is None:
                    for p in got:
                        self._ref[p] = 0
                        self._free.append(p)
                    return None
                self._ref[page] = 1
                got.append(page)
            self._counters["page_allocs"] += len(got)
            self._counters["transfer_pages_in"] += len(got)
        self._publish()
        return got

    def publish_hashes(
        self, kept: List[Tuple[int, int]], hashes: List[bytes]
    ) -> None:
        """Warm the prefix index from TRANSFERRED pages: the sender's
        chained page hashes travel with the payload, so the decode
        worker's cache serves future shared-prefix admissions without
        ever having prefilled them. Only full prompt pages carry a
        hash (``hashes[lp]``); the partial tail page is skipped by
        construction. First publisher wins, same as
        :meth:`publish_prefix`."""
        if not self.prefix_cache_enabled:
            return
        with self._lock:
            for lp, phys in kept:
                if lp >= len(hashes):
                    continue
                h = hashes[lp]
                if h in self._index:
                    continue
                self._index[h] = phys
                self._index.move_to_end(h)
                self._page_hash[phys] = h
                self._ref[phys] += 1
                self._counters["prefix_published"] += 1

    # ------------------------------------------------- pause/resume surface

    def detach_keep(self, slot: int) -> Tuple[List[Tuple[int, int]], int]:
        """Pause support: release the SLOT but keep its pages alive
        under the request's own refcount holds. Returns
        ``(kept, length)`` where ``kept`` is ``[(logical, physical)]``
        for :meth:`reattach` — the refcounts transfer to the caller, so
        nothing is freed and nothing can be reused underneath it."""
        with self._lock:
            kept = [
                (lp, int(p))
                for lp, p in enumerate(self._tables[slot])
                if p != self.sentinel
            ]
            length = int(self._lengths[slot])
            self._tables[slot] = self.sentinel
            self._lengths[slot] = 0
            self._owner.pop(slot, None)
        self._publish()
        return kept, length

    def reattach(
        self, slot: int, kept: List[Tuple[int, int]], length: int
    ) -> None:
        """Resume a paused request into a (freshly allocated) slot: the
        kept pages slot back into the table at their logical positions
        and decode continues where it stopped — no re-prefill."""
        with self._lock:
            for lp, page in kept:
                self._tables[slot, lp] = page
        self.set_length(slot, length)
        self._publish()

    def release_kept(self, kept: List[Tuple[int, int]]) -> None:
        """Drop a paused request's page holds (deadline-aware reclaim,
        or the request expired in the queue). The request must
        re-prefill on resume; its published prefix pages may still hit."""
        with self._lock:
            for _, page in kept:
                self._unref_locked(page)
        self._publish()

    # ------------------------------------------------------ slot API (base)

    def alloc(self, owner=None) -> Optional[int]:
        with self._lock:
            for slot in range(self.slots):
                if slot not in self._owner:
                    self._owner[slot] = owner
                    self._lengths[slot] = 0
                    break
            else:
                return None
        self._publish()
        return slot

    def free(self, slot: int) -> None:
        """Retire a slot: O(1) per page — refcounts drop, pages whose
        count reaches zero return to the free list, pages pinned by the
        prefix index (or another slot) live on. No cache writes."""
        with self._lock:
            if slot not in self._owner:
                return
            del self._owner[slot]
            for lp in range(self.pages_per_slot):
                phys = int(self._tables[slot, lp])
                if phys != self.sentinel:
                    self._unref_locked(phys)
                self._tables[slot, lp] = self.sentinel
            self._lengths[slot] = 0
        self._publish()

    def table_row(self, slot: int) -> np.ndarray:
        """The slot's page-table row (a copy — executable inputs can't
        alias bookkeeping, same contract as ``lengths_array``)."""
        return self._tables[slot].copy()

    def tables_array(self) -> np.ndarray:
        """[slots, pages_per_slot] int32 for the decode step (a copy)."""
        return self._tables.copy()

    # --------------------------------------------------------------- stats

    def stats(self) -> Dict[str, float]:
        with self._lock:
            active_slots = len(self._owner)
            free = len(self._free)
            reclaimable = self._reclaimable
            counters = dict(self._counters)
            index_entries = len(self._index)
        lookups = counters.get("prefix_lookups", 0)
        out = {
            "slots_total": self.slots,
            "slots_active": active_slots,
            "slots_free": self.slots - active_slots,
            "kv_max_len": self.max_len,
            "page_tokens": self.page_tokens,
            "pages_total": self.num_pages,
            "pages_free": free,
            "pages_cached": reclaimable,
            "pages_active": self.num_pages - free - reclaimable,
            "page_watermark": self.watermark,
            "prefix_index_entries": index_entries,
            "prefix_hit_rate": (
                counters.get("prefix_hit_requests", 0) / lookups
                if lookups
                else 0.0
            ),
        }
        for key in (
            "page_allocs", "page_evictions", "page_cow", "prefix_hits",
            "prefix_hit_requests", "prefix_lookups", "prefix_published",
        ):
            out[key] = counters.get(key, 0)
        return out

    def _publish(self) -> None:
        _metrics.update("serve", self.stats())
