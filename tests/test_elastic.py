"""Elastic tests — the reference's model (SURVEY.md §4.2/§4.3):
driver logic in-process against fake scripted discovery; integration via
real localhost gangs with file-mutation membership changes and failing
workers."""

import os
import sys
import time
from typing import List

import numpy as np
import pytest

import horovod_tpu as hvd_mod
from horovod_tpu.elastic import (
    ElasticDriver,
    FixedHosts,
    HostDiscovery,
    HostDiscoveryScript,
    HostManager,
    JaxState,
    ObjectState,
)
from horovod_tpu.elastic.worker import notification_manager, run as elastic_run
from horovod_tpu.common.basics import (
    HorovodInternalError,
    HostsUpdatedInterrupt,
)
from horovod_tpu.runner.hosts import HostInfo


class FakeDiscovery(HostDiscovery):
    """Scripted host sequences — the reference's fake-discovery test
    pattern (test_elastic_driver.py [V])."""

    def __init__(self, hosts: List[HostInfo]):
        self.hosts = list(hosts)

    def find_available_hosts_and_slots(self):
        return list(self.hosts)


class TestDiscovery:
    def test_script_discovery(self, tmp_path):
        listing = tmp_path / "hosts.txt"
        listing.write_text("a:2\nb:2\n")
        disc = HostDiscoveryScript(f"cat {listing}")
        assert disc.find_available_hosts_and_slots() == [
            HostInfo("a", 2),
            HostInfo("b", 2),
        ]
        # membership driven by mutating the file — §4.3's mechanism
        listing.write_text("a:2\n")
        assert disc.find_available_hosts_and_slots() == [HostInfo("a", 2)]

    def test_script_failure_means_no_hosts(self):
        assert HostDiscoveryScript("exit 1").find_available_hosts_and_slots() == []

    def test_default_slots(self, tmp_path):
        listing = tmp_path / "hosts.txt"
        listing.write_text("a\n")
        disc = HostDiscoveryScript(f"cat {listing}", default_slots=4)
        assert disc.find_available_hosts_and_slots() == [HostInfo("a", 4)]

    def test_host_manager_blacklist(self):
        disc = FakeDiscovery([HostInfo("a", 2), HostInfo("b", 2)])
        mgr = HostManager(disc)
        assert mgr.refresh() is True
        assert [h.hostname for h in mgr.current_hosts()] == ["a", "b"]
        mgr.blacklist("a")
        assert mgr.is_blacklisted("a")
        assert [h.hostname for h in mgr.current_hosts()] == ["b"]
        # blacklisted host keeps being filtered on refresh
        mgr.refresh()
        assert [h.hostname for h in mgr.current_hosts()] == ["b"]

    def test_refresh_reports_change(self):
        disc = FakeDiscovery([HostInfo("a", 2)])
        mgr = HostManager(disc)
        assert mgr.refresh() is True
        assert mgr.refresh() is False
        disc.hosts.append(HostInfo("b", 2))
        assert mgr.refresh() is True


class TestAssignment:
    def _driver(self, disc, **kw):
        kw.setdefault("min_np", 1)
        return ElasticDriver(disc, ["true"], **kw)

    def test_below_min_np_is_none(self):
        d = self._driver(FakeDiscovery([HostInfo("a", 2)]), min_np=4)
        d.host_manager.refresh()
        assert d.compute_assignment() is None

    def test_max_np_clamps(self):
        d = self._driver(
            FakeDiscovery([HostInfo("a", 4), HostInfo("b", 4)]), max_np=6
        )
        d.host_manager.refresh()
        a = d.compute_assignment()
        assert a.world_size == 6
        # ranks dense, reference numbering
        assert [s.rank for s in a.slots] == list(range(6))

    def test_failure_then_reassignment(self):
        d = self._driver(FakeDiscovery([HostInfo("a", 2), HostInfo("b", 2)]))
        d.host_manager.refresh()
        assert d.compute_assignment().world_size == 4
        d.handle_host_failure("a")
        a = d.compute_assignment()
        assert a.world_size == 2
        assert a.hostnames == ["b"]

    def test_slots_per_host_override(self):
        d = self._driver(
            FakeDiscovery([HostInfo("a", 1)]), slots_per_host=4
        )
        d.host_manager.refresh()
        assert d.compute_assignment().world_size == 4


class TestState:
    def test_object_state_commit_restore(self):
        s = ObjectState(step=0, best=1.5)
        s.step = 10
        s.commit()
        s.step = 99
        s.restore()
        assert s.step == 10 and s.best == 1.5

    def test_object_state_initial_save(self):
        s = ObjectState(step=5)
        s.step = 7
        s.restore()  # never committed → back to construction values
        assert s.step == 5

    def test_jax_state_tree_commit_restore(self, hvd):
        import jax.numpy as jnp

        params = {"w": jnp.ones((4, 4)), "b": jnp.zeros(4)}
        s = JaxState(params=params, step=0)
        s.params = {"w": jnp.full((4, 4), 2.0), "b": jnp.ones(4)}
        s.step = 3
        s.commit()
        s.params = {"w": jnp.full((4, 4), -1.0), "b": jnp.ones(4)}
        s.step = 8
        s.restore()
        assert s.step == 3
        np.testing.assert_allclose(np.asarray(s.params["w"]), 2.0)
        np.testing.assert_allclose(np.asarray(s.params["b"]), 1.0)

    def test_jax_state_sync_replicates(self, hvd):
        import jax
        import jax.numpy as jnp

        s = JaxState(params={"w": jnp.arange(8.0)})
        s.sync()
        leaf = s.params["w"]
        assert isinstance(leaf, jax.Array)
        assert leaf.sharding.is_fully_replicated
        np.testing.assert_allclose(np.asarray(leaf), np.arange(8.0))


class TestRunWrapper:
    def test_internal_error_restores_and_retries(self, hvd):
        calls = []

        class S(ObjectState):
            def sync(self):
                calls.append("sync")

        state = S(step=0)
        attempts = {"n": 0}

        @elastic_run
        def train(st):
            attempts["n"] += 1
            if attempts["n"] == 1:
                st.step = 50  # uncommitted progress, must be rolled back
                raise HorovodInternalError("peer died")
            return st.step

        assert train(state) == 0  # rolled back to initial commit
        assert attempts["n"] == 2
        assert calls == ["sync", "sync"]  # re-synced after restore

    def test_hosts_updated_keeps_state(self, hvd):
        state = ObjectState(step=0)
        attempts = {"n": 0}

        @elastic_run
        def train(st):
            attempts["n"] += 1
            if attempts["n"] == 1:
                st.step = 7
                raise HostsUpdatedInterrupt()
            return st.step

        assert train(state) == 7  # progress preserved on membership change
        assert attempts["n"] == 2

    def test_commit_raises_on_pending_update(self, hvd):
        state = ObjectState(step=0)
        notification_manager._updated.set()
        with pytest.raises(HostsUpdatedInterrupt):
            state.commit()
        # flag consumed
        state.commit()


class TestNotificationEndToEnd:
    def test_driver_notifies_worker_manager(self, monkeypatch):
        """Worker manager registers in the KV; driver pings it; the flag
        surfaces as HostsUpdatedInterrupt."""
        from horovod_tpu.elastic.worker import WorkerNotificationManager
        from horovod_tpu.runner.rendezvous import RendezvousServer
        from horovod_tpu.runner.service import BasicClient

        import horovod_tpu.runner.secret as secret_mod

        key = secret_mod.make_secret_key()
        server = RendezvousServer(secret_key=key)
        port = server.start()
        try:
            monkeypatch.setenv("HOROVOD_GLOO_RENDEZVOUS_ADDR", "127.0.0.1")
            monkeypatch.setenv("HOROVOD_GLOO_RENDEZVOUS_PORT", str(port))
            monkeypatch.setenv("HOROVOD_SECRET_KEY", key.hex())
            monkeypatch.setenv("HOROVOD_ELASTIC_EPOCH", "0")
            monkeypatch.setenv("HOROVOD_PROCESS_ID", "0")
            monkeypatch.setenv("HOROVOD_HOSTNAME", "localhost")
            mgr = WorkerNotificationManager()
            mgr.init()
            try:
                addr = server.store.get("workers.0", "0")
                assert addr is not None
                host, _, sport = addr.decode().partition(":")
                out = BasicClient(host, int(sport), key).request(
                    {"type": "hosts_updated", "epoch": 0}
                )
                assert out["ok"] is True
                with pytest.raises(HostsUpdatedInterrupt):
                    mgr.raise_if_updated()
            finally:
                mgr.shutdown()
        finally:
            server.stop()


def _clean_env():
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env.pop("JAX_PLATFORMS", None)
    env["PALLAS_AXON_POOL_IPS"] = ""
    return env


@pytest.mark.slow
class TestDriverIntegration:
    """Real localhost gangs (§4.3's chaos style, scaled to CI)."""

    def test_gang_success(self, monkeypatch):
        for k, v in _clean_env().items():
            monkeypatch.setenv(k, v)
        d = ElasticDriver(
            FakeDiscovery([HostInfo("localhost", 2)]),
            [sys.executable, "-c", "import os; assert os.environ['HOROVOD_SIZE']=='2'"],
            min_np=2,
            discovery_interval=0.2,
        )
        try:
            d.host_manager.refresh()
            assert d.run() == 0
        finally:
            d.shutdown()

    def test_worker_failure_blacklists_and_exhausts(self, monkeypatch):
        for k, v in _clean_env().items():
            monkeypatch.setenv(k, v)
        d = ElasticDriver(
            FakeDiscovery([HostInfo("localhost", 1)]),
            [sys.executable, "-c", "raise SystemExit(5)"],
            min_np=1,
            discovery_interval=0.1,
            start_timeout=0.5,
        )
        try:
            d.host_manager.refresh()
            rc = d.run()
            assert rc != 0
            assert d.host_manager.is_blacklisted("localhost")
        finally:
            d.shutdown()

    def test_membership_shrink_restarts_gang(self, monkeypatch, tmp_path):
        """World of 2 sleeps; discovery shrinks to 1; restarted world of
        1 exits 0 — the §3.4 restart-on-change path with a live gang."""
        for k, v in _clean_env().items():
            monkeypatch.setenv(k, v)
        script = tmp_path / "w.py"
        script.write_text(
            "import os, sys, time\n"
            "if os.environ['HOROVOD_SIZE'] == '1':\n"
            "    sys.exit(0)\n"
            "time.sleep(120)\n"
        )
        listing = tmp_path / "hosts.txt"
        listing.write_text("localhost:2\n")
        d = ElasticDriver(
            HostDiscoveryScript(f"cat {listing}"),
            [sys.executable, str(script)],
            min_np=1,
            discovery_interval=0.2,
        )
        try:
            d.host_manager.refresh()
            import threading

            result = {}
            t = threading.Thread(target=lambda: result.update(rc=d.run()))
            t.start()
            time.sleep(1.5)  # let epoch-0 gang come up
            listing.write_text("localhost:1\n")  # shrink membership
            t.join(timeout=60)
            assert not t.is_alive(), "driver did not converge"
            assert result["rc"] == 0
        finally:
            d.shutdown()

    def test_worker_sigkill_triggers_gang_restart(self, monkeypatch,
                                                  tmp_path):
        """§4.3's fault injection: SIGKILL a live worker PID mid-run;
        the driver must detect the dead gang, reset, relaunch, and the
        job must still complete (the reference's integration tests kill
        worker PIDs exactly like this [V])."""
        for k, v in _clean_env().items():
            monkeypatch.setenv(k, v)
        flag = tmp_path / "second_epoch"
        script = tmp_path / "w.py"
        # epoch 0: sleep forever (to be killed); epoch 1+: exit 0
        script.write_text(
            "import os, sys, time, pathlib\n"
            f"flag = pathlib.Path({str(flag)!r})\n"
            "if int(os.environ.get('HOROVOD_ELASTIC_EPOCH', '0')) >= 1:\n"
            "    sys.exit(0)\n"
            "flag.write_text('up')\n"
            "time.sleep(120)\n"
        )
        # Two "hosts" (both local): the failed worker's host gets
        # blacklisted, the surviving host carries the epoch-1 gang —
        # the reference's kill-and-survive scenario shape [V].
        d = ElasticDriver(
            FakeDiscovery(
                [HostInfo("localhost", 1), HostInfo("127.0.0.1", 1)]
            ),
            [sys.executable, str(script)],
            min_np=1,
            discovery_interval=0.2,
        )
        try:
            d.host_manager.refresh()
            import signal as _signal
            import threading

            result = {}
            t = threading.Thread(target=lambda: result.update(rc=d.run()))
            t.start()
            deadline = time.monotonic() + 20
            while not flag.exists() and time.monotonic() < deadline:
                time.sleep(0.05)
            assert flag.exists(), "epoch-0 worker never came up"
            with d._lock:
                procs = list(d._procs)
            assert procs
            procs[0].send_signal(_signal.SIGKILL)
            t.join(timeout=60)
            assert not t.is_alive(), "driver did not recover from SIGKILL"
            assert result["rc"] == 0  # epoch-1 relaunch exited clean
        finally:
            d.shutdown()
